/* Foreign-runtime RPC client: a pure-C embedder that drives the
 * JSON-RPC stdio frontend (automerge_tpu/rpc.py) and maintains a LIVE
 * materialized document tree by applying streamed patches — the role
 * the reference's wasm interop layer plays for JS hosts
 * (reference: rust/automerge-wasm/src/interop.rs:787-1001
 * apply_patch_to_{map,array,text}: navigate the patch path into live
 * foreign objects and mutate in place; conflict flags surfaced).
 *
 * The client spawns the server process given on its command line
 * (e.g. `python -m automerge_tpu.rpc`), performs local edits, forks,
 * concurrent merges and a full sync session, and after every patch
 * batch DEEP-COMPARES its incrementally-maintained tree against the
 * server's `materialize` snapshot — cross-runtime convergence, asserted
 * from C. Exit 0 = every assertion held.
 *
 * No code is shared with the Python implementation: JSON parsing,
 * the value tree and patch application are self-contained here.
 */
#define _POSIX_C_SOURCE 200809L
#include <errno.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

/* ---------------- minimal JSON value tree -------------------------------- */

typedef enum { J_NULL, J_BOOL, J_NUM, J_STR, J_ARR, J_OBJ } JType;

typedef struct JVal {
  JType t;
  int b;
  double num;
  char *str;            /* J_STR (UTF-8) */
  struct JVal **items;  /* J_ARR / J_OBJ values */
  char **keys;          /* J_OBJ keys */
  size_t n, cap;
} JVal;

static int checks = 0, failures = 0;
#define CHECK(cond)                                                         \
  do {                                                                      \
    checks++;                                                               \
    if (!(cond)) {                                                          \
      failures++;                                                           \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__,    \
              #cond);                                                       \
    }                                                                       \
  } while (0)

static void *xmalloc(size_t n) {
  void *p = calloc(1, n ? n : 1);
  if (!p) {
    fprintf(stderr, "oom\n");
    exit(2);
  }
  return p;
}

static void *xrealloc(void *p, size_t n) {
  void *q = realloc(p, n ? n : 1);
  if (!q) {
    fprintf(stderr, "oom\n");
    exit(2);
  }
  return q;
}

static char *xstrdup(const char *s) {
  char *d = xmalloc(strlen(s) + 1);
  strcpy(d, s);
  return d;
}

static JVal *jnew(JType t) {
  JVal *v = xmalloc(sizeof(JVal));
  v->t = t;
  return v;
}

static void jfree(JVal *v) {
  if (!v) return;
  free(v->str);
  for (size_t i = 0; i < v->n; i++) {
    jfree(v->items[i]);
    if (v->keys) free(v->keys[i]);
  }
  free(v->items);
  free(v->keys);
  free(v);
}

static void jgrow(JVal *v) {
  if (v->n == v->cap) {
    v->cap = v->cap ? v->cap * 2 : 4;
    v->items = xrealloc(v->items, v->cap * sizeof(JVal *));
    if (v->t == J_OBJ) v->keys = xrealloc(v->keys, v->cap * sizeof(char *));
  }
}

static void jarr_insert(JVal *a, size_t idx, JVal *item) {
  jgrow(a);
  if (idx > a->n) idx = a->n;
  memmove(a->items + idx + 1, a->items + idx,
          (a->n - idx) * sizeof(JVal *));
  a->items[idx] = item;
  a->n++;
}

static void jarr_delete(JVal *a, size_t idx) {
  if (idx >= a->n) return;
  jfree(a->items[idx]);
  memmove(a->items + idx, a->items + idx + 1,
          (a->n - idx - 1) * sizeof(JVal *));
  a->n--;
}

static JVal *jobj_get(const JVal *o, const char *key) {
  for (size_t i = 0; i < o->n; i++)
    if (strcmp(o->keys[i], key) == 0) return o->items[i];
  return NULL;
}

static void jobj_put(JVal *o, const char *key, JVal *val) {
  for (size_t i = 0; i < o->n; i++)
    if (strcmp(o->keys[i], key) == 0) {
      jfree(o->items[i]);
      o->items[i] = val;
      return;
    }
  jgrow(o);
  o->keys[o->n] = xstrdup(key);
  o->items[o->n] = val;
  o->n++;
}

static void jobj_del(JVal *o, const char *key) {
  for (size_t i = 0; i < o->n; i++)
    if (strcmp(o->keys[i], key) == 0) {
      jfree(o->items[i]);
      free(o->keys[i]);
      memmove(o->items + i, o->items + i + 1,
              (o->n - i - 1) * sizeof(JVal *));
      memmove(o->keys + i, o->keys + i + 1, (o->n - i - 1) * sizeof(char *));
      o->n--;
      return;
    }
}

/* ---------------- JSON parser --------------------------------------------- */

typedef struct {
  const char *s;
  size_t pos, len;
  int err;
} Parser;

static void pskip(Parser *p) {
  while (p->pos < p->len && strchr(" \t\r\n", p->s[p->pos])) p->pos++;
}

static JVal *pvalue(Parser *p);

static int phex(Parser *p) {
  int v = 0;
  for (int i = 0; i < 4; i++) {
    char c = p->pos < p->len ? p->s[p->pos++] : 0;
    v <<= 4;
    if (c >= '0' && c <= '9') v |= c - '0';
    else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
    else { p->err = 1; return 0; }
  }
  return v;
}

static void utf8_push(char **buf, size_t *n, size_t *cap, long cp) {
  char tmp[4];
  int len;
  if (cp < 0x80) { tmp[0] = (char)cp; len = 1; }
  else if (cp < 0x800) {
    tmp[0] = (char)(0xC0 | (cp >> 6));
    tmp[1] = (char)(0x80 | (cp & 0x3F));
    len = 2;
  } else if (cp < 0x10000) {
    tmp[0] = (char)(0xE0 | (cp >> 12));
    tmp[1] = (char)(0x80 | ((cp >> 6) & 0x3F));
    tmp[2] = (char)(0x80 | (cp & 0x3F));
    len = 3;
  } else {
    tmp[0] = (char)(0xF0 | (cp >> 18));
    tmp[1] = (char)(0x80 | ((cp >> 12) & 0x3F));
    tmp[2] = (char)(0x80 | ((cp >> 6) & 0x3F));
    tmp[3] = (char)(0x80 | (cp & 0x3F));
    len = 4;
  }
  if (*n + 4 >= *cap) {
    *cap = *cap ? *cap * 2 : 32;
    *buf = xrealloc(*buf, *cap + 4);
  }
  memcpy(*buf + *n, tmp, len);
  *n += len;
}

static char *pstring(Parser *p) {
  if (p->s[p->pos] != '"') { p->err = 1; return NULL; }
  p->pos++;
  char *buf = NULL;
  size_t n = 0, cap = 0;
  while (p->pos < p->len) {
    char c = p->s[p->pos++];
    if (c == '"') {
      utf8_push(&buf, &n, &cap, 0);
      buf[n - 1] = '\0';
      return buf;
    }
    if (c == '\\') {
      char e = p->pos < p->len ? p->s[p->pos++] : 0;
      switch (e) {
        case '"': case '\\': case '/': utf8_push(&buf, &n, &cap, e); break;
        case 'b': utf8_push(&buf, &n, &cap, '\b'); break;
        case 'f': utf8_push(&buf, &n, &cap, '\f'); break;
        case 'n': utf8_push(&buf, &n, &cap, '\n'); break;
        case 'r': utf8_push(&buf, &n, &cap, '\r'); break;
        case 't': utf8_push(&buf, &n, &cap, '\t'); break;
        case 'u': {
          long cp = phex(p);
          if (cp >= 0xD800 && cp < 0xDC00 && p->pos + 1 < p->len &&
              p->s[p->pos] == '\\' && p->s[p->pos + 1] == 'u') {
            p->pos += 2;
            long lo = phex(p);
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          }
          utf8_push(&buf, &n, &cap, cp);
          break;
        }
        default: p->err = 1; free(buf); return NULL;
      }
    } else {
      utf8_push(&buf, &n, &cap, (unsigned char)c);
    }
  }
  p->err = 1;
  free(buf);
  return NULL;
}

static JVal *pvalue(Parser *p) {
  pskip(p);
  if (p->pos >= p->len) { p->err = 1; return jnew(J_NULL); }
  char c = p->s[p->pos];
  if (c == '{') {
    p->pos++;
    JVal *o = jnew(J_OBJ);
    pskip(p);
    if (p->s[p->pos] == '}') { p->pos++; return o; }
    for (;;) {
      pskip(p);
      char *k = pstring(p);
      if (p->err) { free(k); return o; }
      pskip(p);
      if (p->s[p->pos] != ':') { p->err = 1; free(k); return o; }
      p->pos++;
      JVal *v = pvalue(p);
      jgrow(o);
      o->keys[o->n] = k;
      o->items[o->n] = v;
      o->n++;
      pskip(p);
      if (p->s[p->pos] == ',') { p->pos++; continue; }
      if (p->s[p->pos] == '}') { p->pos++; return o; }
      p->err = 1;
      return o;
    }
  }
  if (c == '[') {
    p->pos++;
    JVal *a = jnew(J_ARR);
    pskip(p);
    if (p->s[p->pos] == ']') { p->pos++; return a; }
    for (;;) {
      JVal *v = pvalue(p);
      jarr_insert(a, a->n, v);
      pskip(p);
      if (p->s[p->pos] == ',') { p->pos++; continue; }
      if (p->s[p->pos] == ']') { p->pos++; return a; }
      p->err = 1;
      return a;
    }
  }
  if (c == '"') {
    JVal *v = jnew(J_STR);
    v->str = pstring(p);
    if (!v->str) v->str = xstrdup("");
    return v;
  }
  if (strncmp(p->s + p->pos, "true", 4) == 0) {
    p->pos += 4;
    JVal *v = jnew(J_BOOL);
    v->b = 1;
    return v;
  }
  if (strncmp(p->s + p->pos, "false", 5) == 0) {
    p->pos += 5;
    return jnew(J_BOOL);
  }
  if (strncmp(p->s + p->pos, "null", 4) == 0) {
    p->pos += 4;
    return jnew(J_NULL);
  }
  char *end = NULL;
  JVal *v = jnew(J_NUM);
  v->num = strtod(p->s + p->pos, &end);
  if (end == p->s + p->pos) p->err = 1;
  p->pos = end - p->s;
  return v;
}

static JVal *jparse(const char *s) {
  Parser p = {s, 0, strlen(s), 0};
  JVal *v = pvalue(&p);
  if (p.err) {
    fprintf(stderr, "JSON parse error near byte %zu: %.40s\n", p.pos,
            s + (p.pos < 40 ? 0 : p.pos - 40));
    exit(2);
  }
  return v;
}

/* deep equality; numbers compared as doubles (ints <= 2^53 exact) */
static int jequal(const JVal *a, const JVal *b) {
  if (a->t != b->t) return 0;
  switch (a->t) {
    case J_NULL: return 1;
    case J_BOOL: return a->b == b->b;
    case J_NUM: return a->num == b->num;
    case J_STR: return strcmp(a->str, b->str) == 0;
    case J_ARR:
      if (a->n != b->n) return 0;
      for (size_t i = 0; i < a->n; i++)
        if (!jequal(a->items[i], b->items[i])) return 0;
      return 1;
    case J_OBJ:
      if (a->n != b->n) return 0;
      for (size_t i = 0; i < a->n; i++) {
        JVal *bv = jobj_get(b, a->keys[i]);
        if (!bv || !jequal(a->items[i], bv)) return 0;
      }
      return 1;
  }
  return 0;
}

static void jdump(const JVal *v, FILE *f) {
  switch (v->t) {
    case J_NULL: fputs("null", f); break;
    case J_BOOL: fputs(v->b ? "true" : "false", f); break;
    case J_NUM: fprintf(f, "%g", v->num); break;
    case J_STR: fprintf(f, "\"%s\"", v->str); break;
    case J_ARR:
      fputc('[', f);
      for (size_t i = 0; i < v->n; i++) {
        if (i) fputc(',', f);
        jdump(v->items[i], f);
      }
      fputc(']', f);
      break;
    case J_OBJ:
      fputc('{', f);
      for (size_t i = 0; i < v->n; i++) {
        if (i) fputc(',', f);
        fprintf(f, "\"%s\":", v->keys[i]);
        jdump(v->items[i], f);
      }
      fputc('}', f);
      break;
  }
}

/* ---------------- RPC transport ------------------------------------------- */

static FILE *to_srv, *from_srv;
static int next_id = 1;

static void esc_into(char *dst, size_t cap, const char *s) {
  size_t j = 0;
  for (; *s; s++) {
    if (j + 8 >= cap) { /* fail fast: truncation would corrupt the call */
      fprintf(stderr, "esc_into: payload exceeds %zu-byte buffer\n", cap);
      exit(2);
    }
    unsigned char c = (unsigned char)*s;
    if (c == '"' || c == '\\') {
      dst[j++] = '\\';
      dst[j++] = c;
    } else if (c < 0x20) {
      j += snprintf(dst + j, cap - j, "\\u%04x", c);
    } else {
      dst[j++] = c;
    }
  }
  dst[j] = '\0';
}

/* send {"id":n,"method":m,"params":{<fmt printf-built body>}}; returns the
 * parsed "result" value (caller frees); asserts no error came back */
static JVal *rpc(const char *method, const char *fmt, ...) {
  char params[1 << 16];
  va_list ap;
  va_start(ap, fmt);
  int plen = vsnprintf(params, sizeof params, fmt, ap);
  va_end(ap);
  if (plen < 0 || (size_t)plen >= sizeof params) {
    fprintf(stderr, "rpc: params for %s exceed the request buffer\n", method);
    exit(2);
  }
  fprintf(to_srv, "{\"id\":%d,\"method\":\"%s\",\"params\":{%s}}\n",
          next_id++, method, params);
  fflush(to_srv);
  static char *line = NULL;
  static size_t cap = 0;
  ssize_t n = getline(&line, &cap, from_srv);
  if (n <= 0) {
    fprintf(stderr, "server closed the pipe (method %s)\n", method);
    exit(2);
  }
  JVal *resp = jparse(line);
  JVal *err = jobj_get(resp, "error");
  if (err) {
    fprintf(stderr, "RPC error for %s: ", method);
    jdump(err, stderr);
    fputc('\n', stderr);
    exit(2);
  }
  JVal *res = jobj_get(resp, "result");
  /* detach result from the envelope so the envelope can be freed */
  for (size_t i = 0; i < resp->n; i++)
    if (resp->items[i] == res) resp->items[i] = jnew(J_NULL);
  jfree(resp);
  return res ? res : jnew(J_NULL);
}

/* ---------------- live tree: patch application ----------------------------- */
/* Mirrors interop.rs apply_patch semantics: navigate `path` from the root
 * into live containers, then mutate in place. Text objects are UTF-8
 * strings indexed by CODE POINT (the server's text unit). */

static size_t cp_to_byte(const char *s, size_t cp_index) {
  size_t i = 0, cp = 0;
  while (s[i] && cp < cp_index) {
    i++;
    while ((s[i] & 0xC0) == 0x80) i++;
    cp++;
  }
  return i;
}

static void text_splice(JVal *node, size_t pos, size_t del_cps,
                        const char *ins) {
  const char *old = node->str ? node->str : "";
  size_t b0 = cp_to_byte(old, pos);
  size_t b1 = b0 + cp_to_byte(old + b0, del_cps);
  size_t nlen = strlen(old) - (b1 - b0) + strlen(ins);
  char *out = xmalloc(nlen + 1);
  memcpy(out, old, b0);
  strcpy(out + b0, ins);
  strcat(out, old + b1);
  free(node->str);
  node->str = out;
}

/* patch "value" payloads arrive as plain JSON subtrees (objects/lists
 * materialized); adopt them directly as live nodes */
static JVal *jclone(const JVal *v) {
  JVal *c = jnew(v->t);
  c->b = v->b;
  c->num = v->num;
  if (v->str) c->str = xstrdup(v->str);
  for (size_t i = 0; i < v->n; i++) {
    jgrow(c);
    if (v->t == J_OBJ) c->keys[c->n] = xstrdup(v->keys[i]);
    c->items[c->n] = jclone(v->items[i]);
    c->n++;
  }
  return c;
}

static int conflicts_seen = 0;

static void apply_patch(JVal *root, const JVal *patch) {
  const JVal *path = jobj_get(patch, "path");
  JVal *node = root;
  for (size_t i = 0; path && i < path->n; i++) {
    const JVal *step = path->items[i];  /* [parent_exid, key-or-index] */
    const JVal *key = step->items[1];
    if (node->t == J_OBJ && key->t == J_STR) {
      node = jobj_get(node, key->str);
    } else if (node->t == J_ARR && key->t == J_NUM) {
      size_t idx = (size_t)key->num;
      node = idx < node->n ? node->items[idx] : NULL;
    } else {
      node = NULL;
    }
    if (!node) {
      fprintf(stderr, "patch path does not resolve\n");
      exit(2);
    }
  }
  const char *action = jobj_get(patch, "action")->str;
  if (strcmp(action, "PutMap") == 0) {
    const JVal *c = jobj_get(patch, "conflict");
    if (c && c->t == J_BOOL && c->b) conflicts_seen++;
    jobj_put(node, jobj_get(patch, "key")->str,
             jclone(jobj_get(patch, "value")));
  } else if (strcmp(action, "PutSeq") == 0) {
    const JVal *c = jobj_get(patch, "conflict");
    if (c && c->t == J_BOOL && c->b) conflicts_seen++;
    size_t idx = (size_t)jobj_get(patch, "index")->num;
    if (idx < node->n) {
      jfree(node->items[idx]);
      node->items[idx] = jclone(jobj_get(patch, "value"));
    }
  } else if (strcmp(action, "Insert") == 0) {
    size_t idx = (size_t)jobj_get(patch, "index")->num;
    const JVal *vals = jobj_get(patch, "values");
    for (size_t i = 0; i < vals->n; i++)
      jarr_insert(node, idx + i, jclone(vals->items[i]));
  } else if (strcmp(action, "SpliceText") == 0) {
    text_splice(node, (size_t)jobj_get(patch, "index")->num, 0,
                jobj_get(patch, "value")->str);
  } else if (strcmp(action, "DeleteMap") == 0) {
    jobj_del(node, jobj_get(patch, "key")->str);
  } else if (strcmp(action, "DeleteSeq") == 0) {
    size_t idx = (size_t)jobj_get(patch, "index")->num;
    size_t len = (size_t)jobj_get(patch, "length")->num;
    if (node->t == J_STR) {
      text_splice(node, idx, len, "");
    } else {
      for (size_t i = 0; i < len; i++) jarr_delete(node, idx);
    }
  } else if (strcmp(action, "IncrementPatch") == 0) {
    const JVal *prop = jobj_get(patch, "prop");
    JVal *target = NULL;
    if (node->t == J_OBJ && prop->t == J_STR)
      target = jobj_get(node, prop->str);
    else if (node->t == J_ARR && prop->t == J_NUM &&
             (size_t)prop->num < node->n)
      target = node->items[(size_t)prop->num];
    CHECK(target && target->t == J_NUM);
    if (target && target->t == J_NUM)
      target->num += jobj_get(patch, "value")->num;
  } else if (strcmp(action, "MarkPatch") == 0) {
    /* marks are tracked out-of-tree (materialize has no mark channel);
     * verified against the `marks` RPC read below */
  } else if (strcmp(action, "FlagConflict") == 0) {
    conflicts_seen++;
  } else {
    fprintf(stderr, "unknown patch action %s\n", action);
    exit(2);
  }
}

static void apply_patch_batch(JVal *root, const JVal *patches) {
  for (size_t i = 0; i < patches->n; i++)
    apply_patch(root, patches->items[i]);
}

/* the convergence assertion: live tree == server materialize snapshot */
static void check_converged(JVal *tree, int doc, const char *label) {
  JVal *snap = rpc("materialize", "\"doc\":%d", doc);
  if (!jequal(tree, snap)) {
    failures++;
    fprintf(stderr, "DIVERGED at %s\nlocal:  ", label);
    jdump(tree, stderr);
    fprintf(stderr, "\nserver: ");
    jdump(snap, stderr);
    fputc('\n', stderr);
  } else {
    checks++;
  }
  jfree(snap);
}

/* ---------------- scenario ------------------------------------------------- */

static void pop_and_apply(JVal *tree, int doc) {
  /* popPatches never closes an open transaction; flush pending local
   * edits first so their patches are in this batch */
  jfree(rpc("commit", "\"doc\":%d", doc));
  JVal *patches = rpc("popPatches", "\"doc\":%d", doc);
  apply_patch_batch(tree, patches);
  jfree(patches);
}

/* take an int field out of a result object, freeing the result */
static int res_field_int(JVal *res, const char *field) {
  JVal *f = jobj_get(res, field);
  int v = f && f->t == J_NUM ? (int)f->num : -1;
  jfree(res);
  return v;
}

/* take a string field ("$obj" ids) out of a result object */
static char *res_field_str(JVal *res, const char *field) {
  JVal *f = jobj_get(res, field);
  char *s = f && f->t == J_STR ? xstrdup(f->str) : xstrdup("");
  jfree(res);
  return s;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <server-cmd> [args...]\n", argv[0]);
    return 2;
  }
  int in_pipe[2], out_pipe[2];
  if (pipe(in_pipe) || pipe(out_pipe)) return 2;
  pid_t pid = fork();
  if (pid == 0) {
    dup2(in_pipe[0], 0);
    dup2(out_pipe[1], 1);
    close(in_pipe[1]);
    close(out_pipe[0]);
    execvp(argv[1], argv + 1);
    perror("execvp");
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);
  to_srv = fdopen(in_pipe[1], "w");
  from_srv = fdopen(out_pipe[0], "r");

  /* -- doc A: local edits mirrored into the live tree through patches ---- */
  int a = res_field_int(
      rpc("create", "\"actor\":\"01010101010101010101010101010101\""), "doc");
  CHECK(a > 0);
  JVal *tree = jnew(J_OBJ);
  jfree(rpc("popPatches", "\"doc\":%d", a)); /* pin the patch cursor */

  char *t = res_field_str(
      rpc("putObject", "\"doc\":%d,\"obj\":\"_root\",\"prop\":\"t\","
          "\"type\":\"text\"", a),
      "$obj");
  jfree(rpc("spliceText",
            "\"doc\":%d,\"obj\":\"%s\",\"pos\":0,\"text\":\"hello world\"",
            a, t));
  char *cfg = res_field_str(
      rpc("putObject", "\"doc\":%d,\"obj\":\"_root\",\"prop\":\"cfg\","
          "\"type\":\"map\"", a),
      "$obj");
  jfree(rpc("put", "\"doc\":%d,\"obj\":\"%s\",\"prop\":\"n\",\"value\":7",
            a, cfg));
  jfree(rpc("put", "\"doc\":%d,\"obj\":\"%s\",\"prop\":\"c\","
            "\"value\":{\"$counter\":10}", a, cfg));
  char *lst = res_field_str(
      rpc("putObject", "\"doc\":%d,\"obj\":\"_root\",\"prop\":\"l\","
          "\"type\":\"list\"", a),
      "$obj");
  jfree(rpc("insert", "\"doc\":%d,\"obj\":\"%s\",\"index\":0,"
            "\"value\":\"first\"", a, lst));
  jfree(rpc("insert", "\"doc\":%d,\"obj\":\"%s\",\"index\":1,"
            "\"value\":2.5", a, lst));
  pop_and_apply(tree, a);
  check_converged(tree, a, "initial build");

  /* incremental edits: splice, delete, increment, nested object */
  jfree(rpc("spliceText",
            "\"doc\":%d,\"obj\":\"%s\",\"pos\":5,\"del\":6,"
            "\"text\":\", patched \\u00e9!\"", a, t));
  jfree(rpc("increment",
            "\"doc\":%d,\"obj\":\"%s\",\"prop\":\"c\",\"by\":5", a, cfg));
  jfree(rpc("delete", "\"doc\":%d,\"obj\":\"%s\",\"prop\":\"n\"", a, cfg));
  char *sub = res_field_str(
      rpc("insertObject", "\"doc\":%d,\"obj\":\"%s\",\"index\":1,"
          "\"type\":\"map\"", a, lst),
      "$obj");
  jfree(rpc("put", "\"doc\":%d,\"obj\":\"%s\",\"prop\":\"deep\","
            "\"value\":true", a, sub));
  jfree(rpc("delete", "\"doc\":%d,\"obj\":\"%s\",\"index\":0", a, lst));
  pop_and_apply(tree, a);
  check_converged(tree, a, "incremental edits");

  /* counter survived as a number and incremented */
  {
    JVal *cfg_node = jobj_get(tree, "cfg");
    JVal *cval = cfg_node ? jobj_get(cfg_node, "c") : NULL;
    CHECK(cval && cval->t == J_NUM && cval->num == 15);
  }

  /* -- concurrent fork + merge: remote patches, conflict flags ----------- */
  int b = res_field_int(
      rpc("fork", "\"doc\":%d,\"actor\":"
          "\"02020202020202020202020202020202\"", a),
      "doc");
  CHECK(b > 0);
  jfree(rpc("put", "\"doc\":%d,\"obj\":\"%s\",\"prop\":\"who\","
            "\"value\":\"A\"", a, cfg));
  jfree(rpc("put", "\"doc\":%d,\"obj\":\"%s\",\"prop\":\"who\","
            "\"value\":\"B\"", b, cfg));
  jfree(rpc("spliceText", "\"doc\":%d,\"obj\":\"%s\",\"pos\":0,"
            "\"text\":\">> \"", b, t));
  jfree(rpc("commit", "\"doc\":%d", a));
  jfree(rpc("commit", "\"doc\":%d", b));
  jfree(rpc("merge", "\"doc\":%d,\"other\":%d", a, b));
  int conflicts_before = conflicts_seen;
  pop_and_apply(tree, a);
  check_converged(tree, a, "after merge");
  CHECK(conflicts_seen > conflicts_before); /* "who" conflicted */

  /* -- marks: tracked via the marks read, MarkPatch observed -------------- */
  jfree(rpc("mark", "\"doc\":%d,\"obj\":\"%s\",\"start\":0,\"end\":5,"
            "\"name\":\"bold\",\"value\":true", a, t));
  jfree(rpc("commit", "\"doc\":%d", a));
  JVal *patches = rpc("popPatches", "\"doc\":%d", a);
  int saw_mark = 0;
  for (size_t i = 0; i < patches->n; i++) {
    JVal *act = jobj_get(patches->items[i], "action");
    if (act && strcmp(act->str, "MarkPatch") == 0) saw_mark = 1;
  }
  apply_patch_batch(tree, patches);
  jfree(patches);
  CHECK(saw_mark);
  JVal *marks = rpc("marks", "\"doc\":%d,\"obj\":\"%s\"", a, t);
  CHECK(marks->n == 1);
  if (marks->n == 1) {
    JVal *m0 = marks->items[0];
    CHECK(strcmp(jobj_get(m0, "name")->str, "bold") == 0);
    CHECK(jobj_get(m0, "start")->num == 0);
    CHECK(jobj_get(m0, "end")->num == 5);
  }
  jfree(marks);

  /* -- sync session into a fresh peer, mirrored by its own live tree ----- */
  int c = res_field_int(
      rpc("create", "\"actor\":\"03030303030303030303030303030303\""),
      "doc");
  JVal *tree_c = jnew(J_OBJ);
  jfree(rpc("popPatches", "\"doc\":%d", c));
  int sa = res_field_int(rpc("syncStateNew", ""), "sync");
  int sc = res_field_int(rpc("syncStateNew", ""), "sync");
  for (int round = 0; round < 40; round++) {
    JVal *ma = rpc("generateSyncMessage", "\"doc\":%d,\"sync\":%d", a, sa);
    JVal *mc = rpc("generateSyncMessage", "\"doc\":%d,\"sync\":%d", c, sc);
    int quiet = ma->t == J_NULL && mc->t == J_NULL;
    if (ma->t == J_STR) {
      char esc[1 << 15];
      esc_into(esc, sizeof esc, ma->str);
      jfree(rpc("receiveSyncMessage",
                "\"doc\":%d,\"sync\":%d,\"data\":\"%s\"", c, sc, esc));
    }
    if (mc->t == J_STR) {
      char esc[1 << 15];
      esc_into(esc, sizeof esc, mc->str);
      jfree(rpc("receiveSyncMessage",
                "\"doc\":%d,\"sync\":%d,\"data\":\"%s\"", a, sa, esc));
    }
    jfree(ma);
    jfree(mc);
    if (quiet) break;
  }
  pop_and_apply(tree_c, c);
  check_converged(tree_c, c, "synced peer");
  CHECK(jequal(tree, tree_c)); /* both live trees converged cross-doc */

  jfree(rpc("shutdown", ""));
  fclose(to_srv);
  fclose(from_srv);
  int status = 0;
  waitpid(pid, &status, 0);
  free(t);
  free(cfg);
  free(lst);
  free(sub);
  jfree(tree);
  jfree(tree_c);

  if (failures) {
    fprintf(stderr, "rpc_client: %d/%d assertions FAILED\n", failures,
            checks);
    return 1;
  }
  printf("rpc_client: all assertions passed (%d)\n", checks);
  return 0;
}
