"""Reference Python client for the automerge_tpu line-framed JSON-RPC
protocol, with the retry discipline the cluster expects.

Dependency-free (stdlib only) so bench harnesses and CI scripts can use
it without installing the package; it is also the reference
implementation of the client-side retry contract:

* an error response carrying ``retriable: true`` (Unavailable during a
  failover window, Backpressure from a full shard queue, NotLeader
  mid-promotion, a poisoned-journal degraded doc) is retried with
  **capped exponential backoff + seeded jitter** until the call's
  **deadline budget** is spent;
* ``retriable: false`` (and errors with no flag) surface immediately —
  retrying a genuinely rejected request only hides bugs;
* transport death (connection reset by a dying router/node) redials and
  retries under the same budget;
* the caller sees either a result or ``RpcError`` — never a raw socket
  exception — plus how long the call was blocked and how many attempts
  it took (the double-apply bound for non-idempotent operations).

Usage::

    c = RetryingClient("127.0.0.1:7000", deadline_s=60)
    r = c.call("openDurable", name="doc1")          # retried as needed
    r = c.call("put", doc=r["doc"], obj="_root", prop="k", value=1)
    print(c.last.attempts, c.last.blocked_s)

``applyChanges`` with a pre-built change chunk is the clean retry unit:
it is atomic, durable at ack, and idempotent (change-hash deduplicated),
so an ambiguous retry can never double-apply. ``increment`` and friends
are not idempotent — a retry whose first attempt was applied-but-unacked
may double-apply; ``last.attempts`` bounds that ambiguity.
"""

from __future__ import annotations

import json
import random
import socket
import time
from typing import Any, Dict, List, Optional, Tuple

# legacy servers (and the router's RouterError path before it carried the
# flag) signal outages by type; treat these as retriable when no explicit
# retriable flag is present
RETRIABLE_TYPES = frozenset({
    "Unavailable", "NotLeader", "Backpressure", "RouterError",
    "ReplicationTimeout", "JournalPoisoned",
    "DeadlineExceeded", "Overloaded",
})


class RpcError(Exception):
    """A (final) error response: ``.type``, ``.retriable``, ``.raw``."""

    def __init__(self, err: Dict[str, Any]):
        super().__init__(f"{err.get('type')}: {err.get('message')}")
        self.type = err.get("type")
        self.retriable = bool(err.get("retriable", False))
        self.raw = err


class Deadline(RpcError):
    """The retry budget ran out before a retriable call succeeded."""

    def __init__(self, err: Dict[str, Any], waited: float, attempts: int):
        super().__init__(err)
        self.waited = waited
        self.attempts = attempts


class IntegrityError(RpcError):
    """The server found corrupt stored or replicated state (a digest
    mismatch, a bad snapshot chunk, a journal CRC failure). NEVER
    retriable — retrying re-reads the same damaged bytes — and distinct
    from transient ``RpcError``s so callers can alert instead of loop:
    the right response is operator attention (scrub/repair), not
    backoff."""

    def __init__(self, err: Dict[str, Any]):
        super().__init__(err)
        self.retriable = False


class CallStats:
    """What the previous ``call`` cost: attempts sent and seconds spent
    blocked in backoff/redial (0.0 for a clean first-try success)."""

    __slots__ = ("attempts", "blocked_s", "errors")

    def __init__(self):
        self.attempts = 0
        self.blocked_s = 0.0
        self.errors: List[str] = []


def is_retriable(err: Dict[str, Any]) -> bool:
    """The one place the retry decision lives: an explicit boolean
    ``retriable`` wins; absent one, fall back to the legacy type set."""
    if "retriable" in err:
        return bool(err["retriable"])
    return err.get("type") in RETRIABLE_TYPES


class RetryingClient:
    """One connection to a router/server with the reference retry loop.

    ``deadline_s`` is the default per-call budget; ``call`` takes an
    override. Backoff starts at ``backoff_s`` and doubles to
    ``max_backoff_s`` with seeded jitter — deterministic per seed, like
    everything else in the chaos harness.
    """

    def __init__(
        self,
        address: str | Tuple[str, int],
        *,
        deadline_s: float = 30.0,
        backoff_s: float = 0.05,
        max_backoff_s: float = 1.0,
        seed: int = 0,
        timeout_s: Optional[float] = None,
    ):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self.address = address
        self.deadline_s = deadline_s
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.timeout_s = timeout_s
        self.rng = random.Random(seed)
        self.last = CallStats()
        self._rid = 0
        self._sock: Optional[socket.socket] = None
        self._f = None

    # -- plumbing ------------------------------------------------------------

    def _ensure_conn(self, timeout: Optional[float] = None) -> None:
        if self._sock is not None:
            return
        if timeout is None:
            timeout = self.timeout_s
        sock = socket.create_connection(self.address, timeout=timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._sock = sock
        self._f = sock.makefile("r")

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._f = None

    def close(self) -> None:
        self._drop_conn()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def request(self, method: str, params: Optional[dict] = None,
                trace: Optional[dict] = None,
                timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        """One request, one raw response dict — no retry. Raises OSError
        on transport death OR a garbled frame (both are the retry loop's
        signal to drop the connection and redial — after either, the
        stream can no longer be trusted to be in sync). ``timeout``
        bounds this single attempt: a black-holed response path raises
        ``socket.timeout`` (an OSError) instead of blocking forever."""
        self._ensure_conn(timeout=timeout)
        if timeout is not None or self.timeout_s is not None:
            t = min(x for x in (timeout, self.timeout_s) if x is not None)
            self._sock.settimeout(max(t, 0.05))
        self._rid += 1
        req: Dict[str, Any] = {
            "id": self._rid, "method": method, "params": params or {}}
        if trace is not None:
            req["trace"] = trace
        if deadline_ms is not None and deadline_ms > 0:
            # deadline propagation: the remaining per-call budget rides
            # as a top-level field (like "trace"); router and nodes
            # refuse the request once it expires instead of executing
            # work this client already gave up on
            req["deadlineMs"] = int(deadline_ms)
        try:
            self._sock.sendall((json.dumps(req) + "\n").encode("utf-8"))
            while True:
                raw = self._f.readline()
                if not raw:
                    raise OSError("connection closed mid-request")
                try:
                    resp = json.loads(raw)
                except ValueError as e:
                    # a truncated/garbled line (peer died mid-write, or
                    # a chaos proxy chewed the stream): transport death,
                    # not a caller-visible parse error
                    raise OSError(f"garbled response frame: {e}") from e
                # match by id: a late frame for an abandoned earlier
                # attempt is discarded, exactly per the protocol contract
                if isinstance(resp, dict) and resp.get("id") == self._rid:
                    return resp
        except OSError:
            self._drop_conn()
            raise

    # -- the reference retry loop --------------------------------------------

    def call(self, method: str, *, deadline_s: Optional[float] = None,
             trace: Optional[dict] = None, **params) -> Any:
        """Send with retry-on-retriable. Returns the result; raises
        ``RpcError`` for a non-retriable error, ``Deadline`` when the
        budget runs out. ``self.last`` holds the attempt/blocked stats
        of this call afterwards."""
        budget = self.deadline_s if deadline_s is None else deadline_s
        deadline = time.monotonic() + budget
        stats = CallStats()
        self.last = stats
        backoff = self.backoff_s
        t_first_fail = None
        while True:
            stats.attempts += 1
            err: Dict[str, Any]
            try:
                # each attempt is bounded by what is left of the budget:
                # a peer that receives but never answers (the asymmetric
                # partition) times the attempt out instead of hanging
                # the whole call past its deadline. The same remaining
                # budget ships as deadlineMs, so server and client agree
                # on who gave up.
                attempt_budget = deadline - time.monotonic()
                resp = self.request(method, params, trace=trace,
                                    timeout=max(attempt_budget, 0.05),
                                    deadline_ms=max(attempt_budget, 0.05)
                                    * 1000.0)
                if "error" not in resp:
                    if t_first_fail is not None:
                        stats.blocked_s = time.monotonic() - t_first_fail
                    return resp.get("result")
                err = resp["error"]
                if err.get("type") == "IntegrityError":
                    raise IntegrityError(err)
                if not is_retriable(err):
                    raise RpcError(err)
            except OSError as e:
                err = {"type": "Transport", "message": str(e),
                       "retriable": True}
            if t_first_fail is None:
                t_first_fail = time.monotonic()
            stats.errors.append(str(err.get("type")))
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                stats.blocked_s = time.monotonic() - t_first_fail
                raise Deadline(err, stats.blocked_s, stats.attempts)
            # a server retryAfterMs hint (a shedding node pacing its
            # retries) overrides the exponential schedule: jittered
            # 0.75-1.25x so a shed wave does not re-arrive in lockstep,
            # still capped by max_backoff_s and the remaining budget
            ra = err.get("retryAfterMs")
            if isinstance(ra, (int, float)) and ra > 0:
                hinted = (ra / 1000.0) * (0.75 + 0.5 * self.rng.random())
                sleep = min(hinted, self.max_backoff_s, remaining)
            else:
                # capped exponential backoff with seeded jitter, clamped
                # to the remaining budget so the last sleep cannot
                # overshoot
                sleep = min(backoff * (0.5 + self.rng.random()), remaining)
                backoff = min(backoff * 2, self.max_backoff_s)
            time.sleep(sleep)
