"""Sync protocol: convergence, wire codecs, bloom behavior, state reuse.

Mirrors the reference's in-process sync tests (reference:
rust/automerge/src/sync.rs doctests, javascript/test/sync_test.ts): peers
are values in one process and messages are shuttled as bytes — no
transport needed.
"""

import random

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.sync import (
    BloomFilter,
    Have,
    Message,
    SyncState,
    generate_sync_message,
    receive_sync_message,
    sync,
)
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i):
    return ActorId(bytes([i]) * 16)


def sync_autodocs(a, b, sa=None, sb=None):
    a.commit()
    b.commit()
    return sync(a.doc, b.doc, sa, sb)


def test_empty_docs_converge_immediately():
    a = AutoDoc(actor=actor(1))
    b = AutoDoc(actor=actor(2))
    sync_autodocs(a, b)
    assert a.get_heads() == b.get_heads() == []


def test_one_sided_catchup():
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "hello sync")
    a.commit()
    b = AutoDoc(actor=actor(2))
    sync_autodocs(a, b)
    assert b.get_heads() == a.get_heads()
    assert b.text(t) == "hello sync"


def test_bidirectional_divergence():
    base = AutoDoc(actor=actor(1))
    base.put("_root", "x", 1)
    base.commit()
    b = base.fork(actor=actor(2))
    base.put("_root", "a", "from-a")
    base.commit()
    b.put("_root", "b", "from-b")
    b.commit()
    sync_autodocs(base, b)
    assert base.get_heads() == b.get_heads()
    assert base.hydrate() == b.hydrate() == {"x": 1, "a": "from-a", "b": "from-b"}


def test_multi_round_interleaved_edits():
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "v0")
    a.commit()
    b = a.fork(actor=actor(2))
    sa, sb = sync_autodocs(a, b)
    for i in range(3):
        a.splice_text(t, a.length(t), 0, f" a{i}")
        a.commit()
        b.splice_text(t, 0, 0, f"b{i} ")
        b.commit()
        sa, sb = sync_autodocs(a, b, sa, sb)
        assert a.text(t) == b.text(t)
        assert sorted(sa.shared_heads) == sorted(a.get_heads())


def test_sync_reuses_state_incrementally():
    """After initial sync, new rounds only carry the new changes."""
    a = AutoDoc(actor=actor(1))
    for i in range(20):
        a.put("_root", f"k{i}", i)
        a.commit()
    b = AutoDoc(actor=actor(2))
    sa, sb = sync_autodocs(a, b)
    a.put("_root", "new", True)
    a.commit()
    msg = a.generate_sync_message(sa)
    assert msg is not None
    assert len(msg.changes) == 1  # only the fresh change travels


def test_counter_merge_through_sync():
    a = AutoDoc(actor=actor(1))
    a.put("_root", "c", ScalarValue("counter", 100))
    a.commit()
    b = AutoDoc(actor=actor(2))
    sync_autodocs(a, b)
    a.increment("_root", "c", 5)
    a.commit()
    b.increment("_root", "c", 7)
    b.commit()
    sync_autodocs(a, b)
    assert a.get("_root", "c")[0] == ("counter", 112)
    assert b.get("_root", "c")[0] == ("counter", 112)


def test_message_roundtrip_bytes():
    a = AutoDoc(actor=actor(1))
    a.put("_root", "k", "v")
    a.commit()
    state = SyncState()
    msg = a.generate_sync_message(state)
    data = msg.encode()
    assert data[0] == 0x42
    decoded = Message.decode(data)
    assert decoded.heads == msg.heads
    assert decoded.need == msg.need
    assert len(decoded.have) == len(msg.have)
    assert [c.hash for c in decoded.changes] == [c.hash for c in msg.changes]
    assert decoded.encode() == data


def test_state_roundtrip_bytes():
    a = AutoDoc(actor=actor(1))
    a.put("_root", "k", 1)
    a.commit()
    b = AutoDoc(actor=actor(2))
    sa, sb = sync_autodocs(a, b)
    data = sa.encode()
    assert data[0] == 0x43
    revived = SyncState.decode(data)
    assert revived.shared_heads == sa.shared_heads
    # a revived state still syncs correctly
    a.put("_root", "k2", 2)
    a.commit()
    sync_autodocs(a, b, revived, SyncState())
    assert b.hydrate() == a.hydrate()


def test_peer_data_loss_triggers_reset():
    """If B loses everything, A must do a full resend (reference:
    sync.rs auto-reset when last_sync is unknown)."""
    a = AutoDoc(actor=actor(1))
    a.put("_root", "k", 1)
    a.commit()
    b = AutoDoc(actor=actor(2))
    sa, sb = sync_autodocs(a, b)
    # B is wiped and restarts with the persisted shared_heads state
    b2 = AutoDoc(actor=actor(3))
    sb2 = SyncState.decode(sb.encode())
    sync_autodocs(a, b2, SyncState.decode(sa.encode()), sb2)
    assert b2.hydrate() == a.hydrate()


def test_bloom_false_positive_recovery_via_need():
    """Even if the bloom filter hides every change (forced false positive),
    the explicit need list still fetches what is missing."""
    a = AutoDoc(actor=actor(1))
    a.put("_root", "k", 1)
    a.commit()
    b = AutoDoc(actor=actor(2))
    a.commit()
    b.commit()
    sa, sb = SyncState(), SyncState()
    for _ in range(20):
        ma = generate_sync_message(a.doc, sa)
        if ma is not None:
            # tamper: every bloom claims to contain everything
            for h in ma.have:
                h.bloom.bits = bytearray(b"\xff" * max(len(h.bloom.bits), 2))
                h.bloom.num_entries = max(h.bloom.num_entries, 1)
            receive_sync_message(b.doc, sb, Message.decode(ma.encode()))
        mb = generate_sync_message(b.doc, sb)
        if mb is not None:
            for h in mb.have:
                h.bloom.bits = bytearray(b"\xff" * max(len(h.bloom.bits), 2))
                h.bloom.num_entries = max(h.bloom.num_entries, 1)
            receive_sync_message(a.doc, sa, Message.decode(mb.encode()))
        if ma is None and mb is None:
            break
    assert b.hydrate() == a.hydrate() == {"k": 1}


def test_malformed_messages_raise_syncerror():
    import pytest as _pytest
    from automerge_tpu.sync import SyncError

    a = AutoDoc(actor=actor(1))
    a.put("_root", "k", 1)
    a.commit()
    msg = a.generate_sync_message(SyncState()).encode()
    for bad in (
        b"",
        b"\x41\x00",
        msg[:5],
        msg[:-3],
        msg + b"",  # sanity: well-formed decodes
    ):
        if bad == msg:
            Message.decode(bad)
            continue
        with _pytest.raises(SyncError):
            Message.decode(bad)
    # hostile bloom parameters must be rejected, not looped on
    hostile = bytearray([0x42, 0]) + bytearray([0]) + bytearray([1])
    hostile += bytes([0])  # last_sync count 0
    from automerge_tpu.utils.leb128 import uleb_bytes

    bloom = uleb_bytes(1) + uleb_bytes(10) + uleb_bytes(10**15) + b"\x00\x02"
    hostile += uleb_bytes(len(bloom)) + bloom
    hostile += bytes([0])  # changes count 0
    with _pytest.raises(SyncError):
        Message.decode(bytes(hostile))


def test_bloom_filter_basics():
    import hashlib

    hashes = [hashlib.sha256(bytes([i])).digest() for i in range(100)]
    f = BloomFilter.from_hashes(hashes)
    assert all(f.contains(h) for h in hashes)
    other = [hashlib.sha256(b"x" + bytes([i])).digest() for i in range(200)]
    fp = sum(f.contains(h) for h in other)
    assert fp <= 12  # ~1% expected with 10 bits/entry; generous slack
    assert BloomFilter.from_bytes(f.to_bytes()) == f
    assert BloomFilter.from_bytes(b"") == BloomFilter()
    assert not BloomFilter().contains(hashes[0])


def test_random_topology_convergence():
    rng = random.Random(42)
    docs = [AutoDoc(actor=actor(10 + i)) for i in range(4)]
    docs[0].put("_root", "seed", 1)
    docs[0].commit()
    for d in docs[1:]:
        sync_autodocs(docs[0], d)
    lst = docs[0].put_object("_root", "l", ObjType.LIST)
    docs[0].commit()
    for d in docs[1:]:
        sync_autodocs(docs[0], d)
    for step in range(10):
        d = rng.choice(docs)
        ln = d.length(lst)
        d.insert(lst, rng.randrange(ln + 1), step)
        d.commit()
        x, y = rng.sample(range(len(docs)), 2)
        sync_autodocs(docs[x], docs[y])
    # full pairwise sweep to settle
    for i in range(len(docs)):
        for j in range(i + 1, len(docs)):
            sync_autodocs(docs[i], docs[j])
    states = [d.hydrate() for d in docs]
    assert all(s == states[0] for s in states)
