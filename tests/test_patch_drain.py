"""Incremental PatchLog drain: correctness vs the full walk, and cost
scaling with edit size instead of document size.

Reference bar: the event-log PatchLog costs O(ops applied) per drain
(reference: rust/automerge/src/patches/patch_log.rs:43-103). The
heads-cursor design recovers that via diff_incremental — these tests pin
both the equivalence (randomized, against apply_patches materialization
and against the full diff) and the asymptotics (drain after one edit on a
large doc must not walk the doc).
"""

import time

import numpy as np
import pytest

from automerge_tpu import patches as P
from automerge_tpu.api import AutoDoc
from automerge_tpu.patches import apply_patches
from automerge_tpu.patches.diff import diff, diff_incremental
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i: int) -> ActorId:
    return ActorId(bytes([i]) * 16)


class Tracker:
    def __init__(self, doc: AutoDoc):
        self.state = {}
        doc.set_patch_callback(lambda ps: self._apply(ps), from_scratch=True)

    def _apply(self, ps):
        self.state = apply_patches(self.state, ps)


def test_randomized_drain_tracks_hydrate():
    """Random mutation batches over maps/lists/text/counters/nested objects
    + merges; the observer view must track hydrate() after every drain."""
    rng = np.random.default_rng(42)
    d = AutoDoc(actor=actor(1))
    t = Tracker(d)
    text = d.put_object("_root", "text", ObjType.TEXT)
    lst = d.put_object("_root", "list", ObjType.LIST)
    d.put("_root", "cnt", ScalarValue("counter", 0))
    d.commit()
    nested = []
    for round_ in range(30):
        n_ops = int(rng.integers(1, 8))
        for _ in range(n_ops):
            kind = int(rng.integers(0, 8))
            if kind == 0:
                d.put("_root", f"k{int(rng.integers(0, 6))}", int(rng.integers(0, 100)))
            elif kind == 1:
                ln = d.length(text)
                pos = int(rng.integers(0, ln + 1))
                ndel = int(rng.integers(0, min(3, ln - pos) + 1))
                d.splice_text(text, pos, ndel, "ab"[: int(rng.integers(0, 3))])
            elif kind == 2:
                ln = d.length(lst)
                d.insert(lst, int(rng.integers(0, ln + 1)), int(rng.integers(0, 50)))
            elif kind == 3 and d.length(lst):
                d.delete(lst, int(rng.integers(0, d.length(lst))))
            elif kind == 4:
                d.increment("_root", "cnt", int(rng.integers(-2, 3)))
            elif kind == 5:
                o = d.put_object("_root", f"o{int(rng.integers(0, 3))}", ObjType.MAP)
                nested.append(o)
            elif kind == 6 and nested:
                o = nested[int(rng.integers(0, len(nested)))]
                try:
                    d.put(o, f"p{int(rng.integers(0, 4))}", int(rng.integers(0, 9)))
                except Exception:
                    pass  # object may have been overwritten
            elif kind == 7 and d.length(lst):
                d.put(lst, int(rng.integers(0, d.length(lst))), "x")
        d.commit()  # commit fires the observer drain
        assert t.state == d.hydrate(), f"diverged at round {round_}"


def test_merge_route_drain_tracks_hydrate():
    """Fork/merge (the batched apply path) drains incrementally too."""
    d = AutoDoc(actor=actor(1))
    text = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(text, 0, 0, "base text here")
    d.commit()
    t = Tracker(d)
    forks = [d.fork(actor=actor(10 + i)) for i in range(4)]
    for i, f in enumerate(forks):
        f.splice_text(text, i, 1, f"({i})")
        f.put("_root", f"w{i}", i)
        f.commit()
    for f in forks:
        d.merge(f)
        assert t.state == d.hydrate()


def test_incremental_matches_full_diff_semantically():
    """diff_incremental's patches materialize the same state as diff's."""
    d = AutoDoc(actor=actor(1))
    text = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(text, 0, 0, "hello world")
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        d.insert(lst, i, i)
    d.commit()
    before_heads = d.get_heads()
    before_len = len(d.doc.history)
    before_hyd = d.hydrate()
    d.splice_text(text, 0, 5, "goodbye")
    d.delete(lst, 2)
    d.insert(lst, 0, "first")
    d.put("_root", "new", True)
    d.commit()
    after_heads = d.get_heads()
    new = d.doc.history[before_len:]
    full = diff(d.doc, before_heads, after_heads)
    inc = diff_incremental(
        d.doc, d.doc.clock_at(before_heads), d.doc.clock_at(after_heads), new
    )
    assert inc is not None
    import copy

    got_inc = apply_patches(copy.deepcopy(before_hyd), inc)
    got_full = apply_patches(copy.deepcopy(before_hyd), full)
    assert got_inc == got_full == d.hydrate()


def test_drain_with_pending_tx_falls_back():
    """A live transaction's eagerly-applied ops skew current-state
    positions; the drain must fall back to the clock-filtered full walk
    (review repro: PutSeq index off by the uncommitted insert)."""
    d = AutoDoc(actor=actor(1))
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        d.insert(lst, i, i)
    d.commit()
    # activate the log WITHOUT a callback so commits do not auto-drain
    d.patch_log.set_active(True)
    d.patch_log.reset(d.doc)
    d.put(lst, 2, "changed")
    d.commit()
    # reopen an implicit transaction with a pending op, then drain manually
    d.insert(lst, 0, "uncommitted")
    patches = d.make_patches()
    put = [p for p in patches if type(p.action).__name__ == "PutSeq"]
    assert put and put[0].action.index == 2, patches
    d.commit()


def test_nested_object_in_text_matches_full_walk():
    """The full walk never recurses into objects nested in TEXT; the fast
    path must suppress those content patches too (review repro)."""
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "abc")
    o = d.insert_object(t, 1, ObjType.MAP)
    d.commit()
    before_heads = d.get_heads()
    before_len = len(d.doc.history)
    d.put(o, "k", 1)
    d.commit()
    full = diff(d.doc, before_heads, d.get_heads())
    inc = diff_incremental(
        d.doc,
        d.doc.clock_at(before_heads),
        d.doc.clock_at(d.get_heads()),
        d.doc.history[before_len:],
    )
    assert inc is not None
    assert [(p.obj, str(p.action)) for p in inc] == [
        (p.obj, str(p.action)) for p in full
    ]


def test_drain_scales_with_edit_not_doc():
    """On a ~60k-op text doc, single-edit drains must use the incremental
    path and stay orders of magnitude under a full walk. The DRAIN alone
    is timed — commit pays change encoding and the splice pays session
    re-init, neither of which is the path under test."""
    import automerge_tpu.patches.patch_log as PL

    d = AutoDoc(actor=actor(1))
    text = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text_many(text, [[i, 0, "x"] for i in range(60_000)])
    d.commit()
    # activate without a callback: commits leave the cursor alone, each
    # drain is an explicit make_patches call we can time in isolation
    d.patch_log.set_active(True)
    d.patch_log.reset(d.doc)

    fallbacks = 0
    real_inc = PL.diff_incremental

    def counting(doc, b, a, new):
        nonlocal fallbacks
        r = real_inc(doc, b, a, new)
        if r is None:
            fallbacks += 1
        return r

    PL.diff_incremental = counting
    # a gen-2 GC pause inside one timed drain costs tens of ms (the whole
    # suite's live object graph is scanned) and swamps the asymptotics this
    # test pins; GC timing is not the path under test
    import gc

    gc.disable()
    try:
        dt_inc = 0.0
        drained = 0
        for i in range(50):
            d.splice_text(text, i * 7 % 50_000, 0, "y")
            d.commit()
            t0 = time.perf_counter()
            ps = d.make_patches()
            dt_inc += time.perf_counter() - t0
            drained += len(ps)
    finally:
        gc.enable()
        PL.diff_incremental = real_inc
    assert drained == 50 and fallbacks == 0

    # one full walk for comparison (the pre-round-3 per-drain cost)
    t0 = time.perf_counter()
    diff(d.doc, [], d.get_heads())
    dt_full = time.perf_counter() - t0
    # 50 incremental drains must beat ONE full walk with real margin
    assert dt_inc * 2 < dt_full, (dt_inc, dt_full)


def test_mark_patches_emitted_and_equivalent():
    """Mark changes reach observers (reference: diff.rs MarkDiff) with
    replace-all span semantics, identically from the full walk and the
    incremental drain — including position shifts from plain text edits
    inside marked ranges."""
    from automerge_tpu.patches.patch import MarkPatch

    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "styled text here")
    d.commit()
    before_heads = d.get_heads()
    before_len = len(d.doc.history)
    d.mark(t, 0, 6, "bold", True)
    d.commit()
    full = diff(d.doc, before_heads, d.get_heads())
    inc = diff_incremental(
        d.doc, d.doc.clock_at(before_heads), d.doc.clock_at(d.get_heads()),
        d.doc.history[before_len:],
    )
    assert inc is not None
    fm = [p for p in full if isinstance(p.action, MarkPatch)]
    im = [p for p in inc if isinstance(p.action, MarkPatch)]
    assert len(fm) == len(im) == 1
    spans = [(m.start, m.end, m.name, m.value) for m in fm[0].action.marks]
    assert spans == [(0, 6, "bold", True)]
    assert spans == [(m.start, m.end, m.name, m.value) for m in im[0].action.marks]

    # a plain edit inside the marked range shifts the span -> new MarkPatch
    before_heads = d.get_heads()
    before_len = len(d.doc.history)
    d.splice_text(t, 2, 0, "XX")
    d.commit()
    inc2 = diff_incremental(
        d.doc, d.doc.clock_at(before_heads), d.doc.clock_at(d.get_heads()),
        d.doc.history[before_len:],
    )
    full2 = diff(d.doc, before_heads, d.get_heads())
    im2 = [p for p in inc2 if isinstance(p.action, MarkPatch)]
    fm2 = [p for p in full2 if isinstance(p.action, MarkPatch)]
    assert len(im2) == len(fm2) == 1
    assert [(m.start, m.end) for m in im2[0].action.marks] == [(0, 8)]

    # unmark clears -> MarkPatch with an empty span set
    before_heads = d.get_heads()
    before_len = len(d.doc.history)
    d.unmark(t, 0, 8, "bold")
    d.commit()
    inc3 = diff_incremental(
        d.doc, d.doc.clock_at(before_heads), d.doc.clock_at(d.get_heads()),
        d.doc.history[before_len:],
    )
    im3 = [p for p in inc3 if isinstance(p.action, MarkPatch)]
    assert len(im3) == 1 and im3[0].action.marks == []

    # observer route delivers mark records through the C shim encoding
    from automerge_tpu.capi import shim
    h = shim.call("create", b"\x07" * 16)[0][1]
    doc2 = shim._docs[h]
    t2 = doc2.put_object("_root", "t", ObjType.TEXT)
    doc2.splice_text(t2, 0, 0, "abc")
    doc2.commit()
    shim.call("pop_patches", h)  # activate
    doc2.mark(t2, 0, 2, "em", True)
    doc2.commit()
    items = shim.call("pop_patches", h)
    kinds = [items[i + 2][1] for i in range(0, len(items), 6)]
    assert "mark" in kinds and "mark_end" in kinds
    shim.call("free", h)


def test_list_mark_patches_and_clear_records():
    """Marks on LIST objects reach the diff (review find) and the C-record
    framing carries a mark_clear so an emptied set is observable."""
    from automerge_tpu.capi import shim
    from automerge_tpu.patches.patch import MarkPatch

    d = AutoDoc(actor=actor(1))
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        d.insert(lst, i, i)
    d.commit()
    before_heads = d.get_heads()
    before_len = len(d.doc.history)
    d.mark(lst, 0, 3, "sel", True)
    d.commit()
    full = diff(d.doc, before_heads, d.get_heads())
    inc = diff_incremental(
        d.doc, d.doc.clock_at(before_heads), d.doc.clock_at(d.get_heads()),
        d.doc.history[before_len:],
    )
    fm = [p for p in full if isinstance(p.action, MarkPatch)]
    im = [p for p in inc if isinstance(p.action, MarkPatch)]
    assert len(fm) == len(im) == 1
    assert [(m.start, m.end) for m in fm[0].action.marks] == [(0, 3)]

    # shim framing: clear record + span pair; after unmark: clear alone
    h = shim.call("create", b"\x08" * 16)[0][1]
    doc2 = shim._docs[h]
    t2 = doc2.put_object("_root", "t", ObjType.TEXT)
    doc2.splice_text(t2, 0, 0, "abc")
    doc2.commit()
    shim.call("pop_patches", h)
    doc2.mark(t2, 0, 2, "em", True)
    doc2.commit()
    items = shim.call("pop_patches", h)
    kinds = [items[i + 2][1] for i in range(0, len(items), 6)]
    assert kinds == ["mark_clear", "mark", "mark_end"]
    doc2.unmark(t2, 0, 2, "em")
    doc2.commit()
    items = shim.call("pop_patches", h)
    kinds = [items[i + 2][1] for i in range(0, len(items), 6)]
    assert kinds == ["mark_clear"]
    shim.call("free", h)


def test_marked_doc_drain_still_scales():
    """A single mark near the front must not force O(object) span
    resolution for edits far past it (the block-bound pre-check)."""
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text_many(t, [[i, 0, "x"] for i in range(40_000)])
    d.commit()
    d.mark(t, 0, 50, "bold", True)
    d.commit()
    d.patch_log.set_active(True)
    d.patch_log.reset(d.doc)

    calls = 0
    from automerge_tpu.core import marks as M

    real_calc = M.calculate_marks

    def counting(*a, **k):
        nonlocal calls
        calls += 1
        return real_calc(*a, **k)

    M.calculate_marks = counting
    try:
        # edits far beyond the marked prefix: no span resolution
        for i in range(5):
            d.splice_text(t, 30_000 + i, 0, "y")
            d.commit()
            d.make_patches()
        far_calls = calls
        # an edit inside the marked range DOES resolve spans
        d.splice_text(t, 10, 0, "z")
        d.commit()
        ps = d.make_patches()
    finally:
        M.calculate_marks = real_calc
    assert far_calls == 0, far_calls
    assert calls > 0
    from automerge_tpu.patches.patch import MarkPatch

    assert any(isinstance(p.action, MarkPatch) for p in ps)
