"""Marks: span resolution, expand policies, merge/sync/save-load transport.

Mirrors the reference's mark tests (reference:
rust/automerge/tests/test_mark_patches.rs, automerge-wasm test/marks).
"""

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.core.marks import Mark
from automerge_tpu.types import ActorId, ObjType


def actor(i):
    return ActorId(bytes([i]) * 16)


def make_text(content="the quick fox", a=1):
    d = AutoDoc(actor=actor(a))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, content)
    d.commit()
    return d, t


def test_basic_mark_span():
    d, t = make_text("hello world")
    d.mark(t, 0, 5, "bold", True)
    d.commit()
    assert d.marks(t) == [Mark(0, 5, "bold", True)]


def test_mark_value_and_multiple_names():
    d, t = make_text("abcdef")
    d.mark(t, 0, 4, "bold", True)
    d.mark(t, 2, 6, "link", "https://x")
    d.commit()
    assert d.marks(t) == [
        Mark(0, 4, "bold", True),
        Mark(2, 6, "link", "https://x"),
    ]


def test_unmark_removes_span():
    d, t = make_text("abcdef")
    d.mark(t, 0, 6, "bold", True)
    d.commit()
    d.unmark(t, 1, 3, "bold")
    d.commit()
    assert d.marks(t) == [Mark(0, 1, "bold", True), Mark(3, 6, "bold", True)]


def test_overlapping_same_name_later_wins():
    d, t = make_text("abcdef")
    d.mark(t, 0, 6, "size", 10)
    d.commit()
    d.mark(t, 2, 4, "size", 20)
    d.commit()
    assert d.marks(t) == [
        Mark(0, 2, "size", 10),
        Mark(2, 4, "size", 20),
        Mark(4, 6, "size", 10),
    ]


def test_expand_after_grows_with_typing():
    d, t = make_text("ab")
    d.mark(t, 0, 2, "bold", True, expand="after")
    d.commit()
    d.splice_text(t, 2, 0, "XY")  # typed at the end boundary
    d.commit()
    assert d.text(t) == "abXY"
    assert d.marks(t) == [Mark(0, 4, "bold", True)]


def test_expand_none_does_not_grow():
    d, t = make_text("ab")
    d.mark(t, 0, 2, "bold", True, expand="none")
    d.commit()
    d.splice_text(t, 2, 0, "XY")
    d.splice_text(t, 0, 0, "Z")
    d.commit()
    assert d.text(t) == "ZabXY"
    assert d.marks(t) == [Mark(1, 3, "bold", True)]


def test_expand_before():
    d, t = make_text("ab")
    d.mark(t, 0, 2, "bold", True, expand="before")
    d.commit()
    d.splice_text(t, 0, 0, "Z")
    d.splice_text(t, 3, 0, "Y")
    d.commit()
    assert d.text(t) == "ZabY"
    assert d.marks(t) == [Mark(0, 3, "bold", True)]


def test_expand_both():
    d, t = make_text("ab")
    d.mark(t, 0, 2, "bold", True, expand="both")
    d.commit()
    d.splice_text(t, 0, 0, "Z")
    d.splice_text(t, 3, 0, "Y")
    d.commit()
    assert d.marks(t) == [Mark(0, 4, "bold", True)]


def test_mark_survives_save_load():
    d, t = make_text("persistent")
    d.mark(t, 0, 6, "em", True, expand="none")
    d.commit()
    d2 = AutoDoc.load(d.save())
    assert d2.marks(t) == [Mark(0, 6, "em", True)]


def test_mark_travels_through_merge():
    d, t = make_text("shared text")
    f = d.fork(actor=actor(2))
    f.mark(t, 0, 6, "bold", True)
    f.commit()
    d.merge(f)
    assert d.marks(t) == [Mark(0, 6, "bold", True)]


def test_mark_travels_through_sync():
    from automerge_tpu.sync import sync

    d, t = make_text("over the wire")
    d.mark(t, 5, 8, "link", "u")
    d.commit()
    b = AutoDoc(actor=actor(2))
    d.commit()
    b.commit()
    sync(d.doc, b.doc)
    assert b.marks(t) == [Mark(5, 8, "link", "u")]


def test_concurrent_edit_inside_marked_span():
    d, t = make_text("bold text here")
    d.mark(t, 0, 9, "bold", True)
    d.commit()
    f = d.fork(actor=actor(2))
    f.splice_text(t, 4, 0, "er")  # insert inside the span
    f.commit()
    d.merge(f)
    assert d.text(t) == "bolder text here"
    assert d.marks(t) == [Mark(0, 11, "bold", True)]


def test_deleted_span_chars_shrink_mark():
    d, t = make_text("abcdef")
    d.mark(t, 1, 5, "bold", True)
    d.commit()
    d.splice_text(t, 2, 2, "")  # delete two marked chars
    d.commit()
    assert d.text(t) == "abef"
    assert d.marks(t) == [Mark(1, 3, "bold", True)]


def test_marks_at_historical_heads():
    d, t = make_text("history")
    h1 = d.get_heads()
    d.mark(t, 0, 4, "bold", True)
    d.commit()
    h2 = d.get_heads()
    assert d.marks(t, heads=h1) == []
    assert d.marks(t, heads=h2) == [Mark(0, 4, "bold", True)]


def test_marks_do_not_break_device_merge():
    from automerge_tpu.ops import DeviceDoc

    d, t = make_text("kernel safe")
    d.mark(t, 0, 6, "bold", True)
    d.commit()
    f = d.fork(actor=actor(2))
    f.splice_text(t, 11, 0, "!")
    f.commit()
    dev = DeviceDoc.merge([d, f])
    host = AutoDoc(actor=actor(9))
    host.merge(d)
    host.merge(f)
    assert dev.text(t) == host.text(t) == "kernel safe!"
    assert dev.length(t) == host.length(t)
