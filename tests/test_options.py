"""Options + observability parity: SaveOptions/LoadOptions analogues,
dump(), tracing, text width encodings.

Reference surface: automerge.rs:41-135 (LoadOptions: OnPartialLoad,
VerificationMode, StringMigration), 959-973 (SaveOptions retain_orphans,
save_and_verify), 1190-1239 (dump), 1567-1610 (text migration);
text_value.rs:5-15 (width per encoding).
"""

from __future__ import annotations

import io
import logging

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc
from automerge_tpu.testing import assert_doc, map_, new_doc, text_
from automerge_tpu.types import (
    ActorId,
    ObjType,
    get_text_encoding,
    set_text_encoding,
)


def test_save_retains_orphans():
    """Causally-unready changes survive a save/load cycle by default."""
    doc = new_doc(1)
    doc.put("_root", "a", 1)
    doc.commit()

    # a change whose dependency this doc never sees -> parked in the queue
    other = doc.fork(actor=ActorId(bytes([9]) * 16))
    other.put("_root", "b", 2)
    other.commit()
    dep_hash = other.get_heads()[0]
    other.put("_root", "c", 3)
    other.commit()
    orphan = other.get_changes([])[-1]
    assert orphan.dependencies == [dep_hash]

    doc.apply_changes([orphan])
    assert doc.get("_root", "c") is None  # queued, not applied

    reloaded = AutoDoc.load(doc.save())
    # the orphan rode along; delivering its dependency completes it
    dep = next(c for c in other.get_changes([]) if c.hash == dep_hash)
    reloaded.apply_changes([dep])
    assert reloaded.get("_root", "c") is not None

    # and retain_orphans=False drops it
    bare = AutoDoc.load(doc.save(retain_orphans=False))
    bare.apply_changes([dep])
    assert bare.get("_root", "c") is None


def test_save_and_verify():
    doc = new_doc(2)
    doc.put("_root", "x", 1)
    data = doc.save_and_verify()
    assert AutoDoc.load(data).get("_root", "x") is not None


def test_string_migration_convert_to_text():
    doc = new_doc(3)
    doc.put("_root", "title", "hello")
    lst = doc.put_object("_root", "lst", ObjType.LIST)
    doc.insert(lst, 0, "world")
    doc.insert(lst, 1, 42)
    t = doc.put_object("_root", "t", ObjType.TEXT)
    doc.splice_text(t, 0, 0, "stays scalar chars")
    doc.commit()

    migrated = AutoDoc.load(doc.save(), string_migration="convert_to_text")
    got = migrated.get("_root", "title")
    assert got[0][0] == "obj" and got[0][1] == ObjType.TEXT
    assert migrated.text(got[0][2]) == "hello"
    lgot = migrated.get(lst, 0)
    assert lgot[0][0] == "obj" and lgot[0][1] == ObjType.TEXT
    assert migrated.text(lgot[0][2]) == "world"
    assert migrated.get(lst, 1)[0][0] == "scalar"  # non-strings untouched
    assert migrated.text(t) == "stays scalar chars"  # text chars untouched

    # the migration is ordinary history: it merges and survives save/load
    again = AutoDoc.load(migrated.save())
    assert again.text(got[0][2]) == "hello"


def test_dump_prints_op_table():
    doc = new_doc(4)
    doc.put("_root", "k", 1)
    t = doc.put_object("_root", "t", ObjType.TEXT)
    doc.splice_text(t, 0, 0, "ab")
    doc.splice_text(t, 0, 1, "")
    doc.put("_root", "k", 2)
    doc.commit()
    buf = io.StringIO()
    doc.doc.dump(file=buf)
    out = buf.getvalue()
    assert "id" in out and "pred" in out and "succ" in out
    assert "make(text)" in out
    assert "int:1" in out and "int:2" in out
    # delete ops are not stored (they live as succ entries, like the
    # reference's doc format): the deleted char row shows its successor
    lines = out.strip().splitlines()
    a_row = next(l for l in lines if "str:'a'" in l)
    assert "@" in a_row.split("str:'a'")[1], "deleted char should show succ"
    n_ops = sum(len(c.ops) for c in doc.get_changes([]))
    n_deletes = sum(
        1 for c in doc.get_changes([]) for op in c.ops if op.action == 3
    )
    assert len(lines) == 1 + n_ops - n_deletes


def test_tracing_hooks_emit_when_enabled():
    from automerge_tpu import trace

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = Capture()
    trace.logger.addHandler(h)
    old_level = trace.logger.level
    trace.logger.setLevel(logging.DEBUG)
    try:
        doc = new_doc(5)
        doc.put("_root", "x", 1)
        doc.commit()
        data = doc.save()
        AutoDoc.load(data)
        doc2 = new_doc(6)
        doc2.apply_changes(doc.get_changes([]))
    finally:
        trace.logger.removeHandler(h)
        trace.logger.setLevel(old_level)
    joined = "\n".join(records)
    assert "commit" in joined
    assert "save" in joined
    assert "load" in joined
    assert "apply_changes" in joined


def test_tracing_silent_when_disabled():
    from automerge_tpu import trace

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Capture()
    trace.logger.addHandler(h)
    trace.logger.setLevel(logging.WARNING)
    try:
        doc = new_doc(7)
        doc.put("_root", "x", 1)
        doc.commit()
    finally:
        trace.logger.removeHandler(h)
    assert records == []


@pytest.fixture
def restore_encoding():
    old = get_text_encoding()
    yield
    set_text_encoding(old)


def test_text_width_encodings(restore_encoding):
    """Index units per encoding (reference: text_value.rs, Op::width).

    "a🐻b" is 3 code points, 6 UTF-8 bytes, 4 UTF-16 units.
    """
    s = "a\U0001f43bb"

    def build():
        doc = AutoDoc(actor=ActorId(bytes([1]) * 16))
        t = doc.put_object("_root", "t", ObjType.TEXT)
        for i, ch in enumerate(s):
            doc.splice_text(t, doc.length(t), 0, ch)
        doc.commit()
        return doc, t

    set_text_encoding("unicode")
    doc, t = build()
    assert doc.length(t) == 3
    assert doc.get(t, 1)[0] == ("scalar", ("str", "\U0001f43b"))

    set_text_encoding("utf16")
    doc, t = build()
    assert doc.length(t) == 4
    # index 1 and 2 both land inside the bear's two UTF-16 units
    assert doc.get(t, 1)[0] == ("scalar", ("str", "\U0001f43b"))
    assert doc.get(t, 2)[0] == ("scalar", ("str", "\U0001f43b"))
    assert doc.get(t, 3)[0] == ("scalar", ("str", "b"))
    # device path agrees on widths
    dev = DeviceDoc.merge([doc])
    assert dev.length(t) == 4

    set_text_encoding("utf8")
    doc, t = build()
    assert doc.length(t) == 6
    assert doc.get(t, 4)[0] == ("scalar", ("str", "\U0001f43b"))
    assert doc.get(t, 5)[0] == ("scalar", ("str", "b"))
    dev = DeviceDoc.merge([doc])
    assert dev.length(t) == 6


def test_per_document_text_encoding_coexists():
    """Two documents with DIFFERENT width units in one process (reference
    makes the unit a build/doc property, text_value.rs:5-15): each
    document's reads, edits, forks and device path count in its own unit,
    with no process-global flips."""
    s = "a\U0001f43bb"  # 3 code points, 6 utf-8 bytes, 4 utf-16 units

    du = AutoDoc(actor=ActorId(bytes([1]) * 16), text_encoding="unicode")
    d8 = AutoDoc(actor=ActorId(bytes([2]) * 16), text_encoding="utf8")
    d16 = AutoDoc(actor=ActorId(bytes([3]) * 16), text_encoding="utf16")
    objs = []
    for d in (du, d8, d16):
        t = d.put_object("_root", "t", ObjType.TEXT)
        for ch in s:
            d.splice_text(t, d.length(t), 0, ch)
        d.commit()
        objs.append(t)
    # interleaved reads: each doc keeps its own unit
    assert du.length(objs[0]) == 3
    assert d8.length(objs[1]) == 6
    assert d16.length(objs[2]) == 4
    assert d16.get(objs[2], 2)[0] == ("scalar", ("str", "\U0001f43b"))
    assert d8.get(objs[1], 4)[0] == ("scalar", ("str", "\U0001f43b"))
    # forks inherit the encoding
    f16 = d16.fork(actor=ActorId(bytes([9]) * 16))
    assert f16.doc.text_encoding == "utf16"
    assert f16.length(objs[2]) == 4
    # save/load: the load option fixes the unit per loaded doc
    saved = d16.save()
    l8 = AutoDoc.load(saved, text_encoding="utf8")
    l16 = AutoDoc.load(saved, text_encoding="utf16")
    assert l8.length(objs[2]) == 6
    assert l16.length(objs[2]) == 4
    # device path follows the doc's unit
    dev = DeviceDoc.merge([d16])
    assert dev.length(objs[2]) == 4
    dev8 = DeviceDoc.merge([AutoDoc.load(saved, text_encoding="utf8")])
    assert dev8.length(objs[2]) == 6


def test_per_document_encoding_splice_positions():
    """Splice positions count in the document's unit (utf-16 here), and
    the bulk-ingest path agrees with the per-edit path."""
    d = AutoDoc(actor=ActorId(bytes([5]) * 16), text_encoding="utf16")
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "x\U0001f43by")  # widths 1,2,1
    d.splice_text(t, 3, 1, "z")  # position 3 = after the bear
    d.commit()
    assert d.text(t) == "x\U0001f43bz"
    b = AutoDoc(actor=ActorId(bytes([6]) * 16), text_encoding="utf16")
    tb = b.put_object("_root", "t", ObjType.TEXT)
    b.splice_text_many(tb, [[0, 0, "x\U0001f43by"], [3, 1, "z"]])
    b.commit()
    assert b.text(tb) == "x\U0001f43bz"
