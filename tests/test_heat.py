"""Doc-heat accounting (obs/heat.py): bounded space-saving table of
per-document decayed rates, deterministic under explicit clocks, and
gauge-publication hygiene (stale series removed)."""

import math

from automerge_tpu import obs
from automerge_tpu.obs import heat
from automerge_tpu.obs.heat import HeatTable


def test_note_and_snapshot_rates():
    t = HeatTable(cap=8, half_life=60.0, enabled=True)
    for _ in range(10):
        t.note("a", "read", now=100.0)
    t.note("a", "bytes", 4096, now=100.0)
    snap = t.snapshot(now=100.0)
    assert snap["docs"] == 1 and snap["evictions"] == 0
    e = snap["entries"][0]
    assert e["doc"] == "a"
    # 10 undecayed read events -> rank 10, rate 10 * ln2 / half_life
    assert e["rank"] == 10.0
    assert math.isclose(e["rates"]["read"], 10 * math.log(2) / 60.0)
    assert e["totals"]["read"] == 10.0 and e["totals"]["bytes"] == 4096.0
    # bytes do not contribute to rank (unit mismatch would drown counts)
    t.note("b", "bytes", 1e9, now=100.0)
    snap = t.snapshot(now=100.0)
    assert [x["doc"] for x in snap["entries"]] == ["a", "b"]
    assert snap["entries"][1]["rank"] == 0.0


def test_decay_half_life():
    t = HeatTable(cap=8, half_life=10.0, enabled=True)
    t.note("a", "write", 8.0, now=0.0)
    e = t.snapshot(now=10.0)["entries"][0]
    assert math.isclose(e["rank"], 4.0)  # one half-life
    e = t.snapshot(now=30.0)["entries"][0]
    assert math.isclose(e["rank"], 1.0)  # three half-lives
    # totals never decay
    assert e["totals"]["write"] == 8.0


def test_cap_is_bounded_and_space_saving_eviction():
    t = HeatTable(cap=4, half_life=60.0, enabled=True)
    # one genuinely hot doc, then a stream of cold one-shot docs
    for _ in range(100):
        t.note("hot", "read", now=0.0)
    for i in range(50):
        t.note(f"cold{i}", "read", now=0.0)
    snap = t.snapshot(now=0.0)
    assert snap["docs"] <= 4  # bounded by construction
    assert snap["evictions"] > 0
    # the hot doc survives the cold stream (the space-saving guarantee)
    assert snap["entries"][0]["doc"] == "hot"
    assert snap["entries"][0]["rank"] >= 100.0
    # a late newcomer inherits the victim's rank as its error bound
    late = [e for e in snap["entries"] if e["doc"] != "hot"]
    assert all(e["err"] >= 1.0 for e in late)


def test_disabled_table_records_nothing():
    t = HeatTable(cap=4, enabled=False)
    t.note("a", "read", now=0.0)
    assert t.snapshot(now=0.0)["entries"] == []
    assert t.snapshot(now=0.0)["enabled"] is False


def test_unknown_kind_and_empty_doc_ignored():
    t = HeatTable(cap=4, enabled=True)
    t.note("", "read", now=0.0)
    t.note("a", "nonsense", now=0.0)
    assert t.snapshot(now=0.0)["entries"] == []


def test_forget_and_reset():
    t = HeatTable(cap=4, enabled=True)
    t.note("a", "read", now=0.0)
    t.note("b", "read", now=0.0)
    assert t.forget("a") is True
    assert t.forget("a") is False
    assert [e["doc"] for e in t.snapshot(now=0.0)["entries"]] == ["b"]
    t.reset()
    assert t.snapshot(now=0.0)["docs"] == 0


def test_snapshot_deterministic_order_and_top():
    t = HeatTable(cap=8, half_life=60.0, enabled=True)
    for d in ("z", "m", "a"):
        t.note(d, "read", 5.0, now=0.0)  # identical ranks
    docs = [e["doc"] for e in t.snapshot(now=0.0)["entries"]]
    assert docs == ["a", "m", "z"]  # ties broken by name
    t.note("hotter", "read", 9.0, now=0.0)
    snap = t.snapshot(now=0.0, top=2)
    assert [e["doc"] for e in snap["entries"]] == ["hotter", "a"]
    assert snap["docs"] == 4  # top= truncates entries, not the count


def test_publish_gauges_removes_stale_series():
    obs.reset_all()
    t = HeatTable(cap=8, half_life=60.0, enabled=True)
    t.note("a", "read", 10.0, now=0.0)
    t.note("b", "read", 5.0, now=0.0)
    assert t.publish_gauges(top=2, now=0.0) == 2
    names = {(e["labels"].get("doc"), e["labels"].get("kind"))
             for e in obs.snapshot() if e["name"] == "doc.heat"}
    assert names == {("a", "read"), ("b", "read")}
    # b falls out of the top set -> its series must disappear
    t.note("c", "write", 20.0, now=0.0)
    t.publish_gauges(top=2, now=0.0)
    names = {(e["labels"].get("doc"), e["labels"].get("kind"))
             for e in obs.snapshot() if e["name"] == "doc.heat"}
    assert names == {("a", "read"), ("c", "write")}
    obs.reset_all()


def test_global_table_hooks():
    heat.reset()
    heat.note("gdoc", "sync", now=0.0)
    snap = heat.snapshot(now=0.0)
    assert any(e["doc"] == "gdoc" for e in snap["entries"])
    heat.reset()
    assert heat.snapshot(now=0.0)["docs"] == 0
