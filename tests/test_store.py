"""Tiered document store: policy units, tier transitions through the
RPC layer, single-flight hydration, salvage cold-opens, and the
end-to-end socket-serving path under residency budgets."""

import json
import os
import socket
import threading
import time

import pytest

from automerge_tpu import obs
from automerge_tpu.rpc import RpcServer
from automerge_tpu.store import (
    TIER_COLD,
    TIER_HOT,
    TIER_WARM,
    DocStats,
    StoreBackpressure,
    StoreBudgets,
    pick_demotions,
)
from automerge_tpu.store.docstore import ColdDocRef


# -- policy units -------------------------------------------------------------


def _stats(*rows):
    return [DocStats(n, t, la, rb) for (n, t, la, rb) in rows]


def test_policy_hot_budget_demotes_lru_first():
    b = StoreBudgets(hot_docs=2, min_idle_s=0.0)
    st = _stats(("a", TIER_HOT, 1.0, 10), ("b", TIER_HOT, 3.0, 10),
                ("c", TIER_HOT, 2.0, 10), ("d", TIER_WARM, 0.5, 10))
    out = pick_demotions(st, b, now=10.0)
    assert [(d.name, d.to, d.reason) for d in out] == [
        ("a", TIER_WARM, "hot_budget")]


def test_policy_warm_bytes_goes_cold_until_under():
    b = StoreBudgets(warm_bytes=25, min_idle_s=0.0)
    st = _stats(("a", TIER_WARM, 1.0, 10), ("b", TIER_WARM, 2.0, 10),
                ("c", TIER_WARM, 3.0, 10))
    out = pick_demotions(st, b, now=10.0)
    assert [(d.name, d.to) for d in out] == [("a", TIER_COLD)]
    assert out[0].reason == "warm_budget"


def test_policy_rss_watermark_demotes_oldest_first():
    b = StoreBudgets(max_rss_bytes=100, min_idle_s=0.0)
    st = _stats(("a", TIER_WARM, 2.0, 30), ("b", TIER_HOT, 1.0, 30))
    out = pick_demotions(st, b, now=10.0, rss_bytes=160)
    # 60 bytes over: both demote, LRU (b) first
    assert [(d.name, d.to, d.reason) for d in out] == [
        ("b", TIER_COLD, "rss"), ("a", TIER_COLD, "rss")]


def test_policy_min_idle_floor_protects_recent_docs():
    b = StoreBudgets(warm_bytes=1, min_idle_s=5.0)
    st = _stats(("fresh", TIER_WARM, 9.0, 100), ("old", TIER_WARM, 1.0, 100))
    out = pick_demotions(st, b, now=10.0)
    assert [d.name for d in out] == ["old"]


def test_policy_idle_age_out_and_coldest_decision_wins():
    b = StoreBudgets(hot_docs=1, warm_bytes=5, idle_cold_s=4.0,
                     min_idle_s=0.0)
    st = _stats(("a", TIER_HOT, 1.0, 10), ("b", TIER_HOT, 8.0, 10))
    out = pick_demotions(st, b, now=10.0)
    by_name = {d.name: d for d in out}
    # a: idle 9s -> cold (idle pass wins over later budget passes)
    assert by_name["a"].to == TIER_COLD and by_name["a"].reason == "idle"
    # b: hot-budget demotion to warm, then warm-bytes takes it cold —
    # the coldest decision survives the merge
    assert by_name["b"].to == TIER_COLD


def test_policy_inactive_budgets_never_demote():
    st = _stats(("a", TIER_HOT, 0.0, 10**9))
    assert pick_demotions(st, StoreBudgets(), now=1e9) == []


# -- metrics removal API (the per-doc gauge hygiene satellite) ---------------


def test_registry_remove_labels_and_gauge_remove():
    from automerge_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.gauge("doc.journal_bytes", doc="a").set(7)
    reg.gauge("doc.journal_bytes", doc="b").set(9)
    reg.counter("doc.journal_bytes", doc="a").inc()  # same name, other type
    assert reg.remove_labels("doc.journal_bytes", {"doc": "a"}) == 2
    left = [e for e in reg.snapshot() if e["name"] == "doc.journal_bytes"]
    assert [e["labels"] for e in left] == [{"doc": "b"}]
    assert reg.gauge_remove("doc.journal_bytes", doc="b") is True
    assert reg.gauge_remove("doc.journal_bytes", doc="b") is False


def test_doc_gauges_removed_on_close(tmp_path):
    from automerge_tpu.api import AutoDoc

    dd = AutoDoc.open(str(tmp_path / "g1"))
    dd.put("_root", "k", 1)
    dd.commit()
    name = dd.obs_name
    assert any(
        e["name"] == "doc.journal_bytes" and e["labels"].get("doc") == name
        for e in obs.snapshot()
    )
    dd.close()
    assert not any(
        e["name"].startswith("doc.") and e["labels"].get("doc") == name
        for e in obs.snapshot()
    )


# -- tier transitions through the RPC layer ----------------------------------


@pytest.fixture
def server(tmp_path):
    s = RpcServer(durable_dir=str(tmp_path / "docs"))
    os.makedirs(s.durable_dir, exist_ok=True)
    yield s
    s.close_durables()


def test_demote_hydrate_round_trip_byte_identical(server):
    s = server
    h = s.openDurable({"name": "rt"})["doc"]
    s.put({"doc": h, "obj": "_root", "prop": "k", "value": 42})
    s.commit({"doc": h})
    save1 = s.save({"doc": h})
    assert s.store.demote("rt", TIER_COLD) == TIER_COLD
    assert isinstance(s._docs[h], ColdDocRef)
    # first access hydrates lazily; contents byte-identical
    assert s.get({"doc": h, "obj": "_root", "prop": "k"}) == 42
    assert s.store.tier("rt") == TIER_WARM
    assert s.save({"doc": h}) == save1


def test_cold_releases_flock_and_memory_footprint(server, tmp_path):
    from automerge_tpu.api import AutoDoc

    s = server
    h = s.openDurable({"name": "fl"})["doc"]
    s.put({"doc": h, "obj": "_root", "prop": "k", "value": 1})
    s.commit({"doc": h})
    s.store.demote("fl", TIER_COLD)
    # the journal flock is released: a second opener succeeds
    other = AutoDoc.open(os.path.join(s.durable_dir, "fl"))
    assert other.get("_root", "k") is not None
    other.close()
    # and the handle placeholder is a few slots, not a document
    assert isinstance(s._docs[h], ColdDocRef)


def test_hot_tier_device_mirror_drops_and_rebuilds(server):
    s = server
    h = s.openDurable({"name": "dev", "device": True})["doc"]
    s.put({"doc": h, "obj": "_root", "prop": "k", "value": 5})
    s.commit({"doc": h})
    assert s.store.tier("dev") == TIER_HOT
    dd = s._docs[h]
    assert dd.device_doc is not None
    assert s.store.demote("dev", TIER_WARM) == TIER_WARM
    assert dd.device_doc is None
    # the device gauges were removed with the mirror
    assert not any(
        e["name"] in ("doc.resident_ops", "doc.device_bytes")
        and e["labels"].get("doc") == "dev"
        for e in obs.snapshot()
    )
    # access promotes back to hot (want_device, no hot budget)
    assert s.get({"doc": h, "obj": "_root", "prop": "k"}) == 5
    assert s.store.tier("dev") == TIER_HOT
    assert s._docs[h].device_doc is not None


def test_mutation_on_evicted_instance_is_retriable(server):
    from automerge_tpu.storage.durable import DocumentEvicted

    s = server
    h = s.openDurable({"name": "ev"})["doc"]
    s.put({"doc": h, "obj": "_root", "prop": "k", "value": 1})
    s.commit({"doc": h})
    dd = s._docs[h]
    s.store.demote("ev", TIER_COLD)
    # a caller still holding the evicted instance: reads serve (the
    # op-store is immutable now), mutations refuse retriably instead of
    # silently staging state that would die with the instance
    assert dd.get("_root", "k") is not None
    with pytest.raises(DocumentEvicted):
        dd.put("_root", "k", 2)
    with pytest.raises(DocumentEvicted):
        dd.commit()
    assert DocumentEvicted.retriable is True
    # the RPC envelope surfaces the flag for the client retry loop
    resp = s.handle({"id": 1, "method": "commit", "params": {"doc": h}})
    assert "error" not in resp  # ...because _doc hydrated first
    # but a race that lands on the closed instance maps to retriable
    s.store.demote("ev", TIER_COLD)
    err = s._dispatch(2, "storeDemote", {
        "id": 2, "method": "storeDemote", "params": {"name": "nope"}})
    assert "error" in err  # sanity: dispatch error envelope shape


def test_read_path_refreshes_last_access(server):
    s = server
    h = s.openDurable({"name": "ra"})["doc"]
    s.put({"doc": h, "obj": "_root", "prop": "k", "value": 1})
    s.commit({"doc": h})

    def gauge():
        for e in obs.snapshot():
            if (e["name"] == "doc.last_access_seconds"
                    and e["labels"].get("doc") == "ra"):
                return e["value"]
        return None

    t0 = gauge()
    assert t0 is not None
    dd = s._docs[h]
    la0 = dd.last_access
    time.sleep(0.02)
    # a pure READ must refresh the policy stamp (the satellite:
    # read-hot docs previously looked idle and would have been demoted)
    s.get({"doc": h, "obj": "_root", "prop": "k"})
    assert dd.last_access > la0
    # the scrape-visible gauge refreshes at a bounded cadence, not per
    # request (hot-path cost); with the cadence zeroed it tracks reads
    assert gauge() == pytest.approx(t0)
    dd.TOUCH_EXPORT_INTERVAL_S = 0.0
    time.sleep(0.01)
    s.get({"doc": h, "obj": "_root", "prop": "k"})
    t1 = gauge()
    assert t1 is not None and t1 > t0
    assert dd.last_access == pytest.approx(t1)


def test_single_flight_hydration_opens_exactly_once(server):
    s = server
    h = s.openDurable({"name": "sf"})["doc"]
    s.put({"doc": h, "obj": "_root", "prop": "k", "value": 3})
    s.commit({"doc": h})
    s.store.demote("sf", TIER_COLD)

    opens = []
    orig = s._store_open_cold

    def slow_open(name):
        opens.append(name)
        time.sleep(0.05)
        return orig(name)

    s._store_open_cold = slow_open
    results, errors = [], []

    def reader():
        try:
            results.append(s.get({"doc": h, "obj": "_root", "prop": "k"}))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=reader) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    assert results == [3] * 8
    assert opens == ["sf"], "stampede must hydrate exactly once"


def test_hydration_backpressure_is_retriable(server):
    s = server
    for n in ("bp1", "bp2"):
        h = s.openDurable({"name": n})["doc"]
        s.put({"doc": h, "obj": "_root", "prop": "k", "value": 1})
        s.commit({"doc": h})
        s.store.demote(n, TIER_COLD)
    # one hydration slot; make opens slow enough to collide
    s.store._hydrations = threading.Semaphore(1)
    orig = s._store_open_cold

    def slow_open(name):
        time.sleep(0.2)
        return orig(name)

    s._store_open_cold = slow_open
    h1 = s._durable_names["bp1"]
    h2 = s._durable_names["bp2"]
    out = {}

    def read(name, h):
        out[name] = s.handle({
            "id": 1, "method": "get",
            "params": {"doc": h, "obj": "_root", "prop": "k"}})

    t1 = threading.Thread(target=read, args=("bp1", h1))
    t1.start()
    time.sleep(0.05)  # let bp1 take the slot
    read("bp2", h2)
    t1.join()
    assert out["bp1"].get("result") == 1
    err = out["bp2"].get("error")
    assert err is not None and err["type"] == "StoreBackpressure"
    assert err["retriable"] is True
    # and once the slot frees, the same doc hydrates fine
    assert s.get({"doc": h2, "obj": "_root", "prop": "k"}) == 1


def test_cold_open_salvages_damaged_snapshot(server):
    """A cold doc whose snapshot was damaged hydrates through the
    salvage path + journal replay instead of erroring the request."""
    s = server
    h = s.openDurable({"name": "sv"})["doc"]
    s.put({"doc": h, "obj": "_root", "prop": "early", "value": "snap"})
    s.commit({"doc": h})
    s.durableCompact({"doc": h})  # snapshot.am now holds 'early'
    s.put({"doc": h, "obj": "_root", "prop": "late", "value": "tail"})
    s.commit({"doc": h})  # journal tail holds 'late'
    s.store.demote("sv", TIER_COLD)  # tiny journal: closes, no compact
    snap = os.path.join(s.durable_dir, "sv", "snapshot.am")
    assert os.path.exists(snap)
    with open(snap, "ab") as f:
        f.write(b"\x00garbage-chunk-tail\xff" * 8)
    before = obs.legacy_counters.get("load.salvaged_chunks", 0)
    # the serving request succeeds: salvage drops the damage, replays
    # the journal tail on top
    assert s.get({"doc": h, "obj": "_root", "prop": "early"}) == "snap"
    assert s.get({"doc": h, "obj": "_root", "prop": "late"}) == "tail"
    after = obs.legacy_counters.get("load.salvaged_chunks", 0)
    assert after > before, "salvage path did not engage"


def test_budgets_drive_eviction_and_counters(server):
    s = server
    hs = {}
    for i in range(4):
        n = f"bd{i}"
        hs[n] = s.openDurable({"name": n})["doc"]
        s.put({"doc": hs[n], "obj": "_root", "prop": "k", "value": i})
        s.commit({"doc": hs[n]})
    # budgets arrive after the working set exists (the min-idle floor
    # protects in-flight docs; 0.5s keeps re-demotion out of the reads)
    s.store.budgets = StoreBudgets(
        hot_docs=1, warm_bytes=1, min_idle_s=0.5, evict_interval_s=0.0)
    time.sleep(0.6)
    s.store.maybe_evict()
    status = s.storeStatus({})
    assert status["tiers"]["cold"] >= 3, status
    demos = [
        e for e in obs.snapshot()
        if e["name"] == "store.demotions" and e["type"] == "counter"
    ]
    assert demos, "demotion counters never fired"
    assert all(
        set(e["labels"]) == {"from", "to", "reason"} for e in demos)
    # everything stays serveable (hydrate on access)
    for i in range(4):
        assert s.get(
            {"doc": hs[f"bd{i}"], "obj": "_root", "prop": "k"}) == i
    # store.tier gauges reflect the population
    tiers = {
        e["labels"]["tier"]: e["value"]
        for e in obs.snapshot()
        if e["name"] == "store.tier" and e["type"] == "gauge"
    }
    assert sum(tiers.values()) == 4


def test_store_status_and_demote_rpc_surface(server):
    s = server
    s.openDurable({"name": "st1"})
    out = s.handle({"id": 1, "method": "storeStatus",
                    "params": {"docs": True}})["result"]
    assert out["tiers"]["warm"] == 1
    assert "st1" in out["docs"]
    assert out["rssBytes"] > 0
    res = s.handle({"id": 2, "method": "storeDemote",
                    "params": {"name": "st1"}})["result"]
    assert res == {"name": "st1", "tier": "cold"}
    bad = s.handle({"id": 3, "method": "storeDemote",
                    "params": {"name": "missing"}})
    assert "error" in bad


# -- end to end through the socket serving path -------------------------------


def _req(sock, f, rid, method, **params):
    sock.sendall((json.dumps(
        {"id": rid, "method": method, "params": params}) + "\n").encode())
    resp = json.loads(f.readline())
    assert "error" not in resp, resp
    return resp.get("result")


def test_socket_serving_under_budgets_zipfian(tmp_path, monkeypatch):
    """Dozens of docs through the real serve path under a tight budget:
    live population bounded, every doc's contents intact through
    demote/hydrate cycles, no stranded flocks after shutdown."""
    from automerge_tpu.api import AutoDoc
    from automerge_tpu.serve import SocketRpcServer

    monkeypatch.setenv("AUTOMERGE_TPU_STORE_WARM_BYTES", "1")
    monkeypatch.setenv("AUTOMERGE_TPU_STORE_MIN_IDLE", "0.05")
    monkeypatch.setenv("AUTOMERGE_TPU_STORE_EVICT_INTERVAL", "0.1")
    srv = SocketRpcServer(host="127.0.0.1", port=0,
                          durable_dir=str(tmp_path / "zd"))
    srv.start()
    ndocs = 24
    try:
        sock = socket.create_connection(srv.address[:2])
        f = sock.makefile("r")
        rid = 0
        handles = {}
        for i in range(ndocs):
            rid += 1
            handles[i] = _req(sock, f, rid, "openDurable",
                              name=f"z{i:03}")["doc"]
            rid += 1
            _req(sock, f, rid, "put", doc=handles[i], obj="_root",
                 prop="v", value=i)
            rid += 1
            _req(sock, f, rid, "commit", doc=handles[i])
        time.sleep(0.4)  # the sweeper demotes the idle majority
        rid += 1
        st = _req(sock, f, rid, "storeStatus")
        assert st["tiers"]["cold"] > 0, st
        # skewed re-access: doc 0 hammered, the tail touched once
        for i in [0] * 10 + list(range(ndocs)):
            rid += 1
            assert _req(sock, f, rid, "get", doc=handles[i],
                        obj="_root", prop="v") == i
        rid += 1
        _req(sock, f, rid, "shutdown")
        sock.close()
    finally:
        srv.stop()
    # zero stranded flocks: every journal is reopenable
    for i in range(ndocs):
        dd = AutoDoc.open(str(tmp_path / "zd" / f"z{i:03}"))
        assert dd.get("_root", "v") is not None
        dd.close()
