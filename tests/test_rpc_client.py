"""Cross-runtime convergence: the pure-C RPC client (clients/c) drives
the JSON-RPC stdio frontend from a separate process, maintains a live
materialized tree by applying streamed patches (the reference's
interop.rs applyPatch role), and asserts convergence against the
server's materialize snapshots from C.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "clients", "c", "rpc_client.c")


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no C toolchain")
@pytest.mark.skipif(os.name != "posix", reason="fork/exec pipes")
def test_c_client_live_patch_convergence(tmp_path):
    exe = str(tmp_path / "rpc_client")
    r = subprocess.run(
        ["gcc", "-O1", "-Wall", "-Werror", "-o", exe, SRC],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [exe, sys.executable, "-m", "automerge_tpu.rpc"],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=REPO,
    )
    assert r.returncode == 0, f"stdout: {r.stdout}\nstderr: {r.stderr}"
    assert "all assertions passed" in r.stdout
