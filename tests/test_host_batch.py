"""Differential suite for the vectorized cross-document host staging
(ops/host_batch.py).

The scalar per-doc ``DeviceDoc.stage_batches`` path is the oracle: for
random interleavings x mixed doc sizes x out-of-order/duplicate
delivery, staging the same deltas through ``host_batch.stage_docs`` (+
the shared packed launch) must leave every document in a bit-identical
state — column-level OpLog equality, identical resolution arrays and
host caches, identical materialized documents including ``at(heads)``
views. Fallback routes (scalar knob, empty logs, non-tail splices) are
exercised and asserted non-vacuous.
"""

import os
import random
import threading

import numpy as np
import pytest

from automerge_tpu import obs
from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import host_batch
from automerge_tpu.ops.batched import CrossDocBatcher, resolve_stages
from automerge_tpu.ops.device_doc import DeviceDoc
from automerge_tpu.ops.oplog import OpLog
from automerge_tpu.types import ActorId, ObjType, ScalarValue

LOG_COLS = (
    "id_key", "obj_key", "elem_key", "action", "prop", "insert",
    "value_tag", "value_int", "width", "expand", "mark_name_idx",
    "elem_ref", "obj_dense", "pred_src", "pred_tgt", "pred_key",
    "obj_table",
)
DEV_COLS = (
    "visible", "winner", "conflicts", "elem_index", "succ_count",
    "inc_count", "counter_val",
)


def assert_identical(vec: DeviceDoc, sca: DeviceDoc, tag=""):
    """Column-level OpLog equality + full DeviceDoc state equality."""
    a, b = vec.log, sca.log
    assert a.n == b.n and a.n_objs == b.n_objs, tag
    assert [x.bytes for x in a.actors] == [x.bytes for x in b.actors], tag
    assert a.props == b.props and a.mark_names == b.mark_names, tag
    assert a.n_miss_elem == b.n_miss_elem, tag
    assert a.n_miss_pred == b.n_miss_pred, tag
    for c in LOG_COLS:
        va, vb = np.asarray(getattr(a, c)), np.asarray(getattr(b, c))
        assert va.shape == vb.shape and np.array_equal(va, vb), (tag, c)
    for row in range(a.n):
        assert a.values[row].tag == b.values[row].tag, (tag, row)
        assert a.values[row].value == b.values[row].value, (tag, row)
    for c in DEV_COLS:
        va = np.asarray(getattr(vec, c))
        vb = np.asarray(getattr(sca, c))
        assert np.array_equal(va, vb), (tag, c)
    for c in ("obj_vis_len", "obj_text_width"):
        assert np.array_equal(vec.res[c], sca.res[c]), (tag, c)
    assert np.array_equal(vec._rows_by_obj, sca._rows_by_obj), tag
    assert np.array_equal(vec._obj_sorted, sca._obj_sorted), tag
    assert sorted(vec._obj_type.items()) == sorted(sca._obj_type.items()), tag
    assert vec.hydrate() == sca.hydrate(), tag


def build_workload(seed, n_docs=5, cycles=4, dup=True, shuffle=True,
                   ballast=0):
    """Mixed-size docs, two editors each (one ranked below / one above
    the base actor), text edits + counters + marks + new objects/props +
    deletes; per-cycle deltas optionally shuffled and re-delivered.
    ``ballast`` adds an untouched archive object so drained deltas stay
    on the dirty-subset (pack-eligible) path."""
    rng = random.Random(seed)
    docs, deltas = [], []
    for i in range(n_docs):
        base = AutoDoc(actor=ActorId(bytes([20]) * 16))
        t = base.put_object("_root", "t", ObjType.TEXT)
        base.splice_text(t, 0, 0, "seed text " * (i + 1))
        base.put("_root", "ctr", ScalarValue("counter", 0))
        if ballast:
            arch = base.put_object("_root", "archive", ObjType.TEXT)
            base.splice_text(arch, 0, 0, "x" * ballast)
        base.commit()
        e1 = base.fork(actor=ActorId(bytes([3 + i]) + bytes(15)))
        e2 = base.fork(actor=ActorId(bytes([190 - i]) + bytes(15)))
        seen = {a.stored.hash for a in base.doc.history}
        cyc = []
        for c in range(cycles):
            for j in range(2 + i):
                e1.splice_text(t, (c + j) % 5, 0, "A")
                e2.splice_text(t, (c + j) % 3, 0, "B")
            e1.increment("_root", "ctr", 1)
            if c == 1:
                e2.mark(t, 1, 4, "em", True)
                e2.put_object("_root", f"obj{i}", ObjType.LIST)
                e1.put("_root", f"key{i}", "v")
            if c == 2:
                e1.delete("_root", f"key{i}")
            e1.commit()
            e2.commit()
            e1.merge(e2)
            e2.merge(e1)
            d = [a.stored for a in e1.doc.history
                 if a.stored.hash not in seen]
            seen.update(x.hash for x in d)
            if shuffle:
                rng.shuffle(d)
            if dup and d and rng.random() < 0.5:
                d = d + rng.sample(d, 1)  # duplicate delivery
            cyc.append(d)
        docs.append(base)
        deltas.append(cyc)
    return docs, deltas


def drive_pair(docs, deltas, cycles):
    """One vectorized and one scalar replica set over the same deltas;
    returns (vec_devs, sca_devs, vectorized_count)."""
    vec = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    sca = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    n_vec = 0
    for c in range(cycles):
        stages, results = host_batch.stage_docs(
            [(vec[i], [deltas[i][c]]) for i in range(len(docs))]
        )
        for r in results.values():
            assert r.error is None, repr(r.error)
            n_vec += bool(r.vectorized)
        if stages:
            resolve_stages(stages)
        for i in range(len(docs)):
            _, st = sca[i].stage_batches([deltas[i][c]])
            if st is not None:
                resolve_stages([st])
        for i in range(len(docs)):
            assert_identical(vec[i], sca[i], (c, i))
    return vec, sca, n_vec


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_differential_random_interleavings(seed):
    docs, deltas = build_workload(seed)
    vec, sca, n_vec = drive_pair(docs, deltas, 4)
    # non-vacuous: the vectorized path actually handled (most) cycles —
    # including cycle 0, where both editors' actors are NEW to the
    # resident log (the monotone rank-remap path)
    assert n_vec >= len(docs) * 3, n_vec
    # historical views agree (element order + clock-masked visibility)
    for i in (0, len(docs) - 1):
        heads = vec[i].current_heads()
        assert vec[i].at(heads).hydrate() == sca[i].at(heads).hydrate()
        assert vec[i].at([]).hydrate() == sca[i].at([]).hydrate()


def test_scalar_knob_forces_per_doc(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_HOST_BATCH", "0")
    docs, deltas = build_workload(5, n_docs=3, cycles=2)
    vec, sca, n_vec = drive_pair(docs, deltas, 2)
    assert n_vec == 0  # every doc went through the scalar oracle path


def test_out_of_order_delivery_buffers_pending():
    docs, deltas = build_workload(9, n_docs=3, cycles=3, dup=False,
                                  shuffle=False)
    # deliver cycle 1 BEFORE cycle 0: the dependency gap buffers cycle 1
    # in _pending, cycle 0's arrival releases both
    vec = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    sca = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    work = [(vec[i], [deltas[i][1]]) for i in range(3)]
    stages, results = host_batch.stage_docs(work)
    if stages:
        resolve_stages(stages)
    assert all(vec[i].pending_changes() > 0 for i in range(3))
    stages, results = host_batch.stage_docs(
        [(vec[i], [deltas[i][0]]) for i in range(3)]
    )
    for r in results.values():
        assert r.error is None
    if stages:
        resolve_stages(stages)
    for i in range(3):
        sca[i].stage_batches([deltas[i][1]])
        _, st = sca[i].stage_batches([deltas[i][0]])
        if st is not None:
            resolve_stages([st])
        assert vec[i].pending_changes() == sca[i].pending_changes() == 0
        assert_identical(vec[i], sca[i], i)


def test_empty_log_doc_falls_back_and_matches():
    # a device doc opened before any history exists (empty resident log)
    # must route scalar (initial build) and still match
    base = AutoDoc(actor=ActorId(bytes([20]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "hello")
    base.commit()
    chs = [a.stored for a in base.doc.history]
    vec = DeviceDoc.resolve(OpLog.from_changes([]))
    sca = DeviceDoc.resolve(OpLog.from_changes([]))
    stages, results = host_batch.stage_docs([(vec, [chs])])
    assert not any(r.vectorized for r in results.values())
    for r in results.values():
        assert r.error is None and r.applied == len(chs)
    if stages:
        resolve_stages(stages)
    _, st = sca.stage_batches([chs])
    if st is not None:
        resolve_stages([st])
    assert_identical(vec, sca)


def test_non_tail_delivery_demotes_to_scalar():
    """A delta whose Lamport ids sit BELOW the resident maximum (a slow
    replica's old edits arriving late) must demote to the scalar splice
    — counted — and still converge bit-identically."""
    base = AutoDoc(actor=ActorId(bytes([20]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "base ")
    base.commit()
    slow = base.fork(actor=ActorId(bytes([9]) + bytes(15)))
    slow.splice_text(t, 0, 0, "S")
    slow.commit()
    slow_delta = [a.stored for a in slow.doc.history
                  if a.stored.hash not in
                  {x.stored.hash for x in base.doc.history}]
    fast = base.fork(actor=ActorId(bytes([80]) + bytes(15)))
    for c in range(3):
        fast.splice_text(t, c, 0, "F" * 4)
        fast.commit()
    fast_deltas = [a.stored for a in fast.doc.history
                   if a.stored.hash not in
                   {x.stored.hash for x in base.doc.history}]

    vec = DeviceDoc.resolve(OpLog.from_documents([base]))
    sca = DeviceDoc.resolve(OpLog.from_documents([base]))
    # integrate the fast editor first: resident max Lamport id grows
    stages, _ = host_batch.stage_docs([(vec, [fast_deltas])])
    if stages:
        resolve_stages(stages)
    _, st = sca.stage_batches([fast_deltas])
    if st is not None:
        resolve_stages([st])
    before = obs.counter_values(
        "host_batch.fallback_docs", "reason").get("order", 0)
    # the slow replica's delta: counters below the resident max -> the
    # splice would be mid-array, not a tail append
    stages, results = host_batch.stage_docs([(vec, [slow_delta])])
    for r in results.values():
        assert r.error is None
    if stages:
        resolve_stages(stages)
    after = obs.counter_values(
        "host_batch.fallback_docs", "reason").get("order", 0)
    assert after == before + 1, (before, after)
    _, st = sca.stage_batches([slow_delta])
    if st is not None:
        resolve_stages([st])
    assert_identical(vec, sca)


def test_cross_doc_batcher_leader_staged(monkeypatch):
    """Concurrent submitters hand RAW batches to the flush leader, which
    stages every co-arriving document in one vectorized pass before one
    shared launch — results identical to the scalar reference."""
    monkeypatch.setenv("AUTOMERGE_TPU_HOST_BATCH", "1")
    docs, deltas = build_workload(13, n_docs=4, cycles=2, dup=False,
                                  ballast=400)
    vec = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    sca = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    batcher = CrossDocBatcher(window_ms=200.0, max_docs=4, mode="1")
    for c in range(2):
        launches0 = obs.counter_values(
            "device.kernel_launches", "path").get("batched", 0)
        applied = {}
        errors = []

        def worker(i, c=c):
            try:
                applied[i] = batcher.apply(vec[i], [deltas[i][c]])
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for th in ts:
            th.start()
        for th in ts:
            th.join(30)
        assert not errors, errors
        assert all(applied[i] > 0 for i in range(4)), applied
        launches1 = obs.counter_values(
            "device.kernel_launches", "path").get("batched", 0)
        # all four co-arriving docs shared ONE packed launch
        assert launches1 - launches0 == 1, (launches0, launches1)
        for i in range(4):
            _, st = sca[i].stage_batches([deltas[i][c]])
            if st is not None:
                resolve_stages([st])
            assert_identical(vec[i], sca[i], (c, i))


def test_duplicate_doc_entries_merge_into_one_staging():
    docs, deltas = build_workload(21, n_docs=2, cycles=2, dup=False)
    vec = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    sca = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    # the same doc twice in one work list: both cycles must merge into
    # ONE staging (a second append would invalidate stage row indices)
    work = [(vec[0], [deltas[0][0]]), (vec[1], [deltas[1][0]]),
            (vec[0], [deltas[0][1]])]
    stages, results = host_batch.stage_docs(work)
    for r in results.values():
        assert r.error is None
    if stages:
        resolve_stages(stages)
    _, st = sca[0].stage_batches([deltas[0][0], deltas[0][1]])
    if st is not None:
        resolve_stages([st])
    _, st = sca[1].stage_batches([deltas[1][0]])
    if st is not None:
        resolve_stages([st])
    assert_identical(vec[0], sca[0], 0)
    assert_identical(vec[1], sca[1], 1)


def test_stage_docs_rejects_historical_views():
    base = AutoDoc(actor=ActorId(bytes([20]) * 16))
    base.put("_root", "x", 1)
    base.commit()
    dev = DeviceDoc.resolve(OpLog.from_documents([base]))
    view = dev.at(dev.current_heads())
    with pytest.raises(ValueError):
        host_batch.stage_docs([(view, [[]])])
