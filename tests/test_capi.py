"""Build and run the C ABI test program (automerge_tpu/capi).

The reference ships a C frontend exercised by cmocka suites
(reference: automerge-c/test/); here the cdylib embeds the Python
runtime and the C program drives create/edit/save/load/merge/sync
through am.h alone.
"""

from __future__ import annotations

import os
import shutil
import subprocess

import pytest

from automerge_tpu import capi


@pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None,
    reason="no C/C++ toolchain",
)
@pytest.mark.parametrize("source", capi.TEST_SOURCES)
def test_c_abi_end_to_end(tmp_path, source):
    lib = capi.build()
    assert lib is not None, "cdylib build failed"
    exe = capi.build_test(lib, str(tmp_path), source=source)
    assert exe is not None, f"C test program build failed ({source})"
    env = dict(os.environ)
    # the embedded interpreter must not try to reach the TPU tunnel here
    env["JAX_PLATFORMS"] = "cpu"
    env["AUTOMERGE_TPU_PYROOT"] = capi._REPO_ROOT
    r = subprocess.run(
        [exe], capture_output=True, text=True, timeout=300, env=env
    )
    assert r.returncode == 0, f"stdout: {r.stdout}\nstderr: {r.stderr}"
    assert "all assertions passed" in r.stdout
