"""Property-based roundtrip tests (hypothesis).

The analogue of the reference's proptest suites: arbitrary scalar values
and ops through the change codec (reference: types.rs:948-1020 gen_op /
gen_scalar_value, change.rs:341-419 gen_change), sync-message roundtrips
(sync.rs:654), and RLE/delta/boolean column codecs over arbitrary data.
Every encode must decode back to an equal value, and change hashes must
be stable across a reencode.
"""

from __future__ import annotations

import math

import pytest

# environments without hypothesis must still COLLECT cleanly: a guarded
# skip keeps the rest of the suite's 700+ tests running instead of
# aborting collection on the import below
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from automerge_tpu.expanded import collapse_change, expand_change
from automerge_tpu.storage.change import (
    ChangeOp,
    HEAD_STORED,
    ROOT_STORED,
    StoredChange,
    build_change,
    parse_change,
)
from automerge_tpu.storage.values import ValueEncoder, decode_values
from automerge_tpu.sync.bloom import BloomFilter
from automerge_tpu.sync.protocol import Have, Message, SyncState
from automerge_tpu.types import Action, Key, ScalarValue
from automerge_tpu.utils.codecs import (
    BooleanEncoder,
    DeltaEncoder,
    RleEncoder,
    boolean_decode,
    delta_decode,
    rle_decode,
)

# -- generators ---------------------------------------------------------------

scalar_values = st.one_of(
    st.just(ScalarValue("null")),
    st.booleans().map(lambda b: ScalarValue("bool", b)),
    st.integers(min_value=0, max_value=2**63 - 1).map(
        lambda n: ScalarValue("uint", n)
    ),
    st.integers(min_value=-(2**62), max_value=2**62).map(
        lambda n: ScalarValue("int", n)
    ),
    st.floats(allow_nan=False).map(lambda f: ScalarValue("f64", f)),
    st.text(max_size=24).map(lambda s: ScalarValue("str", s)),
    st.binary(max_size=24).map(lambda b: ScalarValue("bytes", b)),
    st.integers(min_value=-(2**31), max_value=2**31).map(
        lambda n: ScalarValue("counter", n)
    ),
    st.integers(min_value=-(2**62), max_value=2**62).map(
        lambda n: ScalarValue("timestamp", n)
    ),
    st.tuples(st.integers(min_value=11, max_value=15), st.binary(max_size=12)).map(
        lambda t: ScalarValue("unknown", t)
    ),
)

opids = st.tuples(
    st.integers(min_value=1, max_value=2**31), st.integers(min_value=0, max_value=2)
)

keys = st.one_of(
    st.text(min_size=1, max_size=12).map(Key.map),
    st.just(Key.seq(HEAD_STORED)),
    opids.map(Key.seq),
)


@st.composite
def change_ops(draw):
    action = draw(
        st.sampled_from(
            [
                Action.MAKE_MAP,
                Action.PUT,
                Action.MAKE_LIST,
                Action.DELETE,
                Action.MAKE_TEXT,
                Action.INCREMENT,
                Action.MAKE_TABLE,
            ]
        )
    )
    if action == Action.INCREMENT:
        value = ScalarValue("int", draw(st.integers(-1000, 1000)))
    elif action == Action.PUT:
        value = draw(scalar_values)
    else:
        value = ScalarValue("null")
    return ChangeOp(
        obj=draw(st.one_of(st.just(ROOT_STORED), opids)),
        key=draw(keys),
        insert=draw(st.booleans()),
        action=int(action),
        value=value,
        pred=sorted(draw(st.lists(opids, max_size=3, unique=True))),
        expand=draw(st.booleans()),
        mark_name=None,
    )


@st.composite
def stored_changes(draw):
    actor = draw(st.binary(min_size=1, max_size=16))
    others = draw(
        st.lists(st.binary(min_size=1, max_size=16), max_size=2, unique=True)
    )
    others = sorted(o for o in others if o != actor)
    n_actors = 1 + len(others)
    ops = draw(st.lists(change_ops(), max_size=8))

    def clamp(opid):
        return (opid[0], opid[1] % n_actors)

    ops = [
        ChangeOp(
            obj=c.obj if c.obj == ROOT_STORED else clamp(c.obj),
            key=c.key if c.key.elem in (None, HEAD_STORED) else Key.seq(clamp(c.key.elem)),
            insert=c.insert,
            action=c.action,
            value=c.value,
            pred=sorted({clamp(p) for p in c.pred}),
            expand=c.expand,
            mark_name=c.mark_name,
        )
        for c in ops
    ]
    return StoredChange(
        dependencies=sorted(
            draw(st.lists(st.binary(min_size=32, max_size=32), max_size=3, unique=True))
        ),
        actor=actor,
        other_actors=others,
        seq=draw(st.integers(1, 2**31)),
        start_op=draw(st.integers(1, 2**31)),
        timestamp=draw(st.integers(0, 2**44)),
        message=draw(st.one_of(st.none(), st.text(max_size=20))),
        ops=ops,
        extra_bytes=draw(st.binary(max_size=8)),
    )


# -- properties ---------------------------------------------------------------


@given(st.lists(scalar_values, max_size=32))
@settings(max_examples=200, deadline=None)
def test_value_column_roundtrip(values):
    enc = ValueEncoder()
    for v in values:
        enc.append(v)
    meta, raw = enc.finish()
    decoded = decode_values(meta, raw, len(values))
    for got, want in zip(decoded, values):
        if want.tag == "f64":
            assert got.tag == "f64" and math.isclose(
                got.value, want.value, rel_tol=0, abs_tol=0
            )
        else:
            assert got == want


@given(stored_changes())
@settings(max_examples=150, deadline=None)
def test_change_chunk_roundtrip(change):
    built = build_change(change)
    parsed, _ = parse_change(built.raw_bytes)
    assert parsed.hash == built.hash
    assert parsed.actor == change.actor
    assert parsed.seq == change.seq
    assert parsed.start_op == change.start_op
    assert parsed.timestamp == change.timestamp
    assert (parsed.message or None) == (change.message or None)
    assert parsed.dependencies == change.dependencies
    assert len(parsed.ops) == len(change.ops)
    for got, want in zip(parsed.ops, change.ops):
        assert got.obj == want.obj
        assert got.key == want.key
        assert bool(got.insert) == bool(want.insert)
        assert got.action == want.action
        assert got.pred == want.pred
        if want.action == Action.PUT and want.value.tag != "f64":
            assert got.value == want.value
    # re-encoding the parsed form is byte-identical (hash-stable)
    rebuilt = build_change(parsed)
    assert rebuilt.raw_bytes == built.raw_bytes


@given(stored_changes())
@settings(max_examples=100, deadline=None)
def test_expanded_change_roundtrip(change):
    import json

    from hypothesis import assume

    # the expanded JSON form rebuilds the actor table from op-id references
    # (as the reference's ExpandedChange -> Change does), so an other-actor
    # no op mentions cannot survive the roundtrip — not a representable case
    referenced = {
        idx
        for op in change.ops
        for idx in (
            [op.obj[1]] if op.obj != ROOT_STORED else []
        )
        + ([op.key.elem[1]] if op.key.elem not in (None, HEAD_STORED) else [])
        + [p[1] for p in op.pred]
    }
    assume(all(i + 1 in referenced for i in range(len(change.other_actors))))

    built = build_change(change)
    j = json.loads(json.dumps(expand_change(built)))
    collapsed = collapse_change(j)
    assert collapsed.hash == built.hash


@given(
    st.lists(st.binary(min_size=32, max_size=32), max_size=4, unique=True),
    st.lists(st.binary(min_size=32, max_size=32), max_size=4, unique=True),
    st.lists(st.binary(min_size=32, max_size=32), max_size=6, unique=True),
    st.lists(stored_changes(), max_size=3),
)
@settings(max_examples=50, deadline=None)
def test_sync_message_roundtrip(heads, need, bloom_hashes, changes):
    built = [build_change(c) for c in changes]
    msg = Message(
        heads=sorted(heads),
        need=sorted(need),
        have=[Have(sorted(heads), BloomFilter.from_hashes(bloom_hashes))],
        changes=built,
    )
    decoded = Message.decode(msg.encode())
    assert decoded.heads == msg.heads
    assert decoded.need == msg.need
    assert len(decoded.have) == 1
    assert decoded.have[0].last_sync == msg.have[0].last_sync
    for h in bloom_hashes:
        assert decoded.have[0].bloom.contains(h)
    assert [c.hash for c in decoded.changes] == [c.hash for c in built]


@given(st.lists(st.binary(min_size=32, max_size=32), max_size=5, unique=True))
@settings(max_examples=100, deadline=None)
def test_sync_state_roundtrip(shared_heads):
    s = SyncState()
    s.shared_heads = sorted(shared_heads)
    s2 = SyncState.decode(s.encode())
    assert s2.shared_heads == s.shared_heads


@given(
    st.lists(
        st.one_of(st.none(), st.integers(-(2**60), 2**60)), max_size=64
    )
)
@settings(max_examples=200, deadline=None)
def test_rle_roundtrip(values):
    enc = RleEncoder("int")
    for v in values:
        enc.append(v)
    buf = bytes(enc.finish())
    got = rle_decode(buf, "int", len(values))
    got += [None] * (len(values) - len(got))  # trailing nulls are implicit
    assert got == values


@given(st.lists(st.one_of(st.none(), st.integers(-(2**50), 2**50)), max_size=64))
@settings(max_examples=200, deadline=None)
def test_delta_roundtrip(values):
    enc = DeltaEncoder()
    for v in values:
        enc.append(v)
    buf = bytes(enc.finish())
    got = delta_decode(buf, len(values))
    got += [None] * (len(values) - len(got))  # trailing nulls are implicit
    assert got == values


@given(st.lists(st.booleans(), max_size=128))
@settings(max_examples=200, deadline=None)
def test_boolean_roundtrip(values):
    enc = BooleanEncoder()
    for v in values:
        enc.append(v)
    buf = bytes(enc.finish())
    assert boolean_decode(buf, len(values)) == values
