"""Randomized differential guard over the array-native fast paths.

Every fast path in the framework (array save, vectorized load
reconstruction, session commit, array-driven rebuild) must be
byte-identical to its per-op python reference path. This suite drives
randomly-generated documents — nested objects, all scalar kinds,
counters, marks, deletes, concurrent forks — through both and compares
bytes, hashes, and hydrated state.
"""

import random

import pytest

from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.core.document import (
    reconstruct_changes,
    reconstruct_changes_fast,
)
from automerge_tpu.storage.document import encode_doc_ops, parse_document
from automerge_tpu.types import ActorId, ObjType, ScalarValue

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core unavailable"
)


def _random_doc(seed: int) -> AutoDoc:
    rng = random.Random(seed)
    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = d.put_object("_root", "text", ObjType.TEXT)
    d.splice_text(t, 0, 0, "seed é\U0001F680 text")
    lst = d.put_object("_root", "lst", ObjType.LIST)
    objs = [lst]
    scalars = [
        None, True, False, 7, -9, 1.25, "s", b"\x00\x01",
        ScalarValue("counter", 3), ScalarValue("timestamp", 12345),
        ScalarValue("uint", 2**63 + rng.randrange(100)),
    ]
    for i in range(rng.randrange(3, 8)):
        d.insert(lst, i, rng.choice(scalars))
    m = d.insert_object(lst, 0, ObjType.MAP)
    d.put(m, "deep", rng.choice(scalars))
    if rng.random() < 0.7:
        d.mark(t, 0, 4, "bold", True, expand=rng.choice(["none", "both", "after"]))
    d.commit()
    # concurrent forks: text edits, counter increments, deletes, conflicts
    for i in range(rng.randrange(2, 6)):
        f = d.fork(actor=ActorId(bytes([10 + i]) * 16))
        for _ in range(rng.randrange(1, 6)):
            roll = rng.random()
            ln = f.length(t)
            if roll < 0.5 and ln:
                pos = rng.randrange(ln + 1)
                nd = min(rng.randrange(0, 3), ln - pos)
                f.splice_text(t, pos, nd, rng.choice(["A", "bb", "ü"]))
            elif roll < 0.7:
                f.put("_root", rng.choice(["k1", "k2"]), rng.choice(scalars))
            elif roll < 0.85 and f.length(lst) > 1:
                f.delete(lst, rng.randrange(f.length(lst)))
            else:
                f.put(m, "deep", rng.choice(scalars))
        f.commit()
        d.merge(f)
    d.commit()
    return d


@pytest.mark.parametrize("seed", range(12))
def test_fast_save_and_load_match_python(seed, monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_DEBUG", "1")
    d = _random_doc(seed)
    doc = d.doc
    sorted_idx = doc.actors.sorted_order()
    remap = [0] * len(sorted_idx)
    for p, g in enumerate(sorted_idx):
        remap[g] = p
    fast_cols = doc._doc_op_cols_fast(remap)
    slow_cols = encode_doc_ops(doc._doc_ops(remap))
    for (s, a), (_, b) in zip(fast_cols, slow_cols):
        assert a == b, f"seed {seed}: save column {s} diverged"

    data = d.save()
    parsed, _ = parse_document(data)
    fast = reconstruct_changes_fast(parsed, verify=True)
    slow = reconstruct_changes(parsed, verify=True)
    assert [c.raw_bytes for c in fast] == [c.raw_bytes for c in slow], seed

    loaded = AutoDoc.load(data)
    assert loaded.hydrate() == d.hydrate(), seed
    assert loaded.save() == data, seed
