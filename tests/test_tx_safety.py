"""Transaction/document safety guards (round-3 advisor findings).

The reference enforces all of these statically through Rust's &mut borrow
on Automerge (rust/automerge/src/transaction/manual_transaction.rs); a
dynamic-language frontend has to enforce them at runtime.
"""

import gc

import pytest

from automerge_tpu import functional as F
from automerge_tpu.api import AutoDoc
from automerge_tpu.core.document import AutomergeError, Document
from automerge_tpu.core.transaction import Transaction


def test_second_concurrent_manual_transaction_raises():
    doc = Document()
    tx = Transaction(doc)
    tx.put("_root", "a", 1)
    with pytest.raises(AutomergeError):
        Transaction(doc)
    tx.commit()
    # after commit a new transaction opens fine
    tx2 = Transaction(doc)
    tx2.put("_root", "b", 2)
    tx2.commit()
    data = doc.save()
    loaded = Document.load(data)
    assert loaded.get_heads() == doc.get_heads()


def test_second_transaction_allowed_after_rollback():
    doc = Document()
    tx = Transaction(doc)
    tx.put("_root", "a", 1)
    tx.rollback()
    tx2 = Transaction(doc)
    tx2.put("_root", "b", 2)
    tx2.commit()
    assert doc.hydrate() == {"b": 2}


def test_autodoc_transaction_guard_still_works():
    d = AutoDoc()
    tx = d.transaction()
    tx.put("_root", "k", "v")
    with pytest.raises(AutomergeError):
        d.put("_root", "other", 1)
    tx.commit()
    d.put("_root", "other", 1)
    d.commit()
    assert d.hydrate() == {"k": "v", "other": 1}


def test_save_with_pending_transaction_ops_raises():
    doc = Document()
    tx = Transaction(doc)
    tx.put("_root", "a", 1)
    with pytest.raises(AutomergeError):
        doc.save()
    tx.commit()
    data = doc.save()
    assert Document.load(data).hydrate() == {"a": 1}


def test_save_with_open_empty_transaction_ok():
    doc = Document()
    tx = Transaction(doc)
    tx.put("_root", "a", 1)
    tx.commit()
    tx2 = Transaction(doc)  # open but no pending ops
    data = doc.save()
    assert Document.load(data).hydrate() == {"a": 1}
    tx2.rollback()


def test_abandoned_transaction_after_later_commit_is_erased():
    # an abandoned (never committed) transaction whose rollback window was
    # closed by a later commit must not leave its ops readable: the op
    # store is rebuilt from history on the next read.
    doc = Document()
    tx = Transaction(doc)
    tx.put("_root", "ghost", 1)
    # simulate the "doc advanced underneath" branch of __del__: another
    # actor's change lands before the abandoned tx is collected
    other = Document()
    otx = Transaction(other)
    otx.put("_root", "real", 2)
    otx.commit()
    # drop the live tx reference, forcing __del__'s non-rollback branch
    doc.max_op += 1  # make max_op differ from tx.start_op - 1
    del tx
    gc.collect()
    doc.max_op -= 1
    doc.merge(other)
    state = doc.hydrate()
    assert "ghost" not in state
    assert state == {"real": 2}
    # and save/load agrees with reads
    reloaded = Document.load(doc.save())
    assert reloaded.hydrate() == state


def test_functional_merge_supersedes_input():
    a = F.init(b"aaaa")
    b = F.init(b"bbbb")
    a = F.change(a, lambda d: d.__setitem__("x", 1))
    b = F.change(b, lambda d: d.__setitem__("y", 2))
    merged = F.merge(a, b)
    assert dict(merged) == {"x": 1, "y": 2}
    # the pre-merge value is consumed: changing it again would mint a
    # duplicate (actor, seq)
    with pytest.raises(RuntimeError):
        F.change(a, lambda d: d.__setitem__("z", 3))
    # the merged value still works
    merged2 = F.change(merged, lambda d: d.__setitem__("z", 3))
    assert dict(merged2)["z"] == 3


def test_functional_apply_changes_supersedes_input():
    a = F.init(b"aaaa")
    b = F.init(b"bbbb")
    b2 = F.change(b, lambda d: d.__setitem__("y", 2))
    chs = F.get_changes(b2)
    a2 = F.apply_changes(a, chs)
    assert dict(a2) == {"y": 2}
    with pytest.raises(RuntimeError):
        F.change(a, lambda d: d.__setitem__("z", 3))


def test_functional_failed_apply_does_not_brick_doc():
    # a malformed chunk must not consume the input value: no (actor, seq)
    # was spent, so the doc stays usable (superseding happens only after
    # the operation succeeds).
    d = F.change(F.init(b"aaaa"), lambda x: x.__setitem__("x", 1))
    with pytest.raises(Exception):
        F.apply_changes(d, [b"not a change chunk"])
    d2 = F.change(d, lambda x: x.__setitem__("y", 2))
    assert dict(d2) == {"x": 1, "y": 2}


def test_functional_failed_change_fn_does_not_brick_doc():
    d = F.change(F.init(b"aaaa"), lambda x: x.__setitem__("x", 1))
    with pytest.raises(ValueError):
        F.change(d, lambda x: (_ for _ in ()).throw(ValueError("boom")))
    d2 = F.change(d, lambda x: x.__setitem__("y", 2))
    assert dict(d2) == {"x": 1, "y": 2}


def test_save_incremental_after_with_pending_tx_raises():
    doc = Document()
    tx = Transaction(doc)
    tx.put("_root", "a", 1)
    tx.commit()
    heads = doc.get_heads()
    tx2 = Transaction(doc)
    tx2.put("_root", "b", 2)
    with pytest.raises(AutomergeError):
        doc.save_incremental_after(heads)
    tx2.commit()
    blob = doc.save_incremental_after(heads)
    assert blob  # the committed change is exported


def test_functional_reentrant_change_raises():
    # a change() callback taking the same value again must not mint a
    # second change with the same (actor, seq)
    d = F.change(F.init(b"aaaa"), lambda x: x.__setitem__("x", 1))
    captured = {}

    def reenter(x):
        x["y"] = 2
        captured["inner"] = None
        F.change(d, lambda z: z.__setitem__("evil", True))

    with pytest.raises(RuntimeError):
        F.change(d, reenter)
    assert "inner" in captured  # we got as far as the reentrant call
    # the failed outer change released the value
    d2 = F.change(d, lambda x: x.__setitem__("ok", True))
    assert dict(d2) == {"x": 1, "ok": True}


def test_merge_from_doc_with_pending_tx_raises():
    src = Document()
    tx = Transaction(src)
    tx.put("_root", "a", 1)
    dst = Document()
    with pytest.raises(AutomergeError):
        dst.merge(src)
    tx.commit()
    dst.merge(src)
    assert dst.hydrate() == {"a": 1}


def test_fork_with_pending_tx_raises():
    doc = Document()
    tx = Transaction(doc)
    tx.put("_root", "a", 1)
    with pytest.raises(AutomergeError):
        doc.fork()
    tx.commit()
    assert doc.fork().hydrate() == {"a": 1}


def test_functional_merge_no_split_brain():
    # the advisor's probe scenario: change() on pre- and post-merge values
    # must not both succeed (one history line per actor).
    a = F.init(b"aaaa")
    b = F.init(b"bbbb")
    a = F.change(a, lambda d: d.__setitem__("x", 1))
    b = F.change(b, lambda d: d.__setitem__("y", 2))
    merged = F.merge(a, b)
    with pytest.raises(RuntimeError):
        F.change(a, lambda d: d.__setitem__("from_old", True))
    after = F.change(merged, lambda d: d.__setitem__("from_new", True))
    # both branches exchange cleanly with a third peer
    c = F.init(b"cccc")
    c = F.apply_changes(c, F.get_changes(after))
    assert dict(c) == dict(after)
