"""Placement advisor (cluster/advisor.py): a pure, deterministic
function from a telemetry snapshot to ranked explained report-only
recommendations — unit-tested on synthetic skew."""

from automerge_tpu.cluster import advisor


def _heat(entries):
    return {"entries": [{"doc": d, "rank": r} for d, r in entries]}


def test_empty_snapshot_no_recommendations():
    out = advisor.advise({})
    assert out["recommendations"] == []
    assert out["groups"] == [] and out["groupLoads"] == {}


def test_balanced_groups_no_recommendations():
    snap = {"groups": [
        {"group": 0, "leader": "a:1", "heat": _heat([("d1", 5.0)])},
        {"group": 1, "leader": "b:1", "heat": _heat([("d2", 5.0)])},
    ]}
    out = advisor.advise(snap)
    assert out["recommendations"] == []
    assert out["groupLoads"] == {"0": 5.0, "1": 5.0}


def test_imbalance_migrates_cold_ballast():
    snap = {"groups": [
        {"group": 0, "leader": "a:1",
         "heat": _heat([("big", 6.0), ("mid", 5.0), ("small", 1.0),
                        ("tiny", 0.5)])},
        {"group": 1, "leader": "b:1", "heat": _heat([("idle", 1.0)])},
    ]}
    out = advisor.advise(snap)
    kinds = [r["kind"] for r in out["recommendations"]]
    assert kinds and set(kinds) == {"migrate"}
    # cold ballast moves, never the hottest doc
    moved = [r["doc"] for r in out["recommendations"]]
    assert "big" not in moved
    assert moved[0] in ("tiny", "small", "mid")
    r = out["recommendations"][0]
    assert r["group"] == 0 and r["to"] == 1
    assert "cold ballast" in r["reason"]


def test_hot_doc_recommends_replica_not_migration():
    snap = {"groups": [
        {"group": 0, "leader": "a:1",
         "heat": _heat([("viral", 9.0), ("small", 1.0)])},
        {"group": 1, "leader": "b:1", "heat": _heat([("idle", 1.0)])},
    ]}
    out = advisor.advise(snap)
    recs = out["recommendations"]
    assert recs[0]["kind"] == "replicate" and recs[0]["doc"] == "viral"
    assert "read replica" in recs[0]["reason"]
    assert not any(r["kind"] == "migrate" for r in recs)


def test_staleness_attention():
    snap = {"groups": [
        {"group": 0, "leader": "a:1", "heat": _heat([("d", 1.0)]),
         "staleness": {
             "f1:2": {"computed": {"d": 4.5, "e": 0.1}},
             "f2:3": {"computed": {"d": 0.0}},
         }},
    ]}
    out = advisor.advise(snap, staleness_threshold=1.0)
    recs = [r for r in out["recommendations"] if r["kind"] == "staleness"]
    assert len(recs) == 1
    r = recs[0]
    assert r["node"] == "f1:2" and r["doc"] == "d" and r["score"] == 4.5
    assert "replication" in r["reason"]


def test_tier_mismatch_promotes_hot_cold_doc():
    snap = {"groups": [
        {"group": 0, "leader": "a:1",
         "heat": _heat([("hotcold", 8.0), ("ok", 3.0)]),
         "tiers": {"hotcold": "cold", "ok": "hot"}},
    ]}
    out = advisor.advise(snap)
    recs = out["recommendations"]
    assert len(recs) == 1
    assert recs[0]["kind"] == "promote" and recs[0]["doc"] == "hotcold"
    assert "hydration" in recs[0]["reason"]


def test_deterministic_ranking_and_truncation():
    snap = {"groups": [
        {"group": 0, "leader": "a:1",
         "heat": _heat([("viral", 9.0), ("small", 1.0)]),
         "tiers": {"viral": "warm"},
         "staleness": {"f:1": {"computed": {"x": 2.0}}}},
        {"group": 1, "leader": "b:1", "heat": _heat([("idle", 1.0)])},
    ]}
    out1 = advisor.advise(snap)
    out2 = advisor.advise(snap)
    assert out1 == out2  # pure function, stable ordering
    scores = [r["score"] for r in out1["recommendations"]]
    assert scores == sorted(scores, reverse=True)
    capped = advisor.advise(snap, max_recommendations=1)
    assert len(capped["recommendations"]) == 1
    assert capped["recommendations"][0] == out1["recommendations"][0]


def test_every_recommendation_has_a_readable_reason():
    snap = {"groups": [
        {"group": 0, "leader": "a:1",
         "heat": _heat([("v", 9.0), ("s", 1.0), ("t", 0.2)]),
         "tiers": {"v": "cold"},
         "staleness": {"f:1": {"computed": {"v": 3.0}}}},
        {"group": 1, "leader": "b:1", "heat": _heat([])},
    ]}
    out = advisor.advise(snap)
    assert out["recommendations"]
    for r in out["recommendations"]:
        assert isinstance(r["reason"], str) and len(r["reason"]) > 20
        assert r["kind"] in ("migrate", "replicate", "staleness", "promote")


def test_render_text_shapes():
    snap = {"groups": [
        {"group": 0, "leader": "a:1",
         "heat": _heat([("viral", 9.0), ("small", 1.0)])},
        {"group": 1, "leader": "b:1", "heat": _heat([("idle", 1.0)])},
    ]}
    text = advisor.render_text(advisor.advise(snap))
    assert "group" in text and "a:1" in text
    assert "report-only" in text
    assert "1. [replicate]" in text
    empty = advisor.render_text(advisor.advise({}))
    assert "no recommendations" in empty


def test_malformed_telemetry_never_raises():
    snap = {"groups": [
        {"group": 0, "leader": "a:1", "error": "unreachable"},
        {"group": 1, "heat": {"entries": None}},
        {"group": 2, "heat": _heat([("d", 2.0)]),
         "staleness": {"f": None, "g": {"computed": None}},
         "tiers": None},
        "not-a-dict",
    ]}
    out = advisor.advise(snap)
    assert isinstance(out["recommendations"], list)
