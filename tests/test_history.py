"""History rings (obs/history.py): fixed-memory downsampled metric
trends — counter deltas stay additive across tiers, gauge max/last
envelopes stay true, and nothing any input does can grow the rings."""

from automerge_tpu.obs.history import TIERS, HistoryRing
from automerge_tpu.obs.metrics import MetricsRegistry


def _ring(allowlist, slots=8, cap=64):
    reg = MetricsRegistry()
    return reg, HistoryRing(allowlist=allowlist, slots=slots, cap=cap,
                            registry=reg)


def test_counter_deltas_per_slot():
    reg, ring = _ring(("c.x",))
    c = reg.counter("c.x")
    ring.sample(now=1.0)          # baseline: first sample's delta is 0
    c.inc(3)
    ring.sample(now=2.0)
    c.inc(2)
    ring.sample(now=3.0)
    slots = ring.series("c.x", tier=0)
    assert [s["delta"] for s in slots] == [0.0, 3.0, 2.0]
    assert [s["t"] for s in slots] == [1.0, 2.0, 3.0]


def test_counter_reset_protection():
    reg, ring = _ring(("c.x",))
    c = reg.counter("c.x")
    c.inc(10)
    ring.sample(now=1.0)
    reg.reset()                   # process restart: total drops to 0
    reg.counter("c.x").inc(4)
    ring.sample(now=2.0)
    deltas = [s["delta"] for s in ring.series("c.x", tier=0)]
    assert deltas[-1] >= 0.0      # never a negative rate


def test_counter_aggregates_across_label_sets():
    reg, ring = _ring(("c.x",))
    reg.counter("c.x", k="a").inc(2)
    reg.counter("c.x", k="b").inc(5)
    ring.sample(now=1.0)
    reg.counter("c.x", k="a").inc(1)
    ring.sample(now=2.0)
    assert ring.series("c.x", tier=0)[-1]["delta"] == 1.0


def test_gauge_max_and_last():
    reg, ring = _ring(("g.x",))
    reg.gauge("g.x", n="1").set(5.0)
    reg.gauge("g.x", n="2").set(9.0)
    ring.sample(now=1.0)
    s = ring.series("g.x", tier=0)[-1]
    assert s["max"] == 9.0 and s["last"] == 9.0


def test_downsampling_preserves_delta_sums_and_max_envelope():
    reg, ring = _ring(("c.x", "g.x"), slots=200)
    c = reg.counter("c.x")
    g = reg.gauge("g.x")
    per1 = int(round(TIERS[1] / TIERS[0]))
    per2 = int(round(TIERS[2] / TIERS[1]))
    n = per1 * per2               # exactly one tier-2 slot's worth
    total = 0
    peak = 0.0
    for i in range(n):
        c.inc(i % 3)
        total += i % 3
        val = float((i * 7) % 11)
        peak = max(peak, val)
        g.set(val)
        ring.sample(now=float(i + 1))
    t1 = ring.series("c.x", tier=1)
    assert len(t1) == per2
    # additivity: the coarse deltas sum to everything except the first
    # sample's baseline (delta 0), i.e. to the true total
    assert sum(s["delta"] for s in t1) == float(total)
    t2 = ring.series("c.x", tier=2)
    assert len(t2) == 1 and t2[0]["delta"] == float(total)
    # the gauge spike envelope survives both downsampling folds
    assert ring.series("g.x", tier=1)[0]["max"] <= peak
    assert ring.series("g.x", tier=2)[0]["max"] == peak


def test_rings_are_bounded():
    reg, ring = _ring(("c.x",), slots=4)
    c = reg.counter("c.x")
    for i in range(1000):
        c.inc()
        ring.sample(now=float(i))
    for tier in range(len(TIERS)):
        assert len(ring.series("c.x", tier=tier)) <= 4
    assert ring.samples == 1000


def test_series_cap_counts_dropped():
    reg, ring = _ring(tuple(f"m{i}" for i in range(8)), cap=3)
    for i in range(8):
        reg.counter(f"m{i}").inc()
    ring.sample(now=1.0)
    st = ring.status()
    assert len(st["series"]) == 3
    assert st["droppedSeries"] == 5


def test_allowlist_filters():
    reg, ring = _ring(("wanted",))
    reg.counter("wanted").inc()
    reg.counter("unwanted").inc()
    reg.gauge("also.unwanted").set(1)
    ring.sample(now=1.0)
    assert [s["name"] for s in ring.status()["series"]] == ["wanted"]


def test_status_filters_and_reset():
    reg, ring = _ring(("a", "b"))
    reg.counter("a").inc()
    reg.gauge("b").set(2)
    ring.sample(now=1.0)
    st = ring.status(name="b")
    assert [s["name"] for s in st["series"]] == ["b"]
    st = ring.status(tier=1)
    assert all(list(s["tiers"].keys()) == ["1"] for s in st["series"])
    ring.reset()
    assert ring.status()["series"] == [] and ring.samples == 0


def test_background_sampler_start_stop():
    reg, ring = _ring(("c.x",))
    reg.counter("c.x").inc()
    assert ring.start() is True
    assert ring.start() is False  # idempotent
    ring.stop()
    assert ring._thread is None
