"""Cross-frontend equivalence: one edit script through the Python API,
the JSON-RPC frontend, and the C-ABI shim dispatch must produce
byte-identical saves and identical materializations.

The reference pins the same property across Rust/WASM/C/JS by porting one
test corpus to every frontend (reference: automerge-c/test/ported_wasm/,
javascript/test/legacy_tests.ts); here the frontends share one engine, so
the assertion is strict byte equality of the save, not just semantic
agreement.
"""

import json

from automerge_tpu.api import AutoDoc
from automerge_tpu.capi import shim
from automerge_tpu.rpc import RpcServer
from automerge_tpu.types import ActorId, ObjType, ScalarValue

ACTOR = bytes.fromhex("0d" * 16)


def _via_python() -> bytes:
    d = AutoDoc(actor=ActorId(ACTOR))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "common script")
    d.put("_root", "n", ScalarValue("counter", 3))
    d.increment("_root", "n", 4)
    lst = d.put_object("_root", "l", ObjType.LIST)
    d.insert(lst, 0, 1)
    d.insert(lst, 1, "two")
    d.delete(lst, 0)
    d.put("_root", "flag", True)
    d.delete("_root", "flag")
    d.commit(message="cross")
    d.mark(t, 0, 6, "bold", True)
    d.commit(message="marks")
    return d.save()


def _via_rpc() -> bytes:
    import base64

    srv = RpcServer()

    def call(method, **params):
        resp = srv.handle({"id": 1, "method": method, "params": params})
        assert "error" not in resp, resp
        return resp["result"]

    d = call("create", actor=ACTOR.hex())["doc"]
    t = call("putObject", doc=d, obj="_root", prop="t", type="text")["$obj"]
    call("spliceText", doc=d, obj=t, pos=0, text="common script")
    call("put", doc=d, obj="_root", prop="n", value={"$counter": 3})
    call("increment", doc=d, obj="_root", prop="n", by=4)
    lst = call("putObject", doc=d, obj="_root", prop="l", type="list")["$obj"]
    call("insert", doc=d, obj=lst, index=0, value=1)
    call("insert", doc=d, obj=lst, index=1, value="two")
    call("delete", doc=d, obj=lst, index=0)
    call("put", doc=d, obj="_root", prop="flag", value=True)
    call("delete", doc=d, obj="_root", prop="flag")
    call("commit", doc=d, message="cross")
    call("mark", doc=d, obj=t, start=0, end=6, name="bold", value=True)
    call("commit", doc=d, message="marks")
    return base64.b64decode(call("save", doc=d))


def _via_capi_shim() -> bytes:
    # the C ABI's dispatch surface (am_embed.cpp marshals into exactly
    # these calls; the compiled .so itself is exercised by test_capi.py)
    h = shim.call("create", ACTOR)[0][1]
    t = shim.call("put_object", h, "_root", "t", 2)[0][1]
    shim.call("splice_text", h, t, 0, 0, "common script")
    shim.call("put", h, "_root", "n", shim.COUNTER, 3)
    shim.call("increment", h, "_root", "n", 4)
    lst = shim.call("put_object", h, "_root", "l", 1)[0][1]
    shim.call("insert", h, lst, 0, shim.INT, 1)
    shim.call("insert", h, lst, 1, shim.STR, "two")
    shim.call("list_delete", h, lst, 0)
    shim.call("put", h, "_root", "flag", shim.BOOL, 1)
    shim.call("delete", h, "_root", "flag")
    shim.call("commit", h, "cross")
    shim.call("mark_bool", h, t, 0, 6, "bold", 1, "after")
    shim.call("commit", h, "marks")
    data = shim.call("save", h)[0][1]
    shim.call("free", h)
    return data


def test_three_frontends_byte_identical():
    py = _via_python()
    rpc = _via_rpc()
    capi = _via_capi_shim()
    assert py == rpc, "python vs rpc save bytes differ"
    assert py == capi, "python vs capi save bytes differ"
    # and the save loads back to the same content everywhere
    doc = AutoDoc.load(py)
    assert doc.get("_root", "n")[0] == ("counter", 7)
