"""Run-coded persistence (storage/runsnap.py): codec round-trips, digest
bit-identity across representation modes and the legacy→run-coded format
upgrade, zero-re-encode hydration counters, the all-dense compaction
shortcut at the ratio-gate boundary, snapshot shipping (replication /
migration catch-up), and forged-corruption detection down to the
``journal-info --verify`` exit code."""

import json
import os

import numpy as np
import pytest

from automerge_tpu import obs, trace
from automerge_tpu.api import AutoDoc
from automerge_tpu.integrity import verify_snapshot_bytes
from automerge_tpu.ops.oplog import OpLog
from automerge_tpu.storage import runsnap
from automerge_tpu.storage.durable import SNAPSHOT_NAME
from automerge_tpu.types import ActorId, ObjType

COLS = (
    "id_key", "obj_key", "prop", "elem_key", "action", "insert",
    "value_tag", "value_int", "width", "expand", "mark_name_idx",
    "elem_ref", "obj_dense", "pred_src", "pred_tgt", "pred_key",
)


def actor(i):
    return ActorId(bytes([i]) * 16)


def build_doc(n_changes=25, with_text=True):
    d = AutoDoc(actor=actor(1))
    if with_text:
        t = d.put_object("_root", "t", ObjType.TEXT)
    for i in range(n_changes):
        if with_text:
            d.splice_text(t, d.length(t), 0, f"w{i} ")
        d.put("_root", f"k{i % 7}", i)
        d.commit()
    d.put("_root", "pi", 3.25)
    d.put("_root", "s", "str-value")
    d.commit()
    return d


def assert_logs_equal(a: OpLog, b: OpLog):
    for name in COLS:
        x, y = getattr(a, name), getattr(b, name)
        if x is None:
            assert y is None, name
            continue
        assert y.dtype == x.dtype, (name, x.dtype, y.dtype)
        assert np.array_equal(x, y), name
    assert np.array_equal(a.obj_table, b.obj_table)
    assert a.n_miss_elem == b.n_miss_elem
    assert a.n_miss_pred == b.n_miss_pred
    assert [a.values[i] for i in range(a.n)] == [b.values[i] for i in range(b.n)]


# -- codec round trips --------------------------------------------------------


def test_codec_round_trip_compressed():
    d = build_doc()
    hist = [ac.stored for ac in d.doc.history]
    log = OpLog.from_changes(hist)
    log.compressed(sync=True)
    data = runsnap.encode_snapshot(log, d.get_heads())
    assert runsnap.is_runsnap(data)

    img = runsnap.parse(data)
    assert img.n_changes == len(hist)
    assert [c.hash for c in img.changes] == [c.hash for c in hist]
    # raw chunk bytes ship verbatim — digests and sync frames bit-identical
    assert [c.raw_bytes for c in img.changes] == [c.raw_bytes for c in hist]
    assert sorted(img.heads) == sorted(d.get_heads())
    assert_logs_equal(log, img.to_oplog())


def test_codec_round_trip_dense_mode(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    d = build_doc(n_changes=8)
    hist = [ac.stored for ac in d.doc.history]
    log = OpLog.from_changes(hist)
    data = runsnap.encode_snapshot(log, d.get_heads())
    img = runsnap.parse(data)
    assert img.flags & runsnap.FLAG_COMPRESSED == 0
    log2 = img.to_oplog()
    assert log2._comp is None  # dense file → no compressed image installed
    assert_logs_equal(log, log2)


def test_encode_requires_raw_bytes():
    d = build_doc(n_changes=2)
    hist = [ac.stored for ac in d.doc.history]
    log = OpLog.from_changes(hist)
    log.changes[0].raw_bytes = None
    with pytest.raises(runsnap.RunSnapError):
        runsnap.encode_snapshot(log, d.get_heads())


# -- durable wiring: digest identity across modes and the format upgrade -----


def roundtrip_digest(tmp_path, name, env=None):
    """Open→write→compact→close→reopen; returns (digest@close,
    digest@reopen, snapshot bytes)."""
    for k, v in (env or {}).items():
        os.environ[k] = v
    try:
        p = str(tmp_path / name)
        d = AutoDoc.open(p, actor=actor(2))
        t = d.put_object("_root", "t", ObjType.TEXT)
        for i in range(12):
            d.splice_text(t, d.length(t), 0, f"x{i} ")
            d.commit()
        assert d.compact()
        d.put("_root", "tail", 1)  # journal tail beyond the snapshot
        d.commit()
        dig = d.doc_digest()["digest"]
        text = d.text(t)
        d.close()
        snap = open(os.path.join(p, SNAPSHOT_NAME), "rb").read()
        d2 = AutoDoc.open(p)
        dig2 = d2.doc_digest()["digest"]
        assert d2.text(t) == text
        d2.close()
        return dig, dig2, snap
    finally:
        for k in (env or {}):
            os.environ.pop(k, None)


def test_digest_identity_all_modes(tmp_path):
    """The same workload digests identically whether persisted run-coded
    (compressed or run-native demoted off), dense-mode, or legacy-chunk —
    the codec never changes the change set."""
    a = roundtrip_digest(tmp_path, "runsnap")
    b = roundtrip_digest(tmp_path, "dense", {"AUTOMERGE_TPU_COMPRESSED": "0"})
    c = roundtrip_digest(tmp_path, "legacy", {"AUTOMERGE_TPU_RUNSNAP": "0"})
    assert a[0] == a[1] == b[0] == b[1] == c[0] == c[1]
    assert runsnap.is_runsnap(a[2])
    assert runsnap.is_runsnap(b[2])  # dense-demoted columns still ship ARSN
    assert not runsnap.is_runsnap(c[2])


def test_legacy_snapshot_upgrade(tmp_path):
    """A doc written entirely under the legacy knob reopens with the new
    reader and upgrades to ARSN on its next compaction, digest unchanged."""
    p = str(tmp_path / "up")
    os.environ["AUTOMERGE_TPU_RUNSNAP"] = "0"
    try:
        d = AutoDoc.open(p, actor=actor(3))
        for i in range(6):
            d.put("_root", f"k{i}", i)
            d.commit()
        assert d.compact()
        dig = d.doc_digest()["digest"]
        d.close()
    finally:
        os.environ.pop("AUTOMERGE_TPU_RUNSNAP", None)
    assert not runsnap.is_runsnap(
        open(os.path.join(p, SNAPSHOT_NAME), "rb").read())

    d2 = AutoDoc.open(p)
    assert d2.doc_digest()["digest"] == dig
    assert d2.compact()
    d2.close()
    assert runsnap.is_runsnap(
        open(os.path.join(p, SNAPSHOT_NAME), "rb").read())
    d3 = AutoDoc.open(p)
    assert d3.doc_digest()["digest"] == dig
    d3.close()


def test_cold_open_zero_reencode(tmp_path):
    """Device-mode cold open from an ARSN snapshot never re-encodes run
    tables from changes (the counter the CI gate asserts); the legacy
    knob makes the same assertion non-vacuous."""
    p = str(tmp_path / "zero")
    d = AutoDoc.open(p, actor=actor(4))
    for i in range(10):
        d.put("_root", f"k{i}", i)
        d.commit()
    assert d.compact()
    d.close()

    trace.reset_counters()
    d2 = AutoDoc.open(p, device=True)
    assert trace.counters.get("oplog.hydrate_reencode", 0) == 0
    assert d2.device_doc is not None
    d2.close()

    # warm→hot promotion off the retained image: still zero
    trace.reset_counters()
    d3 = AutoDoc.open(p)
    d3.build_device_mirror()
    d3.drop_device_mirror()
    d3.build_device_mirror()
    assert trace.counters.get("oplog.hydrate_reencode", 0) == 0
    d3.close()

    # non-vacuous: the legacy-format path DOES re-encode
    os.environ["AUTOMERGE_TPU_RUNSNAP"] = "0"
    try:
        d4 = AutoDoc.open(p)
        assert d4.compact()  # rewrites the snapshot legacy-format
        d4.close()
        trace.reset_counters()
        d5 = AutoDoc.open(p, device=True)
        assert trace.counters.get("oplog.hydrate_reencode", 0) > 0
        d5.close()
    finally:
        os.environ.pop("AUTOMERGE_TPU_RUNSNAP", None)


def _codec_bytes():
    return dict(obs.counter_values("store.hydrate_bytes", "codec"))


def test_hydrate_bytes_codec_labels(tmp_path):
    p = str(tmp_path / "lab")
    d = AutoDoc.open(p, actor=actor(5))
    d.put("_root", "k", 1)
    d.commit()
    assert d.compact()
    d.close()
    before = _codec_bytes()
    AutoDoc.open(p).close()
    after = _codec_bytes()
    assert after.get("runsnap", 0) > before.get("runsnap", 0)
    assert after.get("chunk", 0) == before.get("chunk", 0)

    os.environ["AUTOMERGE_TPU_RUNSNAP"] = "0"
    try:
        d2 = AutoDoc.open(p)
        assert d2.compact()
        d2.close()
    finally:
        os.environ.pop("AUTOMERGE_TPU_RUNSNAP", None)
    before = _codec_bytes()
    AutoDoc.open(p).close()
    after = _codec_bytes()
    assert after.get("chunk", 0) > before.get("chunk", 0)


# -- the all-dense compaction shortcut at the ratio-gate boundary -------------


def test_dense_shortcut_at_ratio_gate(tmp_path, monkeypatch):
    """With the compression gate at 0.0 every column demotes; the
    snapshot writer must short-circuit to the dense path (counted) and
    the file must still round-trip. At the default gate the same doc
    keeps run tables and the shortcut must NOT fire."""
    d = build_doc(n_changes=6)
    hist = [ac.stored for ac in d.doc.history]

    # boundary side A: gate 0.0 → run_gate(n_runs, n_rows) fails for all
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESS_GATE", "0.0")
    log = OpLog.from_changes(hist)
    log.compressed(sync=True)  # demotes every column
    live = [nm for nm in COLS if getattr(log, nm, None) is not None]
    assert log._comp.all_dense(live)
    trace.reset_counters()
    data = runsnap.encode_snapshot(log, d.get_heads())
    assert trace.counters.get("compact.dense_shortcut", 0) == 1
    img = runsnap.parse(data)
    log_rt = img.to_oplog()
    assert_logs_equal(log, log_rt)
    # demotion decisions survive hydration (sticky: no re-encode retry)
    assert log_rt._comp is not None and log_rt._comp.all_dense(live)

    # boundary side B: default gate → runs survive, no shortcut
    monkeypatch.delenv("AUTOMERGE_TPU_COMPRESS_GATE")
    log2 = OpLog.from_changes(hist)
    log2.compressed(sync=True)
    trace.reset_counters()
    runsnap.encode_snapshot(log2, d.get_heads())
    assert trace.counters.get("compact.dense_shortcut", 0) == 0


# -- snapshot shipping (replication / migration catch-up) ---------------------


def test_replicated_snapshot_ships_arsn_verbatim(tmp_path):
    """snapshot_bytes() → apply_replicated_snapshot moves the run-coded
    image verbatim; the receiver's digest matches bit-for-bit and its
    own hydrations start run-coded (image adopted)."""
    p1 = str(tmp_path / "leader")
    d1 = AutoDoc.open(p1, actor=actor(6))
    t = d1.put_object("_root", "t", ObjType.TEXT)
    for i in range(9):
        d1.splice_text(t, d1.length(t), 0, f"s{i} ")
        d1.commit()
    blob = d1.snapshot_bytes()
    assert runsnap.is_runsnap(blob)
    dig = d1.doc_digest()["digest"]

    p2 = str(tmp_path / "follower")
    d2 = AutoDoc.open(p2, actor=actor(7))
    before = _codec_bytes()
    d2.apply_replicated_snapshot(blob, b"cursor-1")
    after = _codec_bytes()
    assert d2.doc_digest()["digest"] == dig
    assert d2.text(t) == d1.text(t)
    assert d2._run_image is not None  # adopted, not re-derived
    assert after.get("runsnap", 0) - before.get("runsnap", 0) == len(blob)

    # corruption must raise (on_partial="error" semantics), not degrade
    bad = bytearray(blob)
    bad[len(blob) // 2] ^= 0xFF
    p3 = str(tmp_path / "f2")
    d3 = AutoDoc.open(p3, actor=actor(8))
    with pytest.raises(runsnap.RunSnapError):
        d3.apply_replicated_snapshot(bytes(bad), None)
    d3.close()
    d1.close()
    d2.close()


def test_corrupt_arsn_salvages_embedded_changes(tmp_path):
    """A bit-flipped ARSN snapshot opens in salvage mode: the embedded
    change chunks are magic-prefixed, so the legacy carve recovers them
    — same degradation story as a damaged chunk snapshot."""
    p = str(tmp_path / "sal")
    d = AutoDoc.open(p, actor=actor(9))
    for i in range(5):
        d.put("_root", f"k{i}", i)
        d.commit()
    assert d.compact()
    n_changes = len(d.doc.history)
    d.close()

    sp = os.path.join(p, SNAPSHOT_NAME)
    blob = bytearray(open(sp, "rb").read())
    blob[8] ^= 0xFF  # corrupt the meta section, changes stay intact
    open(sp, "wb").write(bytes(blob))

    d2 = AutoDoc.open(p)
    assert len(d2.doc.history) == n_changes
    assert d2._run_image is None  # salvage path, no image
    d2.close()


# -- verification & the journal-info exit code --------------------------------


def _forge(data: bytes, offset: int) -> bytes:
    bad = bytearray(data)
    bad[offset] ^= 0xFF
    return bytes(bad)


def test_verify_reports_first_bad_section(tmp_path):
    d = build_doc(n_changes=6)
    hist = [ac.stored for ac in d.doc.history]
    log = OpLog.from_changes(hist)
    data = runsnap.encode_snapshot(log, d.get_heads())

    rep = verify_snapshot_bytes(data)
    assert rep.ok and rep.kind == "snapshot" and rep.units >= 7

    # forge every section in turn: each must flag at (or before) its own
    # frame, never report ok, and parse() must refuse
    offsets, pos = [], 6
    while pos < len(data):
        from automerge_tpu.utils.leb128 import decode_uleb

        plen, body = decode_uleb(data, pos + 1)
        offsets.append((pos, body))
        pos = body + plen + 4
    assert len(offsets) >= 7
    for start, body in offsets:
        bad = _forge(data, body)  # flip the first payload byte
        r = verify_snapshot_bytes(bad)
        assert not r.ok
        assert r.first_bad_offset is not None and r.first_bad_offset <= start + 1
        with pytest.raises(runsnap.RunSnapError):
            runsnap.parse(bad)


def test_journal_info_verify_rc1_on_forged_arsn(tmp_path, capsys):
    from automerge_tpu.cli import main as cli_main

    p = str(tmp_path / "ji")
    d = AutoDoc.open(p, actor=actor(10))
    d.put("_root", "k", "v")
    d.commit()
    assert d.compact()
    d.close()

    assert cli_main(["journal-info", p, "--verify"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["snapshot_codec"] == "runsnap"
    snap_report = [r for r in out["verify"] if r["kind"] == "snapshot"]
    assert snap_report and snap_report[0]["ok"]

    sp = os.path.join(p, SNAPSHOT_NAME)
    blob = open(sp, "rb").read()
    open(sp, "wb").write(_forge(blob, len(blob) - 10))
    assert cli_main(["journal-info", p, "--verify"]) == 1
    captured = capsys.readouterr()
    out = json.loads(captured.out)
    bad = [r for r in out["verify"] if r["kind"] == "snapshot"][0]
    assert not bad["ok"] and bad["first_bad_offset"] is not None
    assert "corrupt" in captured.err
