"""Export-for-export parity of the functional API with the reference's JS
wrapper (reference: javascript/src/stable.ts:194-1183 exports, plus
javascript/src/next.ts:289-350 splice/getCursor/getCursorPosition and
next.ts:387-438 mark/unmark). Each test mirrors the reference semantics of
one export; the checklist test pins the mapping so a future rename breaks
loudly.
"""

from __future__ import annotations

import pytest

import automerge_tpu.functional as am


# stable.ts export -> functional.py name (None = deliberately absent with a
# reason in the comment).
STABLE_EXPORTS = {
    "init": "init",  # stable.ts:194
    "view": "view",  # stable.ts:235
    "clone": "clone",  # stable.ts:260
    "free": "free",  # stable.ts:281
    "from": "from_dict",  # stable.ts:301 ("from" is a Python keyword)
    "change": "change",  # stable.ts:355
    "changeAt": "change_at",  # stable.ts:449
    "emptyChange": "empty_change",  # stable.ts:579
    "load": "load",  # stable.ts:621
    "loadIncremental": "load_incremental",  # stable.ts:673
    "saveIncremental": "save_incremental",  # stable.ts:711
    "save": "save",  # stable.ts:731
    "merge": "merge",  # stable.ts:750
    "getActorId": "get_actor",  # stable.ts:768
    "getConflicts": "get_conflicts",  # stable.ts:829
    "getLastLocalChange": "get_last_local_change",  # stable.ts:852
    "getObjectId": "get_object_id",  # stable.ts:864
    "getChanges": "get_changes",  # stable.ts:883
    "getAllChanges": "get_all_changes",  # stable.ts:895
    "applyChanges": "apply_changes",  # stable.ts:911
    "getHistory": "get_history",  # stable.ts:942
    "diff": "diff",  # stable.ts:964
    "equals": "equals",  # stable.ts:999
    "encodeSyncState": "encode_sync_state",  # stable.ts:1016
    "decodeSyncState": "decode_sync_state",  # stable.ts:1028
    "generateSyncMessage": "generate_sync_message",  # stable.ts:1046
    "receiveSyncMessage": "receive_sync_message",  # stable.ts:1074
    "initSyncState": "init_sync_state",  # stable.ts:1116
    "encodeChange": "encode_change",  # stable.ts:1121
    "decodeChange": "decode_change",  # stable.ts:1126
    "encodeSyncMessage": "encode_sync_message",  # stable.ts:1131
    "decodeSyncMessage": "decode_sync_message",  # stable.ts:1136
    "getMissingDeps": "get_missing_deps",  # stable.ts:1143
    "getHeads": "get_heads",  # stable.ts:1151
    "dump": "dump",  # stable.ts:1157
    "toJS": "to_dict",  # stable.ts:1163
    "isAutomerge": "is_automerge",  # stable.ts:1171
    "saveSince": "save_since",  # stable.ts:1183
    "insertAt": "insert_at",  # stable.ts:108
    "deleteAt": "delete_at",  # stable.ts:122
}

NEXT_EXPORTS = {
    "splice": "splice",  # next.ts:289
    "getCursor": "get_cursor",  # next.ts:336
    "getCursorPosition": "get_cursor_position",  # next.ts:366
    "mark": "mark",  # next.ts:387
    "unmark": "unmark",  # next.ts:413
    "marks": "marks",  # next.ts:438
}


def test_export_checklist():
    for js_name, py_name in {**STABLE_EXPORTS, **NEXT_EXPORTS}.items():
        assert hasattr(am, py_name), f"{js_name} -> {py_name} missing"
        assert py_name in am.__all__, f"{py_name} not exported in __all__"


def _two_docs():
    d1 = am.from_dict({"k": 1}, actor=bytes([1]) * 16)
    d2 = am.merge(am.init(actor=bytes([2]) * 16), d1)
    d2 = am.change(d2, lambda d: d.update({"other": "x"}))
    return am.clone(d1, actor=bytes([1]) * 16), d2


# -- view / clone / free ------------------------------------------------------


def test_view_reads_at_heads_and_rejects_change():
    d1 = am.from_dict({"n": 1}, actor=bytes([3]) * 16)
    h1 = am.get_heads(d1)
    d2 = am.change(d1, lambda d: d.update({"n": 2}))
    v = am.view(d2, h1)
    assert v.to_py() == {"n": 1}
    assert am.get_heads(v) == h1
    # change on a view raises, like the reference's
    # "Attempting to change an outdated document"
    with pytest.raises(RuntimeError):
        am.change(v, lambda d: d.update({"n": 3}))
    # clone() gives a writable copy at those heads (stable.ts view docs)
    w = am.change(am.clone(v), lambda d: d.update({"n": 3}))
    assert w.to_py() == {"n": 3}
    am.free(v)  # no-op, exists for parity


def test_is_automerge():
    assert am.is_automerge(am.init())
    assert not am.is_automerge({"k": 1})
    assert not am.is_automerge(None)


# -- emptyChange --------------------------------------------------------------


def test_empty_change_creates_opless_change():
    d1 = am.from_dict({"k": 1}, actor=bytes([4]) * 16)
    n_before = len(am.get_all_changes(d1))
    d2 = am.empty_change(d1, "acknowledged")
    raw = am.get_all_changes(d2)
    assert len(raw) == n_before + 1
    last = am.decode_change(raw[-1])
    assert last["ops"] == []
    assert last["message"] == "acknowledged"
    assert d2.to_py() == {"k": 1}
    # message is optional, like emptyChange(doc) in the reference
    d3 = am.empty_change(d2)
    assert len(am.get_all_changes(d3)) == n_before + 2


# -- equals -------------------------------------------------------------------


def test_equals_compares_contents_not_history():
    a = am.from_dict({"x": [1, 2]}, actor=bytes([5]) * 16)
    b = am.from_dict({"x": [1, 2]}, actor=bytes([6]) * 16)
    assert am.equals(a, b)  # different actors/history, same value
    assert am.equals(a, {"x": [1, 2]})  # plain values allowed
    assert not am.equals(a, {"x": [1]})
    assert am.equals(1, 1) and not am.equals(1, 2)


# -- object ids ---------------------------------------------------------------


def test_get_object_id():
    d = am.from_dict({"m": {"n": 1}, "l": [1]}, actor=bytes([7]) * 16)
    assert am.get_object_id(d) == "_root"
    assert am.get_object_id(d["m"]) not in (None, "_root")
    assert am.get_object_id(d["l"]) not in (None, "_root")
    assert am.get_object_id(42) is None  # scalars have no id (stable.ts:864)


# -- incremental save/load + saveSince ---------------------------------------


def test_save_incremental_cursor_travels_with_value():
    d1 = am.from_dict({"a": 1}, actor=bytes([8]) * 16)
    first = am.save_incremental(d1)
    assert first  # everything so far
    # cursor advanced: nothing new on the same value
    assert am.save_incremental(d1) == b""
    # a change() later, the successor's incremental save has ONLY the delta
    d2 = am.change(d1, lambda d: d.update({"b": 2}))
    delta = am.save_incremental(d2)
    # the delta is exactly the changes since d1's heads — only the new one
    assert delta == am.save_since(d2, am.get_heads(d1))
    assert delta != first
    # receiver folds: init + first + delta == sender
    r = am.load_incremental(am.load_incremental(am.init(), first), delta)
    assert r.to_py() == {"a": 1, "b": 2}


def test_save_resets_incremental_cursor():
    d1 = am.from_dict({"a": 1}, actor=bytes([9]) * 16)
    am.save(d1)
    assert am.save_incremental(d1) == b""


def test_save_since():
    d1 = am.from_dict({"a": 1}, actor=bytes([10]) * 16)
    h1 = am.get_heads(d1)
    d2 = am.change(d1, lambda d: d.update({"b": 2}))
    delta = am.save_since(d2, h1)
    assert delta and am.save_since(d2, am.get_heads(d2)) == b""
    base = am.load(am.save(am.clone(d1)))
    assert am.load_incremental(base, delta).to_py() == {"a": 1, "b": 2}


# -- history ------------------------------------------------------------------


def test_get_history_lazy_change_and_snapshot():
    d = am.from_dict({"n": 1}, actor=bytes([11]) * 16)
    d = am.change(d, lambda x: x.update({"n": 2}))
    d = am.change(d, lambda x: x.update({"n": 3}))
    hist = am.get_history(d)
    assert len(hist) == 3
    assert [h.snapshot.to_py()["n"] for h in hist] == [1, 2, 3]
    assert [h.change["seq"] for h in hist] == [1, 2, 3]
    assert hist[-1].change["hash"] == am.get_heads(d)[0].hex()


# -- change codec -------------------------------------------------------------


def test_encode_decode_change_roundtrip():
    d = am.from_dict({"k": "v", "l": [1]}, actor=bytes([12]) * 16)
    raw = am.get_all_changes(d)[0]
    decoded = am.decode_change(raw)
    assert decoded["actor"] == (bytes([12]) * 16).hex()
    assert decoded["seq"] == 1
    assert am.encode_change(decoded) == raw  # hash-preserving roundtrip


# -- missing deps -------------------------------------------------------------


def test_get_missing_deps():
    d1 = am.from_dict({"a": 1}, actor=bytes([13]) * 16)
    d2 = am.change(am.clone(d1), lambda x: x.update({"b": 2}))
    raw2 = am.get_all_changes(d2)[-1]
    assert am.get_missing_deps(d1) == []
    # naming an unknown head reports it missing (stable.ts:1143 semantics)
    unknown = am.get_heads(d2)
    assert am.get_missing_deps(d1, unknown) == unknown
    assert raw2  # and applying it clears the gap
    d1b = am.load_incremental(d1, raw2)
    assert am.get_missing_deps(d1b, am.get_heads(d2)) == []


# -- functional sync quartet --------------------------------------------------


def test_functional_sync_round_trip():
    a, b = _two_docs()
    sa, sb = am.init_sync_state(), am.init_sync_state()
    # run the protocol to quiescence, values and states threaded functionally
    for _ in range(20):
        sa, msg = am.generate_sync_message(a, sa)
        if msg is not None:
            b, sb = am.receive_sync_message(b, sb, msg)
        sb, msg_b = am.generate_sync_message(b, sb)
        if msg_b is not None:
            a, sa = am.receive_sync_message(a, sa, msg_b)
        if msg is None and msg_b is None:
            break
    assert a.to_py() == b.to_py()


def test_generate_sync_message_does_not_mutate_input_state():
    a, _ = _two_docs()
    s0 = am.init_sync_state()
    s1, msg = am.generate_sync_message(a, s0)
    assert msg is not None
    assert s0.last_sent_heads == [] and not s0.in_flight  # input untouched
    assert s1.last_sent_heads == am.get_heads(a)


def test_sync_state_and_message_codecs():
    a, b = _two_docs()
    sa = am.init_sync_state()
    sa, msg = am.generate_sync_message(a, sa)
    decoded = am.decode_sync_message(msg)
    assert am.encode_sync_message(decoded) == msg
    # persist/restore the durable part of the state
    restored = am.decode_sync_state(am.encode_sync_state(sa))
    assert restored.shared_heads == sa.shared_heads


# -- insertAt / deleteAt / splice / cursors / marks ---------------------------


def test_insert_at_delete_at():
    d = am.from_dict({"l": [1, 4]}, actor=bytes([14]) * 16)
    d = am.change(d, lambda x: am.insert_at(x["l"], 1, 2, 3))
    assert d.to_py()["l"] == [1, 2, 3, 4]
    d = am.change(d, lambda x: am.delete_at(x["l"], 1, 2))
    assert d.to_py()["l"] == [1, 4]


def test_insert_at_negative_index_normalised_once():
    # splice semantics: -1 resolves against the PRE-insert length, once
    d = am.from_dict({"l": [1, 2, 3]}, actor=bytes([17]) * 16)
    d = am.change(d, lambda x: am.insert_at(x["l"], -1, "a", "b"))
    assert d.to_py()["l"] == [1, 2, "a", "b", 3]


def test_delete_at_negative_index_normalised_once():
    d = am.from_dict({"l": [1, 2, 3, 4], "t": am.Text("abcd")},
                     actor=bytes([20]) * 16)
    d = am.change(d, lambda x: am.delete_at(x["l"], -2, 2))
    assert d.to_py()["l"] == [1, 2]
    d = am.change(d, lambda x: am.delete_at(x["t"], -2, 2))
    assert d.to_py()["t"] == "ab"


def test_insert_at_delete_at_on_text():
    # stable.ts insertAt/deleteAt work on Text too
    d = am.from_dict({"t": am.Text("ad")}, actor=bytes([18]) * 16)
    d = am.change(d, lambda x: am.insert_at(x["t"], 1, "b", "c"))
    assert d.to_py()["t"] == "abcd"
    d = am.change(d, lambda x: am.delete_at(x["t"], 1, 2))
    assert d.to_py()["t"] == "ad"


def test_load_marks_history_saved():
    d = am.from_dict({"a": 1}, actor=bytes([19]) * 16)
    d2 = am.load(am.save(d))
    # nothing new to save incrementally right after load (wasm semantics)
    assert am.save_incremental(d2) == b""


def test_splice_by_path_and_cursor():
    d = am.from_dict({"note": am.Text("hello world")}, actor=bytes([15]) * 16)
    d = am.change(d, lambda x: am.splice(x, ["note"], 5, 6, "!"))
    assert d.to_py()["note"] == "hello!"
    # a cursor taken before an earlier insert still lands on the same char
    cur = am.get_cursor(d, ["note"], 5)
    d = am.change(d, lambda x: am.splice(x, ["note"], 0, 0, ">> "))
    assert am.get_cursor_position(d, ["note"], cur) == 8
    d = am.change(d, lambda x: am.splice(x, ["note"], cur, 1))
    assert d.to_py()["note"] == ">> hello"


def test_mark_unmark_by_path():
    d = am.from_dict({"t": am.Text("abcdef")}, actor=bytes([16]) * 16)
    d = am.change(d, lambda x: am.mark(x, ["t"], (1, 4), "bold", True))
    spans = am.marks(d, "t")
    assert any(m.name == "bold" for m in spans)
    d = am.change(d, lambda x: am.unmark(x, ["t"], (1, 4), "bold"))
    assert not [m for m in am.marks(d, "t") if m.name == "bold" and m.value]
