"""Fault-tolerant sync: property suite over seeded lossy schedules plus
unit coverage of the session machinery (framing, ARQ, epochs, resync).

The property tests are the convergence guarantee the ISSUE demands: two
peers reach identical heads under 200 seeded random fault schedules
(drop/dup/reorder at 10-40% rates) within a bounded tick count. Everything
is deterministic per seed — a failure message names the seed, which
reproduces the exact schedule.
"""

import random

import pytest

from automerge_tpu import trace
from automerge_tpu.api import AutoDoc
from automerge_tpu.sync import (
    Channel,
    FaultyChannel,
    Message,
    SessionConfig,
    SyncDriver,
    SyncSession,
)
from automerge_tpu.sync.session import (
    FLAG_RESET,
    decode_frame,
    encode_frame,
)
from automerge_tpu.types import ActorId

MAX_TICKS = 3000  # the bounded round count for every schedule


def actor(i):
    return ActorId(bytes([i]) * 16)


def make_peers(rng):
    """Two docs with optional shared history plus divergent tails."""
    a = AutoDoc(actor=actor(1))
    b = AutoDoc(actor=actor(2))
    for i in range(rng.randrange(0, 4)):
        a.put("_root", f"base{i}", i)
        a.commit()
    b.merge(a)
    for i in range(rng.randrange(1, 6)):
        a.put("_root", f"a{i}", i)
        a.commit()
    for i in range(rng.randrange(1, 6)):
        b.put("_root", f"b{i}", i)
        b.commit()
    return a, b


def run_schedule(seed, truncate_max=0.0, bitflip_max=0.0):
    rng = random.Random(seed * 7919)
    rates = dict(
        drop=rng.uniform(0.1, 0.4),
        dup=rng.uniform(0.1, 0.4),
        reorder=rng.uniform(0.1, 0.4),
        truncate=rng.uniform(0.0, truncate_max) if truncate_max else 0.0,
        bitflip=rng.uniform(0.0, bitflip_max) if bitflip_max else 0.0,
    )
    a, b = make_peers(rng)
    drv = SyncDriver(
        a, b,
        FaultyChannel(seed=seed, **rates),
        FaultyChannel(seed=seed + 10_000, **rates),
    )
    stats = drv.run(max_ticks=MAX_TICKS)
    assert stats.converged, f"seed {seed} rates {rates}: no convergence {stats}"
    assert a.get_heads() == b.get_heads(), f"seed {seed}: heads differ"
    assert stats.ticks <= MAX_TICKS
    return stats


# -- the 200-schedule property suite ----------------------------------------
# batched 25 seeds per test: failures name the seed, batches keep collection
# cheap and let tier-1 parallelise if it ever wants to

@pytest.mark.parametrize("batch", range(8))
def test_converges_under_lossy_schedules(batch):
    for seed in range(batch * 25, (batch + 1) * 25):
        run_schedule(seed)


@pytest.mark.slow
@pytest.mark.parametrize("batch", range(8))
def test_converges_under_corrupting_schedules(batch):
    """Heavy cases: the same 200 seeds with truncation and bit-flips on
    top of loss/duplication/reordering."""
    for seed in range(batch * 25, (batch + 1) * 25):
        run_schedule(seed, truncate_max=0.15, bitflip_max=0.15)


@pytest.mark.slow
def test_converges_with_larger_histories():
    for seed in range(10):
        rng = random.Random(seed)
        a = AutoDoc(actor=actor(1))
        b = AutoDoc(actor=actor(2))
        for i in range(40):
            a.put("_root", f"a{i}", i)
            a.commit()
        for i in range(40):
            b.put("_root", f"b{i}", i)
            b.commit()
        drv = SyncDriver(
            a, b,
            FaultyChannel(seed=seed, drop=0.3, dup=0.2, reorder=0.3),
            FaultyChannel(seed=seed + 99, drop=0.3, dup=0.2, reorder=0.3),
        )
        stats = drv.run(max_ticks=MAX_TICKS)
        assert stats.converged and a.get_heads() == b.get_heads(), (seed, stats)


# -- harness unit coverage ---------------------------------------------------

def test_reliable_channel_is_fifo():
    ch = Channel()
    ch.send(b"one", now=0)
    ch.send(b"two", now=0)
    assert ch.drain(0) == [b"one", b"two"]
    assert ch.drain(0) == []
    assert ch.pending == 0


def test_faulty_channel_deterministic_per_seed():
    def stats_for(seed):
        ch = FaultyChannel(seed=seed, drop=0.3, dup=0.3, reorder=0.3,
                           truncate=0.2, bitflip=0.2)
        out = []
        for i in range(50):
            ch.send(bytes([i]) * 20, now=i)
            out.extend(ch.drain(i))
        return ch.stats.as_dict(), out

    s1, o1 = stats_for(42)
    s2, o2 = stats_for(42)
    s3, o3 = stats_for(43)
    assert s1 == s2 and o1 == o2
    assert (s1, o1) != (s3, o3)
    assert s1["dropped"] > 0 and s1["duplicated"] > 0


def test_faulty_channel_explicit_schedule():
    ch = FaultyChannel(schedule=["drop", "dup", "ok"])
    ch.send(b"a", 0)
    ch.send(b"b", 0)
    ch.send(b"c", 0)
    got = ch.drain(0)
    assert got == [b"b", b"b", b"c"]
    assert ch.stats.dropped == 1 and ch.stats.duplicated == 1
    with pytest.raises(ValueError):
        FaultyChannel(schedule=["explode"]).send(b"x", 0)


def test_reliable_driver_matches_protocol_sync():
    a, b = make_peers(random.Random(0))
    stats = SyncDriver(a, b).run()
    assert stats.converged
    assert a.get_heads() == b.get_heads()
    assert stats.a["retries"] == 0 and stats.b["retries"] == 0
    assert stats.a["resyncs"] == 0 and stats.b["resyncs"] == 0


def test_frame_roundtrip_and_crc():
    frame = encode_frame(7, b"payload", FLAG_RESET, seq=3)
    epoch, flags, seq, inner = decode_frame(frame)
    assert (epoch, flags, seq, inner) == (7, FLAG_RESET, 3, b"payload")
    # any single-bit corruption is detected
    for i in range(1, len(frame)):
        bad = bytearray(frame)
        bad[i] ^= 0x10
        with pytest.raises(Exception):
            decode_frame(bytes(bad))
    # a CRC-valid frame whose header fields are truncated raises the
    # frame-level error type, not a leaked LEB decode error
    import zlib
    from automerge_tpu.sync import SyncError
    payload = bytes([0x00, 0x80])  # flags + dangling ULEB continuation
    crafted = (bytes([0x45])
               + (zlib.crc32(payload) & 0xFFFFFFFF).to_bytes(4, "big")
               + payload)
    with pytest.raises(SyncError, match="session frame"):
        decode_frame(crafted)


def test_session_ignores_garbage_and_counts_it():
    a, b = make_peers(random.Random(1))
    sess = SyncSession(a, epoch=1)
    assert sess.receive(b"") is False
    assert sess.receive(b"\x00\x01\x02") is False
    assert sess.receive(b"\x45truncated") is False
    assert sess.stats["malformed"] == 3


def test_session_duplicate_detection():
    a, b = make_peers(random.Random(2))
    sa = SyncSession(a, epoch=1)
    sb = SyncSession(b, epoch=2)
    frame = sa.poll(1)
    assert frame is not None
    assert sb.receive(frame, 1) is True
    assert sb.receive(frame, 2) is False  # exact dup ignored
    assert sb.stats["dups"] == 1
    # a duplicate triggers a reply (the dup means our answer was lost)
    out = sb.poll(3)
    assert out is not None


def test_session_retransmits_with_backoff():
    a, b = make_peers(random.Random(3))
    cfg = SessionConfig(timeout=2.0, backoff_factor=2.0, max_timeout=16.0,
                        jitter=0.0)
    sess = SyncSession(a, config=cfg, epoch=1)
    first = sess.poll(0)
    assert first is not None
    assert sess.poll(1) is None  # within timeout: silent
    r1 = sess.poll(2)            # base timeout hit
    assert r1 == first
    assert sess.stats["retries"] == 1
    assert sess.poll(3) is None  # backoff doubled: not yet
    r2 = sess.poll(6)
    assert r2 == first
    assert sess.stats["retries"] == 2
    # timeouts cap at max_timeout
    t = sess._cur_timeout
    for now in range(7, 200):
        sess.poll(now)
    assert sess._cur_timeout <= cfg.max_timeout


def test_peer_restart_epoch_handshake():
    """A peer that loses its session state mid-sync (keeping only the
    persisted shared_heads) recovers: the fresh epoch tells the survivor
    to drop its stale bookkeeping."""
    rng = random.Random(4)
    a, b = make_peers(rng)
    sa = SyncSession(a, epoch=1)
    sb = SyncSession(b, epoch=2)
    # run a couple of rounds by hand, then "crash" b
    for now in range(1, 4):
        fa = sa.poll(now)
        if fa is not None:
            sb.receive(fa, now)
        fb = sb.poll(now)
        if fb is not None:
            sa.receive(fb, now)
    saved = sb.encode()  # shared_heads only, like SyncState.encode
    sb2 = SyncSession.restore(b, saved, epoch=3)
    drv = SyncDriver(a, b, session_a=sa, session_b=sb2)
    stats = drv.run()
    assert stats.converged
    assert a.get_heads() == b.get_heads()
    assert sa.stats["resets"] >= 1  # sa noticed the epoch change
    assert sa.peer_epoch == 3


def test_forced_resync_recovers_suppressed_changes():
    """If the peer's sent_hashes suppress a resend (their changes frame
    was lost forever), the divergence detector must force a full resync
    rather than stall."""
    rng = random.Random(5)
    a, b = make_peers(rng)
    sa = SyncSession(a, epoch=1)
    sb = SyncSession(b, epoch=2)
    # poison: mark every one of a's changes as already sent
    sa.state.sent_hashes.update(c.hash for c in sa._doc.get_changes([]))
    drv = SyncDriver(a, b, session_a=sa, session_b=sb)
    stats = drv.run()
    assert stats.converged, stats
    assert a.get_heads() == b.get_heads()
    assert stats.a["resyncs"] + stats.b["resyncs"] >= 1


def test_session_interop_with_bare_protocol_message():
    """A session tolerates a raw 0x42 protocol message (no envelope)."""
    rng = random.Random(6)
    a, b = make_peers(rng)
    from automerge_tpu.sync import SyncState, generate_sync_message

    plain_state = SyncState()
    msg = generate_sync_message(b.doc, plain_state)
    assert msg is not None
    sess = SyncSession(a, epoch=1)
    assert sess.receive(msg.encode(), 0) is True
    assert sess.state.their_heads == msg.heads


def test_trace_counters_emitted():
    trace.reset_counters()
    a, b = make_peers(random.Random(7))
    drv = SyncDriver(
        a, b,
        FaultyChannel(seed=1, drop=0.4, dup=0.3, reorder=0.3),
        FaultyChannel(seed=2, drop=0.4, dup=0.3, reorder=0.3),
    )
    stats = drv.run()
    assert stats.converged
    total_retries = stats.a["retries"] + stats.b["retries"]
    if total_retries:
        assert trace.counters.get("sync.retry", 0) == total_retries
    total_dups = stats.a["dups"] + stats.b["dups"]
    if total_dups:
        assert trace.counters.get("sync.dup", 0) == total_dups


def test_durable_peer_restart_resumes_without_full_resync(tmp_path):
    """A durable peer persists shared_heads (journal metadata) as sync
    progresses; after a restart the restored session resumes through the
    epoch/reset handshake with its sync progress intact — no stall-forced
    full resync, no renegotiation from empty shared_heads."""
    a = AutoDoc(actor=actor(1))
    for i in range(4):
        a.put("_root", f"a{i}", i)
        a.commit()
    bd = AutoDoc.open(str(tmp_path / "b"), fsync="never", actor=actor(2))
    bd.put("_root", "b0", 0)
    bd.commit()
    sa = SyncSession(a, epoch=1)
    sb = bd.attach_sync_session("peer-a", SyncSession(bd, epoch=2))
    stats = SyncDriver(a, bd, session_a=sa, session_b=sb).run()
    assert stats.converged
    shared = list(sb.state.shared_heads)
    assert shared  # progress was made AND persisted
    assert "sync/peer-a" in bd.meta
    bd.close()  # "crash": the session object is gone, only disk survives

    bd2 = AutoDoc.open(str(tmp_path / "b"))
    sb2 = bd2.restore_sync_session("peer-a")
    assert sb2.state.shared_heads == shared
    assert sb2.epoch != sb.epoch  # the survivor must notice the restart
    # diverge both sides, then resume (reliable link: any resync here
    # could only come from the restart itself, so asserting zero is
    # exactly the "no forced full resync" property)
    a.put("_root", "new_a", 1)
    a.commit()
    bd2.put("_root", "new_b", 2)
    bd2.commit()
    stats2 = SyncDriver(a, bd2, session_a=sa, session_b=sb2).run()
    assert stats2.converged
    assert a.get_heads() == bd2.get_heads()
    assert sa.stats["resets"] >= 1  # epoch handshake ran
    assert stats2.a["resyncs"] + stats2.b["resyncs"] == 0  # no full resync
    assert sb2.state.shared_heads  # progress persisted for the NEXT restart

    # a second restart mid-divergence resumes over a lossy link too
    shared2 = list(sb2.state.shared_heads)
    bd2.close()
    bd3 = AutoDoc.open(str(tmp_path / "b"))
    sb3 = bd3.restore_sync_session("peer-a")
    assert sb3.state.shared_heads == shared2
    a.put("_root", "new_a2", 3)
    a.commit()
    drv = SyncDriver(
        a, bd3,
        FaultyChannel(seed=11, drop=0.2, dup=0.2, reorder=0.2),
        FaultyChannel(seed=12, drop=0.2, dup=0.2, reorder=0.2),
        session_a=sa, session_b=sb3,
    )
    stats3 = drv.run(max_ticks=MAX_TICKS)
    assert stats3.converged
    assert a.get_heads() == bd3.get_heads()
    bd3.close()


def test_durable_sync_state_survives_compaction(tmp_path):
    """Compaction truncates the journal but re-appends metadata, so the
    persisted shared_heads survive a snapshot cycle + restart."""
    a = AutoDoc(actor=actor(3))
    a.put("_root", "x", 1)
    a.commit()
    bd = AutoDoc.open(str(tmp_path / "b"), fsync="never", actor=actor(4))
    sb = bd.attach_sync_session("a", SyncSession(bd, epoch=1))
    stats = SyncDriver(a, bd, session_a=SyncSession(a, epoch=2), session_b=sb).run()
    assert stats.converged
    shared = list(sb.state.shared_heads)
    assert shared
    assert bd.compact()
    bd.close()
    bd2 = AutoDoc.open(str(tmp_path / "b"))
    assert bd2.restore_sync_session("a").state.shared_heads == shared
    bd2.close()


def test_durable_restore_bumps_epoch_even_without_progress(tmp_path):
    """Two crash-restarts with NO sync progress in between must still
    present distinct epochs — the bumped epoch is persisted eagerly at
    restore time, not lazily on the next shared_heads change."""
    d = str(tmp_path / "b")
    bd = AutoDoc.open(d, fsync="never", actor=actor(6))
    bd.attach_sync_session("p", SyncSession(bd, epoch=1))._maybe_persist()
    bd.close()
    epochs = []
    for _ in range(3):
        bd = AutoDoc.open(d)
        epochs.append(bd.restore_sync_session("p").epoch)
        bd.close()  # crash again before any sync frame is exchanged
    assert len(set(epochs)) == 3, epochs


def test_durable_sync_receive_batches_fsync(tmp_path):
    """An N-change sync message absorbed by a durable peer's session pays
    ONE journal fsync at the ack boundary, not N."""
    peer = AutoDoc(actor=actor(7))
    for i in range(10):
        peer.put("_root", f"p{i}", i)
        peer.commit()
    gs = SyncSession(peer, epoch=5)
    gs.state.their_have = []
    gs.state.their_need = [c.hash for c in peer.doc.get_changes([])]
    frame = gs.poll(0)  # carries all 10 changes

    dd = AutoDoc.open(str(tmp_path / "b"), fsync="always", actor=actor(8))
    sess = SyncSession(dd, epoch=1)
    trace.reset_timers()
    assert sess.receive(frame, 0) is True
    t = trace.timing_summary()
    assert t["journal.append"]["n"] == 10
    assert t["journal.fsync"]["n"] == 1
    dd.close()


def test_patch_callback_exception_propagates_not_rejected():
    """A raising patch OBSERVER is not a rejected frame: the exception
    must propagate out of receive (as it always did) and the message must
    still count as applied, not swallowed into stats['rejected']."""
    a, b = make_peers(random.Random(8))
    gs = SyncSession(b, epoch=5)
    gs.state.their_have = []
    gs.state.their_need = [c.hash for c in b.doc.get_changes([])]
    frame = gs.poll(0)  # carries b's changes

    def boom(patches):
        raise RuntimeError("observer failed")

    a.set_patch_callback(boom)
    sess = SyncSession(a, epoch=1)
    with pytest.raises(RuntimeError, match="observer failed"):
        sess.receive(frame, 0)
    assert sess.stats["rejected"] == 0  # the changes DID apply


def test_durable_restore_unknown_peer_is_fresh(tmp_path):
    bd = AutoDoc.open(str(tmp_path / "b"), fsync="never", actor=actor(5))
    sess = bd.restore_sync_session("never-met")
    assert sess.state.shared_heads == []
    assert sess.epoch == 1
    bd.close()


def test_session_absorbs_apply_rejected_changes():
    """A CRC-valid frame whose changes the document rejects (peer lost its
    doc and re-created divergent history under the same actor) must be
    absorbed and counted, never raised."""
    a = AutoDoc(actor=actor(1))
    a.put("_root", "x", 1)
    a.commit()
    # a "reincarnated" peer: same actor id, different history → same
    # (actor, seq) slot with a different hash
    ghost = AutoDoc(actor=actor(1))
    ghost.put("_root", "x", 999)
    ghost.commit()
    gs = SyncSession(ghost, epoch=5)
    gs.state.their_have = []
    gs.state.their_need = [c.hash for c in ghost.doc.get_changes([])]
    frame = gs.poll(0)  # carries the conflicting change
    sess = SyncSession(a, epoch=1)
    assert sess.receive(frame, 0) is False
    assert sess.stats["rejected"] == 1
