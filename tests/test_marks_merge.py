"""Marks across concurrent merges: expand policies on REMOTE inserts,
overlapping concurrent marks, hidden marks, disconnected coalescing.

Ported from the reference's wasm mark suites (reference:
rust/automerge-wasm/test/marks.mts — "marks [..] at the beginning of a
string", "marks [..] with splice", "marks across multiple forks",
"coalesse handles async merge", "does not show marks hidden in merge",
"coalesse disconnected marks with async merge"). Every scenario is
asserted on the host document AND the batched device merge kernel.
"""

from automerge_tpu.api import AutoDoc
from automerge_tpu.core.marks import Mark
from automerge_tpu.ops import DeviceDoc
from automerge_tpu.types import ActorId, ObjType


def actor(i):
    return ActorId(bytes([i]) * 16)


def make_text(content, a=1):
    d = AutoDoc(actor=actor(a))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, content)
    d.commit()
    return d, t


def device_marks(doc, t):
    dev = DeviceDoc.merge([doc])
    return dev.marks(t)


def test_remote_insert_before_none_expand_mark():
    # marks.mts "should handle marks [..] at the beginning of a string"
    d, t = make_text("aaabbbccc")
    d.mark(t, 0, 3, "bold", True, expand="none")
    d.commit()
    assert d.marks(t) == [Mark(0, 3, "bold", True)]

    f = d.fork(actor=actor(2))
    f.insert(t, 0, "A")
    f.insert(t, 4, "B")
    f.commit()
    d.merge(f)
    assert d.marks(t) == [Mark(1, 4, "bold", True)]
    assert device_marks(d, t) == [Mark(1, 4, "bold", True)]


def test_remote_splice_through_none_expand_mark():
    # marks.mts "should handle marks [..] with splice"
    d, t = make_text("aaabbbccc")
    d.mark(t, 0, 3, "bold", True, expand="none")
    d.commit()

    f = d.fork(actor=actor(2))
    f.splice_text(t, 0, 2, "AAA")
    f.splice_text(t, 4, 0, "BBB")
    f.commit()
    d.merge(f)
    assert d.marks(t) == [Mark(3, 4, "bold", True)]
    assert device_marks(d, t) == [Mark(3, 4, "bold", True)]


def test_marks_across_multiple_forks():
    # marks.mts "should handle marks across multiple forks"
    d, t = make_text("aaabbbccc")
    d.mark(t, 0, 3, "bold", True)  # default expand
    d.commit()

    f2 = d.fork(actor=actor(2))
    f2.splice_text(t, 1, 1, "Z")  # replace inside the mark
    f2.commit()
    f3 = d.fork(actor=actor(3))
    f3.splice_text(t, 0, 0, "AAA")  # before the mark: not included
    f3.commit()
    d.merge(f2)
    d.merge(f3)
    assert d.marks(t) == [Mark(3, 6, "bold", True)]
    assert device_marks(d, t) == [Mark(3, 6, "bold", True)]


def test_remote_insert_at_boundaries_expand_both():
    # merged analogue of marks.mts "should handle expand marks (..)":
    # the concurrent remote inserts land exactly at the mark's boundary
    # elements; expand both absorbs them after merge.
    d, t = make_text("aaabbbccc")
    d.mark(t, 3, 6, "bold", True, expand="both")
    d.commit()

    f = d.fork(actor=actor(2))
    f.insert(t, 6, "A")  # at the end boundary
    f.insert(t, 3, "A")  # at the start boundary
    f.commit()
    d.merge(f)
    assert d.text(t) == "aaaAbbbAccc"
    assert d.marks(t) == [Mark(3, 8, "bold", True)]
    assert device_marks(d, t) == [Mark(3, 8, "bold", True)]


def test_remote_insert_at_boundaries_expand_none():
    # same shape with expand none: boundary inserts stay OUTSIDE the span
    d, t = make_text("aaabbbccc")
    d.mark(t, 3, 6, "bold", True, expand="none")
    d.commit()

    f = d.fork(actor=actor(2))
    f.insert(t, 6, "A")
    f.insert(t, 3, "A")
    f.commit()
    d.merge(f)
    assert d.text(t) == "aaaAbbbAccc"
    assert d.marks(t) == [Mark(4, 7, "bold", True)]
    assert device_marks(d, t) == [Mark(4, 7, "bold", True)]


def test_concurrent_overlapping_marks_lamport_winner():
    # marks.mts "coalesse handles async merge": doc1 bumps its op counter
    # so its later mark ops win over doc2's concurrent overlapping mark.
    d, t = make_text("the quick fox jumps over the lazy dog")
    f = d.fork(actor=actor(2))

    d.put("_root", "key1", "value")
    d.put("_root", "key2", "value")
    d.mark(t, 10, 20, "xxx", "aaa")
    d.mark(t, 15, 25, "xxx", "aaa")
    d.commit()

    f.mark(t, 5, 30, "xxx", "bbb")
    f.commit()

    d.merge(f)
    want = [
        Mark(5, 10, "xxx", "bbb"),
        Mark(10, 25, "xxx", "aaa"),
        Mark(25, 30, "xxx", "bbb"),
    ]
    assert d.marks(t) == want
    assert device_marks(d, t) == want

    # marks survive save/load byte roundtrip
    d2 = AutoDoc.load(d.save())
    assert d2.marks(t) == want


def test_hidden_mark_not_shown_after_merge():
    # marks.mts "does not show marks hidden in merge": doc2's concurrent
    # mark lies entirely inside doc1's higher-Lamport span.
    d, t = make_text("the quick fox jumps over the lazy dog")
    f = d.fork(actor=actor(2))

    d.put("_root", "key1", "value")
    d.put("_root", "key2", "value")
    d.mark(t, 10, 20, "xxx", "aaa")
    d.mark(t, 15, 25, "xxx", "aaa")
    d.commit()

    f.mark(t, 11, 24, "xxx", "bbb")
    f.commit()

    d.merge(f)
    assert d.marks(t) == [Mark(10, 25, "xxx", "aaa")]
    assert device_marks(d, t) == [Mark(10, 25, "xxx", "aaa")]


def test_disconnected_marks_coalesce_after_merge():
    # marks.mts "coalesse disconnected marks with async merge"
    d, t = make_text("the quick fox jumps over the lazy dog")
    f = d.fork(actor=actor(2))

    d.put("_root", "key1", "value")
    d.put("_root", "key2", "value")
    d.mark(t, 5, 11, "xxx", "aaa")
    d.mark(t, 19, 25, "xxx", "aaa")
    d.commit()

    f.mark(t, 10, 20, "xxx", "aaa")
    f.commit()

    d.merge(f)
    assert d.marks(t) == [Mark(5, 25, "xxx", "aaa")]
    assert device_marks(d, t) == [Mark(5, 25, "xxx", "aaa")]


def test_merged_marks_on_load_patch_stream():
    # marks.mts "loading marks": a fresh doc loading the merged bytes
    # materializes the same marks through the patch stream.
    d, t = make_text("the quick fox jumps over the lazy dog")
    d.mark(t, 5, 10, "xxx", "aaa")
    d.commit()

    d2 = AutoDoc.load(d.save())
    assert d2.marks(t) == [Mark(5, 10, "xxx", "aaa")]
    # patch-stream materialization parity is covered by test_patch_log;
    # here we only require the loaded marks to match byte-for-byte
    assert d2.save() == d.save()
