"""The per-edit fast-splice path (api.py splice cache + native fastcall).

Differential: every scenario is replayed through a second document with
the fast path disabled (AUTOMERGE_TPU sessions off via manual python
transactions) or through splice_text_many, and the results must agree.
"""

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.types import ActorId, ObjType
from automerge_tpu import native


def _mk():
    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = d.put_object("_root", "text", ObjType.TEXT)
    return d, t


def test_fast_path_interleaved_with_reads():
    d, t = _mk()
    d.splice_text(t, 0, 0, "hello")
    assert d.text(t) == "hello"  # read drains but keeps the session
    d.splice_text(t, 5, 0, " world")  # cache may rebuild; must still land
    d.splice_text(t, 0, 1, "H")
    assert d.text(t) == "Hello world"
    d.commit()
    assert AutoDoc.load(d.save()).text(t) == "Hello world"


def test_fast_path_survives_python_mutation_between_splices():
    d, t = _mk()
    d.splice_text(t, 0, 0, "abc")
    d.put("_root", "k", 1)  # python-path op; drains/drops sessions
    d.splice_text(t, 3, 0, "def")
    assert d.text(t) == "abcdef"
    assert d.hydrate() == {"text": "abcdef", "k": 1}


def test_fast_path_across_commits():
    d, t = _mk()
    for i in range(5):
        d.splice_text(t, d.length(t), 0, f"x{i}")
        d.commit()
    assert d.text(t) == "x0x1x2x3x4"
    loaded = AutoDoc.load(d.save())
    assert loaded.text(t) == "x0x1x2x3x4"


def test_fast_path_non_ascii_widths():
    d, t = _mk()
    d.splice_text(t, 0, 0, "aé中\U0001f600b")  # 1,2,3,4-byte utf8
    assert d.text(t) == "aé中\U0001f600b"
    d.splice_text(t, 2, 1, "z")  # positions are width-unit based (unicode=cp)
    assert d.text(t) == "aéz\U0001f600b"
    d.commit()
    assert AutoDoc.load(d.save()).text(t) == "aéz\U0001f600b"


def test_fast_path_out_of_bounds_raises():
    d, t = _mk()
    d.splice_text(t, 0, 0, "abc")
    with pytest.raises(Exception):
        d.splice_text(t, 99, 0, "x")
    # the transaction is still usable after the error
    d.splice_text(t, 3, 0, "d")
    assert d.text(t) == "abcd"


def test_fastcall_module_loads():
    if native.load() is None:
        pytest.skip("native unavailable")
    fc = native.fastcall()
    assert fc is None or hasattr(fc, "splice")


def test_fast_path_differential_vs_batch():
    import numpy as np

    rng = np.random.default_rng(42)
    edits, ln = [], 0
    for _ in range(2000):
        if ln == 0 or rng.random() < 0.7:
            pos = int(rng.integers(0, ln + 1))
            edits.append([pos, 0, chr(97 + int(rng.integers(0, 26)))])
            ln += 1
        else:
            edits.append([int(rng.integers(0, ln)), 1])
            ln -= 1
    a, ta = _mk()
    for e in edits:
        a.splice_text(ta, e[0], e[1], "".join(e[2:]))
    a.commit()
    b, tb = _mk()
    b.splice_text_many(tb, edits, clamp=False)
    b.commit()
    assert a.text(ta) == b.text(tb)
