"""DeviceDoc read parity: historical reads, marks, cursors, diff.

The device view at any heads must agree with the host document — same
text/keys/values/hydrate at every snapshot, same mark spans, same cursor
resolution, and diffs whose application transforms the before-state into
the after-state (reference surface: rust/automerge/src/read.rs:32-236
historical ``*_at`` variants, automerge/diff.rs, cursor.rs, marks.rs).
"""

import random

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc
from automerge_tpu.patches import apply_patches
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i: int) -> ActorId:
    return ActorId(bytes([i]) * 16)


def host_merge(docs):
    out = AutoDoc(actor=actor(250))
    for d in docs:
        out.merge(d)
    return out


def build_history():
    """Two actors diverge and re-merge over text/map/list/counter state;
    returns (docs, snapshots) where snapshots are heads after each phase."""
    a = AutoDoc(actor=actor(1))
    text = a.put_object("_root", "text", ObjType.TEXT)
    notes = a.put_object("_root", "notes", ObjType.LIST)
    a.put("_root", "clicks", ScalarValue("counter", 0))
    a.splice_text(text, 0, 0, "hello world")
    a.insert(notes, 0, "first")
    a.commit()
    snaps = [a.get_heads()]

    b = a.fork(actor=actor(2))
    a.splice_text(text, 5, 0, " brave")
    a.put("_root", "from_a", 1)
    a.increment("_root", "clicks", 3)
    a.commit()
    snaps.append(a.get_heads())

    b.splice_text(text, 0, 5, "goodbye")
    b.insert(notes, 1, "second")
    b.put("_root", "from_b", 2)
    b.increment("_root", "clicks", 10)
    b.delete("_root", "from_b")
    b.put("_root", "from_b", 3)
    b.commit()
    snaps.append(b.get_heads())

    a.merge(b)
    a.splice_text(text, 0, 0, ">> ")
    a.commit()
    snaps.append(a.get_heads())
    return [a, b], snaps, text, notes


def test_historical_reads_match_host():
    docs, snaps, text, notes = build_history()
    host = host_merge(docs)
    dev = DeviceDoc.merge(docs)
    for heads in snaps:
        assert dev.text(text, heads=heads) == host.text(text, heads=heads)
        assert dev.keys("_root", heads=heads) == host.keys("_root", heads=heads)
        assert dev.length(text, heads=heads) == host.length(text, heads=heads)
        assert dev.length(notes, heads=heads) == host.length(notes, heads=heads)
        assert dev.hydrate(heads=heads) == host.hydrate(heads=heads)
        got = dev.get("_root", "clicks", heads=heads)
        want = host.get("_root", "clicks", heads=heads)
        if want is None:
            assert got is None
        else:
            assert got[0][1] == want[0][1]  # counter value


def test_current_heads_matches_host():
    docs, _, _, _ = build_history()
    host = host_merge(docs)
    dev = DeviceDoc.merge(docs)
    assert sorted(dev.current_heads()) == sorted(host.get_heads())


def test_view_at_empty_heads_is_empty():
    docs, _, _, _ = build_history()
    dev = DeviceDoc.merge(docs)
    assert dev.hydrate(heads=[]) == {}


def test_device_diff_applies_between_snapshots():
    docs, snaps, _, _ = build_history()
    host = host_merge(docs)
    dev = DeviceDoc.merge(docs)
    pairs = [([], snaps[0]), (snaps[0], snaps[1]), (snaps[0], snaps[3]),
             (snaps[1], snaps[3]), (snaps[2], snaps[3]), (snaps[3], snaps[0])]
    for before, after in pairs:
        patches = dev.diff(before, after)
        got = apply_patches(host.hydrate(heads=before), patches)
        assert got == host.hydrate(heads=after), (before, after, patches)


def test_device_diff_matches_host_diff():
    docs, snaps, _, _ = build_history()
    host = host_merge(docs)
    dev = DeviceDoc.merge(docs)
    assert dev.diff(snaps[0], snaps[3]) == host.diff(snaps[0], snaps[3])


def test_make_patches_materializes_current_state():
    docs, _, _, _ = build_history()
    dev = DeviceDoc.merge(docs)
    assert apply_patches({}, dev.make_patches()) == dev.hydrate()


def test_marks_match_host():
    a = AutoDoc(actor=actor(1))
    text = a.put_object("_root", "text", ObjType.TEXT)
    a.splice_text(text, 0, 0, "hello wonderful world")
    a.mark(text, 0, 11, "bold", True)
    a.commit()
    h1 = a.get_heads()
    b = a.fork(actor=actor(2))
    a.mark(text, 6, 15, "italic", True)
    a.commit()
    b.unmark(text, 0, 5, "bold")
    b.splice_text(text, 5, 0, " there")
    b.commit()
    a.merge(b)
    host = host_merge([a, b])
    dev = DeviceDoc.merge([a, b])
    assert dev.marks(text) == host.marks(text)
    assert dev.marks(text, heads=h1) == host.marks(text, heads=h1)


def test_cursors_match_host():
    docs, snaps, text, notes = build_history()
    host = host_merge(docs)
    dev = DeviceDoc.merge(docs)
    n = host.length(text)
    for pos in (0, 1, n // 2, n - 1):
        c_host = host.get_cursor(text, pos)
        c_dev = dev.get_cursor(text, pos)
        assert c_dev == c_host
        assert dev.get_cursor_position(text, c_dev) == pos
    # cursors survive history: resolve a current cursor at an old snapshot
    c = dev.get_cursor(text, 4)
    assert dev.get_cursor_position(text, c, heads=snaps[0]) == \
        host.get_cursor_position(text, c, heads=snaps[0])
    with pytest.raises(ValueError):
        dev.get_cursor(text, 10_000)


def test_cursor_of_deleted_element_reports_would_be_index():
    a = AutoDoc(actor=actor(1))
    lst = a.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        a.insert(lst, i, i)
    a.commit()
    c = host_merge([a]).get_cursor(lst, 2)
    a.delete(lst, 2)
    a.commit()
    host = host_merge([a])
    dev = DeviceDoc.merge([a])
    assert dev.get_cursor_position(lst, c) == host.get_cursor_position(lst, c) == 2


def test_randomized_fork_merge_history_parity():
    rng = random.Random(7)
    root = AutoDoc(actor=actor(1))
    text = root.put_object("_root", "text", ObjType.TEXT)
    root.splice_text(text, 0, 0, "seed text here")
    root.commit()
    docs = [root]
    snaps = [root.get_heads()]
    for step in range(12):
        if len(docs) < 4 and rng.random() < 0.4:
            docs.append(docs[rng.randrange(len(docs))].fork(actor=actor(10 + step)))
        d = docs[rng.randrange(len(docs))]
        n = d.length(text)
        op = rng.random()
        if op < 0.5:
            d.splice_text(text, rng.randrange(n + 1), 0, rng.choice("abcdef") * 2)
        elif op < 0.75 and n > 2:
            d.splice_text(text, rng.randrange(n - 1), 1, "")
        else:
            d.put("_root", f"k{rng.randrange(5)}", step)
        d.commit()
        if rng.random() < 0.35 and len(docs) > 1:
            i, j = rng.sample(range(len(docs)), 2)
            docs[i].merge(docs[j])
        snaps.append(docs[0].get_heads())
    host = host_merge(docs)
    dev = DeviceDoc.merge(docs)
    assert dev.hydrate() == host.hydrate()
    for heads in snaps[::2]:
        assert dev.text(text, heads=heads) == host.text(text, heads=heads)
        assert dev.hydrate(heads=heads) == host.hydrate(heads=heads)
    for before, after in [(snaps[0], None), (snaps[3], snaps[9]), ([], None)]:
        patches = dev.diff(before, after)
        want = host.hydrate(heads=after) if after is not None else host.hydrate()
        assert apply_patches(host.hydrate(heads=before), patches) == want


def test_range_readers_and_parents_parity():
    """map_range/list_range/values/parents agree host vs device
    (reference: read.rs:32-117)."""
    doc = AutoDoc(actor=ActorId(bytes([5]) * 16))
    for k, v in [("alpha", 1), ("beta", 2), ("gamma", 3), ("delta", 4)]:
        doc.put("_root", k, v)
    lst = doc.put_object("_root", "lst", ObjType.LIST)
    for i, v in enumerate([10, 20, 30, 40]):
        doc.insert(lst, i, v)
    inner = doc.insert_object(lst, 2, ObjType.MAP)
    doc.put(inner, "deep", True)
    doc.commit()
    dev = DeviceDoc.merge([doc])

    assert doc.map_range("_root", "b", "g") == dev.map_range("_root", "b", "g")
    assert [k for k, _, _ in doc.map_range("_root", "b", "g")] == ["beta", "delta"]
    assert doc.list_range(lst, 1, 3) == dev.list_range(lst, 1, 3)
    assert len(doc.list_range(lst, 1, 3)) == 2
    # bounded-walk edge cases: end past length, start past length, open end
    assert doc.list_range(lst, 3, 99) == dev.list_range(lst, 3, 99)
    assert doc.list_range(lst, 99) == [] == dev.list_range(lst, 99)
    assert doc.list_range(lst) == dev.list_range(lst)
    assert [i for i, _, _ in doc.list_range(lst)] == [0, 1, 2, 3, 4]
    assert doc.values("_root") == dev.values("_root")
    assert doc.values(lst) == dev.values(lst)
    assert doc.parents(inner) == dev.parents(inner)
    assert dev.parents(inner) == [(lst, 2), ("_root", "lst")]
    # historical list_range at pre-insert heads
    heads0 = doc.get_heads()
    doc.insert(lst, 0, 99)
    doc.commit()
    assert doc.list_range(lst, 0, 2, heads=heads0) == dev.list_range(lst, 0, 2)[:2]


def test_parents_at_historical_heads():
    """parents resolves sequence indices at the given heads
    (reference: read.rs parents_at)."""
    doc = AutoDoc(actor=ActorId(bytes([6]) * 16))
    lst = doc.put_object("_root", "lst", ObjType.LIST)
    for i, v in enumerate([1, 2, 3]):
        doc.insert(lst, i, v)
    inner = doc.insert_object(lst, 2, ObjType.MAP)
    doc.put(inner, "x", 1)
    doc.commit()
    heads0 = doc.get_heads()
    assert doc.parents(inner) == [(lst, 2), ("_root", "lst")]
    doc.insert(lst, 0, 99)  # shifts the element right
    doc.insert(lst, 0, 98)
    doc.commit()
    assert doc.parents(inner) == [(lst, 4), ("_root", "lst")]
    assert doc.parents(inner, heads=heads0) == [(lst, 2), ("_root", "lst")]
    # element deleted at current heads: index resolves at the old heads only
    doc.delete(lst, 4)
    doc.commit()
    assert doc.parents(inner)[0][1] is None
    assert doc.parents(inner, heads=heads0) == [(lst, 2), ("_root", "lst")]
    dev = DeviceDoc.merge([doc])
    assert dev.parents(inner) == doc.parents(inner)
    assert dev.parents(inner, heads=heads0) == doc.parents(inner, heads=heads0)
