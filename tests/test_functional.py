"""The idiomatic functional API (automerge_tpu.functional).

Mirrors the reference's JS wrapper semantics (reference:
javascript/src/stable.ts init/change/merge, proxies.ts map/list/text
proxies, javascript/test/basic_tests): documents are immutable values,
change() returns a new one, proxies write through a transaction.
"""

from __future__ import annotations

import pytest

import automerge_tpu.functional as am
from automerge_tpu.ops import DeviceDoc


def test_change_returns_new_value_and_preserves_input():
    d1 = am.init(actor=bytes([1]) * 16)
    d2 = am.change(d1, lambda d: d.update({"title": "hello"}))
    assert d1.to_py() == {}
    assert d2.to_py() == {"title": "hello"}
    assert d2["title"] == "hello"


def test_nested_containers_from_plain_values():
    d = am.from_dict(
        {
            "config": {"depth": {"n": 3}},
            "items": [1, "two", [True, None]],
            "text": am.Text("abc"),
            "votes": am.Counter(10),
        },
        actor=bytes([2]) * 16,
    )
    assert d.to_py() == {
        "config": {"depth": {"n": 3}},
        "items": [1, "two", [True, None]],
        "text": "abc",
        "votes": 10,
    }
    assert d["config"]["depth"]["n"] == 3
    assert list(d["items"][2]) == [True, None]
    assert str(d["text"]) == "abc"


def test_nested_path_requires_assignment():
    d = am.init()
    # reads of missing keys raise (no silent auto-create, matching the JS
    # wrapper where reading a missing key yields undefined, not a new map)
    with pytest.raises(KeyError):
        am.change(d, lambda r: r["typo"]["b"])
    d2 = am.change(am.init(), lambda r: r.update({"a": {"b": {"c": 1}}}))
    assert d2.to_py() == {"a": {"b": {"c": 1}}}


def test_list_mutations():
    d = am.from_dict({"l": [1, 2, 3]})

    def edit(r):
        lst = r["l"]
        lst.append(4)
        lst.insert(0, 0)
        del lst[2]
        lst[0] = 100
        assert lst.pop() == 4
        lst.extend([7, 8])

    d2 = am.change(d, edit)
    assert d2.to_py()["l"] == [100, 1, 3, 7, 8]


def test_text_and_marks():
    d = am.from_dict({"t": am.Text("hello world")})

    def edit(r):
        t = r["t"]
        t.splice(5, 6, "!")
        t.append("!")
        t.mark(0, 5, "bold", True)

    d2 = am.change(d, edit)
    assert str(d2["t"]) == "hello!!"
    marks = d2._auto.marks(d2._auto.get("_root", "t")[0][2])
    assert marks and marks[0].name == "bold"


def test_counter_increment():
    d = am.from_dict({"n": am.Counter(5)})
    d2 = am.change(d, lambda r: r.increment("n", 3))
    assert d2["n"] == 8


def test_merge_is_a_value_operation():
    base = am.from_dict({"t": am.Text("base")}, actor=bytes([1]) * 16)
    a = am.change(am.clone(base, actor=bytes([2]) * 16), lambda r: r["t"].append(" A"))
    b = am.change(am.clone(base, actor=bytes([3]) * 16), lambda r: r["t"].insert(0, "B "))
    m1 = am.merge(a, b)
    m2 = am.merge(b, a)
    assert m1 == m2
    assert str(m1["t"]) == str(m2["t"])
    # inputs untouched
    assert str(a["t"]) == "base A"
    assert str(b["t"]) == "B base"


def test_save_load_roundtrip():
    d = am.from_dict({"x": 1, "l": [1, 2]})
    d2 = am.load(am.save(d))
    assert d2 == d


def test_change_at_is_concurrent():
    d1 = am.from_dict({"t": am.Text("aaabbb")}, actor=bytes([1]) * 16)
    heads = am.get_heads(d1)
    d2 = am.change(d1, lambda r: r["t"].append("ccc"))
    d3 = am.change_at(d2, heads, lambda r: r["t"].insert(0, "X"))
    # the historical edit didn't see ccc but both survive
    assert str(d3["t"]) == "Xaaabbbccc"


def test_doc_is_immutable():
    d = am.init()
    with pytest.raises(TypeError):
        d.foo = 1


def test_functional_docs_feed_device_merge():
    base = am.from_dict({"t": am.Text("shared ")}, actor=bytes([1]) * 16)
    docs = []
    for i in range(4):
        c = am.clone(base, actor=bytes([10 + i]) * 16)
        docs.append(am.change(c, lambda r, i=i: r["t"].append(f"[{i}]")))
    dev = DeviceDoc.merge([d._auto for d in docs])
    host = docs[0]
    for other in docs[1:]:
        host = am.merge(host, other)
    assert dev.hydrate() == host.to_py()


def test_history_level_functions():
    """getChanges/applyChanges/diff/getLastLocalChange analogues
    (reference: javascript/src/stable.ts:194-1183)."""
    import automerge_tpu.functional as am

    d1 = am.from_dict({"notes": am.Text("hi"), "n": 1})
    h0 = am.get_heads(d1)
    d2 = am.change(d1, lambda d: d["notes"].append(" there"))
    d2 = am.change(d2, lambda d: d["notes"].mark(0, 2, "bold", True))

    raw = am.get_changes(d2, h0)
    assert raw and all(isinstance(c, bytes) for c in raw)
    last = am.get_last_local_change(d2)
    assert last == raw[-1]

    # a peer at h0 catches up by applying the raw chunks
    d1b = am.clone(d1, actor=b"\x07" * 16)
    d3 = am.apply_changes(d1b, raw)
    assert str(d3["notes"]) == "hi there"
    assert [m.name for m in am.marks(d3, "notes")] == ["bold"]

    patches = am.diff(d2, h0, am.get_heads(d2))
    assert patches


def test_marks_on_nested_text():
    import automerge_tpu.functional as am

    d = am.from_dict({"a": {"b": am.Text("nested")}})
    d = am.change(d, lambda r: r["a"]["b"].mark(0, 3, "em", True))
    assert [m.name for m in d["a"]["b"].marks()] == ["em"]


def test_get_conflicts():
    """stable.ts getConflicts: concurrent writers at one prop surface as
    {opid: value}; single-writer props return None."""
    import automerge_tpu.functional as F

    d1 = F.init(actor=b"\x01" * 16)
    d1 = F.change(d1, lambda d: d.__setitem__("pets", [{"name": "Lassie"}]))
    d2 = F.load(F.save(d1), actor=b"\x02" * 16)
    d2 = F.change(d2, lambda d: d["pets"][0].__setitem__("name", "Beethoven"))
    d1 = F.change(d1, lambda d: d["pets"][0].__setitem__("name", "Babe"))
    d3 = F.merge(d1, d2)
    conflicts = F.get_conflicts(d3["pets"][0], "name")
    assert conflicts is not None
    assert sorted(conflicts.values()) == ["Babe", "Beethoven"]
    assert all("@" in k for k in conflicts)  # opid-shaped keys
    # non-conflicting prop
    assert F.get_conflicts(d3, "pets") is None
    # resolving the conflict clears it
    d4 = F.change(d3, lambda d: d["pets"][0].__setitem__("name", "Rex"))
    assert F.get_conflicts(d4["pets"][0], "name") is None
