"""Cursors: stable position references across edits, history and peers.

Reference: rust/automerge/src/cursor.rs, automerge-wasm test/cursors.
"""

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.core.document import AutomergeError
from automerge_tpu.types import ActorId, ObjType


def actor(i):
    return ActorId(bytes([i]) * 16)


def test_cursor_tracks_through_edits():
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello world")
    d.commit()
    cur = d.get_cursor(t, 6)  # "w"
    d.splice_text(t, 0, 0, ">>> ")
    d.commit()
    assert d.get_cursor_position(t, cur) == 10
    d.splice_text(t, 0, 4, "")
    d.commit()
    assert d.get_cursor_position(t, cur) == 6


def test_cursor_on_deleted_element_degrades_gracefully():
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "abc")
    d.commit()
    cur = d.get_cursor(t, 1)  # "b"
    d.splice_text(t, 1, 1, "")
    d.commit()
    assert d.get_cursor_position(t, cur) == 1  # where it would be


def test_cursor_across_merge():
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "shared")
    d.commit()
    cur = d.get_cursor(t, 3)
    f = d.fork(actor=actor(2))
    f.splice_text(t, 0, 0, "ab ")
    f.commit()
    d.merge(f)
    assert d.get_cursor_position(t, cur) == 6
    # the other peer resolves the same cursor identically
    assert f.get_cursor_position(t, cur) == 6


def test_cursor_historical():
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "abcdef")
    d.commit()
    h1 = d.get_heads()
    d.splice_text(t, 0, 3, "")
    d.commit()
    cur = d.get_cursor(t, 0, heads=h1)  # "a" at h1
    assert d.get_cursor_position(t, cur, heads=h1) == 0
    assert d.get_cursor_position(t, cur) == 0  # deleted; degrades to 0


def test_cursor_in_list():
    d = AutoDoc(actor=actor(1))
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        d.insert(lst, i, i)
    d.commit()
    cur = d.get_cursor(lst, 3)
    d.insert(lst, 0, "x")
    d.delete(lst, 1)
    d.commit()
    assert d.get_cursor_position(lst, cur) == 3


def test_cursor_errors():
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "ab")
    d.commit()
    with pytest.raises(AutomergeError):
        d.get_cursor(t, 99)
    with pytest.raises(AutomergeError):
        d.get_cursor("_root", 0)
