"""Change chunk and chunk framing tests."""

import hashlib

import pytest

from automerge_tpu.storage.change import (
    ChangeOp,
    HEAD_STORED,
    ROOT_STORED,
    StoredChange,
    build_change,
    parse_change,
)
from automerge_tpu.storage.chunk import (
    CHUNK_CHANGE,
    ChunkParseError,
    MAGIC_BYTES,
    compress_chunk,
    parse_chunk,
    write_chunk,
)
from automerge_tpu.types import Key, ScalarValue


class TestChunkFraming:
    def test_header_layout(self):
        raw = write_chunk(CHUNK_CHANGE, b"hello")
        assert raw[:4] == MAGIC_BYTES
        assert raw[8] == CHUNK_CHANGE
        assert raw[9] == 5
        assert raw[10:] == b"hello"
        # checksum = first 4 bytes of sha256(type || uleb(len) || data)
        assert raw[4:8] == hashlib.sha256(b"\x01\x05hello").digest()[:4]

    def test_roundtrip(self):
        raw = write_chunk(CHUNK_CHANGE, bytes(range(200)))
        chunk, end = parse_chunk(raw)
        assert end == len(raw)
        assert chunk.checksum_valid
        assert chunk.data == bytes(range(200))

    def test_bad_magic_rejected(self):
        with pytest.raises(ChunkParseError):
            parse_chunk(b"\x00\x00\x00\x00" + b"\x00" * 10)

    def test_truncated_rejected(self):
        raw = write_chunk(CHUNK_CHANGE, b"hello")
        with pytest.raises(ChunkParseError):
            parse_chunk(raw[:-1])

    def test_compressed_roundtrip(self):
        data = b"abcdef" * 100
        raw = write_chunk(CHUNK_CHANGE, data)
        comp = compress_chunk(raw)
        assert len(comp) < len(raw)
        chunk, _ = parse_chunk(comp)
        assert chunk.chunk_type == CHUNK_CHANGE
        assert chunk.data == data
        assert chunk.checksum_valid


def _sample_change():
    actor = bytes.fromhex("aabbccdd" * 4)
    other = bytes.fromhex("00112233" * 4)
    ops = [
        # make a text object under root
        ChangeOp(
            obj=ROOT_STORED,
            key=Key.map("content"),
            insert=False,
            action=4,
            value=ScalarValue.null(),
        ),
        # insert two chars at head of it
        ChangeOp(
            obj=(1, 0),
            key=Key.seq(HEAD_STORED),
            insert=True,
            action=1,
            value=ScalarValue("str", "h"),
        ),
        ChangeOp(
            obj=(1, 0),
            key=Key.seq((2, 0)),
            insert=True,
            action=1,
            value=ScalarValue("str", "i"),
        ),
        # a put with a pred from another actor
        ChangeOp(
            obj=ROOT_STORED,
            key=Key.map("n"),
            insert=False,
            action=1,
            value=ScalarValue("int", -42),
            pred=[(9, 1)],
        ),
    ]
    return StoredChange(
        dependencies=[b"\x11" * 32],
        actor=actor,
        other_actors=[other],
        seq=2,
        start_op=10,
        timestamp=1700000000,
        message="hello world",
        ops=ops,
    )


class TestChangeChunk:
    def test_roundtrip(self):
        change = build_change(_sample_change())
        assert change.hash is not None and len(change.hash) == 32
        parsed, end = parse_change(change.raw_bytes)
        assert end == len(change.raw_bytes)
        assert parsed.hash == change.hash
        assert parsed.actor == change.actor
        assert parsed.other_actors == change.other_actors
        assert parsed.seq == 2
        assert parsed.start_op == 10
        assert parsed.timestamp == 1700000000
        assert parsed.message == "hello world"
        assert parsed.dependencies == change.dependencies
        assert len(parsed.ops) == 4
        for a, b in zip(parsed.ops, change.ops):
            assert (a.obj, a.key, a.insert, a.action, a.value, a.pred) == (
                b.obj,
                b.key,
                b.insert,
                b.action,
                b.value,
                b.pred,
            )

    def test_deterministic_bytes(self):
        c1 = build_change(_sample_change())
        c2 = build_change(_sample_change())
        assert c1.raw_bytes == c2.raw_bytes
        assert c1.hash == c2.hash

    def test_compressed_parse(self):
        change = build_change(_sample_change())
        comp = compress_chunk(change.raw_bytes)
        parsed, _ = parse_change(comp)
        assert parsed.hash == change.hash
        assert parsed.raw_bytes == change.raw_bytes

    def test_scalar_kinds_roundtrip(self):
        kinds = [
            ScalarValue.null(),
            ScalarValue("bool", True),
            ScalarValue("bool", False),
            ScalarValue("uint", 2**40),
            ScalarValue("int", -7),
            ScalarValue("f64", 3.5),
            ScalarValue("str", "héllo"),
            ScalarValue("bytes", b"\x00\x01"),
            ScalarValue("counter", 10),
            ScalarValue("timestamp", 1234567),
            ScalarValue("unknown", (12, b"xyz")),
        ]
        ops = [
            ChangeOp(
                obj=ROOT_STORED,
                key=Key.map(f"k{i}"),
                insert=False,
                action=1,
                value=v,
            )
            for i, v in enumerate(kinds)
        ]
        change = build_change(
            StoredChange(
                dependencies=[],
                actor=b"\x01" * 16,
                other_actors=[],
                seq=1,
                start_op=1,
                timestamp=0,
                message=None,
                ops=ops,
            )
        )
        parsed, _ = parse_change(change.raw_bytes)
        assert [op.value for op in parsed.ops] == kinds


def test_fast_save_columns_match_python_path():
    """The array-native doc-op encoder (_doc_op_cols_fast +
    encode_doc_ops_arrays) produces byte-identical columns to the per-op
    python path on a doc with marks, counters, conflicts, nested objects,
    deletes, and multi-actor merges."""
    from automerge_tpu.api import AutoDoc
    from automerge_tpu.storage.document import encode_doc_ops
    from automerge_tpu.types import ActorId, ObjType, ScalarValue

    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello world")
    d.put("_root", "c", ScalarValue("counter", 5))
    d.put("_root", "n", None)
    d.put("_root", "f", 1.5)
    d.put("_root", "b", True)
    d.mark(t, 0, 5, "bold", True, expand="both")
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(6):
        d.insert(lst, i, i)
    m = d.insert_object(lst, 2, ObjType.MAP)
    d.put(m, "deep", "x")
    d.commit()
    for i in range(5):
        f = d.fork(actor=ActorId(bytes([10 + i]) * 16))
        f.splice_text(t, i, 1, "XY")
        f.increment("_root", "c", i)
        if f.length(lst) > 1:
            f.delete(lst, 0)
        f.put(m, "deep", f"v{i}")
        f.commit()
        d.merge(f)
    d.splice_text(t, 2, 3, "")
    d.commit()

    doc = d.doc
    sorted_idx = doc.actors.sorted_order()
    remap = [0] * len(sorted_idx)
    for p, g in enumerate(sorted_idx):
        remap[g] = p
    fast_cols = doc._doc_op_cols_fast(remap)
    slow_cols = encode_doc_ops(doc._doc_ops(remap))
    assert [s for s, _ in fast_cols] == [s for s, _ in slow_cols]
    for (s, a), (_, b) in zip(fast_cols, slow_cols):
        assert a == b, f"column {s} diverged"
    d2 = AutoDoc.load(d.save())
    assert d2.hydrate() == d.hydrate()
    assert d2.save() == d.save()


def test_fast_reconstruct_matches_python_path():
    """reconstruct_changes_fast rebuilds byte-identical change chunks to
    the per-op python path on a doc with deletes (succ synthesis), marks,
    counters, conflicts, and multi-actor merges."""
    from automerge_tpu.api import AutoDoc
    from automerge_tpu.core.document import (
        reconstruct_changes,
        reconstruct_changes_fast,
    )
    from automerge_tpu.storage.document import parse_document
    from automerge_tpu.types import ActorId, ObjType, ScalarValue

    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "reconstruct me")
    d.put("_root", "c", ScalarValue("counter", 1))
    d.mark(t, 0, 5, "bold", True)
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        d.insert(lst, i, i)
    d.commit()
    for i in range(4):
        f = d.fork(actor=ActorId(bytes([20 + i]) * 16))
        f.splice_text(t, i * 2, 1, "AB")
        f.increment("_root", "c", i)
        f.put("_root", "k", i)  # concurrent map conflict
        if f.length(lst) > 0:
            f.delete(lst, 0)
        f.commit()
        d.merge(f)
    d.commit()
    data = d.save()
    parsed, _ = parse_document(data)
    fast = reconstruct_changes_fast(parsed, verify=True)
    slow = reconstruct_changes(parsed, verify=True)
    assert len(fast) == len(slow)
    for x, y in zip(fast, slow):
        assert x.raw_bytes == y.raw_bytes
        assert x.hash == y.hash
