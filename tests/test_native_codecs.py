"""Differential tests: the native C++ codecs must be byte-identical to the
pure-Python codecs (change hashes are computed over these bytes).
"""

import random

import numpy as np
import pytest

from automerge_tpu import native
from automerge_tpu.utils.codecs import (
    BooleanEncoder,
    DeltaEncoder,
    RleEncoder,
    boolean_decode,
    delta_decode,
    rle_decode,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native codecs unavailable (no compiler)"
)


def py_rle_encode(values, kind):
    enc = RleEncoder(kind)
    for v in values:
        enc.append(v)
    return enc.finish()


def arrays_from(values):
    vals = np.array([0 if v is None else v for v in values], np.int64)
    mask = np.array([v is not None for v in values], np.uint8)
    return vals, mask


CASES = [
    [],
    [None, None, None],
    [5],
    [5, 7],
    [5, 7, 7],
    [5, 7, 7, 7, 9],
    [7, 7, 5],
    [5, None],
    [None, 5],
    [None, None, 3, 3, 3, None, 1, 2, 3, 3, None],
    [0] * 100,
    list(range(50)),
    [2**40, 2**40, -(2**40), 0, None],
    [-1, -1, -5, None, -(2**33)],
]

# Negative values are only representable in the signed (SLEB) codec, so the
# unsigned variant is only generated for non-negative cases.
SIGNED_CASES = [(case, signed) for case in CASES for signed in (False, True)
                if signed or not any(v is not None and v < 0 for v in case)]


@pytest.mark.parametrize("case,signed", SIGNED_CASES)
def test_rle_encode_identical(case, signed):
    kind = "int" if signed else "uint"
    expected = py_rle_encode(case, kind)
    vals, mask = arrays_from(case)
    assert native.rle_encode_array(vals, mask, signed) == expected


@pytest.mark.parametrize("case,signed", SIGNED_CASES)
def test_rle_decode_identical(case, signed):
    kind = "int" if signed else "uint"
    buf = py_rle_encode(case, kind)
    vals, mask = native.rle_decode_array(buf, signed, len(case) + 8)
    got = [int(v) if m else None for v, m in zip(vals, mask)]
    assert got == rle_decode(buf, kind, count=len(case))


@pytest.mark.parametrize("seed", range(5))
def test_rle_fuzz_roundtrip(seed):
    rng = random.Random(seed)
    values = []
    for _ in range(rng.randrange(1, 400)):
        r = rng.random()
        if r < 0.2:
            values.append(None)
        elif r < 0.6:
            values.append(rng.randrange(10))  # encourage runs
        else:
            values.append(rng.randrange(-(2**50), 2**50))
    expected = py_rle_encode(values, "int")
    vals, mask = arrays_from(values)
    assert native.rle_encode_array(vals, mask, True) == expected
    dvals, dmask = native.rle_decode_array(expected, True, len(values))
    got = [int(v) if m else None for v, m in zip(dvals, dmask)]
    assert got == values


@pytest.mark.parametrize("seed", range(3))
def test_delta_identical(seed):
    rng = random.Random(100 + seed)
    values = []
    acc = 0
    for _ in range(rng.randrange(1, 300)):
        if rng.random() < 0.15:
            values.append(None)
        else:
            acc += rng.randrange(-5, 50)
            values.append(acc)
    enc = DeltaEncoder()
    for v in values:
        enc.append(v)
    expected = enc.finish()
    vals, mask = arrays_from(values)
    assert native.delta_encode_array(vals, mask) == expected
    dvals, dmask = native.delta_decode_array(expected, len(values))
    got = [int(v) if m else None for v, m in zip(dvals, dmask)]
    assert got == delta_decode(expected, count=len(values))== values


@pytest.mark.parametrize("seed", range(3))
def test_boolean_identical(seed):
    rng = random.Random(200 + seed)
    values = [rng.random() < 0.5 for _ in range(rng.randrange(1, 500))]
    enc = BooleanEncoder()
    for v in values:
        enc.append(v)
    expected = enc.finish()
    assert native.bool_encode_array(np.array(values, np.uint8)) == expected
    got = native.bool_decode_array(expected, len(values))
    assert list(got) == boolean_decode(expected, count=len(values)) == values


def test_malformed_input_rejected():
    with pytest.raises(ValueError):
        native.rle_decode_array(b"\x01\x80\x80", False, 10)  # truncated uleb
    with pytest.raises(ValueError):
        native.rle_decode_array(b"\x80", False, 10)  # truncated header
    # overlong encodings rejected like the python parser
    with pytest.raises(ValueError):
        native.rle_decode_array(b"\x01\x85\x00", False, 10)


def test_hostile_run_lengths_clamped():
    # header claims 2^40 values; capacity clamps, no OOM
    from automerge_tpu.utils.leb128 import sleb_bytes, uleb_bytes

    buf = sleb_bytes(1 << 40) + uleb_bytes(7)
    vals, mask = native.rle_decode_array(buf, False, 100)
    assert len(vals) == 100 and all(vals == 7)
