"""CLI: export/import/merge/examine/examine-sync/change roundtrips."""

import json
import subprocess
import sys

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.cli import main
from automerge_tpu.sync import SyncState
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i):
    return ActorId(bytes([i]) * 16)


@pytest.fixture
def doc_file(tmp_path):
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "title", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello cli")
    d.put("_root", "count", 3)
    d.commit()
    p = tmp_path / "doc.automerge"
    p.write_bytes(d.save())
    return p


def test_export(doc_file, tmp_path, capsys):
    out = tmp_path / "doc.json"
    assert main(["export", str(doc_file), "-o", str(out)]) == 0
    assert json.loads(out.read_text()) == {"title": "hello cli", "count": 3}


def test_import_roundtrip(tmp_path):
    src = tmp_path / "in.json"
    src.write_text(json.dumps({"a": 1, "items": [1, 2, {"x": True}], "s": "txt"}))
    binout = tmp_path / "out.automerge"
    assert main(["import", str(src), "-o", str(binout)]) == 0
    jsonout = tmp_path / "roundtrip.json"
    assert main(["export", str(binout), "-o", str(jsonout)]) == 0
    assert json.loads(jsonout.read_text()) == {
        "a": 1,
        "items": [1, 2, {"x": True}],
        "s": "txt",
    }


def test_merge(doc_file, tmp_path):
    d = AutoDoc.load(doc_file.read_bytes())
    f = d.fork(actor=actor(2))
    f.put("_root", "extra", "merged")
    f.commit()
    other = tmp_path / "other.automerge"
    other.write_bytes(f.save())
    merged = tmp_path / "merged.automerge"
    assert main(["merge", str(doc_file), str(other), "-o", str(merged)]) == 0
    m = AutoDoc.load(merged.read_bytes())
    assert m.hydrate() == {"title": "hello cli", "count": 3, "extra": "merged"}


def test_examine(doc_file, tmp_path):
    out = tmp_path / "changes.json"
    assert main(["examine", str(doc_file), "-o", str(out)]) == 0
    changes = json.loads(out.read_text())
    assert len(changes) == 1
    ops = changes[0]["ops"]
    assert ops[0]["action"] == "makeText"
    assert changes[0]["hash"]
    assert all("obj" in op for op in ops)


def test_examine_sync(doc_file, tmp_path):
    d = AutoDoc.load(doc_file.read_bytes())
    msg = d.generate_sync_message(SyncState())
    msg_file = tmp_path / "msg.sync"
    msg_file.write_bytes(msg.encode())
    out = tmp_path / "msg.json"
    assert main(["examine-sync", str(msg_file), "-o", str(out)]) == 0
    decoded = json.loads(out.read_text())
    assert decoded["heads"] == [h.hex() for h in d.get_heads()]


def test_change_script(tmp_path):
    out = tmp_path / "new.automerge"
    script = 'set .title "doc"; set .meta \'{"v": 1}\'; counter .n 5; increment .n 3'
    assert main(["change", script, "-o", str(out)]) == 0
    d = AutoDoc.load(out.read_bytes())
    assert d.hydrate() == {"title": "doc", "meta": {"v": 1}, "n": 8}


def test_change_on_existing(doc_file, tmp_path):
    out = tmp_path / "edited.automerge"
    script = "splice .title 5 0 ' brave'; delete .count"
    assert main(["change", str(doc_file), script, "-o", str(out)]) == 0
    d = AutoDoc.load(out.read_bytes())
    assert d.hydrate() == {"title": "hello brave cli"}


def test_module_invocation(doc_file):
    r = subprocess.run(
        [sys.executable, "-m", "automerge_tpu", "export", str(doc_file), "-o", "-"],
        capture_output=True,
        cwd="/root/repo",
    )
    assert r.returncode == 0
    assert json.loads(r.stdout) == {"title": "hello cli", "count": 3}


def test_export_salvage_recovers_damaged_save(tmp_path, capsys):
    """A save with a corrupted trailing chunk exports what survives when
    --salvage is given (and reports the dropped span on stderr)."""
    d = AutoDoc(actor=actor(1))
    d.put("_root", "keep", 1)
    d.commit()
    good = d.save_incremental_after([])
    d.put("_root", "lost", 2)
    d.commit()
    full = d.save_incremental_after([])
    bad = bytearray(full)
    bad[len(good) + 14] ^= 0xFF  # corrupt the second change chunk
    p = tmp_path / "damaged.automerge"
    p.write_bytes(bytes(bad))

    # strict export fails cleanly
    with pytest.raises(Exception):
        main(["export", str(p)])

    out = tmp_path / "salvaged.json"
    assert main(["export", str(p), "--salvage", "-o", str(out)]) == 0
    assert json.loads(out.read_text()) == {"keep": 1}
    err = capsys.readouterr().err
    assert "dropped span" in err


def _durable_doc(tmp_path, n=3):
    d = str(tmp_path / "ddoc")
    dd = AutoDoc.open(d, fsync="never", actor=actor(1))
    for i in range(n):
        dd.put("_root", f"k{i}", i)
        dd.commit()
    dd.close()
    return d


def test_journal_info(tmp_path):
    d = _durable_doc(tmp_path)
    out = tmp_path / "info.json"
    assert main(["journal-info", d, "-o", str(out)]) == 0
    info = json.loads(out.read_text())
    assert info["records"] == 3 and info["change_records"] == 3
    assert info["torn_tail"] is None
    assert info["bytes"] == info["valid_bytes"] > 0
    assert info["snapshot_bytes"] is None  # never compacted yet


def test_journal_info_reports_torn_tail_read_only(tmp_path):
    d = _durable_doc(tmp_path)
    jp = tmp_path / "ddoc" / "journal.waj"
    jp.write_bytes(jp.read_bytes() + b"\x99torn-garbage")
    size_before = jp.stat().st_size
    out = tmp_path / "info.json"
    assert main(["journal-info", d, "-o", str(out)]) == 0
    info = json.loads(out.read_text())
    assert info["torn_tail"] is not None
    assert info["torn_tail"]["dropped_bytes"] == len(b"\x99torn-garbage")
    assert info["records"] == 3
    assert jp.stat().st_size == size_before  # inspection never repairs


def test_journal_info_missing_dir(tmp_path):
    assert main(["journal-info", str(tmp_path / "nope")]) == 1


def test_journal_info_reports_bad_header_as_recoverable(tmp_path):
    """A damaged header must not be reported as total loss when the
    records behind it are what open() will actually recover."""
    d = _durable_doc(tmp_path)
    jp = tmp_path / "ddoc" / "journal.waj"
    data = bytearray(jp.read_bytes())
    data[0] ^= 0xFF
    jp.write_bytes(bytes(data))
    out = tmp_path / "info.json"
    assert main(["journal-info", d, "-o", str(out)]) == 0
    info = json.loads(out.read_text())
    assert info["records"] == 3  # recoverable, not zero
    assert "header will be rewritten" in info["torn_tail"]["reason"]


def test_compact_missing_dir_errors_without_creating(tmp_path):
    """A mistyped path must fail, not silently create a fresh durable doc."""
    target = tmp_path / "aplha"
    assert main(["compact", str(target)]) == 1
    assert not target.exists()


def test_compact_then_reopen(tmp_path):
    d = _durable_doc(tmp_path)
    out = tmp_path / "compact.json"
    assert main(["compact", d, "-o", str(out)]) == 0
    result = json.loads(out.read_text())
    assert result["compacted"] is True
    assert result["records_before"] == 3 and result["records_after"] == 0
    info_out = tmp_path / "info.json"
    assert main(["journal-info", d, "-o", str(info_out)]) == 0
    info = json.loads(info_out.read_text())
    assert info["records"] == 0 and info["snapshot_bytes"] > 0
    # the document survives the CLI round-trip intact
    dd = AutoDoc.open(d)
    assert dd.hydrate() == {"k0": 0, "k1": 1, "k2": 2}
    dd.close()


def test_examine_sync_session_frame(tmp_path):
    """examine-sync understands session frames (0x45 envelope) as well as
    bare protocol messages."""
    from automerge_tpu.sync import SyncSession

    d = AutoDoc(actor=actor(1))
    d.put("_root", "x", 1)
    d.commit()
    frame = SyncSession(d, epoch=5).poll(0)
    p = tmp_path / "frame.sync"
    p.write_bytes(frame)
    out = tmp_path / "frame.json"
    assert main(["examine-sync", str(p), "-o", str(out)]) == 0
    parsed = json.loads(out.read_text())
    assert parsed["frame"]["epoch"] == 5
    assert parsed["message"]["heads"]
