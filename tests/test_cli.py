"""CLI: export/import/merge/examine/examine-sync/change roundtrips."""

import json
import subprocess
import sys

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.cli import main
from automerge_tpu.sync import SyncState
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i):
    return ActorId(bytes([i]) * 16)


@pytest.fixture
def doc_file(tmp_path):
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "title", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello cli")
    d.put("_root", "count", 3)
    d.commit()
    p = tmp_path / "doc.automerge"
    p.write_bytes(d.save())
    return p


def test_export(doc_file, tmp_path, capsys):
    out = tmp_path / "doc.json"
    assert main(["export", str(doc_file), "-o", str(out)]) == 0
    assert json.loads(out.read_text()) == {"title": "hello cli", "count": 3}


def test_import_roundtrip(tmp_path):
    src = tmp_path / "in.json"
    src.write_text(json.dumps({"a": 1, "items": [1, 2, {"x": True}], "s": "txt"}))
    binout = tmp_path / "out.automerge"
    assert main(["import", str(src), "-o", str(binout)]) == 0
    jsonout = tmp_path / "roundtrip.json"
    assert main(["export", str(binout), "-o", str(jsonout)]) == 0
    assert json.loads(jsonout.read_text()) == {
        "a": 1,
        "items": [1, 2, {"x": True}],
        "s": "txt",
    }


def test_merge(doc_file, tmp_path):
    d = AutoDoc.load(doc_file.read_bytes())
    f = d.fork(actor=actor(2))
    f.put("_root", "extra", "merged")
    f.commit()
    other = tmp_path / "other.automerge"
    other.write_bytes(f.save())
    merged = tmp_path / "merged.automerge"
    assert main(["merge", str(doc_file), str(other), "-o", str(merged)]) == 0
    m = AutoDoc.load(merged.read_bytes())
    assert m.hydrate() == {"title": "hello cli", "count": 3, "extra": "merged"}


def test_examine(doc_file, tmp_path):
    out = tmp_path / "changes.json"
    assert main(["examine", str(doc_file), "-o", str(out)]) == 0
    changes = json.loads(out.read_text())
    assert len(changes) == 1
    ops = changes[0]["ops"]
    assert ops[0]["action"] == "makeText"
    assert changes[0]["hash"]
    assert all("obj" in op for op in ops)


def test_examine_sync(doc_file, tmp_path):
    d = AutoDoc.load(doc_file.read_bytes())
    msg = d.generate_sync_message(SyncState())
    msg_file = tmp_path / "msg.sync"
    msg_file.write_bytes(msg.encode())
    out = tmp_path / "msg.json"
    assert main(["examine-sync", str(msg_file), "-o", str(out)]) == 0
    decoded = json.loads(out.read_text())
    assert decoded["heads"] == [h.hex() for h in d.get_heads()]


def test_change_script(tmp_path):
    out = tmp_path / "new.automerge"
    script = 'set .title "doc"; set .meta \'{"v": 1}\'; counter .n 5; increment .n 3'
    assert main(["change", script, "-o", str(out)]) == 0
    d = AutoDoc.load(out.read_bytes())
    assert d.hydrate() == {"title": "doc", "meta": {"v": 1}, "n": 8}


def test_change_on_existing(doc_file, tmp_path):
    out = tmp_path / "edited.automerge"
    script = "splice .title 5 0 ' brave'; delete .count"
    assert main(["change", str(doc_file), script, "-o", str(out)]) == 0
    d = AutoDoc.load(out.read_bytes())
    assert d.hydrate() == {"title": "hello brave cli"}


def test_module_invocation(doc_file):
    r = subprocess.run(
        [sys.executable, "-m", "automerge_tpu", "export", str(doc_file), "-o", "-"],
        capture_output=True,
        cwd="/root/repo",
    )
    assert r.returncode == 0
    assert json.loads(r.stdout) == {"title": "hello cli", "count": 3}


def test_export_salvage_recovers_damaged_save(tmp_path, capsys):
    """A save with a corrupted trailing chunk exports what survives when
    --salvage is given (and reports the dropped span on stderr)."""
    d = AutoDoc(actor=actor(1))
    d.put("_root", "keep", 1)
    d.commit()
    good = d.save_incremental_after([])
    d.put("_root", "lost", 2)
    d.commit()
    full = d.save_incremental_after([])
    bad = bytearray(full)
    bad[len(good) + 14] ^= 0xFF  # corrupt the second change chunk
    p = tmp_path / "damaged.automerge"
    p.write_bytes(bytes(bad))

    # strict export fails cleanly
    with pytest.raises(Exception):
        main(["export", str(p)])

    out = tmp_path / "salvaged.json"
    assert main(["export", str(p), "--salvage", "-o", str(out)]) == 0
    assert json.loads(out.read_text()) == {"keep": 1}
    err = capsys.readouterr().err
    assert "dropped span" in err


def test_examine_sync_session_frame(tmp_path):
    """examine-sync understands session frames (0x45 envelope) as well as
    bare protocol messages."""
    from automerge_tpu.sync import SyncSession

    d = AutoDoc(actor=actor(1))
    d.put("_root", "x", 1)
    d.commit()
    frame = SyncSession(d, epoch=5).poll(0)
    p = tmp_path / "frame.sync"
    p.write_bytes(frame)
    out = tmp_path / "frame.json"
    assert main(["examine-sync", str(p), "-o", str(out)]) == 0
    parsed = json.loads(out.read_text())
    assert parsed["frame"]["epoch"] == 5
    assert parsed["message"]["heads"]
