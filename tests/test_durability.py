"""Crash-safe durability: journal round-trips, torn-tail recovery, the
kill-at-every-write-boundary property suite, compaction bounds, and the
device warm-recovery path.

The property suite is the guarantee the durable layer exists for: a
deterministic workload (commits + sync-absorbed changes + metadata +
compactions) runs against the crash-injection filesystem
(storage/crashsim.py), crashing at every write boundary; every plausible
post-crash disk image (conservative / optimistic / seeded torn + rename
reorderings) must reopen to a valid document containing every change
that was acked before the crash. Everything is seeded — a failure names
the boundary and seed that reproduce it.
"""

import random

import pytest

from automerge_tpu import trace
from automerge_tpu.api import AutoDoc
from automerge_tpu.storage.crashsim import CrashPoint, SimFS
from automerge_tpu.storage.journal import (
    JOURNAL_MAGIC,
    Journal,
    REC_CHANGE,
    REC_META,
    decode_meta,
    encode_meta,
    encode_record,
    scan_records,
)
from automerge_tpu.types import ActorId

DIR = "/dd"  # SimFS namespace is flat; any path works


def actor(i):
    return ActorId(bytes([i]) * 16)


# -- journal unit coverage ----------------------------------------------------


def test_journal_append_reopen_roundtrip(tmp_path):
    p = str(tmp_path / "j.waj")
    j, records, tail = Journal.open(p, fsync="always")
    assert records == [] and not tail.torn
    payloads = [bytes([i]) * (i + 1) for i in range(5)]
    for pl in payloads:
        j.append(REC_CHANGE, pl)
    j.append_meta("k", b"v1")
    j.append_meta("k", b"v2")  # latest wins at replay time
    assert j.record_count == 7
    j.close()

    j2, records, tail = Journal.open(p)
    assert not tail.torn
    assert [r.payload for r in records if r.rec_type == REC_CHANGE] == payloads
    metas = [decode_meta(r.payload) for r in records if r.rec_type == REC_META]
    assert metas == [("k", b"v1"), ("k", b"v2")]
    assert j2.record_count == 7
    j2.close()


def test_journal_truncates_torn_tail(tmp_path):
    p = str(tmp_path / "j.waj")
    j, _, _ = Journal.open(p)
    j.append(REC_CHANGE, b"alpha")
    j.append(REC_CHANGE, b"beta")
    j.close()
    good_size = (tmp_path / "j.waj").stat().st_size

    # every possible torn suffix of a third record truncates back to the
    # two valid records
    rec = encode_record(REC_CHANGE, b"gamma")
    base = (tmp_path / "j.waj").read_bytes()
    for cut in range(1, len(rec)):
        (tmp_path / "j.waj").write_bytes(base + rec[:cut])
        trace.reset_counters()
        j2, records, tail = Journal.open(p)
        assert tail.torn and tail.dropped_bytes == cut
        assert [r.payload for r in records] == [b"alpha", b"beta"]
        assert trace.counters.get("journal.truncated_tail") == cut
        j2.close()
        assert (tmp_path / "j.waj").stat().st_size == good_size
    # a full third record appended after recovery still lands cleanly
    j3, records, _ = Journal.open(p)
    j3.append(REC_CHANGE, b"gamma")
    j3.close()
    recs, rep = scan_records((tmp_path / "j.waj").read_bytes())
    assert [r.payload for r in recs] == [b"alpha", b"beta", b"gamma"]
    assert not rep.torn


def test_journal_rejects_corrupt_middle_as_tail(tmp_path):
    """A flipped byte in record 2 of 3 drops records 2 AND 3: the journal
    never resynchronises past damage (append-only ⇒ first failure IS the
    tail)."""
    p = str(tmp_path / "j.waj")
    j, _, _ = Journal.open(p)
    for pl in (b"one", b"two", b"three"):
        j.append(REC_CHANGE, pl)
    j.close()
    data = bytearray((tmp_path / "j.waj").read_bytes())
    second = len(JOURNAL_MAGIC) + len(encode_record(REC_CHANGE, b"one"))
    data[second + 8] ^= 0xFF  # inside record 2's payload
    (tmp_path / "j.waj").write_bytes(bytes(data))
    _, records, tail = Journal.open(p)
    assert [r.payload for r in records] == [b"one"]
    assert tail.reason == "record checksum mismatch"


def test_journal_corrupt_header_salvages_records(tmp_path):
    """Single-sector damage to the 4-byte header must not destroy the
    CRC-framed records behind it: they re-verify under a synthetic header
    and the file is rewritten around them."""
    p = str(tmp_path / "j.waj")
    j, _, _ = Journal.open(p)
    for pl in (b"one", b"two", b"three"):
        j.append(REC_CHANGE, pl)
    j.close()
    data = bytearray((tmp_path / "j.waj").read_bytes())
    data[1] ^= 0xFF  # hit the magic
    (tmp_path / "j.waj").write_bytes(bytes(data))

    trace.reset_counters()
    j2, records, tail = Journal.open(p)
    assert [r.payload for r in records] == [b"one", b"two", b"three"]
    assert j2.record_count == 3
    assert trace.counters.get("journal.truncated_tail") == 4  # just the header
    j2.append(REC_CHANGE, b"four")
    j2.close()
    recs, rep = scan_records((tmp_path / "j.waj").read_bytes())
    assert [r.payload for r in recs] == [b"one", b"two", b"three", b"four"]
    assert not rep.torn


def test_header_salvage_is_crash_atomic():
    """The bad-header rewrite itself is swept: a crash at any boundary of
    the salvaging open leaves either the old damaged file (salvage reruns)
    or the complete rewritten one — never fewer records."""
    base = SimFS()
    j, _, _ = Journal.open("/j", fs=base)
    for pl in (b"one", b"two", b"three"):
        j.append(REC_CHANGE, pl)
    j.close()
    damaged = bytearray(base.read_bytes("/j"))
    damaged[0] ^= 0xFF

    probe = SimFS.from_disk({"/j": bytes(damaged)})
    Journal.open("/j", fs=probe)[0].close()
    total = probe.ops
    for k in range(1, total + 1):
        fs = SimFS.from_disk({"/j": bytes(damaged)})
        fs.crash_at = k
        try:
            Journal.open("/j", fs=fs)[0].close()
        except CrashPoint:
            pass
        for state in fs.crash_states(random.Random(k)):
            fs2 = SimFS.from_disk(state)
            j2, records, _ = Journal.open("/j", fs=fs2)
            assert [r.payload for r in records] == [b"one", b"two", b"three"], (
                f"crash at {k}: salvage lost records"
            )
            j2.close()


def test_durable_doc_survives_corrupt_journal_header(tmp_path):
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fsync="always", actor=actor(1))
    for i in range(3):
        dd.put("_root", f"k{i}", i)
        dd.commit()
    dd.close()
    jp = tmp_path / "doc" / "journal.waj"
    data = bytearray(jp.read_bytes())
    data[0] ^= 0x01
    jp.write_bytes(bytes(data))
    dd2 = AutoDoc.open(d)
    assert dd2.hydrate() == {"k0": 0, "k1": 1, "k2": 2}
    dd2.close()


def test_journal_empty_and_garbage_files_reinitialise(tmp_path):
    for content in (b"", b"AM", b"garbage-not-a-journal"):
        p = tmp_path / "j.waj"
        p.write_bytes(content)
        j, records, tail = Journal.open(str(p))
        assert records == []
        j.append(REC_CHANGE, b"x")
        j.close()
        recs, rep = scan_records(p.read_bytes())
        assert [r.payload for r in recs] == [b"x"] and not rep.torn
        p.unlink()


def test_journal_fsync_policies(tmp_path):
    trace.reset_timers()
    j, _, _ = Journal.open(str(tmp_path / "a.waj"), fsync="always")
    for i in range(4):
        j.append(REC_CHANGE, b"x")
    j.close()
    assert trace.timing_summary()["journal.fsync"]["n"] >= 4

    trace.reset_timers()
    j, _, _ = Journal.open(
        str(tmp_path / "i.waj"), fsync="interval", fsync_interval=4
    )
    for i in range(8):
        j.append(REC_CHANGE, b"x")
    assert trace.timing_summary()["journal.fsync"]["n"] == 2
    j.close()

    trace.reset_timers()
    j, _, _ = Journal.open(str(tmp_path / "n.waj"), fsync="never")
    for i in range(8):
        j.append(REC_CHANGE, b"x")
    assert "journal.fsync" not in trace.timing_summary()
    j.close()  # close still syncs so the bytes are not lost on clean exit

    with pytest.raises(ValueError):
        Journal.open(str(tmp_path / "z.waj"), fsync="sometimes")


def test_meta_roundtrip():
    for name, blob in (("k", b""), ("sync/peer-1", b"\x00\xff" * 40), ("", b"x")):
        assert decode_meta(encode_meta(name, blob)) == (name, blob)


# -- the crash-point property suite ------------------------------------------


def _run_workload(fs, *, fsync="always", compact_max_records=4):
    """The deterministic durable workload; returns the acked change
    hashes in ack order. Raises CrashPoint mid-flight on a scheduled
    crash (the partial acked list is attached to the exception)."""
    acked = []
    try:
        peer = AutoDoc(actor=actor(9))
        for i in range(3):
            peer.put("_root", f"p{i}", i)
            peer.commit()
        peer_changes = peer.get_changes([])

        dd = AutoDoc.open(
            DIR, fs=fs, fsync=fsync, actor=actor(1),
            compact_max_records=compact_max_records,
        )
        for i in range(8):
            dd.put("_root", f"k{i}", i)
            h = dd.commit()
            acked.append(h)
            if i == 2:
                dd.set_meta("note", b"mid-run")  # metadata rides along
            if i in (3, 5) and peer_changes:
                ch = peer_changes.pop(0)
                dd.apply_changes([ch])  # a change absorbed "from sync"
                acked.append(ch.hash)
        return acked
    except CrashPoint as e:
        e.acked = acked
        raise


def _check_crash_point(k, seed):
    fs = SimFS(crash_at=k)
    try:
        acked = _run_workload(fs)
    except CrashPoint as e:
        acked = e.acked
    rng = random.Random(seed * 100_003 + k)
    for si, state in enumerate(fs.crash_states(rng)):
        fs2 = SimFS.from_disk(state)
        trace.reset_counters()
        dd = AutoDoc.open(DIR, fs=fs2)
        try:
            have = set(dd.doc.history_index)
            missing = [h for h in acked if h not in have]
            assert not missing, (
                f"crash at boundary {k} state {si}: {len(missing)} acked "
                f"changes lost (last fs ops: {fs.op_trace[-4:]})"
            )
            # per-actor seq prefix: recovery must never create gaps
            for actor_idx, idxs in dd.doc.states.items():
                seqs = sorted(dd.doc.history[i].stored.seq for i in idxs)
                assert seqs == list(range(1, len(seqs) + 1)), (
                    f"crash at {k} state {si}: seq gap for actor {actor_idx}"
                )
            dd.hydrate()  # the recovered doc must actually read
        finally:
            dd.close()


def _total_boundaries():
    fs = SimFS()
    _run_workload(fs)
    return fs.ops


def test_crash_point_sweep_sampled():
    """Tier-1 version: every 3rd write boundary (plus both ends) of the
    mixed workload, all crash-state variants."""
    total = _total_boundaries()
    assert total > 20  # the workload really does hit the fs
    for k in sorted(set(range(1, total + 1, 3)) | {1, total}):
        _check_crash_point(k, seed=0)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_crash_point_sweep_full(seed):
    """Every write boundary, four seeds of torn/reorder variants."""
    total = _total_boundaries()
    for k in range(1, total + 1):
        _check_crash_point(k, seed=seed)


def _run_incremental_compact_workload(fs):
    """Commit → compact → commit → compact …: after the first compaction
    the doc holds a run-coded image, so every later compact() exercises
    the INCREMENTAL path (retained image + journal-tail merge). Crashing
    at every write boundary of this workload proves a torn incremental
    merge never leaves a half-spliced snapshot on disk."""
    acked = []
    try:
        dd = AutoDoc.open(
            DIR, fs=fs, fsync="always", actor=actor(1),
            compact_max_records=1 << 30,  # only the explicit compacts below
        )
        for r in range(3):
            for i in range(3):
                dd.put("_root", f"r{r}k{i}", i)
                acked.append(dd.commit())
            dd.compact()
        return acked
    except CrashPoint as e:
        e.acked = acked
        raise


def _check_incremental_crash_point(k, seed):
    from automerge_tpu.integrity import verify_snapshot_bytes
    from automerge_tpu.storage.durable import SNAPSHOT_NAME

    fs = SimFS(crash_at=k)
    try:
        acked = _run_incremental_compact_workload(fs)
    except CrashPoint as e:
        acked = e.acked
    rng = random.Random(seed * 100_003 + k)
    for si, state in enumerate(fs.crash_states(rng)):
        fs2 = SimFS.from_disk(state)
        snap_path = DIR + "/" + SNAPSHOT_NAME
        if fs2.exists(snap_path):
            # the visible snapshot is atomic-rename-protected: whatever
            # boundary the crash hit, it must verify clean end to end —
            # a half-spliced image would surface exactly here
            rep = verify_snapshot_bytes(fs2.read_bytes(snap_path))
            assert rep.ok, (
                f"crash at boundary {k} state {si}: torn snapshot "
                f"({rep.reason} at {rep.first_bad_offset})"
            )
        dd = AutoDoc.open(DIR, fs=fs2)
        try:
            have = set(dd.doc.history_index)
            missing = [h for h in acked if h not in have]
            assert not missing, (
                f"crash at boundary {k} state {si}: {len(missing)} acked "
                f"changes lost after incremental compaction"
            )
            dd.hydrate()
        finally:
            dd.close()


def _incremental_total_boundaries():
    fs = SimFS()
    _run_incremental_compact_workload(fs)
    return fs.ops


def test_incremental_compact_crash_sweep_sampled():
    """Tier-1: every 4th write boundary (plus both ends) of the
    compact-heavy workload, all crash-state variants."""
    total = _incremental_total_boundaries()
    assert total > 20
    for k in sorted(set(range(1, total + 1, 4)) | {1, total}):
        _check_incremental_crash_point(k, seed=0)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(3))
def test_incremental_compact_crash_sweep_full(seed):
    """Every write boundary of the compact-heavy workload."""
    total = _incremental_total_boundaries()
    for k in range(1, total + 1):
        _check_incremental_crash_point(k, seed=seed)


def test_crash_sweep_reports_truncated_tails():
    """Across a sweep, at least one torn state exercises the journal
    tail-truncation counter (the observability the ISSUE demands)."""
    total = _total_boundaries()
    saw_truncate = 0
    for k in range(1, total + 1, 2):
        fs = SimFS(crash_at=k)
        try:
            _run_workload(fs)
        except CrashPoint:
            pass
        for state in fs.crash_states(random.Random(k)):
            trace.reset_counters()
            dd = AutoDoc.open(DIR, fs=SimFS.from_disk(state))
            saw_truncate += trace.counters.get("journal.truncated_tail", 0)
            dd.close()
    assert saw_truncate > 0


def test_harness_catches_missing_dir_fsync():
    """Sensitivity check: a durable layer that skips the directory fsync
    between snapshot rename and journal truncation MUST fail the sweep
    (rename-before-flush reordering loses acked changes)."""

    class NoSyncDirFS(SimFS):
        def sync_dir(self, path):
            self._tick(("sync_dir-skipped",))  # boundary counted, no commit

    fs = NoSyncDirFS()
    _run_workload(fs)
    total = fs.ops
    violations = 0
    for k in range(1, total + 1):
        fs = NoSyncDirFS(crash_at=k)
        try:
            acked = _run_workload(fs)
        except CrashPoint as e:
            acked = e.acked
        for state in fs.crash_states(random.Random(k)):
            dd = AutoDoc.open(DIR, fs=SimFS.from_disk(state))
            have = set(dd.doc.history_index)
            if any(h not in have for h in acked):
                violations += 1
            dd.close()
    assert violations > 0


def test_weaker_fsync_policies_stay_prefix_consistent():
    """Under fsync="never"/"interval" acked changes may be lost on crash,
    but the reopened document must still be a gap-free prefix."""
    for policy in ("interval", "never"):
        total_fs = SimFS()
        _run_workload(total_fs, fsync=policy)
        for k in range(1, total_fs.ops + 1, 4):
            fs = SimFS(crash_at=k)
            try:
                _run_workload(fs, fsync=policy)
            except CrashPoint:
                pass
            for state in fs.crash_states(random.Random(k)):
                dd = AutoDoc.open(DIR, fs=SimFS.from_disk(state))
                for actor_idx, idxs in dd.doc.states.items():
                    seqs = sorted(
                        dd.doc.history[i].stored.seq for i in idxs
                    )
                    assert seqs == list(range(1, len(seqs) + 1))
                dd.hydrate()
                dd.close()


# -- compaction ---------------------------------------------------------------


def test_compaction_bounds_replay(tmp_path):
    """With a low threshold, reopening replays far fewer records than the
    total committed changes — recovery time is bounded by the threshold,
    not the document's age."""
    d = str(tmp_path / "doc")
    n_commits = 40
    dd = AutoDoc.open(d, fsync="never", compact_max_records=8, actor=actor(1))
    for i in range(n_commits):
        dd.put("_root", f"k{i}", i)
        dd.commit()
    expect = dd.hydrate()
    assert dd.journal.record_count <= 9  # thresholds actually engaged
    dd.close()

    trace.reset_counters()
    dd2 = AutoDoc.open(d)
    assert trace.counters.get("journal.replayed_records", 0) < n_commits
    assert trace.counters.get("compact.runs", 0) == 0  # replay alone, no churn
    assert dd2.hydrate() == expect
    dd2.close()


def test_compaction_preserves_meta_and_queue(tmp_path):
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fsync="never", actor=actor(1))
    dd.set_meta("sync/peer", b"\x01\x02")
    dd.put("_root", "x", 1)
    dd.commit()
    assert dd.compact()
    assert dd.journal.record_count == 1  # just the re-appended meta
    dd.close()
    dd2 = AutoDoc.open(d)
    assert dd2.meta == {"sync/peer": b"\x01\x02"}
    assert dd2.hydrate() == {"x": 1}
    dd2.close()


def test_compact_skipped_during_open_manual_transaction(tmp_path):
    dd = AutoDoc.open(str(tmp_path / "doc"), fsync="never", actor=actor(1))
    tx = dd.transaction()
    tx.put("_root", "x", 1)
    assert dd.compact() is False  # pending ops: deferred, not raised
    tx.commit()
    assert dd.compact() is True
    dd.close()


# -- snapshot damage ----------------------------------------------------------


def test_damaged_snapshot_degrades_to_salvage(tmp_path):
    d = tmp_path / "doc"
    dd = AutoDoc.open(str(d), fsync="never", actor=actor(1))
    for i in range(4):
        dd.put("_root", f"k{i}", i)
        dd.commit()
    dd.compact()
    dd.put("_root", "post", "journal")
    post_hash = dd.commit()
    dd.close()

    snap = d / "snapshot.am"
    data = bytearray(snap.read_bytes())
    data[len(data) // 2] ^= 0xFF
    snap.write_bytes(bytes(data))

    trace.reset_counters()
    dd2 = AutoDoc.open(str(d))
    # open degrades instead of refusing, reports what it dropped, and the
    # journaled change is retained — applied if its deps survived, queued
    # awaiting them otherwise (re-fetchable via sync), never silently lost
    assert dd2.doc.salvage_report is not None
    assert trace.counters.get("load.dropped_chunks", 0) >= 1
    in_history = post_hash in dd2.doc.history_index
    in_queue = any(c.hash == post_hash for c in dd2.doc.queue)
    assert in_history or in_queue
    dd2.hydrate()
    dd2.close()


# -- device warm recovery -----------------------------------------------------


def test_device_warm_recovery_matches_host(tmp_path):
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fsync="never", compact_max_records=5, actor=actor(1))
    for i in range(8):
        dd.put("_root", f"k{i}", i)
        dd.commit()
    dd.put("_root", "tail", "x")
    dd.commit()
    expect = dd.hydrate()
    assert dd.journal.record_count > 0  # journal really has post-snapshot work
    dd.close()

    trace.reset_counters()
    trace.reset_timers()
    dd2 = AutoDoc.open(d, device=True)
    timings = trace.timing_summary()
    assert "device.recover" in timings  # the recovery span covers the feed
    # warm path: replayed changes went through OpLog.append_changes, never
    # a from-scratch rebuild
    assert trace.counters.get("device.apply_rebuild", 0) == 0
    assert dd2.device_doc is not None
    assert dd2.device_doc.hydrate() == expect == dd2.hydrate()
    dd2.close()


def test_device_recovery_without_snapshot(tmp_path):
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fsync="never", actor=actor(1))
    dd.put("_root", "only", "journal")
    dd.commit()
    dd.close()
    dd2 = AutoDoc.open(d, device=True)
    assert dd2.device_doc.hydrate() == dd2.hydrate()
    dd2.close()


def test_batch_apply_pays_one_fsync(tmp_path):
    """A 20-change batch absorbed through an ack-point method fsyncs once
    at the boundary, not once per change — same acked-durable guarantee."""
    peer = AutoDoc(actor=actor(9))
    for i in range(20):
        peer.put("_root", f"p{i}", i)
        peer.commit()
    changes = peer.get_changes([])

    dd = AutoDoc.open(str(tmp_path / "doc"), fsync="always", actor=actor(1))
    trace.reset_timers()
    dd.apply_changes(changes)
    t = trace.timing_summary()
    assert t["journal.append"]["n"] == 20
    assert t["journal.fsync"]["n"] == 1
    assert dd.journal.record_count == 20
    dd.close()
    dd2 = AutoDoc.open(str(tmp_path / "doc"))
    assert len(dd2.doc.history) == 20
    dd2.close()


def test_second_open_of_live_journal_is_refused(tmp_path):
    """Two live journals on one file would interleave appends and corrupt
    it; the advisory lock turns that into a clean error (and releases on
    close, with no stale-lockfile hazard)."""
    import fcntl  # noqa: F401 — the guard is POSIX-only, like this test

    from automerge_tpu.storage.journal import JournalError

    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fsync="never", actor=actor(1))
    with pytest.raises(JournalError, match="locked"):
        AutoDoc.open(d)
    dd.close()
    dd2 = AutoDoc.open(d)  # released with the handle
    dd2.close()


# -- real-filesystem integration ---------------------------------------------


def test_real_fs_reopen_after_partial_append(tmp_path):
    """Torn tail on the real OS filesystem: bytes chopped off the journal
    mid-record recover to the last full record."""
    d = tmp_path / "doc"
    dd = AutoDoc.open(str(d), fsync="always", actor=actor(1))
    dd.put("_root", "a", 1)
    h1 = dd.commit()
    dd.put("_root", "b", 2)
    dd.commit()
    dd.close()

    jp = d / "journal.waj"
    data = jp.read_bytes()
    jp.write_bytes(data[:-7])  # tear the second record

    dd2 = AutoDoc.open(str(d))
    assert h1 in dd2.doc.history_index
    assert dd2.hydrate() == {"a": 1}
    dd2.close()


def test_failed_append_poisons_until_compaction_repairs(tmp_path):
    """A journal append failure leaves memory ahead of disk: further
    changes must be refused (never acked over a stranded dependency)
    until compact() re-establishes disk >= memory from the full
    in-memory history."""
    from automerge_tpu.storage.journal import JournalError

    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fsync="never", actor=actor(1))
    dd.put("_root", "ok", 0)
    dd.commit()

    orig_append = dd.journal.append

    def boom(*a, **kw):
        raise OSError(28, "No space left on device")

    dd.journal.append = boom
    dd.put("_root", "lost", 1)
    with pytest.raises(OSError):
        dd.commit()  # change entered history but never hit the journal
    dd.journal.append = orig_append

    dd.put("_root", "dependent", 2)
    with pytest.raises(JournalError, match="out of sync"):
        dd.commit()  # poisoned: refuses instead of stranding a dependent

    assert dd.compact() is True  # snapshot carries the full history
    dd.put("_root", "after", 3)
    dd.commit()
    dd.close()
    dd2 = AutoDoc.open(d)
    h = dd2.hydrate()
    assert h["lost"] == 1 and h["dependent"] == 2 and h["after"] == 3
    dd2.close()


def test_failed_append_keeps_reads_consistent_with_heads(tmp_path):
    """When the journal listener raises mid-apply, the change is already
    in history/heads — reads must still surface its ops (the op store is
    marked stale and rebuilds from history), never a torn in-memory doc."""
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fsync="never", actor=actor(1))
    dd.put("_root", "ok", 0)
    dd.commit()

    src = AutoDoc(actor=actor(2))
    src.put("_root", "incoming", 1)
    src.commit()
    change = src.doc.get_changes([])[-1]

    def boom(*a, **kw):
        raise OSError(28, "No space left on device")

    dd.journal.append = boom
    with pytest.raises(OSError):
        dd.apply_changes([change])
    assert change.hash in dd.doc.history_index  # heads advertise it...
    assert dd.hydrate()["incoming"] == 1  # ...and reads must agree
    dd.close()


def test_close_commits_pending_autocommit_tx(tmp_path):
    """close() (and the context manager) must flush a pending autocommit
    transaction like every other AutoDoc exit surface does."""
    d = str(tmp_path / "doc")
    with AutoDoc.open(d, actor=actor(1)) as dd:
        dd.put("_root", "k", 1)  # no explicit commit
    dd2 = AutoDoc.open(d)
    assert dd2.hydrate() == {"k": 1}
    dd2.close()


def test_open_is_reusable_across_generations(tmp_path):
    """Three open/edit/close generations accumulate state correctly."""
    d = str(tmp_path / "doc")
    for gen in range(3):
        dd = AutoDoc.open(d, fsync="never", actor=actor(gen + 1))
        dd.put("_root", f"gen{gen}", gen)
        dd.commit()
        dd.close()
    dd = AutoDoc.open(d)
    assert dd.hydrate() == {"gen0": 0, "gen1": 1, "gen2": 2}
    dd.close()


# -- group commit (the serving layer's durability contract) -------------------


def test_journal_fsync_combiner_under_concurrent_appends(tmp_path):
    """N threads appending + syncing one journal: every record durable,
    strictly fewer physical fsyncs than sync calls (the leader-elected
    combiner), and the group_commit.batch_size histogram saw a multi-
    append fsync."""
    import threading
    import time as _time

    from automerge_tpu import obs
    from automerge_tpu.storage.journal import OS_FS

    class SlowFS:
        """Real FS with an fsync slow enough that arrivals overlap."""

        def __getattr__(self, name):
            return getattr(OS_FS, name)

        def fsync(self, f):
            _time.sleep(0.005)
            OS_FS.fsync(f)

    p = str(tmp_path / "j.waj")
    j, _, _ = Journal.open(p, fs=SlowFS(), fsync="always")
    trace.reset_timers()
    h = obs.registry.histogram("group_commit.batch_size")
    n0, max0 = h.n, h.vmax
    n_threads, n_appends = 6, 5
    errs = []

    def committer(ti):
        try:
            for k in range(n_appends):
                j.append(REC_CHANGE, bytes([ti]) * (k + 1))
        except Exception as e:  # noqa: BLE001
            errs.append(repr(e))

    ts = [__import__("threading").Thread(target=committer, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    j.close()
    assert not errs, errs
    total = n_threads * n_appends
    fsyncs = trace.timing_summary().get("journal.fsync", {}).get("n", 0)
    assert 0 < fsyncs < total, (fsyncs, total)
    assert h.vmax >= 2 and h.n > n0, (h.n, h.vmax)
    # every record survived, uninterleaved and verifiable
    _, records, tail = Journal.open(p)
    assert not tail.torn and len(records) == total


def _run_grouped_workload(fs):
    """The group-commit workload: commits batch inside ack_scope exactly
    like the serving layer's drained flights; a change counts as ACKED
    only once its scope (and its single deferred fsync) has exited."""
    acked = []
    try:
        dd = AutoDoc.open(DIR, fs=fs, fsync="always", actor=actor(1),
                          compact_max_records=6)
        for g in range(4):
            staged = []
            with dd.ack_scope():
                for i in range(3):
                    dd.put("_root", f"g{g}_k{i}", i)
                    staged.append(dd.commit())
            acked.extend(staged)  # ack AFTER the group fsync
        dd.close()
        return acked
    except CrashPoint as e:
        e.acked = acked
        raise


def test_group_commit_crash_sweep():
    """Crash at every write boundary of the batched workload: every
    post-crash image must replay to (at least) the acked prefix — group
    commit defers fsyncs inside a scope, it must never weaken the
    acked-means-durable contract."""
    fs = SimFS()
    _run_grouped_workload(fs)
    total = fs.ops
    assert total > 10
    for k in range(1, total + 1):
        fs = SimFS(crash_at=k)
        try:
            acked = _run_grouped_workload(fs)
        except CrashPoint as e:
            acked = e.acked
        for si, state in enumerate(fs.crash_states(random.Random(k))):
            dd = AutoDoc.open(DIR, fs=SimFS.from_disk(state))
            try:
                have = set(dd.doc.history_index)
                missing = [h for h in acked if h not in have]
                assert not missing, (
                    f"group-commit crash at {k} state {si}: "
                    f"{len(missing)} acked changes lost"
                )
                for actor_idx, idxs in dd.doc.states.items():
                    seqs = sorted(
                        dd.doc.history[i].stored.seq for i in idxs
                    )
                    assert seqs == list(range(1, len(seqs) + 1))
            finally:
                dd.close()


def test_nested_ack_scope_defers_to_outermost_fsync(tmp_path):
    """The serving layer wraps whole batches of (already ack-wrapped)
    calls in one outer scope: only the OUTERMOST exit pays the policy
    fsync, so k batched commits cost one fsync, not k."""
    dd = AutoDoc.open(str(tmp_path / "doc"), actor=actor(1))
    trace.reset_timers()
    with dd.ack_scope():
        for i in range(5):
            dd.put("_root", f"k{i}", i)
            dd.commit()  # inner (memoized) ack wrapper: nested scope
    t = trace.timing_summary()
    assert t["journal.fsync"]["n"] == 1, t.get("journal.fsync")
    dd.close()
    dd2 = AutoDoc.open(str(tmp_path / "doc"))
    assert len(dd2.doc.history) == 5
    dd2.close()


def test_background_compaction_catches_up_off_ack_path(tmp_path):
    """background_compact=True: threshold crossings schedule compaction
    on the daemon thread; the journal shrinks without any ack paying the
    snapshot, and close() retires the compactor cleanly."""
    import time as _time

    dd = AutoDoc.open(str(tmp_path / "doc"), actor=actor(1),
                      fsync="never", compact_max_records=8,
                      background_compact=True)
    # the background-compaction contract: mutations serialize under the
    # doc lock (the serving layer's executor does exactly this per batch)
    for i in range(40):
        with dd.lock:
            dd.put("_root", f"k{i}", i)
            dd.commit()
    deadline = _time.monotonic() + 10
    while dd.journal.record_count > 8 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert dd.journal.record_count <= 8, dd.journal.record_count
    dd.close()
    dd2 = AutoDoc.open(str(tmp_path / "doc"))
    assert len(dd2.doc.history) == 40
    assert dd2.hydrate()["k39"] == 39
    dd2.close()


def test_cost_ratio_defers_compaction_for_large_snapshots(tmp_path):
    """compact_cost_ratio: a journal far smaller than the snapshot defers
    compaction (cost model) even past the record threshold; growth past
    the ratio compacts as usual."""
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, actor=actor(1), fsync="never",
                      compact_max_records=4)
    # build a snapshot worth of (incompressible) state, compacted
    import hashlib

    for i in range(50):
        blob = "".join(
            hashlib.sha256(f"{i}:{r}".encode()).hexdigest()
            for r in range(4)
        )
        dd.put("_root", f"base{i:03}", blob)
        dd.commit()
    dd.compact()
    snap_bytes = dd._last_snapshot_bytes
    assert snap_bytes > 0
    dd.close()

    dd = AutoDoc.open(d, fsync="never", compact_max_records=4,
                      compact_cost_ratio=0.5)
    assert dd._last_snapshot_bytes > 0  # tracked from the existing snapshot
    trace.reset_counters()
    for i in range(8):  # past the record threshold, tiny vs the snapshot
        dd.put("_root", f"n{i}", i)
        dd.commit()
    assert dd.journal.record_count >= 8  # deferred by cost
    assert trace.counters.get("compact.deferred_by_cost", 0) > 0
    dd.close()


# -- live disk faults: group-commit fsync failure semantics -------------------


def test_group_commit_fsync_eio_errors_every_covered_waiter(tmp_path):
    """An injected EIO on the COMBINED fsync: every ack_scope waiter the
    fsync covered errors (an un-fsynced ack is no ack, for the whole
    group), the journal poisons itself — no retry-after-fsync-failure —
    and the on-disk acked prefix (everything acked before the fault)
    replays intact on reopen."""
    import threading
    import time as _time

    from automerge_tpu import obs
    from automerge_tpu.storage.crashsim import FaultyFS
    from automerge_tpu.storage.journal import OS_FS, JournalPoisoned

    class SlowFaultyFS(FaultyFS):
        """Arrivals overlap the in-flight fsync, so the combiner forms
        real multi-waiter groups before the injected fault lands."""

        def fsync(self, f):
            _time.sleep(0.005)
            super().fsync(f)

    fs = SlowFaultyFS(OS_FS)
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fs=fs, fsync="always", actor=actor(1))
    pre = [f"pre{i}" for i in range(5)]
    for k in pre:
        dd.put("_root", k, 1)
        dd.commit()  # acked + durable before any fault

    obs.reset_all()
    n_threads = 6
    results = [None] * n_threads
    start = threading.Barrier(n_threads)

    def committer(ti):
        start.wait()
        try:
            with dd.ack_scope():
                with dd.lock:
                    dd.put("_root", f"w{ti}", ti)
                    dd.commit()
            results[ti] = "acked"
        except Exception as e:  # noqa: BLE001
            results[ti] = type(e).__name__

    fs.arm("fsync", "EIO", count=1)
    ts = [threading.Thread(target=committer, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

    # the fault fired, the journal poisoned, and NO waiter the poisoned
    # fsync covered was acked: with count=1 the very first physical fsync
    # dies, so every committer errors (none can have been covered by an
    # earlier successful fsync)
    assert dd.journal.poisoned and dd.journal.poisoned_reason == "fsync"
    assert obs.counter_values("journal.poisoned", "reason") == {"fsync": 1}
    assert all(r != "acked" for r in results), results
    assert dd.degraded

    # no-ack-after-poison: the journal never acks another write until
    # reopened/compacted, and the refusal is the retriable kind
    with pytest.raises(JournalPoisoned):
        dd.put("_root", "late", 1)
        dd.commit()
    assert JournalPoisoned.retriable is True

    # the acked prefix is replayable: everything acked pre-fault reads
    # back; the un-acked group MAY be present (durability is allowed to
    # exceed acks, never to lag them)
    dd2 = AutoDoc.open(d, actor=actor(2))
    got = dd2.hydrate()
    for k in pre:
        assert got.get(k) == 1, (k, sorted(got))
    dd2.close()
