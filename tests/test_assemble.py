"""Native log assembly (ops/assemble.py) vs the decode fallback paths.

The assembler is the merge hot path: per-change cached columns ->
Lamport-ordered resolved device columns in one native call. These tests
pin its output to the batch-extraction and per-op python paths on every
workload shape, and exercise the edges (partial history, cache reuse,
empty logs, degenerate counter ranges).
"""

from __future__ import annotations

import warnings

import numpy as np
import pytest

import automerge_tpu.ops.assemble as A
from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import OpLog
from automerge_tpu.types import ActorId, ObjType, ScalarValue

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core unavailable"
)

FIELDS = (
    "id_key", "obj_key", "prop", "elem_ref", "action", "insert",
    "value_tag", "value_int", "width", "expand", "mark_name_idx",
    "pred_src", "pred_tgt", "obj_dense", "obj_table",
)


def assemble(changes):
    for ch in changes:
        ch.cached_cols = None
    import os

    os.environ["AUTOMERGE_TPU_DEBUG"] = "1"
    try:
        return OpLog.from_changes(changes)
    finally:
        os.environ.pop("AUTOMERGE_TPU_DEBUG", None)


def fallback(changes, slow=False):
    if slow:
        return OpLog.from_changes(changes, fast=False)
    orig = A.assemble_log

    def boom(*a, **k):
        raise A.AssembleError("disabled for differential test")

    A.assemble_log = boom
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return OpLog.from_changes(changes)
    finally:
        A.assemble_log = orig


def assert_logs_equal(log_a, log_b):
    assert log_a.n == log_b.n
    assert log_a.n_objs == log_b.n_objs
    for f in FIELDS:
        va = np.asarray(getattr(log_a, f))
        vb = np.asarray(getattr(log_b, f))
        assert np.array_equal(va, vb), f
    # string tables may be ordered differently; resolved strings must match
    pa = [log_a.props[i] if i >= 0 else None for i in log_a.prop]
    pb = [log_b.props[i] if i >= 0 else None for i in log_b.prop]
    assert pa == pb
    ma = [log_a.mark_names[i] if i >= 0 else None for i in log_a.mark_name_idx]
    mb = [log_b.mark_names[i] if i >= 0 else None for i in log_b.mark_name_idx]
    assert ma == mb
    step = max(log_a.n // 97, 1)
    for r in range(0, log_a.n, step):
        assert log_a.values[r] == log_b.values[r]


def rich_doc():
    d = AutoDoc(actor=ActorId(bytes([5]) * 16))
    t = d.put_object("_root", "text", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello \U0001F600 world")
    d.mark(t, 0, 5, "bold", True)
    m = d.put_object("_root", "cfg", ObjType.MAP)
    d.put(m, "a", 1)
    d.put(m, "c", ScalarValue("counter", 3))
    d.increment(m, "c", 4)
    lst = d.put_object("_root", "l", ObjType.LIST)
    d.insert(lst, 0, "x")
    d.insert(lst, 1, 2.5)
    d.commit()
    e = d.fork()
    e.splice_text(t, 2, 3, "XYZ")
    e.commit()
    d.put(m, "a", 2)
    d.commit()
    d.merge(e)
    return d, t


def test_matches_fallback_on_rich_doc():
    d, _ = rich_doc()
    changes = [a.stored for a in d.doc.history]
    log_a = assemble(changes)
    assert_logs_equal(log_a, fallback(changes))
    assert_logs_equal(log_a, fallback(changes, slow=True))


def test_matches_fallback_after_save_load_roundtrip():
    d, _ = rich_doc()
    loaded = AutoDoc.load(d.save())
    changes = [a.stored for a in loaded.doc.history]
    assert_logs_equal(assemble(changes), fallback(changes))


def test_partial_history_obj_fallback():
    """A log missing the make op of a referenced object must still build,
    with the object table unioned exactly like the python paths."""
    d = AutoDoc(actor=ActorId(bytes([7]) * 16))
    m = d.put_object("_root", "m", ObjType.MAP)
    d.commit()
    d.put(m, "x", 1)
    d.put(m, "y", 2)
    d.commit()
    changes = [a.stored for a in d.doc.history]
    partial = changes[1:]  # drop the change holding the make op
    log_a = assemble(partial)
    log_b = fallback(partial)
    assert_logs_equal(log_a, log_b)
    assert log_a.n_objs == 2  # root + the foreign object id


def test_cache_reused_across_merges():
    d, _ = rich_doc()
    changes = [a.stored for a in d.doc.history]
    log1 = assemble(changes)
    caches = [ch.cached_cols for ch in changes]
    assert all(c is not None for c in caches)
    log2 = OpLog.from_changes(changes)
    # same cache objects, not re-decoded
    assert [ch.cached_cols for ch in changes] == caches
    assert_logs_equal(log1, log2)


def test_empty_and_single_change():
    assert OpLog.from_changes([]).n == 0
    d = AutoDoc(actor=ActorId(bytes([9]) * 16))
    d.put("_root", "k", 1)
    d.commit()
    changes = [a.stored for a in d.doc.history]
    assert_logs_equal(assemble(changes), fallback(changes))


def test_degenerate_counter_range_uses_comparator_sort():
    """A sparse counter range far beyond max(4N, 2^22) must route the
    Lamport ordering through the comparator-sort branch and still match
    the fallback exactly."""
    from automerge_tpu.storage.change import (
        ChangeOp, Key, StoredChange, build_change,
    )

    def synth(actor: bytes, start_op: int, keys):
        ops = [
            ChangeOp(
                obj=(0, 0),
                key=Key.map(k),
                insert=False,
                action=1,  # put
                value=ScalarValue("int", i),
            )
            for i, k in enumerate(keys)
        ]
        return build_change(
            StoredChange(
                dependencies=[], actor=actor, other_actors=[], seq=1,
                start_op=start_op, timestamp=0, message=None, ops=ops,
            )
        )

    # interleaved ranks at wildly separated counters: range ~ 2^34 >>
    # max(4N, 2^22) forces the std::sort path in assemble.cpp
    changes = [
        synth(b"\x01" * 8, 1, ["a", "b", "c"]),
        synth(b"\x02" * 8, 1 << 34, ["d", "e"]),
        synth(b"\x03" * 8, 5, ["f", "g", "h", "i"]),
        synth(b"\x02" * 8 + b"x", (1 << 34) + 1, ["j"]),
    ]
    log_a = assemble(changes)
    log_b = fallback(changes)
    assert_logs_equal(log_a, log_b)
    # sanity: ordering really is by (counter, actor-rank)
    assert np.all(np.diff(np.asarray(log_a.id_key)) > 0)


def test_conflicting_width_encoding_recomputed():
    from automerge_tpu.types import using_text_encoding

    d = AutoDoc(actor=ActorId(bytes([11]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "a\U0001F600b")  # 4-byte emoji
    d.commit()
    changes = [a.stored for a in d.doc.history]
    with using_text_encoding("utf8"):
        log8 = assemble(changes)
        w8 = log8.width[np.asarray(log8.value_tag) == 6]
    # same cached changes, different active unit: widths must follow it
    with using_text_encoding("utf16"):
        log16 = OpLog.from_changes(changes)
        w16 = log16.width[np.asarray(log16.value_tag) == 6]
    assert w8.tolist() == [1, 4, 1]  # utf8 bytes
    assert w16.tolist() == [1, 2, 1]  # utf16 units (surrogate pair)
