"""Drain-cycle performance observatory (automerge_tpu/obs/prof.py):
per-cycle stage attribution, top-K boundedness, occupancy at the pack
site, the perfStatus / profileStart / profileStop RPC surface, the
perf-report CLI (live and offline), and the scripts/ci/perf_gate
trajectory gate."""

import json
import os
import subprocess
import sys
import time

import pytest

from automerge_tpu import obs
from automerge_tpu.api import AutoDoc
from automerge_tpu.obs import prof
from automerge_tpu.rpc import RpcServer
from automerge_tpu.types import ActorId, ObjType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERF_GATE = os.path.join(REPO, "scripts", "ci", "perf_gate")


@pytest.fixture(autouse=True)
def _fresh_profiler():
    prof.profiler.reset()
    yield
    prof.profiler.reset()


def _spin(seconds):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        pass


# -- report aggregation -------------------------------------------------------


def test_cycle_attributes_stages_and_split():
    with prof.cycle(kind="t") as c:
        with obs.span("device.stage.dedup"):
            _spin(0.002)
        with obs.span("device.apply"):
            with obs.span("device.stage.splice"):
                _spin(0.004)
            _spin(0.001)
        with obs.span("device.kernel"):
            _spin(0.003)
        with obs.span("journal.fsync"):
            _spin(0.002)
    r = c.report
    assert r["stages"]["dedup"] >= 0.002
    assert r["stages"]["splice"] >= 0.004
    assert r["stages"]["kernel"] >= 0.003
    assert r["stages"]["fsync"] >= 0.002
    # apply (host umbrella) counts once: splice stays breakdown-only.
    # Lower bounds are exact (the spins are inside the spans); upper
    # bounds stay loose — a loaded CI box can preempt between clock
    # reads, and the invariant that matters is attributed <= wall.
    assert 0.005 <= r["host_s"] < 0.1
    assert 0.003 <= r["device_s"] < 0.1
    assert 0.002 <= r["fsync_s"] < 0.1
    assert r["attributed_s"] <= r["wall_s"] * 1.01
    assert r["attributed_frac"] > 0.8


def test_nested_device_work_never_double_counts():
    # the per-doc fallback path launches a kernel INSIDE device.apply;
    # the attributed total must stay <= wall and the split must move the
    # nested device time out of the host share
    with prof.cycle(kind="t") as c:
        with obs.span("device.apply"):
            with obs.span("device.kernel"):
                _spin(0.004)
            _spin(0.001)
    r = c.report
    assert r["attributed_s"] <= r["wall_s"] * 1.01
    assert r["stages"]["kernel"] >= 0.004
    assert r["device_s"] >= 0.004  # reassigned to the device side
    assert r["host_s"] < r["device_s"]  # pure host remainder only


def test_cycle_notes_and_occupancy():
    with prof.cycle(kind="t", docs=3) as c:
        prof.note("useful_rows", 75)
        prof.note("padded_rows", 25)
        prof.note("launches")
    r = c.report
    assert r["occupancy"] == 0.75
    assert r["docs"] == 3 and r["launches"] == 1
    s = prof.profiler.status()
    assert s["occupancy"] == 0.75
    assert s["docs_per_launch"] == 3.0


def test_summarize_reports_matches_status():
    reports = []
    for _ in range(3):
        with prof.cycle(kind="t") as c:
            with obs.span("device.kernel"):
                _spin(0.001)
        reports.append(c.report)
    merged = prof.summarize_reports(reports)
    status = prof.profiler.status()
    assert merged["cycles"] == status["cycles"] == 3
    assert merged["stages"].keys() == status["stages"].keys()
    assert merged["attributed_s"] == status["attributed_s"]


def test_disabled_profiler_is_a_noop():
    prof.profiler.enabled = False
    try:
        with prof.cycle(kind="t") as c:
            with obs.span("device.kernel"):
                pass
        assert c.report is None
        assert prof.profiler.cycles == 0
    finally:
        prof.profiler.enabled = True


def test_top_k_table_stays_bounded():
    k = prof.profiler.top_k
    for i in range(50 * k):
        with prof.cycle(kind="t", doc=f"doc{i % (10 * k)}"):
            pass
    assert len(prof.profiler._doc_costs) <= 4 * k
    top = prof.profiler.top_docs()
    assert len(top) <= k
    # the table orders by attributed seconds, descending
    secs = [e["seconds"] for e in top]
    assert secs == sorted(secs, reverse=True)


def test_cycle_doc_wall_does_not_double_count_staging():
    # a serve drain attributes its whole wall to its doc; staging
    # seconds note_doc'd for the SAME doc inside that cycle are part of
    # the wall and must not add on top
    with prof.cycle(kind="t", doc="d1") as c:
        prof.note_doc("d1", 0.001)
        _spin(0.004)
    r = c.report
    assert r["doc_costs"]["d1"] == pytest.approx(r["wall_s"], rel=0.01)


def test_umbrella_opened_before_cycle_clamps_to_cycle_wall():
    # a span entered BEFORE the cycle but exited inside it contributes
    # only its overlap with the cycle, never pre-cycle time
    outer = obs.span("device.apply")
    outer.__enter__()
    _spin(0.01)
    with prof.cycle(kind="t") as c:
        outer.__exit__(None, None, None)
    r = c.report
    assert r["attributed_s"] <= r["wall_s"] * 1.05, r
    assert r["attributed_frac"] <= 1.0
    # the aggregate view clamps too
    assert prof.summarize_reports([r])["attributed_frac"] <= 1.0


def test_device_umbrella_under_host_umbrella_reassigns_split():
    # a live accelerator serve drain: rpc.request (host umbrella) wraps
    # the batched device region — the split must still call it device
    with prof.cycle(kind="t") as c:
        with obs.span("rpc.request"):
            with obs.span("device.batched"):
                with obs.span("device.kernel"):
                    _spin(0.004)
            _spin(0.001)
    r = c.report
    assert r["attributed_s"] <= r["wall_s"] * 1.01
    assert r["device_s"] >= 0.004, r
    assert r["host_s"] < r["device_s"], r
    assert r["stages"]["kernel"] >= 0.004


def test_whale_doc_survives_pruning():
    # space-saving property: a doc that dominates the cost can never be
    # rotated out by a crowd of cheap ones
    prof.profiler._doc_costs["whale"] = 100.0
    for i in range(100 * prof.profiler.top_k):
        with prof.cycle(kind="t", doc=f"cheap{i}"):
            pass
    assert "whale" in dict(
        (e["doc"], e["seconds"]) for e in prof.profiler.top_docs()
    )


# -- real drains through the device layer ------------------------------------


def _mkdoc(i, ballast=300):
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "live text ")
    arch = base.put_object("_root", "a", ObjType.TEXT)
    base.splice_text(arch, 0, 0, "x" * ballast)
    base.commit()
    chs = [a.stored for a in base.doc.history]
    f = base.fork(actor=ActorId(bytes([10 + i]) * 16))
    f.splice_text(t, i % 5, 0, f"<{i}>")
    f.commit()
    have = {c.hash for c in chs}
    delta = [a.stored for a in f.doc.history if a.stored.hash not in have]
    return chs, delta


def _cross_doc_work(n, seed=0):
    from automerge_tpu.ops import DeviceDoc, OpLog

    return [
        (DeviceDoc.resolve(OpLog.from_changes(chs)), [delta])
        for chs, delta in (_mkdoc(seed + i) for i in range(n))
    ]


def test_batched_drain_cycle_report():
    from automerge_tpu.ops.batched import apply_cross_doc

    apply_cross_doc(_cross_doc_work(3))  # warm the jit caches
    work = _cross_doc_work(3, seed=3)
    prof.profiler.reset()
    with prof.cycle(kind="t") as c:
        apply_cross_doc(work)
    r = c.report
    # the acceptance contract: >=90% of the drain wall clock lands in
    # named stages, occupancy comes from the pack site, one launch
    assert r["attributed_frac"] >= 0.9, r
    assert r["launches"] == 1 and r["docs"] == 3
    assert r["useful_rows"] > 0 and r["occupancy"] is not None
    assert 0 < r["occupancy"] <= 1.0
    for stage in ("pack", "h2d", "kernel", "readback", "scatter"):
        assert r["stages"].get(stage, 0) > 0, (stage, r["stages"])
    # the host staging half attributes through the vectorized cross-doc
    # stages (host_pack/host_splice) — or through the scalar splice
    # stage when AUTOMERGE_TPU_HOST_BATCH=0 forces the per-doc path
    assert (
        r["stages"].get("host_splice", 0) > 0
        or r["stages"].get("splice", 0) > 0
    ), r["stages"]
    # the pack site's counters fired alongside
    rows = obs.counter_values("device.batch_rows", "").get("", 0)
    pad = obs.counter_values("device.batch_padding_rows", "").get("", 0)
    assert rows > 0 and rows / (rows + pad) == pytest.approx(
        r["occupancy"], abs=0.2
    )
    # per-doc attribution reached the top-K table
    assert prof.profiler.top_docs()


def test_cycle_report_lands_in_flight_ring():
    from automerge_tpu.ops.batched import apply_cross_doc

    with prof.cycle(kind="t"):
        apply_cross_doc(_cross_doc_work(2, seed=6))
    evs = [
        {"name": n, "fields": f}
        for _t, n, f in obs.flight.events
        if n == "drain.cycle_report"
    ]
    assert evs
    merged = prof.summarize_flight_events(evs)
    assert merged["cycles"] >= 1
    assert merged["stages"].get("kernel", {}).get("seconds", 0) > 0
    assert merged["attributed_frac"] > 0


# -- RPC surface --------------------------------------------------------------


def test_perf_status_rpc():
    rpc = RpcServer()
    with prof.cycle(kind="t"):
        with obs.span("device.kernel"):
            _spin(0.001)
    resp = rpc.handle({"id": 1, "method": "perfStatus", "params": {}})
    assert "error" not in resp, resp
    s = resp["result"]
    assert s["cycles"] >= 1
    assert "host_pct" in s and "device_pct" in s and "stages" in s
    assert "drain_cycle_seconds" in s and "queue_wait_seconds" in s
    json.dumps(s)  # the whole status must be JSON-serializable


def test_profile_start_stop_rpc_clean_degrade(tmp_path):
    rpc = RpcServer()
    # stop with nothing active: a clean {"ok": false}, not an error
    resp = rpc.handle({"id": 1, "method": "profileStop", "params": {}})
    assert "error" not in resp and resp["result"]["ok"] is False
    d = str(tmp_path / "jaxprof")
    start = rpc.handle(
        {"id": 2, "method": "profileStart", "params": {"dir": d}}
    )["result"]
    if not start["ok"]:
        # the clean-degrade contract on boxes without a profiler backend
        assert "reason" in start
        return
    # a second start while active degrades, never raises
    again = rpc.handle(
        {"id": 3, "method": "profileStart", "params": {}}
    )["result"]
    assert again["ok"] is False
    # kernel-launch sites annotate while the capture is active
    from automerge_tpu.ops.batched import apply_cross_doc

    apply_cross_doc(_cross_doc_work(2, seed=9))
    stop = rpc.handle(
        {"id": 4, "method": "profileStop", "params": {}}
    )["result"]
    assert stop["ok"] is True and stop["dir"] == d
    # the capture produced an xplane/trace artifact under the dir
    found = [
        os.path.join(r, fn) for r, _d, fs in os.walk(d) for fn in fs
    ]
    assert found, "profiler capture produced no artifacts"


def test_annotate_is_free_when_inactive():
    from contextlib import AbstractContextManager

    cm = prof.annotate("amtpu.test")
    assert isinstance(cm, AbstractContextManager)
    with cm:
        pass
    assert prof._jax_trace["active"] is False


# -- perf-report CLI ----------------------------------------------------------


def test_perf_report_live_server(tmp_path, capsys):
    """Live mode: serve drains are real profiler cycles, and
    ``perf-report --connect`` renders them from the perfStatus RPC."""
    import socket as socketmod

    from automerge_tpu.cli import main as cli_main
    from automerge_tpu.serve import SocketRpcServer

    srv = SocketRpcServer(host="127.0.0.1", port=0,
                          durable_dir=str(tmp_path / "dur"))
    os.makedirs(str(tmp_path / "dur"), exist_ok=True)
    srv.start()
    host, port = srv.address
    try:
        sock = socketmod.create_connection((host, port))
        f = sock.makefile("r")
        rid = [0]

        def call(method, **params):
            rid[0] += 1
            sock.sendall((json.dumps(
                {"id": rid[0], "method": method, "params": params}
            ) + "\n").encode())
            resp = json.loads(f.readline())
            assert "error" not in resp, resp
            return resp["result"]

        d = call("openDurable", name="livedoc", fsync="never")["doc"]
        for i in range(6):
            call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
            call("commit", doc=d)
        out_path = tmp_path / "live.json"
        rc = cli_main(["perf-report", "--connect", f"{host}:{port}",
                       "--format", "json", "-o", str(out_path)])
        assert rc == 0
        rep = json.loads(out_path.read_text())
        # every drain of the shard pool was a profiler cycle, anchored
        # to the real serve path, with the doc named in the top table
        assert rep["cycles"] >= 1
        assert any(e["doc"] == "livedoc" for e in rep["top_docs"])
        assert rep["drain_cycle_seconds"]["p50"] > 0
        text_path = tmp_path / "live.txt"
        rc = cli_main(["perf-report", "--connect", f"{host}:{port}",
                       "-o", str(text_path)])
        assert rc == 0
        assert "drain cycles:" in text_path.read_text()
        sock.close()
    finally:
        srv.stop()
    # drain.cycle_seconds / drain.docs recorded at the drain loop
    assert obs.registry.histogram("drain.cycle_seconds").n >= 1
    assert obs.registry.histogram("drain.docs").n >= 1


def test_perf_report_offline_from_flight_dump(tmp_path, capsys):
    from automerge_tpu.cli import main as cli_main
    from automerge_tpu.ops.batched import apply_cross_doc

    with prof.cycle(kind="t"):
        apply_cross_doc(_cross_doc_work(2, seed=12))
    dump = obs.flight.dump(str(tmp_path / "flight-test-1-1.json"))
    out_path = tmp_path / "report.txt"
    rc = cli_main(["perf-report", dump, "-o", str(out_path)])
    assert rc == 0
    text = out_path.read_text()
    assert "drain cycles:" in text and "attributed" in text
    assert "split: host" in text and "device" in text
    rc = cli_main(["perf-report", dump, "--format", "json",
                   "-o", str(tmp_path / "report.json")])
    assert rc == 0
    rep = json.loads((tmp_path / "report.json").read_text())
    assert rep["cycles"] >= 1 and rep["source"] == "flight"


def test_perf_report_no_input_errors(tmp_path, capsys):
    from automerge_tpu.cli import main as cli_main

    assert cli_main(["perf-report"]) == 1


# -- scripts/ci/perf_gate -----------------------------------------------------


def _bench_json(scale=1.0, host=None, config=None):
    d = {
        "metric": "x", "value": 1.0,
        "git_commit": "deadbeef",
        "config": dict(config or {"BENCH_REPS": 1}),
        "configs": {
            "micro": {
                "map_10000": {
                    "put_ops_per_sec": 700000.0 * scale,
                    "apply_ops_per_sec": 130000.0 * scale,
                    "save_ms": 22.0 / scale,
                    "load_ms": 50.0 / scale,
                },
                "map_1000": {"put_ops_per_sec": 500000.0 * scale},
                "range_10000": {"iter_elems_per_sec": 1.2e6 * scale},
            },
        },
    }
    if host is not None:
        d["host"] = host
    return d


def _run_gate(tmp_path, cur, baseline, extra_env=None):
    traj = tmp_path / "traj"
    traj.mkdir(exist_ok=True)
    (traj / "BENCH_r01.json").write_text(json.dumps(baseline))
    cur_path = tmp_path / "cur.json"
    cur_path.write_text(json.dumps(cur))
    out = tmp_path / "out"
    env = dict(
        os.environ,
        PERF_GATE_JSON=str(cur_path),
        PERF_GATE_DIR=str(traj),
        PERF_GATE_OUT=str(out),
        **(extra_env or {}),
    )
    p = subprocess.run(
        [sys.executable, PERF_GATE], env=env,
        capture_output=True, text=True, timeout=120,
    )
    return p, out


def test_perf_gate_passes_and_self_tests(tmp_path):
    fp = {"cpu_count": 8, "machine": "x"}
    p, out = _run_gate(
        tmp_path, _bench_json(1.0, host=fp), _bench_json(1.0, host=fp)
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert "PASS" in p.stdout
    assert "self-test ok" in p.stdout
    # the next trajectory artifact was emitted with the round bumped
    assert (out / "BENCH_r02.json").exists(), p.stdout


def test_perf_gate_fails_on_real_regression(tmp_path):
    # a 3x across-the-board slowdown sits far past the 0.5 floor
    p, _ = _run_gate(tmp_path, _bench_json(1 / 3.0), _bench_json(1.0))
    assert p.returncode == 1, p.stdout + p.stderr
    assert "REGRESSION" in p.stdout + p.stderr


def test_perf_gate_noise_tolerance(tmp_path):
    # 30% slower is noise under the default 0.5 relative floor
    p, _ = _run_gate(tmp_path, _bench_json(0.7), _bench_json(1.0))
    assert p.returncode == 0, p.stdout + p.stderr


def test_perf_gate_self_test_survives_big_improvement(tmp_path):
    # a genuine 3x speedup must PASS — the self-test injects from the
    # baseline, so an improved current run cannot absorb the injection
    p, _ = _run_gate(tmp_path, _bench_json(3.0), _bench_json(1.0))
    assert p.returncode == 0, p.stdout + p.stderr
    assert "self-test ok" in p.stdout, p.stdout


def test_perf_gate_refuses_cross_host_comparison(tmp_path):
    p, out = _run_gate(
        tmp_path,
        _bench_json(0.01, host={"cpu_count": 8, "machine": "a"}),
        _bench_json(1.0, host={"cpu_count": 64, "machine": "b"}),
    )
    # a 100x "regression" against another box: refused, not failed
    assert p.returncode == 0, p.stdout + p.stderr
    assert "SKIPPED" in p.stdout
    assert (out / "BENCH_r02.json").exists()


def test_perf_gate_unfingerprinted_baseline_warns_or_refuses(tmp_path):
    # pre-fingerprint baseline: compares with a loud warning by
    # default, refuses under PERF_GATE_REQUIRE_FINGERPRINT=1
    cur = _bench_json(1.0, host={"cpu_count": 8, "machine": "x"})
    p, _ = _run_gate(tmp_path, cur, _bench_json(1.0))
    assert p.returncode == 0 and "WARNING" in p.stdout, p.stdout
    p, _ = _run_gate(
        tmp_path, cur, _bench_json(1.0),
        extra_env={"PERF_GATE_REQUIRE_FINGERPRINT": "1"},
    )
    assert p.returncode == 0 and "SKIPPED" in p.stdout, p.stdout


def test_perf_gate_size_gated_metrics_skip_on_mismatch(tmp_path):
    base = _bench_json(1.0, config={"BENCH_REPLAY_EDITS": 259778})
    base["configs"]["replay"] = {"ops_per_sec": 1e9}  # huge-box number
    cur = _bench_json(1.0, config={"BENCH_REPLAY_EDITS": 20000})
    cur["configs"]["replay"] = {"ops_per_sec": 1e5}
    p, _ = _run_gate(tmp_path, cur, base)
    # sizes differ -> replay is not comparable; micro still gates; pass
    assert p.returncode == 0, p.stdout + p.stderr
    assert "replay" not in p.stdout


def test_perf_gate_salvages_committed_r05_tail():
    # the real committed trajectory: r05's wrapper has parsed=null and
    # only a truncated tail — its micro guards must still be recovered
    import importlib.util
    from importlib.machinery import SourceFileLoader

    loader = SourceFileLoader("perf_gate_mod", PERF_GATE)
    spec = importlib.util.spec_from_loader("perf_gate_mod", loader)
    pg = importlib.util.module_from_spec(spec)
    loader.exec_module(pg)
    point = pg.load_point(os.path.join(REPO, "BENCH_r05.json"))
    assert point is not None and point.get("salvaged") is True
    micro = point["configs"]["micro"]["map_10000"]
    assert micro["put_ops_per_sec"] > 0 and micro["save_ms"] > 0
