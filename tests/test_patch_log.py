"""Live PatchLog / observer path: every mutating route notifies.

Reference behavior: patches/patch_log.rs (active/inactive switch, every
mutator has a *_log_patches variant, lib.rs:100-102) and
automerge/current_state.rs (patches materializing the whole doc on load).
Here: a patch callback attached to AutoDoc fires after commit, merge,
apply_changes, sync receive, and incremental load, and replaying the
patches tracks hydrate() exactly.
"""

from automerge_tpu.api import AutoDoc
from automerge_tpu.patches import apply_patches
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i: int) -> ActorId:
    return ActorId(bytes([i]) * 16)


class Tracker:
    """A materialized view maintained purely from patch notifications."""

    def __init__(self, doc: AutoDoc, from_scratch=True):
        self.state = {}
        self.notifications = 0
        doc.set_patch_callback(self._on_patches, from_scratch=from_scratch)

    def _on_patches(self, patches):
        self.notifications += 1
        self.state = apply_patches(self.state, patches)


def test_callback_fires_on_commit():
    d = AutoDoc(actor=actor(1))
    t = Tracker(d)
    d.put("_root", "a", 1)
    d.commit()
    assert t.state == d.hydrate() == {"a": 1}
    text = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(text, 0, 0, "hi")
    d.commit()
    assert t.state == d.hydrate() == {"a": 1, "t": "hi"}
    assert t.notifications == 2


def test_from_scratch_materializes_existing_state():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "pre", "existing")
    d.commit()
    t = Tracker(d, from_scratch=True)
    assert t.state == {"pre": "existing"}
    assert t.notifications == 1


def test_attach_without_scratch_reports_only_new_changes():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "pre", "existing")
    d.commit()
    seen = []
    d.set_patch_callback(lambda ps: seen.extend(ps))
    assert seen == []  # nothing new yet
    d.put("_root", "new", 1)
    d.commit()
    assert len(seen) == 1 and seen[0].action.key == "new"


def test_callback_fires_on_merge_and_apply_changes():
    d = AutoDoc(actor=actor(1))
    t = Tracker(d)
    other = AutoDoc(actor=actor(2))
    other.put("_root", "via_merge", True)
    other.commit()
    d.merge(other)
    assert t.state == d.hydrate()

    third = AutoDoc(actor=actor(3))
    third.put("_root", "via_apply", ScalarValue("counter", 4))
    third.commit()
    d.apply_changes(third.get_changes([]))
    assert t.state == d.hydrate()


def test_callback_fires_on_sync_receive():
    from automerge_tpu.sync import SyncState

    d1 = AutoDoc(actor=actor(1))
    d2 = AutoDoc(actor=actor(2))
    t = Tracker(d2)
    d1.put("_root", "synced", "yes")
    d1.commit()
    s1, s2 = SyncState(), SyncState()
    for _ in range(10):
        m = d1.generate_sync_message(s1)
        if m is not None:
            d2.receive_sync_message(s2, m)
        m2 = d2.generate_sync_message(s2)
        if m2 is not None:
            d1.receive_sync_message(s1, m2)
        if m is None and m2 is None:
            break
    assert t.state == d2.hydrate() == {"synced": "yes"}


def test_callback_fires_on_incremental_load():
    d1 = AutoDoc(actor=actor(1))
    d1.put("_root", "a", 1)
    d1.commit()
    saved = d1.save()
    d1.put("_root", "b", 2)
    d1.commit()
    incr = d1.save_incremental_after([h for h in _heads_of(saved)])

    d2 = AutoDoc.load(saved)
    t = Tracker(d2)
    d2.load_incremental(incr)
    assert t.state == d2.hydrate() == {"a": 1, "b": 2}


def _heads_of(saved: bytes):
    return AutoDoc.load(saved).get_heads()


def test_inactive_log_reports_nothing():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "a", 1)
    d.commit()
    assert d.make_patches() == []  # log starts inactive
    seen = []
    d.set_patch_callback(lambda ps: seen.extend(ps))
    d.set_patch_callback(None)  # detach deactivates
    d.put("_root", "b", 2)
    d.commit()
    assert seen == []


def test_tracker_follows_deep_edits():
    d = AutoDoc(actor=actor(1))
    t = Tracker(d)
    m = d.put_object("_root", "m", ObjType.MAP)
    lst = d.put_object(m, "list", ObjType.LIST)
    d.insert(lst, 0, "x")
    d.commit()
    d.insert(lst, 1, "y")
    d.delete(lst, 0)
    d.put(m, "k", 9)
    d.commit()
    assert t.state == d.hydrate() == {"m": {"list": ["y"], "k": 9}}
