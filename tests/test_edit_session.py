"""Native text-edit session: the local-transaction hot path.

The session (native/session.cpp) owns one text object's visible-element
state inside an AutoDoc transaction; splices resolve in C++ and commit
encodes straight from arrays (storage/change.encode_ops_with_tail).
These tests pin the invariant that the session path is BYTE-IDENTICAL
to the python transaction path — same ops, same change chunks, same
hashes — across drains, mixed transactions, rollbacks, unicode widths,
and fallback conditions (reference semantics: transaction/inner.rs
inner_splice).
"""

import random

import pytest

from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.core.marks import Mark
from automerge_tpu.types import ActorId, ObjType

pytestmark = pytest.mark.skipif(
    not native.available() or not hasattr(native.load() or object, "am_edit_create"),
    reason="native edit session unavailable",
)


def actor(i):
    return ActorId(bytes([i]) * 16)


def two_docs():
    """Two fresh docs with a text object; doc b has sessions disabled."""
    a = AutoDoc(actor=actor(1))
    ta = a.put_object("_root", "t", ObjType.TEXT)
    b = AutoDoc(actor=actor(1))
    tb = b.put_object("_root", "t", ObjType.TEXT)
    tx = b._ensure_tx()
    tx.enable_sessions = False
    return a, ta, b, tb


def assert_same_changes(a, b):
    ca = a.get_changes([])
    cb = b.get_changes([])
    assert len(ca) == len(cb)
    for x, y in zip(ca, cb):
        assert x.raw_bytes == y.raw_bytes


def test_session_matches_python_randomized():
    rng = random.Random(7)
    a, ta, b, tb = two_docs()
    edits = []
    ln = 0
    for _ in range(400):
        if ln == 0 or rng.random() < 0.7:
            pos = rng.randint(0, ln)
            txt = chr(rng.randint(97, 122)) * rng.randint(1, 3)
            edits.append((pos, 0, txt))
            ln += len(txt)
        else:
            pos = rng.randint(0, ln - 1)
            nd = min(rng.randint(1, 3), ln - pos)
            edits.append((pos, nd, ""))
            ln -= nd
    for pos, nd, txt in edits:
        a.splice_text(ta, pos, nd, txt)
        b.splice_text(tb, pos, nd, txt)
    a.commit()
    b.commit()
    assert a.text(ta) == b.text(tb)
    assert_same_changes(a, b)


def test_mid_transaction_read_drains():
    a, ta, b, tb = two_docs()
    for d, t in ((a, ta), (b, tb)):
        d.splice_text(t, 0, 0, "hello")
        assert d.text(t) == "hello"  # read mid-tx drains the session
        d.splice_text(t, 5, 0, " world")
        d.commit()
    assert a.text(ta) == "hello world"
    assert_same_changes(a, b)


def test_mixed_ops_same_transaction():
    a, ta, b, tb = two_docs()
    for d, t in ((a, ta), (b, tb)):
        d.splice_text(t, 0, 0, "abc")
        d.put("_root", "k", 1)  # python op: forces drain
        d.splice_text(t, 2, 1, "XY")
        d.commit()
    assert a.text(ta) == "abXY"
    assert a.hydrate() == b.hydrate()
    assert_same_changes(a, b)


def test_length_fast_path_and_clamping():
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "abcdef")
    assert a.length(t) == 6  # served from the live session
    a.splice_text(t, 2, 2, "")
    assert a.length(t) == 4
    a.commit()
    assert a.text(t) == "abef"


def test_unicode_widths_utf16():
    from automerge_tpu.types import set_text_encoding

    set_text_encoding("utf16")
    try:
        a, ta, b, tb = two_docs()
        for d, t in ((a, ta), (b, tb)):
            d.splice_text(t, 0, 0, "a\U0001F600b")  # emoji width 2
            assert d.length(t) == 4
            d.splice_text(t, 1, 2, "X")  # deletes the emoji (width 2)
            d.commit()
        assert a.text(ta) == "aXb"
        assert_same_changes(a, b)
    finally:
        set_text_encoding("unicode")


def test_marked_object_falls_back():
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "hello world")
    a.mark(t, 0, 5, "bold", True)
    a.commit()
    # marked object: session ineligible, python path keeps mark semantics
    a.splice_text(t, 5, 0, "!")
    a.commit()
    assert a._tx is None
    assert a.marks(t) == [Mark(0, 6, "bold", True)]


def test_conflicted_element_falls_back():
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "lst", ObjType.TEXT)
    a.splice_text(t, 0, 0, "x")
    a.commit()
    f = a.fork(actor=actor(2))
    # concurrent puts at index 0 -> conflicted element (multiple winners)
    a.put(t, 0, "A")
    f.put(t, 0, "B")
    a.commit()
    f.commit()
    a.merge(f)
    assert len(a.get_all(t, 0)) == 2
    a.splice_text(t, 1, 0, "z")  # falls back (conflict) but must work
    a.commit()
    assert a.length(t) == 2


def test_rollback_discards_session_ops():
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "keep")
    a.commit()
    a.splice_text(t, 4, 0, " DISCARD")
    assert a.rollback() == 8
    assert a.text(t) == "keep"
    assert a.doc.max_op == 5  # make op + 4 chars


def test_batch_ingest_matches_per_edit():
    rng = random.Random(11)
    edits = []
    ln = 0
    for _ in range(500):
        if ln == 0 or rng.random() < 0.8:
            pos = rng.randint(0, ln + 2)  # may exceed: clamped
            edits.append([pos, 0, chr(rng.randint(97, 122))])
            ln += 1
        else:
            edits.append([rng.randint(0, ln), 2])  # may overrun: clamped
            ln = max(ln - 2, 0)
    a = AutoDoc(actor=actor(1))
    ta = a.put_object("_root", "t", ObjType.TEXT)
    from automerge_tpu import bench as W

    W.apply_edits(a, ta, edits)
    a.commit()
    b = AutoDoc(actor=actor(1))
    tb = b.put_object("_root", "t", ObjType.TEXT)
    b.splice_text_many(tb, edits)
    b.commit()
    assert a.text(ta) == b.text(tb)
    assert_same_changes(a, b)


def test_session_change_loads_and_merges():
    """Changes committed via the array-native path interop like any other:
    save/load roundtrip, head verification, merge into a python-path doc."""
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "the quick fox")
    a.splice_text(t, 4, 5, "slow")
    a.commit()
    data = a.save()
    b = AutoDoc.load(data)
    assert b.text(t) == "the slow fox"
    c = b.fork(actor=actor(3))
    c.splice_text(t, 0, 3, "one")
    c.commit()
    a.merge(c)
    assert a.text(t) == c.text(t)


def test_mixed_session_and_ineligible_object_ordering():
    """A python-path splice on an ineligible object while another object's
    session holds pending ops must not reorder implicit op ids (the change
    format derives ids from row position): the saved bytes must reload."""
    a = AutoDoc(actor=actor(1))
    ta = a.put_object("_root", "a", ObjType.TEXT)
    tb = a.put_object("_root", "b", ObjType.TEXT)
    a.splice_text(tb, 0, 0, "ze")
    a.mark(tb, 0, 1, "bold", True)  # marks make b session-ineligible
    a.commit()
    a.splice_text(ta, 0, 0, "hello")  # session on a
    a.splice_text(tb, 1, 0, "Q")      # python path on b
    a.splice_text(ta, 5, 0, "!")      # back to the session
    a.commit()
    assert a.text(ta) == "hello!"
    assert a.text(tb) == "zQe"
    b = AutoDoc.load(a.save())
    assert b.text(ta) == "hello!"
    assert b.text(tb) == "zQe"
    assert b.get_heads() == a.get_heads()


def test_batch_fallback_width_clamping_utf16():
    """splice_text_many's python fallback clamps in width units, matching
    the native path (astral chars are width 2 under utf16)."""
    from automerge_tpu.types import set_text_encoding

    set_text_encoding("utf16")
    try:
        edits = [
            (0, 0, "\U0001F389" * 3),
            (6, 0, "end"),
            (2, 4, ""),
            (5, 9, "tail"),
        ]
        a = AutoDoc(actor=actor(1))
        ta = a.put_object("_root", "t", ObjType.TEXT)
        na = a.splice_text_many(ta, edits)  # native session path
        a.commit()
        b = AutoDoc(actor=actor(1))
        tbx = b.put_object("_root", "t", ObjType.TEXT)
        tx = b._ensure_tx()
        tx.enable_sessions = False  # force the python fallback
        nb = b.splice_text_many(tbx, edits)
        b.commit()
        assert a.text(ta) == b.text(tbx)
        assert na == nb
        assert_same_changes(a, b)
    finally:
        set_text_encoding("unicode")


def test_session_survives_reads():
    """Reads drain pending ops but keep the session alive (watermark), so
    alternating splice/read editor loops stay on the native path."""
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    for i in range(20):
        a.splice_text(t, i, 0, "x")
        assert a.text(t) == "x" * (i + 1)  # read drains (keeps session)
    tx = a._tx
    assert tx is not None and len(tx._sessions) == 1  # still live
    ent = next(iter(tx._sessions.values()))
    assert ent[0].op_count() == 20 and ent[1] == 20  # all drained
    a.splice_text(t, 0, 5, "Y")
    a.commit()
    assert a.text(t) == "Y" + "x" * 15
    b = AutoDoc.load(a.save())
    assert b.text(t) == a.text(t)
