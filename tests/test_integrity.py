"""End-to-end integrity (integrity.py): digest determinism across
residency modes, merge orders and round trips; read-back verification;
scrubber detect-and-repair; chaos bit rot; quarantine plumbing; and the
reference client's ``IntegrityError`` surfacing.
"""

import json
import os
import random
import socket
import threading

import pytest

from automerge_tpu import integrity, obs
from automerge_tpu.api import AutoDoc
from automerge_tpu.rpc import RpcServer
from automerge_tpu.storage.durable import JOURNAL_NAME, SNAPSHOT_NAME
from automerge_tpu.types import ActorId, ObjType


def actor(i):
    return ActorId(bytes([i]) * 16)


def _ctr(name):
    """Total across label sets of one counter (0 when never counted)."""
    return sum(
        e["value"] for e in obs.snapshot()
        if e["type"] == "counter" and e["name"] == name
    )


def _flip_byte(path, frac=0.5):
    data = open(path, "rb").read()
    i = int(len(data) * frac) % max(1, len(data))
    bad = data[:i] + bytes([data[i] ^ 0x40]) + data[i + 1:]
    with open(path, "wb") as f:
        f.write(bad)
    return i


def _build_forks(seed, n_forks=4, edits=5):
    rng = random.Random(seed)
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "seed text")
    base.put("_root", "n", 0)
    base.commit()
    forks = []
    for i in range(n_forks):
        f = base.fork(actor=actor(10 + i))
        for e in range(edits):
            if rng.random() < 0.5:
                pos = rng.randrange(0, f.length(t) + 1)
                f.splice_text(t, pos, 0, f"w{i}.{e} ")
            else:
                f.put("_root", f"k{i}", e * 7 + i)
            f.commit()
        forks.append(f)
    return base, t, forks


# -- digest determinism property suite ----------------------------------------


@pytest.mark.parametrize("seed", [3, 11, 27])
def test_digest_invariant_across_merge_orders(seed):
    base, _t, forks = _build_forks(seed)
    rng = random.Random(seed + 1)
    digests = set()
    for _ in range(4):
        order = list(range(len(forks)))
        rng.shuffle(order)
        m = AutoDoc.load(base.save())
        for i in order:
            m.merge(forks[i])
        digests.add(integrity.doc_digest(m.doc)["digest"])
    assert len(digests) == 1, digests


@pytest.mark.parametrize("seed", [5, 19, 42])
def test_digest_invariant_under_out_of_order_delivery(seed):
    """Any causally-valid interleaving of per-fork change sequences
    (replication reordering across links) lands on the same digest."""
    base, _t, forks = _build_forks(seed)
    have = base.get_heads()
    per_fork = [list(f.get_changes(have)) for f in forks]
    rng = random.Random(seed * 13 + 1)
    digests = set()
    for _ in range(3):
        idx = [0] * len(per_fork)
        m = AutoDoc.load(base.save())
        while True:
            cand = [i for i in range(len(per_fork))
                    if idx[i] < len(per_fork[i])]
            if not cand:
                break
            i = rng.choice(cand)
            m.apply_changes([per_fork[i][idx[i]]])
            idx[i] += 1
        digests.add(integrity.doc_digest(m.doc)["digest"])
    assert len(digests) == 1, digests


def test_digest_invariant_across_residency_modes(monkeypatch):
    """Dense, compressed, and run-native residency hold the same
    history, so the digest must not move; the column-level oracle
    (decoded resident image == dense image) backs it up."""
    base, _t, forks = _build_forks(7)
    m = AutoDoc.load(base.save())
    for f in forks:
        m.merge(f)
    want = None
    for comp, rn in (("1", "1"), ("1", "0"), ("0", "0")):
        monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", comp)
        monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", rn)
        d = integrity.doc_digest(AutoDoc.load(m.save()).doc)
        if want is None:
            want = d
        assert d == want, (comp, rn, d, want)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    from automerge_tpu.ops.oplog import OpLog

    log = OpLog.from_documents([m])
    dense = integrity.column_digests(log, source="dense")
    resident = integrity.column_digests(log, source="resident")
    assert dense == resident


def test_digest_save_load_and_demote_hydrate_round_trips(tmp_path):
    srv = RpcServer(durable_dir=str(tmp_path / "docs"))
    try:
        h = srv.openDurable({"name": "rt"})["doc"]
        srv.put({"doc": h, "obj": "_root", "prop": "k", "value": 42})
        srv.commit({"doc": h})
        srv.put({"doc": h, "obj": "_root", "prop": "k2", "value": "x"})
        srv.commit({"doc": h})
        d1 = srv.docDigest({"name": "rt"})
        assert d1["changes"] == 2
        # handle addressing and name addressing agree
        assert srv.docDigest({"doc": h}) == d1
        # save/load round trip
        import base64

        loaded = AutoDoc.load(base64.b64decode(srv.save({"doc": h})))
        assert integrity.doc_digest(loaded.doc)["digest"] == d1["digest"]
        # demote to cold, digest by name hydrates and agrees
        srv.store.demote("rt", "cold")
        assert srv.docDigest({"name": "rt"}) == d1
    finally:
        srv.close_durables()


def test_durable_digest_incremental_matches_full(tmp_path):
    dd = AutoDoc.open(str(tmp_path / "d1"))
    base, _t, forks = _build_forks(9, n_forks=2, edits=3)
    dd.merge(base)
    for f in forks:
        dd.merge(f)
    got = dd.doc_digest()
    assert got == integrity.doc_digest(dd._core)
    dd.close()
    dd2 = AutoDoc.open(str(tmp_path / "d1"))
    assert dd2.doc_digest() == got  # recompute-on-open lands identically
    dd2.close()


def test_docdigest_unknown_name_is_an_error(tmp_path):
    srv = RpcServer(durable_dir=str(tmp_path / "docs"))
    try:
        resp = srv.handle({"id": 1, "method": "docDigest",
                           "params": {"name": "ghost"}})
        assert "error" in resp
    finally:
        srv.close_durables()


# -- read-back verification ----------------------------------------------------


def test_verify_doc_dir_clean_and_first_bad_offset(tmp_path):
    dd = AutoDoc.open(str(tmp_path / "v"))
    dd.put("_root", "k", "v" * 200)
    dd.commit()
    dd.compact()
    dd.close()
    path = str(tmp_path / "v")
    reports = integrity.verify_doc_dir(path)
    assert len(reports) == 2 and all(r.ok for r in reports), reports
    # snapshot bit flip: strict chunk walk reports the damaged frame
    _flip_byte(os.path.join(path, SNAPSHOT_NAME))
    bad = [r for r in integrity.verify_doc_dir(path) if not r.ok]
    assert [r.kind for r in bad] == ["snapshot"]
    assert bad[0].first_bad_offset is not None


def test_verify_journal_detects_mid_file_rot(tmp_path):
    dd = AutoDoc.open(str(tmp_path / "j"))
    for i in range(6):
        dd.put("_root", f"k{i}", "payload-%03d" % i)
        dd.commit()
    dd.close()
    jpath = os.path.join(str(tmp_path / "j"), JOURNAL_NAME)
    r = integrity.verify_journal_bytes(open(jpath, "rb").read())
    assert r.ok and r.units >= 6
    _flip_byte(jpath, frac=0.6)
    r = integrity.verify_journal_bytes(open(jpath, "rb").read())
    assert not r.ok and r.valid_bytes < r.total_bytes
    assert r.first_bad_offset == r.valid_bytes


# -- device-mirror audit --------------------------------------------------------


def test_compressed_verify_against_catches_divergence(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    from automerge_tpu.ops.oplog import OpLog

    base, _t, forks = _build_forks(21, n_forks=2)
    m = AutoDoc.load(base.save())
    for f in forks:
        m.merge(f)
    log = OpLog.from_documents([m])
    comp = log.compressed(sync=True)
    assert comp is not None
    assert comp.verify_against(log) == []
    # silently corrupt one dense oracle cell covered by a run entry: the
    # audit must name the diverged column
    import numpy as np

    for name in ("action", "succ_count", "obj_actor"):
        arr = getattr(log, name, None)
        ent = comp.entries.get(name)
        cov = comp.covered.get(name, 0)
        if arr is not None and ent is not None and cov > 0:
            arr = np.asarray(arr)
            old = arr[0]
            arr[0] = old + 1
            try:
                assert name in comp.verify_against(log)
            finally:
                arr[0] = old
            assert comp.verify_against(log) == []
            return
    pytest.skip("no run-coded column to tamper with")


# -- the scrubber ---------------------------------------------------------------


def test_scrubber_repairs_live_doc_bit_rot_with_zero_loss(tmp_path):
    srv = RpcServer(durable_dir=str(tmp_path / "docs"))
    try:
        h = srv.openDurable({"name": "live"})["doc"]
        for i in range(5):
            srv.put({"doc": h, "obj": "_root", "prop": f"k{i}", "value": i})
            srv.commit({"doc": h})
        digest_before = srv.docDigest({"name": "live"})
        path = srv._durable_path("live")
        corrupt0 = _ctr("journal.scrub_corrupt")
        repaired0 = _ctr("journal.scrub_repaired")
        _flip_byte(os.path.join(path, JOURNAL_NAME), frac=0.5)
        summary = srv.scrubNow({})
        assert summary["corrupt"] >= 1 and summary["repaired"] >= 1, summary
        assert _ctr("journal.scrub_corrupt") > corrupt0
        assert _ctr("journal.scrub_repaired") > repaired0
        # zero acked-write loss: in-memory history repaired the disk
        assert all(r.ok for r in integrity.verify_doc_dir(path))
        assert srv.docDigest({"name": "live"}) == digest_before
        for i in range(5):
            assert srv.get(
                {"doc": h, "obj": "_root", "prop": f"k{i}"}) == i
        # a second round finds nothing
        clean0 = _ctr("journal.scrub_clean")
        summary = srv.scrubNow({})
        assert summary["corrupt"] == 0
        assert _ctr("journal.scrub_clean") > clean0
    finally:
        srv.close_durables()


def test_scrubber_detects_cold_doc_rot_and_salvages(tmp_path):
    srv = RpcServer(durable_dir=str(tmp_path / "docs"))
    try:
        h = srv.openDurable({"name": "cold"})["doc"]
        srv.put({"doc": h, "obj": "_root", "prop": "k", "value": "vv"})
        srv.commit({"doc": h})
        srv.store.demote("cold", "cold")
        path = srv._durable_path("cold")
        _flip_byte(os.path.join(path, JOURNAL_NAME), frac=0.7)
        corrupt0 = _ctr("journal.scrub_corrupt")
        summary = srv.scrubNow({})
        assert summary["corrupt"] >= 1, summary
        assert _ctr("journal.scrub_corrupt") > corrupt0
        # unreplicated deployment: salvage is the last resort, and the
        # rewritten files verify clean afterwards
        assert _ctr("journal.scrub_repaired") >= 1
        assert all(r.ok for r in integrity.verify_doc_dir(path))
    finally:
        srv.close_durables()


def test_scrubber_chaos_bitflip_detected_without_disk_damage(
        tmp_path, monkeypatch):
    """FaultyFS BITFLIP corrupts the bytes the scrub READS (the disk
    stays clean): detection fires, and the repair path re-verifies clean
    once the armed fault is spent."""
    monkeypatch.setenv("AUTOMERGE_TPU_CHAOS", "1")
    srv = RpcServer(durable_dir=str(tmp_path / "docs"))
    try:
        h = srv.openDurable({"name": "bf"})["doc"]
        srv.put({"doc": h, "obj": "_root", "prop": "k", "value": 1})
        srv.commit({"doc": h})
        srv.chaosDisk({"name": "bf", "op": "read", "err": "BITFLIP",
                       "count": 1})
        flips0 = obs.counter_values(
            "chaos.injected", "kind").get("disk_read_flip", 0)
        summary = srv.scrubNow({})
        assert summary["corrupt"] >= 1, summary
        assert obs.counter_values("chaos.injected", "kind").get(
            "disk_read_flip", 0) > flips0
        summary = srv.scrubNow({})
        assert summary["corrupt"] == 0, summary
    finally:
        srv.close_durables()


def test_faultyfs_read_bitflip_semantics(tmp_path):
    from automerge_tpu.storage.crashsim import FaultyFS

    p = str(tmp_path / "blob")
    with open(p, "wb") as f:
        f.write(b"A" * 64)
    fs = FaultyFS()
    fs.arm("read", "BITFLIP", count=1)
    flipped = fs.read_bytes(p)
    assert flipped != b"A" * 64
    assert len(flipped) == 64
    assert sum(a != b for a, b in zip(flipped, b"A" * 64)) == 1
    assert fs.read_bytes(p) == b"A" * 64  # armed count spent
    with pytest.raises(ValueError):
        fs.arm("write", "BITFLIP")  # only reads can rot silently
    fs.arm("read", "EIO", count=1)
    with pytest.raises(OSError):
        fs.read_bytes(p)


# -- quarantine plumbing --------------------------------------------------------


def test_hub_quarantine_revokes_the_vote():
    from automerge_tpu.cluster.replication import ReplicationHub

    hub = ReplicationHub("n1", ack_replicas=1)

    class _Link:
        quarantined = False
        durable_lsn = {}

        def stop(self):
            pass

    a, b = _Link(), _Link()
    hub._links["h:1"] = a
    hub._links["h:2"] = b
    assert sorted(hub.follower_addrs()) == ["h:1", "h:2"]
    assert hub.quarantine("h:1") is True
    assert hub.follower_addrs() == ["h:2"]
    assert hub.quarantined_addrs() == ["h:1"]
    assert a.quarantined and not b.quarantined
    assert hub.quarantine("nope") is False
    # gauge reflects the quarantined count
    assert any(
        e["name"] == "cluster.quarantined" and e["value"] == 1
        for e in obs.snapshot()
    )
    hub.close()


# -- gauge hygiene --------------------------------------------------------------


def test_digest_gauge_removed_on_close_and_demotion(tmp_path):
    srv = RpcServer(durable_dir=str(tmp_path / "docs"))

    def gauge(name):
        for e in obs.snapshot():
            if (e["name"] == "doc.digest_changes"
                    and e["labels"].get("doc") == name):
                return e["value"]
        return None

    try:
        h = srv.openDurable({"name": "g1"})["doc"]
        srv.put({"doc": h, "obj": "_root", "prop": "k", "value": 1})
        srv.commit({"doc": h})
        assert gauge("g1") == 1
        srv.store.demote("g1", "cold")
        assert gauge("g1") is None  # cold demotion removed the gauge
        h2 = srv.openDurable({"name": "g2"})["doc"]
        srv.put({"doc": h2, "obj": "_root", "prop": "k", "value": 1})
        srv.commit({"doc": h2})
        assert gauge("g2") == 1
        srv.free({"doc": h2})
        assert gauge("g2") is None  # close removed the gauge
    finally:
        srv.close_durables()


# -- cli: journal-info --verify ------------------------------------------------


def test_cli_journal_info_verify_clean_and_corrupt(tmp_path, capsys):
    from automerge_tpu.cli import main

    d = str(tmp_path / "vd")
    dd = AutoDoc.open(d)
    for i in range(4):
        dd.put("_root", f"k{i}", "x" * 50)
        dd.commit()
    dd.compact()
    dd.put("_root", "tail", 1)
    dd.commit()
    dd.close()
    out = tmp_path / "info.json"
    assert main(["journal-info", d, "--verify", "-o", str(out)]) == 0
    info = json.loads(out.read_text())
    assert {v["kind"] for v in info["verify"]} == {"snapshot", "journal"}
    assert all(v["ok"] for v in info["verify"])
    # a flipped snapshot byte: exit 1, damaged kind + first bad offset
    _flip_byte(os.path.join(d, SNAPSHOT_NAME))
    assert main(["journal-info", d, "--verify", "-o", str(out)]) == 1
    info = json.loads(out.read_text())
    bad = [v for v in info["verify"] if not v["ok"]]
    assert bad and bad[0]["kind"] == "snapshot", info["verify"]
    assert bad[0]["first_bad_offset"] is not None
    assert "corrupt at byte" in capsys.readouterr().err
    # inspection never repairs, and without --verify the deep scan (a
    # full read-back of every byte) stays off
    assert main(["journal-info", d, "-o", str(out)]) == 0
    assert "verify" not in json.loads(out.read_text())


# -- reference client: IntegrityError ------------------------------------------


def _client_mod():
    import importlib.util
    import pathlib

    path = (pathlib.Path(__file__).parent.parent / "clients" / "python"
            / "amtpu_client.py")
    spec = importlib.util.spec_from_file_location("amtpu_client", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_client_surfaces_integrity_error_without_retry():
    """An IntegrityError is never retried (re-reading damaged bytes
    cannot help) and arrives as its own exception type — even when a
    buggy server marks it retriable."""
    amtpu = _client_mod()
    ls = socket.socket()
    ls.bind(("127.0.0.1", 0))
    ls.listen(8)

    def serve():
        for _ in range(2):
            c, _ = ls.accept()
            f = c.makefile("r")
            req = json.loads(f.readline())
            c.sendall((json.dumps({"id": req["id"], "error": {
                "type": "IntegrityError",
                "message": "digest mismatch",
                # deliberately wrong flag on the second round: the type
                # check must win over the retriable hint
                "retriable": bool(req["params"].get("lie")),
            }}) + "\n").encode())
            c.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    addr = "127.0.0.1:%d" % ls.getsockname()[1]
    c = amtpu.RetryingClient(addr, deadline_s=5, backoff_s=0.01)
    with pytest.raises(amtpu.IntegrityError) as ei:
        c.call("docDigest", name="x")
    assert ei.value.retriable is False
    assert isinstance(ei.value, amtpu.RpcError)
    assert c.last.attempts == 1
    c.close()
    c = amtpu.RetryingClient(addr, deadline_s=5, backoff_s=0.01)
    with pytest.raises(amtpu.IntegrityError):
        c.call("docDigest", name="x", lie=True)
    assert c.last.attempts == 1
    c.close()
    ls.close()
