"""Dot dumps of document structure (the analogue of the reference's
optree-visualisation feature, visualisation.rs / op_set.rs:265-285)."""

from automerge_tpu.api import AutoDoc
from automerge_tpu.types import ActorId, ObjType
from automerge_tpu.visualisation import changes_to_dot, doc_to_dot


def test_doc_to_dot_renders_objects_and_tombstones():
    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hi")
    d.splice_text(t, 0, 1, "")  # tombstone
    d.put("_root", "k", 1)
    d.commit()
    dot = doc_to_dot(d)
    assert dot.startswith("digraph automerge")
    assert "tombstone" in dot
    assert "'i'" in dot and "k = int 1" in dot
    assert dot.count("subgraph") == 2  # root + text


def test_changes_to_dot_renders_dag():
    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    d.put("_root", "a", 1)
    d.commit()
    f = d.fork(actor=ActorId(bytes([2]) * 16))
    d.put("_root", "b", 2)
    d.commit()
    f.put("_root", "c", 3)
    f.commit()
    d.merge(f)
    dot = changes_to_dot(d)
    assert dot.startswith("digraph changes")
    # 3 changes, 2 dep edges, 2 heads highlighted
    assert dot.count("seq") == 3
    assert dot.count("->") == 2
    assert dot.count("palegreen") == 2
