"""Differential suite for run-native resolution kernels (ops/merge.py)
and the double-buffered drain pipeline (ops/batched.py).

The fully dense path (``AUTOMERGE_TPU_COMPRESSED=0``) is the oracle:
the same multi-seed workloads resolved through run-native kernels (run
tables as the kernel's input, expansion gathers fused in-jit) and
through the eager-expansion staging (``AUTOMERGE_TPU_RUN_NATIVE=0``)
must leave every document bit-identical — column-level OpLog equality,
full DeviceDoc state, identical ``at(heads)`` views — across the
stage_docs + packed-launch path, the per-doc async dispatch, the
pipelined (double-buffered) drain with out-of-order/duplicate delivery,
and ratio-gate-demoted mixed encodings. Plus staging-level properties:
the run-table expansion decodes exactly, degenerate columns demote
dense through ``compressed.run_gate`` with per-column fallback
counters, and kernel input bytes genuinely undercut the dense image.
"""

import numpy as np
import pytest

from automerge_tpu import obs
from automerge_tpu.ops import host_batch, merge
from automerge_tpu.ops.batched import apply_cross_doc, resolve_stages
from automerge_tpu.ops.device_doc import DeviceDoc
from automerge_tpu.ops.oplog import OpLog

from .test_host_batch import assert_identical, build_workload


def _drive_staged(docs, deltas, cycles):
    """The stage_docs + shared packed launch path (the serve drain)."""
    devs = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    for c in range(cycles):
        stages, results = host_batch.stage_docs(
            [(devs[i], [deltas[i][c]]) for i in range(len(docs))]
        )
        for r in results.values():
            assert r.error is None, repr(r.error)
        if stages:
            resolve_stages(stages)
    return devs


def _drive_pipelined(docs, deltas, cycles, step):
    """The double-buffered drain: chunked apply_cross_doc with chunk
    N+1's host staging under chunk N's in-flight packed kernel."""
    devs = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    for c in range(cycles):
        apply_cross_doc(
            [(devs[i], [deltas[i][c]]) for i in range(len(docs))],
            max_docs_per_launch=step,
            pipeline=True,
        )
    return devs


def _check_same(got, oracle, docs):
    for i in range(len(docs)):
        assert_identical(got[i], oracle[i], i)
        heads = got[i].current_heads()
        assert got[i].at(heads).hydrate() == oracle[i].at(heads).hydrate()
        assert got[i].at([]).hydrate() == oracle[i].at([]).hydrate()


# -- end-to-end differential: run-native vs eager-expand vs dense ------------


@pytest.mark.parametrize("seed", [2, 17, 40])
def test_differential_staged_launches(monkeypatch, seed):
    docs, deltas = build_workload(seed, n_docs=4, cycles=4)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", "1")
    rn0 = obs.counter_values("device.kernel_launches", "path").get(
        "run_native", 0)
    native = _drive_staged(docs, deltas, 4)
    rn1 = obs.counter_values("device.kernel_launches", "path").get(
        "run_native", 0)
    monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", "0")
    eager = _drive_staged(docs, deltas, 4)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    dense = _drive_staged(docs, deltas, 4)
    _check_same(native, dense, docs)
    _check_same(eager, dense, docs)
    # non-vacuous: the run-native dispatch path actually launched
    assert rn1 > rn0


@pytest.mark.parametrize("seed", [7, 29])
def test_differential_per_doc_async_dispatch(monkeypatch, seed):
    # the per-doc apply_batches path (DeviceDoc._dispatch_async →
    # prepare_resolution), including its in-flight double buffering
    docs, deltas = build_workload(seed, n_docs=2, cycles=4, dup=True)

    def run():
        devs = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
        for i, dv in enumerate(devs):
            dv.apply_batches([deltas[i][c] for c in range(4)])
        return devs

    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", "1")
    native = run()
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    dense = run()
    _check_same(native, dense, docs)


@pytest.mark.parametrize("seed", [5, 33])
def test_differential_pipelined_drain(monkeypatch, seed):
    # out-of-order + duplicate delivery through the double-buffered
    # chunked drain (2-doc chunks → dispatch/stage/collect interleave)
    docs, deltas = build_workload(seed, n_docs=5, cycles=4, dup=True,
                                  shuffle=True)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", "1")
    piped = _drive_pipelined(docs, deltas, 4, step=2)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    dense = _drive_pipelined(docs, deltas, 4, step=2)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    serial = _drive_staged(docs, deltas, 4)
    _check_same(piped, dense, docs)
    _check_same(serial, dense, docs)


def test_differential_gate_demoted_mixed_encodings(monkeypatch):
    # high-entropy edits (many tiny objects, scattered splice points)
    # drive some columns past the run gate: a MIX of run-native stacks
    # and dense-demoted columns in one launch must still match the
    # oracle, and the demotions must be counted per column
    docs, deltas = build_workload(13, n_docs=3, cycles=4, shuffle=True)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", "1")
    fb0 = sum(obs.counter_values(
        "device.run_native_fallback", "reason").values())
    native = _drive_staged(docs, deltas, 4)
    fb1 = sum(obs.counter_values(
        "device.run_native_fallback", "reason").values())
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    dense = _drive_staged(docs, deltas, 4)
    _check_same(native, dense, docs)
    assert fb1 > fb0  # some column really did demote dense


# -- staging-level properties -------------------------------------------------


def _expand_plan(dense, stacks, plan, to_np=np.asarray):
    """Host-side oracle for the in-jit expansion: w[j] (+ s*i)."""
    out = {k: to_np(v) for k, v in dense.items()}
    for (n, rcap, cls, names, bools), arrs in zip(plan, stacks):
        i = np.arange(n)
        for idx, name in enumerate(names):
            w = to_np(arrs[0][idx])
            cum = to_np(arrs[1][idx])
            j = np.clip(np.searchsorted(cum, i, side="right"), 0, rcap - 1)
            col = w[j]
            if cls == "delta":
                col = col + int(to_np(arrs[2][idx])) * i
            out[name] = col.astype(bool) if bools[idx] else col
    return out


def test_staging_expansion_decodes_exactly(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    rng = np.random.default_rng(3)
    n = 256
    cols = {
        "action": np.zeros(n, np.int32),                       # 1 run
        "obj": np.repeat(np.arange(8, dtype=np.int32), 32),    # RLE
        "elem_ref": (np.arange(n) - 1).astype(np.int32),       # delta
        "insert": np.ones(n, bool),                            # bool RLE
        "noise": rng.integers(0, 1 << 20, n).astype(np.int32),  # dense
    }
    dense, stacks, plan = merge.stage_cols_run_native(cols)
    assert plan, "nothing run-encoded"
    assert "noise" in dense  # past the gate → shipped dense
    got = _expand_plan(dense, stacks, plan)
    for k, v in cols.items():
        assert np.array_equal(got[k], v), k
    # input bytes genuinely undercut the dense image for this shape
    run_bytes = sum(
        a.nbytes for arrs in stacks for a in arrs
    ) + sum(v.nbytes for v in dense.values())
    assert run_bytes * 2 < sum(
        np.asarray(v).nbytes for v in cols.values())


def test_degenerate_columns_demote_with_reasons(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    rng = np.random.default_rng(9)
    n = 128
    cols = {
        "action": rng.integers(0, 1 << 20, n).astype(np.int32),  # ratio
        "wide": np.arange(n, dtype=np.int64),                    # dtype
    }
    def fallbacks():
        # exact (column, reason) series — counter_values collapses
        # multi-label families last-wins, so read the snapshot
        return {
            (e["labels"].get("column"), e["labels"].get("reason")):
                e["value"]
            for e in obs.snapshot()
            if e["type"] == "counter"
            and e["name"] == "device.run_native_fallback"
        }

    before = fallbacks()
    dense, stacks, plan = merge.stage_cols_run_native(cols)
    after = fallbacks()
    assert not plan and set(dense) == {"action", "wide"}
    assert after.get(("action", "ratio"), 0) == \
        before.get(("action", "ratio"), 0) + 1
    assert after.get(("wide", "dtype"), 0) == \
        before.get(("wide", "dtype"), 0) + 1
    # short columns never run-encode (run table would not pay for itself)
    _, _, plan2 = merge.stage_cols_run_native(
        {"action": np.zeros(8, np.int32)})
    assert not plan2


def test_run_native_disabled_restores_eager_staging(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", "0")
    assert not merge.run_native_enabled()
    monkeypatch.setenv("AUTOMERGE_TPU_RUN_NATIVE", "1")
    assert merge.run_native_enabled()
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    assert not merge.run_native_enabled()  # dense oracle wins outright
