"""Chaos fabric: the TCP fault proxy, live disk-fault injection, the
degraded-document semantics, and the cluster paths the faults force.

Three layers: proxy units over a local echo server (passthrough,
asymmetric partition, sever/heal, seeded determinism), disk-fault
semantics on a durable doc (ENOSPC append, fsync EIO poison, compact
revive, reopen), and in-process leader/follower pairs with the fault
proxy on the replication link (ack-gate timeout under partition,
retention-overflow snapshot catch-up). The full multi-process soak
lives in scripts/ci/run_chaos.
"""

import json
import os
import socket
import threading
import time

import pytest

from automerge_tpu import obs
from automerge_tpu.api import AutoDoc
from automerge_tpu.cluster import ChaosProxy, ChaosSchedule, ClusterNode
from automerge_tpu.rpc import RpcServer
from automerge_tpu.storage.crashsim import FaultyFS
from automerge_tpu.storage.journal import JournalPoisoned
from automerge_tpu.types import ActorId


def actor(i):
    return ActorId(bytes([i]) * 16)


def wait_until(pred, timeout=10.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# -- proxy units --------------------------------------------------------------


class EchoServer:
    """A line-echo TCP server for proxy tests."""

    def __init__(self):
        self.ls = socket.socket()
        self.ls.bind(("127.0.0.1", 0))
        self.ls.listen(16)
        self.received = []
        threading.Thread(target=self._accept, daemon=True).start()

    @property
    def target(self):
        return "127.0.0.1:%d" % self.ls.getsockname()[1]

    def _accept(self):
        while True:
            try:
                c, _ = self.ls.accept()
            except OSError:
                return
            threading.Thread(target=self._pump, args=(c,),
                             daemon=True).start()

    def _pump(self, c):
        while True:
            try:
                d = c.recv(4096)
            except OSError:
                return
            if not d:
                return
            self.received.append(d)
            try:
                c.sendall(d)
            except OSError:
                return

    def close(self):
        self.ls.close()


@pytest.fixture
def echo():
    srv = EchoServer()
    yield srv
    srv.close()


def _connect(proxy):
    host, _, port = proxy.address.rpartition(":")
    s = socket.create_connection((host, int(port)), timeout=5)
    s.settimeout(5)
    return s


def test_proxy_transparent_passthrough(echo):
    p = ChaosProxy(echo.target, seed=1).start()
    try:
        s = _connect(p)
        s.sendall(b"hello proxy\n")
        assert s.recv(100) == b"hello proxy\n"
        s.close()
    finally:
        p.stop()


def test_proxy_asymmetric_partition_and_heal(echo):
    """Black-holing one direction swallows bytes without resetting the
    connection — the far side sees silence. The other direction still
    flows, and heal() restores both."""
    p = ChaosProxy(echo.target, seed=2).start()
    try:
        s = _connect(p)
        s.sendall(b"before\n")
        assert s.recv(100) == b"before\n"
        # server->client black-holed: the request ARRIVES (the server
        # echoes into the void), the response never returns
        p.partition("s2c")
        n_seen = len(echo.received)
        s.sendall(b"void\n")
        wait_until(lambda: len(echo.received) > n_seen,
                   msg="request delivery through partition")
        s.settimeout(0.3)
        with pytest.raises(socket.timeout):
            s.recv(100)
        p.heal()
        s.settimeout(5)
        s.sendall(b"after\n")
        assert s.recv(100) == b"after\n"
        kinds = obs.counter_values("chaos.injected", "kind")
        assert kinds.get("blackhole_s2c", 0) >= 1
        assert kinds.get("partition_s2c", 0) >= 1
        s.close()
    finally:
        p.stop()


def test_proxy_sever_cuts_and_refuses_until_heal(echo):
    p = ChaosProxy(echo.target, seed=3).start()
    try:
        s = _connect(p)
        s.sendall(b"x\n")
        assert s.recv(100) == b"x\n"
        p.sever()
        # the live connection resets (possibly after one send); a fresh
        # one is refused (accepted then immediately closed)
        with pytest.raises(OSError):
            for _ in range(20):
                s.sendall(b"y\n")
                if s.recv(100) == b"":
                    raise OSError("peer closed")
                time.sleep(0.05)
        s2 = _connect(p)
        s2.settimeout(1)
        assert s2.recv(10) == b""
        s2.close()
        p.heal()
        s3 = _connect(p)
        s3.sendall(b"z\n")
        assert s3.recv(100) == b"z\n"
        s3.close()
        wait_until(lambda: p.live_connections() == 1,
                   msg="severed conns reaped")
    finally:
        p.stop()
        wait_until(lambda: p.live_connections() == 0,
                   msg="no leaked proxied connections")


def test_proxy_seeded_faults_are_deterministic(echo):
    """Two proxies with the same seed drop the same chunks — the replay
    property CHAOS_SEED relies on."""

    def run(seed):
        p = ChaosProxy(echo.target, seed=seed).start()
        p.set_policy("c2s", drop=0.5)
        got = []
        try:
            s = _connect(p)
            for i in range(20):
                n0 = len(echo.received)
                s.sendall(b"m%02d\n" % i)
                time.sleep(0.03)
                got.append(len(echo.received) > n0)
            s.close()
        finally:
            p.stop()
        return got

    a = run(1234)
    b = run(1234)
    c = run(4321)
    assert a == b
    assert True in a and False in a  # both outcomes actually occurred
    assert c != a  # and the seed matters


def test_chaos_schedule_runs_in_order_and_records_errors():
    ran = []
    sched = ChaosSchedule()
    sched.at(0.05, "b", lambda: ran.append("b"))
    sched.at(0.0, "a", lambda: ran.append("a"))
    sched.at(0.1, "boom", lambda: 1 / 0)
    assert sched.plan() == [(0.0, "a"), (0.05, "b"), (0.1, "boom")]
    sched.start()
    assert sched.join(timeout=5)
    assert ran == ["a", "b"]
    assert sched.executed == [(0.0, "a"), (0.05, "b"), (0.1, "boom")]
    assert sched.errors and sched.errors[0][0] == "boom"


# -- live disk faults on a durable document -----------------------------------


def test_enospc_append_degrades_then_compact_recovers(tmp_path):
    fs = FaultyFS()
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fs=fs, fsync="always", actor=actor(1))
    dd.put("_root", "a", 1)
    dd.commit()

    fs.arm("write", "ENOSPC")
    dd.put("_root", "b", 2)
    with pytest.raises(OSError):
        dd.commit()
    assert dd.degraded and not dd.journal.poisoned  # broken, journal live
    # every further mutation refuses with the retriable error, BEFORE
    # touching the disk (no silently stranded dependents)
    dd.put("_root", "c", 3)
    with pytest.raises(JournalPoisoned) as ei:
        dd.commit()
    assert ei.value.retriable is True

    fs.clear()
    assert dd.compact() is True  # fresh snapshot re-establishes disk>=memory
    assert not dd.degraded
    dd.put("_root", "d", 4)
    dd.commit()
    dd.close()
    dd2 = AutoDoc.open(d)
    assert dd2.hydrate()["a"] == 1 and dd2.hydrate()["d"] == 4
    dd2.close()


def test_fsync_eio_poisons_and_reopen_replays_acked_prefix(tmp_path):
    obs.reset_all()
    fs = FaultyFS()
    d = str(tmp_path / "doc")
    dd = AutoDoc.open(d, fs=fs, fsync="always", actor=actor(1))
    for i in range(4):
        dd.put("_root", f"k{i}", i)
        dd.commit()

    fs.arm("fsync", "EIO", count=1)
    dd.put("_root", "doomed", 1)
    with pytest.raises(OSError):
        dd.commit()
    # poisoned: no retry-after-fsync-failure — the journal closed itself
    assert dd.journal.poisoned and dd.journal.poisoned_reason == "fsync"
    assert obs.counter_values("journal.poisoned", "reason") == {"fsync": 1}
    assert obs.counter_values("chaos.injected", "kind") == {"disk_fsync": 1}
    with pytest.raises(JournalPoisoned):
        dd.put("_root", "more", 1)
        dd.commit()
    # reads on the degraded doc still serve
    assert dd.hydrate()["k3"] == 3
    dd.close()

    # the fault is cleared (count=1 consumed): a reopen recovers, and
    # every write acked BEFORE the fault is present
    dd2 = AutoDoc.open(d, actor=actor(2))
    got = dd2.hydrate()
    for i in range(4):
        assert got[f"k{i}"] == i
    dd2.put("_root", "recovered", 1)
    dd2.commit()
    dd2.close()


def test_poisoned_journal_revive_keeps_flock_accounting(tmp_path):
    """Poison then compact-revive: the flocks_held gauge returns to its
    pre-fault level (the chaos soak's leak invariant) and the journal
    accepts appends again."""
    fs = FaultyFS()
    g = obs.registry.gauge("serve.flocks_held")
    base = g.value
    dd = AutoDoc.open(str(tmp_path / "doc"), fs=fs, fsync="always",
                      actor=actor(1))
    assert g.value == base + 1
    fs.arm("fsync", "EIO", count=1)
    dd.put("_root", "x", 1)
    with pytest.raises(OSError):
        dd.commit()
    assert g.value == base  # poison released the handle + flock
    assert dd.compact() is True
    assert g.value == base + 1  # revive re-acquired them
    dd.put("_root", "y", 2)
    dd.commit()
    dd.close()
    assert g.value == base


# -- the RPC surface ----------------------------------------------------------


def test_rpc_chaos_disk_degraded_retriable_and_reopen(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_CHAOS", "1")
    rpc = RpcServer(durable_dir=str(tmp_path))
    h = rpc.handle({"id": 1, "method": "openDurable",
                    "params": {"name": "doc1"}})["result"]["doc"]
    rpc.handle({"id": 2, "method": "put", "params": {
        "doc": h, "obj": "_root", "prop": "a", "value": 1}})
    assert "error" not in rpc.handle(
        {"id": 3, "method": "commit", "params": {"doc": h}})

    r = rpc.handle({"id": 4, "method": "chaosDisk", "params": {
        "name": "doc1", "op": "fsync", "err": "EIO", "count": 1}})
    assert r["result"]["armed"] == {"fsync": ["EIO", 1]}

    rpc.handle({"id": 5, "method": "put", "params": {
        "doc": h, "obj": "_root", "prop": "b", "value": 2}})
    r = rpc.handle({"id": 6, "method": "commit", "params": {"doc": h}})
    assert r["error"]["type"] == "OSError", r
    # degraded mode is visible, and further writes carry retriable: true
    info = rpc.handle({"id": 7, "method": "durableInfo",
                       "params": {"doc": h}})["result"]
    assert info["degraded"] is True and info["poisoned"] == "fsync"
    rpc.handle({"id": 8, "method": "put", "params": {
        "doc": h, "obj": "_root", "prop": "c", "value": 3}})
    r = rpc.handle({"id": 9, "method": "commit", "params": {"doc": h}})
    assert r["error"]["type"] == "JournalPoisoned"
    assert r["error"]["retriable"] is True
    # reads still answer on the degraded doc
    assert rpc.handle({"id": 10, "method": "get", "params": {
        "doc": h, "obj": "_root", "prop": "a"}})["result"] == 1

    # durableReopen recovers IN PLACE: the handle stays valid
    r = rpc.handle({"id": 11, "method": "durableReopen",
                    "params": {"name": "doc1"}})["result"]
    assert r["doc"] == h and r["reopened"] is True
    rpc.handle({"id": 12, "method": "put", "params": {
        "doc": h, "obj": "_root", "prop": "d", "value": 4}})
    assert "error" not in rpc.handle(
        {"id": 13, "method": "commit", "params": {"doc": h}})
    info = rpc.handle({"id": 14, "method": "durableInfo",
                       "params": {"doc": h}})["result"]
    assert info["degraded"] is False and info["poisoned"] is None
    rpc.close_durables()


def test_rpc_chaos_disk_requires_env(tmp_path):
    rpc = RpcServer(durable_dir=str(tmp_path))
    assert not rpc.chaos_enabled
    rpc.handle({"id": 1, "method": "openDurable",
                "params": {"name": "doc1"}})
    r = rpc.handle({"id": 2, "method": "chaosDisk", "params": {
        "name": "doc1", "op": "fsync"}})
    assert "error" in r and "AUTOMERGE_TPU_CHAOS" in r["error"]["message"]
    rpc.close_durables()


# -- cluster under chaos (in-process) -----------------------------------------


class Client:
    """Minimal JSON-RPC socket client (same idiom as test_cluster.py)."""

    def __init__(self, address):
        self.sock = socket.create_connection(address)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.f = self.sock.makefile("r")
        self.rid = 0

    def call(self, method, allow_error=False, **params):
        self.rid += 1
        self.sock.sendall((json.dumps(
            {"id": self.rid, "method": method, "params": params}
        ) + "\n").encode())
        resp = json.loads(self.f.readline())
        if not allow_error:
            assert "error" not in resp, resp
        return resp if "error" in resp else resp.get("result")

    def close(self):
        self.sock.close()


def start_node(tmp, name, **kw):
    d = os.path.join(str(tmp), name)
    node = ClusterNode(
        node_id=name, host="127.0.0.1", port=0, durable_dir=d, **kw
    )
    node.start()
    return node


def test_ack_gate_errors_not_deadlocks_under_asymmetric_partition(
        tmp_path, monkeypatch):
    """The replication link black-holed in the response direction: the
    quorum gate must time out into a RETRIABLE error (never hang, never
    ack), and healing the link resumes acks and convergence."""
    monkeypatch.setenv("AUTOMERGE_TPU_CLUSTER_ACK_TIMEOUT", "0.6")
    monkeypatch.setenv("AUTOMERGE_TPU_REPL_IO_TIMEOUT", "0.5")
    fol = start_node(tmp_path, "f1", role="follower")
    proxy = ChaosProxy("%s:%d" % fol.address, seed=5).start()
    led = start_node(tmp_path, "l1", role="leader",
                     replicate_to=[proxy.address], ack_replicas=1)
    try:
        c = Client(led.address)
        d = c.call("openDurable", name="docA")["doc"]
        c.call("put", doc=d, obj="_root", prop="k0", value=0)
        c.call("commit", doc=d)  # healthy quorum ack through the proxy

        proxy.partition("s2c")
        t0 = time.monotonic()
        c.call("put", doc=d, obj="_root", prop="k1", value=1)
        r = c.call("commit", doc=d, allow_error=True)
        dt = time.monotonic() - t0
        assert "error" in r, r
        assert "ReplicationTimeout" in r["error"]["type"], r
        assert r["error"]["retriable"] is True, r
        assert dt < 10, f"gate hung for {dt}s"

        proxy.heal()
        # the link self-heals and the pending write replicates; retrying
        # the commit eventually acks
        deadline = time.monotonic() + 20
        while True:
            r = c.call("commit", doc=d, allow_error=True)
            if not isinstance(r, dict) or "error" not in r:
                break
            assert time.monotonic() < deadline, r
            time.sleep(0.1)
        fc = Client(fol.address)
        wait_until(
            lambda: (fc.call("clusterStatus")["docs"].get("docA") or {})
            .get("acked", 0) >= 2,
            timeout=15, msg="follower holding the healed writes")
        fc.close()
        c.close()
    finally:
        proxy.stop()
        led.stop()
        fol.stop()


def test_slow_follower_catches_up_via_forced_snapshot(tmp_path, monkeypatch):
    """A follower cut off while the leader keeps writing falls off the
    (tiny) retention buffer; reconnecting must recover through
    snapshot+tail — counted in cluster.catchup_snapshots — with no
    operator involved."""
    monkeypatch.setenv("AUTOMERGE_TPU_REPL_RETAIN_BYTES", "256")
    monkeypatch.setenv("AUTOMERGE_TPU_REPL_IO_TIMEOUT", "0.5")
    obs.reset_all()
    fol = start_node(tmp_path, "f1", role="follower")
    proxy = ChaosProxy("%s:%d" % fol.address, seed=6).start()
    led = start_node(tmp_path, "l1", role="leader",
                     replicate_to=[proxy.address])  # no ack gate: full rate
    try:
        c = Client(led.address)
        d = c.call("openDurable", name="docA")["doc"]
        c.call("put", doc=d, obj="_root", prop="k0", value=0)
        c.call("commit", doc=d)
        fc = Client(fol.address)
        wait_until(
            lambda: (fc.call("clusterStatus")["docs"].get("docA") or {})
            .get("cursor") is not None,
            msg="initial replication")

        proxy.partition("both")
        for i in range(1, 40):  # far more than 256 retained bytes
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
            c.call("commit", doc=d)
        proxy.heal()

        target = led.rpc.hub.lsn("docA")
        wait_until(
            lambda: (fc.call("clusterStatus")["docs"]["docA"]["cursor"]
                     or {}).get("lsn", 0) >= target,
            timeout=20, msg="follower converging past the trimmed tail")
        kinds = obs.counter_values("cluster.catchup_snapshots", "reason")
        assert sum(kinds.values()) >= 1, kinds
        # and the follower's state matches the leader byte-for-byte
        # (replHarvest is the follower-ok full-state surface)
        assert (fc.call("replHarvest", name="docA")["snapshot"]
                == c.call("replHarvest", name="docA")["snapshot"])
        fc.close()
        c.close()
    finally:
        proxy.stop()
        led.stop()
        fol.stop()


# -- the reference retry client (clients/python) ------------------------------


def _client_mod():
    import importlib.util
    import pathlib

    path = (pathlib.Path(__file__).parent.parent / "clients" / "python"
            / "amtpu_client.py")
    spec = importlib.util.spec_from_file_location("amtpu_client", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_retry_client_rides_out_garbled_frames_and_retriable_errors():
    """The reference client's contract: result or RpcError, never a raw
    socket/JSON exception — garbled frames and retriable errors redial
    and retry under the deadline budget."""
    amtpu = _client_mod()
    ls = socket.socket()
    ls.bind(("127.0.0.1", 0))
    ls.listen(8)
    script = ["garbage", "retriable", "ok"]

    def serve():
        for behavior in script:
            c, _ = ls.accept()
            f = c.makefile("r")
            req = json.loads(f.readline())
            if behavior == "garbage":
                c.sendall(b"{not json at all\n")
            elif behavior == "retriable":
                c.sendall((json.dumps({"id": req["id"], "error": {
                    "type": "Unavailable", "retriable": True,
                    "message": "try later"}}) + "\n").encode())
                # next request arrives on the SAME conn and succeeds
                req = json.loads(f.readline())
                c.sendall((json.dumps(
                    {"id": req["id"], "result": "done"}) + "\n").encode())
                c.close()
                return
            c.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    c = amtpu.RetryingClient(
        "127.0.0.1:%d" % ls.getsockname()[1], deadline_s=10, backoff_s=0.01)
    assert c.call("anything") == "done"
    assert c.last.attempts == 3, c.last.attempts
    assert c.last.blocked_s > 0
    c.close()
    ls.close()


def test_retry_client_deadline_bounds_a_blackholed_response():
    """A peer that receives but never answers (the asymmetric partition
    shape) must cost at most the deadline budget, not hang forever."""
    amtpu = _client_mod()
    ls = socket.socket()
    ls.bind(("127.0.0.1", 0))
    ls.listen(8)
    threading.Thread(
        target=lambda: [ls.accept() for _ in range(10)],
        daemon=True).start()  # accept, read nothing, answer nothing
    c = amtpu.RetryingClient(
        "127.0.0.1:%d" % ls.getsockname()[1], deadline_s=0.8,
        backoff_s=0.01)
    t0 = time.monotonic()
    with pytest.raises(amtpu.Deadline):
        c.call("hello")
    dt = time.monotonic() - t0
    assert dt < 5.0, f"deadline not enforced: blocked {dt}s"
    c.close()
    ls.close()
