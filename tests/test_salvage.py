"""Salvage loading and hardened chunk parsing.

A pristine save is all-or-nothing under the strict loader; with
``on_error="salvage"`` a damaged save degrades gracefully: checksum-invalid
or truncated chunks are skipped, the scan resynchronises at the next
MAGIC_BYTES occurrence, every chunk that still verifies is applied, and
``doc.salvage_report`` names exactly what was dropped.
"""

import pytest

from automerge_tpu import trace
from automerge_tpu.api import AutoDoc
from automerge_tpu.storage.chunk import (
    CHUNK_CHANGE,
    ChunkParseError,
    DroppedRegion,
    MAGIC_BYTES,
    parse_chunk,
    scan_chunks,
    write_chunk,
)
from automerge_tpu.storage.document import salvage_scan
from automerge_tpu.types import ActorId


def actor(i):
    return ActorId(bytes([i]) * 16)


def chunk_offsets(buf):
    offs, pos = [], 0
    while pos < len(buf):
        offs.append(pos)
        _, pos = parse_chunk(buf, pos)
    return offs


def chain_save(n=5):
    """A save of n concatenated change chunks from one actor (each change
    depends on the previous one)."""
    d = AutoDoc(actor=actor(1))
    for i in range(n):
        d.put("_root", f"k{i}", i)
        d.commit()
    data = d.save_incremental_after([])
    hashes = [c.hash for c in d.doc.get_changes([])]
    return data, hashes


# -- chunk header parsing (the satellite fixes) ------------------------------

def test_eight_byte_exact_header_is_truncated():
    """magic+checksum with no type byte must be a clean parse error, not an
    IndexError from reading buf[pos+8]."""
    buf = MAGIC_BYTES + b"\x00\x00\x00\x00"
    assert len(buf) == 8
    with pytest.raises(ChunkParseError, match="truncated chunk header"):
        parse_chunk(buf)


@pytest.mark.parametrize("size", range(0, 9))
def test_every_short_header_is_truncated(size):
    buf = (MAGIC_BYTES + b"\x00\x00\x00\x00\x01")[:size]
    with pytest.raises(ChunkParseError, match="truncated chunk header"):
        parse_chunk(buf)


def test_uleb_length_overrun_is_chunk_parse_error():
    """A length field whose continuation bytes run past end-of-input must
    raise ChunkParseError naming the byte offset — no LEBDecodeError leaks."""
    buf = MAGIC_BYTES + b"\x00\x00\x00\x00" + bytes([CHUNK_CHANGE]) + b"\x80\x80"
    with pytest.raises(ChunkParseError, match="at byte 9"):
        parse_chunk(buf)
    # and at a non-zero base offset the message names the true position
    good = write_chunk(CHUNK_CHANGE, b"data")
    with pytest.raises(ChunkParseError, match=f"at byte {len(good) + 9}"):
        parse_chunk(good + buf, len(good))


# -- tolerant scanning -------------------------------------------------------

def test_scan_chunks_clean_input():
    data, _ = chain_save(3)
    items = list(scan_chunks(data))
    assert len(items) == 3
    assert not any(isinstance(x, DroppedRegion) for x in items)


def test_scan_chunks_resyncs_after_garbage():
    a = write_chunk(CHUNK_CHANGE, b"alpha")
    b = write_chunk(CHUNK_CHANGE, b"beta")
    buf = a + b"\x00garbage\x01" + b
    items = list(scan_chunks(buf))
    chunks = [x for x in items if not isinstance(x, DroppedRegion)]
    drops = [x for x in items if isinstance(x, DroppedRegion)]
    assert [c.data for c in chunks] == [b"alpha", b"beta"]
    assert [c.offset for c in chunks] == [0, len(a) + 9]
    assert len(drops) == 1
    assert drops[0].offset == len(a)
    assert drops[0].end == len(a) + 9  # resynced exactly at b's magic
    # a garbage span has no readable header: no bogus checksum reported
    assert drops[0].checksum == b""


def test_scan_chunks_truncated_tail():
    data, _ = chain_save(2)
    cut = data[: len(data) - 3]
    items = list(scan_chunks(cut))
    chunks = [x for x in items if not isinstance(x, DroppedRegion)]
    drops = [x for x in items if isinstance(x, DroppedRegion)]
    assert len(chunks) == 1
    assert len(drops) == 1
    assert drops[0].end == len(cut)


def test_salvage_scan_reports_checksum_of_corrupt_chunk():
    data, hashes = chain_save(3)
    offs = chunk_offsets(data)
    bad = bytearray(data)
    bad[offs[1] + 12] ^= 0xFF  # flip a body byte of chunk 2
    chunks, report = salvage_scan(bytes(bad))
    assert len(chunks) == 2
    assert len(report.dropped) == 1
    # the stored checksum survives and names the original change hash
    assert report.dropped[0].checksum == hashes[1][:4]
    assert report.dropped[0].reason == "checksum mismatch"


# -- salvage loading ---------------------------------------------------------

def test_salvage_load_one_corrupt_chunk_keeps_the_rest():
    """The acceptance case: one corrupted chunk in a save; every other
    verifiable change loads; the report names exactly the dropped hash."""
    data, hashes = chain_save(5)
    offs = chunk_offsets(data)
    bad = bytearray(data)
    bad[offs[2] + 15] ^= 0xFF
    bad = bytes(bad)

    # strict load: all-or-nothing, as before
    with pytest.raises(Exception):
        AutoDoc.load(bad)

    d = AutoDoc.load(bad, on_error="salvage")
    rep = d.salvage_report
    assert rep is not None
    assert rep.applied_chunks == 4
    assert rep.dropped_checksums == [hashes[2][:4]]
    # changes before the hole are applied; the dependents of the destroyed
    # change wait in the queue (recoverable via sync once a peer provides it)
    applied = {c.hash for c in d.doc.get_changes([])}
    assert applied == {hashes[0], hashes[1]}
    assert d.doc.get_missing_deps([]) == [hashes[2]]
    assert d.keys("_root") == ["k0", "k1"]


def test_salvage_load_concurrent_branches_lose_only_the_corrupt_one():
    """With concurrent branches, destroying one branch's chunk must not
    take the other branch down."""
    a = AutoDoc(actor=actor(1))
    a.put("_root", "base", 0)
    a.commit()
    b = a.fork(actor=actor(2))
    a.put("_root", "from_a", 1)
    a.commit()
    b.put("_root", "from_b", 2)
    b.commit()
    a.merge(b)
    base, ca, cb = (c for c in a.doc.get_changes([]))
    data = bytes(base.raw_bytes + ca.raw_bytes + cb.raw_bytes)
    offs = chunk_offsets(data)
    bad = bytearray(data)
    bad[offs[1] + 14] ^= 0x0F  # corrupt a's branch change
    d = AutoDoc.load(bytes(bad), on_error="salvage")
    rep = d.salvage_report
    assert rep.applied_chunks == 2
    assert rep.dropped_checksums == [ca.hash[:4]]
    assert d.keys("_root") == ["base", "from_b"]


def test_salvage_load_corrupt_document_chunk_keeps_trailing_changes():
    d0 = AutoDoc(actor=actor(1))
    d0.put("_root", "x", 1)
    d0.commit()
    doc_chunk = d0.save()
    d0.put("_root", "y", 2)
    tail_hash = d0.commit()
    tail = d0.doc.get_change_by_hash(tail_hash).raw_bytes
    data = doc_chunk + tail
    bad = bytearray(data)
    bad[20] ^= 0xFF  # destroy the document chunk body
    d = AutoDoc.load(bytes(bad), on_error="salvage")
    rep = d.salvage_report
    assert rep.applied_chunks == 1
    assert len(rep.dropped) == 1
    # the trailing change chunk still parsed; its dep (inside the destroyed
    # document chunk) is reported missing
    assert d.doc.get_missing_deps([]) != []


def test_salvage_load_pristine_save_reports_nothing_dropped():
    data, hashes = chain_save(4)
    d = AutoDoc.load(data, on_error="salvage")
    rep = d.salvage_report
    assert rep.applied_chunks == 4
    assert rep.dropped == []
    assert {c.hash for c in d.doc.get_changes([])} == set(hashes)
    assert "salvaged 4 chunk(s)" in rep.summary()


def test_salvage_emits_trace_counters():
    trace.reset_counters()
    data, _ = chain_save(3)
    bad = bytearray(data)
    bad[chunk_offsets(data)[1] + 12] ^= 0xFF
    AutoDoc.load(bytes(bad), on_error="salvage")
    assert trace.counters.get("load.salvaged_chunks") == 2
    assert trace.counters.get("load.dropped_chunks") == 1


def test_load_incremental_salvage_alias_and_unknown_mode():
    data, _ = chain_save(2)
    d = AutoDoc(actor=actor(9))
    applied = d.load_incremental(data, on_error="salvage")
    assert applied == 2
    assert d.salvage_report is not None
    with pytest.raises(ValueError, match="unknown on_partial"):
        AutoDoc(actor=actor(9)).load_incremental(data, on_partial="bogus")


def test_rpc_load_salvage_reports_drops():
    from tests.test_rpc import call
    from automerge_tpu.rpc import RpcServer
    import base64

    data, hashes = chain_save(3)
    bad = bytearray(data)
    bad[chunk_offsets(data)[1] + 12] ^= 0xFF
    srv = RpcServer()
    out = call(srv, "load", data=base64.b64encode(bytes(bad)).decode(),
               onError="salvage")
    assert out["salvage"]["appliedChunks"] == 2
    dropped = out["salvage"]["dropped"]
    assert len(dropped) == 1
    assert base64.b64decode(dropped[0]["checksum"]) == hashes[1][:4]
    # strict load of the same bytes answers with an error, not a crash
    resp = srv.handle({"id": 1, "method": "load",
                       "params": {"data": base64.b64encode(bytes(bad)).decode()}})
    assert "error" in resp
