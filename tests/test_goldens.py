"""Cross-format hardening: byte-level goldens beyond the tiny fixtures.

Three layers (VERDICT r1 item 10):
  1. the reference's hand-written change-chunk wire example
     (reference: rust/automerge/tests/test.rs:1266-1291 — a spec-level
     byte vector, decoded and re-encoded byte-exactly here)
  2. hand-assembled sync-message bytes checked field by field
  3. committed golden documents (marks, counters, multi-actor, compressed
     doc columns) that every future build must load to the pinned state
     AND re-encode to the pinned bytes
"""

from __future__ import annotations

import os
import zlib

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.expanded import collapse_change, expand_change
from automerge_tpu.storage.change import build_change, parse_change
from automerge_tpu.sync.protocol import Message
from automerge_tpu.types import ActorId, ObjType, ScalarValue

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")

# The reference's hand-written change chunk (test.rs:1266-1291): actor
# 0x1234, seq 1, startOp 1, time -12345604 (sleb), message
# "Initialization", one op: set x=1 (uint), 10 trailing extra bytes.
REFERENCE_CHANGE = bytes(
    [
        0x85, 0x6F, 0x4A, 0x83,  # magic
        0xB2, 0x98, 0x9E, 0xA9,  # checksum
        1, 61, 0, 2, 0x12, 0x34,  # type=change, len, deps=0, actor "1234"
        1, 1, 0xFC, 0xFA, 0xDC, 0xFF, 5,  # seq, startOp, time
        14,  # message length
        *b"Initialization",
        0, 6,  # other actors = 0, column count
        0x15, 3, 0x34, 1, 0x42, 2,  # keyStr, insert, action col specs
        0x56, 2, 0x57, 1, 0x70, 2,  # valLen, valRaw, predNum col specs
        0x7F, 1, 0x78,  # keyStr: "x"
        1,  # insert: false
        0x7F, 1,  # action: set
        0x7F, 19,  # valLen: 1 byte, type uint
        1,  # valRaw: 1
        0x7F, 0,  # predNum: 0
        0, 1, 2, 3, 4, 5, 6, 7, 8, 9,  # extra bytes
    ]
)


class TestReferenceWireExample:
    def test_parse_reference_change_bytes(self):
        ch, pos = parse_change(REFERENCE_CHANGE)
        assert pos == len(REFERENCE_CHANGE)
        assert ch.actor == bytes([0x12, 0x34])
        assert ch.seq == 1
        assert ch.start_op == 1
        assert ch.message == "Initialization"
        assert ch.dependencies == []
        assert len(ch.ops) == 1
        op = ch.ops[0]
        assert op.key.prop == "x"
        assert not op.insert
        assert op.value == ScalarValue("uint", 1)
        assert op.pred == []
        assert ch.extra_bytes == bytes(range(10))

    def test_reencode_is_byte_identical(self):
        ch, _ = parse_change(REFERENCE_CHANGE)
        rebuilt = build_change(ch)
        assert rebuilt.raw_bytes == REFERENCE_CHANGE
        assert rebuilt.hash == ch.hash

    def test_expanded_roundtrip_preserves_reference_bytes(self):
        import json

        ch, _ = parse_change(REFERENCE_CHANGE)
        j = json.loads(json.dumps(expand_change(ch)))
        collapsed = collapse_change(j)
        assert collapsed.raw_bytes == REFERENCE_CHANGE

    def test_timestamp_sleb(self):
        ch, _ = parse_change(REFERENCE_CHANGE)
        # 0xFC 0xFA 0xDC 0xFF 0x05 decodes to this sleb value
        assert ch.timestamp == 1610038652

    def test_applies_as_a_document(self):
        doc = AutoDoc(actor=ActorId(bytes([9]) * 16))
        doc.load_incremental(REFERENCE_CHANGE, on_partial="error")
        assert doc.get("_root", "x")[0] == ("scalar", ScalarValue("uint", 1))


class TestSyncMessageBytes:
    def test_wire_fields(self):
        """Message encode lays out 0x42 | heads | need | have | changes
        exactly as sync.rs:473-557 does."""
        doc = AutoDoc(actor=ActorId(bytes([1]) * 16))
        doc.put("_root", "k", 1)
        doc.commit()
        ch = doc.get_changes([])[0]
        h = ch.hash
        msg = Message(heads=[h], need=[], have=[], changes=[ch])
        raw = msg.encode()
        assert raw[0] == 0x42  # MESSAGE_TYPE_SYNC
        assert raw[1] == 1  # heads count
        assert raw[2:34] == h  # head hash bytes
        assert raw[34] == 0  # need count
        assert raw[35] == 0  # have count
        assert raw[36] == 1  # change count
        # change payload is the length-prefixed raw chunk
        ln = raw[37]
        assert raw[38 : 38 + ln] == ch.raw_bytes
        # and decodes back
        dec = Message.decode(raw)
        assert dec.heads == [h] and [c.hash for c in dec.changes] == [h]

    def test_sync_state_bytes(self):
        from automerge_tpu.sync.protocol import SyncState

        s = SyncState()
        s.shared_heads = [bytes(range(32))]
        raw = s.encode()
        assert raw[0] == 0x43  # MESSAGE_TYPE_SYNC_STATE
        assert raw[1] == 1
        assert raw[2:34] == bytes(range(32))
        assert SyncState.decode(raw).shared_heads == s.shared_heads


def _golden_doc() -> AutoDoc:
    """Deterministic document covering marks, counters, multi-actor merges,
    nested objects, deletes, and >256-byte columns (deflate kicks in)."""
    a = AutoDoc(actor=ActorId(bytes([0xAA]) * 16))
    text = a.put_object("_root", "text", ObjType.TEXT)
    a.splice_text(text, 0, 0, "the quick brown fox jumps over the lazy dog " * 12)
    a.mark(text, 4, 9, "bold", True, expand="both")
    a.mark(text, 10, 15, "link", "https://example.com", expand="none")
    a.put("_root", "votes", ScalarValue("counter", 100))
    a.put("_root", "when", ScalarValue("timestamp", 1700000000000))
    a.put("_root", "blob", ScalarValue("bytes", bytes(range(64))))
    nested = a.put_object("_root", "nested", ObjType.MAP)
    lst = a.put_object(nested, "list", ObjType.LIST)
    for i in range(40):
        a.insert(lst, i, i * 7)
    a.commit()

    b = a.fork(actor=ActorId(bytes([0xBB]) * 16))
    b.splice_text(text, 0, 4, "THE ")
    b.increment("_root", "votes", 11)
    b.put("_root", "who", "actor-b")
    b.commit()

    c = a.fork(actor=ActorId(bytes([0xCC]) * 16))
    c.delete(lst, 0)
    c.put(lst, 0, "replaced")
    c.increment("_root", "votes", -3)
    c.put("_root", "who", "actor-c")
    c.commit()

    a.merge(b)
    a.merge(c)
    a.splice_text(text, 0, 0, "¡unicode – 🦊! ")
    a.commit()
    return a


GOLDEN_PATH = os.path.join(GOLDEN_DIR, "rich_multiactor.automerge")


def test_golden_document_bytes_stable():
    """The committed golden must load to the same state and re-save to the
    exact committed bytes — any drift in codecs/column layout fails here."""
    doc = _golden_doc()
    data = doc.save()
    if not os.path.exists(GOLDEN_PATH):
        if os.environ.get("AUTOMERGE_TPU_REGEN_GOLDENS"):
            os.makedirs(GOLDEN_DIR, exist_ok=True)
            with open(GOLDEN_PATH, "wb") as f:
                f.write(data)
        else:
            pytest.fail(
                "golden fixture missing; it must be committed. Set "
                "AUTOMERGE_TPU_REGEN_GOLDENS=1 to regenerate deliberately."
            )
    golden = open(GOLDEN_PATH, "rb").read()
    assert data == golden, "save bytes drifted from the committed golden"

    loaded = AutoDoc.load(golden)
    assert loaded.hydrate() == doc.hydrate()
    assert loaded.get_heads() == doc.get_heads()
    text_id = loaded.get("_root", "text")[0][2]
    marks = loaded.marks(text_id)
    assert {m.name for m in marks} == {"bold", "link"}
    assert loaded.get("_root", "votes")[0] == ("counter", 108)
    # deflate did engage for the big text column
    assert len(golden) < len(doc.save(deflate=False))
    # and a resave of the LOADED doc is also byte-identical
    assert loaded.save() == golden


def test_golden_change_chunks_stable():
    """Each change chunk re-encodes byte-identically after parse (hash
    verification would catch value drift; this catches encoding drift)."""
    doc = _golden_doc()
    for ch in doc.get_changes([]):
        reparsed, _ = parse_change(ch.raw_bytes)
        assert build_change(reparsed).raw_bytes == ch.raw_bytes


def test_golden_compressed_chunk_roundtrip():
    from automerge_tpu.storage.chunk import compress_chunk

    doc = _golden_doc()
    big = max(doc.get_changes([]), key=lambda c: len(c.raw_bytes))
    comp = compress_chunk(big.raw_bytes)
    assert comp[8] == 2  # compressed chunk type
    assert len(comp) < len(big.raw_bytes)
    reparsed, _ = parse_change(comp)
    assert reparsed.hash == big.hash
    assert reparsed.raw_bytes == big.raw_bytes


def test_remote_insert_at_mark_boundary_converges():
    """A REMOTE insert landing at concurrent mark boundaries: placement is
    RGA (op-id) order — mark boundary ops are ordinary invisible elements
    in the reference too (inner.rs:716-741 do_insert of MarkBegin/End) —
    so the guaranteed property across replicas is CONVERGENCE: same text,
    same spans, in both merge orders and on the device (VERDICT r1 weak #8).
    Local boundary inserts honoring expand are covered in test_marks."""
    from automerge_tpu.ops import DeviceDoc

    for expand in ("both", "none", "after", "before"):
        a = AutoDoc(actor=ActorId(bytes([1]) * 16))
        t = a.put_object("_root", "t", ObjType.TEXT)
        a.splice_text(t, 0, 0, "hello world")
        a.commit()
        b = a.fork(actor=ActorId(bytes([2]) * 16))

        a.mark(t, 0, 5, "bold", True, expand=expand)
        a.commit()
        # concurrent remote inserts at both boundaries
        b.splice_text(t, 5, 0, "XYZ")
        b.splice_text(t, 0, 0, "Q")
        b.commit()

        a.merge(b)
        b.merge(a)
        assert a.text(t) == b.text(t), expand
        spans_a = sorted((m.start, m.end, m.name) for m in a.marks(t))
        spans_b = sorted((m.start, m.end, m.name) for m in b.marks(t))
        assert spans_a == spans_b, (expand, spans_a, spans_b)
        assert spans_a, f"mark lost in merge under expand={expand}"
        dev = DeviceDoc.merge([a, b])
        spans_d = sorted((m.start, m.end, m.name) for m in dev.marks(t))
        assert spans_d == spans_a, (expand, spans_d, spans_a)
