"""Unified observability (automerge_tpu/obs): labeled metrics registry,
log-bucketed histograms + percentiles, hierarchical spans with Perfetto
export, Prometheus exposition, and the trace.py back-compat shims."""

import json
import logging
import math
import threading

import numpy as np
import pytest

from automerge_tpu import obs, trace
from automerge_tpu.api import AutoDoc
from automerge_tpu.obs.metrics import (
    FACTOR,
    MetricsRegistry,
    parse_prometheus,
)
from automerge_tpu.types import ActorId


# -- histogram bucket boundaries & percentile math ---------------------------


def test_histogram_bucket_boundaries():
    reg = MetricsRegistry()
    h = reg.histogram("x")
    # 1.0 sits exactly on the upper bound of bucket 0 -> (FACTOR^-1, 1.0]
    h.observe(1.0)
    cum = h.cumulative_buckets()
    assert cum == [(1.0, 1)]
    # nudging past the boundary moves to the next bucket, le == FACTOR
    h.observe(1.0 + 1e-9)
    cum = dict(h.cumulative_buckets())
    assert cum[1.0] == 1
    assert math.isclose(max(cum), FACTOR)
    # zero and negatives take the dedicated zero bucket (le == 0.0)
    h.observe(0.0)
    h.observe(-3.0)
    assert dict(h.cumulative_buckets())[0.0] == 2
    assert h.n == 4 and h.vmin == -3.0


def test_histogram_percentiles_match_numpy():
    reg = MetricsRegistry()
    h = reg.histogram("lat")
    rng = np.random.default_rng(7)
    xs = rng.lognormal(mean=-6.0, sigma=1.5, size=4000)
    for x in xs:
        h.observe(float(x))
    for q in (0.50, 0.95, 0.99):
        est = h.percentile(q)
        exact = float(np.quantile(xs, q))
        # one log bucket is ~19% wide; that bounds the estimate error
        assert abs(est - exact) / exact < 0.2, (q, est, exact)
    # exact accumulators are untouched by bucketing
    assert h.n == len(xs)
    assert math.isclose(h.total, float(xs.sum()), rel_tol=1e-9)
    assert h.percentile(0.0) >= h.vmin and h.percentile(1.0) <= h.vmax


def test_histogram_empty_and_summary():
    reg = MetricsRegistry()
    h = reg.histogram("empty")
    assert h.percentile(0.5) == 0.0
    s = h.summary()
    assert s["count"] == 0 and s["sum"] == 0.0
    h.observe(2.0)
    s = h.summary()
    assert s["count"] == 1 and s["p50"] == 2.0  # clamped to min==max


# -- labels & cardinality ----------------------------------------------------


def test_label_cardinality_cap():
    reg = MetricsRegistry(max_label_sets=4)
    for i in range(20):
        reg.counter("req", peer=f"p{i}").inc()
    fam = reg._families[("req", "counter")]
    # 4 real children + the overflow catch-all
    assert len(fam.children) == 5
    overflow = reg.counter("req", overflow="true")
    assert overflow.value == 16
    total = sum(c.value for c in fam.children.values())
    assert total == 20  # no increment is lost, only its label detail


def test_same_name_counter_and_histogram_coexist():
    reg = MetricsRegistry()
    reg.counter("device.delta_resolve").inc()
    reg.histogram("device.delta_resolve").observe(0.5)
    text = reg.render_prometheus()
    parsed = parse_prometheus(text)
    assert parsed[("device_delta_resolve_total", ())] == 1.0
    assert parsed[("device_delta_resolve_count", ())] == 1.0


# -- concurrency -------------------------------------------------------------


def test_concurrent_increments_are_exact():
    obs.reset_all()
    n_threads, n_incs = 8, 2500

    def worker(k):
        for i in range(n_incs):
            trace.count("stress.total")  # the shim path (the old race)
            obs.count("stress.labeled", labels={"t": str(k)})
            with obs.span("stress.span"):
                pass

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    want = n_threads * n_incs
    assert trace.counters["stress.total"] == want
    assert trace.counters["stress.labeled"] == want
    per_label = [
        obs.registry.counter("stress.labeled", t=str(k)).value
        for k in range(n_threads)
    ]
    assert per_label == [n_incs] * n_threads
    assert trace.timings["stress.span"][1] == want
    assert obs.registry.histogram("stress.span").n == want


# -- Prometheus exposition ---------------------------------------------------


def test_prometheus_render_round_trip():
    reg = MetricsRegistry()
    reg.counter("sync.retry").inc(3)
    reg.counter("sync.reset", source="peer").inc()
    reg.gauge("journal.bytes", path="/tmp/x").set(1234.5)
    # hostile label values: spaces, '=', quotes, backslash, newline
    reg.counter("rpc.errors", type='Bad "quote"=x\\y\nz').inc(2)
    h = reg.histogram("rpc.request", method="put")
    for v in (0.001, 0.002, 0.004, 5.0):
        h.observe(v)
    text = reg.render_prometheus()
    parsed = parse_prometheus(text)
    assert parsed[("sync_retry_total", ())] == 3.0
    assert parsed[("sync_reset_total", (("source", "peer"),))] == 1.0
    assert parsed[("journal_bytes", (("path", "/tmp/x"),))] == 1234.5
    assert parsed[
        ("rpc_errors_total", (("type", 'Bad "quote"=x\\y\nz'),))
    ] == 2.0
    assert parsed[("rpc_request_count", (("method", "put"),))] == 4.0
    assert math.isclose(
        parsed[("rpc_request_sum", (("method", "put"),))], 5.007
    )
    # cumulative bucket series: the +Inf bucket equals the count, and
    # cumulative counts are monotone over increasing le
    buckets = sorted(
        (math.inf if dict(k[1])["le"] == "+Inf" else float(dict(k[1])["le"]), v)
        for k, v in parsed.items()
        if k[0] == "rpc_request_bucket"
    )
    counts = [v for _, v in buckets]
    assert counts == sorted(counts) and counts[-1] == 4.0
    # TYPE lines are present and well-formed
    assert "# TYPE sync_retry_total counter" in text
    assert "# TYPE rpc_request histogram" in text
    assert "# TYPE journal_bytes gauge" in text


def test_prometheus_name_sanitization():
    reg = MetricsRegistry()
    reg.counter("load.salvaged-chunks").inc()
    parsed = parse_prometheus(reg.render_prometheus())
    assert parsed[("load_salvaged_chunks_total", ())] == 1.0


# -- spans & Perfetto export -------------------------------------------------


def test_span_nesting_and_export(tmp_path):
    obs.reset_all()
    with obs.span("outer", kind="test"):
        with obs.span("middle"):
            with obs.span("leaf", rows=7):
                pass
        with obs.span("middle2"):
            pass
    path = str(tmp_path / "trace.json")
    n = obs.export_trace(path)
    assert n == 4
    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert isinstance(events, list) and len(events) == 4
    by_name = {e["name"]: e for e in events}
    for e in events:  # chrome-trace schema
        assert e["ph"] == "X"
        for key in ("name", "cat", "ts", "dur", "pid", "tid", "args"):
            assert key in e, (key, e)
    outer, middle, leaf = by_name["outer"], by_name["middle"], by_name["leaf"]
    assert "parent_id" not in outer["args"]
    assert middle["args"]["parent_id"] == outer["args"]["span_id"]
    assert leaf["args"]["parent_id"] == middle["args"]["span_id"]
    assert by_name["middle2"]["args"]["parent_id"] == outer["args"]["span_id"]
    # time containment (what makes Perfetto render the flame chart)
    for child, parent in ((middle, outer), (leaf, middle)):
        assert child["ts"] >= parent["ts"]
        assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 1e-3
    assert outer["args"]["kind"] == "test" and leaf["args"]["rows"] == 7


def test_span_ring_buffer_is_bounded():
    rec = obs.SpanRecorder(capacity=16)
    for i in range(100):
        rec.record(obs.SpanRecord(f"s{i}", i, None, 0.0, 0.1, 1, {}, "ok"))
    assert len(rec) == 16
    assert rec.snapshot()[0].name == "s84"  # oldest evicted


def test_span_error_status():
    obs.reset_all()
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    rec = obs.recorder.snapshot()[-1]
    assert rec.name == "boom" and rec.status == "error"
    assert trace.timings["boom"][1] == 1  # timing still accumulated


def _mesh_device_apply(n_deltas=3):
    """A base doc + a few committed deltas pushed through the persistent
    DeviceDoc incremental path (CPU backend)."""
    from automerge_tpu.ops import DeviceDoc, OpLog

    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    from automerge_tpu.types import ObjType

    tobj = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(tobj, 0, 0, "hello world")
    base.commit()
    dev = DeviceDoc.resolve(OpLog.from_changes(
        [a.stored for a in base.doc.history]
    ))
    for i in range(n_deltas):
        base.splice_text(tobj, 0, 0, f"d{i} ")
        base.commit()
        dev.apply_changes([base.doc.history[-1].stored])
    return dev


def test_export_covers_device_merge_apply_and_sync_round(tmp_path):
    """Acceptance: a full device-merge apply and a full sync round render
    as nested spans in the exported Perfetto JSON."""
    obs.reset_all()
    _mesh_device_apply()

    # one full sync round through resilient sessions
    from automerge_tpu.sync.session import SyncSession

    a, b = AutoDoc(), AutoDoc()
    a.put("_root", "x", 1)
    a.commit()
    sa, sb = SyncSession(a, epoch=1), SyncSession(b, epoch=2)
    for tick in range(32):
        fa, fb = sa.poll(float(tick)), sb.poll(float(tick))
        if fa is not None:
            sb.receive(fa, float(tick))
        if fb is not None:
            sa.receive(fb, float(tick))
        if sa.converged() and sb.converged():
            break
    assert a.get_heads() == b.get_heads()

    path = str(tmp_path / "pipeline.json")
    obs.export_trace(path)
    events = json.load(open(path))["traceEvents"]
    by_id = {e["args"]["span_id"]: e for e in events}
    names = {e["name"] for e in events}
    # the device-merge pipeline spans, nested under device.apply
    assert {"device.apply", "device.extract"} <= names, names
    applies = [e for e in events if e["name"] == "device.apply"]
    nested_in_apply = {
        e["name"]
        for e in events
        if e["args"].get("parent_id") in {a_["args"]["span_id"] for a_ in applies}
    }
    assert "device.extract" in nested_in_apply or "device.delta_resolve" in nested_in_apply
    # the sync round spans: receive wraps apply
    assert {"sync.generate", "sync.receive", "sync.apply"} <= names, names
    sync_applies = [e for e in events if e["name"] == "sync.apply"]
    assert sync_applies
    for e in sync_applies:
        parent = by_id[e["args"]["parent_id"]]
        assert parent["name"] == "sync.receive"


# -- structured event lines (k=v escaping) -----------------------------------


def test_event_quotes_hostile_values():
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = Capture()
    obs.logger.addHandler(h)
    old = obs.logger.level
    obs.logger.setLevel(logging.DEBUG)
    try:
        obs.event("sync.malformed", error='bad frame: got "x" a=1 b\\c',
                  n=3, ok="plain")
    finally:
        obs.logger.removeHandler(h)
        obs.logger.setLevel(old)
    (line,) = records
    name, _, body = line.partition(" ")
    assert name == "sync.malformed"
    fields = obs.parse_event_fields(body)
    assert fields["error"] == 'bad frame: got "x" a=1 b\\c'
    assert fields["n"] == "3" and fields["ok"] == "plain"
    # literal backslash-n must round-trip as backslash+n, not newline
    # (sequential-replace unescaping gets this wrong)
    for hostile in ("path\\nfile", "C:\\new\\table", 'x\\"y', "a\nb\\n"):
        enc = obs._fmt_field(hostile)
        assert obs.parse_event_fields(f"v={enc}")["v"] == hostile, hostile
    # unquoted simple values stay bare (grep-ably identical to before)
    assert "ok=plain" in body and 'n=3' in body


# -- back-compat shims -------------------------------------------------------


def test_trace_shims_feed_legacy_views():
    trace.reset_counters()
    trace.reset_timers()
    trace.count("compat.hits")
    trace.count("compat.hits", n=4)
    assert trace.counters["compat.hits"] == 5
    with trace.time("compat.phase", rows=3):
        pass
    with trace.span("compat.phase"):
        pass
    summary = trace.timing_summary()
    assert summary["compat.phase"]["n"] == 2
    assert summary["compat.phase"]["s"] >= 0.0
    trace.reset_timers()
    assert trace.timing_summary() == {}
    trace.reset_counters()
    assert trace.counters == {}
    # the shim shares the obs registry: labels visible in Prometheus
    obs.count("compat.labeled", labels={"kind": "a"})
    assert ("compat_labeled_total", (("kind", "a"),)) in parse_prometheus(
        obs.render_prometheus()
    )


def test_trace_dicts_alias_obs_objects():
    # bench.py stashes/clears/updates trace.timings in place; that only
    # works if the module-level names alias the live obs dicts
    assert trace.counters is obs.legacy_counters
    assert trace.timings is obs.legacy_timings


# -- RPC + CLI surfaces ------------------------------------------------------


def test_rpc_metrics_method_round_trips():
    from automerge_tpu.rpc import RpcServer

    obs.reset_all()
    srv = RpcServer()
    doc = srv.handle({"id": 1, "method": "create", "params": {}})["result"]["doc"]
    srv.handle({"id": 2, "method": "put",
                "params": {"doc": doc, "obj": "_root", "prop": "k", "value": 1}})
    srv.handle({"id": 3, "method": "nope"})          # unknown method
    srv.handle({"id": 4, "method": "put", "params": {"doc": 999}})  # error
    out = srv.handle({"id": 5, "method": "metrics", "params": {}})
    body = out["result"]["body"]
    assert out["result"]["format"] == "prometheus"
    parsed = parse_prometheus(body)
    assert parsed[("rpc_request_count", (("method", "create"),))] == 1.0
    assert parsed[("rpc_request_count", (("method", "put"),))] == 2.0
    assert parsed[
        ("rpc_errors_total", (("method", "unknown"), ("type", "UnknownMethod")))
    ] == 1.0
    assert parsed[
        ("rpc_errors_total", (("method", "put"), ("type", "ValueError")))
    ] == 1.0
    # json format carries the structured snapshot + legacy views
    js = srv.handle({"id": 6, "method": "metrics",
                     "params": {"format": "json"}})["result"]
    assert any(e["name"] == "rpc.request" for e in js["metrics"])
    assert isinstance(js["counters"], dict) and isinstance(js["timings"], dict)


def test_rpc_serve_instruments_bytes(tmp_path):
    import io

    from automerge_tpu.rpc import RpcServer

    obs.reset_all()
    reqs = "\n".join([
        json.dumps({"id": 1, "method": "create", "params": {}}),
        "this is not json",
        json.dumps({"id": 2, "method": "shutdown"}),
    ]) + "\n"
    out = io.StringIO()
    RpcServer().serve(stdin=io.StringIO(reqs), stdout=out)
    assert trace.counters["rpc.bytes_in"] > 0
    assert trace.counters["rpc.bytes_out"] > 0
    parsed = parse_prometheus(obs.render_prometheus())
    assert parsed[
        ("rpc_errors_total", (("method", "unknown"), ("type", "ParseError")))
    ] == 1.0
    assert parsed[("rpc_request_bytes_count", ())] == 3.0


def test_cli_metrics_subcommand(tmp_path, capsys):
    from automerge_tpu.cli import main

    doc = AutoDoc(actor=ActorId(bytes([3]) * 16))
    doc.put("_root", "k", 42)
    doc.commit()
    save = tmp_path / "doc.automerge"
    save.write_bytes(doc.save())
    prom = tmp_path / "metrics.prom"
    tracef = tmp_path / "trace.json"
    rc = main(["metrics", str(save), "-o", str(prom),
               "--trace-out", str(tracef)])
    assert rc == 0
    parsed = parse_prometheus(prom.read_text())
    assert ("load_count", ()) in parsed  # the instrumented load span
    events = json.load(open(tracef))["traceEvents"]
    assert any(e["name"] == "load" for e in events)
    # json format on a durable directory
    ddir = tmp_path / "dur"
    dd = AutoDoc.open(str(ddir), fsync="never")
    dd.put("_root", "x", 1)
    dd.commit()
    dd.close()
    out_json = tmp_path / "m.json"
    rc = main(["metrics", str(ddir), "--format", "json", "-o", str(out_json)])
    assert rc == 0
    snap = json.loads(out_json.read_text())
    assert "journal.replayed_records" in snap["counters"]


# -- cross-process trace context ---------------------------------------------


def _spans_named(name):
    return [r for r in obs.recorder.snapshot() if r.name == name]


def test_trace_scope_propagates_into_spans():
    obs.reset_all()
    with obs.trace_scope("trace-abc", 4242):
        with obs.span("ts.outer") as sp:
            assert obs.current_trace_context() == ("trace-abc", sp.span_id)
            with obs.span("ts.inner"):
                pass
    outer, inner = _spans_named("ts.outer")[0], _spans_named("ts.inner")[0]
    # the remote parent heads the local chain; the trace id rides every span
    assert outer.parent_id == 4242 and outer.trace_id == "trace-abc"
    assert inner.parent_id == outer.span_id and inner.trace_id == "trace-abc"
    # outside the scope: no trace, no context
    assert obs.current_trace_context() is None
    with obs.span("ts.bare"):
        pass
    assert _spans_named("ts.bare")[0].trace_id is None


def test_trace_scope_rejects_hostile_input():
    obs.reset_all()
    for tid, sid in (({"x": 1}, "nope"), ("", 1), ("t" * 500, 1),
                     (None, None), (7, True)):
        with obs.trace_scope(tid, sid):
            with obs.span("ts.hostile"):
                pass
    assert all(r.trace_id is None for r in _spans_named("ts.hostile"))
    # sane id + junk parent: trace id still propagates, parent is local
    with obs.trace_scope("ok", "junk"):
        with obs.span("ts.half"):
            pass
    r = _spans_named("ts.half")[0]
    assert r.trace_id == "ok" and r.parent_id is None


def test_span_links_recorded_and_exported(tmp_path):
    obs.reset_all()
    with obs.span("lk.covered", links=[("tr1", 11), ("tr2", None)]):
        pass
    r = _spans_named("lk.covered")[0]
    assert r.links == (("tr1", 11), ("tr2", None))
    path = str(tmp_path / "links.json")
    obs.export_trace(path)
    ev = [e for e in json.load(open(path))["traceEvents"]
          if e["name"] == "lk.covered"][0]
    assert ev["args"]["links"] == [["tr1", 11], ["tr2", None]]


def test_decode_wire_traces_sanitizes():
    good = [["t1", 5], ["t2", None]]
    assert obs.decode_wire_traces(good) == [("t1", 5), ("t2", None)]
    hostile = [["t", "x"], "junk", [1, 2], ["", 3], ["ok", True],
               ["a" * 500, 1], ["fine", 9]]
    assert obs.decode_wire_traces(hostile) == [("fine", 9)]
    assert obs.decode_wire_traces("notalist") == []
    assert obs.decode_wire_traces([["t", 1]] * 100, limit=4) == [("t", 1)] * 4


def test_rpc_trace_field_activates_context():
    from automerge_tpu.rpc import RpcServer

    obs.reset_all()
    srv = RpcServer()
    resp = srv.handle({"id": 1, "method": "create", "params": {},
                       "trace": {"t": "req-77", "s": 909}})
    assert "error" not in resp
    spans = [r for r in obs.recorder.snapshot()
             if r.name == "rpc.request" and r.trace_id == "req-77"]
    assert spans and spans[0].parent_id == 909
    # hostile trace values answer normally, without a trace
    for tr in ("junk", {"t": 5, "s": "x"}, {"t": None}, []):
        resp = srv.handle({"id": 2, "method": "heads",
                           "params": {"doc": 999}, "trace": tr})
        assert "error" in resp  # invalid handle — but answered, not raised
    # absent trace: plain request, no trace recorded
    srv.handle({"id": 3, "method": "create", "params": {}})
    last = [r for r in obs.recorder.snapshot()
            if r.name == "rpc.request"][-1]
    assert last.trace_id is None


def test_spans_dropped_counter_on_ring_wrap(monkeypatch):
    obs.reset_all()
    small = obs.SpanRecorder(capacity=8)
    monkeypatch.setattr(obs, "recorder", small)
    for _ in range(20):
        with obs.span("wrap.me"):
            pass
    parsed = parse_prometheus(obs.render_prometheus())
    assert parsed[("obs_spans_dropped_total", ())] == 12.0


# -- multi-node Prometheus merging -------------------------------------------


def test_merge_prometheus_multi_node_families():
    from automerge_tpu.obs.metrics import MetricsRegistry, merge_prometheus

    a, b = MetricsRegistry(), MetricsRegistry()
    # conflicting label SETS on one family name across nodes
    a.counter("rpc.errors", method="put").inc(2)
    b.counter("rpc.errors", type="transport", peer="x").inc(5)
    b.gauge("cluster.replication_lag", doc="d1").set(3)
    merged = merge_prometheus({"n1": a.render_prometheus(),
                               "n2": b.render_prometheus()})
    parsed = parse_prometheus(merged)  # lossless: re-parses cleanly
    assert parsed[("rpc_errors_total",
                   (("method", "put"), ("node", "n1")))] == 2.0
    assert parsed[("rpc_errors_total",
                   (("node", "n2"), ("peer", "x"),
                    ("type", "transport")))] == 5.0
    assert parsed[("cluster_replication_lag",
                   (("doc", "d1"), ("node", "n2")))] == 3.0
    # ONE merged family set: a single TYPE line per family
    assert merged.count("# TYPE rpc_errors_total counter") == 1


def test_merge_prometheus_histogram_bucket_union():
    from automerge_tpu.obs.metrics import MetricsRegistry, merge_prometheus

    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("lat").observe(0.001)   # hits a tiny bucket
    b.histogram("lat").observe(100.0)   # hits a huge bucket
    b.histogram("lat").observe(200.0)
    merged = merge_prometheus({"a": a.render_prometheus(),
                               "b": b.render_prometheus()})
    assert merged.count("# TYPE lat histogram") == 1
    parsed = parse_prometheus(merged)
    # each node's sparse buckets survive under its node label…
    a_buckets = [k for k in parsed
                 if k[0] == "lat_bucket" and ("node", "a") in k[1]]
    b_buckets = [k for k in parsed
                 if k[0] == "lat_bucket" and ("node", "b") in k[1]]
    assert a_buckets and b_buckets
    # …with per-node counts intact
    assert parsed[("lat_count", (("node", "a"),))] == 1.0
    assert parsed[("lat_count", (("node", "b"),))] == 2.0
    # and the +Inf bound survives both parse and merge
    assert any(("le", "+Inf") in k[1] for k in a_buckets)


def test_merge_prometheus_hostile_node_labels():
    from automerge_tpu.obs.metrics import MetricsRegistry, merge_prometheus

    r = MetricsRegistry()
    r.counter("c").inc()
    evil = 'node"with\\quotes\nand newlines'
    merged = merge_prometheus({evil: r.render_prometheus()})
    parsed = parse_prometheus(merged)
    assert parsed[("c_total", (("node", evil),))] == 1.0
    # a pre-existing node label is replaced by the scraper's identity
    r2 = MetricsRegistry()
    r2.counter("c", node="liar").inc(9)
    merged = merge_prometheus({"true-node": r2.render_prometheus()})
    parsed = parse_prometheus(merged)
    assert parsed[("c_total", (("node", "true-node"),))] == 9.0


# -- per-doc accounting gauges ------------------------------------------------


def _gauge_value(name, **labels):
    for e in obs.snapshot():
        if e["name"] == name and e["type"] == "gauge" and e["labels"] == labels:
            return e["value"]
    return None


def test_per_doc_gauges_durable_layer(tmp_path):
    obs.reset_all()
    dd = AutoDoc.open(str(tmp_path / "docA"), fsync="never")
    try:
        dd.put("_root", "k", 1)
        dd.commit()
        jb = _gauge_value("doc.journal_bytes", doc="docA")
        la = _gauge_value("doc.last_access_seconds", doc="docA")
        assert jb is not None and jb > 0
        assert la is not None and 0 < la <= obs.now()
        before = la
        dd.put("_root", "k", 2)
        dd.commit()
        assert _gauge_value("doc.last_access_seconds", doc="docA") >= before
        assert _gauge_value("doc.journal_bytes", doc="docA") > jb
    finally:
        dd.close()


def test_per_doc_gauges_device_layer(tmp_path):
    obs.reset_all()
    dd = AutoDoc.open(str(tmp_path / "docB"), fsync="never", device=True)
    try:
        dd.put("_root", "k", 1)
        dd.commit()
        dd.device_doc.apply_changes([dd.doc.history[-1].stored])
        ops = _gauge_value("doc.resident_ops", doc="docB")
        db = _gauge_value("doc.device_bytes", doc="docB")
        assert ops == dd.device_doc.log.n and ops > 0
        assert db is not None and db > 0
    finally:
        dd.close()


def test_flocks_held_gauge(tmp_path):
    from automerge_tpu.storage.journal import Journal

    def held():
        return obs.registry.gauge("serve.flocks_held").value

    v0 = held()
    j, _, _ = Journal.open(str(tmp_path / "j.waj"), fsync="never")
    assert held() == v0 + 1
    j.close()
    assert held() == v0
    j.close()  # idempotent: no double decrement
    assert held() == v0


# -- overhead guard ----------------------------------------------------------


def test_disabled_path_overhead_is_bounded():
    """Always-on span/counter cost must stay micro-scale with tracing off
    (the hot paths run these per delta/append). Generous bound: CI boxes
    are noisy; the real budget is asserted relatively in scripts/ci/run_obs."""
    assert not obs.enabled()
    import timeit

    def one_span():
        with obs.span("ovh.span"):
            pass

    one_span()  # warm (family + child creation)
    obs.count("ovh.count")
    n = 2000
    t = timeit.timeit(one_span, number=n) / n
    assert t < 500e-6, f"span cost {t * 1e6:.1f}us"
    t = timeit.timeit(lambda: obs.count("ovh.count"), number=n) / n
    assert t < 200e-6, f"count cost {t * 1e6:.1f}us"


# -- parse/merge edge cases: empty families, non-finite values, buckets ------


def test_parse_prometheus_zero_sample_family():
    from automerge_tpu.obs.metrics import merge_prometheus

    text = "# HELP lonely no samples yet\n# TYPE lonely counter\n"
    assert parse_prometheus(text) == {}
    # a zero-sample family merges away without crashing the scrape
    merged = merge_prometheus({"n1": text})
    assert parse_prometheus(merged) == {}


def test_nonfinite_gauges_render_parse_and_merge():
    from automerge_tpu.obs.metrics import merge_prometheus

    reg = MetricsRegistry()
    reg.gauge("g", k="nan").set(float("nan"))
    reg.gauge("g", k="pinf").set(float("inf"))
    reg.gauge("g", k="ninf").set(float("-inf"))
    reg.gauge("g", k="fin").set(1.5)
    text = reg.render_prometheus()
    # the Prometheus exposition spellings, not Python's repr
    assert 'g{k="pinf"} +Inf' in text
    assert 'g{k="ninf"} -Inf' in text
    assert 'g{k="nan"} NaN' in text
    parsed = parse_prometheus(text)
    assert parsed[("g", (("k", "pinf"),))] == math.inf
    assert parsed[("g", (("k", "ninf"),))] == -math.inf
    assert math.isnan(parsed[("g", (("k", "nan"),))])
    assert parsed[("g", (("k", "fin"),))] == 1.5
    # and the multi-node merge keeps them intact under the node label
    merged = merge_prometheus({"a": text})
    parsed = parse_prometheus(merged)
    assert parsed[("g", (("k", "pinf"), ("node", "a")))] == math.inf
    assert math.isnan(parsed[("g", (("k", "nan"), ("node", "a")))])


def test_merged_histogram_buckets_stay_cumulative_monotone():
    from automerge_tpu.obs.metrics import merge_prometheus

    a, b = MetricsRegistry(), MetricsRegistry()
    for v in (0.0005, 0.002, 0.9):
        a.histogram("lat").observe(v)
    for v in (40.0, 150.0, 151.0, 0.001):
        b.histogram("lat").observe(v)
    merged = merge_prometheus({"a": a.render_prometheus(),
                               "b": b.render_prometheus()})
    parsed = parse_prometheus(merged)
    for node, n_obs in (("a", 3), ("b", 4)):
        rows = []
        for (name, labels), v in parsed.items():
            if name != "lat_bucket" or ("node", node) not in labels:
                continue
            le = dict(labels)["le"]
            rows.append((math.inf if le == "+Inf" else float(le), v))
        rows.sort()
        assert rows, f"no buckets for node {node}"
        # cumulative-monotone: counts never decrease with the bound
        counts = [v for _, v in rows]
        assert counts == sorted(counts)
        # the +Inf bucket equals the series count exactly
        assert rows[-1][0] == math.inf
        assert rows[-1][1] == float(n_obs)
        assert parsed[("lat_count", (("node", node),))] == float(n_obs)
