"""Differential guard over the native map-put session (fastcall map_put ->
map_session.cpp -> encode_map_tail_cols).

The per-op map hot path replaces the reference's local_map_op flow
(reference: rust/automerge/src/transaction/inner.rs:399-451 pred lookup +
op insert + succ marking) with a native session; its change chunks must be
byte-identical to the per-op python path, and every ineligible shape must
fall back to that path with identical results.
"""

import random

import pytest

from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.types import ActorId, ObjType, ScalarValue

pytestmark = pytest.mark.skipif(
    not native.available() or native.fastcall() is None,
    reason="native map session unavailable",
)


def _python_twin(build):
    """Run ``build`` against the session-enabled AutoDoc and a manual-tx
    (python-only) twin; both must produce identical bytes."""
    fast = AutoDoc(actor=ActorId(bytes([21]) * 16))
    build(fast, fast)
    h_fast = fast.commit()

    slow = AutoDoc(actor=ActorId(bytes([21]) * 16))
    tx = slow.transaction()
    build(slow, tx)
    h_slow = tx.commit()

    assert h_fast == h_slow
    assert fast.save() == slow.save()
    assert fast.hydrate() == slow.hydrate()
    return fast


def test_all_scalar_kinds_byte_identical():
    def build(doc, w):
        w.put("_root", "i", 7)
        w.put("_root", "neg", -12345)
        w.put("_root", "s", "héllo \U0001f680")
        w.put("_root", "f", 2.5)
        w.put("_root", "t", True)
        w.put("_root", "fa", False)
        w.put("_root", "n", None)
        w.put("_root", "by", b"\x00\xff")

    d = _python_twin(build)
    assert d.hydrate()["neg"] == -12345


def test_overwrites_set_pred_chain():
    def build(doc, w):
        w.put("_root", "k", 1)
        w.put("_root", "k", 2)
        w.put("_root", "k", "three")

    d = _python_twin(build)
    assert d.hydrate() == {"k": "three"}
    # reload sees exactly one visible op (preds consumed the others)
    r = AutoDoc.load(d.save())
    assert r.get_all("_root", "k") == d.get_all("_root", "k")


def test_preloaded_winners_cross_commit():
    """The second transaction's session preloads committed winners; its
    overwrites must name them as preds, same as the python path."""

    def base(doc):
        for i in range(20):
            doc.put("_root", f"k{i}", i)
        doc.commit()

    fast = AutoDoc(actor=ActorId(bytes([22]) * 16))
    base(fast)
    for i in range(0, 20, 2):
        fast.put("_root", f"k{i}", i * 100)
    fast.commit()

    slow = AutoDoc(actor=ActorId(bytes([22]) * 16))
    base(slow)
    tx = slow.transaction()
    for i in range(0, 20, 2):
        tx.put("_root", f"k{i}", i * 100)
    tx.commit()

    assert fast.save() == slow.save()
    assert fast.hydrate() == slow.hydrate()


def test_nested_map_session():
    def build(doc, w):
        pass

    d = AutoDoc(actor=ActorId(bytes([23]) * 16))
    m = d.put_object("_root", "m", ObjType.MAP)
    d.commit()
    for i in range(100):
        d.put(m, f"x{i}", i)
    d.commit()
    assert d.hydrate()["m"]["x42"] == 42
    r = AutoDoc.load(d.save())
    assert r.hydrate() == d.hydrate()


@pytest.mark.filterwarnings("ignore:.*(log assembly|extraction|native save).*:RuntimeWarning")
def test_ineligible_values_fall_back():
    """Counters, bigints, non-str keys: generic path, identical results.
    (>2^63 ints overflow the i64 array paths and warn through the graceful
    per-op fallback — a pre-existing, tested fallback, so silenced here.)"""

    def build(doc, w):
        w.put("_root", "a", 1)
        w.put("_root", "c", ScalarValue("counter", 5))
        w.put("_root", "big", 2**70)
        w.put("_root", "u", ScalarValue("uint", 3))
        w.put("_root", "b", 2)

    d = _python_twin(build)
    assert d.hydrate()["big"] == 2**70
    d.increment("_root", "c", 2)
    assert d.hydrate()["c"] == 7


def test_empty_key_raises():
    d = AutoDoc(actor=ActorId(bytes([24]) * 16))
    d.put("_root", "ok", 1)  # session live
    with pytest.raises(Exception, match="empty"):
        d.put("_root", "", 2)


def test_conflicted_key_uses_python_path():
    """A key with two concurrent winners is session-ineligible; preds must
    cover BOTH (the python path's multi-pred), so the conflict collapses."""
    a = AutoDoc(actor=ActorId(bytes([1]) * 16))
    a.put("_root", "k", "a")
    a.commit()
    b = a.fork(actor=ActorId(bytes([2]) * 16))
    b.put("_root", "k", "b")
    b.commit()
    a.put("_root", "k", "a2")
    a.commit()
    a.merge(b)
    assert len(a.get_all("_root", "k")) == 2  # conflicted
    a.put("_root", "k", "resolved")
    a.commit()
    assert a.get_all("_root", "k")[0][0][1].to_py() == "resolved"
    assert len(a.get_all("_root", "k")) == 1
    r = AutoDoc.load(a.save())
    assert len(r.get_all("_root", "k")) == 1


def test_interleaved_map_and_text_sessions():
    def build(doc, w):
        w.put("_root", "k1", 1)
        w.put("_root", "k2", 2)

    d = AutoDoc(actor=ActorId(bytes([25]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.commit()
    d.splice_text(t, 0, 0, "ab")
    d.put("_root", "k1", 1)
    d.splice_text(t, 2, 0, "cd")
    d.put("_root", "k2", 2)
    d.commit()
    assert d.text(t) == "abcd"
    assert d.hydrate()["k1"] == 1 and d.hydrate()["k2"] == 2
    r = AutoDoc.load(d.save())
    assert r.hydrate() == d.hydrate()


def test_reads_mid_transaction_drain():
    d = AutoDoc(actor=ActorId(bytes([26]) * 16))
    d.put("_root", "k", 1)
    assert d.get("_root", "k")[0][1].to_py() == 1  # drains, session stays
    d.put("_root", "k", 2)
    assert sorted(d.keys()) == ["k"]
    d.commit()
    assert d.hydrate() == {"k": 2}


def test_rollback_discards_session_ops():
    d = AutoDoc(actor=ActorId(bytes([27]) * 16))
    d.put("_root", "keep", 1)
    d.commit()
    d.put("_root", "drop", 2)
    d.rollback()
    assert d.hydrate() == {"keep": 1}
    r = AutoDoc.load(d.save())
    assert r.hydrate() == {"keep": 1}


def test_observer_patches_cover_session_ops():
    d = AutoDoc(actor=ActorId(bytes([28]) * 16))
    seen = []
    d.set_patch_callback(lambda ps: seen.extend(ps))
    for i in range(5):
        d.put("_root", f"k{i}", i)
    d.commit()
    assert len(seen) == 5
    assert all(p.obj == "_root" for p in seen)


def test_merge_convergence_with_session_changes():
    a = AutoDoc(actor=ActorId(bytes([3]) * 16))
    for i in range(200):
        a.put("_root", f"k{i:03}", i)
    a.commit()
    b = a.fork(actor=ActorId(bytes([4]) * 16))
    for i in range(0, 200, 3):
        b.put("_root", f"k{i:03}", -i)
    b.commit()
    for i in range(0, 200, 5):
        a.put("_root", f"k{i:03}", i * 7)
    a.commit()
    c = a.fork(actor=ActorId(bytes([5]) * 16))
    a.merge(b)
    b.merge(c)
    assert a.hydrate() == b.hydrate()
    assert a.save_and_verify() is not None


@pytest.mark.filterwarnings("ignore:.*(log assembly|extraction|native save).*:RuntimeWarning")
def test_randomized_differential():
    rng = random.Random(99)
    vals = [None, True, False, 0, 1, -1, 2**40, -(2**40), 1.5, "", "x",
            "é\U0001f680", b"", b"\x00", 2**70, ScalarValue("counter", 1)]

    def build(doc, w):
        for i in range(300):
            w.put("_root", f"k{rng.randrange(40):02}", rng.choice(vals))

    rng_state = rng.getstate()
    fast = AutoDoc(actor=ActorId(bytes([29]) * 16))
    build(fast, fast)
    h1 = fast.commit()
    rng.setstate(rng_state)
    slow = AutoDoc(actor=ActorId(bytes([29]) * 16))
    tx = slow.transaction()
    build(slow, tx)
    h2 = tx.commit()
    assert h1 == h2
    assert fast.save() == slow.save()
