"""Sharded merge over a virtual 8-device CPU mesh matches the host result.

conftest.py forces JAX_PLATFORMS=cpu with 8 virtual devices, so this runs
the real shard_map/psum path (the collectives the driver's multi-chip
dry-run exercises) without TPU hardware.
"""

import jax
import pytest

# the whole module drives jax.shard_map collectives; CPU-only JAX builds
# without it must skip (not error) so the env failure count stays zero
if not hasattr(jax, "shard_map"):
    pytest.skip(
        "jax.shard_map unavailable in this JAX build (CPU-only image)",
        allow_module_level=True,
    )

from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.parallel import default_mesh, sharded_merge_columns
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i):
    return ActorId(bytes([i]) * 16)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_matches_single_device(n_devices):
    assert len(jax.devices()) >= n_devices
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "shared base text")
    base.put("_root", "count", ScalarValue("counter", 0))
    base.commit()
    forks = [base.fork(actor=actor(10 + i)) for i in range(4)]
    for i, f in enumerate(forks):
        f.splice_text(t, i, 2, f"[{i}]")
        f.increment("_root", "count", i + 1)
        f.commit()

    log = OpLog.from_documents(forks)
    mesh = default_mesh(n_devices)
    res = sharded_merge_columns(log.padded_columns(), mesh)
    dev_sharded = DeviceDoc(log, res)
    dev_single = DeviceDoc.resolve(log)
    assert dev_sharded.hydrate() == dev_single.hydrate()
    host = AutoDoc(actor=actor(99))
    for f in forks:
        host.merge(f)
    assert dev_sharded.hydrate() == host.hydrate()


def _single_device_res(log, covered=None):
    """Oracle: the single-device jax kernel (dict transport, device
    linearization) on the same padded columns."""
    from automerge_tpu.ops.merge import ALL_OUTPUTS, merge_columns

    return merge_columns(
        log.padded_columns(covered=covered),
        linearize="device",
        fetch=ALL_OUTPUTS,
        n_objs=log.n_objs,
    )


def _assert_res_equal(sharded, single, P):
    import numpy as np

    for k in (
        "visible", "winner", "conflicts", "elem_index", "succ_count",
        "inc_count", "counter_inc", "is_elem", "parent_row",
        "obj_vis_len", "obj_text_width",
    ):
        a, b = np.asarray(sharded[k]), np.asarray(single[k])
        m = min(len(a), len(b))
        assert np.array_equal(a[:m], b[:m]), k


def test_sharded_large_fanin_100k():
    """>=100k ops through the fully-sharded path (scatter winners +
    sharded linearization) on the 8-device mesh, equal to the
    single-device kernel and converging to the native sequential apply."""
    from automerge_tpu import bench as W

    trace = W.load_trace(60_000)
    base = W.build_base(trace, 40_000)
    changes = list(base.changes) + W.synth_fanin(base, trace, 128, 500, 40_000)
    log = OpLog.from_changes(changes)
    assert log.n >= 100_000
    mesh = default_mesh(8)
    res = sharded_merge_columns(
        log.padded_columns(), mesh, n_objs=log.n_objs, n_props=len(log.props)
    )
    single = _single_device_res(log)
    _assert_res_equal(res, single, log.n)
    # end-to-end convergence vs the independent native oracle
    t_native, native_text = W.seq_apply_baseline(changes, base.text_obj)
    dev = DeviceDoc(log, res)
    assert dev.text(base.text_exid) == native_text


def test_sharded_marks_and_historical():
    """Marks + counters through the sharded path, current AND historical
    (covered-mask) views, equal to the single-device kernel."""
    import numpy as np

    from automerge_tpu.types import ObjType, ScalarValue

    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "the quick brown fox jumps")
    base.put("_root", "n", ScalarValue("counter", 10))
    base.commit()
    forks = [base.fork(actor=actor(20 + i)) for i in range(3)]
    forks[0].mark(t, 0, 9, "bold", True)
    forks[0].increment("_root", "n", 5)
    forks[0].commit()
    forks[1].mark(t, 4, 15, "italic", True, expand="both")
    forks[1].splice_text(t, 10, 5, "red")
    forks[1].commit()
    forks[2].delete("_root", "n")
    forks[2].splice_text(t, 0, 3, "a")
    forks[2].commit()

    log = OpLog.from_documents(forks)
    mesh = default_mesh(4)
    # current state
    res = sharded_merge_columns(
        log.padded_columns(), mesh, n_objs=log.n_objs, n_props=len(log.props)
    )
    _assert_res_equal(res, _single_device_res(log), log.n)
    dev = DeviceDoc(log, res)
    host = AutoDoc(actor=actor(99))
    for f in forks:
        host.merge(f)
    assert dev.hydrate() == host.hydrate()
    assert dev.marks(log.export_id(log.import_id(t))) == host.marks(t)
    # historical view: clock cut at half the log's ops
    covered = np.zeros(log.n, np.bool_)
    covered[: log.n // 2] = True
    res_h = sharded_merge_columns(
        log.padded_columns(covered=covered), mesh,
        n_objs=log.n_objs, n_props=len(log.props),
    )
    _assert_res_equal(res_h, _single_device_res(log, covered=covered), log.n)


def test_sharded_packed_transport():
    """The slope-RLE packed transport through the sharded path matches the
    dict transport exactly."""
    from automerge_tpu import bench as W

    trace = W.load_trace(6_000)
    base = W.build_base(trace, 3_000)
    changes = list(base.changes) + W.synth_fanin(base, trace, 16, 100, 3_000)
    log = OpLog.from_changes(changes)
    mesh = default_mesh(4)
    kw = dict(n_objs=log.n_objs, n_props=len(log.props))
    res_d = sharded_merge_columns(log.padded_columns(), mesh, **kw)
    res_p = sharded_merge_columns(
        log.padded_columns(), mesh, transport="packed", **kw
    )
    _assert_res_equal(res_p, res_d, log.n)


def test_sharded_sort_fallback_path():
    """A sparse obj x prop space exceeds the dense group-table budget and
    exercises the replicated sort-based fallback, still sharded-scatter."""
    doc = AutoDoc(actor=actor(9))
    from automerge_tpu.types import ObjType

    for i in range(200):
        o = doc.put_object("_root", f"o{i}", ObjType.MAP)
        doc.put(o, f"p{i}a", i)
        doc.put(o, f"p{i}b", -i)
    doc.commit()
    log = OpLog.from_documents([doc])
    mesh = default_mesh(2)
    res = sharded_merge_columns(
        log.padded_columns(), mesh, n_objs=log.n_objs, n_props=len(log.props)
    )
    _assert_res_equal(res, _single_device_res(log), log.n)


def test_linearize_collectives_scale_with_chains_not_rows():
    """The condensed linearization's per-doubling-step collectives must be
    sized to the CONDENSED chain bucket (R2/n per shard), not to the row
    capacity — the o(P) communication requirement. Captured by recording
    every all_gather's shard shape at trace time."""
    import numpy as np

    import automerge_tpu.parallel.sharding as S
    from automerge_tpu import bench as W

    # early-trace slices are sequential typing runs -> long first-child
    # chains -> strong condensation (the shape the optimization targets)
    trace = W.load_trace(8_000)
    base = W.build_base(trace, 6_000)
    changes = list(base.changes) + W.synth_fanin(base, trace, 8, 200, 0)
    log = OpLog.from_changes(changes)
    cols = log.padded_columns()
    Ptot = len(cols["action"])
    n = 4
    mesh = default_mesh(n)
    n_objs2 = log.n_objs + 2
    R2, cond_np = S.condense_host(cols, n_objs2, n)
    assert R2 <= Ptot // 4, "workload must actually condense"

    gathered = []
    orig = jax.lax.all_gather

    def spy(x, axis_name, **kw):
        gathered.append(tuple(x.shape))
        return orig(x, axis_name, **kw)

    S._make_sharded_fn.cache_clear()
    jax.lax.all_gather, patched = spy, True
    try:
        res = sharded_merge_columns(
            cols, mesh, n_objs=log.n_objs, n_props=len(log.props)
        )
    finally:
        jax.lax.all_gather = orig
        S._make_sharded_fn.cache_clear()

    # correctness unchanged
    _assert_res_equal(res, _single_device_res(log), log.n)

    Rl, Pl = R2 // n, Ptot // n
    small = [s for s in gathered if s[0] <= Rl]
    big = [s for s in gathered if s[0] >= Pl]
    assert small, "condensed doubling ran no chain-sized collectives"
    # the doubling loops (2 loops x ~log R2 steps x 2-3 arrays) all move
    # chain-bucket slices; only O(1) full-row collectives remain (winner /
    # conflicts / the single expansion gather), NOT one per doubling step
    assert len(big) <= 4, (len(big), sorted(set(gathered)))
    assert all(s[0] <= Rl or s[0] >= Pl for s in gathered), sorted(set(gathered))
    # communication volume: bytes per doubling step bounded by the chain
    # bucket, an order of magnitude under the row capacity here
    assert Rl * 8 < Pl, (Rl, Pl)
