"""Sharded merge over a virtual 8-device CPU mesh matches the host result.

conftest.py forces JAX_PLATFORMS=cpu with 8 virtual devices, so this runs
the real shard_map/psum path (the collectives the driver's multi-chip
dry-run exercises) without TPU hardware.
"""

import jax
import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.parallel import default_mesh, sharded_merge_columns
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i):
    return ActorId(bytes([i]) * 16)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_matches_single_device(n_devices):
    assert len(jax.devices()) >= n_devices
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "shared base text")
    base.put("_root", "count", ScalarValue("counter", 0))
    base.commit()
    forks = [base.fork(actor=actor(10 + i)) for i in range(4)]
    for i, f in enumerate(forks):
        f.splice_text(t, i, 2, f"[{i}]")
        f.increment("_root", "count", i + 1)
        f.commit()

    log = OpLog.from_documents(forks)
    mesh = default_mesh(n_devices)
    res = sharded_merge_columns(log.padded_columns(), mesh)
    dev_sharded = DeviceDoc(log, res)
    dev_single = DeviceDoc.resolve(log)
    assert dev_sharded.hydrate() == dev_single.hydrate()
    host = AutoDoc(actor=actor(99))
    for f in forks:
        host.merge(f)
    assert dev_sharded.hydrate() == host.hydrate()
