"""The chain-condensed all-device linearization (ops/merge.py
device_linearize_condensed) must produce the same document order as the
host preorder walk and the plain pointer-doubling kernel, on every forest
shape: typing chains, interleaved multi-actor chains, random splices,
deletes, multiple sequence objects, and forests whose runs break at
change boundaries.
"""

from __future__ import annotations

import numpy as np
import pytest

from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.ops.merge import (
    condensed_caps,
    merge_columns,
    merge_kernel,
    merge_kernel_condensed,
)
from automerge_tpu.types import ActorId, ObjType


def _assert_condensed_matches(docs_or_doc):
    docs = docs_or_doc if isinstance(docs_or_doc, list) else [docs_or_doc]
    log = OpLog.from_documents(docs)
    cols = log.padded_columns(include_aorder=True)
    rcap, obj_cap = condensed_caps(log)
    out_c = merge_kernel_condensed(rcap)(cols)
    out_o = merge_kernel_condensed(rcap, obj_cap)(cols)  # packed-sort arm
    out_d = merge_kernel(cols)
    host = merge_columns(
        log.columns(), fetch=("elem_index", "visible", "winner"),
        n_objs=log.n_objs, n_props=len(log.props),
    )
    n = log.n
    ei_c = np.asarray(out_c["elem_index"])[:n]
    ei_o = np.asarray(out_o["elem_index"])[:n]
    ei_d = np.asarray(out_d["elem_index"])[:n]
    ei_h = np.asarray(host["elem_index"])[:n]
    np.testing.assert_array_equal(ei_c, ei_d)
    np.testing.assert_array_equal(ei_c, ei_h)
    np.testing.assert_array_equal(ei_o, ei_h)
    np.testing.assert_array_equal(
        np.asarray(out_o["winner"])[:n], np.asarray(host["winner"])[:n]
    )


def test_typing_chain():
    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello world, this is a chain")
    d.commit()
    _assert_condensed_matches(d)


def test_interleaved_actors_and_deletes():
    a = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "base text for everyone")
    a.commit()
    forks = [a.fork(actor=ActorId(bytes([10 + i]) * 16)) for i in range(6)]
    rng = np.random.default_rng(7)
    for i, f in enumerate(forks):
        for _ in range(20):
            ln = f.length(t)
            pos = int(rng.integers(0, ln + 1))
            ndel = int(rng.integers(0, min(2, ln - pos) + 1))
            f.splice_text(t, pos, ndel, "ab"[: int(rng.integers(0, 3))])
        f.commit()
    for f in forks:
        a.merge(f)
    _assert_condensed_matches(a)


def test_multiple_sequence_objects():
    d = AutoDoc(actor=ActorId(bytes([2]) * 16))
    t1 = d.put_object("_root", "t1", ObjType.TEXT)
    t2 = d.put_object("_root", "t2", ObjType.TEXT)
    lst = d.put_object("_root", "l", ObjType.LIST)
    d.splice_text(t1, 0, 0, "first object")
    d.splice_text(t2, 0, 0, "second")
    for i in range(10):
        d.insert(lst, i, i)
    d.commit()
    d.splice_text(t1, 5, 3, "X")
    d.delete(lst, 2)
    d.commit()
    _assert_condensed_matches(d)


def test_prepend_heavy_sibling_order():
    # every insert at position 0: all elements are siblings of HEAD, so
    # every element is its own run (worst case for condensation)
    d = AutoDoc(actor=ActorId(bytes([3]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    for i in range(60):
        d.splice_text(t, 0, 0, chr(ord("a") + i % 26))
    d.commit()
    _assert_condensed_matches(d)


def test_cross_change_chain_continuation():
    # one actor typing across many commits: the chain spans changes but
    # stays contiguous in actor order
    d = AutoDoc(actor=ActorId(bytes([4]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    for w in ("alpha ", "beta ", "gamma ", "delta"):
        d.splice_text(t, d.length(t), 0, w)
        d.commit()
    _assert_condensed_matches(d)


def test_randomized_forests():
    rng = np.random.default_rng(42)
    for trial in range(4):
        a = AutoDoc(actor=ActorId(bytes([1]) * 16))
        t = a.put_object("_root", "t", ObjType.TEXT)
        a.splice_text(t, 0, 0, "seed")
        a.commit()
        forks = [a.fork(actor=ActorId(bytes([20 + i]) * 16)) for i in range(4)]
        for f in forks:
            for _ in range(int(rng.integers(5, 40))):
                ln = f.length(t)
                pos = int(rng.integers(0, ln + 1))
                ndel = int(rng.integers(0, min(3, ln - pos) + 1))
                txt = "xyz"[: int(rng.integers(0, 4))]
                f.splice_text(t, pos, ndel, txt)
            f.commit()
        for f in forks:
            a.merge(f)
        _assert_condensed_matches(a)
