"""Metric-catalogue audit: every metric family a full-stack smoke
registers must appear in README.md's observability documentation — a
new instrument without a catalogue entry fails here, so the docs can
never silently drift behind the code."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the smoke runs in a subprocess so its registry starts clean (the test
# session's own imports have already dirtied the in-process one)
_SMOKE = r"""
import json, sys, tempfile

from automerge_tpu import obs
from automerge_tpu.obs import heat
from automerge_tpu.rpc import RpcServer


def call(srv, method, **params):
    resp = srv.handle({"id": 1, "method": method, "params": params})
    assert "error" not in resp, resp
    return resp["result"]


with tempfile.TemporaryDirectory() as tmp:
    srv = RpcServer(durable_dir=tmp)
    # document surface: create / edit / commit / save / load / merge
    a = call(srv, "create", actor="01" * 16)["doc"]
    t = call(srv, "putObject", doc=a, obj="_root", prop="t",
             type="text")["$obj"]
    call(srv, "spliceText", doc=a, obj=t, pos=0, text="hello world")
    call(srv, "commit", doc=a)
    saved = call(srv, "save", doc=a)
    b = call(srv, "load", data=saved)["doc"]
    call(srv, "put", doc=b, obj="_root", prop="n", value=3)
    call(srv, "commit", doc=b)
    call(srv, "merge", doc=a, other=b)
    call(srv, "materialize", doc=a)
    # sync round trip
    sa = call(srv, "syncStateNew")["sync"]
    sb = call(srv, "syncStateNew")["sync"]
    for _ in range(6):
        m1 = call(srv, "generateSyncMessage", doc=a, sync=sa)
        if m1:
            call(srv, "receiveSyncMessage", doc=b, sync=sb, data=m1)
        m2 = call(srv, "generateSyncMessage", doc=b, sync=sb)
        if m2:
            call(srv, "receiveSyncMessage", doc=a, sync=sa, data=m2)
        if not m1 and not m2:
            break
    # durable write path + compaction
    d = call(srv, "openDurable", name="smoke-doc")["doc"]
    call(srv, "put", doc=d, obj="_root", prop="k", value="v")
    call(srv, "commit", doc=d)
    call(srv, "durableCompact", doc=d)
    # heat table publication (doc.heat gauges)
    heat.table.publish_gauges()
    call(srv, "heatStatus")
    call(srv, "historyStatus")
    call(srv, "metrics")

names = sorted({e["name"] for e in obs.snapshot()})
print(json.dumps(names))
"""


def test_every_registered_family_is_documented():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", _SMOKE], capture_output=True, text=True,
        cwd=REPO, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    names = json.loads(out.stdout.strip().splitlines()[-1])
    assert names, "smoke registered no metric families"
    readme = open(os.path.join(REPO, "README.md")).read()
    missing = [n for n in names if n not in readme]
    assert not missing, (
        "metric families registered by the smoke but absent from "
        f"README.md's catalogue: {missing}"
    )
