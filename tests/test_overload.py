"""Overload resilience unit coverage: admission control (priority
classes, proportional shedding, brownout hysteresis), deadline
enforcement on the RPC surface, the replication ack-gate circuit
breaker, shard-pool load signals, queue-gauge hygiene, the
retriable-flag contract audit, and the reference client's retryAfterMs
pacing. Everything here is wall-clock injectable or event-driven — no
load generation, no sleeps longer than a breaker cooldown."""

import json
import socket
import threading
import time
from types import SimpleNamespace

import pytest

from automerge_tpu import obs
from automerge_tpu.cluster.replication import ReplicationHub, ReplicationTimeout
from automerge_tpu.rpc import RpcServer
from automerge_tpu.serve.admission import (
    NO_SHED_RANK,
    AdmissionController,
    Overloaded,
    priority_class,
)
from automerge_tpu.serve.shards import ShardPool


def call(srv, method, **params):
    resp = srv.handle({"id": 1, "method": method, "params": params})
    assert "error" not in resp, resp
    return resp["result"]


def _counter_total(name):
    return sum(
        e["value"] for e in obs.snapshot()
        if e["type"] == "counter" and e["name"] == name
    )


# -- priority classes ---------------------------------------------------------


@pytest.mark.parametrize(
    "method,rank,cls",
    [
        ("replApply", 0, "replication"),
        ("clusterStatus", 0, "replication"),
        ("metrics", 0, "replication"),
        ("put", 1, "mutation"),
        ("someBrandNewMethod", 1, "mutation"),  # unknown defaults protected
        ("generateSyncMessage", 2, "sync"),
        ("get", 3, "read"),
        ("save", 3, "read"),
        ("durableCompact", 4, "background"),
        ("storeDemote", 4, "background"),
    ],
)
def test_priority_class_mapping(method, rank, cls):
    assert priority_class(method) == (rank, cls)


# -- proportional shedding math -----------------------------------------------


def test_shed_fraction_band_and_shed_rank():
    ac = AdmissionController(enabled=True)
    try:
        soft, hard = ac.soft, ac.hard
        # rank 0 is never shed, at any score
        assert ac.shed_fraction(0, 1e9) == 0.0
        # background sheds across [soft, 2*soft]: 0 below, linear inside
        assert ac.shed_fraction(4, soft * 0.99) == 0.0
        assert ac.shed_fraction(4, soft * 1.5) == pytest.approx(0.5)
        assert ac.shed_fraction(4, soft * 2.0) == pytest.approx(1.0)
        assert ac.shed_fraction(4, soft * 9.0) == 1.0
        # interactive mutations hold out until the hard threshold
        assert ac.shed_fraction(1, hard * 0.99) == 0.0
        assert ac.shed_fraction(1, hard * 1.5) == pytest.approx(0.5)
        # full-shed advertisement: nothing at low score, background first,
        # everything sheddable at twice the hard limit
        assert ac.shed_rank(score=soft * 0.5) == NO_SHED_RANK
        assert ac.shed_rank(score=soft * 2.0) == 4
        assert ac.shed_rank(score=hard * 2.0) == 1
    finally:
        ac.reset()


def test_admit_sheds_by_class_and_overloaded_contract():
    ac = AdmissionController(enabled=True)
    try:
        # pin the score past background full-shed but below the mutation
        # threshold: background is refused deterministically, mutations
        # pass, and replication passes no matter what
        ac.load_score = lambda now=None: 1.6
        assert ac.hard > 1.6 >= 2.0 * ac._shed_threshold(4)
        before = obs.counter_values("serve.shed", "class").get("background", 0)
        with pytest.raises(Overloaded) as ei:
            ac.admit("durableCompact")
        err = ei.value
        assert err.retriable is True
        assert err.shed_class == "background"
        assert 50 <= err.retry_after_ms <= 5000
        after = obs.counter_values("serve.shed", "class").get("background", 0)
        assert after == before + 1
        ac.admit("put")  # mutation admitted at this score
        ac.load_score = lambda now=None: 100.0
        ac.admit("replApply")  # replication is NEVER shed
        ac.admit("metrics")
    finally:
        ac.reset()


def test_admit_disabled_is_a_noop():
    ac = AdmissionController(enabled=False)
    try:
        ac.load_score = lambda now=None: 100.0
        ac.admit("durableCompact")
        ac.admit("put")
        assert ac.advertisement(now=1.0)["shedClass"] == NO_SHED_RANK
    finally:
        ac.reset()


# -- brownout hysteresis ------------------------------------------------------


class _FakePool:
    def __init__(self):
        self.util = 0.0

    def utilization(self):
        return self.util

    def backlog(self):
        return 0

    def expected_wait(self):
        return 0.0


def test_brownout_hysteresis_and_batcher_widen():
    from automerge_tpu.degrade import BROWNOUT, brownout_active

    fp = _FakePool()
    batcher = SimpleNamespace(window=8.0)
    ac = AdmissionController(pool=fp, batcher=batcher, enabled=True)
    try:
        step = ac.sample_s + 0.01
        t = 100.0
        # sustained pressure above enter, but shorter than the hold: no flip
        fp.util = ac.brownout_enter + 1.0
        assert ac.load_score(now=t) == pytest.approx(fp.util)
        assert not brownout_active()
        t += ac.enter_hold_s / 2
        ac.load_score(now=t)
        assert not brownout_active()
        # past the hold: enter, exactly once, and the batch window widens
        t += ac.enter_hold_s
        ac.load_score(now=t)
        assert brownout_active()
        assert ac.transitions == {"on": 1, "off": 0}
        assert batcher.window == pytest.approx(8.0 * ac.window_widen)
        # a dip below exit shorter than the exit hold does not flap out
        fp.util = 0.0
        t += step
        ac.load_score(now=t)
        t += ac.exit_hold_s / 2
        # a spike back above exit resets the exit clock
        fp.util = ac.brownout_exit + 0.2
        ac.load_score(now=t)
        fp.util = 0.0
        t += step
        ac.load_score(now=t)
        t += ac.exit_hold_s / 2
        ac.load_score(now=t)
        assert brownout_active()  # exit clock was reset by the spike
        # sustained calm past the full exit hold: exit, window restored
        t += ac.exit_hold_s
        ac.load_score(now=t)
        assert not brownout_active()
        assert ac.transitions == {"on": 1, "off": 1}
        assert batcher.window == pytest.approx(8.0)
        assert not BROWNOUT.is_set()
    finally:
        ac.reset()


# -- shard-pool load signals --------------------------------------------------


def test_shard_pool_expected_wait_and_gauge_hygiene():
    started = threading.Event()
    release = threading.Event()

    def execute(key, items):
        for it in items:
            if it == "block":
                started.set()
                release.wait(10)

    waits = []
    pool = ShardPool(execute, workers=1, max_queue=8, max_batch=1, name="ol")
    pool.wait_observer = waits.append
    try:
        pool.submit("k", "block")
        assert started.wait(10)
        # the single worker is pinned inside execute: utilization is 1.0
        # and anything submitted behind it waits depth x service time
        with pool._lock:
            pool._svc_ewma = 0.1
        pool.submit("k", "a")
        pool.submit("k", "b")
        assert pool.utilization() == 1.0
        assert pool.backlog() == 2
        assert pool.depth("k") == 2
        assert pool.expected_wait() == pytest.approx(0.2)
        release.set()
        deadline = time.monotonic() + 10
        while pool.backlog() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.backlog() == 0
        assert pool.expected_wait() == 0.0  # empty pool: no stale signal
        assert pool._svc_ewma > 0.0
        assert waits and all(w >= 0.0 for w in waits)
        # drained queues drop their rpc.queue_depth series (the registry's
        # label table must not grow with every doc handle ever served)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            series = [
                e for e in obs.snapshot()
                if e["name"] == "rpc.queue_depth"
                and e["labels"].get("doc") == "k"
            ]
            if not series:
                break
            time.sleep(0.01)
        assert not series, series
    finally:
        release.set()
        pool.stop()


def test_remove_doc_gauges_queue_key():
    obs.gauge_set("rpc.queue_depth", 3.0, labels={"doc": "gone-42"})
    assert any(
        e["name"] == "rpc.queue_depth" and e["labels"].get("doc") == "gone-42"
        for e in obs.snapshot()
    )
    n = obs.remove_doc_gauges(None, queue_key="gone-42")
    assert n >= 1
    assert not any(
        e["name"] == "rpc.queue_depth" and e["labels"].get("doc") == "gone-42"
        for e in obs.snapshot()
    )


# -- deadline enforcement on the RPC surface ----------------------------------


def test_expired_deadline_refused_without_executing():
    srv = RpcServer()
    d = call(srv, "create", actor="07" * 16)["doc"]
    call(srv, "put", doc=d, obj="_root", prop="k", value=1)
    call(srv, "commit", doc=d)
    heads0 = call(srv, "heads", doc=d)
    before = obs.counter_values(
        "serve.deadline_expired", "stage").get("pre_fsync", 0)
    req = {"id": 5, "method": "put",
           "params": {"doc": d, "obj": "_root", "prop": "x", "value": 2},
           "_deadline_ts": obs.now() - 1.0}
    resp = srv.handle(req)
    err = resp["error"]
    assert err["type"] == "DeadlineExceeded"
    assert err["retriable"] is True
    after = obs.counter_values(
        "serve.deadline_expired", "stage").get("pre_fsync", 0)
    assert after == before + 1
    # differential: the mutation did NOT execute
    assert call(srv, "heads", doc=d) == heads0
    assert call(srv, "keys", doc=d, obj="_root") == ["k"]
    # a live deadline executes normally
    live = {"id": 6, "method": "put",
            "params": {"doc": d, "obj": "_root", "prop": "x", "value": 2},
            "_deadline_ts": obs.now() + 60.0}
    assert "error" not in srv.handle(live)
    assert call(srv, "get", doc=d, obj="_root", prop="x") == 2


def test_expired_deadline_executes_when_admission_disabled(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_ADMISSION", "0")
    srv = RpcServer()
    assert srv.deadlines_enabled is False
    d = call(srv, "create", actor="08" * 16)["doc"]
    req = {"id": 2, "method": "put",
           "params": {"doc": d, "obj": "_root", "prop": "x", "value": 7},
           "_deadline_ts": obs.now() - 1.0}
    assert "error" not in srv.handle(req)  # uncontrolled baseline executes
    assert call(srv, "get", doc=d, obj="_root", prop="x") == 7


def test_parse_line_stamps_deadline():
    srv = RpcServer()
    line = json.dumps({"id": 1, "method": "heads",
                       "params": {}, "deadlineMs": 1500})
    req, early = srv._parse_line(line)
    assert early is None
    t0 = obs.now()
    assert t0 < req["_deadline_ts"] <= t0 + 1.6
    # zero, negative, and boolean deadlineMs never stamp
    for bad in (0, -5, True, "100"):
        req, early = srv._parse_line(
            json.dumps({"id": 1, "method": "heads", "params": {},
                        "deadlineMs": bad}))
        assert early is None and "_deadline_ts" not in req


# -- the replication ack-gate circuit breaker ---------------------------------


def test_replication_breaker_trips_bypasses_and_recovers():
    hub = ReplicationHub("t-breaker", ack_replicas=1)
    try:
        hub.breaker_enabled = True
        hub.breaker_threshold = 3
        hub.breaker_cooldown = 0.05
        hub._wait_acked = lambda name: (_ for _ in ()).throw(
            ReplicationTimeout("follower set stalled"))
        trips0 = _counter_total("repl.breaker_trips")
        # repeated timeouts surface to the callers AND count toward the trip
        for _ in range(hub.breaker_threshold):
            with pytest.raises(ReplicationTimeout):
                hub.wait_acked("doc")
        assert hub.breaker_state() == "open"
        assert _counter_total("repl.breaker_trips") == trips0 + 1
        # open within cooldown: ack on leader durability alone, loudly
        bypass0 = _counter_total("repl.breaker_bypass")
        hub.wait_acked("doc")  # does not raise
        assert _counter_total("repl.breaker_bypass") == bypass0 + 1
        assert hub.breaker_state() == "open"
        # after cooldown a half-open probe waits for real acks; success
        # re-closes the breaker
        time.sleep(hub.breaker_cooldown + 0.02)
        hub._wait_acked = lambda name: None
        hub.wait_acked("doc")
        assert hub.breaker_state() == "closed"
        # a failed probe re-opens on a single strike
        hub._wait_acked = lambda name: (_ for _ in ()).throw(
            ReplicationTimeout("still stalled"))
        for _ in range(hub.breaker_threshold):
            with pytest.raises(ReplicationTimeout):
                hub.wait_acked("doc")
        assert hub.breaker_state() == "open"
        time.sleep(hub.breaker_cooldown + 0.02)
        with pytest.raises(ReplicationTimeout):
            hub.wait_acked("doc")  # the probe itself
        assert hub.breaker_state() == "open"
    finally:
        hub.close()


def test_replication_breaker_disabled_passes_timeouts_through():
    hub = ReplicationHub("t-nobreaker", ack_replicas=1)
    try:
        hub.breaker_enabled = False
        hub._wait_acked = lambda name: (_ for _ in ()).throw(
            ReplicationTimeout("stalled"))
        for _ in range(10):
            with pytest.raises(ReplicationTimeout):
                hub.wait_acked("doc")
        assert hub.breaker_state() == "closed"
    finally:
        hub.close()


# -- the retriable-flag contract audit ----------------------------------------


def _audit_server():
    srv = RpcServer()
    d = call(srv, "create", actor="0a" * 16)["doc"]
    return srv, d


@pytest.mark.parametrize(
    "case",
    [
        "unknown_method", "bad_doc", "bad_params", "bad_changes",
        "open_durable_unsupported", "bad_sync_state", "expired_deadline",
    ],
)
def test_every_error_answer_carries_an_explicit_retriable_flag(case):
    """The client retry loop keys on ``retriable``; every error envelope
    the dispatch surface produces must carry it as an explicit bool —
    a missing flag silently falls back to the legacy type list."""
    srv, d = _audit_server()
    reqs = {
        "unknown_method": {"method": "nope", "params": {}},
        "bad_doc": {"method": "get",
                    "params": {"doc": 999, "obj": "_root", "prop": "x"}},
        "bad_params": {"method": "put", "params": {"doc": d}},
        "bad_changes": {"method": "applyChanges",
                        "params": {"doc": d, "changes": ["!!not-b64!!"]}},
        "open_durable_unsupported": {"method": "openDurable",
                                     "params": {"name": "x"}},
        "bad_sync_state": {"method": "receiveSyncMessage",
                           "params": {"doc": d, "state": "@@@",
                                      "message": "@@@"}},
        "expired_deadline": {"method": "put",
                             "params": {"doc": d, "obj": "_root",
                                        "prop": "x", "value": 1},
                             "_deadline_ts": obs.now() - 1.0},
    }
    req = dict(reqs[case])
    req["id"] = 1
    resp = srv.handle(req)
    assert "error" in resp, (case, resp)
    err = resp["error"]
    assert isinstance(err.get("retriable"), bool), (case, err)
    if case == "expired_deadline":
        assert err["type"] == "DeadlineExceeded" and err["retriable"] is True
    if case == "unknown_method":
        assert err["type"] == "UnknownMethod" and err["retriable"] is False


def test_frame_level_errors_carry_explicit_retriable():
    srv = RpcServer()
    resp, stop = srv._handle_line("{definitely not json\n")
    assert not stop
    assert resp["error"]["type"] == "ParseError"
    assert resp["error"]["retriable"] is False
    resp, stop = srv._handle_line("[1, 2, 3]\n")
    assert resp["error"]["type"] == "ParseError"
    assert resp["error"]["retriable"] is False
    big = json.dumps({"id": 1, "method": "put",
                      "params": {"pad": "x" * (srv.max_request_bytes + 64)}})
    resp, stop = srv._handle_line(big)
    assert resp["error"]["type"] == "RequestTooLarge"
    assert resp["error"]["retriable"] is False


# -- the reference client honors retryAfterMs ---------------------------------


def _client_mod():
    import importlib.util
    import pathlib

    path = (pathlib.Path(__file__).parent.parent / "clients" / "python"
            / "amtpu_client.py")
    spec = importlib.util.spec_from_file_location("amtpu_client", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_retry_client_paces_itself_on_retry_after_hint():
    """A shedding node's retryAfterMs hint overrides the exponential
    schedule: the retry lands ~0.75-1.25x the hint later, not after the
    (deliberately tiny) default backoff."""
    amtpu = _client_mod()
    ls = socket.socket()
    ls.bind(("127.0.0.1", 0))
    ls.listen(4)
    gaps = []

    def serve():
        c, _ = ls.accept()
        f = c.makefile("r")
        req = json.loads(f.readline())
        c.sendall((json.dumps({"id": req["id"], "error": {
            "type": "Overloaded", "retriable": True,
            "retryAfterMs": 400,
            "message": "shedding mutation work"}}) + "\n").encode())
        t_err = time.monotonic()
        req = json.loads(f.readline())  # the paced retry, same connection
        gaps.append(time.monotonic() - t_err)
        c.sendall((json.dumps(
            {"id": req["id"], "result": "done"}) + "\n").encode())
        c.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    c = amtpu.RetryingClient(
        "127.0.0.1:%d" % ls.getsockname()[1],
        deadline_s=10, backoff_s=0.001, seed=3)
    try:
        assert c.call("put") == "done"
        t.join(5)
        assert not t.is_alive()
        assert c.last.attempts == 2
        assert c.last.errors == ["Overloaded"]
        # jittered hint band is [0.3, 0.5]s; generous upper slack for a
        # loaded CI box, but far above what backoff_s=1ms would produce
        assert gaps and 0.25 <= gaps[0] <= 1.5, gaps
        assert c.last.blocked_s >= 0.25
    finally:
        c.close()
        ls.close()
