"""Flight recorder: bounded rings, crash-dump files, and the
multi-process merge that stitches dumps into one clock-aligned Perfetto
timeline with parent/link ids connecting across process boundaries."""

import json
import os

import pytest

from automerge_tpu import obs
from automerge_tpu.obs.flight import FlightRecorder, merge_flights
from automerge_tpu.obs.metrics import MetricsRegistry
from automerge_tpu.obs.spans import SpanRecord, SpanRecorder


def test_flight_dump_contents(tmp_path):
    obs.reset_all()
    obs.flight.events.clear()
    obs.flight.deltas.clear()
    with obs.span("fl.work", rows=3):
        obs.count("fl.counter", n=2, labels={"k": "v"})
        obs.gauge_set("fl.gauge", 7.5)
        obs.event("fl.event", what="happened")
    rec = FlightRecorder(obs.recorder, obs.registry)
    rec.install(str(tmp_path), "node-1")
    path = rec.dump(reason="test")
    assert os.path.basename(path).startswith("flight-node-1-")
    d = json.load(open(path))
    assert d["format"] == "automerge_tpu-flight-v1"
    assert d["node_id"] == "node-1" and d["reason"] == "test"
    assert d["origin_wall"] > 0
    assert any(s["name"] == "fl.work" and s["fields"] == {"rows": 3}
               for s in d["spans"])
    # events and metric deltas landed in the GLOBAL flight rings (the
    # obs entry points feed obs.flight, not this scratch recorder)
    gpath = tmp_path / "global.json"
    obs.flight.dump(str(gpath), reason="test")
    g = json.load(open(gpath))
    assert any(e["name"] == "fl.event" and e["fields"] == {"what": "happened"}
               for e in g["events"])
    deltas = {(e["kind"], e["name"]) for e in g["metric_deltas"]}
    assert ("count", "fl.counter") in deltas
    assert ("gauge", "fl.gauge") in deltas
    assert any(m["name"] == "fl.counter" for m in g["metrics"])
    # a second dump gets a fresh sequence number, never overwrites
    assert rec.dump(reason="again") != path


def test_flight_rings_are_bounded():
    rec = FlightRecorder(SpanRecorder(4), MetricsRegistry(), capacity=8)
    for i in range(100):
        rec.note_event(f"e{i}", {"i": i})
        rec.note_delta("count", f"c{i}", None, 1)
    assert len(rec.events) == 8 and len(rec.deltas) == 8
    assert rec.events[0][1] == "e92"  # oldest evicted
    off = FlightRecorder(SpanRecorder(4), MetricsRegistry(), capacity=0)
    off.note_event("x", {})
    off.note_delta("count", "x", None, 1)
    assert len(off.events) == 0 and len(off.deltas) == 0


def _fake_process(tmp_path, node_id, spans, origin_wall, clock_sync=()):
    """Write a flight dump for a simulated process: its own span
    recorder, its own clock origin."""
    srec = SpanRecorder(64)
    for s in spans:
        srec.record(s)
    rec = FlightRecorder(srec, MetricsRegistry(), capacity=8)
    for cs in clock_sync:
        rec.note_clock_sync(*cs)
    path = str(tmp_path / f"flight-{node_id}.json")
    rec.node_id = node_id
    rec.dump(path, reason="test")
    # dumps self-report origin_wall from the shared process clock; the
    # simulated processes need distinct origins
    d = json.load(open(path))
    d["node_id"] = node_id
    d["origin_wall"] = origin_wall
    json.dump(d, open(path, "w"))
    return path


def test_merge_connects_parents_and_links_across_dumps(tmp_path):
    """The acceptance shape: one client request's spans across router,
    leader and follower processes connect by parent/link ids in a single
    merged timeline."""
    tid = "req-cross"
    # "router" process: root span of the trace
    router_span = SpanRecord("router.request", 1001, None, 0.10, 0.30,
                             1, {}, "ok", trace_id=tid)
    # "leader" process: rpc.request parented to the ROUTER's span id,
    # plus a group-commit fsync linking the trace
    leader_req = SpanRecord("rpc.request", 2001, 1001, 0.02, 0.20,
                            1, {}, "ok", trace_id=tid)
    leader_fsync = SpanRecord("journal.fsync", 2002, None, 0.10, 0.05,
                              2, {}, "ok", links=((tid, 2001),))
    # "follower" process: repl.apply linking back to the leader span
    follower_apply = SpanRecord("repl.apply", 3001, None, 0.01, 0.04,
                                1, {}, "ok", trace_id=tid,
                                links=((tid, 2001),))
    p_router = _fake_process(tmp_path, "router", [router_span], 1000.0)
    p_leader = _fake_process(
        tmp_path, "leader", [leader_req, leader_fsync], 1000.1)
    p_follower = _fake_process(
        tmp_path, "follower", [follower_apply], 1000.2)

    doc, info = merge_flights([p_router, p_leader, p_follower])
    ev = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by_id = {e["args"]["span_id"]: e for e in ev}
    assert len(info["processes"]) == 3 and info["spans"] == 4

    # each process got its own pid, named
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M"}
    assert names == {"router", "leader", "follower"}

    # the parent chain crosses dumps: the leader's rpc.request names the
    # router's span as parent, and both carry the trace id
    lr = by_id[2001]
    assert lr["args"]["parent_id"] == 1001
    assert by_id[1001]["args"]["trace_id"] == tid
    assert lr["pid"] != by_id[1001]["pid"]

    # links cross dumps too: the follower's apply (and the leader's
    # group-commit fsync) both name the leader request span
    assert by_id[3001]["args"]["links"] == [[tid, 2001]]
    assert by_id[2002]["args"]["links"] == [[tid, 2001]]
    assert by_id[3001]["pid"] != lr["pid"]

    # wall-clock alignment: all three processes share one timeline, so
    # the leader's request (origin 1000.1 + 0.02) sits inside the
    # router's span (origin 1000.0 + 0.10 .. 0.40)
    assert by_id[1001]["ts"] <= lr["ts"] <= by_id[1001]["ts"] + 0.30e6


def test_merge_aligns_clocks_from_rtt_midpoints(tmp_path):
    """A follower whose self-reported wall origin is WRONG (skewed
    clock) still lands correctly: the leader's RTT samples around the
    follower's monotonic 'now' pin it to the shared timeline."""
    leader_span = SpanRecord("a", 1, None, 1.0, 0.1, 1, {}, "ok")
    follower_span = SpanRecord("b", 2, None, 4.0, 0.1, 1, {}, "ok")
    # truth: leader origin_wall=1000, follower's TRUE origin is 1005 —
    # at leader-mono 10.0 (wall 1010) the follower's mono clock reads
    # 5.0, and again at 20.0/15.0 (median of consistent samples).
    samples = [("follower", 9.9, 10.1, 5.0), ("follower", 19.9, 20.1, 15.0)]
    p_leader = _fake_process(tmp_path, "leader", [leader_span], 1000.0,
                             clock_sync=samples)
    # follower lies about its wall origin by a full minute
    p_follower = _fake_process(tmp_path, "follower", [follower_span], 1060.0)
    doc, info = merge_flights([p_leader, p_follower])
    ev = {e["args"]["span_id"]: e for e in doc["traceEvents"]
          if e.get("ph") == "X"}
    # leader span at wall 1001.0, follower span at true wall 1005+4=1009
    # -> 8s apart on the merged timeline, not 64s
    dt_us = ev[2]["ts"] - ev[1]["ts"]
    assert abs(dt_us - 8e6) < 1e3, dt_us
    assert info["processes"]["follower"]["aligned"] == "rtt"
    assert info["processes"]["leader"]["aligned"] == "wall"


def test_merge_collapses_multiple_dumps_from_one_process(tmp_path):
    """A process that dumped twice (failover + exit) with overlapping
    span rings renders each span ONCE, under one pid."""
    s1 = SpanRecord("early", 21, None, 0.0, 0.1, 1, {}, "ok")
    s2 = SpanRecord("late", 22, None, 1.0, 0.1, 1, {}, "ok")
    p_a = str(tmp_path / "flight-r-1.json")
    p_b = str(tmp_path / "flight-r-2.json")
    # failover dump holds s1; the later exit dump holds s1 AND s2
    for path, spans, mono in ((p_a, [s1], 5.0), (p_b, [s1, s2], 9.0)):
        srec = SpanRecorder(16)
        for s in spans:
            srec.record(s)
        rec = FlightRecorder(srec, MetricsRegistry(), capacity=4)
        rec.node_id = "router-7"
        rec.dump(path, reason="x")
        d = json.load(open(path))
        d["node_id"] = "router-7"
        d["dumped_at_mono"] = mono
        json.dump(d, open(path, "w"))
    doc, info = merge_flights([p_b, p_a])  # order must not matter
    ev = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert sorted(e["args"]["span_id"] for e in ev) == [21, 22]
    assert len({e["pid"] for e in ev}) == 1
    assert len(info["processes"]) == 1
    assert info["processes"]["router-7"]["spans"] == 2
    assert info["spans"] == 2


def test_merge_aligns_when_sampler_is_not_first_dump(tmp_path):
    """The RTT BFS roots at the dump that HOLDS samples — a sampled-only
    follower sorting first (alphabetically or by mtime) must not disable
    alignment."""
    a = SpanRecord("a", 1, None, 1.0, 0.1, 1, {}, "ok")
    b = SpanRecord("b", 2, None, 4.0, 0.1, 1, {}, "ok")
    p_fol = _fake_process(tmp_path, "a-follower", [b], 2060.0)
    p_led = _fake_process(tmp_path, "z-leader", [a], 2000.0,
                          clock_sync=[("a-follower", 9.9, 10.1, 5.0)])
    # follower first: the old first-dump root would reach nobody
    doc, info = merge_flights([p_fol, p_led])
    assert info["processes"]["a-follower"]["aligned"] == "rtt"
    ev = {e["args"]["span_id"]: e for e in doc["traceEvents"]
          if e.get("ph") == "X"}
    assert abs((ev[2]["ts"] - ev[1]["ts"]) - 8e6) < 1e3


def test_merge_rejects_non_flight_files(tmp_path):
    bad = tmp_path / "x.json"
    bad.write_text("{}")
    with pytest.raises(ValueError):
        merge_flights([str(bad)])
    with pytest.raises(ValueError):
        merge_flights([])


def test_cli_flight_merge_subcommand(tmp_path, capsys):
    from automerge_tpu.cli import main

    s1 = SpanRecord("one", 11, None, 0.0, 0.1, 1, {}, "ok")
    s2 = SpanRecord("two", 12, 11, 0.0, 0.05, 1, {}, "ok")
    _fake_process(tmp_path, "p1", [s1], 100.0)
    _fake_process(tmp_path, "p2", [s2], 100.0)
    out = tmp_path / "merged.json"
    # a directory of dumps is accepted and globbed
    rc = main(["flight-merge", str(tmp_path), "-o", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert names == {"one", "two"}
    err = capsys.readouterr().err
    assert "2 processes" in err
    # no dumps -> clean failure
    rc = main(["flight-merge", str(tmp_path / "empty_dir_nope")])
    assert rc == 1


def test_sigusr2_dumps_flight_recording(tmp_path):
    import glob
    import signal
    import time

    rec = FlightRecorder(obs.recorder, obs.registry)
    rec.install(str(tmp_path), "sig-node")
    os.kill(os.getpid(), signal.SIGUSR2)
    # the handler runs at the next bytecode boundary of the main thread
    deadline = time.monotonic() + 5.0
    dumps = []
    while time.monotonic() < deadline:
        dumps = glob.glob(str(tmp_path / "flight-sig-node-*.json"))
        if dumps:
            break
        time.sleep(0.01)
    assert dumps, "SIGUSR2 produced no flight dump"
    d = json.load(open(dumps[0]))
    assert d["reason"] == "signal"
    assert d["node_id"] == "sig-node"


def test_dump_carries_history_rings(tmp_path):
    from automerge_tpu.obs.history import HistoryRing
    from automerge_tpu.obs.metrics import MetricsRegistry as _Reg

    reg = _Reg()
    reg.counter("rpc.bytes_in").inc(7)
    ring = HistoryRing(allowlist=("rpc.bytes_in",), slots=4, registry=reg)
    ring.sample(now=1.0)
    ring.sample(now=2.0)
    rec = FlightRecorder(obs.recorder, obs.registry)
    rec.install(str(tmp_path), "hist-node")
    rec.history_provider = ring.status
    d = json.load(open(rec.dump(reason="test")))
    hist = d["history"]
    assert hist["samples"] == 2
    assert [s["name"] for s in hist["series"]] == ["rpc.bytes_in"]
    # a broken provider never breaks the dump itself
    rec.history_provider = lambda: (_ for _ in ()).throw(RuntimeError())
    d2 = json.load(open(rec.dump(reason="test2")))
    assert "history" not in d2
