"""The reference's integration-test corpus, ported scenario by scenario.

Source: rust/automerge/tests/test.rs (62 multi-actor merge scenarios built
on the automerge-test DSL). Each scenario here drives the SAME edit/merge
script through this framework's host document layer, then asserts the
realized (conflict-aware) document on BOTH the host AutoDoc and the
batched device merge (DeviceDoc over the same change set) — the
distribution-as-values testing style SURVEY §4 calls out.

DSL: automerge_tpu.testing (assert_doc / map_ / list_ / realize), the
analogue of reference rust/automerge-test/src/lib.rs:90-204.
"""

from __future__ import annotations

import json

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.core.document import Document
from automerge_tpu.errors import AutomergeError
from automerge_tpu.expanded import collapse_change, expand_change
from automerge_tpu.ops import DeviceDoc
from automerge_tpu.testing import (
    assert_doc,
    assert_obj,
    list_,
    map_,
    new_doc,
    realize,
    sorted_actors,
    text_,
)
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def counter(n: int) -> ScalarValue:
    return ScalarValue("counter", n)


def sorted_docs(n: int = 2):
    """n fresh docs whose actors are byte-ordered doc0 < doc1 < ..."""
    import os

    raws = set()
    while len(raws) < n:
        raws.add(os.urandom(16))
    return [AutoDoc(actor=ActorId(a)) for a in sorted(raws)]


def check(doc: AutoDoc, expected) -> None:
    """Assert the realized doc on the host AND through the device merge."""
    doc.commit()
    assert_doc(doc, expected)
    dev = DeviceDoc.merge([doc])
    assert_doc(dev, expected)


# ---- basic map / list conflict scenarios (test.rs:22-348) -------------------


def test_no_conflict_on_repeated_assignment():
    doc = new_doc()
    doc.put("_root", "foo", 1)
    doc.put("_root", "foo", 2)
    check(doc, map_({"foo": 2}))


def test_repeated_map_assignment_which_resolves_conflict_not_ignored():
    doc1, doc2 = new_doc(), new_doc()
    doc1.put("_root", "field", 123)
    doc2.merge(doc1)
    doc2.put("_root", "field", 456)
    doc1.put("_root", "field", 789)
    doc1.merge(doc2)
    assert len(doc1.get_all("_root", "field")) == 2
    doc1.put("_root", "field", 123)
    check(doc1, map_({"field": 123}))


def test_repeated_list_assignment_which_resolves_conflict_not_ignored():
    doc1, doc2 = new_doc(), new_doc()
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, 123)
    doc2.merge(doc1)
    doc2.put(lst, 0, 456)
    doc1.merge(doc2)
    doc1.put(lst, 0, 789)
    check(doc1, map_({"list": list_([789])}))


def test_list_deletion():
    doc = new_doc()
    lst = doc.put_object("_root", "list", ObjType.LIST)
    doc.insert(lst, 0, 123)
    doc.insert(lst, 1, 456)
    doc.insert(lst, 2, 789)
    doc.delete(lst, 1)
    check(doc, map_({"list": list_([123, 789])}))


def test_merge_concurrent_map_prop_updates():
    doc1, doc2 = new_doc(), new_doc()
    doc1.put("_root", "foo", "bar")
    doc2.put("_root", "hello", "world")
    doc1.merge(doc2)
    assert doc1.get("_root", "foo")[0] == ("scalar", ScalarValue("str", "bar"))
    check(doc1, map_({"foo": "bar", "hello": "world"}))
    doc2.merge(doc1)
    check(doc2, map_({"foo": "bar", "hello": "world"}))
    assert realize(doc1) == realize(doc2)


def test_add_concurrent_increments_of_same_property():
    doc1, doc2 = new_doc(), new_doc()
    doc1.put("_root", "counter", counter(0))
    doc2.merge(doc1)
    doc1.increment("_root", "counter", 1)
    doc2.increment("_root", "counter", 2)
    doc1.merge(doc2)
    check(doc1, map_({"counter": counter(3)}))


def test_add_increments_only_to_preceeded_values():
    doc1, doc2 = new_doc(), new_doc()
    doc1.put("_root", "counter", counter(0))
    doc1.increment("_root", "counter", 1)
    doc2.put("_root", "counter", counter(0))
    doc2.increment("_root", "counter", 3)
    doc1.merge(doc2)
    check(doc1, map_({"counter": {counter(1), counter(3)}}))


def test_concurrent_updates_of_same_field():
    doc1, doc2 = new_doc(), new_doc()
    doc1.put("_root", "field", "one")
    doc2.put("_root", "field", "two")
    doc1.merge(doc2)
    check(doc1, map_({"field": {"one", "two"}}))


def test_concurrent_updates_of_same_list_element():
    doc1, doc2 = new_doc(), new_doc()
    birds = doc1.put_object("_root", "birds", ObjType.LIST)
    doc1.insert(birds, 0, "finch")
    doc2.merge(doc1)
    doc1.put(birds, 0, "greenfinch")
    doc2.put(birds, 0, "goldfinch")
    doc1.merge(doc2)
    check(doc1, map_({"birds": list_([{"greenfinch", "goldfinch"}])}))


def test_assignment_conflicts_of_different_types():
    doc1, doc2, doc3 = new_doc(), new_doc(), new_doc()
    doc1.put("_root", "field", "string")
    doc2.put_object("_root", "field", ObjType.LIST)
    doc3.put_object("_root", "field", ObjType.MAP)
    doc1.merge(doc2)
    doc1.merge(doc3)
    check(doc1, map_({"field": {"string", list_([]), map_({})}}))


def test_changes_within_conflicting_map_field():
    doc1, doc2 = new_doc(), new_doc()
    doc1.put("_root", "field", "string")
    map_id = doc2.put_object("_root", "field", ObjType.MAP)
    doc2.put(map_id, "innerKey", 42)
    doc1.merge(doc2)
    check(doc1, map_({"field": {"string", map_({"innerKey": 42})}}))


def test_changes_within_conflicting_list_element():
    doc1, doc2 = sorted_docs()
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "hello")
    doc2.merge(doc1)

    map1 = doc1.put_object(lst, 0, ObjType.MAP)
    doc1.put(map1, "map1", True)
    doc1.put(map1, "key", 1)

    map2 = doc2.put_object(lst, 0, ObjType.MAP)
    doc1.merge(doc2)
    doc2.put(map2, "map2", True)
    doc2.put(map2, "key", 2)
    doc1.merge(doc2)
    check(
        doc1,
        map_(
            {
                "list": list_(
                    [
                        {
                            map_({"map2": True, "key": 2}),
                            map_({"map1": True, "key": 1}),
                        }
                    ]
                )
            }
        ),
    )


def test_concurrently_assigned_nested_maps_should_not_merge():
    doc1, doc2 = new_doc(), new_doc()
    m1 = doc1.put_object("_root", "config", ObjType.MAP)
    doc1.put(m1, "background", "blue")
    m2 = doc2.put_object("_root", "config", ObjType.MAP)
    doc2.put(m2, "logo_url", "logo.png")
    doc1.merge(doc2)
    check(
        doc1,
        map_(
            {
                "config": {
                    map_({"background": "blue"}),
                    map_({"logo_url": "logo.png"}),
                }
            }
        ),
    )


# ---- list insertion ordering (test.rs:351-788) ------------------------------


def test_concurrent_insertions_at_different_list_positions():
    doc1, doc2 = sorted_docs()
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "one")
    doc1.insert(lst, 1, "three")
    doc2.merge(doc1)
    doc1.splice(lst, 1, 0, ["two"])
    doc2.insert(lst, 2, "four")
    doc1.merge(doc2)
    check(doc1, map_({"list": list_(["one", "two", "three", "four"])}))


def test_concurrent_insertions_at_same_list_position():
    doc1, doc2 = sorted_docs()
    birds = doc1.put_object("_root", "birds", ObjType.LIST)
    doc1.insert(birds, 0, "parakeet")
    doc2.merge(doc1)
    doc1.insert(birds, 1, "starling")
    doc2.insert(birds, 1, "chaffinch")
    doc1.merge(doc2)
    check(doc1, map_({"birds": list_(["parakeet", "chaffinch", "starling"])}))


def test_concurrent_assignment_and_deletion_of_a_map_entry():
    doc1, doc2 = new_doc(), new_doc()
    doc1.put("_root", "bestBird", "robin")
    doc2.merge(doc1)
    doc1.delete("_root", "bestBird")
    doc2.put("_root", "bestBird", "magpie")
    doc1.merge(doc2)
    check(doc1, map_({"bestBird": "magpie"}))


def test_concurrent_assignment_and_deletion_of_list_entry():
    doc1, doc2 = new_doc(), new_doc()
    birds = doc1.put_object("_root", "birds", ObjType.LIST)
    doc1.insert(birds, 0, "blackbird")
    doc1.insert(birds, 1, "thrush")
    doc1.insert(birds, 2, "goldfinch")
    doc2.merge(doc1)
    doc1.put(birds, 1, "starling")
    doc2.delete(birds, 1)
    check(doc2, map_({"birds": list_(["blackbird", "goldfinch"])}))
    check(doc1, map_({"birds": list_(["blackbird", "starling", "goldfinch"])}))
    doc1.merge(doc2)
    check(doc1, map_({"birds": list_(["blackbird", "starling", "goldfinch"])}))


def test_insertion_after_a_deleted_list_element():
    doc1, doc2 = new_doc(), new_doc()
    birds = doc1.put_object("_root", "birds", ObjType.LIST)
    doc1.insert(birds, 0, "blackbird")
    doc1.insert(birds, 1, "thrush")
    doc1.insert(birds, 2, "goldfinch")
    doc2.merge(doc1)
    doc1.splice(birds, 1, 2, [])
    doc2.splice(birds, 2, 0, ["starling"])
    doc1.merge(doc2)
    check(doc1, map_({"birds": list_(["blackbird", "starling"])}))
    doc2.merge(doc1)
    check(doc2, map_({"birds": list_(["blackbird", "starling"])}))


def test_concurrent_deletion_of_same_list_element():
    doc1, doc2 = new_doc(), new_doc()
    birds = doc1.put_object("_root", "birds", ObjType.LIST)
    doc1.insert(birds, 0, "albatross")
    doc1.insert(birds, 1, "buzzard")
    doc1.insert(birds, 2, "cormorant")
    doc2.merge(doc1)
    doc1.delete(birds, 1)
    doc2.delete(birds, 1)
    doc1.merge(doc2)
    check(doc1, map_({"birds": list_(["albatross", "cormorant"])}))
    doc2.merge(doc1)
    check(doc2, map_({"birds": list_(["albatross", "cormorant"])}))


def test_concurrent_updates_at_different_levels():
    doc1, doc2 = new_doc(), new_doc()
    animals = doc1.put_object("_root", "animals", ObjType.MAP)
    birds = doc1.put_object(animals, "birds", ObjType.MAP)
    doc1.put(birds, "pink", "flamingo")
    doc1.put(birds, "black", "starling")
    mammals = doc1.put_object(animals, "mammals", ObjType.LIST)
    doc1.insert(mammals, 0, "badger")
    doc2.merge(doc1)
    doc1.put(birds, "brown", "sparrow")
    doc2.delete(animals, "birds")
    doc1.merge(doc2)
    doc1.commit()
    expected = map_({"mammals": list_(["badger"])})
    assert_obj(doc1, animals, expected)
    doc2.commit()
    assert_obj(doc2, animals, expected)


def test_concurrent_updates_of_concurrently_deleted_objects():
    doc1, doc2 = new_doc(), new_doc()
    birds = doc1.put_object("_root", "birds", ObjType.MAP)
    blackbird = doc1.put_object(birds, "blackbird", ObjType.MAP)
    doc1.put(blackbird, "feathers", "black")
    doc2.merge(doc1)
    doc1.delete(birds, "blackbird")
    doc2.put(blackbird, "beak", "orange")
    doc1.merge(doc2)
    check(doc1, map_({"birds": map_({})}))


def test_does_not_interleave_sequence_insertions_at_same_position():
    doc1, doc2 = sorted_docs()
    wisdom = doc1.put_object("_root", "wisdom", ObjType.LIST)
    doc2.merge(doc1)
    doc1.splice(wisdom, 0, 0, ["to", "be", "is", "to", "do"])
    doc2.splice(wisdom, 0, 0, ["to", "do", "is", "to", "be"])
    doc1.merge(doc2)
    check(
        doc1,
        map_(
            {
                "wisdom": list_(
                    ["to", "do", "is", "to", "be", "to", "be", "is", "to", "do"]
                )
            }
        ),
    )


def test_multiple_insertions_at_same_list_position_with_greater_actor_id():
    doc1, doc2 = sorted_docs()
    assert doc2.get_actor().bytes > doc1.get_actor().bytes
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "two")
    doc2.merge(doc1)
    doc2.insert(lst, 0, "one")
    check(doc2, map_({"list": list_(["one", "two"])}))


def test_multiple_insertions_at_same_list_position_with_lesser_actor_id():
    doc2, doc1 = sorted_docs()
    assert doc2.get_actor().bytes < doc1.get_actor().bytes
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "two")
    doc2.merge(doc1)
    doc2.insert(lst, 0, "one")
    check(doc2, map_({"list": list_(["one", "two"])}))


def test_insertion_consistent_with_causality():
    doc1, doc2 = new_doc(), new_doc()
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "four")
    doc2.merge(doc1)
    doc2.insert(lst, 0, "three")
    doc1.merge(doc2)
    doc1.insert(lst, 0, "two")
    doc2.merge(doc1)
    doc2.insert(lst, 0, "one")
    check(doc2, map_({"list": list_(["one", "two", "three", "four"])}))


# ---- save / load (test.rs:790-902, 1164-1264, 1313-1376) --------------------


def test_save_and_restore_empty():
    doc = new_doc()
    loaded = AutoDoc.load(doc.save())
    check(loaded, map_({}))


def test_save_restore_complex():
    doc1 = new_doc()
    todos = doc1.put_object("_root", "todos", ObjType.LIST)
    first_todo = doc1.insert_object(todos, 0, ObjType.MAP)
    doc1.put(first_todo, "title", "water plants")
    doc1.put(first_todo, "done", False)
    doc2 = new_doc()
    doc2.merge(doc1)
    doc2.put(first_todo, "title", "weed plants")
    doc1.put(first_todo, "title", "kill plants")
    doc1.merge(doc2)
    reloaded = AutoDoc.load(doc1.save())
    check(
        reloaded,
        map_(
            {
                "todos": list_(
                    [
                        map_(
                            {
                                "title": {"weed plants", "kill plants"},
                                "done": False,
                            }
                        )
                    ]
                )
            }
        ),
    )


def test_handle_repeated_out_of_order_changes():
    doc1 = new_doc()
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "a")
    doc1.commit()
    doc2 = doc1.fork()
    doc1.insert(lst, 1, "b")
    doc1.commit()
    doc1.insert(lst, 2, "c")
    doc1.commit()
    doc1.insert(lst, 3, "d")
    doc1.commit()
    changes = doc1.get_changes([])
    doc2.apply_changes(changes[2:])
    doc2.apply_changes(changes[2:])
    doc2.apply_changes(changes)
    assert doc1.save() == doc2.save()


def test_list_counter_del():
    doc1, doc2, doc3 = sorted_docs(3)
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "a")
    doc1.insert(lst, 1, "b")
    doc1.insert(lst, 2, "c")
    doc1.commit()
    saved = doc1.save()
    doc2 = AutoDoc.load(saved, actor=doc2.get_actor())
    doc3 = AutoDoc.load(saved, actor=doc3.get_actor())

    doc1.put(lst, 1, counter(0))
    doc2.put(lst, 1, counter(10))
    doc3.put(lst, 1, counter(100))

    doc1.put(lst, 2, counter(0))
    doc2.put(lst, 2, counter(10))
    doc3.put(lst, 2, 100)

    doc1.increment(lst, 1, 1)
    doc1.increment(lst, 2, 1)
    doc1.merge(doc2)
    doc1.merge(doc3)
    doc1.commit()

    assert_obj(
        doc1,
        lst,
        list_(
            [
                "a",
                {counter(1), counter(10), counter(100)},
                {100, counter(1), counter(10)},
            ]
        ),
    )

    doc1.increment(lst, 1, 1)
    doc1.increment(lst, 2, 1)
    doc1.commit()
    assert_obj(
        doc1,
        lst,
        list_(
            [
                "a",
                {counter(2), counter(11), counter(101)},
                {counter(2), counter(11)},
            ]
        ),
    )

    doc1.delete(lst, 2)
    assert doc1.length(lst) == 2
    doc4 = AutoDoc.load(doc1.save())
    assert doc4.length(lst) == 2
    doc1.delete(lst, 1)
    assert doc1.length(lst) == 1
    doc5 = AutoDoc.load(doc1.save())
    assert doc5.length(lst) == 1


def test_observe_counter_change_application():
    doc = new_doc()
    doc.put("_root", "counter", counter(1))
    doc.increment("_root", "counter", 2)
    doc.increment("_root", "counter", 5)
    changes = doc.get_changes([])
    doc2 = new_doc()
    doc2.apply_changes(changes)
    check(doc2, map_({"counter": counter(8)}))


def test_increment_non_counter_map():
    doc = new_doc()
    with pytest.raises(AutomergeError):
        doc.increment("_root", "nothing", 2)
    doc.put("_root", "non-counter", "mystring")
    with pytest.raises(AutomergeError):
        doc.increment("_root", "non-counter", 2)
    doc.put("_root", "counter", counter(1))
    doc.increment("_root", "counter", 2)

    doc1 = AutoDoc(actor=ActorId(bytes([1])))
    doc2 = AutoDoc(actor=ActorId(bytes([2])))
    doc1.put("_root", "key", counter(1))
    doc2.put("_root", "key", "mystring")
    doc1.merge(doc2)
    doc1.increment("_root", "key", 2)  # counter in a conflict: still ok


def test_increment_non_counter_list():
    doc = new_doc()
    lst = doc.put_object("_root", "list", ObjType.LIST)
    doc.insert(lst, 0, "mystring")
    with pytest.raises(AutomergeError):
        doc.increment(lst, 0, 2)
    doc.insert(lst, 0, counter(1))
    doc.increment(lst, 0, 2)

    doc1 = AutoDoc(actor=ActorId(bytes([1])))
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, None)
    doc1.commit()
    doc2 = doc1.fork(actor=ActorId(bytes([2])))
    doc1.put(lst, 0, counter(1))
    doc2.put(lst, 0, "mystring")
    doc1.merge(doc2)
    doc1.increment(lst, 0, 2)


def test_local_inc_in_map():
    doc1, doc2, doc3 = sorted_docs(3)
    doc1.put("_root", "hello", "world")
    doc1.commit()
    saved = doc1.save()
    doc2 = AutoDoc.load(saved, actor=doc2.get_actor())
    doc3 = AutoDoc.load(saved, actor=doc3.get_actor())

    doc1.put("_root", "cnt", 20)
    doc2.put("_root", "cnt", counter(0))
    doc3.put("_root", "cnt", counter(10))
    doc1.merge(doc2)
    doc1.merge(doc3)
    check(doc1, map_({"cnt": {20, counter(0), counter(10)}, "hello": "world"}))

    doc1.increment("_root", "cnt", 5)
    check(doc1, map_({"cnt": {counter(5), counter(15)}, "hello": "world"}))
    doc4 = AutoDoc.load(doc1.save())
    assert doc4.save() == doc1.save()


def test_merging_test_conflicts_then_saving_and_loading():
    actor1, actor2 = sorted_actors()
    doc1 = AutoDoc(actor=actor1)
    text = doc1.put_object("_root", "text", ObjType.TEXT)
    doc1.splice_text(text, 0, 0, "hello")
    doc1.commit()
    doc2 = AutoDoc.load(doc1.save(), actor=actor2)
    check(doc2, map_({"text": text_("hello")}))

    doc2.splice_text(text, 4, 1, "")
    doc2.splice_text(text, 4, 0, "!")
    doc2.splice_text(text, 5, 0, " ")
    doc2.splice_text(text, 6, 0, "world")
    check(doc2, map_({"text": text_("hell! world")}))
    doc3 = AutoDoc.load(doc2.save())
    check(doc3, map_({"text": text_("hell! world")}))


def test_delete_only_change():
    actor = ActorId(bytes(range(16)))
    doc1 = AutoDoc(actor=actor)
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "a")
    doc1.commit()
    doc2 = AutoDoc.load(doc1.save(), actor=actor)
    doc2.delete(lst, 0)
    doc2.commit()
    doc3 = AutoDoc.load(doc2.save(), actor=actor)
    doc3.insert(lst, 0, "b")
    doc3.commit()
    doc4 = AutoDoc.load(doc3.save(), actor=actor)
    changes = doc4.get_changes([])
    assert len(changes) == 3
    assert changes[2].start_op == 4


def test_save_and_reload_create_object():
    doc = new_doc()
    lst = doc.put_object("_root", "foo", ObjType.LIST)
    doc.commit()
    doc2 = AutoDoc.load(doc.save())
    doc2.insert(lst, 0, 1)
    check(doc2, map_({"foo": list_([1])}))
    AutoDoc.load(doc2.save())


def test_compressed_changes():
    doc = new_doc()
    doc.put("_root", "bytes", ScalarValue("bytes", bytes([10] * 300)))
    change = doc.get_last_local_change()
    uncompressed = change.raw_bytes
    assert len(uncompressed) > 256
    from automerge_tpu.storage.chunk import compress_chunk
    from automerge_tpu.storage.change import parse_change

    compressed = compress_chunk(uncompressed)
    assert len(compressed) < len(uncompressed)
    reloaded, _ = parse_change(compressed)
    assert reloaded.raw_bytes == uncompressed
    assert reloaded.hash == change.hash


def test_compressed_doc_cols():
    doc = new_doc()
    lst = doc.put_object("_root", "list", ObjType.LIST)
    expected = []
    for i in range(200):
        doc.insert(lst, i, i)
        expected.append(i)
    doc.commit()
    uncompressed = doc.save(deflate=False)
    compressed = doc.save()
    assert len(compressed) < len(uncompressed)
    loaded = AutoDoc.load(compressed)
    check(loaded, map_({"list": list_(expected)}))


def test_change_encoding_expanded_change_round_trip():
    doc = new_doc()
    doc.put("_root", "x", 1)
    doc.commit()
    change = doc.get_last_local_change()
    expanded = expand_change(change)
    unexpanded = collapse_change(json.loads(json.dumps(expanded)))
    assert unexpanded.raw_bytes == change.raw_bytes
    assert unexpanded.hash == change.hash


def test_save_and_load_incremented_counter():
    doc = new_doc()
    doc.put("_root", "counter", counter(1))
    doc.commit()
    doc.increment("_root", "counter", 1)
    doc.commit()
    changes1 = doc.get_changes([])
    jsons = [json.dumps(expand_change(c)) for c in changes1]
    changes2 = [collapse_change(json.loads(j)) for j in jsons]
    assert [c.hash for c in changes1] == [c.hash for c in changes2]
    assert [c.raw_bytes for c in changes1] == [c.raw_bytes for c in changes2]


def test_load_incremental_with_corrupted_tail():
    doc = new_doc()
    doc.put("_root", "key", "value")
    doc.commit()
    data = doc.save() + bytes([1, 2, 3, 4])
    loaded = new_doc()
    applied = loaded.load_incremental(data)
    assert applied == 1
    check(loaded, map_({"key": "value"}))


def test_load_doc_with_deleted_objects():
    doc = new_doc()
    doc.put_object("_root", "list", ObjType.LIST)
    doc.put_object("_root", "text", ObjType.TEXT)
    doc.put_object("_root", "map", ObjType.MAP)
    doc.put_object("_root", "table", ObjType.TABLE)
    doc.delete("_root", "list")
    doc.delete("_root", "text")
    doc.delete("_root", "map")
    doc.delete("_root", "table")
    saved = doc.save()
    loaded = AutoDoc.load(saved)
    check(loaded, map_({}))


def test_insert_after_many_deletes():
    doc = new_doc()
    obj = doc.put_object("_root", "object", ObjType.MAP)
    for i in range(100):
        doc.put(obj, str(i), i)
        doc.delete(obj, str(i))
    check(doc, map_({"object": map_({})}))


def test_simple_bad_saveload():
    doc = new_doc()
    doc.put("_root", "count", 0)
    doc.commit()
    doc.commit()  # empty commit
    doc.put("_root", "count", 0)
    doc.commit()
    AutoDoc.load(doc.save())


def test_ops_on_wrong_objects():
    doc = new_doc()
    lst = doc.put_object("_root", "list", ObjType.LIST)
    doc.insert(lst, 0, "a")
    doc.insert(lst, 1, "b")
    with pytest.raises(AutomergeError):
        doc.put(lst, "a", "AAA")
    with pytest.raises(AutomergeError):
        doc.splice_text(lst, 0, 0, "hello world")
    mp = doc.put_object("_root", "map", ObjType.MAP)
    doc.put(mp, "a", "AAA")
    doc.put(mp, "b", "BBB")
    with pytest.raises(AutomergeError):
        doc.insert(mp, 0, "b")
    with pytest.raises(AutomergeError):
        doc.splice_text(mp, 0, 0, "hello world")
    text = doc.put_object("_root", "text", ObjType.TEXT)
    doc.splice_text(text, 0, 0, "hello world")
    with pytest.raises(AutomergeError):
        doc.put(text, "a", "AAA")


def test_negative_64():
    doc = new_doc()
    doc.put("_root", "a", -64)
    check(doc, map_({"a": -64}))


def test_bad_change_on_node_boundary():
    doc = new_doc()
    doc.put("_root", "a", "z")
    doc.put("_root", "b", 0)
    doc.put("_root", "c", 0)
    doc.commit()
    for i in range(15):
        doc.put("_root", "a", "a" * i)
        doc.put("_root", "b", i + 1)
        doc.put("_root", "c", i + 1)
        doc.commit()
    doc2 = AutoDoc.load(doc.save())
    doc.put("_root", "a", "a" * 17)
    doc.put("_root", "b", 17)
    doc.put("_root", "c", 17)
    doc.commit()
    changes = doc.get_changes(doc2.get_heads())
    doc2.apply_changes(changes)
    AutoDoc.load(doc2.save())
    assert realize(doc2) == realize(doc)


def test_regression_nth_miscount():
    doc = new_doc()
    lst = doc.put_object("_root", "listval", ObjType.LIST)
    for i in range(30):
        doc.insert(lst, i, None)
        mp = doc.put_object(lst, i, ObjType.MAP)
        doc.put(mp, "test", i)
    doc.commit()
    dev = DeviceDoc.merge([doc])
    for i in range(30):
        got = doc.get(lst, i)
        assert got[0][0] == "obj" and got[0][1] == ObjType.MAP, (i, got)
        inner = doc.get(got[0][2], "test")
        assert inner[0] == ("scalar", ScalarValue("int", i))
        dgot = dev.get(lst, i)
        assert dgot[0][2] == got[0][2]
        assert dev.get(dgot[0][2], "test")[0] == ("scalar", ScalarValue("int", i))


def test_regression_nth_miscount_smaller():
    doc = new_doc()
    lst = doc.put_object("_root", "listval", ObjType.LIST)
    for i in range(64):
        doc.insert(lst, i, None)
        doc.put(lst, i, i)
    doc.commit()
    dev = DeviceDoc.merge([doc])
    for i in range(64):
        assert doc.get(lst, i)[0] == ("scalar", ScalarValue("int", i))
        assert dev.get(lst, i)[0] == ("scalar", ScalarValue("int", i))


def test_regression_insert_opid():
    doc = new_doc()
    lst = doc.put_object("_root", "list", ObjType.LIST)
    doc.commit()
    n = 30
    for i in range(n + 1):
        doc.insert(lst, i, None)
        doc.put(lst, i, i)
    doc.commit()
    new_doc2 = new_doc()
    new_doc2.apply_changes(doc.get_changes([]))
    for i in range(n + 1):
        assert doc.get(lst, i)[0] == ("scalar", ScalarValue("int", i))
        assert new_doc2.get(lst, i)[0] == ("scalar", ScalarValue("int", i))
    # applying with patches: materializing from the patch stream reproduces
    # the document (the patch-log half of the reference scenario)
    from automerge_tpu.patches.patch import apply_patches

    view = {}
    apply_patches(view, new_doc2.diff([], new_doc2.get_heads()))
    assert view == new_doc2.hydrate()
    # and the live observer path: a from-scratch callback materializes the
    # same state (reference: PatchLog::active + make_patches)
    collected = []
    new_doc2.set_patch_callback(collected.extend, from_scratch=True)
    view2 = {}
    apply_patches(view2, collected)
    assert view2 == new_doc2.hydrate()


def test_big_list():
    doc = new_doc()
    lst = doc.put_object("_root", "list", ObjType.LIST)
    doc.commit()
    n = 16
    for i in range(n + 1):
        doc.insert(lst, i, None)
    for i in range(n + 1):
        doc.put_object(lst, i, ObjType.MAP)
    doc.commit()
    new_doc2 = new_doc()
    new_doc2.apply_changes(doc.get_changes([]))
    assert realize(new_doc2) == realize(doc)
    dev = DeviceDoc.merge([doc])
    assert realize(dev) == realize(doc)


# ---- marks / isolation (test.rs:1689-1846) ----------------------------------


def test_marks():
    doc = new_doc()
    text = doc.put_object("_root", "text", ObjType.TEXT)
    doc.splice_text(text, 0, 0, "hello world")
    doc.mark(text, 0, len("hello"), "bold", True, expand="both")
    doc.splice_text(text, len("hello"), 0, " cool")
    doc.unmark(text, 0, len("hello"), "bold", expand="before")
    doc.splice_text(text, 0, 0, "why ")
    marks = doc.marks(text)
    assert marks[0].start == 9
    assert marks[0].end == 14
    assert marks[0].name == "bold"
    assert marks[0].value is True
    doc.commit()
    dev = DeviceDoc.merge([doc])
    dmarks = dev.marks(text)
    assert [(m.start, m.end, m.name, m.value) for m in dmarks] == [
        (m.start, m.end, m.name, m.value) for m in marks
    ]


def test_can_transaction_at():
    doc1 = Document(ActorId(bytes([7]) * 16))
    tx = doc1.transaction()
    txt = tx.put_object("_root", "text", ObjType.TEXT)
    tx.put("_root", "size", 100)
    tx.splice_text(txt, 0, 0, "aaabbbccc")
    tx.commit()
    heads1 = doc1.get_heads()

    tx = doc1.transaction()
    assert tx.text(txt) == "aaabbbccc"
    tx.splice_text(txt, 3, 3, "QQQ")
    tx.put("_root", "size", 200)
    assert tx.text(txt) == "aaaQQQccc"
    tx.commit()

    tx = doc1.transaction_at(heads1)
    assert tx.text(txt) == "aaabbbccc"
    assert tx.get("_root", "size")[0] == ("scalar", ScalarValue("int", 100))
    tx.splice_text(txt, 3, 3, "ZZZ")
    tx.put("_root", "size", 300)
    assert tx.text(txt) == "aaaZZZccc"
    tx.commit()
    assert doc1.text(txt) == "aaaZZZQQQccc"
    assert doc1.get("_root", "size")[0] == ("scalar", ScalarValue("int", 300))

    tx = doc1.transaction_at(heads1)
    assert tx.text(txt) == "aaabbbccc"
    tx.splice_text(txt, 3, 3, "TTT")
    tx.put("_root", "size", 400)
    assert tx.text(txt) == "aaaTTTccc"
    tx.commit()
    assert doc1.text(txt) == "aaaTTTZZZQQQccc"
    assert doc1.get("_root", "size")[0] == ("scalar", ScalarValue("int", 400))


def test_can_isolate():
    doc1 = AutoDoc(actor=ActorId(bytes([7]) * 16))
    txt = doc1.put_object("_root", "text", ObjType.TEXT)
    doc1.put("_root", "size", 100)
    doc1.splice_text(txt, 0, 0, "aaabbbccc")
    heads1 = doc1.get_heads()
    doc1.put("_root", "size", 150)

    doc1.isolate(heads1)
    doc2 = doc1.fork(actor=ActorId(bytes([8]) * 16))
    doc2.put("_root", "other", 999)
    doc2.splice_text(txt, 9, 0, "111")

    assert doc1.text(txt) == "aaabbbccc"
    assert doc1.get("_root", "size")[0] == ("scalar", ScalarValue("int", 100))
    doc1.splice_text(txt, 3, 3, "QQQ")
    doc1.put("_root", "size", 200)
    assert doc1.text(txt) == "aaaQQQccc"

    heads2 = doc1.get_heads()
    doc1.merge(doc2)
    assert doc1.get("_root", "size")[0] == ("scalar", ScalarValue("int", 200))
    assert doc1.get("_root", "other") is None

    doc1.isolate(heads1)
    assert heads1 != heads2
    assert doc1.text(txt) == "aaabbbccc"
    doc1.splice_text(txt, 3, 3, "ZZZ")
    doc1.put("_root", "size", 300)
    assert doc1.text(txt) == "aaaZZZccc"

    doc1.get_heads()  # commit boundary
    doc1.integrate()
    assert doc1.text(txt) == "aaaZZZQQQccc111"
    assert doc1.get("_root", "other")[0] == ("scalar", ScalarValue("int", 999))

    doc1.isolate(heads1)
    assert doc1.text(txt) == "aaabbbccc"
    doc1.splice_text(txt, 3, 3, "TTT")
    doc1.put("_root", "size", 400)
    assert doc1.text(txt) == "aaaTTTccc"
    doc1.get_heads()
    doc1.integrate()
    assert doc1.text(txt) == "aaaTTTZZZQQQccc111"
    assert doc1.get("_root", "size")[0] == ("scalar", ScalarValue("int", 400))


def test_inserting_text_near_deleted_marks():
    doc = new_doc()
    text = doc.put_object("_root", "text", ObjType.TEXT)
    doc.splice_text(text, 0, 0, "hello world")
    doc.mark(text, 2, 8, "bold", True, expand="after")
    doc.mark(text, 3, 6, "link", True, expand="none")
    doc.splice_text(text, 1, 10, "")
    assert doc.text(text) == "h"
    doc.splice_text(text, 0, 0, "a")
    assert doc.text(text) == "ah"
    doc.splice_text(text, 2, 0, "a")
    assert doc.text(text) == "aha"
    doc.marks(text)  # must not crash


def test_load_incremental_partial_change_stream():
    doc = Document(ActorId(bytes([3]) * 16))
    tx = doc.transaction()
    tx.put("_root", "a", 1)
    tx.commit()
    start_heads = doc.get_heads()
    tx = doc.transaction()
    tx.put("_root", "b", 2)
    tx.commit()
    changes = doc.get_changes(start_heads)
    encoded = b"".join(c.raw_bytes for c in changes)
    doc2 = Document(ActorId(bytes([4]) * 16))
    # the change depends on history doc2 doesn't have: it must queue, not fail
    doc2.load_incremental(encoded)
    assert doc2.get("_root", "b") is None


def test_multiple_insertions_same_position_greater_actor():
    """Insertion-order tie at one position: the greater actor's element
    sorts after the HEAD anchor consistently (test.rs:711-733)."""
    a1, a2 = sorted_actors()
    doc1 = AutoDoc(actor=a1)
    doc2 = AutoDoc(actor=a2)
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "two")
    doc1.commit()
    doc2.merge(doc1)
    doc2.insert(lst, 0, "one")
    assert_doc(doc2, map_({"list": list_(["one", "two"])}))


def test_multiple_insertions_same_position_lesser_actor():
    """Same tie with the actors swapped (test.rs:736-757)."""
    a2, a1 = sorted_actors()
    doc1 = AutoDoc(actor=a1)
    doc2 = AutoDoc(actor=a2)
    lst = doc1.put_object("_root", "list", ObjType.LIST)
    doc1.insert(lst, 0, "two")
    doc1.commit()
    doc2.merge(doc1)
    doc2.insert(lst, 0, "one")
    assert_doc(doc2, map_({"list": list_(["one", "two"])}))


def test_ops_on_wrong_object_types_error():
    """Map verbs on lists, seq verbs on maps, map verbs on text: typed
    errors, never silent success (test.rs:1379-1402 InvalidOp)."""
    doc = new_doc(77)
    lst = doc.put_object("_root", "list", ObjType.LIST)
    doc.insert(lst, 0, "a")
    doc.insert(lst, 1, "b")
    with pytest.raises(AutomergeError):
        doc.put(lst, "a", "AAA")  # map key on a list
    with pytest.raises(AutomergeError):
        doc.splice_text(lst, 0, 0, "hello world")  # text splice on a list
    m = doc.put_object("_root", "map", ObjType.MAP)
    doc.put(m, "a", "AAA")
    doc.put(m, "b", "BBB")
    with pytest.raises(AutomergeError):
        doc.insert(m, 0, "b")  # seq insert on a map
    with pytest.raises(AutomergeError):
        doc.splice_text(m, 0, 0, "hello world")
    t = doc.put_object("_root", "text", ObjType.TEXT)
    doc.splice_text(t, 0, 0, "hello world")
    with pytest.raises(AutomergeError):
        doc.put(t, "a", "AAA")  # map key on text


def test_save_restore_complex_transactional():
    """Nested todo edited concurrently on both sides of a fork; the merge
    keeps both conflict values and survives save/load
    (test.rs:858-903)."""
    doc1 = new_doc(81)
    todos = doc1.put_object("_root", "todos", ObjType.LIST)
    first = doc1.insert_object(todos, 0, ObjType.MAP)
    doc1.put(first, "title", "water plants")
    doc1.put(first, "done", False)
    doc1.commit()

    doc2 = new_doc(82)
    doc2.merge(doc1)
    doc2.put(first, "title", "weed plants")
    doc2.commit()
    doc1.put(first, "title", "kill plants")
    doc1.commit()
    doc1.merge(doc2)

    reloaded = AutoDoc.load(doc1.save())
    titles = sorted(
        v[1].value for v, _ in reloaded.get_all(first, "title")
    )
    assert titles == ["kill plants", "weed plants"]
    assert reloaded.get(first, "done")[0][1].value is False
    dev = DeviceDoc.merge([reloaded])
    assert dev.hydrate() == reloaded.hydrate()


def test_local_inc_in_map_bumps_all_visible_counters():
    """A local increment lands on EVERY visible conflicting counter, and a
    non-counter conflict loser disappears (test.rs:1079-1121)."""
    import os as _os

    v = sorted(
        (ActorId(_os.urandom(16)) for _ in range(3)), key=lambda a: a.bytes
    )
    doc1 = AutoDoc(actor=v[0])
    doc1.put("_root", "hello", "world")
    doc1.commit()
    doc2 = AutoDoc.load(doc1.save())
    doc2.set_actor(v[1])
    doc3 = AutoDoc.load(doc1.save())
    doc3.set_actor(v[2])

    doc1.put("_root", "cnt", ScalarValue("uint", 20))
    doc2.put("_root", "cnt", ScalarValue("counter", 0))
    doc3.put("_root", "cnt", ScalarValue("counter", 10))
    doc1.commit(); doc2.commit(); doc3.commit()
    doc1.merge(doc2)
    doc1.merge(doc3)
    def rendered_vals():
        out = []
        for v, _ in doc1.get_all("_root", "cnt"):
            if v[0] == "counter":
                out.append(("counter", v[1]))
            else:
                out.append((v[1].tag, v[1].value))
        return sorted(out)

    assert rendered_vals() == [("counter", 0), ("counter", 10), ("uint", 20)]

    doc1.increment("_root", "cnt", 5)
    doc1.commit()
    # the uint loses (increment predecessors overwrite it); counters bump
    assert rendered_vals() == [("counter", 5), ("counter", 15)]
    doc4 = AutoDoc.load(doc1.save())
    assert doc4.save() == doc1.save()
    dev = DeviceDoc.merge([doc1])
    assert dev.hydrate() == doc1.hydrate()


def test_merging_text_conflicts_then_saving_and_loading():
    """test.rs:1124-1160: splices on a loaded doc under a new actor,
    surviving another save/load cycle."""
    a1, a2 = sorted_actors()
    doc1 = AutoDoc(actor=a1)
    text = doc1.put_object("_root", "text", ObjType.TEXT)
    doc1.splice_text(text, 0, 0, "hello")
    doc1.commit()
    doc2 = AutoDoc.load(doc1.save())
    doc2.set_actor(a2)
    assert doc2.text(text) == "hello"
    doc2.splice_text(text, 4, 1, "")
    doc2.splice_text(text, 4, 0, "!")
    doc2.splice_text(text, 5, 0, " ")
    doc2.splice_text(text, 6, 0, "world")
    assert doc2.text(text) == "hell! world"
    doc3 = AutoDoc.load(doc2.save())
    assert doc3.text(text) == "hell! world"
    dev = DeviceDoc.merge([doc3])
    assert dev.hydrate() == doc3.hydrate()


def test_bad_change_on_storage_boundary():
    """test.rs:1467-1501: repeated same-key transactions, a fork loaded
    from the save, then one more change applied from the change stream —
    the reload must stay valid (the reference's op-tree page-boundary
    regression, generic at the storage level here)."""
    doc = new_doc(91)
    doc.put("_root", "a", "z")
    doc.put("_root", "b", 0)
    doc.put("_root", "c", 0)
    doc.commit()
    for i in range(15):
        doc.put("_root", "a", "a" * i)
        doc.put("_root", "b", i + 1)
        doc.put("_root", "c", i + 1)
        doc.commit()
    doc2 = AutoDoc.load(doc.save())
    i = 17
    doc.put("_root", "a", "a" * i)
    doc.put("_root", "b", i)
    doc.put("_root", "c", i)
    doc.commit()
    changes = doc.get_changes(doc2.get_heads())
    doc2.apply_changes(changes)
    AutoDoc.load(doc2.save())
    assert doc2.get("_root", "b")[0][1].value == 17
