"""Fast (native columnar) vs slow (per-op) OpLog extraction equivalence."""

import numpy as np
import pytest

from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.types import ActorId, ObjType, ScalarValue

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native codecs unavailable"
)


def actor(i):
    return ActorId(bytes([i]) * 16)


def build_docs():
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "columnar extraction test ✓ ünïcode")
    base.put("_root", "n", ScalarValue("counter", 7))
    base.put("_root", "pi", 3.25)
    base.put("_root", "blob", b"\x00\x01\x02")
    lst = base.put_object("_root", "l", ObjType.LIST)
    base.insert(lst, 0, "item")
    base.mark(t, 0, 9, "bold", True)
    base.commit()
    forks = [base.fork(actor=actor(10 + i)) for i in range(3)]
    for i, f in enumerate(forks):
        f.splice_text(t, i * 2, 1, f"<{i}>")
        f.increment("_root", "n", i + 1)
        f.put("_root", f"k{i}", i)
        f.commit()
    return forks, t


def collect_changes(docs):
    out = []
    for d in docs:
        out.extend(a.stored for a in d.doc.history)
    return out


def test_fast_slow_equivalence():
    forks, t = build_docs()
    changes = collect_changes(forks)
    fast = OpLog.from_changes(changes, fast=True)
    slow = OpLog.from_changes(changes, fast=False)
    assert fast.n == slow.n
    for field in (
        "id_key", "obj_key", "prop", "elem_ref", "action", "insert",
        "value_tag", "value_int", "width", "expand", "mark_name_idx",
        "pred_src", "pred_tgt", "obj_dense",
    ):
        np.testing.assert_array_equal(
            getattr(fast, field), getattr(slow, field), err_msg=field
        )
    assert fast.props == slow.props
    assert fast.mark_names == slow.mark_names
    for i in range(fast.n):
        assert fast.values[i] == slow.values[i], i


def test_fast_path_readback_matches_host():
    forks, t = build_docs()
    log = OpLog.from_changes(collect_changes(forks), fast=True)
    dev = DeviceDoc.resolve(log)
    host = AutoDoc(actor=actor(99))
    for f in forks:
        host.merge(f)
    assert dev.hydrate() == host.hydrate()
    assert dev.text(t) == host.text(t)


def test_roundtrip_through_save_load_bytes():
    """Changes reparsed from saved bytes also take the fast path."""
    forks, t = build_docs()
    saved = [AutoDoc.load(f.save()) for f in forks]
    changes = collect_changes(saved)
    assert all(c.op_col_data is not None for c in changes)
    fast = OpLog.from_changes(changes, fast=True)
    slow = OpLog.from_changes(changes, fast=False)
    assert fast.n == slow.n
    np.testing.assert_array_equal(fast.id_key, slow.id_key)
    dev = DeviceDoc.resolve(fast)
    host = AutoDoc(actor=actor(98))
    for f in saved:
        host.merge(f)
    assert dev.hydrate() == host.hydrate()
