"""Diff/patches: apply_patches(hydrate(before), diff(before, after)) must
equal hydrate(after) for arbitrary histories.

This is the same invariant the reference holds between log_diff and
hydrate::Value::apply_patches (reference: rust/automerge/src/automerge/
diff.rs, hydrate.rs).
"""

import random

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.patches import (
    DeleteMap,
    IncrementPatch,
    Insert,
    Patch,
    PutMap,
    SpliceText,
    apply_patches,
    diff,
)
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i):
    return ActorId(bytes([i]) * 16)


def check_roundtrip(doc, before, after):
    patches = doc.diff(before, after)
    materialized = apply_patches(doc.hydrate(heads=before), patches)
    assert materialized == doc.hydrate(heads=after), patches
    return patches


def test_map_put_delete_update():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "a", 1)
    d.put("_root", "b", "x")
    d.commit()
    h1 = d.get_heads()
    d.put("_root", "a", 2)
    d.delete("_root", "b")
    d.put("_root", "c", True)
    d.commit()
    h2 = d.get_heads()
    patches = check_roundtrip(d, h1, h2)
    kinds = {type(p.action) for p in patches}
    assert kinds == {PutMap, DeleteMap}


def test_counter_increment_patch():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "c", ScalarValue("counter", 10))
    d.commit()
    h1 = d.get_heads()
    d.increment("_root", "c", 5)
    d.increment("_root", "c", -2)
    d.commit()
    h2 = d.get_heads()
    patches = check_roundtrip(d, h1, h2)
    assert patches == [Patch("_root", [], IncrementPatch("c", 3))]


def test_text_splice_patches():
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello world")
    d.commit()
    h1 = d.get_heads()
    d.splice_text(t, 5, 0, " there,")
    d.splice_text(t, 0, 5, "goodbye")
    d.commit()
    h2 = d.get_heads()
    check_roundtrip(d, h1, h2)


def test_empty_before_materializes_everything():
    d = AutoDoc(actor=actor(1))
    m = d.put_object("_root", "m", ObjType.MAP)
    d.put(m, "x", 1)
    lst = d.put_object(m, "l", ObjType.LIST)
    d.insert(lst, 0, "a")
    d.commit()
    h = d.get_heads()
    patches = d.diff([], h)
    materialized = apply_patches({}, patches)
    assert materialized == d.hydrate()


def test_list_insert_delete_put():
    d = AutoDoc(actor=actor(1))
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        d.insert(lst, i, i)
    d.commit()
    h1 = d.get_heads()
    d.delete(lst, 0)
    d.insert(lst, 2, "mid")
    d.put(lst, 0, "replaced")
    d.commit()
    h2 = d.get_heads()
    check_roundtrip(d, h1, h2)


def test_nested_object_changes():
    d = AutoDoc(actor=actor(1))
    m = d.put_object("_root", "cfg", ObjType.MAP)
    d.put(m, "x", 1)
    d.commit()
    h1 = d.get_heads()
    d.put(m, "x", 2)
    inner = d.put_object(m, "inner", ObjType.MAP)
    d.put(inner, "deep", "v")
    d.commit()
    h2 = d.get_heads()
    patches = check_roundtrip(d, h1, h2)
    # nested object path points through the parent
    assert any(p.path and p.path[0][1] == "cfg" for p in patches)


def test_merge_diff():
    """Diff across a merge shows the remote edits."""
    a = AutoDoc(actor=actor(1))
    t = a.put_object("_root", "t", ObjType.TEXT)
    a.splice_text(t, 0, 0, "shared")
    a.commit()
    b = a.fork(actor=actor(2))
    b.splice_text(t, 6, 0, " +remote")
    b.commit()
    h1 = a.get_heads()
    a.merge(b)
    h2 = a.get_heads()
    check_roundtrip(a, h1, h2)


def test_diff_reverse_direction():
    """Diff works backwards in time too (after < before)."""
    d = AutoDoc(actor=actor(1))
    d.put("_root", "k", 1)
    d.commit()
    h1 = d.get_heads()
    d.put("_root", "k", 2)
    d.put("_root", "extra", True)
    d.commit()
    h2 = d.get_heads()
    patches = d.diff(h2, h1)
    materialized = apply_patches(d.hydrate(heads=h2), patches)
    assert materialized == d.hydrate(heads=h1) == {"k": 1}


def test_diff_incremental_cursor():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "a", 1)
    d.commit()
    first = d.diff_incremental()
    materialized = apply_patches({}, first)
    assert materialized == {"a": 1}
    d.put("_root", "b", 2)
    d.commit()
    second = d.diff_incremental()
    materialized = apply_patches(materialized, second)
    assert materialized == {"a": 1, "b": 2}
    assert d.diff_incremental() == []


def test_conflict_put_carries_flag():
    base = AutoDoc(actor=actor(1))
    base.put("_root", "k", "base")
    base.commit()
    b = base.fork(actor=actor(2))
    base.put("_root", "k", "a-side")
    base.commit()
    b.put("_root", "k", "b-side")
    b.commit()
    h1 = base.get_heads()
    base.merge(b)
    h2 = base.get_heads()
    patches = check_roundtrip(base, h1, h2)
    puts = [p for p in patches if isinstance(p.action, PutMap)]
    assert puts and puts[0].action.conflict


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_history_roundtrip(seed):
    rng = random.Random(seed)
    d = AutoDoc(actor=actor(1))
    t = d.put_object("_root", "t", ObjType.TEXT)
    lst = d.put_object("_root", "l", ObjType.LIST)
    d.put("_root", "c", ScalarValue("counter", 0))
    d.commit()
    heads = [d.get_heads()]
    for _ in range(6):
        for _ in range(5):
            r = rng.random()
            if r < 0.35:
                ln = d.length(t)
                if rng.random() < 0.7 or ln == 0:
                    d.splice_text(t, rng.randrange(ln + 1), 0, rng.choice("abcdef"))
                else:
                    d.splice_text(t, rng.randrange(ln), 1, "")
            elif r < 0.6:
                ln = d.length(lst)
                if rng.random() < 0.6 or ln == 0:
                    d.insert(lst, rng.randrange(ln + 1), rng.randrange(100))
                else:
                    d.delete(lst, rng.randrange(ln))
            elif r < 0.8:
                d.put("_root", rng.choice("xyz"), rng.randrange(100))
            else:
                d.increment("_root", "c", rng.randrange(1, 5))
        d.commit()
        heads.append(d.get_heads())
    # every pair of snapshots roundtrips, both directions
    for i in range(len(heads)):
        for j in range(len(heads)):
            check_roundtrip(d, heads[i], heads[j])
