"""The concurrent serving layer: per-document shards, socket transport,
group-commit durability, sync coalescing, backpressure.

Three layers: ShardPool units (ordering/bounding/parallelism), in-process
``SocketRpcServer`` integration over real sockets with concurrent client
threads, and the group-commit durability contract (fsync amortization
plus a crashsim sweep in test_durability.py proving the acked-prefix
guarantee survives batching).
"""

import base64
import json
import socket
import threading
import time

import pytest

from automerge_tpu import obs
from automerge_tpu import trace
from automerge_tpu.api import AutoDoc
from automerge_tpu.serve import QueueFull, ShardPool, SocketRpcServer
from automerge_tpu.types import ActorId


# -- ShardPool units ----------------------------------------------------------


def test_shard_pool_per_key_fifo_and_cross_key_parallel():
    """Items for one key execute in submission order (even across many
    drains); two keys can be in flight on two workers at once."""
    order = {"a": [], "b": []}
    in_flight = set()
    overlap = []
    lock = threading.Lock()
    both_in = threading.Event()

    def execute(key, items):
        with lock:
            in_flight.add(key)
            if len(in_flight) == 2:
                overlap.append(True)
                both_in.set()
        if 0 in items:
            # each key's FIRST batch parks until both keys are in flight
            # (or the 2s timeout proves they never overlap)
            both_in.wait(2)
        order[key].extend(items)
        with lock:
            in_flight.discard(key)

    pool = ShardPool(execute, workers=2, max_queue=64, max_batch=4)
    for i in range(16):
        pool.submit("a", i)
        pool.submit("b", i)
    pool.stop(drain=True)
    assert order["a"] == list(range(16))
    assert order["b"] == list(range(16))
    assert overlap, "two keys never executed concurrently"


def test_shard_pool_backpressure_raises_queue_full():
    blocker = threading.Event()
    started = threading.Event()

    def execute(key, items):
        started.set()
        blocker.wait(10)

    pool = ShardPool(execute, workers=1, max_queue=2, max_batch=1)
    pool.submit("d", 0)
    started.wait(5)  # worker is now stuck holding item 0
    pool.submit("d", 1)
    pool.submit("d", 2)
    with pytest.raises(QueueFull):
        pool.submit("d", 3)
    blocker.set()
    pool.stop(drain=True)


def test_shard_pool_single_writer_per_key():
    """Even with many workers, one key is never executed by two workers
    at once — the single-writer guarantee documents rely on."""
    active = []
    bad = []
    lock = threading.Lock()

    def execute(key, items):
        with lock:
            if key in active:
                bad.append(key)
            active.append(key)
        time.sleep(0.001)
        with lock:
            active.remove(key)

    pool = ShardPool(execute, workers=8, max_queue=512, max_batch=2)
    for i in range(64):
        pool.submit("hot", i)
        pool.submit(f"cold{i % 4}", i)
    pool.stop(drain=True)
    assert not bad


# -- socket server integration ------------------------------------------------


class Client:
    """Minimal pipelining JSON-RPC socket client for the tests."""

    def __init__(self, address):
        self.sock = socket.create_connection(address)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.f = self.sock.makefile("r")
        self.rid = 0

    def pipeline(self, reqs, allow_errors=False):
        first = self.rid + 1
        lines = []
        for method, params in reqs:
            self.rid += 1
            lines.append(json.dumps(
                {"id": self.rid, "method": method, "params": params}))
        self.sock.sendall(("\n".join(lines) + "\n").encode())
        by = {}
        while len(by) < len(reqs):
            resp = json.loads(self.f.readline())
            if not allow_errors:
                assert "error" not in resp, resp
            by[resp["id"]] = resp
        return [by[first + i] for i in range(len(reqs))]

    def call(self, method, **params):
        resp = self.pipeline([(method, params)])[0]
        return resp.get("result")

    def close(self):
        self.sock.close()


@pytest.fixture
def server(tmp_path):
    srv = SocketRpcServer(
        host="127.0.0.1", port=0, durable_dir=str(tmp_path), workers=4
    )
    srv.start()
    yield srv
    srv.stop()


def test_concurrent_clients_distinct_docs(server):
    """Clients editing different documents run in parallel and none of
    the frames garble or drop."""
    errs = []

    def one(ci):
        try:
            c = Client(server.address)
            d = c.call("create", actor=f"{ci:02x}" * 16)["doc"]
            for k in range(30):
                c.call("put", doc=d, obj="_root", prop=f"k{k}", value=k)
            c.call("commit", doc=d)
            assert c.call("length", doc=d, obj="_root") == 30
            c.close()
        except Exception as e:  # noqa: BLE001 — surface in main thread
            errs.append(f"{ci}: {e}")

    ts = [threading.Thread(target=one, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


def test_same_doc_requests_keep_arrival_order(server):
    """Pipelined writes to one doc apply in order: the final read sees
    the last write, and a historical read at each commit is consistent."""
    c = Client(server.address)
    d = c.call("create")["doc"]
    reqs = []
    for k in range(50):
        reqs.append(("put", {"doc": d, "obj": "_root", "prop": "x",
                             "value": k}))
    reqs.append(("commit", {"doc": d}))
    reqs.append(("get", {"doc": d, "obj": "_root", "prop": "x"}))
    resps = c.pipeline(reqs)
    assert resps[-1]["result"] == 49
    c.close()


def test_group_commit_amortizes_fsyncs(server):
    """The acceptance gate: >=4 concurrent committers against ONE durable
    doc, journal fsync count strictly below the commit-request count
    (journal.fsync{policy} span counter), and every acked key durable
    after reopening the directory."""
    trace.reset_timers()
    n_clients, n_commits = 4, 8
    errs = []

    def committer(ci):
        try:
            c = Client(server.address)
            d = c.call("openDurable", name="grp")["doc"]
            reqs = []
            for k in range(n_commits):
                reqs.append(("put", {"doc": d, "obj": "_root",
                                     "prop": f"c{ci}_{k}", "value": k}))
                reqs.append(("commit", {"doc": d}))
            c.pipeline(reqs)
            c.close()
        except Exception as e:  # noqa: BLE001
            errs.append(f"{ci}: {e}")

    ts = [threading.Thread(target=committer, args=(i,))
          for i in range(n_clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    total_commit_requests = n_clients * n_commits
    fsyncs = trace.timing_summary().get("journal.fsync", {}).get("n", 0)
    assert 0 < fsyncs < total_commit_requests, (
        f"{fsyncs} fsyncs for {total_commit_requests} commit requests — "
        "group commit did not amortize"
    )
    # the batch-size histogram saw at least one multi-append fsync
    h = obs.registry.histogram("group_commit.batch_size")
    assert h.n > 0 and h.vmax >= 2, (h.n, h.vmax)
    # durability: close via server stop, then reopen and check every key
    server.stop()
    dd = AutoDoc.open(str(server.rpc.durable_dir) + "/grp")
    keys = set(dd.keys())
    missing = [
        f"c{ci}_{k}" for ci in range(n_clients) for k in range(n_commits)
        if f"c{ci}_{k}" not in keys
    ]
    dd.close()
    assert not missing, missing


def test_backpressure_error_surfaces_and_server_survives(tmp_path):
    """A full per-doc queue answers Backpressure immediately; the dropped
    requests are visible in rpc.errors and the server keeps serving."""
    srv = SocketRpcServer(
        host="127.0.0.1", port=0, durable_dir=str(tmp_path),
        workers=1, max_queue=4, max_batch=1,
    )
    srv.start()
    try:
        c = Client(srv.address)
        d = c.call("openDurable", name="bp")["doc"]  # fsync=always: slow
        reqs = []
        for k in range(60):
            reqs.append(("put", {"doc": d, "obj": "_root",
                                 "prop": f"k{k}", "value": k}))
            reqs.append(("commit", {"doc": d}))
        resps = c.pipeline(reqs, allow_errors=True)
        kinds = [
            r["error"]["type"] if "error" in r else "ok" for r in resps
        ]
        assert "Backpressure" in kinds, kinds[:20]
        assert "ok" in kinds
        # nothing else leaked out of the queue bound
        assert set(kinds) <= {"ok", "Backpressure"}, set(kinds)
        # the server still answers new work afterwards
        assert c.call("length", doc=d, obj="_root") >= 1
        c.close()
    finally:
        srv.stop()


def test_merge_across_shards_under_concurrent_edits(server):
    """merge(doc, other) locks both documents (sorted order): racing
    edits to the source never corrupt the merge target."""
    c = Client(server.address)
    a = c.call("create", actor="aa" * 16)["doc"]
    b = c.call("create", actor="bb" * 16)["doc"]
    c.call("put", doc=b, obj="_root", prop="seed", value=1)
    c.call("commit", doc=b)
    errs = []
    stop = threading.Event()

    def editor():
        try:
            c2 = Client(server.address)
            k = 0
            while not stop.is_set():
                c2.call("put", doc=b, obj="_root", prop=f"e{k}", value=k)
                c2.call("commit", doc=b)
                k += 1
            c2.close()
        except Exception as e:  # noqa: BLE001
            errs.append(str(e))

    t = threading.Thread(target=editor)
    t.start()
    try:
        for _ in range(10):
            c.call("merge", doc=a, other=b)
    finally:
        stop.set()
        t.join()
    assert not errs, errs
    assert c.call("get", doc=a, obj="_root", prop="seed") == 1
    c.close()


def test_receive_sync_coalescing_feeds_device_once(server):
    """A pipelined run of receiveSyncMessage frames for one durable
    device doc coalesces the resident-device feed into apply_batches;
    the device log ends exactly in sync with the host history."""
    c = Client(server.address)
    d = c.call("openDurable", name="dev", device=True)["doc"]
    # three peers, each pushing its own changes through the sync protocol
    peers = []
    for i in range(3):
        p = c.call("create", actor=f"{i + 1:02x}" * 16)["doc"]
        for k in range(4):
            c.call("put", doc=p, obj="_root", prop=f"p{i}_{k}", value=k)
        c.call("commit", doc=p)
        sp = c.call("syncStateNew")["sync"]
        sd = c.call("syncStateNew")["sync"]
        peers.append((p, sp, sd))
    trace.reset_counters()
    # drive rounds; each round pipelines every peer's frame so the runs
    # are adjacent in the doc's queue
    for _ in range(10):
        frames = []
        for p, sp, sd in peers:
            m = c.call("generateSyncMessage", doc=p, sync=sp)
            if m is not None:
                frames.append(("receiveSyncMessage",
                               {"doc": d, "sync": sd, "data": m}))
        if not frames:
            break
        c.pipeline(frames)
        for p, sp, sd in peers:
            back = c.call("generateSyncMessage", doc=d, sync=sd)
            if back is not None:
                c.call("receiveSyncMessage", doc=p, sync=sp, data=back)
    # host absorbed every peer's keys
    keys = c.call("keys", doc=d, obj="_root")
    for i in range(3):
        for k in range(4):
            assert f"p{i}_{k}" in keys
    # the resident device doc tracked the host exactly
    dd = server.rpc._docs[d]
    assert dd.device_doc is not None
    assert len(dd.device_doc.log.changes) == len(dd.doc.history)
    assert trace.counters.get("rpc.coalesced", 0) >= 2
    c.close()


def test_hostile_frames_over_socket(server):
    """Garbled JSON, oversized lines and unknown methods answer errors
    over the socket without killing the connection or the server."""
    c = Client(server.address)
    c.call("configure", maxRequestBytes=4096)
    c.sock.sendall(b"this is not json\n")
    resp = json.loads(c.f.readline())
    assert resp["error"]["type"] == "ParseError"
    c.sock.sendall(b"Z" * 10_000 + b"\n")
    resp = json.loads(c.f.readline())
    assert resp["error"]["type"] == "RequestTooLarge"
    assert c.call("create")["doc"] >= 1  # connection still serves
    c.close()


def test_shutdown_request_flushes_and_releases(tmp_path):
    """The shutdown ack means: durable docs flushed, flocks released."""
    srv = SocketRpcServer(host="127.0.0.1", port=0,
                          durable_dir=str(tmp_path), workers=2)
    srv.start()
    c = Client(srv.address)
    d = c.call("openDurable", name="sd")["doc"]
    c.call("put", doc=d, obj="_root", prop="n", value=7)  # no commit
    assert c.call("shutdown") is None
    srv.wait_stopped(10)
    # the pending autocommit tx was flushed and the flock released
    dd = AutoDoc.open(str(tmp_path / "sd"))
    assert dd.hydrate() == {"n": 7}
    dd.close()
    c.close()


def test_unix_socket_transport(tmp_path):
    srv = SocketRpcServer(unix_path=str(tmp_path / "rpc.sock"), workers=2)
    srv.start()
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(str(tmp_path / "rpc.sock"))
        f = sock.makefile("r")
        sock.sendall(b'{"id":1,"method":"create","params":{}}\n')
        assert json.loads(f.readline())["result"]["doc"] == 1
        sock.close()
    finally:
        srv.stop()
    assert not (tmp_path / "rpc.sock").exists()  # socket file cleaned up


# -- transport-death visibility (stdio satellite) -----------------------------


def test_stdio_transport_death_is_counted():
    """A read or write failure on the stdio loop increments
    rpc.errors{type=transport} instead of dying silently."""
    from automerge_tpu.rpc import RpcServer

    class Exploding:
        def readline(self, limit=None):
            raise OSError("carrier lost")

    trace.reset_counters()
    RpcServer().serve(stdin=Exploding(), stdout=None)
    assert trace.counters.get("rpc.errors", 0) >= 1

    class OkOnce:
        def __init__(self):
            self.lines = ['{"id":1,"method":"create"}\n', ""]

        def readline(self, limit=None):
            return self.lines.pop(0)

    class BrokenOut:
        def write(self, s):
            raise BrokenPipeError("gone")

        def flush(self):
            pass

    before = trace.counters.get("rpc.errors", 0)
    RpcServer().serve(stdin=OkOnce(), stdout=BrokenOut())
    assert trace.counters.get("rpc.errors", 0) > before


# -- sync session coalescing unit --------------------------------------------


def test_session_receive_many_batches_device_feed():
    """receive_many defers per-message device feeds into ONE
    apply_batches call with one batch per message carrying changes."""
    from automerge_tpu.sync import SyncSession

    a = AutoDoc(actor=ActorId(bytes([1]) * 16))
    b = AutoDoc(actor=ActorId(bytes([2]) * 16))
    for i in range(3):
        a.put("_root", f"k{i}", i)
        a.commit()

    class RecordingDev:
        def __init__(self):
            self.batch_calls = []
            self.change_calls = []

        def apply_batches(self, batches):
            self.batch_calls.append([len(x) for x in batches])

        def apply_changes(self, changes):
            self.change_calls.append(len(changes))

    dev = RecordingDev()
    sa = SyncSession(a, epoch=1)
    sb = SyncSession(b, epoch=2, device_doc=dev)
    # run rounds, but deliver a->b frames through receive_many in groups
    pending = []
    for now in range(40):
        fa = sa.poll(now)
        if fa is not None:
            pending.append(fa)
        if len(pending) >= 2 or (fa is None and pending):
            sb.receive_many(list(pending), now)
            pending.clear()
        fb = sb.poll(now)
        if fb is not None:
            sa.receive(fb, now)
        if sa.converged() and sb.converged():
            break
    assert a.get_heads() == b.get_heads()
    # every change reached the device through the batched path only
    assert dev.batch_calls and not dev.change_calls
    total = sum(n for call in dev.batch_calls for n in call)
    assert total == len(b.doc.history)


def test_socket_session_resumes_across_server_restart(tmp_path):
    """The epoch-handshake restart-resume contract over the SOCKET
    transport: a client syncs with a durable server session
    (syncSessionAttach), the server process dies and restarts on the
    same directory, the client reconnects and re-attaches — the bumped
    epoch renegotiates from the persisted shared_heads and the session
    converges again with ZERO full resyncs on either side."""
    from automerge_tpu.sync import SessionConfig, SyncSession

    def drive(client_sess, c, server_session, rounds=60):
        """Pump frames between the in-process client session and the
        server session behind the RPC surface until both converge."""
        for now in range(rounds):
            frame = client_sess.poll(float(now))
            if frame is not None:
                c.call("syncSessionReceive", session=server_session,
                       data=base64.b64encode(frame).decode())
            back = c.call("syncSessionPoll", session=server_session)
            if back is not None:
                client_sess.receive(base64.b64decode(back), float(now))
            stats = c.call("syncSessionStats", session=server_session)
            if client_sess.converged() and stats["converged"]:
                return stats
        raise AssertionError("sessions never converged")

    local = AutoDoc(actor=ActorId(bytes([5]) * 16))
    for i in range(4):
        local.put("_root", f"pre{i}", i)
        local.commit()
    sess = SyncSession(local, epoch=1, config=SessionConfig(timeout=1000.0))

    srv = SocketRpcServer(
        host="127.0.0.1", port=0, durable_dir=str(tmp_path), workers=2
    )
    srv.start()
    c = Client(srv.address)
    d = c.call("openDurable", name="resume")["doc"]
    att = c.call("syncSessionAttach", doc=d, peer="client-A")
    stats = drive(sess, c, att["session"])
    assert stats["resyncs"] == 0 and sess.stats["resyncs"] == 0
    first_epoch = att["epoch"]
    c.close()
    srv.stop()

    # restart on the same directory; the client keeps ITS live session
    srv2 = SocketRpcServer(
        host="127.0.0.1", port=0, durable_dir=str(tmp_path), workers=2
    )
    srv2.start()
    try:
        c2 = Client(srv2.address)
        d2 = c2.call("openDurable", name="resume")["doc"]
        att2 = c2.call("syncSessionAttach", doc=d2, peer="client-A")
        # a new incarnation MUST present a new epoch or the client's dup
        # suppression would eat its frames
        assert att2["epoch"] > first_epoch
        local.put("_root", "post", "after-restart")
        local.commit()
        stats = drive(sess, c2, att2["session"])
        # the epoch handshake renegotiated (a reset happened) but nobody
        # fell back to a FULL resync
        assert stats["resyncs"] == 0, stats
        assert sess.stats["resyncs"] == 0, sess.stats
        assert sess.stats["resets"] >= 1  # the epoch bump was noticed
        assert c2.call("get", doc=d2, obj="_root", prop="post") \
            == "after-restart"
        assert c2.call("get", doc=d2, obj="_root", prop="pre2") == 2
        c2.close()
    finally:
        srv2.stop()
