"""The JSON-RPC stdio frontend (the wasm-module analogue: an embedding
boundary another language runtime drives through marshalled calls,
reference: rust/automerge-wasm/src/lib.rs).

Two layers of tests: in-process RpcServer dispatch (fast, covers the
method surface + error shape) and a real subprocess session driving two
peers to convergence over the wire — the frontend as an actual separate
process, as an embedder would run it.
"""

import json
import os
import subprocess
import sys

import pytest

from automerge_tpu.rpc import RpcServer


def call(srv, method, **params):
    resp = srv.handle({"id": 1, "method": method, "params": params})
    assert "error" not in resp, resp
    return resp["result"]


def test_inprocess_document_surface():
    srv = RpcServer()
    d = call(srv, "create", actor="01" * 16)["doc"]
    t = call(srv, "putObject", doc=d, obj="_root", prop="t", type="text")["$obj"]
    call(srv, "spliceText", doc=d, obj=t, pos=0, text="hello")
    call(srv, "put", doc=d, obj="_root", prop="n", value={"$counter": 5})
    call(srv, "put", doc=d, obj="_root", prop="b", value={"$bytes": "AAEC"})
    lst = call(srv, "putObject", doc=d, obj="_root", prop="l", type="list")["$obj"]
    call(srv, "insert", doc=d, obj=lst, index=0, value=1)
    call(srv, "insert", doc=d, obj=lst, index=1, value="two")
    h1 = call(srv, "commit", doc=d)
    assert h1
    heads1 = call(srv, "heads", doc=d)

    call(srv, "increment", doc=d, obj="_root", prop="n", by=2)
    call(srv, "spliceText", doc=d, obj=t, pos=5, text=" world")
    call(srv, "mark", doc=d, obj=t, start=0, end=5, name="bold", value=True)
    call(srv, "commit", doc=d)

    assert call(srv, "text", doc=d, obj=t) == "hello world"
    assert call(srv, "get", doc=d, obj="_root", prop="n") == {"$counter": 7}
    assert call(srv, "get", doc=d, obj="_root", prop="b") == {"$bytes": "AAEC"}
    assert call(srv, "length", doc=d, obj=lst) == 2
    assert call(srv, "keys", doc=d, obj="_root") == ["b", "l", "n", "t"]
    assert call(srv, "marks", doc=d, obj=t) == [
        {"start": 0, "end": 5, "name": "bold", "value": True}
    ]
    # historical reads + fork at heads
    assert call(srv, "text", doc=d, obj=t, heads=heads1) == "hello"
    assert call(srv, "get", doc=d, obj="_root", prop="n", heads=heads1) == {
        "$counter": 5
    }
    old = call(srv, "fork", doc=d, heads=heads1)["doc"]
    assert call(srv, "text", doc=old, obj=t) == "hello"
    # materialize
    m = call(srv, "materialize", doc=d)
    assert m["t"] == "hello world" and m["l"] == [1, "two"]
    # save / load roundtrip
    data = call(srv, "save", doc=d)
    d2 = call(srv, "load", data=data)["doc"]
    assert call(srv, "text", doc=d2, obj=t) == "hello world"
    # errors answer, never raise
    resp = srv.handle({"id": 9, "method": "get", "params": {"doc": 999, "obj": "_root", "prop": "x"}})
    assert resp["error"]["type"] == "ValueError"
    resp = srv.handle({"id": 10, "method": "nope", "params": {}})
    assert resp["error"]["type"] == "UnknownMethod"


def test_inprocess_patches_and_sync():
    srv = RpcServer()
    a = call(srv, "create", actor="01" * 16)["doc"]
    b = call(srv, "create", actor="02" * 16)["doc"]
    t = call(srv, "putObject", doc=a, obj="_root", prop="t", type="text")["$obj"]
    call(srv, "spliceText", doc=a, obj=t, pos=0, text="sync me")
    call(srv, "commit", doc=a)

    assert call(srv, "popPatches", doc=b) == []  # activates
    sa = call(srv, "syncStateNew")["sync"]
    sb = call(srv, "syncStateNew")["sync"]
    for _ in range(20):
        ma = call(srv, "generateSyncMessage", doc=a, sync=sa)
        mb = call(srv, "generateSyncMessage", doc=b, sync=sb)
        if ma is None and mb is None:
            break
        if ma is not None:
            call(srv, "receiveSyncMessage", doc=b, sync=sb, data=ma)
        if mb is not None:
            call(srv, "receiveSyncMessage", doc=a, sync=sa, data=mb)
    assert call(srv, "heads", doc=a) == call(srv, "heads", doc=b)
    patches = call(srv, "popPatches", doc=b)
    assert any(p["action"] == "PutMap" for p in patches)
    # sync state survives encode/decode
    enc = call(srv, "syncStateEncode", sync=sa)
    sa2 = call(srv, "syncStateDecode", data=enc)["sync"]
    assert call(srv, "generateSyncMessage", doc=a, sync=sa2) is not None


@pytest.mark.skipif(os.name != "posix", reason="subprocess stdio test")
def test_subprocess_two_peer_session():
    """Drive the frontend as a real separate process, like an embedder."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "automerge_tpu.rpc"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    rid = [0]

    def rpc(method, **params):
        rid[0] += 1
        proc.stdin.write(json.dumps({"id": rid[0], "method": method, "params": params}) + "\n")
        proc.stdin.flush()
        resp = json.loads(proc.stdout.readline())
        assert resp["id"] == rid[0]
        assert "error" not in resp, resp
        return resp["result"]

    try:
        a = rpc("create", actor="0a" * 16)["doc"]
        t = rpc("putObject", doc=a, obj="_root", prop="t", type="text")["$obj"]
        rpc("spliceText", doc=a, obj=t, pos=0, text="over the wire")
        rpc("commit", doc=a)
        saved = rpc("save", doc=a)
        b = rpc("load", data=saved)["doc"]
        rpc("spliceText", doc=b, obj=t, pos=0, text=">> ")
        rpc("commit", doc=b)
        rpc("merge", doc=a, other=b)
        assert rpc("text", doc=a, obj=t) == ">> over the wire"
        rpc("shutdown")
    finally:
        proc.stdin.close()
        assert proc.wait(timeout=60) == 0


def test_server_survives_hostile_and_binary_inputs():
    """Review regressions: non-object JSON, non-API method names, and raw
    bytes in responses must answer with errors/wrappers, never kill the
    loop."""
    import io

    srv = RpcServer()
    d = call(srv, "create", actor="01" * 16)["doc"]
    call(srv, "put", doc=d, obj="_root", prop="b", value={"$bytes": "AAEC"})
    t = call(srv, "putObject", doc=d, obj="_root", prop="t", type="text")["$obj"]
    call(srv, "spliceText", doc=d, obj=t, pos=0, text="xy")
    call(srv, "mark", doc=d, obj=t, start=0, end=2, name="blob", value=True)
    call(srv, "commit", doc=d)

    lines = [
        "123",                                    # valid JSON, not an object
        "[1,2]",
        "not json at all",
        json.dumps({"id": 0, "method": [1, 2], "params": {}}),  # unhashable
        json.dumps({"id": 1, "method": "serve", "params": {"x": 1}}),
        json.dumps({"id": 2, "method": "handle", "params": {}}),
        json.dumps({"id": 3, "method": "_doc", "params": {}}),
        json.dumps({"id": 4, "method": "materialize", "params": {"doc": d}}),
        json.dumps({"id": 5, "method": "marks", "params": {"doc": d, "obj": t}}),
        json.dumps({"id": 6, "method": "shutdown"}),
    ]
    out = io.StringIO()
    srv.serve(stdin=iter([ln + "\n" for ln in lines]), stdout=out)
    resps = [json.loads(x) for x in out.getvalue().splitlines()]
    assert len(resps) == len(lines)
    assert all("error" in r for r in resps[:3])
    assert resps[3]["error"]["type"] == "UnknownMethod"   # unhashable method
    assert resps[4]["error"]["type"] == "UnknownMethod"   # serve not callable
    assert resps[5]["error"]["type"] == "UnknownMethod"
    assert resps[6]["error"]["type"] == "UnknownMethod"
    assert resps[7]["result"]["b"] == {"$bytes": "AAEC"}  # bytes wrapped
    assert resps[8]["result"][0]["name"] == "blob"
    assert resps[9]["result"] is None                     # clean shutdown


def test_pop_patches_preserves_open_transaction():
    """popPatches must not force-commit: an explicit commit after a pop
    keeps its message, and the pending ops' patches arrive on the NEXT
    pop (reference: wasm popPatches never closes the transaction)."""
    srv = RpcServer()
    d = call(srv, "create", actor="0a" * 16)["doc"]
    call(srv, "popPatches", doc=d)  # pin cursor
    call(srv, "put", doc=d, obj="_root", prop="x", value=1)
    # pop with the transaction still open: nothing committed yet
    assert call(srv, "popPatches", doc=d) == []
    h = call(srv, "commit", doc=d, message="my edit")
    assert h is not None
    doc = srv._docs[d]
    assert doc.doc.history[-1].stored.message == "my edit"
    patches = call(srv, "popPatches", doc=d)
    assert any(p["action"] == "PutMap" and p.get("key") == "x" for p in patches)
