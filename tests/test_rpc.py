"""The JSON-RPC stdio frontend (the wasm-module analogue: an embedding
boundary another language runtime drives through marshalled calls,
reference: rust/automerge-wasm/src/lib.rs).

Two layers of tests: in-process RpcServer dispatch (fast, covers the
method surface + error shape) and a real subprocess session driving two
peers to convergence over the wire — the frontend as an actual separate
process, as an embedder would run it.
"""

import json
import os
import subprocess
import sys

import pytest

from automerge_tpu.rpc import RpcServer


def call(srv, method, **params):
    resp = srv.handle({"id": 1, "method": method, "params": params})
    assert "error" not in resp, resp
    return resp["result"]


def test_inprocess_document_surface():
    srv = RpcServer()
    d = call(srv, "create", actor="01" * 16)["doc"]
    t = call(srv, "putObject", doc=d, obj="_root", prop="t", type="text")["$obj"]
    call(srv, "spliceText", doc=d, obj=t, pos=0, text="hello")
    call(srv, "put", doc=d, obj="_root", prop="n", value={"$counter": 5})
    call(srv, "put", doc=d, obj="_root", prop="b", value={"$bytes": "AAEC"})
    lst = call(srv, "putObject", doc=d, obj="_root", prop="l", type="list")["$obj"]
    call(srv, "insert", doc=d, obj=lst, index=0, value=1)
    call(srv, "insert", doc=d, obj=lst, index=1, value="two")
    h1 = call(srv, "commit", doc=d)
    assert h1
    heads1 = call(srv, "heads", doc=d)

    call(srv, "increment", doc=d, obj="_root", prop="n", by=2)
    call(srv, "spliceText", doc=d, obj=t, pos=5, text=" world")
    call(srv, "mark", doc=d, obj=t, start=0, end=5, name="bold", value=True)
    call(srv, "commit", doc=d)

    assert call(srv, "text", doc=d, obj=t) == "hello world"
    assert call(srv, "get", doc=d, obj="_root", prop="n") == {"$counter": 7}
    assert call(srv, "get", doc=d, obj="_root", prop="b") == {"$bytes": "AAEC"}
    assert call(srv, "length", doc=d, obj=lst) == 2
    assert call(srv, "keys", doc=d, obj="_root") == ["b", "l", "n", "t"]
    assert call(srv, "marks", doc=d, obj=t) == [
        {"start": 0, "end": 5, "name": "bold", "value": True}
    ]
    # historical reads + fork at heads
    assert call(srv, "text", doc=d, obj=t, heads=heads1) == "hello"
    assert call(srv, "get", doc=d, obj="_root", prop="n", heads=heads1) == {
        "$counter": 5
    }
    old = call(srv, "fork", doc=d, heads=heads1)["doc"]
    assert call(srv, "text", doc=old, obj=t) == "hello"
    # materialize
    m = call(srv, "materialize", doc=d)
    assert m["t"] == "hello world" and m["l"] == [1, "two"]
    # save / load roundtrip
    data = call(srv, "save", doc=d)
    d2 = call(srv, "load", data=data)["doc"]
    assert call(srv, "text", doc=d2, obj=t) == "hello world"
    # errors answer, never raise
    resp = srv.handle({"id": 9, "method": "get", "params": {"doc": 999, "obj": "_root", "prop": "x"}})
    assert resp["error"]["type"] == "ValueError"
    resp = srv.handle({"id": 10, "method": "nope", "params": {}})
    assert resp["error"]["type"] == "UnknownMethod"


def test_inprocess_patches_and_sync():
    srv = RpcServer()
    a = call(srv, "create", actor="01" * 16)["doc"]
    b = call(srv, "create", actor="02" * 16)["doc"]
    t = call(srv, "putObject", doc=a, obj="_root", prop="t", type="text")["$obj"]
    call(srv, "spliceText", doc=a, obj=t, pos=0, text="sync me")
    call(srv, "commit", doc=a)

    assert call(srv, "popPatches", doc=b) == []  # activates
    sa = call(srv, "syncStateNew")["sync"]
    sb = call(srv, "syncStateNew")["sync"]
    for _ in range(20):
        ma = call(srv, "generateSyncMessage", doc=a, sync=sa)
        mb = call(srv, "generateSyncMessage", doc=b, sync=sb)
        if ma is None and mb is None:
            break
        if ma is not None:
            call(srv, "receiveSyncMessage", doc=b, sync=sb, data=ma)
        if mb is not None:
            call(srv, "receiveSyncMessage", doc=a, sync=sa, data=mb)
    assert call(srv, "heads", doc=a) == call(srv, "heads", doc=b)
    patches = call(srv, "popPatches", doc=b)
    assert any(p["action"] == "PutMap" for p in patches)
    # sync state survives encode/decode
    enc = call(srv, "syncStateEncode", sync=sa)
    sa2 = call(srv, "syncStateDecode", data=enc)["sync"]
    assert call(srv, "generateSyncMessage", doc=a, sync=sa2) is not None


@pytest.mark.skipif(os.name != "posix", reason="subprocess stdio test")
def test_subprocess_two_peer_session():
    """Drive the frontend as a real separate process, like an embedder."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "automerge_tpu.rpc"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    rid = [0]

    def rpc(method, **params):
        rid[0] += 1
        proc.stdin.write(json.dumps({"id": rid[0], "method": method, "params": params}) + "\n")
        proc.stdin.flush()
        resp = json.loads(proc.stdout.readline())
        assert resp["id"] == rid[0]
        assert "error" not in resp, resp
        return resp["result"]

    try:
        a = rpc("create", actor="0a" * 16)["doc"]
        t = rpc("putObject", doc=a, obj="_root", prop="t", type="text")["$obj"]
        rpc("spliceText", doc=a, obj=t, pos=0, text="over the wire")
        rpc("commit", doc=a)
        saved = rpc("save", doc=a)
        b = rpc("load", data=saved)["doc"]
        rpc("spliceText", doc=b, obj=t, pos=0, text=">> ")
        rpc("commit", doc=b)
        rpc("merge", doc=a, other=b)
        assert rpc("text", doc=a, obj=t) == ">> over the wire"
        rpc("shutdown")
    finally:
        proc.stdin.close()
        assert proc.wait(timeout=60) == 0


def test_server_survives_hostile_and_binary_inputs():
    """Review regressions: non-object JSON, non-API method names, and raw
    bytes in responses must answer with errors/wrappers, never kill the
    loop."""
    import io

    srv = RpcServer()
    d = call(srv, "create", actor="01" * 16)["doc"]
    call(srv, "put", doc=d, obj="_root", prop="b", value={"$bytes": "AAEC"})
    t = call(srv, "putObject", doc=d, obj="_root", prop="t", type="text")["$obj"]
    call(srv, "spliceText", doc=d, obj=t, pos=0, text="xy")
    call(srv, "mark", doc=d, obj=t, start=0, end=2, name="blob", value=True)
    call(srv, "commit", doc=d)

    lines = [
        "123",                                    # valid JSON, not an object
        "[1,2]",
        "not json at all",
        json.dumps({"id": 0, "method": [1, 2], "params": {}}),  # unhashable
        json.dumps({"id": 1, "method": "serve", "params": {"x": 1}}),
        json.dumps({"id": 2, "method": "handle", "params": {}}),
        json.dumps({"id": 3, "method": "_doc", "params": {}}),
        json.dumps({"id": 4, "method": "materialize", "params": {"doc": d}}),
        json.dumps({"id": 5, "method": "marks", "params": {"doc": d, "obj": t}}),
        json.dumps({"id": 6, "method": "shutdown"}),
    ]
    out = io.StringIO()
    srv.serve(stdin=iter([ln + "\n" for ln in lines]), stdout=out)
    resps = [json.loads(x) for x in out.getvalue().splitlines()]
    assert len(resps) == len(lines)
    assert all("error" in r for r in resps[:3])
    assert resps[3]["error"]["type"] == "UnknownMethod"   # unhashable method
    assert resps[4]["error"]["type"] == "UnknownMethod"   # serve not callable
    assert resps[5]["error"]["type"] == "UnknownMethod"
    assert resps[6]["error"]["type"] == "UnknownMethod"
    assert resps[7]["result"]["b"] == {"$bytes": "AAEC"}  # bytes wrapped
    assert resps[8]["result"][0]["name"] == "blob"
    assert resps[9]["result"] is None                     # clean shutdown


def test_pop_patches_preserves_open_transaction():
    """popPatches must not force-commit: an explicit commit after a pop
    keeps its message, and the pending ops' patches arrive on the NEXT
    pop (reference: wasm popPatches never closes the transaction)."""
    srv = RpcServer()
    d = call(srv, "create", actor="0a" * 16)["doc"]
    call(srv, "popPatches", doc=d)  # pin cursor
    call(srv, "put", doc=d, obj="_root", prop="x", value=1)
    # pop with the transaction still open: nothing committed yet
    assert call(srv, "popPatches", doc=d) == []
    h = call(srv, "commit", doc=d, message="my edit")
    assert h is not None
    doc = srv._docs[d]
    assert doc.doc.history[-1].stored.message == "my edit"
    patches = call(srv, "popPatches", doc=d)
    assert any(p["action"] == "PutMap" and p.get("key") == "x" for p in patches)


# -- server hostility: malformed frames must never kill the process ----------

def test_hostile_invalid_json_and_unknown_method_and_missing_id():
    import io

    srv = RpcServer()
    lines = [
        '{"not json',                                   # invalid JSON
        '{"id": 1, "method": "noSuchMethod"}',          # unknown method
        '{"method": "heads", "params": {"doc": 1}}',    # missing id
        '{"id": 2, "method": "load", "params": {"data": "!!!not-base64!!"}}',
        '{"id": 3, "method": "create", "params": {"actor": "zz"}}',  # bad hex
        '{"id": 4, "method": "create"}',                # still alive?
    ]
    out = io.StringIO()
    srv.serve(stdin=iter([ln + "\n" for ln in lines]), stdout=out)
    resps = [json.loads(x) for x in out.getvalue().splitlines()]
    assert len(resps) == len(lines)
    assert resps[0]["error"]["type"] == "ParseError"
    assert resps[1]["error"]["type"] == "UnknownMethod"
    # a request without an id still answers (id echoes back as null)
    assert "error" in resps[2] and resps[2]["id"] is None
    assert "error" in resps[3]
    assert "error" in resps[4]
    assert resps[5]["result"]["doc"] == 1  # server state intact throughout


def test_hostile_oversized_payload_rejected_without_dying():
    import io

    srv = RpcServer()
    lines = [
        '{"id": 1, "method": "configure", "params": {"maxRequestBytes": 1024}}',
        json.dumps({"id": 2, "method": "load",
                    "params": {"data": "A" * 4096}}),   # oversized base64
        '{"id": 3, "method": "create"}',                # still alive
    ]
    out = io.StringIO()
    srv.serve(stdin=iter([ln + "\n" for ln in lines]), stdout=out)
    resps = [json.loads(x) for x in out.getvalue().splitlines()]
    assert resps[0]["result"]["maxRequestBytes"] == 1024
    assert resps[1]["error"]["type"] == "RequestTooLarge"
    assert resps[2]["result"]["doc"] == 1


def test_configure_rejects_nonsense():
    srv = RpcServer()
    resp = srv.handle({"id": 1, "method": "configure",
                       "params": {"syncTimeoutMs": -5}})
    assert "error" in resp
    resp = srv.handle({"id": 2, "method": "configure",
                       "params": {"maxRequestBytes": "many"}})
    assert "error" in resp
    out = call(srv, "configure", syncTimeoutMs=250)
    assert out["syncTimeoutMs"] == 250


@pytest.mark.skipif(os.name != "posix", reason="subprocess stdio test")
def test_hostile_subprocess_mid_request_eof_clean_shutdown():
    """Cutting the connection in the middle of a request must end the
    process cleanly (exit 0), not hang or traceback."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "automerge_tpu.rpc"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    proc.stdin.write('{"id": 1, "method": "create"}\n')
    proc.stdin.write('{"id": 2, "method": "put", "par')  # cut mid-request
    proc.stdin.flush()
    proc.stdin.close()
    assert proc.wait(timeout=60) == 0
    lines = proc.stdout.read().splitlines()
    assert json.loads(lines[0])["result"]["doc"] == 1
    assert proc.stderr.read() == ""


def test_sync_session_rpc_surface():
    """Two peers over the resilient session RPC: corrupt and duplicated
    frames are absorbed; the docs still converge."""
    srv = RpcServer()
    call(srv, "configure", syncTimeoutMs=100)
    a = call(srv, "create", actor="01" * 16)["doc"]
    b = call(srv, "create", actor="02" * 16)["doc"]
    call(srv, "put", doc=a, obj="_root", prop="from_a", value=1)
    call(srv, "commit", doc=a)
    call(srv, "put", doc=b, obj="_root", prop="from_b", value=2)
    call(srv, "commit", doc=b)
    sa = call(srv, "syncSessionNew", doc=a, epoch=1)["session"]
    sb = call(srv, "syncSessionNew", doc=b, epoch=2)["session"]

    import base64 as b64mod
    for _ in range(30):
        fa = call(srv, "syncSessionPoll", session=sa)
        if fa is not None:
            # a corrupted copy first: must be absorbed, not crash
            corrupt = bytearray(b64mod.b64decode(fa))
            corrupt[len(corrupt) // 2] ^= 0xFF
            r = call(srv, "syncSessionReceive", session=sb,
                     data=b64mod.b64encode(bytes(corrupt)).decode())
            assert r["accepted"] is False
            call(srv, "syncSessionReceive", session=sb, data=fa)
            call(srv, "syncSessionReceive", session=sb, data=fa)  # duplicate
        fb = call(srv, "syncSessionPoll", session=sb)
        if fb is not None:
            call(srv, "syncSessionReceive", session=sa, data=fb)
        stats_a = call(srv, "syncSessionStats", session=sa)
        stats_b = call(srv, "syncSessionStats", session=sb)
        if stats_a["converged"] and stats_b["converged"]:
            break
    assert call(srv, "heads", doc=a) == call(srv, "heads", doc=b)
    stats_b = call(srv, "syncSessionStats", session=sb)
    assert stats_b["malformed"] >= 1 and stats_b["dups"] >= 1
    # persistence across a "restart" with a fresh epoch
    enc = call(srv, "syncSessionEncode", session=sa)
    sa2 = call(srv, "syncSessionRestore", doc=a, data=enc, epoch=9)["session"]
    assert call(srv, "syncSessionStats", session=sa2)["epoch"] == 9
    call(srv, "syncSessionFree", session=sa)
    call(srv, "syncSessionFree", session=sb)


def test_hostile_newline_free_stream_is_drained_not_buffered():
    """An oversized request with no newline must be consumed in bounded
    chunks (readline(limit)) and answered with RequestTooLarge; the server
    keeps serving afterwards."""
    import io

    srv = RpcServer(max_request_bytes=128)
    stream = "Z" * 100_000 + "\n" + '{"id": 1, "method": "create"}\n'
    out = io.StringIO()
    srv.serve(stdin=io.StringIO(stream), stdout=out)
    resps = [json.loads(x) for x in out.getvalue().splitlines()]
    assert resps[0]["error"]["type"] == "RequestTooLarge"
    assert resps[1]["result"]["doc"] == 1


def test_sync_session_rejects_nonpositive_timeout():
    srv = RpcServer()
    d = call(srv, "create")["doc"]
    resp = srv.handle({"id": 1, "method": "syncSessionNew",
                       "params": {"doc": d, "timeoutMs": 0}})
    assert "error" in resp


def test_request_limit_counts_bytes_not_characters():
    """A non-ASCII payload must be measured in encoded bytes: 600 CJK
    chars ≈ 1800 UTF-8 bytes, over a 1k limit even though len() < 1024."""
    import io

    srv = RpcServer(max_request_bytes=1024)
    big = json.dumps({"id": 1, "method": "create",
                      "params": {"actor": "世" * 600}}, ensure_ascii=False)
    assert len(big) < 1024 < len(big.encode())
    out = io.StringIO()
    srv.serve(stdin=io.StringIO(big + "\n"), stdout=out)
    resp = json.loads(out.getvalue().splitlines()[0])
    assert resp["error"]["type"] == "RequestTooLarge"


def test_durable_mode_persists_across_server_restarts(tmp_path):
    """--durable DIR mode: openDurable documents journal every change; a
    fresh server over the same directory recovers them."""
    srv = RpcServer(durable_dir=str(tmp_path))
    d = call(srv, "openDurable", name="alpha")["doc"]
    # reopening the same name returns the same handle (one journal owner),
    # but never silently with a different durability than requested
    assert call(srv, "openDurable", name="alpha")["doc"] == d
    resp = srv.handle({"id": 1, "method": "openDurable",
                       "params": {"name": "alpha", "fsync": "never"}})
    assert "already open" in resp["error"]["message"]
    t = call(srv, "putObject", doc=d, obj="_root", prop="t", type="text")["$obj"]
    call(srv, "spliceText", doc=d, obj=t, pos=0, text="durable")
    call(srv, "put", doc=d, obj="_root", prop="n", value=7)
    call(srv, "commit", doc=d)
    info = call(srv, "durableInfo", doc=d)
    assert info["journalRecords"] >= 1 and info["fsync"] == "always"
    assert call(srv, "durableCompact", doc=d)["journalRecords"] == 0
    call(srv, "put", doc=d, obj="_root", prop="post", value=1)
    call(srv, "commit", doc=d)
    call(srv, "free", doc=d)  # closes the journal

    srv2 = RpcServer(durable_dir=str(tmp_path))
    d2 = call(srv2, "openDurable", name="alpha")["doc"]
    assert call(srv2, "materialize", doc=d2) == {"t": "durable", "n": 7,
                                                "post": 1}
    call(srv2, "free", doc=d2)


def test_durable_mode_rejects_bad_names_and_nondurable_server(tmp_path):
    srv = RpcServer(durable_dir=str(tmp_path))
    for bad in ("../evil", "a/b", "", ".hidden", None, 7, "x" * 100):
        resp = srv.handle({"id": 1, "method": "openDurable",
                           "params": {"name": bad}})
        assert "error" in resp, bad
    # durableInfo on a plain doc is an error, not a crash
    plain = call(srv, "create")["doc"]
    resp = srv.handle({"id": 1, "method": "durableInfo",
                       "params": {"doc": plain}})
    assert "error" in resp

    nondurable = RpcServer()
    resp = nondurable.handle({"id": 1, "method": "openDurable",
                              "params": {"name": "alpha"}})
    assert resp["error"]["message"].startswith("server is not running")


def test_durable_docs_flushed_on_eof_without_free(tmp_path):
    """A client that vanishes (EOF) without free() must not strand a
    pending autocommit transaction: serve() closes durable docs on every
    exit path."""
    import io

    srv = RpcServer(durable_dir=str(tmp_path))
    stream = (
        '{"id":1,"method":"openDurable","params":{"name":"a"}}\n'
        '{"id":2,"method":"put","params":{"doc":1,"obj":"_root","prop":"n","value":7}}\n'
    )  # no commit, no free, then EOF
    out = io.StringIO()
    srv.serve(stdin=io.StringIO(stream), stdout=out)
    srv2 = RpcServer(durable_dir=str(tmp_path))
    d2 = call(srv2, "openDurable", name="a")["doc"]
    assert call(srv2, "get", doc=d2, obj="_root", prop="n") == 7
    call(srv2, "free", doc=d2)
