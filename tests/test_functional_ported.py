"""Reference JS-wrapper scenarios ported against the functional API.

Each test is a behavioral port of a named case from the reference's
wrapper suites (reference: javascript/test/legacy_tests.ts,
change_at.ts, patches.ts, text_test.ts, marks.ts, error.ts,
proxies.ts, extra_api_tests.ts, new-change-api.ts —
file:line cited per test),
driven through
automerge_tpu.functional's immutable-doc idiom: change() returns new
values, merge() consumes the local input, conflicts read through
get_conflicts with opid-exid keys.
"""

from __future__ import annotations

import pytest

import automerge_tpu.functional as am
from automerge_tpu.patches import apply_patches

A1 = bytes.fromhex("aa" * 16)
A2 = bytes.fromhex("bb" * 16)
A3 = bytes.fromhex("cc" * 16)


def _pair():
    return am.init(actor=A1), am.init(actor=A2)


def opid(ctr: int, actor: bytes) -> str:
    return f"{ctr}@{actor.hex()}"


def _val(v):
    """Render a conflict entry for comparison (proxies -> plain values)."""
    return v.to_py() if hasattr(v, "to_py") else v


def test_merge_concurrent_updates_of_different_properties():
    # legacy_tests.ts:1077
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"foo": "bar"}))
    s2 = am.change(s2, lambda d: d.update({"hello": "world"}))
    s3 = am.merge(s1, s2)
    assert s3.to_py() == {"foo": "bar", "hello": "world"}
    assert am.get_conflicts(s3, "foo") is None
    assert am.get_conflicts(s3, "hello") is None
    s4 = am.load(am.save(s3))
    assert am.equals(s3, s4)


def test_add_concurrent_increments_of_same_property():
    # legacy_tests.ts:1090
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"counter": am.Counter()}))
    s2 = am.merge(s2, am.clone(s1))
    s1 = am.change(s1, lambda d: d.increment("counter", 1))
    s2 = am.change(s2, lambda d: d.increment("counter", 2))
    assert s1["counter"] == 1 and s2["counter"] == 2
    s3 = am.merge(s1, s2)
    assert s3["counter"] == 3
    assert am.get_conflicts(s3, "counter") is None
    assert am.equals(am.load(am.save(s3)), s3)


def test_increments_only_apply_to_values_they_precede():
    # legacy_tests.ts:1104 — concurrent counter REPLACE vs increment:
    # each increment lands only on the counter op it named
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"counter": am.Counter(0)}))
    s1 = am.change(s1, lambda d: d.increment("counter", 1))
    s2 = am.change(s2, lambda d: d.update({"counter": am.Counter(100)}))
    s2 = am.change(s2, lambda d: d.increment("counter", 3))
    s3 = am.merge(s1, s2)
    # A2 > A1 lexicographically: s2's write wins
    assert s3.to_py() == {"counter": 103}
    assert {k: _val(v) for k, v in am.get_conflicts(s3, "counter").items()} == {
        opid(1, A1): 1,
        opid(1, A2): 103,
    }
    assert am.equals(am.load(am.save(s3)), s3)


def test_detect_concurrent_updates_of_same_field():
    # legacy_tests.ts:1126
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"field": "one"}))
    s2 = am.change(s2, lambda d: d.update({"field": "two"}))
    s3 = am.merge(s1, s2)
    assert s3.to_py() == {"field": "two"}  # larger actor id wins
    assert {k: _val(v) for k, v in am.get_conflicts(s3, "field").items()} == {
        opid(1, A1): "one",
        opid(1, A2): "two",
    }


def test_detect_concurrent_updates_of_same_list_element():
    # legacy_tests.ts:1141
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"birds": ["finch"]}))
    s2 = am.merge(s2, am.clone(s1))
    s1 = am.change(s1, lambda d: d["birds"].__setitem__(0, "greenfinch"))
    s2 = am.change(s2, lambda d: d["birds"].__setitem__(0, "goldfinch_"))
    s3 = am.merge(s1, s2)
    assert s3.to_py()["birds"] == ["goldfinch_"]
    confl = am.get_conflicts(s3["birds"], 0)
    assert {k: _val(v) for k, v in confl.items()} == {
        opid(3, A1): "greenfinch",
        opid(3, A2): "goldfinch_",
    }


def test_assignment_conflicts_of_different_types():
    # legacy_tests.ts:1158
    s1 = am.init(actor=A1)
    s2 = am.init(actor=A2)
    s3 = am.init(actor=A3)
    s1 = am.change(s1, lambda d: d.update({"field": "string"}))
    s2 = am.change(s2, lambda d: d.update({"field": ["list"]}))
    s3 = am.change(s3, lambda d: d.update({"field": {"thing": "map"}}))
    s1 = am.merge(am.merge(s1, s2), s3)
    assert _val(s1["field"]) in ("string", ["list"], {"thing": "map"})
    confl = {k: _val(v) for k, v in am.get_conflicts(s1, "field").items()}
    assert confl == {
        opid(1, A1): "string",
        opid(1, A2): ["list"],
        opid(1, A3): {"thing": "map"},
    }


def test_changes_within_conflicting_map_field():
    # legacy_tests.ts:1171
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"field": "string"}))
    s2 = am.change(s2, lambda d: d.update({"field": {}}))
    s2 = am.change(s2, lambda d: d["field"].update({"innerKey": 42}))
    s3 = am.merge(s1, s2)
    confl = {k: _val(v) for k, v in am.get_conflicts(s3, "field").items()}
    assert confl == {
        opid(1, A1): "string",
        opid(1, A2): {"innerKey": 42},
    }


def test_changes_within_conflicting_list_element():
    # legacy_tests.ts:1183
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"list": ["hello"]}))
    s2 = am.merge(s2, am.clone(s1))
    s1 = am.change(s1, lambda d: d["list"].__setitem__(0, {"map1": True}))
    s1 = am.change(s1, lambda d: d["list"][0].update({"key": 1}))
    s2 = am.change(s2, lambda d: d["list"].__setitem__(0, {"map2": True}))
    s2 = am.change(s2, lambda d: d["list"][0].update({"key": 2}))
    s3 = am.merge(s1, s2)
    assert s3.to_py()["list"] == [{"map2": True, "key": 2}]
    confl = {k: _val(v) for k, v in am.get_conflicts(s3["list"], 0).items()}
    assert confl == {
        opid(3, A1): {"map1": True, "key": 1},
        opid(3, A2): {"map2": True, "key": 2},
    }


def test_no_merge_of_concurrently_assigned_nested_maps():
    # legacy_tests.ts:1202
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"config": {"background": "blue"}}))
    s2 = am.change(s2, lambda d: d.update({"config": {"logo_url": "logo.png"}}))
    s3 = am.merge(s1, s2)
    assert _val(s3["config"]) in (
        {"background": "blue"}, {"logo_url": "logo.png"},
    )
    confl = {k: _val(v) for k, v in am.get_conflicts(s3, "config").items()}
    assert confl == {
        opid(1, A1): {"background": "blue"},
        opid(1, A2): {"logo_url": "logo.png"},
    }


def test_clear_conflicts_after_assigning_new_value():
    # legacy_tests.ts:1217
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"field": "one"}))
    s2 = am.change(s2, lambda d: d.update({"field": "two"}))
    s3 = am.merge(s1, am.clone(s2))
    s3 = am.change(s3, lambda d: d.update({"field": "three"}))
    assert s3.to_py() == {"field": "three"}
    assert am.get_conflicts(s3, "field") is None
    s2 = am.merge(s2, s3)
    assert s2.to_py() == {"field": "three"}
    assert am.get_conflicts(s2, "field") is None


def test_concurrent_insertions_at_different_list_positions():
    # legacy_tests.ts:1229
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"list": ["one", "three"]}))
    s2 = am.merge(s2, am.clone(s1))
    s1 = am.change(s1, lambda d: d["list"].insert(1, "two"))
    s2 = am.change(s2, lambda d: d["list"].append("four"))
    s3 = am.merge(s1, s2)
    assert s3.to_py() == {"list": ["one", "two", "three", "four"]}


def test_concurrent_insertions_at_same_position_converge():
    # legacy_tests.ts:1240
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"birds": ["parakeet"]}))
    s2 = am.merge(s2, am.clone(s1))
    s1 = am.change(s1, lambda d: d["birds"].append("starling"))
    s2 = am.change(s2, lambda d: d["birds"].append("chaffinch"))
    s3 = am.merge(s1, am.clone(s2))
    birds = s3.to_py()["birds"]
    assert birds in (
        ["parakeet", "starling", "chaffinch"],
        ["parakeet", "chaffinch", "starling"],
    )
    s2b = am.merge(s2, s3)
    assert am.equals(s2b, s3)


def test_concurrent_assignment_and_deletion_add_wins():
    # legacy_tests.ts:1253 — add-wins semantics
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"bestBird": "robin"}))
    s2 = am.merge(s2, am.clone(s1))
    s1 = am.change(s1, lambda d: d.__delitem__("bestBird"))
    s2 = am.change(s2, lambda d: d.update({"bestBird": "magpie"}))
    s3 = am.merge(s1, s2)
    assert s3.to_py() == {"bestBird": "magpie"}


def test_list_insert_order_for_equal_counters_is_reverse_actor():
    # legacy_tests.ts:774 — concurrent same-counter inserts land in
    # reverse actor-id order
    s1 = am.init(actor=A1)
    s2 = am.init(actor=A2)
    s1 = am.change(s1, lambda d: d.update({"list": []}))
    s2 = am.merge(s2, am.clone(s1))
    s1 = am.change(s1, lambda d: d["list"].insert(0, "one"))
    s2 = am.change(s2, lambda d: d["list"].insert(0, "two"))
    s3 = am.merge(s1, s2)
    assert s3.to_py()["list"] == ["two", "one"]  # A2 > A1


def test_root_property_deletion_and_js_delete_behavior():
    # legacy_tests.ts:451,464
    d = am.from_dict({"a": 1, "b": 2}, actor=A1)
    d = am.change(d, lambda x: x.__delitem__("a"))
    assert d.to_py() == {"b": 2}
    assert "a" not in d


def test_type_of_property_can_change():
    # legacy_tests.ts:482
    d = am.from_dict({"x": 1}, actor=A1)
    d = am.change(d, lambda x: x.update({"x": "now a string"}))
    assert d.to_py() == {"x": "now a string"}
    d = am.change(d, lambda x: x.update({"x": [1, 2]}))
    assert d.to_py() == {"x": [1, 2]}


def test_arbitrary_depth_nesting_and_replacement():
    # legacy_tests.ts:571,585
    d = am.from_dict(
        {"a": {"b": {"c": {"d": {"e": "deep"}}}}}, actor=A1
    )
    assert d["a"]["b"]["c"]["d"].to_py() == {"e": "deep"}
    d = am.change(d, lambda x: x["a"]["b"].update({"c": "replaced"}))
    assert d.to_py() == {"a": {"b": {"c": "replaced"}}}


def test_out_by_one_list_assignment_is_insertion():
    # legacy_tests.ts:797,807
    d = am.from_dict({"l": ["a"]}, actor=A1)
    d = am.change(d, lambda x: x["l"].insert(1, "b"))
    assert d.to_py()["l"] == ["a", "b"]
    with pytest.raises(Exception):
        am.change(d, lambda x: x["l"].__setitem__(5, "nope"))


def test_empty_change_references_dependencies():
    # legacy_tests.ts:402,413 — the ack change depends on BOTH heads
    s1, s2 = _pair()
    s1 = am.change(s1, lambda d: d.update({"a": 1}))
    s2 = am.change(s2, lambda d: d.update({"b": 2}))
    h1 = am.get_heads(s1)[0]
    h2 = am.get_heads(s2)[0]
    s1 = am.merge(s1, s2)
    s1 = am.empty_change(s1, "ack")
    last = am.get_history(s1)[-1].change
    assert sorted(last["deps"]) == sorted([h1.hex(), h2.hex()])
    assert last["ops"] == []


def test_change_does_not_mutate_input_and_old_doc_unusable():
    # legacy_tests.ts:85 + stable.ts outdated-document rule
    s1 = am.from_dict({"k": 1}, actor=A1)
    s2 = am.change(s1, lambda d: d.update({"k": 2}))
    assert s2.to_py() == {"k": 2}
    with pytest.raises(RuntimeError):
        am.change(s1, lambda d: d.update({"k": 3}))


def test_no_conflicts_on_repeated_assignment():
    # legacy_tests.ts:135
    d = am.init(actor=A1)
    for v in (1, 2, 3):
        d = am.change(d, lambda x, v=v: x.update({"k": v}))
        assert am.get_conflicts(d, "k") is None
    assert d.to_py() == {"k": 3}


# -- changeAt scenarios (reference: javascript/test/change_at.ts) -------------


def test_change_at_prior_state_lands_concurrent():
    # change_at.ts:6 — edit as of old heads; both edits survive the merge
    d = am.init(actor=A1)
    d = am.change(d, lambda x: x.update({"text": am.Text("aaabbbccc")}))
    heads1 = am.get_heads(d)
    d = am.change(d, lambda x: am.splice(x, ["text"], 3, 3, "BBB"))
    assert d.to_py()["text"] == "aaaBBBccc"

    def edit_old(x):
        assert str(x["text"]) == "aaabbbccc"  # sees the OLD state
        am.splice(x, ["text"], 2, 3, "XXX")
        assert str(x["text"]) == "aaXXXbccc"

    d = am.change_at(d, heads1, edit_old)
    assert d.to_py()["text"] == "aaXXXBBBccc"


def test_change_at_empty_change_leaves_heads_intact():
    # change_at.ts:22 — a no-op changeAt must not collapse a forked history
    d1 = am.init(actor=A1)
    d1 = am.change(d1, lambda x: x.update({"text": "aaabbbccc"}))
    heads_before_fork = am.get_heads(d1)
    d2 = am.clone(d1, actor=A2)
    d2 = am.change(d2, lambda x: x.update({"doc2": "doc2"}))
    d1 = am.change(d1, lambda x: x.update({"doc1": "doc1"}))
    d1 = am.merge(d1, d2)
    assert len(am.get_heads(d1)) == 2
    d1 = am.change_at(d1, heads_before_fork, lambda x: None)
    assert len(am.get_heads(d1)) == 2


def test_change_at_adds_head_beside_unchanged_fork():
    # change_at.ts:47 — the changeAt head joins the untouched fork's head
    d1 = am.init(actor=A1)
    d1 = am.change(d1, lambda x: x.update({"text": "aaabbbccc"}))
    d2 = am.clone(d1, actor=A2)
    d2 = am.change(d2, lambda x: x.update({"doc2": "doc2"}))
    heads_on_fork = am.get_heads(d2)
    d1 = am.change(d1, lambda x: x.update({"doc1": "doc1"}))
    doc1_heads = am.get_heads(d1)
    d1 = am.merge(d1, d2)
    d1 = am.change_at(d1, doc1_heads, lambda x: x.update({"text": "changed"}))
    new_heads = [
        h for h in am.get_heads(d1) if h not in heads_on_fork
    ]
    assert len(new_heads) == 1  # exactly one new head from the isolated edit
    assert set(am.get_heads(d1)) == set(heads_on_fork) | set(new_heads)


# -- patch / diff scenarios (reference: javascript/test/patches.ts) -----------


def test_diff_covers_changes_between_heads():
    # patches.ts:76 — diff(before, after) describes the delta; applying it
    # to the before-state materializes the after-state
    d = am.from_dict({"birds": ["goldfinch"]}, actor=A1)
    before = am.get_heads(d)
    before_state = am.to_dict(d)

    def edit(x):
        x["birds"].append("greenfinch")
        x.update({"fish": ["cod"]})

    d = am.change(d, edit)
    after = am.get_heads(d)
    patches = am.diff(d, before, after)
    assert patches  # non-empty delta
    got = apply_patches(before_state, patches)
    assert got == {"birds": ["goldfinch", "greenfinch"], "fish": ["cod"]}
    # reverse diff walks back
    back = am.diff(d, after, before)
    assert apply_patches(am.to_dict(d), back) == {"birds": ["goldfinch"]}


def test_diff_before_and_after_views_are_readable():
    # patches.ts:7 — before/after states around a change are addressable
    d = am.from_dict({"count": 0}, actor=A1)
    heads_before = am.get_heads(d)
    d = am.change(d, lambda x: x.update({"count": 1}))
    heads_after = am.get_heads(d)
    assert am.view(d, heads_before).to_py() == {"count": 0}
    assert am.view(d, heads_after).to_py() == {"count": 1}


def test_diff_observed_deletion_states():
    # patches.ts:27,49 — deletions in lists and maps round-trip via diff
    d = am.from_dict({"list": ["a", "b", "c"], "obj": {"a": "a", "b": "b"}},
                     actor=A1)
    before = am.get_heads(d)
    before_state = am.to_dict(d)

    def edit(x):
        am.delete_at(x["list"], 1)
        del x["obj"]["b"]

    d = am.change(d, edit)
    assert d.to_py() == {"list": ["a", "c"], "obj": {"a": "a"}}
    got = apply_patches(before_state, am.diff(d, before, am.get_heads(d)))
    assert got == {"list": ["a", "c"], "obj": {"a": "a"}}


# -- text scenarios (reference: javascript/test/text_test.ts) -----------------


def test_text_insert_delete_implicit_explicit():
    # text_test.ts:17,25,36
    d = am.from_dict({"text": am.Text("")}, actor=A1)
    d = am.change(d, lambda x: am.splice(x, ["text"], 0, 0, "abc"))
    d = am.change(d, lambda x: am.splice(x, ["text"], 1, 1))
    d = am.change(d, lambda x: am.splice(x, ["text"], 1, 0))
    assert d.to_py()["text"] == "ac"


def test_text_concurrent_insertion_converges():
    # text_test.ts:48
    s1 = am.from_dict({"text": am.Text("")}, actor=A1)
    s2 = am.merge(am.init(actor=A2), am.clone(s1))
    s1 = am.change(s1, lambda x: am.splice(x, ["text"], 0, 0, "abc"))
    s2 = am.change(s2, lambda x: am.splice(x, ["text"], 0, 0, "xyz"))
    s1 = am.merge(s1, am.clone(s2))
    t = s1.to_py()["text"]
    assert t in ("abcxyz", "xyzabc")
    s2 = am.merge(s2, s1)
    assert s2.to_py()["text"] == t


def test_text_and_other_ops_in_same_change():
    # text_test.ts:60
    d = am.from_dict({"text": am.Text("")}, actor=A1)

    def edit(x):
        x.update({"foo": "bar"})
        am.splice(x, ["text"], 0, 0, "a")

    d = am.change(d, edit)
    assert d.to_py() == {"foo": "bar", "text": "a"}


def test_text_edits_visible_inside_change_callback():
    # text_test.ts:77
    def edit(x):
        x.update({"text": am.Text("")})
        am.splice(x, ["text"], 0, 0, "abcd")
        am.splice(x, ["text"], 2, 1)
        assert str(x["text"]) == "abd"

    d = am.change(am.init(actor=A1), edit)
    assert d.to_py()["text"] == "abd"


def test_text_initial_value_is_one_change_and_unicode():
    # text_test.ts:95,105,115
    s1 = am.from_dict({"text": am.Text("init")}, actor=A1)
    assert s1.to_py()["text"] == "init"
    changes = am.get_all_changes(s1)
    assert len(changes) == 1
    s2 = am.apply_changes(am.init(actor=A2), changes)
    assert s2.to_py()["text"] == "init"
    uni = am.from_dict({"text": am.Text("\U0001F426")}, actor=A3)
    assert uni.to_py()["text"] == "\U0001F426"
    assert am.load(am.save(uni)).to_py()["text"] == "\U0001F426"


def test_splice_into_text_nested_in_arrays():
    # text_test.ts:122
    d = am.from_dict({"dom": [[am.Text("world")]]}, actor=A1)
    d = am.change(d, lambda x: am.splice(x, ["dom", 0, 0], 0, 0, "Hello "))
    assert d.to_py()["dom"][0][0] == "Hello world"


# -- mark / error scenarios (reference: javascript/test/marks.ts, error.ts) ---


def test_partial_unmark_splits_spans_and_survives_save_load():
    # marks.ts:7 — unmark of a middle range splits the span; a loaded copy
    # reports the same spans
    d = am.from_dict(
        {"x": am.Text("the quick fox jumps over the lazy dog")}, actor=A1
    )
    d = am.change(d, lambda x: am.mark(
        x, ["x"], {"start": 5, "end": 10, "expand": "none"},
        "font-weight", "bold",
    ))
    d = am.change(d, lambda x: am.unmark(
        x, ["x"], {"start": 7, "end": 9, "expand": "none"}, "font-weight",
    ))
    spans = [(m.name, m.value, m.start, m.end) for m in am.marks(d, "x")]
    assert spans == [
        ("font-weight", "bold", 5, 7),
        ("font-weight", "bold", 9, 10),
    ]
    d2 = am.load_incremental(am.init(actor=A2), am.save(d))
    spans2 = [(m.name, m.value, m.start, m.end) for m in am.marks(d2, "x")]
    assert spans2 == spans


def test_marks_track_splices_sensibly():
    # marks.ts:74 — a mark shifts under a preceding splice and a full
    # unmark clears it (indices adapted to this API's default codepoint
    # units: each emoji is ONE index unit here, vs the JS wrapper's two)
    d = am.from_dict({"content": am.Text("\U0001F600\U0001F600")}, actor=A1)

    def edit(x):
        am.mark(x, ["content"], {"start": 1, "end": 2, "expand": "none"},
                "bold", True)
        am.splice(x, ["content"], 0, 0, "\U0001F643")

    d = am.change(d, edit)
    spans = [(m.name, m.value, m.start, m.end) for m in am.marks(d, "content")]
    assert spans == [("bold", True, 2, 3)]
    d = am.change(d, lambda x: am.unmark(
        x, ["content"], {"start": 2, "end": 3, "expand": "none"}, "bold",
    ))
    assert am.marks(d, "content") == []


def test_errors_are_exceptions_not_strings():
    # error.ts:5,19 — misuse raises TYPED exceptions, not strings
    from automerge_tpu.errors import AutomergeError

    with pytest.raises(TypeError):
        am.from_dict({"x": object()}, actor=A1)  # unsupported datatype
    d = am.from_dict({"l": [1]}, actor=A1)
    with pytest.raises(AutomergeError):
        am.change(d, lambda x: x["l"].__setitem__(9, "out of range"))


# -- list proxy scenarios (reference: javascript/test/proxies.ts) -------------


def test_list_proxy_iteration_entries_values_keys():
    # proxies.ts:16,29,41
    d = am.from_dict({"list": ["a", "b", "c"]}, actor=A1)

    def edit(x):
        lst = x["list"]
        seen = [(i, v) for i, v in lst.entries()]
        assert seen == [(0, "a"), (1, "b"), (2, "c")]
        assert list(lst.values()) == ["a", "b", "c"]
        assert list(lst.keys()) == [0, 1, 2]

    am.change(d, edit)


def test_list_proxy_splice_removes_and_returns_deleted():
    # proxies.ts:55
    d = am.from_dict({"list": ["a", "b", "c"]}, actor=A1)

    def edit(x):
        assert x["list"].splice(1, 1) == ["b"]

    d = am.change(d, edit)
    assert d.to_py()["list"] == ["a", "c"]


def test_list_proxy_splice_replaces_and_inserts():
    # proxies.ts:64,73
    d = am.from_dict({"list": ["a", "b", "c"]}, actor=A1)

    def edit(x):
        assert x["list"].splice(1, 1, "d", "e") == ["b"]

    d = am.change(d, edit)
    assert d.to_py()["list"] == ["a", "d", "e", "c"]
    def edit2(x):
        assert x["list"].splice(1, 0, "z") == []

    d = am.change(d, edit2)
    assert d.to_py()["list"] == ["a", "z", "d", "e", "c"]


def test_list_proxy_splice_start_only_truncates():
    # proxies.ts:82
    d = am.from_dict({"list": ["a", "b", "c"]}, actor=A1)

    def edit(x):
        assert x["list"].splice(1) == ["b", "c"]

    d = am.change(d, edit)
    assert d.to_py()["list"] == ["a"]


def test_incremental_load_chain_tracks_every_change():
    # extra_api_tests.ts:6 — a replica fed only incremental saves after
    # each change converges with the source
    d1 = am.from_dict({"foo": "bar"}, actor=A1)
    d2 = am.load_incremental(am.init(actor=A2), am.save(d1))
    for edit in (
        lambda x: x.update({"foo2": "bar2"}),
        lambda x: x.update({"foo": "bar2"}),
        lambda x: x.update({"x": "y"}),
    ):
        d1 = am.change(d1, edit)
        d2 = am.load_incremental(d2, am.save_incremental(d1))
    assert am.equals(d1, d2)
    assert am.get_heads(d1) == am.get_heads(d2)


def test_new_change_api_basics():
    # new-change-api.ts:6,18,26
    d = am.from_dict({"foo": "bar"}, actor=A1)

    def edit(x):
        assert x["foo"] == "bar"
        x.update({"foo": "baz"})

    d = am.change(d, edit)
    assert d.to_py() == {"foo": "baz"}
    d = am.from_dict({"list": []}, actor=A2)
    d = am.change(d, lambda x: am.insert_at(x["list"], 0, "a"))
    assert d.to_py()["list"] == ["a"]
    d = am.from_dict({"list": ["a", "b", "c"]}, actor=A3)
    d = am.change(d, lambda x: am.delete_at(x["list"], 0))
    assert d.to_py()["list"] == ["b", "c"]
