"""Differential tests: bulk (native) apply == incremental per-op apply.

The bulk path (core/bulk_load.py) rebuilds the op store via the native
sequential integrate; its result must be indistinguishable from replaying
every op through op_store.insert_op (the incremental path the rest of the
suite exercises).
"""

import random

import pytest

from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.core.document import Document
from automerge_tpu.types import ActorId, ObjType, ScalarValue

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core unavailable"
)


def actor(n: int) -> ActorId:
    return ActorId(bytes([n]) * 16)


def build_divergent_docs(seed: int, n_forks: int = 4, n_edits: int = 40):
    rng = random.Random(seed)
    base = AutoDoc(actor(1))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "the quick brown fox jumps over the lazy dog")
    base.put("_root", "count", ScalarValue("counter", 5))
    base.put("_root", "title", "hello")
    lst = base.put_object("_root", "items", ObjType.LIST)
    base.insert(lst, 0, 1)
    base.insert(lst, 1, 2)
    base.commit()
    forks = [base.fork(actor=actor(10 + i)) for i in range(n_forks)]
    for i, f in enumerate(forks):
        for j in range(n_edits):
            ln = f.length(t)
            which = rng.random()
            if which < 0.5 or ln < 2:
                f.splice_text(t, rng.randrange(ln + 1), 0, f"{i}{j % 10}")
            elif which < 0.8:
                f.splice_text(t, rng.randrange(ln - 1), 1, "")
            elif which < 0.9:
                f.increment("_root", "count", i + j)
            else:
                f.put("_root", "title", f"t{i}-{j}")
        f.commit()
    return base, forks, t, lst


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bulk_matches_incremental(seed):
    base, forks, t, lst = build_divergent_docs(seed)
    changes = [a.stored for a in base.doc.history]
    for f in forks:
        changes.extend(
            a.stored
            for a in f.doc.history
            if a.hash not in {x.hash for x in base.doc.history}
        )

    inc = Document(actor(8))
    old = Document.BULK_MIN_OPS
    try:
        Document.BULK_MIN_OPS = 10**12  # force the incremental path
        inc.apply_changes(changes)
    finally:
        Document.BULK_MIN_OPS = old

    bulk = Document(actor(9))
    bulk.apply_changes(changes, )
    # force the bulk rebuild even under the ops threshold
    from automerge_tpu.core.bulk_load import rebuild_op_store

    rebuild_op_store(bulk)

    assert bulk.text(t) == inc.text(t)
    assert bulk.hydrate() == inc.hydrate()
    assert bulk.get_heads() == inc.get_heads()
    # conflict metadata and historical reads agree
    assert bulk.get_all("_root", "title") == inc.get_all("_root", "title")
    heads_mid = [c.hash for c in inc.history[: len(inc.history) // 2]][-1:]
    if heads_mid:
        assert bulk.text(t, heads=heads_mid) == inc.text(t, heads=heads_mid)


def test_bulk_respects_causal_queue():
    base, forks, t, lst = build_divergent_docs(3, n_forks=2, n_edits=10)
    changes = [a.stored for a in forks[0].doc.history]
    # withhold the base change: everything else is causally unready
    held = changes[0]
    rest = changes[1:]
    doc = Document(actor(9))
    doc.apply_changes(rest)
    assert len(doc.history) == 0
    assert len(doc.queue) == len(rest)
    doc.apply_changes([held])
    assert len(doc.history) == len(changes)
    assert doc.text(t) == forks[0].text(t)


def test_bulk_after_local_edits_keeps_editing_working():
    """The rebuilt store must support subsequent local transactions."""
    base, forks, t, lst = build_divergent_docs(4, n_forks=2, n_edits=15)
    merged = Document(actor(9))
    changes = [a.stored for a in base.doc.history]
    for f in forks:
        changes.extend(a.stored for a in f.doc.history[len(base.doc.history):])
    merged.apply_changes(changes)
    from automerge_tpu.core.bulk_load import rebuild_op_store

    rebuild_op_store(merged)
    doc = AutoDoc(actor(20))
    doc.doc = merged
    merged.set_actor(actor(20))
    before = doc.text(t)
    doc.splice_text(t, 0, 0, ">>")
    doc.commit()
    assert doc.text(t) == ">>" + before


def test_bulk_dedups_within_batch():
    base, forks, t, lst = build_divergent_docs(5, n_forks=2, n_edits=30)
    changes = [a.stored for a in forks[0].doc.history]
    doc = Document(actor(9))
    doc.BULK_MIN_OPS = 1  # force bulk
    doc.apply_changes(changes + [changes[-1], changes[0]])
    assert len(doc.history) == len(changes)
    assert doc.text(t) == forks[0].text(t)


def test_bulk_rejects_duplicate_seq_in_batch():
    base, forks, t, lst = build_divergent_docs(6, n_forks=2, n_edits=5)
    changes = [a.stored for a in forks[0].doc.history]
    from automerge_tpu.storage.change import StoredChange, build_change

    dup = build_change(
        StoredChange(
            dependencies=list(changes[-1].dependencies),
            actor=changes[-1].actor,
            other_actors=list(changes[-1].other_actors),
            seq=changes[-1].seq,  # same actor+seq, different content
            start_op=changes[-1].start_op + 1000,
            timestamp=7,
            message="dup",
            ops=[],
        )
    )
    doc = Document(actor(9))
    doc.BULK_MIN_OPS = 1
    with pytest.raises(Exception, match="duplicate seq"):
        doc.apply_changes(changes + [dup])


def test_extract_trailing_empty_change():
    """A zero-op (message-only) change at the end of a batch must extract."""
    from automerge_tpu.ops import OpLog
    from automerge_tpu.storage.change import StoredChange, build_change

    base, forks, t, lst = build_divergent_docs(7, n_forks=1, n_edits=5)
    changes = [a.stored for a in base.doc.history]
    empty = build_change(
        StoredChange(
            dependencies=[changes[-1].hash],
            actor=b"\x42" * 16,
            other_actors=[],
            seq=1,
            start_op=1000,
            timestamp=0,
            message="empty",
            ops=[],
        )
    )
    log = OpLog.from_changes(changes + [empty], fast=True)
    log2 = OpLog.from_changes(changes + [empty], fast=False)
    assert log.n == log2.n


def test_device_bulk_engine_matches_native(monkeypatch):
    """The device-kernel element-order export (bulk_load._export_via_device)
    rebuilds the exact same op store as the native sequential integrate on
    a dense-concurrency history."""
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "seed text for dense concurrency ")
    base.put("_root", "votes", ScalarValue("counter", 0))
    base.commit()
    changes = list(base.get_changes([]))
    for i in range(20):
        f = base.fork(actor=ActorId(bytes([10 + i]) * 16))
        f.splice_text(t, (i * 3) % f.length(t), 1 if i % 4 == 0 else 0, f"[{i}]")
        f.increment("_root", "votes", i)
        f.commit()
        changes.extend(f.get_changes(base.get_heads()))

    monkeypatch.setenv("AUTOMERGE_TPU_DEBUG", "1")
    docs = {}
    for engine in ("native", "device"):
        monkeypatch.setenv("AUTOMERGE_TPU_BULK", engine)
        d = AutoDoc(actor=ActorId(bytes([99]) * 16))
        # force the bulk path regardless of size thresholds
        monkeypatch.setattr(Document, "BULK_MIN_OPS", 1)
        d.apply_changes(changes)
        docs[engine] = d
    assert docs["native"].hydrate() == docs["device"].hydrate()
    assert docs["native"].get_heads() == docs["device"].get_heads()
    assert docs["native"].text(t) == docs["device"].text(t)
    tid = docs["native"].get("_root", "t")[0][2]
    assert docs["native"].marks(tid) == docs["device"].marks(tid)


def test_flatten_fast_matches_slow():
    """Vectorized flatten (_flatten_fast, native batch decode) produces
    byte-identical arrays to the per-op Python walk on a history with
    marks, counters, deletes, and multi-actor merges."""
    import numpy as np

    from automerge_tpu.core.bulk_load import _flatten_fast, _flatten_slow

    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hello world")
    d.put("_root", "c", ScalarValue("counter", 5))
    d.mark(t, 0, 5, "bold", True, expand="both")
    lst = d.put_object("_root", "l", ObjType.LIST)
    for i in range(8):
        d.insert(lst, i, i)
    d.commit()
    for i in range(6):
        f = d.fork(actor=ActorId(bytes([10 + i]) * 16))
        f.splice_text(t, i, 1, "XY")
        f.increment("_root", "c", i)
        if f.length(lst) > 0:
            f.delete(lst, 0)
        f.commit()
        d.merge(f)
    d.splice_text(t, 2, 3, "")
    d.commit()
    stored = [a.stored for a in d.doc.history]
    fa = _flatten_fast(stored)
    sl = _flatten_slow(stored)
    for k in (
        "op_id", "obj", "elem", "prop", "action", "insert", "is_counter",
        "pred_off", "pred_flat",
    ):
        assert np.array_equal(np.asarray(fa[k]), np.asarray(sl[k])), k
    assert fa["rank_of"] == sl["rank_of"]


def test_array_rebuild_preserves_out_of_i64_uint(monkeypatch):
    """uint values >= 2^63 wrap in the native int64 decode; the array
    rebuild must reroute them through the exact python decoder."""
    monkeypatch.setenv("AUTOMERGE_TPU_DEBUG", "1")
    big = 2**63 + 5
    d = AutoDoc(actor=ActorId(bytes([1]) * 16))
    d.put("_root", "big", ScalarValue("uint", big))
    t = d.put_object("_root", "t", ObjType.TEXT)
    d.splice_text(t, 0, 0, "x")
    d.commit()
    changes = list(d.get_changes([]))
    e = AutoDoc(actor=ActorId(bytes([2]) * 16))
    monkeypatch.setattr(Document, "BULK_MIN_OPS", 1)
    e.apply_changes(changes)
    assert e.get("_root", "big")[0] == ("scalar", ScalarValue("uint", big))


def test_malformed_bulk_change_fails_loud_on_every_read(monkeypatch):
    """A structurally-invalid change (seq key targeting a map object) that
    enters via the deferred bulk path must raise on EVERY read — never
    silently drop the op or serve a half-built store."""
    from automerge_tpu.storage.change import (
        ChangeOp,
        Key,
        ROOT_STORED,
        StoredChange,
        build_change,
    )

    bad = build_change(
        StoredChange(
            dependencies=[], actor=bytes([5]) * 16, other_actors=[],
            seq=1, start_op=1, timestamp=0, message=None,
            ops=[ChangeOp(
                obj=ROOT_STORED, key=Key.seq((999, 0)), insert=True,
                action=1, value=ScalarValue("str", "x"), pred=[],
            )],
        )
    )
    d = AutoDoc(actor=ActorId(bytes([3]) * 16))
    monkeypatch.setattr(Document, "BULK_MIN_OPS", 1)
    try:
        d.apply_changes([bad])
    except Exception:
        return  # rejected at apply: also acceptable
    for _ in range(2):
        with pytest.raises(Exception):
            d.keys()
