"""Differential tests for the incremental device merge path.

A persistent DeviceDoc fed deltas through ``apply_changes`` (OpLog splice +
dirty-set / delta re-resolution) must be indistinguishable from a
from-scratch ``OpLog.from_changes`` + full resolution at every step: same
reads, same patches, same heads, same historical ``at(heads)`` views — for
randomized seeded interleavings of change batches, duplicate re-delivery,
and out-of-order (dependency-gapped) delivery.
"""

import random

import numpy as np
import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i: int) -> ActorId:
    return ActorId(bytes([i]) * 16)


def build_base():
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "the quick brown fox")
    lst = base.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        base.insert(lst, i, i * 10)
    base.put("_root", "c", ScalarValue("counter", 5))
    base.put("_root", "k", "base")
    base.commit()
    return base, t, lst


def patches_repr(dev):
    return [
        (p.obj, tuple(p.path), type(p.action).__name__, str(p.action.__dict__))
        for p in dev.make_patches()
    ]


def assert_same_doc(dev, full, heads_to_check=()):
    assert dev.hydrate() == full.hydrate()
    assert sorted(dev.current_heads()) == sorted(full.current_heads())
    assert patches_repr(dev) == patches_repr(full)
    for h in heads_to_check:
        assert dev.at(h).hydrate() == full.at(h).hydrate()


def edit_fork(f, t, lst, rng, tag):
    """A few random edits + commit on fork ``f``."""
    ln = f.length(t)
    pos = rng.randrange(0, max(ln, 1))
    if rng.random() < 0.3 and ln > 1:
        f.splice_text(t, min(pos, ln - 1), 1, "")
    else:
        f.splice_text(t, pos, 0, f"<{tag}>")
    r = rng.random()
    if r < 0.3:
        f.increment("_root", "c", rng.randrange(1, 5))
    elif r < 0.6:
        f.put("_root", f"k{rng.randrange(3)}", tag)
    elif f.length(lst):
        if rng.random() < 0.5:
            f.insert(lst, rng.randrange(0, f.length(lst) + 1), tag)
        else:
            f.delete(lst, rng.randrange(0, f.length(lst)))
    f.commit()


@pytest.mark.parametrize("seed", [0, 1, 7])
def test_random_interleavings_match_from_scratch(seed):
    rng = random.Random(seed)
    base, t, lst = build_base()
    base_changes = [a.stored for a in base.doc.history]
    dev = DeviceDoc.resolve(OpLog.from_changes(base_changes))

    # several divergent forks editing concurrently, synced through one host
    forks = [base.fork(actor=actor(10 + i)) for i in range(3)]
    host = base
    seen = {c.hash for c in base_changes}
    mid_heads = []
    for rnd in range(6):
        f = forks[rng.randrange(len(forks))]
        edit_fork(f, t, lst, rng, f"{seed}.{rnd}")
        delta = [
            c for c in (a.stored for a in f.doc.history) if c.hash not in seen
        ]
        seen.update(c.hash for c in delta)
        # deliver in random batch splits, occasionally with duplicates
        rng.shuffle(delta)
        while delta:
            k = rng.randrange(1, len(delta) + 1)
            batch = delta[:k]
            delta = delta[k:]
            if rng.random() < 0.3:
                batch = batch + [batch[0]]  # duplicate re-delivery
            dev.apply_changes(batch)
        host.apply_changes(
            [a.stored for a in f.doc.history if a.stored.hash is not None]
        )
        # forks converge through the host so later edits see merged state
        for g in forks:
            g.merge(host)
        full = DeviceDoc.resolve(
            OpLog.from_changes([a.stored for a in host.doc.history])
        )
        if rnd == 2:
            mid_heads = full.current_heads()
        assert dev.pending_changes() == 0
        assert_same_doc(dev, full, [mid_heads] if mid_heads else [])
        assert dev.text(t) == host.text(t)


def test_out_of_order_delivery_buffers_until_deps_arrive():
    base, t, lst = build_base()
    base_changes = [a.stored for a in base.doc.history]
    dev = DeviceDoc.resolve(OpLog.from_changes(base_changes))
    f = base.fork(actor=actor(9))
    seen = {c.hash for c in base_changes}
    chain = []
    for i in range(4):
        f.splice_text(t, 0, 0, f"{i}:")
        f.commit()
        delta = [
            c for c in (a.stored for a in f.doc.history) if c.hash not in seen
        ]
        seen.update(c.hash for c in delta)
        chain.extend(delta)
    # deliver newest-first: everything but the first must buffer
    for ch in reversed(chain[1:]):
        dev.apply_changes([ch])
    assert dev.pending_changes() == len(chain) - 1
    dev.apply_changes([chain[0]])  # the gap fills; all integrate
    assert dev.pending_changes() == 0
    full = DeviceDoc.resolve(
        OpLog.from_changes(base_changes + chain)
    )
    assert_same_doc(dev, full)
    assert dev.text(t) == f.text(t)


def test_incremental_historical_views_and_diff():
    base, t, lst = build_base()
    base_changes = [a.stored for a in base.doc.history]
    dev = DeviceDoc.resolve(OpLog.from_changes(base_changes))
    heads0 = dev.current_heads()
    f = base.fork(actor=actor(5))
    seen = {c.hash for c in base_changes}
    for i in range(3):
        f.splice_text(t, f.length(t), 0, f"+{i}")
        f.increment("_root", "c", 1)
        f.commit()
        delta = [
            c for c in (a.stored for a in f.doc.history) if c.hash not in seen
        ]
        seen.update(c.hash for c in delta)
        dev.apply_changes(delta)
    full = DeviceDoc.resolve(
        OpLog.from_changes([a.stored for a in f.doc.history])
    )
    assert dev.at(heads0).hydrate() == full.at(heads0).hydrate()
    d1 = [(p.obj, type(p.action).__name__) for p in dev.diff(heads0)]
    d2 = [(p.obj, type(p.action).__name__) for p in full.diff(heads0)]
    assert d1 == d2
    assert dev.at(heads0).text(t) == "the quick brown fox"


def test_append_changes_matches_from_changes_columns():
    """Low-level: spliced OpLog columns are identical to a rebuilt log."""
    base, t, lst = build_base()
    base_changes = [a.stored for a in base.doc.history]
    forks = [base.fork(actor=actor(30 + i)) for i in range(3)]
    deltas = []
    seen = {c.hash for c in base_changes}
    for i, f in enumerate(forks):
        f.splice_text(t, i, 0, f"({i})")
        f.put("_root", f"fk{i}", i)
        f.commit()
        d = [c for c in (a.stored for a in f.doc.history) if c.hash not in seen]
        seen.update(c.hash for c in d)
        deltas.append(d)
    log = OpLog.from_changes(base_changes)
    for d in deltas:
        assert log.append_changes(d) is not None
    full = OpLog.from_changes(base_changes + [c for d in deltas for c in d])
    assert log.n == full.n
    for field in (
        "id_key", "obj_key", "prop", "elem_ref", "action", "value_tag",
        "value_int", "width", "mark_name_idx", "obj_dense",
    ):
        assert np.array_equal(
            np.asarray(getattr(log, field)), np.asarray(getattr(full, field))
        ), field
    assert np.array_equal(
        np.asarray(log.insert, bool), np.asarray(full.insert, bool)
    )
    assert np.array_equal(log.obj_table, full.obj_table)
    assert log.props == full.props
    assert sorted(zip(log.pred_src.tolist(), log.pred_tgt.tolist())) == sorted(
        zip(full.pred_src.tolist(), full.pred_tgt.tolist())
    )
    for i in range(log.n):
        a, b = log.values[i], full.values[i]
        assert a.tag == b.tag and a.value == b.value, i


def test_new_actor_sorting_before_existing_remaps_in_place():
    """A delta actor whose bytes sort BEFORE resident actors shifts every
    packed-id rank; the resident DeviceDoc (incl. its object-type cache)
    must follow the monotone remap, not rebuild."""
    base, t, lst = build_base()  # base actor is \x01*16
    mid = base.fork(actor=actor(200))
    mid.splice_text(t, 0, 0, "Z")
    mid.commit()
    base_changes = [a.stored for a in mid.doc.history]
    dev = DeviceDoc.resolve(OpLog.from_changes(base_changes))
    f = mid.fork(actor=ActorId(b"\x00" + b"\x99" * 15))  # sorts first
    f.splice_text(t, 1, 0, "!")
    f.put("_root", "early", 1)
    sub = f.put_object("_root", "m", ObjType.MAP)
    f.put(sub, "x", 2)
    f.commit()
    seen = {c.hash for c in base_changes}
    delta = [c for c in (a.stored for a in f.doc.history) if c.hash not in seen]
    dev.apply_changes(delta)
    full = DeviceDoc.resolve(
        OpLog.from_changes([a.stored for a in f.doc.history])
    )
    assert_same_doc(dev, full)
    assert dev.text(t) == f.text(t)
    assert dev.object_type(dev.get("_root", "m")[0][2]) == ObjType.MAP


def test_append_duplicate_batch_is_noop():
    base, t, lst = build_base()
    base_changes = [a.stored for a in base.doc.history]
    f = base.fork(actor=actor(40))
    f.splice_text(t, 0, 0, "dup")
    f.commit()
    delta = [
        c
        for c in (a.stored for a in f.doc.history)
        if c.hash not in {b.hash for b in base_changes}
    ]
    log = OpLog.from_changes(base_changes)
    info = log.append_changes(delta)
    assert info is not None and info.n_new > 0
    n = log.n
    info2 = log.append_changes(delta)
    assert info2 is not None and info2.n_new == 0 and log.n == n


def test_sync_session_feeds_device_doc():
    from automerge_tpu.sync.session import SyncSession

    base, t, lst = build_base()
    saved = base.save()
    a_doc = AutoDoc.load(saved)
    b_doc = AutoDoc.load(saved)
    a_doc.splice_text(t, 0, 0, "A>")
    a_doc.commit()
    b_dev = DeviceDoc.resolve(
        OpLog.from_changes([x.stored for x in b_doc.doc.history])
    )
    sa = SyncSession(a_doc, epoch=1)
    sb = SyncSession(b_doc, epoch=2, device_doc=b_dev)
    now = 0.0
    for _ in range(20):
        fa = sa.poll(now)
        if fa is not None:
            sb.receive(fa, now)
        fb = sb.poll(now)
        if fb is not None:
            sa.receive(fb, now)
        now += 1.0
        if sa.converged() and sb.converged():
            break
    assert sa.converged() and sb.converged()
    # the resident device doc tracked the host through the session
    assert b_dev.text(t) == b_doc.text(t) == a_doc.text(t)
    full = DeviceDoc.resolve(
        OpLog.from_changes([x.stored for x in b_doc.doc.history])
    )
    assert_same_doc(b_dev, full)


def test_lazy_values_cache_is_bounded():
    from automerge_tpu.ops.extract import LazyValues

    code = np.full(100, 4, np.int32)  # int sleb
    off = np.arange(100, dtype=np.int64)
    ln = np.ones(100, np.int64)
    raw = bytes(range(100))
    lv = LazyValues(code, off, ln, raw, cap=10)
    for i in range(100):
        lv[i]
    assert len(lv.cache) <= 10
    assert lv.misses == 100 and lv.hits == 0
    lv[99]
    assert lv.hits == 1
    s = lv.stats()
    assert s["cap"] == 10 and s["size"] <= 10


def test_change_hash_extraction_cache_hits_on_redelivery():
    import copy

    from automerge_tpu import trace
    from automerge_tpu.ops.assemble import ensure_change_cols

    base, t, lst = build_base()
    ch = [a.stored for a in base.doc.history][0]
    fresh = copy.copy(ch)
    fresh.cached_cols = None  # a re-parsed change: same hash, no memo
    before = trace.counters.get("extract.change_cache_hit", 0)
    ensure_change_cols([ch])  # populates the hash cache
    ensure_change_cols([fresh])
    assert trace.counters.get("extract.change_cache_hit", 0) > before
    assert fresh.cached_cols is not None
