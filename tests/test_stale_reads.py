"""Stale-store text reads (bulk_load.stale_text) — differential vs the
materialized op store.

After a bulk apply the op store is a stale materialized view; text() may
answer straight from history arrays. Every scenario asserts the stale
answer equals the answer after forcing full materialization.
"""

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.core.document import Document
from automerge_tpu.types import ActorId, ObjType


@pytest.fixture(autouse=True)
def _small_bulk_threshold(monkeypatch):
    # force the bulk (stale-marking) apply path at test sizes
    monkeypatch.setattr(Document, "BULK_MIN_OPS", 1)


def _fork_edit(base: AutoDoc, actor: bytes, fn):
    f = base.fork(actor=ActorId(actor))
    fn(f)
    f.commit()
    return f


def _stale_then_materialized(doc: AutoDoc, tobj: str):
    d = doc.doc
    assert d._ops_stale, "precondition: store must be stale"
    stale = doc.text(tobj)
    assert d._ops_stale, "text() on a stale store must not materialize it"
    d.ops  # force materialization
    return stale, d.ops.text(d.import_obj(tobj), None)


def _merged(base: AutoDoc, forks):
    out = AutoDoc.load(base.save())
    for f in forks:
        out.doc.apply_changes([a.stored for a in f.doc.history if a.hash not in out.doc.history_index])
    return out


def test_stale_text_concurrent_inserts():
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "base text here")
    base.commit()
    forks = [
        _fork_edit(base, bytes([i + 2]) * 16, lambda f, i=i: f.splice_text(t, i, 0, f"<{i}>"))
        for i in range(4)
    ]
    m = _merged(base, forks)
    stale, mat = _stale_then_materialized(m, t)
    assert stale == mat


def test_stale_text_deletes_and_updates():
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "abcdefghij")
    base.commit()

    def del_some(f):
        f.splice_text(t, 2, 3, "")

    def ins_mid(f):
        f.splice_text(t, 5, 0, "XYZ")

    m = _merged(base, [
        _fork_edit(base, bytes([2]) * 16, del_some),
        _fork_edit(base, bytes([3]) * 16, ins_mid),
    ])
    stale, mat = _stale_then_materialized(m, t)
    assert stale == mat


def test_stale_text_non_ascii():
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "héllo ✨ wörld 中文")
    base.commit()
    m = _merged(base, [
        _fork_edit(base, bytes([2]) * 16, lambda f: f.splice_text(t, 3, 2, "🎈")),
    ])
    stale, mat = _stale_then_materialized(m, t)
    assert stale == mat


def test_stale_text_with_marks():
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "marked text")
    base.mark(t, 0, 6, "bold", True)
    base.commit()
    m = _merged(base, [
        _fork_edit(base, bytes([2]) * 16, lambda f: f.splice_text(t, 7, 0, "up ")),
    ])
    stale, mat = _stale_then_materialized(m, t)
    assert stale == mat


def test_stale_text_memo_invalidated_by_new_changes():
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "one")
    base.commit()
    f1 = _fork_edit(base, bytes([2]) * 16, lambda f: f.splice_text(t, 3, 0, " two"))
    f2 = _fork_edit(base, bytes([3]) * 16, lambda f: f.splice_text(t, 0, 0, "zero "))
    m = AutoDoc.load(base.save())
    m.doc.apply_changes([a.stored for a in f1.doc.history if a.hash not in m.doc.history_index])
    first = m.text(t)
    m.doc.apply_changes([a.stored for a in f2.doc.history if a.hash not in m.doc.history_index])
    second = m.text(t)
    assert first != second
    m.doc.ops
    assert m.doc.ops.text(m.doc.import_obj(t), None) == second


def test_stale_text_empty_and_missing_fall_back():
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.commit()
    f = _fork_edit(base, bytes([2]) * 16, lambda f: f.put("_root", "k", 1))
    m = _merged(base, [f])
    assert m.text(t) == ""  # empty text object: fallback path
    with pytest.raises(Exception):
        m.text("99@" + "00" * 16)  # unknown object still raises


def test_stale_text_after_sync_roundtrip():
    from automerge_tpu.sync import SyncState
    from automerge_tpu.sync.protocol import generate_sync_message, receive_sync_message

    a = AutoDoc(actor=ActorId(bytes([1]) * 16))
    t = a.put_object("_root", "text", ObjType.TEXT)
    a.splice_text(t, 0, 0, "synced content " * 50)
    a.commit()
    b = AutoDoc.load(a.save())
    a.splice_text(t, 0, 0, "more ")
    a.commit()
    sa, sb = SyncState(), SyncState()
    for _ in range(20):
        ma = generate_sync_message(a.doc, sa)
        if ma:
            receive_sync_message(b.doc, sb, ma)
        mb = generate_sync_message(b.doc, sb)
        if mb:
            receive_sync_message(a.doc, sa, mb)
        if not ma and not mb:
            break
    assert b.text(t) == a.text(t)


def test_stale_text_on_map_object_matches_store_error():
    """text() on a MAP object must behave identically whether the store is
    stale or materialized: the stale path falls back so the store raises
    its typed error (review repro: the merge-backed path once returned "")."""
    a = AutoDoc(actor=ActorId(bytes([1]) * 16))
    m = a.put_object("_root", "m", ObjType.MAP)
    a.put(m, "k", 1)
    a.commit()
    data = a.save_incremental_after([])
    b = AutoDoc(actor=ActorId(bytes([2]) * 16))
    b.load_incremental(data)  # store now stale
    with pytest.raises(Exception, match="sequence read on map object"):
        b.text(m)
    # and the same error after materialization
    b.keys(m)
    with pytest.raises(Exception, match="sequence read on map object"):
        b.text(m)
