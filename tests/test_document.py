"""Document core tests: transactions, merge, conflicts, save/load, history.

Scenario coverage modeled on the reference's integration suite
(rust/automerge/tests/test.rs): multi-actor merges, conflict resolution,
save/load/merge roundtrips, counters, historical reads.
"""

import pytest

from automerge_tpu import ActorId, AutoDoc, AutomergeError, ObjType


def actor(n: int) -> ActorId:
    return ActorId(bytes([n]) * 16)


def new_doc(n: int = 1) -> AutoDoc:
    return AutoDoc(actor(n))


class TestMapBasics:
    def test_put_get(self):
        doc = new_doc()
        doc.put("_root", "hello", "world")
        doc.put("_root", "n", 5)
        doc.put("_root", "f", 2.5)
        doc.put("_root", "b", True)
        assert doc.get("_root", "hello")[0] == ("scalar", ("str", "world"))
        assert doc.get("_root", "n")[0] == ("scalar", ("int", 5))
        assert doc.get("_root", "f")[0] == ("scalar", ("f64", 2.5))
        assert doc.get("_root", "b")[0] == ("scalar", ("bool", True))
        assert doc.keys() == ["b", "f", "hello", "n"]
        assert doc.length() == 4

    def test_overwrite(self):
        doc = new_doc()
        doc.put("_root", "k", 1)
        doc.put("_root", "k", 2)
        assert doc.get("_root", "k")[0] == ("scalar", ("int", 2))
        assert len(doc.get_all("_root", "k")) == 1

    def test_delete(self):
        doc = new_doc()
        doc.put("_root", "k", 1)
        doc.delete("_root", "k")
        assert doc.get("_root", "k") is None
        assert doc.keys() == []
        # deleting a missing key is a silent no-op (reference:
        # transaction/inner.rs:422-423)
        doc.delete("_root", "nope")
        assert doc.keys() == []

    def test_nested_objects(self):
        doc = new_doc()
        inner = doc.put_object("_root", "config", ObjType.MAP)
        doc.put(inner, "x", 1)
        lst = doc.put_object(inner, "items", ObjType.LIST)
        doc.insert(lst, 0, "a")
        doc.insert(lst, 1, "b")
        assert doc.hydrate() == {"config": {"x": 1, "items": ["a", "b"]}}

    def test_conflict_resolution_deterministic(self):
        d1, d2 = new_doc(1), new_doc(2)
        d1.put("_root", "k", "from1")
        d2.put("_root", "k", "from2")
        d1.merge(d2)
        d2.merge(d1)
        # same winner on both sides, and both values visible as conflicts
        assert d1.get("_root", "k")[0] == d2.get("_root", "k")[0]
        assert len(d1.get_all("_root", "k")) == 2
        assert len(d2.get_all("_root", "k")) == 2
        # higher actor wins the lamport tie
        assert d1.get("_root", "k")[0] == ("scalar", ("str", "from2"))

    def test_overwrite_clears_conflict(self):
        d1, d2 = new_doc(1), new_doc(2)
        d1.put("_root", "k", "a")
        d2.put("_root", "k", "b")
        d1.merge(d2)
        d1.put("_root", "k", "resolved")
        assert len(d1.get_all("_root", "k")) == 1
        d2.merge(d1)
        assert d2.get("_root", "k")[0] == ("scalar", ("str", "resolved"))


class TestText:
    def test_splice_and_read(self):
        doc = new_doc()
        t = doc.put_object("_root", "text", ObjType.TEXT)
        doc.splice_text(t, 0, 0, "hello world")
        assert doc.text(t) == "hello world"
        assert doc.length(t) == 11
        doc.splice_text(t, 5, 6, " there")
        assert doc.text(t) == "hello there"
        doc.splice_text(t, 0, 5, "goodbye")
        assert doc.text(t) == "goodbye there"

    def test_concurrent_inserts_converge(self):
        d1 = new_doc(1)
        t = d1.put_object("_root", "text", ObjType.TEXT)
        d1.splice_text(t, 0, 0, "ab")
        d2 = d1.fork(actor(2))
        d1.splice_text(t, 1, 0, "X")
        d2.splice_text(t, 1, 0, "Y")
        d1.merge(d2)
        d2.merge(d1)
        assert d1.text(t) == d2.text(t)
        assert sorted(d1.text(t)) == ["X", "Y", "a", "b"]
        assert d1.text(t)[0] == "a" and d1.text(t)[3] == "b"

    def test_concurrent_deletes_converge(self):
        d1 = new_doc(1)
        t = d1.put_object("_root", "text", ObjType.TEXT)
        d1.splice_text(t, 0, 0, "abcdef")
        d2 = d1.fork(actor(2))
        d1.splice_text(t, 0, 2, "")  # delete ab
        d2.splice_text(t, 2, 2, "")  # delete cd
        d1.merge(d2)
        d2.merge(d1)
        assert d1.text(t) == d2.text(t) == "ef"

    def test_insert_into_deleted_region(self):
        d1 = new_doc(1)
        t = d1.put_object("_root", "text", ObjType.TEXT)
        d1.splice_text(t, 0, 0, "abc")
        d2 = d1.fork(actor(2))
        d1.splice_text(t, 1, 1, "")  # delete 'b'
        d2.splice_text(t, 2, 0, "X")  # insert after 'b'
        d1.merge(d2)
        d2.merge(d1)
        assert d1.text(t) == d2.text(t) == "aXc"


class TestLists:
    def test_insert_set_delete(self):
        doc = new_doc()
        lst = doc.put_object("_root", "l", ObjType.LIST)
        for i, v in enumerate([1, 2, 3]):
            doc.insert(lst, i, v)
        doc.put(lst, 1, 20)
        assert doc.hydrate()["l"] == [1, 20, 3]
        doc.delete(lst, 0)
        assert doc.hydrate()["l"] == [20, 3]
        assert doc.length(lst) == 2

    def test_interleaved_concurrent_lists(self):
        d1 = new_doc(1)
        lst = d1.put_object("_root", "l", ObjType.LIST)
        d1.insert(lst, 0, "base")
        d2 = d1.fork(actor(2))
        d1.insert(lst, 1, "one")
        d2.insert(lst, 1, "two")
        d1.merge(d2)
        d2.merge(d1)
        assert d1.hydrate()["l"] == d2.hydrate()["l"]


class TestCounters:
    def test_counter_increments(self):
        from automerge_tpu.types import ScalarValue

        doc = new_doc()
        doc.put("_root", "c", ScalarValue("counter", 10))
        doc.increment("_root", "c", 5)
        doc.increment("_root", "c", -3)
        assert doc.get("_root", "c")[0] == ("counter", 12)

    def test_concurrent_increments_merge_by_addition(self):
        from automerge_tpu.types import ScalarValue

        d1 = new_doc(1)
        d1.put("_root", "c", ScalarValue("counter", 0))
        d2 = d1.fork(actor(2))
        d1.increment("_root", "c", 10)
        d2.increment("_root", "c", 7)
        d1.merge(d2)
        d2.merge(d1)
        assert d1.get("_root", "c")[0] == ("counter", 17)
        assert d2.get("_root", "c")[0] == ("counter", 17)


class TestHistory:
    def test_heads_advance(self):
        doc = new_doc()
        assert doc.get_heads() == []
        doc.put("_root", "a", 1)
        h1 = doc.get_heads()
        assert len(h1) == 1
        doc.put("_root", "b", 2)
        h2 = doc.get_heads()
        assert len(h2) == 1 and h2 != h1

    def test_read_at_heads(self):
        doc = new_doc()
        doc.put("_root", "k", "v1")
        h1 = doc.get_heads()
        doc.put("_root", "k", "v2")
        assert doc.get("_root", "k")[0] == ("scalar", ("str", "v2"))
        assert doc.get("_root", "k", heads=h1)[0] == ("scalar", ("str", "v1"))

    def test_text_at_heads(self):
        doc = new_doc()
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice_text(t, 0, 0, "abc")
        h1 = doc.get_heads()
        doc.splice_text(t, 3, 0, "def")
        assert doc.text(t) == "abcdef"
        assert doc.text(t, heads=h1) == "abc"
        assert doc.length(t, heads=h1) == 3

    def test_fork_at(self):
        doc = new_doc()
        doc.put("_root", "k", "v1")
        h1 = doc.get_heads()
        doc.put("_root", "k", "v2")
        old = doc.fork_at(h1, actor(9))
        assert old.get("_root", "k")[0] == ("scalar", ("str", "v1"))

    def test_merge_heads_union(self):
        d1, d2 = new_doc(1), new_doc(2)
        d1.put("_root", "a", 1)
        d2.put("_root", "b", 2)
        d1.merge(d2)
        assert len(d1.get_heads()) == 2


class TestSaveLoad:
    def test_roundtrip_map(self):
        doc = new_doc()
        doc.put("_root", "hello", "world")
        doc.put("_root", "n", 42)
        data = doc.save()
        doc2 = AutoDoc.load(data)
        assert doc2.hydrate() == {"hello": "world", "n": 42}
        assert doc2.get_heads() == doc.get_heads()

    def test_roundtrip_text_and_lists(self):
        doc = new_doc()
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice_text(t, 0, 0, "hello world")
        doc.splice_text(t, 5, 1, "-")
        lst = doc.put_object("_root", "l", ObjType.LIST)
        doc.insert(lst, 0, 1)
        doc.insert(lst, 1, 2)
        doc.delete(lst, 0)
        data = doc.save()
        doc2 = AutoDoc.load(data)
        assert doc2.hydrate() == doc.hydrate()
        assert doc2.get_heads() == doc.get_heads()

    def test_roundtrip_multi_actor(self):
        d1, d2 = new_doc(1), new_doc(2)
        d1.put("_root", "a", 1)
        t = d2.put_object("_root", "t", ObjType.TEXT)
        d2.splice_text(t, 0, 0, "xy")
        d1.merge(d2)
        d1.put("_root", "a", 2)
        data = d1.save()
        d3 = AutoDoc.load(data)
        assert d3.hydrate() == d1.hydrate()
        assert d3.get_heads() == d1.get_heads()

    def test_roundtrip_counters(self):
        from automerge_tpu.types import ScalarValue

        d1 = new_doc(1)
        d1.put("_root", "c", ScalarValue("counter", 100))
        d2 = d1.fork(actor(2))
        d1.increment("_root", "c", 1)
        d2.increment("_root", "c", 2)
        d1.merge(d2)
        data = d1.save()
        d3 = AutoDoc.load(data)
        assert d3.get("_root", "c")[0] == ("counter", 103)

    def test_roundtrip_deleted_keys(self):
        doc = new_doc()
        doc.put("_root", "keep", 1)
        doc.put("_root", "drop", 2)
        doc.delete("_root", "drop")
        doc2 = AutoDoc.load(doc.save())
        assert doc2.hydrate() == {"keep": 1}

    def test_save_load_save_stable(self):
        doc = new_doc()
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice_text(t, 0, 0, "stable")
        data1 = doc.save()
        data2 = AutoDoc.load(data1).save()
        assert data1 == data2

    def test_incremental_save(self):
        doc = new_doc()
        doc.put("_root", "a", 1)
        h1 = doc.get_heads()
        doc.put("_root", "b", 2)
        inc = doc.save_incremental_after(h1)
        doc2 = new_doc(2)
        doc2.apply_changes([])
        base = doc.fork_at(h1)
        base.load_incremental(inc)
        assert base.hydrate() == doc.hydrate()

    def test_corrupt_save_rejected(self):
        doc = new_doc()
        doc.put("_root", "a", 1)
        data = bytearray(doc.save())
        data[len(data) // 2] ^= 0xFF
        with pytest.raises(Exception):
            AutoDoc.load(bytes(data))


class TestTransactions:
    def test_manual_commit(self):
        doc = new_doc()
        tx = doc.transaction(message="m1")
        tx.put("_root", "k", 1)
        h = tx.commit()
        assert h is not None
        assert doc.get("_root", "k")[0] == ("scalar", ("int", 1))

    def test_rollback(self):
        doc = new_doc()
        doc.put("_root", "keep", 1)
        doc.commit()
        tx = doc.transaction()
        tx.put("_root", "gone", 2)
        tx.put("_root", "keep", 99)
        tx.rollback()
        assert doc.get("_root", "gone") is None
        assert doc.get("_root", "keep")[0] == ("scalar", ("int", 1))

    def test_rollback_text(self):
        doc = new_doc()
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice_text(t, 0, 0, "abc")
        doc.commit()
        tx = doc.transaction()
        tx.splice_text(t, 1, 1, "XYZ")
        tx.rollback()
        assert doc.text(t) == "abc"

    def test_duplicate_seq_rejected(self):
        d1 = new_doc(1)
        d1.put("_root", "a", 1)
        d1.commit()
        ch = d1.doc.history[0].stored
        d2 = new_doc(1)
        d2.put("_root", "b", 2)  # same actor, seq 1, different change
        with pytest.raises(AutomergeError):
            d2.apply_changes([ch])


class TestIsolation:
    def test_isolated_edits_at_old_heads(self):
        doc = new_doc()
        doc.put("_root", "k", "v1")
        h1 = doc.get_heads()
        doc.put("_root", "k", "v2")
        doc.isolate(h1)
        doc.put("_root", "k", "isolated")
        doc.commit()
        doc.integrate()
        # after integrating, isolated edit conflicts with v2
        vals = {v for v, _ in doc.get_all("_root", "k")}
        assert ("scalar", ("str", "isolated")) in vals
        assert ("scalar", ("str", "v2")) in vals


class TestMidElementSplice:
    """Deleting mid-way through a multi-width text element rewinds to the
    element start and expands the span (reference inner_splice's
    adjusted_index, transaction/inner.rs:631-637)."""

    def test_delete_mid_element_rewinds(self):
        doc = new_doc()
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice(t, 0, 0, ["abc"])  # one element, width 3
        doc.splice_text(t, 0, 0, "x")
        doc.splice_text(t, 4, 0, "y")  # "x" + ["abc"] + "y"
        assert doc.text(t) == "xabcy"
        # delete 1 char at pos 2: mid-element -> whole "abc" element goes
        doc.splice_text(t, 2, 1, "")
        assert doc.text(t) == "xy"

    def test_delete_at_element_start_unaffected(self):
        doc = new_doc()
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice_text(t, 0, 0, "ab")
        doc.splice(t, 1, 0, ["XYZ"])
        assert doc.text(t) == "aXYZb"
        # deleting exactly at the element boundary keeps neighbours intact
        doc.splice_text(t, 1, 3, "")
        assert doc.text(t) == "ab"

    def test_mid_element_delete_with_insert(self):
        doc = new_doc()
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice(t, 0, 0, ["abc", "def"])
        assert doc.text(t) == "abcdef"
        # replace from mid "abc" through mid "def": both elements deleted,
        # replacement lands at the rewound position
        doc.splice_text(t, 1, 4, "Z")
        assert doc.text(t) == "Z"


class TestBlockIndex:
    """The order-statistics block index (op_store.Block) must agree with a
    linear walk after any interleaving of inserts/updates/deletes/merges."""

    def _assert_consistent(self, doc, obj):
        from automerge_tpu.core.op_store import LIST_ENC, TEXT_ENC

        info = doc.doc.ops.get_obj(doc.doc.import_obj(obj))
        data = info.data
        # block partition == element list, aggregates == recount
        walked = []
        vis = width = 0
        for b in data.blocks:
            bvis = bwidth = 0
            for el in b.els:
                walked.append(el)
                assert el.block is b
                w = el.winner()
                if w is not None:
                    bvis += 1
                    bwidth += w.text_width()
            assert (b.vis, b.width) == (bvis, bwidth), "stale block aggregates"
            vis += bvis
            width += bwidth
        linear = list(data.elements())
        assert walked == linear, "block order diverged from element list"
        assert vis == data.visible_len and width == data.text_width
        # nth through the index == nth by scan, every position
        enc = TEXT_ENC if data.obj_type.name == "TEXT" else LIST_ENC
        at = 0
        for el in linear:
            w = el.winner()
            if w is None:
                continue
            ww = w.text_width() if enc == TEXT_ENC else 1
            for i in range(at, at + ww):
                got = doc.doc.ops.nth(doc.doc.import_obj(obj), i, enc)
                assert got is el, f"nth({i}) mismatch"
            assert doc.doc.ops.position_of(doc.doc.import_obj(obj), el, enc) == at
            at += ww

    def test_randomized_block_consistency(self):
        import random

        rng = random.Random(7)
        doc = AutoDoc(actor=ActorId(bytes([1]) * 16))
        t = doc.put_object("_root", "t", ObjType.TEXT)
        for step in range(300):
            n = doc.length(t)
            r = rng.random()
            if r < 0.55 or n == 0:
                doc.splice_text(t, rng.randint(0, n), 0, rng.choice("abcdef") * rng.randint(1, 3))
            elif r < 0.85:
                pos = rng.randint(0, n - 1)
                doc.splice_text(t, pos, min(rng.randint(1, 3), n - pos), "")
            else:
                doc.commit()
                f = doc.fork(actor=ActorId(bytes([rng.randint(2, 250)]) * 16))
                m = doc.length(t)
                f.splice_text(t, rng.randint(0, m), 0, "XY")
                f.commit()
                doc.merge(f)
            if step % 50 == 49:
                self._assert_consistent(doc, t)
        self._assert_consistent(doc, t)

    def test_rollback_restores_block_index(self):
        doc = AutoDoc(actor=ActorId(bytes([1]) * 16))
        t = doc.put_object("_root", "t", ObjType.TEXT)
        doc.splice_text(t, 0, 0, "hello world")
        doc.commit()
        tx = doc.transaction()
        tx.splice_text(t, 0, 3, "XX")
        tx.rollback()
        assert doc.text(t) == "hello world"
        self._assert_consistent(doc, t)
