"""Native host merge engine vs the jax kernel: bit-exact equivalence.

merge_cols.cpp is the second engine behind ops/merge.py merge_columns —
below the size threshold (or via AUTOMERGE_TPU_ENGINE=native) it replaces
the device kernel on remote-accelerator hosts. Every output array must
match the jit kernel exactly on every workload shape, including historical
(covered-mask) views; mirrors the reference requirement that all apply
paths converge to one op set (reference: rust/automerge/tests/test.rs
merge scenarios).
"""

import numpy as np
import pytest

from automerge_tpu import bench as W
from automerge_tpu import native
from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.ops.merge import ALL_OUTPUTS, merge_columns
from automerge_tpu.types import ActorId, ObjType, ScalarValue

pytestmark = pytest.mark.skipif(
    not (native.available() and native.merge_available()),
    reason="native merge engine not available",
)


def actor(i: int) -> ActorId:
    return ActorId(bytes([i]) * 16)


def _rich_changes():
    """Maps, nested objects, text, counters, deletes, marks, conflicts."""
    base = AutoDoc(actor=actor(1))
    base.put("_root", "n", ScalarValue("counter", 5))
    text = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(text, 0, 0, "hello world")
    lst = base.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        base.insert(lst, i, i)
    base.commit()
    d1 = base.fork(actor=actor(2))
    d2 = base.fork(actor=actor(3))
    d1.increment("_root", "n", 3)
    d1.splice_text(text, 0, 5, "goodbye")
    d1.put("_root", "k", "one")
    d1.mark(text, 0, 4, "bold", True)
    d1.commit()
    d2.increment("_root", "n", -1)
    d2.delete(lst, 2)
    d2.insert(lst, 0, "x")
    d2.put("_root", "k", "two")
    d2.commit()
    docs = [d1, d2]
    out = []
    for d in docs:
        out.extend(a.stored for a in d.doc.history)
    return out


_WORKLOAD_CACHE = {}


def _workload(name):
    """Built lazily inside tests — collection must not touch the native
    encoders (the module skipif has to fire first on lib-less hosts)."""
    if name in _WORKLOAD_CACHE:
        return _WORKLOAD_CACHE[name]
    if name == "rich":
        changes = _rich_changes()
    elif name == "mapcounter":
        cdoc, keys = W.build_counter_base(6)
        mc, _ = W.synth_mapcounter(cdoc, keys, 12, 8)
        changes = [a.stored for a in cdoc.doc.history] + mc
    else:
        trace = W.load_trace(4000)
        base = W.build_base(trace, 1500)
        if name == "fanin":
            changes = list(base.changes) + W.synth_fanin(base, trace, 12, 40, 1500)
        else:
            changes = list(base.changes) + W.synth_rga(base, 15, 25)
    _WORKLOAD_CACHE[name] = changes
    return changes


def _assert_same(jx, nv, name, keys=ALL_OUTPUTS):
    for k in keys:
        a, b = np.asarray(jx[k]), np.asarray(nv[k])
        m = min(len(a), len(b))  # obj stats may differ in padded tail length
        assert np.array_equal(a[:m], b[:m]), (name, k)


@pytest.mark.parametrize("name", ["fanin", "rga", "mapcounter", "rich"])
def test_engine_equivalence(name):
    log = OpLog.from_changes(_workload(name))
    cols = log.padded_columns()
    jx = merge_columns(cols, linearize="device", fetch=ALL_OUTPUTS, n_objs=log.n_objs)
    nv = native.merge_cols(cols, log.n_objs)
    _assert_same(jx, nv, name)


def test_engine_equivalence_historical():
    """Covered-mask (clock-gated) views must match too."""
    changes = _rich_changes()
    log = OpLog.from_changes(changes)
    # cover only the first half of the log's ops (a plausible clock cut:
    # covered is per-row; the kernel must gate visibility identically)
    covered = np.zeros(log.n, np.bool_)
    covered[: log.n // 2] = True
    cols = log.padded_columns(covered=covered)
    jx = merge_columns(cols, linearize="device", fetch=ALL_OUTPUTS, n_objs=log.n_objs)
    nv = native.merge_cols(cols, log.n_objs)
    _assert_same(jx, nv, "historical")


def test_merge_columns_engine_env(monkeypatch):
    """AUTOMERGE_TPU_ENGINE=native routes merge_columns to the host engine
    and document reads stay identical."""
    changes = _rich_changes()
    log = OpLog.from_changes(changes)

    res_jax = merge_columns(
        log.padded_columns(), fetch=DeviceDoc.READ_FETCH, n_objs=log.n_objs
    )
    monkeypatch.setenv("AUTOMERGE_TPU_ENGINE", "native")
    res_nat = merge_columns(
        log.padded_columns(), fetch=DeviceDoc.READ_FETCH, n_objs=log.n_objs
    )
    assert set(res_nat) == set(DeviceDoc.READ_FETCH)
    d1 = DeviceDoc(log, res_jax)
    d2 = DeviceDoc(OpLog.from_changes(changes), res_nat)
    assert d1.hydrate() == d2.hydrate()


def test_map_hash_fallback():
    """Sparse (many objects x many disjoint props, few ops) exceeds the
    dense (obj x prop) table budget and exercises the hash group path."""
    doc = AutoDoc(actor=actor(9))
    for i in range(300):
        o = doc.put_object("_root", f"o{i}", ObjType.MAP)
        doc.put(o, f"p{i}a", i)
        doc.put(o, f"p{i}b", -i)
    doc.commit()
    changes = [a.stored for a in doc.doc.history]
    log = OpLog.from_changes(changes)
    cols = log.padded_columns()
    jx = merge_columns(cols, linearize="device", fetch=ALL_OUTPUTS, n_objs=log.n_objs)
    nv = native.merge_cols(cols, log.n_objs)
    _assert_same(jx, nv, "hash-fallback")


@pytest.mark.parametrize("name", ["fanin", "rga", "mapcounter", "rich"])
def test_scatter_kernel_matches_sort_kernel(name):
    """The sort-free scatter resolution (geometry-specialized) must match
    the sort-based kernel bit-for-bit on every workload shape."""
    import jax.numpy as jnp

    from automerge_tpu.ops.merge import (
        merge_kernel_core, scatter_geometry_ok, scatter_kernel_core,
    )

    log = OpLog.from_changes(_workload(name))
    cols_np = log.padded_columns()
    assert scatter_geometry_ok(
        len(cols_np["action"]), log.n_objs, len(log.props)
    )
    cols = {k: jnp.asarray(v) for k, v in cols_np.items()}
    o1 = merge_kernel_core(cols)
    o2 = scatter_kernel_core(log.n_objs, len(log.props))(cols)
    for k in (
        "visible", "winner", "conflicts", "succ_count", "inc_count",
        "counter_inc", "is_elem", "parent_row", "first_child", "next_sib",
        "obj_vis_len", "obj_text_width",
    ):
        a, b = np.asarray(o1[k]), np.asarray(o2[k])
        assert a.shape == b.shape, (name, k, a.shape, b.shape)
        assert np.array_equal(a, b), (name, k)


def test_join_rows_fuzz_and_key_zero():
    """The extraction join (interpolation + memo) against the numpy oracle,
    including the key-0 case the memo's empty marker must not alias
    (review regression) and memo-sized repetitive streams."""
    rng = np.random.default_rng(5)
    for trial in range(120):
        n = int(rng.integers(1, 3000))
        if trial % 3 == 0:
            s = np.sort(rng.integers(0, 1 << 40, n).astype(np.int64))
        elif trial % 3 == 1:  # clustered: adversarial for interpolation
            s = np.sort(
                np.concatenate(
                    [rng.integers(0, 64, n // 2 + 1),
                     rng.integers(1 << 39, (1 << 39) + 64, n // 2 + 1)]
                ).astype(np.int64)
            )[:n]
        else:  # duplicate-heavy
            s = np.sort(rng.integers(0, 40, n).astype(np.int64))
        q = np.concatenate(
            [rng.choice(s, min(n, 40)), rng.integers(-(1 << 41), 1 << 41, 40)]
        ).astype(np.int64)
        got = native.join_rows(s, q, -7)
        pos = np.searchsorted(s, q)
        posc = np.clip(pos, 0, n - 1)
        want = np.where(s[posc] == q, posc, -7).astype(np.int32)
        assert np.array_equal(got, want), trial
    # key 0, large repetitive stream (memo active): absent then present
    s0 = np.sort(rng.integers(1, 1 << 40, 100_000).astype(np.int64))
    q0 = np.zeros(80_000, np.int64)
    assert (native.join_rows(s0, q0, -1) == -1).all()
    s1 = np.unique(np.concatenate([[0], s0]))
    assert (native.join_rows(s1, q0, -1) == 0).all()


def test_join_rows_int64_min_key():
    """INT64_MIN (the memo's empty marker) as a query key must search, not
    false-hit a pristine slot (review regression); memo active via total
    query count regardless of the thread split."""
    rng = np.random.default_rng(9)
    s = np.sort(rng.integers(1, 1 << 40, 50_000).astype(np.int64))
    q = np.full(150_000, np.iinfo(np.int64).min, np.int64)
    assert (native.join_rows(s, q, -3) == -3).all()
