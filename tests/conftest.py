"""Test configuration: force an 8-device virtual CPU mesh for all tests.

The environment pins jax to a real accelerator (the axon TPU tunnel
registers itself in sitecustomize and overrides JAX_PLATFORMS), so tests
must force the platform through jax.config, and XLA_FLAGS must request the
virtual host devices before the CPU backend initializes. Tests exercise
sharding on the 8-device virtual CPU mesh; benchmarks (bench.py) run on
the real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy fault-injection / stress cases (tier-1 runs -m 'not slow')"
    )
