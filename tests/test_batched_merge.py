"""Differential tests for the cross-document batched device merge.

A padded multi-document super-batch (ops/batched.py) must materialize
every document BIT-IDENTICALLY to serial per-doc ``apply_changes`` —
same resolution arrays, same reads, same historical views — across
random interleavings, mixed document sizes, out-of-order delivery,
duplicate re-delivery, empty deltas, and the fallback-ratio boundary.
Plus: the group-commit batcher under real threads, and the whale-doc
mesh residency mode degrading cleanly when jax.shard_map / a
multi-device mesh is unavailable.
"""

import random
import threading

import numpy as np
import pytest

from automerge_tpu import obs
from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.ops.batched import (
    BatchStage,
    CrossDocBatcher,
    apply_cross_doc,
    plan_stages,
)
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i: int) -> ActorId:
    return ActorId(bytes([i]) * 16)


def build_base(ballast: int = 300):
    """A doc with a live text + list + counter and an untouched ballast
    object (keeps delta dirty fractions below the per-doc full-reresolve
    cost model, the serve-shaped profile)."""
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "the quick brown fox")
    lst = base.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        base.insert(lst, i, i * 10)
    base.put("_root", "c", ScalarValue("counter", 5))
    if ballast:
        arch = base.put_object("_root", "archive", ObjType.TEXT)
        base.splice_text(arch, 0, 0, "x" * ballast)
    base.commit()
    return base, t, lst


def edit_fork(f, t, lst, rng, tag):
    ln = f.length(t)
    pos = rng.randrange(0, max(ln, 1))
    if rng.random() < 0.3 and ln > 1:
        f.splice_text(t, min(pos, ln - 1), 1, "")
    else:
        f.splice_text(t, pos, 0, f"<{tag}>")
    r = rng.random()
    if r < 0.3:
        f.increment("_root", "c", rng.randrange(1, 5))
    elif r < 0.6:
        f.put("_root", f"k{rng.randrange(3)}", tag)
    elif f.length(lst):
        if rng.random() < 0.5:
            f.insert(lst, rng.randrange(0, f.length(lst) + 1), tag)
        else:
            f.delete(lst, rng.randrange(0, f.length(lst)))
    f.commit()


def assert_bit_identical(dev, ref, ctx=""):
    assert dev.hydrate() == ref.hydrate(), ctx
    assert sorted(dev.current_heads()) == sorted(ref.current_heads()), ctx
    for a in ("visible", "winner", "conflicts", "elem_index"):
        assert np.array_equal(getattr(dev, a), getattr(ref, a)), (ctx, a)
    n2 = ref.log.n_objs + 2
    assert np.array_equal(
        dev.res["obj_vis_len"][:n2], ref.res["obj_vis_len"][:n2]
    ), ctx
    assert np.array_equal(
        dev.res["obj_text_width"][:n2], ref.res["obj_text_width"][:n2]
    ), ctx


def launch_counts():
    return obs.counter_values("device.kernel_launches", "path")


@pytest.mark.parametrize("seed", [0, 3, 11])
def test_random_interleavings_match_serial_per_doc(seed):
    """N docs of mixed sizes drained over several cycles: the cross-doc
    batch materializes exactly what serial per-doc apply_changes does,
    under shuffled, split, duplicated and dependency-gapped delivery."""
    rng = random.Random(seed)
    n_docs = 4
    docs = []
    for i in range(n_docs):
        # mixed sizes, including one tiny doc with NO ballast (its deltas
        # trip the per-doc full-reresolve fallback inside stage_batches)
        base, t, lst = build_base(ballast=0 if i == 0 else 150 * i)
        chs = [a.stored for a in base.doc.history]
        batched = DeviceDoc.resolve(OpLog.from_changes(chs))
        serial = DeviceDoc.resolve(OpLog.from_changes(chs))
        forks = [base.fork(actor=actor(20 + 4 * i + j)) for j in range(2)]
        docs.append({
            "base": base, "t": t, "lst": lst, "batched": batched,
            "serial": serial, "forks": forks,
            "seen": {c.hash for c in chs},
        })
    for cycle in range(4):
        work = []
        serial_feed = []
        for i, d in enumerate(docs):
            if rng.random() < 0.2:
                work.append((d["batched"], []))  # empty drain for this doc
                serial_feed.append((d["serial"], []))
                continue
            f = d["forks"][rng.randrange(len(d["forks"]))]
            edit_fork(f, d["t"], d["lst"], rng, f"{seed}.{cycle}.{i}")
            delta = [
                a.stored for a in f.doc.history
                if a.stored.hash not in d["seen"]
            ]
            d["seen"].update(c.hash for c in delta)
            rng.shuffle(delta)  # out-of-order: deps may arrive late
            batches = []
            while delta:
                k = rng.randrange(1, len(delta) + 1)
                b = delta[:k]
                delta = delta[k:]
                if b and rng.random() < 0.3:
                    b = b + [b[0]]  # duplicate re-delivery
                batches.append(b)
            work.append((d["batched"], batches))
            serial_feed.append((d["serial"], batches))
            # forks converge through the host doc so later edits merge
            d["base"].apply_changes(
                [a.stored for a in f.doc.history if a.stored.hash is not None]
            )
            for g in d["forks"]:
                g.merge(d["base"])
        apply_cross_doc(work)
        for dev, batches in serial_feed:
            for b in batches:
                dev.apply_changes(b)
        for i, d in enumerate(docs):
            assert d["batched"].pending_changes() == d["serial"].pending_changes()
            assert_bit_identical(
                d["batched"], d["serial"], f"seed {seed} cycle {cycle} doc {i}"
            )
    # historical views ride the same resolution arrays
    for d in docs:
        heads = d["batched"].current_heads()
        assert d["batched"].at(heads).hydrate() == d["serial"].at(heads).hydrate()


def _doc_with_delta(i, ballast=300, edits=1):
    base, t, lst = build_base(ballast=ballast)
    chs = [a.stored for a in base.doc.history]
    f = base.fork(actor=actor(10 + i))
    for j in range(edits):
        f.splice_text(t, (i + j) % max(f.length(t), 1), 0, f"<{i}.{j}>")
    f.commit()
    have = {c.hash for c in chs}
    delta = [a.stored for a in f.doc.history if a.stored.hash not in have]
    return chs, delta


def test_mixed_sizes_share_one_launch():
    """Docs of very different (non-whale) sizes pack into ONE launch."""
    work, serial = [], []
    for i, (ballast, edits) in enumerate([(150, 1), (400, 2), (800, 3)]):
        chs, delta = _doc_with_delta(i, ballast=ballast, edits=edits)
        work.append((DeviceDoc.resolve(OpLog.from_changes(chs)), [delta]))
        s = DeviceDoc.resolve(OpLog.from_changes(chs))
        s.apply_changes(delta)
        serial.append(s)
    before = launch_counts()
    out = apply_cross_doc(work)
    after = launch_counts()
    assert out["batched"] == 3 and out["fallback"] == 0, out
    assert after.get("batched", 0) - before.get("batched", 0) == 1
    assert after.get("per_doc", 0) == before.get("per_doc", 0)
    for (dev, _), s in zip(work, serial):
        assert_bit_identical(dev, s)


def test_empty_deltas_no_launch():
    chs, delta = _doc_with_delta(0)
    dev = DeviceDoc.resolve(OpLog.from_changes(chs))
    before = launch_counts()
    out = apply_cross_doc([(dev, []), (dev, [[]])])
    after = launch_counts()
    assert out == {"applied": 0, "batched": 0, "fallback": 0}
    assert after == before
    # duplicates of already-resident changes are also a no-op
    dev.apply_changes(delta)
    out = apply_cross_doc([(dev, [delta])])
    assert out == {"applied": 0, "batched": 0, "fallback": 0}


def test_fallback_ratio_boundary():
    """The whale rule is STRICT: a doc at exactly ratio x total stays in
    the batch; one row over is peeled (largest first, totals recomputed)."""

    def fake(n):
        return BatchStage(None, np.arange(n), np.arange(1))

    # 20 == 0.5 * (10 + 10 + 20): boundary — stays batched
    batch, whales = plan_stages([fake(10), fake(10), fake(20)], 0.5)
    assert len(batch) == 3 and not whales
    # 21 > 0.5 * 41: peeled; the remaining pair is balanced and stays
    batch, whales = plan_stages([fake(10), fake(10), fake(21)], 0.5)
    assert len(batch) == 2 and len(whales) == 1
    assert whales[0].n_rows == 21
    # ratio >= 1 never peels (a doc cannot exceed its own total)
    batch, whales = plan_stages([fake(1), fake(1000)], 1.0)
    assert len(batch) == 2 and not whales
    # ratio 0 peels everything down to the smallest doc
    batch, whales = plan_stages([fake(3), fake(2), fake(1)], 0.0)
    assert len(batch) == 1 and batch[0].n_rows == 1
    assert [w.n_rows for w in whales] == [3, 2]
    # a single doc is never peeled against itself
    batch, whales = plan_stages([fake(50)], 0.0)
    assert len(batch) == 1 and not whales


def test_whale_falls_back_per_doc_end_to_end():
    """A dominating doc resolves per-doc; results stay bit-identical.
    The whale rule compares DIRTY-SUBSET rows (the kernel work), so the
    whale is a doc whose edited object dwarfs the others' — its ballast
    only keeps it on the subset path."""
    specs = [(150, 1), (150, 1), (2500, 60)]  # the third is the whale
    work, serial = [], []
    for i, (ballast, edits) in enumerate(specs):
        chs, delta = _doc_with_delta(i, ballast=ballast, edits=edits)
        work.append((DeviceDoc.resolve(OpLog.from_changes(chs)), [delta]))
        s = DeviceDoc.resolve(OpLog.from_changes(chs))
        s.apply_changes(delta)
        serial.append(s)
    before = launch_counts()
    out = apply_cross_doc(work, fallback_ratio=0.5)
    after = launch_counts()
    assert out["batched"] == 2 and out["fallback"] == 1, out
    assert after.get("batched", 0) - before.get("batched", 0) == 1
    # the whale's subset re-resolution ran through the per-doc path
    assert after.get("per_doc", 0) - before.get("per_doc", 0) == 1
    for (dev, _), s in zip(work, serial):
        assert_bit_identical(dev, s)


def test_duplicate_doc_in_work_merges_stages():
    """The same DeviceDoc listed twice must merge into one stage — a
    second append would splice the log out from under the first stage's
    row indices (silent corruption, not an exception)."""
    base, t, lst = build_base(ballast=300)
    chs = [a.stored for a in base.doc.history]
    have = {c.hash for c in chs}
    f1 = base.fork(actor=actor(10))
    f1.splice_text(t, 2, 0, "<one>")
    f1.commit()
    d1 = [a.stored for a in f1.doc.history if a.stored.hash not in have]
    f2 = base.fork(actor=actor(11))
    f2.splice_text(t, 0, 0, "<two>")
    f2.put("_root", "k0", "dup")
    f2.commit()
    d2 = [a.stored for a in f2.doc.history if a.stored.hash not in have]
    dev = DeviceDoc.resolve(OpLog.from_changes(chs))
    ref = DeviceDoc.resolve(OpLog.from_changes(chs))
    ref.apply_changes(d1)
    ref.apply_changes(d2)
    out = apply_cross_doc([(dev, [d1]), (dev, [d2])])
    assert out["applied"] == len(d1) + len(d2)
    assert out["batched"] + out["fallback"] <= 1  # ONE stage for the doc
    assert_bit_identical(dev, ref)


def test_stage_batches_contract():
    chs, delta = _doc_with_delta(0)
    dev = DeviceDoc.resolve(OpLog.from_changes(chs))
    # a historical view cannot stage
    view = dev.at(dev.current_heads())
    with pytest.raises(ValueError):
        view.stage_batches([delta])
    # staging appends host-side; the stage carries the dirty subset
    n, stage = dev.stage_batches([delta])
    assert n == len(delta) and stage is not None
    assert stage.doc is dev and len(stage.rows) > 0
    # resolving the stage via the packer completes the apply
    from automerge_tpu.ops.batched import resolve_stages

    resolve_stages([stage])
    ref = DeviceDoc.resolve(OpLog.from_changes(chs))
    ref.apply_changes(delta)
    assert_bit_identical(dev, ref)


def test_cross_doc_batcher_threads():
    """Concurrent workers draining different docs share one launch."""
    n = 3
    work, serial = [], []
    for i in range(n):
        chs, delta = _doc_with_delta(i, ballast=200 + 100 * i)
        work.append((DeviceDoc.resolve(OpLog.from_changes(chs)), [delta]))
        s = DeviceDoc.resolve(OpLog.from_changes(chs))
        s.apply_changes(delta)
        serial.append(s)
    batcher = CrossDocBatcher(mode="1", window_ms=200.0, max_docs=n)
    before = launch_counts()
    errs = []
    barrier = threading.Barrier(n)

    def worker(dev, batches):
        try:
            barrier.wait()
            batcher.apply(dev, batches)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [
        threading.Thread(target=worker, args=(dev, batches))
        for dev, batches in work
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    after = launch_counts()
    assert not errs, errs
    assert after.get("batched", 0) - before.get("batched", 0) == 1
    for (dev, _), s in zip(work, serial):
        assert_bit_identical(dev, s)


def test_cross_doc_batcher_inactive_mode():
    """mode='0' routes through the per-doc apply_batches path."""
    chs, delta = _doc_with_delta(0)
    dev = DeviceDoc.resolve(OpLog.from_changes(chs))
    ref = DeviceDoc.resolve(OpLog.from_changes(chs))
    ref.apply_changes(delta)
    batcher = CrossDocBatcher(mode="0")
    assert not batcher.active()
    assert batcher.apply(dev, [delta]) == len(delta)
    assert_bit_identical(dev, ref)


# -- whale-doc mesh residency -------------------------------------------------


def _mesh_usable(n: int = 2) -> bool:
    import jax

    return hasattr(jax, "shard_map") and len(jax.devices()) >= n


def test_enable_mesh_degrades_cleanly():
    """Without jax.shard_map / a multi-device mesh, enable_mesh refuses
    (returns False) and every apply keeps working single-device — the
    graceful skip the acceptance criteria require. On a capable mesh the
    sharded full re-resolution must match the per-doc kernel exactly."""
    chs, delta = _doc_with_delta(0, ballast=0)  # tiny: full reresolve path
    dev = DeviceDoc.resolve(OpLog.from_changes(chs))
    ref = DeviceDoc.resolve(OpLog.from_changes(chs))
    ok = dev.enable_mesh(2, min_rows=0)
    assert ok == _mesh_usable(2)
    dev.apply_changes(delta)
    ref.apply_changes(delta)
    assert_bit_identical(dev, ref)
    if not ok:
        # the refusal was counted with a reason label
        reasons = {
            e["labels"].get("reason")
            for e in obs.snapshot()
            if e["name"] == "device.mesh_unavailable"
        }
        assert reasons, "mesh refusal not observed"


@pytest.mark.skipif(
    not _mesh_usable(2), reason="jax.shard_map or a multi-device mesh absent"
)
def test_mesh_full_reresolve_matches_single_device():
    chs, delta = _doc_with_delta(1, ballast=400, edits=4)
    dev = DeviceDoc.resolve(OpLog.from_changes(chs))
    ref = DeviceDoc.resolve(OpLog.from_changes(chs))
    assert dev.enable_mesh(2, min_rows=0)
    before = launch_counts()
    # force the full re-resolution path (every delta over the limit)
    import os

    os.environ["AUTOMERGE_TPU_DIRTY_FRACTION"] = "0"
    try:
        dev.apply_changes(delta)
        ref.apply_changes(delta)
    finally:
        del os.environ["AUTOMERGE_TPU_DIRTY_FRACTION"]
    after = launch_counts()
    assert after.get("sharded", 0) > before.get("sharded", 0)
    assert_bit_identical(dev, ref)
