"""Differential suite for compressed resident columns (ops/compressed.py).

Dense residency (``AUTOMERGE_TPU_COMPRESSED=0``) is the oracle: the same
random interleavings x out-of-order/duplicate delivery staged under
compressed residency must leave every document bit-identical —
column-level OpLog equality, full DeviceDoc arrays, identical
``at(heads)`` views. Plus codec-level properties: encode/decode/slice/
splice roundtrips, tail-append run extension (the last run extends
instead of re-encoding), the offset-value-coded join against the
searchsorted oracle, degenerate-run demotion through the ratio gate, and
the compressed H2D staging expanding bit-identically on device.
"""

import numpy as np
import pytest

from automerge_tpu import obs
from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import compressed as C
from automerge_tpu.ops import host_batch
from automerge_tpu.ops.batched import resolve_stages
from automerge_tpu.ops.compressed import CompressedOpColumns, StrideRuns
from automerge_tpu.ops.device_doc import DeviceDoc
from automerge_tpu.ops.oplog import OpLog
from automerge_tpu.types import ActorId, ObjType

from .test_host_batch import assert_identical, build_workload

# -- codec properties ---------------------------------------------------------


def _random_column(rng, n, kind):
    if kind == 0:  # low-cardinality (action/vtag shape)
        return rng.integers(0, 3, n).astype(np.int32)
    if kind == 1:  # strictly sorted keys (id_key shape)
        return np.cumsum(rng.integers(1, 5, n)).astype(np.int64)
    if kind == 2:  # typing chain (elem_ref shape)
        return (np.arange(n) - 1).astype(np.int32)
    return rng.integers(-50, 50, n).astype(np.int32)  # degenerate


@pytest.mark.parametrize("seed", [0, 3, 11])
def test_codec_roundtrip_slice_splice(seed):
    rng = np.random.default_rng(seed)
    for trial in range(60):
        n = int(rng.integers(0, 120))
        x = _random_column(rng, n, trial % 4)
        for stride in (True, False):
            r = StrideRuns.encode(x, stride=stride)
            assert np.array_equal(r.decode(), x)
            assert r.nbytes == 24 * r.run_count
            if n:
                lo = int(rng.integers(0, n))
                hi = int(rng.integers(lo, n + 1))
                assert np.array_equal(r.slice(lo, hi).decode(), x[lo:hi])
                pos = int(rng.integers(0, n + 1))
                ins = _random_column(rng, int(rng.integers(0, 9)), trial % 4)
                spliced = r.splice(pos, ins)
                assert np.array_equal(
                    spliced.decode(),
                    np.concatenate([x[:pos], ins.astype(x.dtype), x[pos:]]),
                )


@pytest.mark.parametrize("seed", [1, 8])
def test_tail_extension_matches_reencode(seed):
    rng = np.random.default_rng(seed)
    for trial in range(80):
        n = int(rng.integers(0, 80))
        k = int(rng.integers(0, 40))
        kind = trial % 4
        x = _random_column(rng, n + k, kind)
        for stride in (True, False):
            r = StrideRuns.encode(x[:n], stride=stride)
            r.extend_tail(x[n:])
            assert np.array_equal(r.decode(), x), (trial, stride)


def test_tail_append_extends_last_run_not_reencodes():
    # the typing-chain contract: continuing runs stay ONE run
    x = np.arange(4096, dtype=np.int64)
    r = StrideRuns.encode(x[:1024])
    for lo in range(1024, 4096, 256):
        r.extend_tail(x[lo:lo + 256])
    assert r.run_count == 1
    assert r.is_sorted
    y = np.full(4096, 9, np.int32)
    r = StrideRuns.encode(y[:100], stride=False)
    r.extend_tail(y[100:])
    assert r.run_count == 1


@pytest.mark.parametrize("seed", [2, 13])
def test_ovc_join_matches_searchsorted_oracle(seed):
    rng = np.random.default_rng(seed)
    for _ in range(40):
        x = np.unique(rng.integers(0, 50_000, int(rng.integers(1, 400))))
        r = StrideRuns.encode(x.astype(np.int64))
        keys = rng.integers(-100, 50_100, 300).astype(np.int64)
        pos = np.searchsorted(x, keys)
        posc = np.clip(pos, 0, len(x) - 1)
        expect = np.where(x[posc] == keys, posc, -3).astype(np.int32)
        assert np.array_equal(r.join(keys, -3), expect)
    # join after a tail extension sees the extended rows
    x = np.unique(rng.integers(0, 10_000, 500)).astype(np.int64)
    r = StrideRuns.encode(x[:300])
    r.extend_tail(x[300:])
    keys = x[::7]
    assert np.array_equal(r.join(keys, -1), np.arange(len(x))[::7])


def test_unsorted_column_refuses_join():
    r = StrideRuns.encode(np.array([5, 3, 9], np.int64))
    assert not r.is_sorted
    with pytest.raises(ValueError):
        r.join(np.array([3], np.int64), -1)


def test_ratio_gate_demotes_degenerate_runs(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")

    class FakeLog:
        pass

    log = FakeLog()
    rng = np.random.default_rng(5)
    n = 512
    log.n = n
    log.pred_src = np.empty(0, np.int32)
    log.pred_tgt = np.empty(0, np.int32)
    log.pred_key = np.empty(0, np.int64)
    for name, _, _ in C.ROW_SPEC:
        setattr(log, name, rng.integers(0, 1 << 30, n).astype(np.int64))
    log.insert = np.asarray(rng.integers(0, 2, n), np.bool_)
    log.expand = np.asarray(rng.integers(0, 2, n), np.bool_)
    before = obs.counter_values("oplog.compress_fallback", "reason")
    comp = CompressedOpColumns().sync(log)
    after = obs.counter_values("oplog.compress_fallback", "reason")
    # random int columns cross the run gate and demote to dense
    demoted = [k for k, v in comp.run_counts().items() if v == -1]
    assert "id_key" in demoted and "action" in demoted, demoted
    assert after.get("ratio", 0) > before.get("ratio", 0)
    assert comp.id_runs() is None
    # demoted columns account dense; the bool columns still compress
    assert comp.nbytes(log) <= comp.dense_nbytes(log)


def test_compressed_image_decodes_to_live_columns():
    base = AutoDoc(actor=ActorId(bytes([20]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "compressed residency " * 8)
    base.commit()
    log = OpLog.from_documents([base])
    comp = log.compressed()
    assert comp is not None
    for name, _, _ in C.ROW_SPEC:
        ent = comp.entries.get(name)
        if ent is None or ent is C._DENSE:
            continue
        col = getattr(log, name)
        if name in ("insert", "expand"):
            col = np.asarray(col, np.bool_).view(np.int8)
        assert np.array_equal(ent.decode(), np.asarray(col)), name
    # the typing doc compresses well and the accounting says so
    assert log.resident_column_nbytes() * 2 < log.dense_column_nbytes()
    assert log.compress_ratio() > 2.0


# -- compressed H2D staging ---------------------------------------------------


def test_stage_cols_device_expands_bit_identically(monkeypatch):
    from automerge_tpu.ops.merge import stage_cols_device

    base = AutoDoc(actor=ActorId(bytes([20]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "expand on device " * 40)
    base.put("_root", "k", 7)
    base.commit()
    log = OpLog.from_documents([base])
    cols = log.padded_columns()
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    h0 = obs.counter_values("device.h2d_bytes", "").get("", 0)
    dev_c = stage_cols_device(cols)
    h1 = obs.counter_values("device.h2d_bytes", "").get("", 0)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    dev_d = stage_cols_device(cols)
    h2 = obs.counter_values("device.h2d_bytes", "").get("", 0)
    for k in cols:
        a, b = np.asarray(dev_c[k]), np.asarray(dev_d[k])
        assert a.dtype == b.dtype and np.array_equal(a, b), k
    # compressed staging moved measurably fewer bytes than dense
    assert (h1 - h0) * 2 < (h2 - h1), (h1 - h0, h2 - h1)


# -- end-to-end differential: compressed vs dense residency -------------------


def _drive(docs, deltas, cycles):
    devs = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
    for c in range(cycles):
        stages, results = host_batch.stage_docs(
            [(devs[i], [deltas[i][c]]) for i in range(len(docs))]
        )
        for r in results.values():
            assert r.error is None, repr(r.error)
        if stages:
            resolve_stages(stages)
    return devs


@pytest.mark.parametrize("seed", [4, 23])
def test_differential_compressed_vs_dense(monkeypatch, seed):
    docs, deltas = build_workload(seed, n_docs=4, cycles=4)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    ovc0 = obs.counter_values("oplog.ovc_join", "").get("", 0)
    comp = _drive(docs, deltas, 4)
    ovc1 = obs.counter_values("oplog.ovc_join", "").get("", 0)
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    dense = _drive(docs, deltas, 4)
    for i in range(len(docs)):
        assert_identical(comp[i], dense[i], i)
        heads = comp[i].current_heads()
        assert comp[i].at(heads).hydrate() == dense[i].at(heads).hydrate()
        assert comp[i].at([]).hydrate() == dense[i].at([]).hydrate()
    # non-vacuous: the offset-value-coded join actually ran
    assert ovc1 > ovc0


def test_scalar_append_path_differential(monkeypatch):
    # the per-doc apply_changes path (OpLog.append_changes) under both
    # modes, including out-of-order delivery that forces non-tail
    # splices and pending buffering — the cache-invalidation edge
    docs, deltas = build_workload(31, n_docs=2, cycles=4, dup=True)

    def run():
        devs = [DeviceDoc.resolve(OpLog.from_documents([d])) for d in docs]
        for i, dv in enumerate(devs):
            order = [2, 0, 1, 3] if i % 2 else [1, 3, 0, 2]
            for c in order:
                dv.apply_changes(deltas[i][c])
        return devs

    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    comp = run()
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    dense = run()
    for i in range(len(docs)):
        assert_identical(comp[i], dense[i], i)
        # the compressed image (rebuilt after any invalidation) still
        # decodes to the live columns
        monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
        cc = comp[i].log.compressed()
        for name, _, _ in C.ROW_SPEC:
            ent = cc.entries.get(name)
            if ent is None or ent is C._DENSE:
                continue
            col = getattr(comp[i].log, name)
            if name in ("insert", "expand"):
                col = np.asarray(col, np.bool_).view(np.int8)
            assert np.array_equal(ent.decode(), np.asarray(col)), (i, name)


def test_splice_into_run_boundaries():
    # splice at run head / mid-run / run tail / between runs
    x = np.repeat(np.arange(4, dtype=np.int32), 10)
    r = StrideRuns.encode(x, stride=False)
    for pos in (0, 5, 10, 19, 20, 39, 40):
        out = r.splice(pos, np.array([99], np.int32))
        expect = np.concatenate([x[:pos], [99], x[pos:]]).astype(np.int32)
        assert np.array_equal(out.decode(), expect), pos
        r = StrideRuns.encode(x, stride=False)  # splice may mutate (tail)


def test_gauges_report_true_resident_bytes(monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    base = AutoDoc(actor=ActorId(bytes([20]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "gauge " * 200)
    base.commit()
    dev = DeviceDoc.resolve(OpLog.from_documents([base]))
    dev.obs_name = "gauged"
    dev._export_doc_gauges()
    snap = {
        (e["name"], e["labels"].get("doc")): e["value"]
        for e in obs.snapshot()
        if e["type"] == "gauge" and e["name"].startswith("doc.")
    }
    got = snap[("doc.device_bytes", "gauged")]
    assert got == dev.resident_nbytes()
    assert got < dev.dense_nbytes()  # true bytes, not dense-equivalent
    assert snap[("doc.compress_ratio", "gauged")] > 1.5
    # the store's admission estimate sees the same truth
    from automerge_tpu.store.policy import device_resident_bytes

    assert device_resident_bytes(dev) == dev.resident_nbytes()


def test_cross_thread_estimate_never_touches_compressed_image(monkeypatch):
    # the DocStore evict sweeper reads residency OFF-thread: its
    # estimate must be pure reads — syncing the compressed image there
    # would race an in-flight append's eager id-run extension
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    base = AutoDoc(actor=ActorId(bytes([20]) * 16))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "estimate " * 50)
    base.commit()
    dev = DeviceDoc.resolve(OpLog.from_documents([base]))
    from automerge_tpu.store.policy import device_resident_bytes

    assert dev.log._comp is None
    est = device_resident_bytes(dev)
    assert dev.log._comp is None  # pure read: image untouched
    assert est == dev.dense_nbytes()  # dense fallback before first stamp
    # the owning thread stamps the cache; the observer then sees truth
    true = dev.resident_nbytes()
    assert device_resident_bytes(dev) == true
    assert true < est


def test_migration_wire_codec_roundtrip(monkeypatch):
    from automerge_tpu.cluster.node import _unwire_blob, _wire_blob

    payload = b"journal rows " * 400
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "1")
    b64, codec = _wire_blob(payload)
    assert codec == "zlib"
    assert len(b64) < len(payload)  # compressed on the wire
    assert _unwire_blob(b64, codec) == payload
    # small payloads and dense mode ship raw; absent codec decodes raw
    b64s, codec_s = _wire_blob(b"tiny")
    assert codec_s is None and _unwire_blob(b64s, None) == b"tiny"
    monkeypatch.setenv("AUTOMERGE_TPU_COMPRESSED", "0")
    b64d, codec_d = _wire_blob(payload)
    assert codec_d is None and _unwire_blob(b64d, codec_d) == payload
