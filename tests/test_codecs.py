"""Codec roundtrip and byte-format tests.

Golden byte expectations follow the reference formats
(rust/automerge/src/columnar/encoding/*.rs); roundtrips are property-style
over randomized inputs.
"""

import random

import pytest

from automerge_tpu.utils.codecs import (
    BooleanEncoder,
    DeltaEncoder,
    MaybeBooleanEncoder,
    RleEncoder,
    boolean_decode,
    delta_decode,
    rle_decode,
)
from automerge_tpu.utils.leb128 import (
    decode_sleb,
    decode_uleb,
    encode_sleb,
    encode_uleb,
    lebsize,
    sleb_bytes,
    uleb_bytes,
    ulebsize,
)


class TestLeb128:
    @pytest.mark.parametrize(
        "value,expected",
        [
            (0, b"\x00"),
            (1, b"\x01"),
            (127, b"\x7f"),
            (128, b"\x80\x01"),
            (300, b"\xac\x02"),
            (2**64 - 1, b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01"),
        ],
    )
    def test_uleb_golden(self, value, expected):
        assert uleb_bytes(value) == expected
        got, pos = decode_uleb(expected, 0)
        assert got == value and pos == len(expected)

    @pytest.mark.parametrize(
        "value,expected",
        [
            (0, b"\x00"),
            (1, b"\x01"),
            (-1, b"\x7f"),
            (63, b"\x3f"),
            (64, b"\xc0\x00"),
            (-64, b"\x40"),
            (-65, b"\xbf\x7f"),
            (-123456, b"\xc0\xbb\x78"),
        ],
    )
    def test_sleb_golden(self, value, expected):
        assert sleb_bytes(value) == expected
        got, pos = decode_sleb(expected, 0)
        assert got == value and pos == len(expected)

    def test_roundtrip_random(self):
        rng = random.Random(0)
        for _ in range(500):
            u = rng.randrange(0, 2**64)
            assert decode_uleb(uleb_bytes(u), 0)[0] == u
            s = rng.randrange(-(2**63), 2**63)
            assert decode_sleb(sleb_bytes(s), 0)[0] == s

    def test_sizes(self):
        for v in [0, 1, 127, 128, 2**32, 2**64 - 1]:
            assert ulebsize(v) == len(uleb_bytes(v))
        for v in [0, 1, -1, 63, 64, -64, -65, 2**62, -(2**62)]:
            assert lebsize(v) == len(sleb_bytes(v))


class TestRle:
    def test_run(self):
        e = RleEncoder("uint")
        for _ in range(5):
            e.append_value(42)
        # run of 5 x 42
        assert e.finish() == b"\x05\x2a"

    def test_literal_run(self):
        e = RleEncoder("uint")
        for v in [1, 2, 3]:
            e.append_value(v)
        # literal run of 3: sleb(-3) = 0x7d
        assert e.finish() == b"\x7d\x01\x02\x03"

    def test_null_runs(self):
        e = RleEncoder("uint")
        e.append_value(7)
        for _ in range(4):
            e.append_null()
        e.append_value(7)
        # literal [7], null x4, literal [7]
        assert e.finish() == b"\x7f\x07\x00\x04\x7f\x07"

    def test_all_null_is_empty(self):
        e = RleEncoder("uint")
        for _ in range(10):
            e.append_null()
        assert e.finish() == b""

    def test_literal_then_run_transition(self):
        # [1, 2, 2] must flush literal [1] then run of 2 x 2
        e = RleEncoder("uint")
        for v in [1, 2, 2]:
            e.append_value(v)
        assert e.finish() == b"\x7f\x01\x02\x02"

    def test_roundtrip_random(self):
        rng = random.Random(1)
        for _ in range(50):
            vals = []
            for _ in range(rng.randrange(0, 200)):
                r = rng.random()
                if r < 0.2:
                    vals.append(None)
                elif r < 0.6:
                    vals.append(rng.randrange(0, 5))
                else:
                    vals.append(rng.randrange(0, 2**40))
            e = RleEncoder("uint")
            for v in vals:
                e.append(v)
            buf = e.finish()
            # trailing nulls are dropped by the encoder iff the whole column
            # is null; otherwise they are encoded
            assert rle_decode(buf, "uint") == ([] if all(v is None for v in vals) else vals)

    def test_string_roundtrip(self):
        vals = ["alpha", "alpha", None, "β-text", ""]
        e = RleEncoder("str")
        for v in vals:
            e.append(v)
        assert rle_decode(e.finish(), "str") == vals


class TestDelta:
    def test_monotonic_compresses(self):
        e = DeltaEncoder()
        for v in range(1, 101):
            e.append(v)
        buf = e.finish()
        # 100 deltas of 1 -> run of 100 x 1 (sleb(100) = e4 00)
        assert buf == b"\xe4\x00\x01"
        assert delta_decode(buf) == list(range(1, 101))

    def test_roundtrip_random(self):
        rng = random.Random(2)
        for _ in range(50):
            vals = [
                None if rng.random() < 0.15 else rng.randrange(-(2**30), 2**30)
                for _ in range(rng.randrange(0, 100))
            ]
            e = DeltaEncoder()
            for v in vals:
                e.append(v)
            got = delta_decode(e.finish())
            assert got == ([] if all(v is None for v in vals) else vals)


class TestBoolean:
    def test_starts_with_false_count(self):
        e = BooleanEncoder()
        for v in [True, True, False]:
            e.append(v)
        # 0 falses, 2 trues, 1 false
        assert e.finish() == b"\x00\x02\x01"

    def test_roundtrip(self):
        rng = random.Random(3)
        for _ in range(50):
            vals = [rng.random() < 0.5 for _ in range(rng.randrange(0, 100))]
            e = BooleanEncoder()
            for v in vals:
                e.append(v)
            assert boolean_decode(e.finish(), len(vals)) == vals

    def test_maybe_boolean_all_false_empty(self):
        e = MaybeBooleanEncoder()
        for _ in range(10):
            e.append(False)
        assert e.finish() == b""
        e2 = MaybeBooleanEncoder()
        e2.append(False)
        e2.append(True)
        assert e2.finish() == b"\x01\x01"
