"""Device merge kernel vs host document: state equivalence.

The kernel (ops/merge.py) must resolve exactly the state the host op store
reaches by sequential application — same winners, same RGA order, same
counter totals, same conflict sets — for any interleaving of replicas.
Mirrors the reference's merge/conflict integration tests
(reference: rust/automerge/tests/test.rs).
"""

import random

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.types import ActorId, ObjType, ScalarValue


def actor(i: int) -> ActorId:
    return ActorId(bytes([i]) * 16)


def host_merge(docs):
    """Sequential host merge of all docs into a fresh doc."""
    out = AutoDoc(actor=actor(250))
    for d in docs:
        out.merge(d)
    return out


def assert_equiv(docs):
    host = host_merge(docs)
    dev = DeviceDoc.merge(docs)
    assert dev.hydrate() == host.hydrate()
    return host, dev


def test_single_doc_map():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "a", 1)
    d.put("_root", "b", "x")
    d.put("_root", "a", 2)
    d.commit()
    host, dev = assert_equiv([d])
    assert dev.keys() == ["a", "b"]
    assert dev.get("_root", "a")[0] == ("scalar", ScalarValue("int", 2))


def test_concurrent_map_conflict_winner():
    base = AutoDoc(actor=actor(1))
    base.put("_root", "k", "base")
    base.commit()
    d1 = base.fork(actor=actor(2))
    d2 = base.fork(actor=actor(3))
    d1.put("_root", "k", "one")
    d1.commit()
    d2.put("_root", "k", "two")
    d2.commit()
    host, dev = assert_equiv([d1, d2])
    # conflict: both visible, winner = higher lamport (same ctr, actor 3)
    assert len(dev.get_all("_root", "k")) == 2
    assert dev.get("_root", "k")[0] == ("scalar", ScalarValue("str", "two"))


def test_text_concurrent_splices():
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "hello world")
    base.commit()
    d1 = base.fork(actor=actor(2))
    d2 = base.fork(actor=actor(3))
    d1.splice_text(t, 5, 0, " brave")
    d1.commit()
    d2.splice_text(t, 0, 5, "goodbye")
    d2.commit()
    host, dev = assert_equiv([d1, d2])
    assert dev.text(t) == host.text(t)
    assert dev.length(t) == host.length(t)


def test_list_insert_delete_interleave():
    base = AutoDoc(actor=actor(1))
    lst = base.put_object("_root", "l", ObjType.LIST)
    for i in range(5):
        base.insert(lst, i, i)
    base.commit()
    d1 = base.fork(actor=actor(2))
    d2 = base.fork(actor=actor(3))
    d1.insert(lst, 2, "a")
    d1.delete(lst, 0)
    d1.commit()
    d2.insert(lst, 2, "b")
    d2.delete(lst, 4)
    d2.commit()
    assert_equiv([d1, d2])


def test_counter_concurrent_increments():
    base = AutoDoc(actor=actor(1))
    base.put("_root", "c", ScalarValue("counter", 10))
    base.commit()
    forks = [base.fork(actor=actor(10 + i)) for i in range(4)]
    for i, f in enumerate(forks):
        for _ in range(i + 1):
            f.increment("_root", "c", 2)
        f.commit()
    host, dev = assert_equiv(forks)
    assert dev.get("_root", "c")[0] == ("counter", 10 + 2 * (1 + 2 + 3 + 4))


def test_nested_objects():
    d = AutoDoc(actor=actor(1))
    m = d.put_object("_root", "config", ObjType.MAP)
    d.put(m, "x", 1)
    lst = d.put_object(m, "items", ObjType.LIST)
    d.insert(lst, 0, "i0")
    inner = d.insert_object(lst, 1, ObjType.MAP)
    d.put(inner, "deep", True)
    t = d.put_object("_root", "note", ObjType.TEXT)
    d.splice_text(t, 0, 0, "hi")
    d.commit()
    host, dev = assert_equiv([d])
    assert dev.hydrate() == {
        "config": {"x": 1, "items": ["i0", {"deep": True}]},
        "note": "hi",
    }


def test_delete_map_key():
    d = AutoDoc(actor=actor(1))
    d.put("_root", "gone", 1)
    d.put("_root", "kept", 2)
    d.delete("_root", "gone")
    d.commit()
    host, dev = assert_equiv([d])
    assert dev.keys() == ["kept"]
    assert dev.get("_root", "gone") is None


def test_overwrite_list_element():
    d = AutoDoc(actor=actor(1))
    lst = d.put_object("_root", "l", ObjType.LIST)
    d.insert(lst, 0, "a")
    d.insert(lst, 1, "b")
    d.commit()
    d2 = d.fork(actor=actor(2))
    d2.put(lst, 0, "A")
    d2.commit()
    d.put(lst, 0, "α")
    d.commit()
    assert_equiv([d, d2])


def test_concurrent_inserts_same_position():
    """RGA convergence: same-position inserts order by descending op id."""
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "ab")
    base.commit()
    forks = [base.fork(actor=actor(2 + i)) for i in range(3)]
    for i, f in enumerate(forks):
        f.splice_text(t, 1, 0, f"<{i}>")
        f.commit()
    host, dev = assert_equiv(forks)
    assert dev.text(t) == host.text(t)


@pytest.mark.parametrize("n_forks,n_edits,seed", [(4, 20, 0), (8, 40, 1)])
def test_random_text_fuzz(n_forks, n_edits, seed):
    rng = random.Random(seed)
    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "t", ObjType.TEXT)
    base.splice_text(t, 0, 0, "the quick brown fox jumps over the lazy dog")
    base.commit()
    forks = [base.fork(actor=actor(50 + i)) for i in range(n_forks)]
    for fi, f in enumerate(forks):
        for _ in range(n_edits):
            ln = f.length(t)
            if rng.random() < 0.6 or ln == 0:
                pos = rng.randrange(ln + 1)
                f.splice_text(t, pos, 0, rng.choice("abcxyz"))
            else:
                pos = rng.randrange(ln)
                f.splice_text(t, pos, 1, "")
        f.commit()
    host, dev = assert_equiv(forks)
    assert dev.text(t) == host.text(t)


@pytest.mark.parametrize("seed", [0, 7])
def test_random_mixed_fuzz(seed):
    rng = random.Random(seed)
    base = AutoDoc(actor=actor(1))
    lst = base.put_object("_root", "list", ObjType.LIST)
    base.put("_root", "n", ScalarValue("counter", 0))
    for i in range(3):
        base.insert(lst, i, i)
    base.commit()
    forks = [base.fork(actor=actor(60 + i)) for i in range(5)]
    keys = ["a", "b", "c"]
    for f in forks:
        for _ in range(15):
            r = rng.random()
            if r < 0.3:
                f.put("_root", rng.choice(keys), rng.randrange(100))
            elif r < 0.5:
                f.increment("_root", "n", rng.randrange(1, 5))
            elif r < 0.75:
                ln = f.length(lst)
                f.insert(lst, rng.randrange(ln + 1), rng.randrange(100))
            else:
                ln = f.length(lst)
                if ln:
                    f.delete(lst, rng.randrange(ln))
        f.commit()
    assert_equiv(forks)


def test_merge_transitive_chain():
    """Merging partially-merged replicas dedups shared changes by hash."""
    a = AutoDoc(actor=actor(1))
    a.put("_root", "x", 1)
    a.commit()
    b = a.fork(actor=actor(2))
    b.put("_root", "y", 2)
    b.commit()
    c = b.fork(actor=actor(3))
    c.put("_root", "z", 3)
    c.commit()
    log = OpLog.from_documents([a, b, c])
    assert len(log.changes) == 3  # shared history deduped
    assert_equiv([a, b, c])


def test_empty_doc():
    d = AutoDoc(actor=actor(1))
    dev = DeviceDoc.merge([d])
    assert dev.hydrate() == {}


def test_device_get_all_width_aware_text():
    """Integer indexing on TEXT is by character position (host nth parity)."""
    doc = AutoDoc(ActorId(bytes([1]) * 16))
    t = doc.put_object("_root", "t", ObjType.TEXT)
    doc.splice_text(t, 0, 0, "ab")
    doc.splice(t, 1, 0, ["XYZ"])  # "a XYZ b": widths 1,3,1
    doc.commit()
    dd = DeviceDoc.merge([doc])
    assert dd.text(t) == "aXYZb"
    for pos, want in [(0, "a"), (1, "XYZ"), (2, "XYZ"), (3, "XYZ"), (4, "b")]:
        got = dd.get_all(t, pos)
        host = doc.get_all(t, pos)
        assert got and got[-1][0] == ("scalar", ("str", want)), (pos, got)
        assert [v for v, _ in got] == [v for v, _ in host], pos
    assert dd.get_all(t, 5) == []


def test_packed_transport_matches_dict(monkeypatch):
    """The byte-minimizing packed transport (slope-RLE runs in, one
    bit-packed vector out — ops/merge.py "packed transport") resolves
    identically to the per-array dict path on a mixed workload."""
    import numpy as np

    from automerge_tpu.ops.merge import merge_columns

    base = AutoDoc(actor=actor(1))
    t = base.put_object("_root", "text", ObjType.TEXT)
    base.splice_text(t, 0, 0, "packed transport base text")
    base.put("_root", "count", ScalarValue("counter", 5))
    lst = base.put_object("_root", "lst", ObjType.LIST)
    base.insert(lst, 0, 1)
    base.commit()
    forks = [base.fork(actor=actor(10 + i)) for i in range(4)]
    for i, f in enumerate(forks):
        f.splice_text(t, i * 3, 1 if i % 2 else 0, f"[{i}]")
        f.increment("_root", "count", i + 1)
        f.put("_root", "k", i)
        f.insert(lst, 0, 10 + i)
        f.commit()

    log = OpLog.from_documents(forks)
    cols = log.padded_columns()
    monkeypatch.setenv("AUTOMERGE_TPU_TRANSPORT", "dict")
    r1 = merge_columns(cols, fetch=DeviceDoc.READ_FETCH, n_objs=log.n_objs)
    monkeypatch.setenv("AUTOMERGE_TPU_TRANSPORT", "packed")
    r2 = merge_columns(cols, fetch=DeviceDoc.READ_FETCH, n_objs=log.n_objs)
    n = log.n
    assert np.array_equal(r1["visible"][:n], r2["visible"][:n])
    assert np.array_equal(r1["winner"][:n], r2["winner"][:n])
    assert np.array_equal(r1["elem_index"][:n], r2["elem_index"][:n])
    # conflicts travels as a flag; consumers only test > 1
    assert np.array_equal(
        np.asarray(r1["conflicts"][:n]) > 1, np.asarray(r2["conflicts"][:n]) > 1
    )
    for k in ("obj_vis_len", "obj_text_width"):
        m = min(len(r1[k]), len(r2[k]))
        assert np.array_equal(np.asarray(r1[k][:m]), np.asarray(r2[k][:m])), k

    # and the full DeviceDoc read surface agrees with the host merge
    dev = DeviceDoc(log, r2)
    assert dev.hydrate() == host_merge(forks).hydrate()
