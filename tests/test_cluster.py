"""Cluster tier: hash ring, journal-shipping replication, failover,
live migration.

Three layers: pure units (ring placement, wire codecs, journal hooks),
in-process leader/follower node pairs over real sockets (replication
convergence, quorum acks, cursor persistence, promotion), and the
router tier end to end (proxying, failover with zero acked-write loss,
migration between shard groups).
"""

import json
import os
import socket
import tempfile
import threading
import time

import pytest

from automerge_tpu.api import AutoDoc
from automerge_tpu.cluster import (
    ClusterNode,
    ClusterRouter,
    HashRing,
    decode_batch,
    decode_cursor,
    encode_batch,
    encode_cursor,
)
from automerge_tpu.storage.journal import (
    Journal,
    JournalError,
    REC_CHANGE,
    REC_META,
)


# -- helpers ------------------------------------------------------------------


class Client:
    """Minimal pipelining JSON-RPC socket client."""

    def __init__(self, address):
        self.sock = socket.create_connection(address)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.f = self.sock.makefile("r")
        self.rid = 0

    def call(self, method, allow_error=False, **params):
        self.rid += 1
        self.sock.sendall((json.dumps(
            {"id": self.rid, "method": method, "params": params}
        ) + "\n").encode())
        resp = json.loads(self.f.readline())
        if not allow_error:
            assert "error" not in resp, resp
        return resp if "error" in resp else resp.get("result")

    def close(self):
        self.sock.close()


def addr_of(node):
    return "%s:%d" % node.address


def start_node(tmp, name, **kw):
    d = os.path.join(str(tmp), name)
    node = ClusterNode(
        node_id=name, host="127.0.0.1", port=0, durable_dir=d, **kw
    )
    node.start()
    return node


def wait_until(pred, timeout=10.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# -- units --------------------------------------------------------------------


def test_hashring_stable_balanced_minimal_movement():
    ring = HashRing(["a", "b", "c"], vnodes=64)
    keys = [f"doc{i}" for i in range(3000)]
    before = {k: ring.member_for(k) for k in keys}
    # stability: a rebuilt ring places identically
    again = HashRing(["c", "a", "b"], vnodes=64)
    assert all(again.member_for(k) == v for k, v in before.items())
    # rough balance: no member below a third of the fair share
    counts = {}
    for v in before.values():
        counts[v] = counts.get(v, 0) + 1
    assert min(counts.values()) > len(keys) / 3 / 3
    # removing a member moves ONLY its keys
    ring.remove("b")
    for k in keys:
        if before[k] != "b":
            assert ring.member_for(k) == before[k]
        else:
            assert ring.member_for(k) in ("a", "c")


def test_cursor_and_batch_codecs_roundtrip():
    blob = encode_cursor("node-1/abc123", 991)
    assert decode_cursor(blob) == ("node-1/abc123", 991)
    records = [(REC_CHANGE, b"\x01" * 40), (REC_META, b"name-blob")]
    assert decode_batch(encode_batch(records)) == records
    # damage must raise, never truncate silently: TCP delivered it, so a
    # bad byte is a bug, not a torn write
    wire = bytearray(encode_batch(records))
    wire[10] ^= 0xFF
    with pytest.raises(JournalError):
        decode_batch(bytes(wire))
    assert decode_batch(b"") == []


def test_journal_hooks_fire_with_seqs(tmp_path):
    events = []
    j, _, _ = Journal.open(str(tmp_path / "j.waj"), fsync="always")
    j.on_record = lambda rt, pl, seq: events.append(("rec", rt, seq))
    j.on_synced = lambda seq: events.append(("sync", seq))
    j.append_change(b"abc")
    j.append_change(b"def")
    assert ("rec", REC_CHANGE, 1) in events and ("rec", REC_CHANGE, 2) in events
    assert ("sync", 1) in events and ("sync", 2) in events
    assert j.acked_seq == 2 and j.append_seq == 2
    j.close()


# -- leader/follower replication ---------------------------------------------


def test_replication_quorum_converges_and_promotes(tmp_path):
    fol = start_node(tmp_path, "f1", role="follower")
    led = start_node(tmp_path, "l1", role="leader",
                     replicate_to=[addr_of(fol)], ack_replicas=1)
    try:
        c = Client(led.address)
        d = c.call("openDurable", name="docA")["doc"]
        for i in range(12):
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
            c.call("commit", doc=d)
        save_l = c.call("save", doc=d)

        fc = Client(fol.address)
        # follower rejects client mutations
        r = fc.call("create", allow_error=True)
        assert r["error"]["type"] == "NotLeader", r
        # the quorum ack means the follower ALREADY holds everything
        st = fc.call("clusterStatus")
        assert st["role"] == "follower"
        cur = st["docs"]["docA"]["cursor"]
        assert cur is not None and cur["lsn"] >= 12
        assert cur["stream"] == led.rpc.hub.stream_id
        # promotion: byte-identical state, serves mutations
        pr = fc.call("clusterPromote")
        assert pr["promoted"] is True
        hf = fc.call("openDurable", name="docA")["doc"]
        assert fc.call("save", doc=hf) == save_l
        fc.call("put", doc=hf, obj="_root", prop="after", value=1)
        fc.call("commit", doc=hf)
        c.close()
        fc.close()
    finally:
        led.stop()
        fol.stop()


def test_replication_cursor_survives_follower_restart(tmp_path):
    fol = start_node(tmp_path, "f1", role="follower")
    fol_addr = addr_of(fol)
    led = start_node(tmp_path, "l1", role="leader",
                     replicate_to=[fol_addr], ack_replicas=1)
    try:
        snapshots = []
        orig_snapshot = led.rpc.hub.snapshot
        led.rpc.hub.snapshot = lambda name: (
            snapshots.append(name) or orig_snapshot(name))

        c = Client(led.address)
        d = c.call("openDurable", name="docA")["doc"]
        for i in range(6):
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
            c.call("commit", doc=d)
        first_snapshots = len(snapshots)  # the initial catch-up
        fol.stop()

        fol2 = start_node(tmp_path, "f1", role="follower")
        try:
            led.rpc.hub.remove_follower(fol_addr)
            c.call("clusterReplicateTo", addr=addr_of(fol2))
            for i in range(6, 12):
                c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
                c.call("commit", doc=d)
            fc = Client(fol2.address)
            st = fc.call("clusterStatus")
            cur = st["docs"]["docA"]["cursor"]
            assert cur["lsn"] >= 12
            # the restart resumed from the persisted cursor: the journal
            # tail shipped, no second snapshot
            assert len(snapshots) == first_snapshots, snapshots
            fc.close()
            c.close()
        finally:
            fol2.stop()
    finally:
        led.stop()


def test_ack_gate_times_out_without_followers(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTOMERGE_TPU_CLUSTER_ACK_TIMEOUT", "0.3")
    led = start_node(tmp_path, "l1", role="leader", ack_replicas=1)
    try:
        c = Client(led.address)
        d = c.call("openDurable", name="docA")["doc"]
        c.call("put", doc=d, obj="_root", prop="k", value=1)
        r = c.call("commit", doc=d, allow_error=True)
        # no follower can confirm the write: the ack MUST NOT happen
        assert "error" in r, r
        assert "ReplicationTimeout" in r["error"]["type"], r
        c.close()
    finally:
        led.stop()


# -- cluster-wide trace propagation -------------------------------------------


def test_trace_propagates_router_leader_follower(tmp_path):
    """One traced client write: the router span parents into the client
    context, the leader's request span parents into the ROUTER span, the
    leader's group-commit fsync links the trace, and the follower's
    replicated apply links back — the parent/link chain the flight
    recorder's merged timeline renders (all in-process here, so one
    recorder sees every hop)."""
    from automerge_tpu import obs

    obs.reset_all()
    fol = start_node(tmp_path, "f1", role="follower")
    led = start_node(tmp_path, "l1", role="leader",
                     replicate_to=[addr_of(fol)], ack_replicas=1)
    router = ClusterRouter([[addr_of(led), addr_of(fol)]], heartbeat=5.0)
    router.start()
    try:
        c = Client(router.address)
        d = c.call("openDurable", name="docT")["doc"]
        tid = "e2e-trace-1"

        def traced(method, **params):
            c.rid += 1
            c.sock.sendall((json.dumps(
                {"id": c.rid, "method": method, "params": params,
                 "trace": {"t": tid, "s": 12345}}
            ) + "\n").encode())
            resp = json.loads(c.f.readline())
            assert "error" not in resp, resp
            return resp.get("result")

        traced("put", doc=d, obj="_root", prop="k", value=1)
        traced("commit", doc=d)  # quorum ack: follower holds it durably
        c.close()

        spans = obs.recorder.snapshot()
        in_trace = [r for r in spans if r.trace_id == tid]
        names = {r.name for r in in_trace}
        # router hop: parented into the client's (remote) span id
        router_spans = [r for r in in_trace if r.name == "router.request"]
        assert router_spans and all(
            r.parent_id == 12345 for r in router_spans)
        # leader hop: rpc.request parented into a ROUTER span
        router_ids = {r.span_id for r in router_spans}
        node_reqs = [r for r in in_trace if r.name == "rpc.request"]
        assert node_reqs and any(
            r.parent_id in router_ids for r in node_reqs)
        # the durable write path nests inside the traced request
        assert "journal.append" in names
        # group commit attribution: some fsync links the trace
        fsyncs = [r for r in spans if r.name == "journal.fsync" and r.links]
        assert any(t == tid for r in fsyncs for t, _s in r.links)
        # follower hop: the shipped batch's apply links the client trace
        applies = [r for r in spans if r.name == "repl.apply"]
        assert applies and any(
            t == tid for r in applies if r.links for t, _s in r.links)
        # and the ship span itself carries the link on the leader side
        ships = [r for r in spans if r.name == "cluster.ship_batch"]
        assert any(
            t == tid for r in ships if r.links for t, _s in r.links)
    finally:
        router.stop()
        led.stop()
        fol.stop()


# -- the router tier ----------------------------------------------------------


def test_router_proxies_and_virtualizes_handles(tmp_path):
    n0 = start_node(tmp_path, "n0", role="leader")
    router = ClusterRouter([[addr_of(n0)]], heartbeat=5.0)
    router.start()
    try:
        c = Client(router.address)
        d = c.call("openDurable", name="docA")["doc"]
        for i in range(10):
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
        c.call("commit", doc=d)
        assert c.call("length", doc=d, obj="_root") == 10
        assert c.call("get", doc=d, obj="_root", prop="k7") == 7
        # reopening the same name returns the SAME virtual handle
        assert c.call("openDurable", name="docA")["doc"] == d
        # plain (anchor-routed) docs work too
        p = c.call("create")["doc"]
        assert p != d
        c.call("put", doc=p, obj="_root", prop="x", value=1)
        c.call("commit", doc=p)
        info = c.call("clusterInfo")
        assert info["groups"][0]["up"] is True
        c.close()
    finally:
        router.stop()
        n0.stop()


def test_cluster_metrics_merges_nodes_with_labels(tmp_path):
    """clusterMetrics fans out to every node and merges the families
    under node labels; the cluster-metrics CLI scrapes it."""
    from automerge_tpu.cli import main
    from automerge_tpu.obs.metrics import parse_prometheus

    fol = start_node(tmp_path, "f1", role="follower")
    led = start_node(tmp_path, "l1", role="leader",
                     replicate_to=[addr_of(fol)], ack_replicas=1)
    router = ClusterRouter([[addr_of(led), addr_of(fol)]], heartbeat=5.0)
    router.start()
    try:
        c = Client(router.address)
        d = c.call("openDurable", name="docM")["doc"]
        c.call("put", doc=d, obj="_root", prop="x", value=1)
        c.call("commit", doc=d)
        res = c.call("clusterMetrics")
        assert res["format"] == "prometheus" and not res["unreachable"]
        parsed = parse_prometheus(res["body"])
        nodes = {dict(k[1]).get("node") for k in parsed}
        # every sample labeled; router + both nodes present
        assert None not in nodes
        assert nodes >= {"router", addr_of(led), addr_of(fol)}
        # per-doc gauges rode along from the leader
        assert ("doc_journal_bytes",
                (("doc", "docM"), ("node", addr_of(led)))) in parsed
        # one merged family set: a single TYPE line per family
        assert res["body"].count("# TYPE rpc_request_count") <= 1
        c.close()
        # the CLI scrape returns the same body shape
        out = tmp_path / "cm.prom"
        rc = main(["cluster-metrics", "%s:%d" % router.address,
                   "-o", str(out)])
        assert rc == 0
        assert 'node="router"' in out.read_text()
    finally:
        router.stop()
        led.stop()
        fol.stop()


def _kill_node_sockets(node):
    """Simulate abrupt node death for in-process tests: stop listening
    and cut every connection without any flush (the real kill -9 sweep
    lives in scripts/ci/run_cluster)."""
    node._shutdown.set()
    if node._listener is not None:
        node._listener.close()
    with node._conns_lock:
        conns = list(node._conns.values())
    for conn in conns:
        conn.close()


def test_router_failover_zero_acked_loss(tmp_path):
    fol1 = start_node(tmp_path, "n1", role="follower")
    fol2 = start_node(tmp_path, "n2", role="follower")
    led = start_node(
        tmp_path, "n0", role="leader",
        replicate_to=[addr_of(fol1), addr_of(fol2)], ack_replicas=1,
    )
    led_addr = addr_of(led)
    router = ClusterRouter(
        [[led_addr, addr_of(fol1), addr_of(fol2)]],
        heartbeat=0.1, miss_limit=3,
    )
    router.start()
    try:
        c = Client(router.address)
        d = c.call("openDurable", name="docA")["doc"]
        sess = c.call("syncSessionAttach", doc=d, peer="client-x")
        acked = []
        for i in range(10):
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
            c.call("commit", doc=d)
            acked.append(i)

        _kill_node_sockets(led)
        # keep writing through the failover: Unavailable is retriable
        i, deadline = 10, time.monotonic() + 30
        while i < 16:
            assert time.monotonic() < deadline, "failover never completed"
            r1 = c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i,
                        allow_error=True)
            if "error" in (r1 or {}):
                time.sleep(0.05)
                continue
            r2 = c.call("commit", doc=d, allow_error=True)
            if "error" in (r2 or {}):
                time.sleep(0.05)
                continue
            acked.append(i)
            i += 1

        info = c.call("clusterInfo")
        assert info["groups"][0]["gen"] >= 1
        assert info["groups"][0]["leader"] != led_addr
        # zero acked-write loss: every acked key is readable
        for i in acked:
            assert c.call("get", doc=d, obj="_root", prop=f"k{i}") == i
        # the attached session re-materializes on the new leader with a
        # bumped epoch (the client side would epoch-handshake, not
        # full-resync)
        sess2 = c.call("syncSessionAttach", doc=d, peer="client-x")
        assert sess2["epoch"] >= 2
        c.close()
    finally:
        router.stop()
        for n in (led, fol1, fol2):
            n.stop()


def test_router_live_migration_between_groups(tmp_path):
    n0 = start_node(tmp_path, "g0", role="leader")
    n1 = start_node(tmp_path, "g1", role="leader")
    router = ClusterRouter([[addr_of(n0)], [addr_of(n1)]], heartbeat=5.0)
    router.start()
    try:
        c = Client(router.address)
        d = c.call("openDurable", name="migdoc")["doc"]
        sess = c.call("syncSessionAttach", doc=d, peer="mig-peer")
        for i in range(20):
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
        c.call("commit", doc=d)
        home = HashRing([0, 1]).member_for("migdoc")
        target = 1 - home
        res = c.call("clusterMigrate", name="migdoc", to=target)
        assert res["migrated"] is True
        # reads and writes keep flowing through the same virtual handle
        for i in range(20):
            assert c.call("get", doc=d, obj="_root", prop=f"k{i}") == i
        c.call("put", doc=d, obj="_root", prop="after", value="moved")
        c.call("commit", doc=d)
        assert c.call("get", doc=d, obj="_root", prop="after") == "moved"
        # the attached session moved WITH the doc: the same virtual
        # handle re-attaches on the destination (epoch bumped), instead
        # of routing to the source's freed copy
        stats = c.call("syncSessionStats", session=sess["session"])
        assert stats["epoch"] > sess["epoch"]
        assert c.call("clusterInfo")["overrides"] == {"migdoc": target}
        # the source released its journal flock
        src_dir = os.path.join(
            str(tmp_path), ["g0", "g1"][home], "migdoc")
        dd = AutoDoc.open(src_dir)
        dd.close()
        c.close()
    finally:
        router.stop()
        n0.stop()
        n1.stop()


def test_cold_doc_is_cheap_migration_source(tmp_path):
    """A document demoted to the cold tier migrates as on-disk
    snapshot+tail bytes: no hydration on the source, contents intact on
    the target, ``cluster.migrate_cold_source`` actually fired."""
    from automerge_tpu import obs

    n0 = start_node(tmp_path, "cg0", role="leader")
    n1 = start_node(tmp_path, "cg1", role="leader")
    router = ClusterRouter([[addr_of(n0)], [addr_of(n1)]], heartbeat=5.0)
    router.start()
    try:
        c = Client(router.address)
        d = c.call("openDurable", name="colddoc")["doc"]
        for i in range(12):
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
        c.call("commit", doc=d)
        home = HashRing([0, 1]).member_for("colddoc")
        src = [n0, n1][home]
        # demote on the source node: journal closed, op-store dropped
        src.rpc.store.demote("colddoc", "cold", "test")
        assert src.rpc.store.tier("colddoc") == "cold"
        before = obs.legacy_counters.get("cluster.migrate_cold_source", 0)
        res = c.call("clusterMigrate", name="colddoc", to=1 - home)
        assert res["migrated"] is True
        after = obs.legacy_counters.get("cluster.migrate_cold_source", 0)
        # both phases (live read + authoritative re-read under the
        # routing pause) took the cold path: the doc was never hydrated
        # on the source — no residency rebuild happened
        assert after - before >= 2, (before, after)
        # the source released the migrated doc entirely
        assert src.rpc.store.tier("colddoc") is None
        # the doc stayed cold on the source for the whole handoff (no
        # residency rebuild) and the target serves the full contents
        for i in range(12):
            assert c.call("get", doc=d, obj="_root", prop=f"k{i}") == i
        c.call("put", doc=d, obj="_root", prop="after", value="moved")
        c.call("commit", doc=d)
        assert c.call("get", doc=d, obj="_root", prop="after") == "moved"
        c.close()
    finally:
        router.stop()
        n0.stop()
        n1.stop()


def test_follower_replica_hydrates_from_cold_on_apply(tmp_path):
    """Replication keeps flowing to a replica the follower's own store
    demoted to cold: the next shipped batch hydrates it in place and the
    persisted cursor survives the demote/hydrate cycle."""
    fol = start_node(tmp_path, "fcold_f", role="follower")
    led = start_node(tmp_path, "fcold_l", role="leader",
                     replicate_to=[addr_of(fol)])
    try:
        c = Client(led.address)
        d = c.call("openDurable", name="repdoc")["doc"]
        c.call("put", doc=d, obj="_root", prop="a", value=1)
        c.call("commit", doc=d)
        wait_until(
            lambda: fol.rpc.store is not None
            and fol.rpc.store.tier("repdoc") is not None,
            msg="follower opened the replica",
        )
        wait_until(
            lambda: (lambda dd: dd is not None and not getattr(
                dd, "_closed", True) and dd.get("_root", "a") is not None)(
                    fol.rpc._docs.get(
                        fol.rpc._durable_names.get("repdoc"))),
            msg="follower applied the first record",
        )
        fol.rpc.store.demote("repdoc", "cold", "test")
        assert fol.rpc.store.tier("repdoc") == "cold"
        c.call("put", doc=d, obj="_root", prop="b", value=2)
        c.call("commit", doc=d)

        def _fol_has_b():
            h = fol.rpc._durable_names.get("repdoc")
            dd = fol.rpc._docs.get(h)
            if dd is None or getattr(dd, "_closed", False):
                return False
            got = dd.get("_root", "b")
            return got is not None
        wait_until(_fol_has_b, msg="cold follower replica hydrated + applied")
        assert fol.rpc.store.tier("repdoc") == "warm"
        c.close()
    finally:
        led.stop()
        fol.stop()


# -- batched follower apply (host_batch feed point) ---------------------------


def test_follower_apply_batching_feeds_device_mirrors(tmp_path):
    """Shipped records drain through the batched follower path: same-doc
    runs share an ack scope, the repl_apply_batch_size histogram
    observes the drains, and every replica's resident device mirror is
    fed through the vectorized cross-doc staging — mirrors converge to
    the leader's state without a rebuild."""
    from automerge_tpu import obs

    fol = start_node(tmp_path, "fb1", role="follower")
    led = start_node(tmp_path, "lb1", role="leader",
                     replicate_to=[addr_of(fol)], ack_replicas=1)
    try:
        fc = Client(fol.address)
        fh = {}
        for name in ("dA", "dB", "dC"):
            # replicas opened WITH device mirrors on the follower
            # (openDurable is follower-ok)
            fh[name] = fc.call("openDurable", name=name, device=True)["doc"]
        c = Client(led.address)
        for name in ("dA", "dB", "dC"):
            d = c.call("openDurable", name=name)["doc"]
            for i in range(6):
                c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
                c.call("commit", doc=d)
        for name in ("dA", "dB", "dC"):
            doc = fol.rpc._docs[fh[name]]

            def fresh(doc=doc):
                with doc.lock:
                    dev = doc.device_doc
                    if dev is None:
                        return False
                    got = dev.hydrate().get("k5")
                    return got == ("scalar", 5) or got == 5
            # generous deadline: three mirrors drain through shared
            # batched launches behind jit warmup — under CI load the
            # first convergence can take well past the default 10s
            # without anything being wrong
            wait_until(fresh, timeout=60.0,
                       msg=f"device mirror of {name} converged")
        hist = [e for e in obs.snapshot()
                if e["name"] == "cluster.repl_apply_batch_size"]
        assert hist and hist[0]["count"] > 0, hist
        c.close()
        fc.close()
    finally:
        led.stop()
        fol.stop()


def test_follower_apply_serial_knob_restores_old_path(tmp_path, monkeypatch):
    """AUTOMERGE_TPU_REPL_BATCH=0 forces the pre-batching serial path:
    no coalesced drains (mirror stays untouched — the A/B baseline),
    replication itself still converges."""
    monkeypatch.setenv("AUTOMERGE_TPU_REPL_BATCH", "0")
    from automerge_tpu import obs

    before = [e for e in obs.snapshot()
              if e["name"] == "cluster.repl_apply_batch_size"]
    n_before = before[0]["count"] if before else 0
    fol = start_node(tmp_path, "fs1", role="follower")
    led = start_node(tmp_path, "ls1", role="leader",
                     replicate_to=[addr_of(fol)], ack_replicas=1)
    try:
        fc = Client(fol.address)
        fh = fc.call("openDurable", name="dS", device=True)["doc"]
        c = Client(led.address)
        d = c.call("openDurable", name="dS")["doc"]
        for i in range(4):
            c.call("put", doc=d, obj="_root", prop=f"k{i}", value=i)
            c.call("commit", doc=d)
        # quorum acks already guarantee the follower holds the records
        st = fc.call("clusterStatus")
        assert st["docs"]["dS"]["cursor"]["lsn"] >= 4
        doc = fol.rpc._docs[fh]
        with doc.lock:
            # host state converged, the mirror was NOT fed (old behavior)
            assert doc.get("_root", "k3") is not None
            assert doc.device_doc.hydrate() == {}
        after = [e for e in obs.snapshot()
                 if e["name"] == "cluster.repl_apply_batch_size"]
        n_after = after[0]["count"] if after else 0
        assert n_after == n_before, (n_before, n_after)
        c.close()
        fc.close()
    finally:
        led.stop()
        fol.stop()
