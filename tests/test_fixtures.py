"""Parse the reference's binary fixture corpus.

These files were produced by the reference implementation
(rust/automerge/tests/fixtures + fuzz-crashers); parsing them exercises
byte-level compatibility of the chunk/column decoders. Storage-level parses
here; full document-load semantics are covered in core tests.
"""

import os

import pytest

from automerge_tpu.storage.change import parse_change
from automerge_tpu.storage.chunk import CHUNK_CHANGE, CHUNK_DOCUMENT, parse_chunk
from automerge_tpu.storage.document import parse_document

FIXTURES = "/root/reference/rust/automerge/tests/fixtures"
CRASHERS = "/root/reference/rust/automerge/tests/fuzz-crashers"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(FIXTURES), reason="reference fixtures not available"
)


def fixture(name):
    with open(os.path.join(FIXTURES, name), "rb") as f:
        return f.read()


def test_two_change_chunks_parse():
    for name in (
        "two_change_chunks.automerge",
        "two_change_chunks_compressed.automerge",
        "two_change_chunks_out_of_order.automerge",
    ):
        buf = fixture(name)
        changes = []
        pos = 0
        while pos < len(buf):
            change, pos = parse_change(buf, pos)
            changes.append(change)
        assert len(changes) == 2
        for c in changes:
            assert c.hash is not None
            assert c.start_op >= 1


def test_two_change_chunks_contents():
    buf = fixture("two_change_chunks.automerge")
    c1, pos = parse_change(buf, 0)
    c2, _ = parse_change(buf, pos)
    # second change depends on the first; first has no deps
    assert c1.dependencies == [] or c2.dependencies == []
    with_dep = c2 if c2.dependencies else c1
    without = c1 if c2.dependencies else c2
    assert with_dep.dependencies == [without.hash]


def test_64bit_obj_id_doc_parses():
    doc, _ = parse_document(fixture("64bit_obj_id_doc.automerge"))
    assert doc.checksum_valid
    assert len(doc.ops) > 0
    assert len(doc.actors) >= 1


def test_64bit_obj_id_change_parses():
    buf = fixture("64bit_obj_id_change.automerge")
    chunks = []
    pos = 0
    while pos < len(buf):
        chunk, pos = parse_chunk(buf, pos)
        chunks.append(chunk)
    assert any(c.chunk_type in (CHUNK_CHANGE, CHUNK_DOCUMENT) for c in chunks)


def test_counter_fixture_ok():
    change, _ = parse_change(fixture("counter_value_is_ok.automerge"))
    assert any(op.value.tag == "counter" for op in change.ops)


def test_counter_fixture_overlong_rejected():
    # Overlong LEB encodings inside the counter value must error, not panic.
    with pytest.raises(Exception):
        parse_change(fixture("counter_value_is_overlong.automerge"))


def test_counter_fixture_bad_meta_rejected():
    with pytest.raises(Exception):
        parse_change(fixture("counter_value_has_incorrect_meta.automerge"))


def test_full_load_with_head_verification():
    """Document.load re-derives change hashes and verifies stored heads.

    Passing this proves the whole reconstruction pipeline (pred-from-succ,
    delete synthesis, change regrouping, columnar re-encode, SHA-256) is
    byte-identical to the Rust reference that produced these files.
    """
    from automerge_tpu import AutoDoc

    doc = AutoDoc.load(fixture("64bit_obj_id_doc.automerge"))
    assert doc.hydrate() == {"a": {}}
    doc2 = AutoDoc.load(fixture("two_change_chunks.automerge"))
    assert doc2.hydrate() == {"a": {"a": "b"}}
    doc3 = AutoDoc.load(fixture("two_change_chunks_out_of_order.automerge"))
    assert doc3.get_heads() == doc2.get_heads()


def test_fuzz_crashers_do_not_crash():
    """Malformed inputs must raise clean errors, never hang or corrupt."""
    if not os.path.isdir(CRASHERS):
        pytest.skip("no crasher corpus")
    for name in os.listdir(CRASHERS):
        with open(os.path.join(CRASHERS, name), "rb") as f:
            buf = f.read()
        try:
            pos = 0
            while pos < len(buf):
                chunk, pos = parse_chunk(buf, pos)
                if chunk.chunk_type == CHUNK_DOCUMENT:
                    parse_document(buf[buf.find(b"\x85o"):])
                elif chunk.chunk_type == CHUNK_CHANGE:
                    parse_change(buf)
        except Exception:
            pass  # clean failure is the requirement
