"""Multi-chip merge: shard the op-log merge over a jax.sharding.Mesh.

The reference's "distribution" is logical (actors + the sync protocol,
reference: rust/automerge/src/sync.rs); its compute is single-threaded. On
TPU every phase of the merge scales across chips:

  1. succ resolution — the pred stream is split across the mesh, every
     device scatter-adds its slice into full-size counter arrays, one
     ``psum`` over ICI combines them (the collective analogue of the
     reference's per-op ``add_succ``, op_set.rs:194-203).
  2. visibility — elementwise, replicated (cheaper than communicating it).
  3. per-key winners — NO sort: a sequence run's group id is the run-head
     insert row itself and map groups index a dense (obj x prop) table, so
     each device scatter-max/adds its ROW SLICE into group-id arrays and
     one ``pmax``/``psum`` pair merges them. This is what makes the
     resolution phase itself shard (round-2 sharded only the pred
     scatter); the sort-based formulation (ops/merge.py resolve_state)
     remains the fallback when the map-group table would be too large.
  4. RGA linearization — the sibling forest builds with scatters (first
     child = max-row child; next sibling = each child pointing its
     predecessor, derived from one replicated sort kept for adjacency);
     the pointer-doubling threading + Wyllie ranking loops — the dominant
     cost on a single chip — run SHARDED: each device advances its node
     slice and an ``all_gather`` re-replicates state between doubling
     steps (O(log n) steps, compute per step P/n).

Scaling model (How-to-Scale style): phases 1+3 are scatter-bound with
per-device cost (Q+P)/n plus P-sized all-reduces; phase 4 is
gather-latency-bound with per-device cost (P log P)/n plus log P
all-gathers. All collectives ride the mesh axis (ICI on real chips).

The packed transport (ops/merge.py encode_transport) runs through this
path too: runs are decoded on device inside the shard_map body, so a
tunnel-attached multi-chip host ships a few KB per column, not columns.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..obs import prof as _prof
from ..ops.merge import (
    NONE32,
    _ceil_log2,
    _unpack_transport,
    encode_transport,
    forest as _forest,
    resolve_state,
    succ_resolution,
    visibility,
)
from ..ops.oplog import ELEM_HEAD, PAD_ACTION

AXIS = "shard"


def default_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` available devices."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count=N for a "
                "virtual CPU mesh)"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


# column -> partition spec: the pred stream splits along the mesh axis, op
# columns are replicated (single source of truth for in_specs + device_put).
# Row WORK is sharded by slicing inside the body, so replicated columns do
# not serialize the resolution phases.
COLUMN_SPECS = {
    "action": P(),
    "insert": P(),
    "prop": P(),
    "elem_ref": P(),
    "obj_dense": P(),
    "value_tag": P(),
    "value_i32": P(),
    "width": P(),
    "covered": P(),
    "pred_src": P(AXIS),
    "pred_tgt": P(AXIS),
}
# (the "aorder" column is opt-in for the single-device condensed kernel
# only — OpLog.columns() excludes it by default, so the sharded specs
# never see it; its own condensation is chain-based)

def _sharded_winners(c, visible, Pl, n_objs2, n_props, G):
    """Scatter-based per-key winners, row-sliced per device.

    Group-id space: [0,P) seq runs (run-head row), then per-object
    HEAD/missing sentinel groups, then the dense (obj x prop) map table,
    then one trash slot for pad rows. Winner = pmax of per-shard
    scatter-max of visible global rows; conflicts = psum of counts.
    """
    Ptot = c["action"].shape[0]
    i0 = jax.lax.axis_index(AXIS) * Pl

    def sl(x):
        return jax.lax.dynamic_slice_in_dim(x, i0, Pl)

    rows_l = i0 + jnp.arange(Pl, dtype=jnp.int32)
    action_l = sl(c["action"])
    valid_l = action_l != PAD_ACTION
    insert_l = sl(c["insert"])
    elem_l = sl(c["elem_ref"])
    obj_l = sl(c["obj_dense"])
    prop_l = sl(c["prop"])
    vis_l = sl(visible)

    run_l = jnp.where(insert_l, rows_l, elem_l)
    seq_gid = jnp.where(
        run_l >= 0,
        run_l,
        Ptot + obj_l * 2 + jnp.where(elem_l == ELEM_HEAD, 0, 1),
    )
    map_gid = Ptot + 2 * n_objs2 + obj_l * n_props + prop_l
    gid = jnp.where(prop_l >= 0, map_gid, seq_gid)
    gid = jnp.where(valid_l, gid, G - 1).astype(jnp.int32)

    win = (
        jnp.full(G, NONE32, jnp.int32)
        .at[gid]
        .max(jnp.where(vis_l, rows_l, NONE32))
    )
    cnt = jnp.zeros(G, jnp.int32).at[gid].add(vis_l.astype(jnp.int32))
    win = jax.lax.pmax(win, AXIS)
    cnt = jax.lax.psum(cnt, AXIS)

    winner_l = jnp.where(valid_l, win[gid], NONE32)
    conflicts_l = jnp.where(valid_l, cnt[gid], 0)
    winner = jax.lax.all_gather(winner_l, AXIS, tiled=True)
    conflicts = jax.lax.all_gather(conflicts_l, AXIS, tiled=True)

    # per-object stats from the local slice (obj arrays sized P+2 to match
    # resolve_state's layout)
    is_elem_l = insert_l & valid_l
    elem_vis_l = is_elem_l & (winner_l >= 0)
    w_width_l = jnp.where(
        elem_vis_l, c["width"][jnp.clip(winner_l, 0, Ptot - 1)], 0
    )
    obj_idx_l = jnp.where(valid_l, obj_l, jnp.int32(Ptot + 1))
    obj_vis_len = jax.lax.psum(
        jnp.zeros(Ptot + 2, jnp.int32)
        .at[obj_idx_l]
        .add(elem_vis_l.astype(jnp.int32)),
        AXIS,
    )
    obj_text_width = jax.lax.psum(
        jnp.zeros(Ptot + 2, jnp.int32).at[obj_idx_l].add(w_width_l), AXIS
    )
    return winner, conflicts, obj_vis_len, obj_text_width


def _sharded_linearize(c, is_elem, parent_row, first_child, next_sib, Pl):
    """Document-order ranking with SHARDED doubling steps.

    Same algorithm as ops/merge.py device_linearize (threaded successors by
    pointer doubling + Wyllie list ranking) but each device advances only
    its slice of the state arrays per step and an all_gather re-replicates
    them — per-step compute drops to P/n gathers, comms is O(P) per step
    over the mesh axis.
    """
    Ptot = c["action"].shape[0]
    E = Ptot + 1
    SE = jnp.int32(Ptot)
    elem_ref = c["elem_ref"]
    next_sib_e = jnp.concatenate([next_sib[:Ptot], jnp.array([-1], jnp.int32)])
    fc_e = jnp.concatenate(
        [jnp.minimum(first_child[:Ptot], SE + 1), jnp.array([-1], jnp.int32)]
    )
    fc_e = jnp.where(fc_e > SE, NONE32, fc_e)
    parent_e = jnp.concatenate(
        [
            jnp.where(is_elem & (elem_ref >= 0), elem_ref, SE),
            jnp.array([Ptot], jnp.int32),
        ]
    ).astype(jnp.int32)
    is_elem_e = jnp.concatenate([is_elem, jnp.array([False])])
    has_sib = next_sib_e != NONE32
    done = has_sib | ~is_elem_e | (parent_e == SE)
    ans = jnp.where(has_sib & is_elem_e, next_sib_e, NONE32)
    jump = parent_e

    # element-space slices: E = P + 1, so row-slice length Pl would leave
    # the sentinel uncovered (n*Pl = P < E). Element space gets its own
    # slice length El = Pl + 1; arrays pad to n*El and padding entries are
    # fixed points of both loops (done=True / dist=0, nxt=SE), so covering
    # them is harmless.
    n_sh = Ptot // Pl
    El = Pl + 1
    Epad = n_sh * El
    i0 = jax.lax.axis_index(AXIS) * El

    def pad_e(x, fill):
        return jnp.concatenate([x, jnp.full(Epad - E, fill, x.dtype)])

    def sl(x):
        return jax.lax.dynamic_slice_in_dim(x, i0, El)

    def regather(x_l):
        return jax.lax.all_gather(x_l, AXIS, tiled=True)

    # thread: resolve next-sibling-of-nearest-ancestor by doubling
    ansP, doneP, jumpP = pad_e(ans, NONE32), pad_e(done, True), pad_e(jump, SE)

    def _thread(_, st):
        ansF, doneF, jumpF = st
        a_l, d_l, j_l = sl(ansF), sl(doneF), sl(jumpF)
        take = (~d_l) & doneF[j_l]
        a_l = jnp.where(take, ansF[j_l], a_l)
        d_l = d_l | take
        j_l = jumpF[j_l]
        return regather(a_l), regather(d_l), regather(j_l)

    ansP, doneP, jumpP = jax.lax.fori_loop(
        0, _ceil_log2(E) + 1, _thread, (ansP, doneP, jumpP)
    )
    ans = ansP[:E]

    succ_e = jnp.where(fc_e != NONE32, fc_e, ans)
    nxt = jnp.where(succ_e < 0, SE, succ_e)
    nxt = nxt.at[SE].set(SE)
    dist = jnp.where(jnp.arange(E, dtype=jnp.int32) == SE, 0, 1).astype(jnp.int32)
    distP, nxtP = pad_e(dist, 0), pad_e(nxt, SE)

    def _rank(_, st):
        dF, nF = st
        d_l, n_l = sl(dF), sl(nF)
        d_l = d_l + dF[n_l]
        n_l = nF[n_l]
        return regather(d_l), regather(n_l)

    distP, nxtP = jax.lax.fori_loop(0, _ceil_log2(E) + 1, _rank, (distP, nxtP))
    dist = distP[:E]
    rows = jnp.arange(Ptot, dtype=jnp.int32)
    start = first_child[Ptot + c["obj_dense"]]
    start_c = jnp.clip(start, 0, Ptot - 1)
    return jnp.where(
        is_elem & (start >= 0), dist[start_c] - dist[rows], NONE32
    )


def _sharded_linearize_condensed(c, cond, Pl, Rl):
    """Document-order ranking over the chain-CONDENSED graph.

    The host collapses first-child chains (native/condense.cpp) to R
    chains; preorder is chain-to-chain (a non-first child is always a
    chain head), so both iterative phases — the ancestor climb and the
    Wyllie ranking — run over R-sized arrays. Per doubling step each
    device advances its R/n slice and all_gathers O(R), not O(P): the
    collective volume follows the CONDENSED problem size (VERDICT r3
    item 7). Expansion back to element ranks is elementwise on P/n
    slices with ONE final P-sized all_gather.
    """
    Ptot = c["action"].shape[0]
    R2 = cond["tail_ans"].shape[0]
    SC = jnp.int32(R2 - 1)  # sentinel chain slot (len 0, self-loop)
    i0 = jax.lax.axis_index(AXIS) * Rl

    def slr(x):
        return jax.lax.dynamic_slice_in_dim(x, i0, Rl)

    def regather(x_l):
        return jax.lax.all_gather(x_l, AXIS, tiled=True)

    cpar = cond["cpar"]
    centry = cond["centry"]
    tail_ans = cond["tail_ans"]
    # climb: first non-missing centry along the cpar chain, starting at
    # the chain itself; chains whose parent is a root terminate with NONE
    done0 = (centry != NONE32) | (cpar == NONE32)
    ans0 = jnp.where(centry != NONE32, centry, NONE32)
    jump0 = jnp.where(cpar == NONE32, jnp.arange(R2, dtype=jnp.int32), cpar)

    def _climb(_, st):
        ansF, doneF, jumpF = st
        a_l, d_l, j_l = slr(ansF), slr(doneF), slr(jumpF)
        take = (~d_l) & doneF[j_l]
        a_l = jnp.where(take, ansF[j_l], a_l)
        d_l = d_l | take
        j_l = jumpF[j_l]
        return regather(a_l), regather(d_l), regather(j_l)

    ans, _, _ = jax.lax.fori_loop(
        0, _ceil_log2(R2) + 1, _climb, (ans0, done0, jump0)
    )
    # A(tail): the within-chain answer wins; else the resolved climb
    a_elem = jnp.where(tail_ans != NONE32, tail_ans, ans)
    # condensed successor: A targets are always chain heads
    cnxt = jnp.where(
        a_elem >= 0, cond["chain_id"][jnp.clip(a_elem, 0, Ptot - 1)], SC
    ).astype(jnp.int32)
    cnxt = cnxt.at[SC].set(SC)
    cdist = cond["clen"].astype(jnp.int32)

    def _rank(_, st):
        dF, nF = st
        d_l, n_l = slr(dF), slr(nF)
        d_l = d_l + dF[n_l]
        n_l = nF[n_l]
        return regather(d_l), regather(n_l)

    cdist, cnxt = jax.lax.fori_loop(
        0, _ceil_log2(R2) + 1, _rank, (cdist, cnxt)
    )

    # expansion: element rank from (chain rank, in-chain offset)
    ip = jax.lax.axis_index(AXIS) * Pl

    def slp(x):
        return jax.lax.dynamic_slice_in_dim(x, ip, Pl)

    cid_l = slp(cond["chain_id"])
    off_l = slp(cond["offset"])
    obj_l = slp(c["obj_dense"])
    is_elem_l = slp(c["insert"]) & (slp(c["action"]) != PAD_ACTION)
    start_l = cond["start_chain"][obj_l]
    dist_l = cdist[jnp.clip(cid_l, 0, R2 - 1)] - off_l
    dstart_l = cdist[jnp.clip(start_l, 0, R2 - 1)]
    rank_l = jnp.where(
        is_elem_l & (cid_l >= 0) & (start_l >= 0), dstart_l - dist_l, NONE32
    )
    return jax.lax.all_gather(rank_l, AXIS, tiled=True)


def _sharded_merge(c, Pl, n_objs2, n_props, G, use_scatter, cond=None, Rl=0):
    """shard_map body: every phase sharded (see module docstring)."""
    partial_counts = succ_resolution(c)
    succ_count, inc_count, counter_inc = (
        jax.lax.psum(x, AXIS) for x in partial_counts
    )
    if use_scatter:
        visible = visibility(c, succ_count, inc_count)
        winner, conflicts, obj_vis_len, obj_text_width = _sharded_winners(
            c, visible, Pl, n_objs2, n_props, G
        )
        is_elem, parent_row, first_child, next_sib = _forest(c)
        core = {
            "visible": visible,
            "counter_inc": counter_inc,
            "winner": winner,
            "conflicts": conflicts,
            "succ_count": succ_count,
            "inc_count": inc_count,
            "first_child": first_child,
            "next_sib": next_sib,
            "parent_row": parent_row,
            "is_elem": is_elem,
            "obj_vis_len": obj_vis_len,
            "obj_text_width": obj_text_width,
        }
    else:
        # map-group table too large for the dense gid space: replicated
        # sort-based resolution (the round-2 shape), sharded scatter only
        core = resolve_state(c, succ_count, inc_count, counter_inc)
        is_elem = core["is_elem"]
        parent_row = core["parent_row"]
        first_child = core["first_child"]
        next_sib = core["next_sib"]
    if cond is not None:
        core["elem_index"] = _sharded_linearize_condensed(c, cond, Pl, Rl)
    else:
        core["elem_index"] = _sharded_linearize(
            c, is_elem, parent_row, first_child, next_sib, Pl
        )
    return core


@lru_cache(maxsize=None)
def _make_sharded_fn(
    mesh: Mesh, Ptot: int, n_objs2: int, n_props: int, packed_key,
    R2: int = 0,
):
    n = mesh.devices.size
    Pl = Ptot // n
    n_props_eff = max(n_props, 1)
    G = Ptot + 2 * n_objs2 + n_objs2 * n_props_eff + 1
    use_scatter = n_objs2 * n_props_eff <= 8 * Ptot + 65536
    if not use_scatter:
        G = Ptot + 1  # unused
    Rl = R2 // n
    cond_specs = (
        {
            "chain_id": P(), "offset": P(), "tail_ans": P(), "cpar": P(),
            "centry": P(), "clen": P(), "start_chain": P(),
        }
        if R2
        else None
    )

    if packed_key is None:

        def body(cols, *cond_arg):
            return _sharded_merge(
                cols, Pl=Pl, n_objs2=n_objs2, n_props=n_props_eff, G=G,
                use_scatter=use_scatter,
                cond=cond_arg[0] if cond_arg else None, Rl=Rl,
            )

        # check_vma=False: outputs pass through all_gather, whose
        # replication the vma checker cannot infer statically (values ARE
        # identical across shards — asserted by the CPU-mesh equality tests)
        in_specs = (
            (dict(COLUMN_SPECS), cond_specs)
            if R2
            else (dict(COLUMN_SPECS),)
        )
        fn = jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False,
        )
        return jax.jit(fn)

    # packed transport: runs decoded on device inside the body; the pred
    # stream is sliced per shard from the expanded columns
    def packed_body(arrays, *cond_arg):
        cols = _unpack_transport(packed_key[0], arrays, Ptot, packed_key[1])
        q = packed_key[1]
        ql = q // n
        qi = jax.lax.axis_index(AXIS) * ql
        c = dict(cols)
        c["pred_src"] = jax.lax.dynamic_slice_in_dim(cols["pred_src"], qi, ql)
        c["pred_tgt"] = jax.lax.dynamic_slice_in_dim(cols["pred_tgt"], qi, ql)
        return _sharded_merge(
            c, Pl=Pl, n_objs2=n_objs2, n_props=n_props_eff, G=G,
            use_scatter=use_scatter,
            cond=cond_arg[0] if cond_arg else None, Rl=Rl,
        )

    in_specs = (P(), cond_specs) if R2 else (P(),)
    fn = jax.shard_map(
        packed_body, mesh=mesh, in_specs=in_specs, out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def make_sharded_merge(mesh: Mesh, n_objs2: int = None, n_props: int = None):
    """Build a jitted N-chip merge for ``mesh`` (dict-transport variant).

    Kept for callers that prepare padded columns themselves. Without real
    ``n_objs2``/``n_props`` geometry the conservative defaults route map
    groups through the sort-based fallback (a dense table sized from
    guesses would silently collapse distinct map keys into one group).
    """
    n = mesh.devices.size

    def run(cols):
        P_ = cols["action"].shape[0]
        if P_ % n:
            raise ValueError(
                f"row capacity {P_} must divide evenly over {n} devices"
            )
        no2 = n_objs2 if n_objs2 is not None else P_ + 2
        np_ = n_props if n_props is not None else P_
        return _make_sharded_fn(mesh, P_, no2, np_, None)(cols)

    return run


def _pad_to_multiple(a: np.ndarray, m: int, fill) -> np.ndarray:
    r = (-len(a)) % m
    if r == 0:
        return a
    return np.concatenate([a, np.full(r, fill, dtype=a.dtype)])


def _next_pow2(n: int) -> int:
    return 1 << (max(n, 1) - 1).bit_length()


def condense_host(cols_np, n_objs2: int, n_shards: int):
    """Host chain condensation feeding the o(P)-collective linearization.

    Builds the sibling forest with one lexsort (ops/oplog.py host_forest)
    and collapses first-child chains natively (native/condense.cpp);
    returns (R2, cond arrays) with chain arrays padded to a pow2 bucket
    R2 > R that divides over ``n_shards``, the last slot reserved as the
    list-end sentinel. Raises NativeUnavailable when the native core is
    absent (callers fall back to the replicated doubling).
    """
    from .. import native
    from ..ops.oplog import host_forest

    insert, parent_row, first_child, next_sib = host_forest(cols_np)
    Ptot = len(insert)
    R, cond = native.chain_condense(
        first_child, next_sib, parent_row, insert, Ptot, n_objs2
    )
    # strictly > R so the last slot is free for the sentinel, and a
    # multiple of n_shards so the per-device slices tile exactly
    R2 = max(_next_pow2(R + 1), 2)
    R2 = -(-R2 // n_shards) * n_shards
    out = {
        "chain_id": np.ascontiguousarray(cond["chain_id"], np.int32),
        "offset": np.ascontiguousarray(cond["offset"], np.int32),
        "tail_ans": _pad_exact(cond["tail_ans"], R2, -1),
        "cpar": _pad_exact(cond["cpar"], R2, -1),
        "centry": _pad_exact(cond["centry"], R2, -1),
        "clen": _pad_exact(cond["len"], R2, 0),
        "start_chain": np.ascontiguousarray(cond["start_chain"], np.int32),
    }
    return R2, out


def _pad_exact(a: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, np.int32)
    out[: len(a)] = a
    return out


def sharded_merge_columns(
    cols_np, mesh: Optional[Mesh] = None, n_objs: Optional[int] = None,
    n_props: Optional[int] = None, transport: str = "dict",
):
    """Host entry: numpy columns in, numpy resolution out, over ``mesh``.

    Arrays are placed with explicit per-column shardings on the mesh's own
    devices — never the process-default backend, which may be a different
    (or unusable) client than the mesh was built over.

    ``n_objs``/``n_props`` (the live object/prop counts, from OpLog) size
    the dense map-group table; absent, conservative defaults route map
    groups through the sort-based fallback. ``transport="packed"`` ships
    slope-RLE runs and decodes on device (the thin-link path).
    """
    mesh = mesh or default_mesh()
    n = mesh.devices.size
    cols_np = dict(cols_np)
    cols_np["pred_src"] = _pad_to_multiple(cols_np["pred_src"], n, 0)
    cols_np["pred_tgt"] = _pad_to_multiple(cols_np["pred_tgt"], n, -1)
    Ptot = len(cols_np["action"])
    if Ptot % n:
        raise ValueError(
            f"row capacity {Ptot} must divide evenly over {n} devices "
            "(padded_columns capacities are powers of two / 8k multiples)"
        )
    n_objs2 = (n_objs + 2) if n_objs is not None else Ptot + 2
    np_eff = n_props if n_props is not None else Ptot

    # chain-condensed linearization (o(P) collectives per doubling step);
    # the replicated full-size doubling remains the no-native fallback
    from .. import native as _native

    R2 = 0
    cond_np = None
    try:
        with obs.span("parallel.condense", rows=Ptot):
            R2, cond_np = condense_host(cols_np, n_objs2, n)
    except _native.NativeUnavailable:
        pass

    def put_cond():
        return {
            k: jax.device_put(v, NamedSharding(mesh, P()))
            for k, v in cond_np.items()
        }

    obs.count("device.kernel_launches", labels={"path": "sharded"})
    _prof.note("launches")
    if transport == "packed":
        static_key, arrays = encode_transport(cols_np)
        fn = _make_sharded_fn(
            mesh, Ptot, n_objs2, np_eff,
            (static_key, len(cols_np["pred_src"])), R2,
        )
        with obs.span("parallel.h2d", rows=Ptot):
            arrs = {
                k: jax.device_put(v, NamedSharding(mesh, P()))
                for k, v in arrays.items()
            }
            cond = put_cond() if R2 else None
        with obs.span("parallel.kernel", rows=Ptot, devices=n), \
                _prof.annotate("amtpu.sharded_launch"):
            out = fn(arrs, cond) if R2 else fn(arrs)
    else:
        with obs.span("parallel.h2d", rows=Ptot):
            cols = {
                k: jax.device_put(v, NamedSharding(mesh, COLUMN_SPECS[k]))
                for k, v in cols_np.items()
            }
            cond = put_cond() if R2 else None
        fn = _make_sharded_fn(mesh, Ptot, n_objs2, np_eff, None, R2)
        with obs.span("parallel.kernel", rows=Ptot, devices=n), \
                _prof.annotate("amtpu.sharded_launch"):
            out = fn(cols, cond) if R2 else fn(cols)
    with obs.span("parallel.readback", rows=Ptot):
        return {k: np.asarray(v) for k, v in out.items()}
