"""Multi-chip merge: shard the op-log merge over a jax.sharding.Mesh.

The reference's "distribution" is logical (actors + the sync protocol,
reference: rust/automerge/src/sync.rs); its compute is single-threaded. On
TPU the merge itself scales across chips: the pred stream — the dominant
data volume, one entry per overwritten/deleted op — is sharded across the
mesh, every device scatter-adds its slice into full-size succ/inc counter
arrays, and one ``psum`` over ICI combines them (a segmented all-reduce,
the collective analogue of the reference's per-op ``add_succ``,
op_set.rs:194-203). State resolution (winners + RGA linearization) then
runs replicated on every chip, so the resolved document is immediately
available device-local for downstream reads on any shard.

Scaling model (How-to-Scale style): succ resolution is memory-bound with
per-device cost Q/n + one P-sized all-reduce; resolution is O(P log P)
sort-bound and replicated. For fan-in merges Q ≈ P, so chips shave the
scatter phase while the all-reduce cost stays flat — the next lever
(sharding the lexsorts) is a later-round optimization.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.merge import device_linearize, resolve_state, succ_resolution

AXIS = "shard"


def default_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` available devices."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count=N for a "
                "virtual CPU mesh)"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


# column -> partition spec: the pred stream splits along the mesh axis, op
# columns are replicated (single source of truth for in_specs + device_put)
COLUMN_SPECS = {
    "action": P(),
    "insert": P(),
    "prop": P(),
    "elem_ref": P(),
    "obj_dense": P(),
    "value_tag": P(),
    "value_i32": P(),
    "width": P(),
    "covered": P(),
    "pred_src": P(AXIS),
    "pred_tgt": P(AXIS),
}


def _sharded_merge(c):
    """shard_map body: sharded pred scatter + psum, replicated resolution."""
    partial_counts = succ_resolution(c)
    succ_count, inc_count, counter_inc = (
        jax.lax.psum(x, AXIS) for x in partial_counts
    )
    core = resolve_state(c, succ_count, inc_count, counter_inc)
    core["elem_index"] = device_linearize(c, core)
    return core


@lru_cache(maxsize=None)
def make_sharded_merge(mesh: Mesh):
    """Build a jitted N-chip merge function for ``mesh``.

    Input: the padded column dict (OpLog.padded_columns). The pred stream
    is split along the mesh axis; op columns are replicated. Output arrays
    are replicated (identical on every chip).
    """
    in_specs = (dict(COLUMN_SPECS),)
    fn = jax.shard_map(
        _sharded_merge,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
    )
    return jax.jit(fn)


def _pad_to_multiple(a: np.ndarray, m: int, fill) -> np.ndarray:
    r = (-len(a)) % m
    if r == 0:
        return a
    return np.concatenate([a, np.full(r, fill, dtype=a.dtype)])


def sharded_merge_columns(cols_np, mesh: Optional[Mesh] = None):
    """Host entry: numpy columns in, numpy resolution out, over ``mesh``.

    Arrays are placed with explicit per-column shardings on the mesh's own
    devices — never the process-default backend, which may be a different
    (or unusable) client than the mesh was built over.
    """
    mesh = mesh or default_mesh()
    n = mesh.devices.size
    cols_np = dict(cols_np)
    # the pred stream must split evenly across the mesh axis
    cols_np["pred_src"] = _pad_to_multiple(cols_np["pred_src"], n, 0)
    cols_np["pred_tgt"] = _pad_to_multiple(cols_np["pred_tgt"], n, -1)
    cols = {
        k: jax.device_put(v, NamedSharding(mesh, COLUMN_SPECS[k]))
        for k, v in cols_np.items()
    }
    fn = make_sharded_merge(mesh)
    out = fn(cols)
    return {k: np.asarray(v) for k, v in out.items()}
