from .sharding import default_mesh, make_sharded_merge, sharded_merge_columns

__all__ = ["default_mesh", "make_sharded_merge", "sharded_merge_columns"]
