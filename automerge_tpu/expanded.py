"""ExpandedChange: a JSON-able view of a change chunk.

The analogue of the reference's legacy ExpandedChange form used by
``decodeChange`` and the CLI's examine output (reference:
rust/automerge/src/legacy/, rust/automerge/src/change.rs:283-338): op ids
become "<ctr>@<actorhex>" strings, values carry explicit datatypes where
the JSON type is ambiguous.
"""

from __future__ import annotations

from typing import List

from .storage.change import ChangeOp, StoredChange
from .types import Action, ScalarValue

_ACTION_NAMES = {
    Action.MAKE_MAP: "makeMap",
    Action.PUT: "set",
    Action.MAKE_LIST: "makeList",
    Action.DELETE: "del",
    Action.MAKE_TEXT: "makeText",
    Action.INCREMENT: "inc",
    Action.MAKE_TABLE: "makeTable",
    Action.MARK: "mark",
}


def _opid_str(opid, actors: List[bytes]) -> str:
    return f"{opid[0]}@{actors[opid[1]].hex()}"


def _value_json(v: ScalarValue):
    if v.tag == "counter":
        return {"value": v.value, "datatype": "counter"}
    if v.tag == "timestamp":
        return {"value": v.value, "datatype": "timestamp"}
    if v.tag == "uint":
        return {"value": v.value, "datatype": "uint"}
    if v.tag == "f64":
        return {"value": v.value, "datatype": "float64"}
    if v.tag == "bytes":
        return {"value": v.value.hex(), "datatype": "bytes"}
    if v.tag == "unknown":
        code, raw = v.value
        return {"value": raw.hex(), "datatype": f"unknown{code}"}
    return v.to_py()


def expand_change(change: StoredChange) -> dict:
    actors = list(change.actors)
    ops = []
    for i, cop in enumerate(change.ops):
        op: dict = {
            "action": _ACTION_NAMES.get(Action(cop.action), str(cop.action)),
            "obj": "_root" if cop.obj[0] == 0 else _opid_str(cop.obj, actors),
            "insert": bool(cop.insert),
            "pred": [_opid_str(p, actors) for p in cop.pred],
        }
        if cop.key.prop is not None:
            op["key"] = cop.key.prop
        else:
            e = cop.key.elem
            op["elemId"] = "_head" if e[0] == 0 else _opid_str(e, actors)
        if cop.action in (Action.PUT, Action.INCREMENT, Action.MARK):
            op["value"] = _value_json(cop.value)
        if cop.mark_name is not None:
            op["name"] = cop.mark_name
        if cop.expand:
            op["expand"] = True
        ops.append(op)
    return {
        "actor": change.actor.hex(),
        "seq": change.seq,
        "startOp": change.start_op,
        "time": change.timestamp,
        "message": change.message,
        "deps": [d.hex() for d in sorted(change.dependencies)],
        "hash": change.hash.hex() if change.hash else None,
        "ops": ops,
    }
