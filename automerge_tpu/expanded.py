"""ExpandedChange: a JSON-able view of a change chunk.

The analogue of the reference's legacy ExpandedChange form used by
``decodeChange`` and the CLI's examine output (reference:
rust/automerge/src/legacy/, rust/automerge/src/change.rs:283-338): op ids
become "<ctr>@<actorhex>" strings, values carry explicit datatypes where
the JSON type is ambiguous.
"""

from __future__ import annotations

from typing import List

from .storage.change import ChangeOp, StoredChange
from .types import Action, Key, ScalarValue

_ACTION_NAMES = {
    Action.MAKE_MAP: "makeMap",
    Action.PUT: "set",
    Action.MAKE_LIST: "makeList",
    Action.DELETE: "del",
    Action.MAKE_TEXT: "makeText",
    Action.INCREMENT: "inc",
    Action.MAKE_TABLE: "makeTable",
    Action.MARK: "mark",
}


def _opid_str(opid, actors: List[bytes]) -> str:
    return f"{opid[0]}@{actors[opid[1]].hex()}"


def _value_json(v: ScalarValue):
    if v.tag == "counter":
        return {"value": v.value, "datatype": "counter"}
    if v.tag == "timestamp":
        return {"value": v.value, "datatype": "timestamp"}
    if v.tag == "uint":
        return {"value": v.value, "datatype": "uint"}
    if v.tag == "f64":
        return {"value": v.value, "datatype": "float64"}
    if v.tag == "bytes":
        return {"value": v.value.hex(), "datatype": "bytes"}
    if v.tag == "unknown":
        code, raw = v.value
        return {"value": raw.hex(), "datatype": f"unknown{code}"}
    return v.to_py()


def expand_change(change: StoredChange) -> dict:
    actors = list(change.actors)
    ops = []
    for i, cop in enumerate(change.ops):
        op: dict = {
            "action": _ACTION_NAMES.get(Action(cop.action), str(cop.action)),
            "obj": "_root" if cop.obj[0] == 0 else _opid_str(cop.obj, actors),
            "insert": bool(cop.insert),
            "pred": [_opid_str(p, actors) for p in cop.pred],
        }
        if cop.key.prop is not None:
            op["key"] = cop.key.prop
        else:
            e = cop.key.elem
            op["elemId"] = "_head" if e[0] == 0 else _opid_str(e, actors)
        if cop.action in (Action.PUT, Action.INCREMENT, Action.MARK):
            op["value"] = _value_json(cop.value)
        if cop.mark_name is not None:
            op["name"] = cop.mark_name
        if cop.expand:
            op["expand"] = True
        ops.append(op)
    return {
        "actor": change.actor.hex(),
        "seq": change.seq,
        "startOp": change.start_op,
        "time": change.timestamp,
        "message": change.message,
        "deps": [d.hex() for d in sorted(change.dependencies)],
        "hash": change.hash.hex() if change.hash else None,
        "ops": ops,
        "extraBytes": change.extra_bytes.hex() if change.extra_bytes else None,
    }


_ACTION_FOR = {name: act for act, name in _ACTION_NAMES.items()}


def _value_from_json(v) -> ScalarValue:
    if isinstance(v, dict):
        dt = v.get("datatype")
        raw = v.get("value")
        if dt == "counter":
            return ScalarValue("counter", int(raw))
        if dt == "timestamp":
            return ScalarValue("timestamp", int(raw))
        if dt == "uint":
            return ScalarValue("uint", int(raw))
        if dt == "float64":
            return ScalarValue("f64", float(raw))
        if dt == "bytes":
            return ScalarValue("bytes", bytes.fromhex(raw))
        if isinstance(dt, str) and dt.startswith("unknown"):
            return ScalarValue("unknown", (int(dt[7:]), bytes.fromhex(raw)))
        raise ValueError(f"unknown datatype {dt!r}")
    if v is None:
        return ScalarValue("null")
    if isinstance(v, bool):
        return ScalarValue("bool", v)
    if isinstance(v, int):
        return ScalarValue("int", v)
    if isinstance(v, float):
        return ScalarValue("f64", v)
    if isinstance(v, str):
        return ScalarValue("str", v)
    raise ValueError(f"cannot collapse value {v!r}")


def collapse_change(expanded: dict) -> StoredChange:
    """The inverse of ``expand_change``: JSON form -> built StoredChange.

    The analogue of the reference's ``ExpandedChange -> Change`` conversion
    (reference: rust/automerge/src/change.rs:283-338 via legacy/). The
    returned change is fully built (hash + raw bytes), so an
    expand/collapse roundtrip preserves the change hash.
    """
    from .storage.change import HEAD_STORED, ROOT_STORED, build_change

    author = bytes.fromhex(expanded["actor"])
    others = sorted(
        {
            bytes.fromhex(s.split("@", 1)[1])
            for op in expanded["ops"]
            for s in [op["obj"], op.get("elemId", "_head"), *op["pred"]]
            if s not in ("_root", "_head")
        }
        - {author}
    )
    actors = [author, *others]
    idx_of = {a: i for i, a in enumerate(actors)}

    def opid(s: str) -> tuple:
        ctr_s, actor_hex = s.split("@", 1)
        return (int(ctr_s), idx_of[bytes.fromhex(actor_hex)])

    ops = []
    for op in expanded["ops"]:
        action = _ACTION_FOR.get(op["action"])
        if action is None:
            raise ValueError(f"unknown action {op['action']!r}")
        if "key" in op:
            key = Key.map(op["key"])
        else:
            e = op.get("elemId", "_head")
            key = Key.seq(HEAD_STORED if e == "_head" else opid(e))
        ops.append(
            ChangeOp(
                obj=ROOT_STORED if op["obj"] == "_root" else opid(op["obj"]),
                key=key,
                insert=bool(op.get("insert")),
                action=int(action),
                value=_value_from_json(op.get("value")),
                # preserve the stored pred order (Lamport by actor BYTES —
                # re-sorting by chunk-local index would change the bytes
                # and the hash)
                pred=[opid(p) for p in op["pred"]],
                expand=bool(op.get("expand")),
                mark_name=op.get("name"),
            )
        )
    return build_change(
        StoredChange(
            dependencies=sorted(bytes.fromhex(d) for d in expanded["deps"]),
            actor=author,
            other_actors=others,
            seq=int(expanded["seq"]),
            start_op=int(expanded["startOp"]),
            timestamp=int(expanded.get("time") or 0),
            message=expanded.get("message"),
            ops=ops,
            extra_bytes=bytes.fromhex(expanded["extraBytes"])
            if expanded.get("extraBytes")
            else b"",
        )
    )
