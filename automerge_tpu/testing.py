"""Test DSL: conflict-aware document realization and assertions.

The analogue of the reference's ``automerge-test`` crate
(reference: rust/automerge-test/src/lib.rs:90-204,336-392): ``realize``
fully hydrates a document INCLUDING all conflicting values per slot, and
``map_``/``list_``/``val`` build the expected shape. Every map key and
sequence index maps to a *set* of realized values, because any property in
a CRDT document can hold concurrent conflicting writes.

Works against anything exposing the ReadDoc surface (keys/length/
object_type/get_all): the host ``Document``/``AutoDoc`` and the device
``DeviceDoc`` alike — which is exactly how the ported integration corpus
(tests/test_ported.py) asserts host/device parity.

Realized encoding (hashable, order-canonical):
  value     -> ("value", tag, payload)
  counter   -> ("value", "counter", current total)
  map/table -> ("map", ((key, frozenset(values)), ... sorted by key))
  list/text -> ("list", (frozenset(values) per index, ...))
"""

from __future__ import annotations

import os
import pprint
from typing import Iterable, Mapping

from .api import AutoDoc
from .types import ActorId, ObjType, ScalarValue

__all__ = [
    "assert_doc",
    "assert_obj",
    "list_",
    "map_",
    "new_doc",
    "pretty",
    "realize",
    "realize_obj",
    "sorted_actors",
    "text_",
    "val",
]


def new_doc(seed: int = None) -> AutoDoc:
    """A fresh AutoDoc with a random (or seeded) actor id."""
    raw = os.urandom(16) if seed is None else (seed % (1 << 128)).to_bytes(16, "little")
    return AutoDoc(actor=ActorId(raw))


def sorted_actors():
    """Two random actor ids, the first ordered before the second."""
    a, b = os.urandom(16), os.urandom(16)
    while a == b:
        b = os.urandom(16)
    a, b = sorted((a, b))
    return ActorId(a), ActorId(b)


# -- realization --------------------------------------------------------------


def realize(doc, heads=None):
    """Fully hydrate ``doc`` from the root, conflicts included."""
    return realize_obj(doc, "_root", ObjType.MAP, heads=heads)


def realize_obj(doc, obj: str, objtype: ObjType = None, heads=None):
    if objtype is None:
        objtype = doc.object_type(obj)
    if objtype in (ObjType.MAP, ObjType.TABLE):
        entries = []
        for key in doc.keys(obj, heads=heads):
            entries.append((key, _realize_values(doc, obj, key, heads)))
        return ("map", tuple(sorted(entries)))
    length = doc.length(obj, heads=heads)
    slots = []
    i = 0
    while i < length:
        vals = _realize_values(doc, obj, i, heads)
        if not vals:
            break
        slots.append(vals)
        # TEXT indexes by character position: advance by the winner's width
        if objtype == ObjType.TEXT:
            i += _slot_width(doc, obj, i, heads)
        else:
            i += 1
    return ("list", tuple(slots))


def _slot_width(doc, obj, i, heads) -> int:
    got = doc.get_all(obj, i, heads=heads)
    if not got:
        return 1
    rendered = got[-1][0]
    if rendered[0] == "scalar" and rendered[1].tag == "str":
        return max(len(rendered[1].value), 1)
    return 1


def _realize_values(doc, obj, prop, heads) -> frozenset:
    out = []
    for rendered, exid in doc.get_all(obj, prop, heads=heads):
        kind = rendered[0]
        if kind == "obj":
            out.append(realize_obj(doc, exid, rendered[1], heads=heads))
        elif kind == "counter":
            out.append(("value", "counter", rendered[1]))
        else:
            sv = rendered[1]
            out.append(("value", sv.tag, sv.value))
    return frozenset(out)


# -- expected-shape constructors ----------------------------------------------


def val(x):
    """Lift a python scalar / ScalarValue / realized node to realized form."""
    if isinstance(x, tuple) and x and x[0] in ("map", "list", "value"):
        return x
    if isinstance(x, ScalarValue):
        if x.tag == "counter":
            return ("value", "counter", x.value)
        return ("value", x.tag, x.value)
    if x is None:
        return ("value", "null", None)
    if isinstance(x, bool):
        return ("value", "bool", x)
    if isinstance(x, int):
        return ("value", "int", x)
    if isinstance(x, float):
        return ("value", "f64", x)
    if isinstance(x, str):
        return ("value", "str", x)
    if isinstance(x, bytes):
        return ("value", "bytes", x)
    raise TypeError(f"cannot realize expected value {x!r}")


def _value_set(v) -> frozenset:
    """One slot's expected value(s): a set/frozenset means conflicts."""
    if isinstance(v, (set, frozenset)):
        return frozenset(val(x) for x in v)
    return frozenset([val(v)])


def map_(entries: Mapping) -> tuple:
    """Expected map: ``map_({"k": 1, "c": {1, 2}})`` (sets = conflicts)."""
    return ("map", tuple(sorted((k, _value_set(v)) for k, v in entries.items())))


def list_(items: Iterable) -> tuple:
    """Expected sequence: ``list_([1, {2, 3}])`` (sets = conflicts)."""
    return ("list", tuple(_value_set(v) for v in items))


def text_(s: str) -> tuple:
    """Expected text object: one single-char slot per character."""
    return ("list", tuple(frozenset([("value", "str", ch)]) for ch in s))


# -- assertions ----------------------------------------------------------------


def _pretty(node, indent=0):
    pad = "  " * indent
    kind = node[0]
    if kind == "value":
        return f"{pad}{node[1]}:{node[2]!r}"
    if kind == "map":
        lines = [f"{pad}map{{"]
        for k, vals in node[1]:
            body = " | ".join(sorted(_pretty(v).strip() for v in vals))
            lines.append(f"{pad}  {k!r} => {{{body}}}")
        lines.append(pad + "}")
        return "\n".join(lines)
    lines = [f"{pad}list["]
    for vals in node[1]:
        body = " | ".join(sorted(_pretty(v).strip() for v in vals))
        lines.append(f"{pad}  {{{body}}}")
    lines.append(pad + "]")
    return "\n".join(lines)


def assert_doc(doc, expected, heads=None):
    """Assert the whole document realizes to ``expected`` (map_/list_)."""
    got = realize(doc, heads=heads)
    if got != expected:
        raise AssertionError(
            "document mismatch\n-- expected --\n%s\n-- got --\n%s"
            % (_pretty(expected), _pretty(got))
        )


def assert_obj(doc, obj: str, expected, heads=None):
    """Assert one object (by exid) realizes to ``expected``."""
    got = realize_obj(doc, obj, heads=heads)
    if got != expected:
        raise AssertionError(
            "object %s mismatch\n-- expected --\n%s\n-- got --\n%s"
            % (obj, _pretty(expected), _pretty(got))
        )


def pretty(node) -> str:
    """Render a realized node for debugging."""
    try:
        return _pretty(node)
    except Exception:
        return pprint.pformat(node)
