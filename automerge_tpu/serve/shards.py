"""Per-document single-writer shards: the concurrency discipline of the
serving layer.

Every request with document affinity hashes to a per-document ordered
queue. A fixed pool of workers drains those queues one document at a
time — a worker that grabs a document's queue drains up to
``max_batch`` requests in one go (the group-commit / sync-coalescing
window) and no other worker touches that document until the drain
finishes. The result: requests against the SAME document execute in
exact arrival order on one thread at a time (the single-writer
guarantee the core document needs), while requests against different
documents run fully in parallel across the pool.

Queues are bounded: a submit against a full queue fails immediately
(the server answers a ``Backpressure`` error instead of buffering
without limit — the client is the retry loop). Gauges:

* ``rpc.queue_depth{doc=...}`` — per-document queue depth at enqueue /
  drain (the registry's cardinality cap collapses a hostile handle
  churn into ``{overflow=true}``).
* ``rpc.pool_busy`` / ``rpc.pool_utilization`` — workers currently
  executing, absolute and as a fraction of the pool.

The pool is generic over the work items: the server submits
``(connection, request)`` pairs and supplies ``execute(key, items)``;
the pool owns only ordering, bounding and thread placement.
"""

from __future__ import annotations

import threading
from collections import deque
from time import monotonic as _monotonic
from typing import Callable, Dict, Hashable, List, Optional

from .. import obs


class QueueFull(Exception):
    """Raised by ``submit`` when the target document's queue is at its
    bound — the backpressure signal."""


class _DocQueue:
    __slots__ = ("items", "scheduled", "first_ts")

    def __init__(self):
        self.items: deque = deque()
        self.scheduled = False  # a worker owns (or is queued to own) this doc
        self.first_ts = 0.0  # enqueue time of the oldest queued item


class ShardPool:
    """N workers over per-key ordered bounded queues. See module docstring."""

    def __init__(
        self,
        execute: Callable[[Hashable, List], None],
        *,
        workers: int = 4,
        max_queue: int = 128,
        max_batch: int = 32,
        name: str = "shard",
    ):
        if workers <= 0:
            raise ValueError("workers must be positive")
        self._execute = execute
        self.max_queue = max(1, int(max_queue))
        self.max_batch = max(1, int(max_batch))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[Hashable, _DocQueue] = {}
        self._ready: deque = deque()  # keys with work and no owning worker
        self._stopping = False
        self._busy = 0
        self._svc_ewma = 0.0  # per-item execution seconds, EWMA
        # optional hook fed each drain's dequeue wait (seconds); the
        # admission controller installs itself here to keep a *recent*
        # wait estimate (the all-time histogram percentile cannot decay,
        # so it would pin the load score high forever after one burst)
        self.wait_observer: Optional[Callable[[float], None]] = None
        self.workers = [
            threading.Thread(
                target=self._worker, name=f"{name}-{i}", daemon=True
            )
            for i in range(workers)
        ]
        for t in self.workers:
            t.start()

    # -- submission ----------------------------------------------------------

    def submit(self, key: Hashable, item) -> None:
        """Enqueue ``item`` for ``key``; raises ``QueueFull`` at the bound."""
        with self._lock:
            if self._stopping:
                raise QueueFull("pool is shutting down")
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _DocQueue()
            if len(q.items) >= self.max_queue:
                obs.count("rpc.errors",
                          labels={"method": "submit", "type": "Backpressure"})
                raise QueueFull(
                    f"queue for doc {key!r} is full "
                    f"({self.max_queue} pending requests)"
                )
            if not q.items:
                q.first_ts = _monotonic()
            q.items.append(item)
            if not q.scheduled:
                q.scheduled = True
                self._ready.append(key)
                self._cond.notify()

    def depth(self, key: Hashable) -> int:
        with self._lock:
            q = self._queues.get(key)
            return len(q.items) if q is not None else 0

    def utilization(self) -> float:
        """Fraction of workers currently executing (0.0..1.0) — one of
        the admission controller's load signals."""
        with self._lock:
            return self._busy / (len(self.workers) or 1)

    def backlog(self) -> int:
        """Total queued items across every per-document queue."""
        with self._lock:
            return sum(len(q.items) for q in self._queues.values())

    def expected_wait(self) -> float:
        """Expected dequeue wait of the deepest queue RIGHT NOW: its
        depth times the recent per-item service time. Per-doc ordering
        means a doc's queue drains serially, so depth x service time is
        what a request arriving behind it will actually wait. This is
        the admission controller's *present-tense* congestion signal —
        the EWMA of past dequeue waits lags a flood on the way up and
        keeps shedding after the drain on the way down."""
        with self._lock:
            if not self._queues or self._svc_ewma <= 0.0:
                return 0.0
            deepest = max(
                (len(q.items) for q in self._queues.values()), default=0)
            return deepest * self._svc_ewma

    # -- the workers ---------------------------------------------------------

    def _worker(self) -> None:
        n_workers = len(self.workers) or 1
        while True:
            with self._lock:
                while not self._ready and not self._stopping:
                    self._cond.wait()
                if self._stopping and not self._ready:
                    return
                key = self._ready.popleft()
                q = self._queues[key]
                batch = []
                while q.items and len(batch) < self.max_batch:
                    batch.append(q.items.popleft())
                waited = _monotonic() - q.first_ts if batch else 0.0
                q.first_ts = _monotonic()  # the remainder starts waiting now
                self._busy += 1
                busy = self._busy
                depth = len(q.items)
            # gauges are sampled at drain boundaries, not per enqueue: a
            # gauge is a level, and per-request registry-lock traffic from
            # every submitter measurably throttles the pool
            if batch:
                # dequeue latency: how long the oldest request of this
                # drain sat queued before a worker picked the doc up
                obs.observe("serve.queue_wait", waited)
                if self.wait_observer is not None:
                    self.wait_observer(waited)
            obs.gauge_set("rpc.queue_depth", depth, labels={"doc": str(key)})
            obs.gauge_set("rpc.pool_busy", busy)
            obs.gauge_set("rpc.pool_utilization", busy / n_workers)
            t0 = _monotonic()
            try:
                if batch:
                    self._execute(key, batch)
            finally:
                dt = _monotonic() - t0
                popped = False
                with self._lock:
                    if batch:
                        per = dt / len(batch)
                        self._svc_ewma = (
                            per if self._svc_ewma <= 0.0
                            else self._svc_ewma + 0.3 * (per - self._svc_ewma)
                        )
                    self._busy -= 1
                    if q.items:
                        # still work: stay scheduled, go back in line so
                        # other documents get a worker in between
                        self._ready.append(key)
                        self._cond.notify()
                    else:
                        q.scheduled = False
                        # drop the empty queue: handles are unbounded over
                        # a server's life, the queue table must not be
                        self._queues.pop(key, None)
                        popped = True
                if popped:
                    # same hygiene for the gauge: the registry's label
                    # table is as unbounded as the queue table was. A
                    # racing submit may have re-created the queue already;
                    # its next drain simply re-creates the series.
                    obs.gauge_remove("rpc.queue_depth", {"doc": str(key)})

    # -- shutdown ------------------------------------------------------------

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop the pool. ``drain=True`` lets queued work finish; False
        discards whatever has not started executing."""
        with self._lock:
            self._stopping = True
            if not drain:
                for q in self._queues.values():
                    q.items.clear()
            self._cond.notify_all()
        for t in self.workers:
            t.join(timeout=timeout)
