"""Concurrent serving layer: socket transport + per-document shards.

``SocketRpcServer`` (serve/server.py) serves the stdio JSON-RPC protocol
over TCP or unix-domain sockets through a per-document single-writer
shard pool (serve/shards.py), with group-commit durability and
sync-receive coalescing. ``python -m automerge_tpu.rpc --socket`` /
``--unix`` is the command-line entry.
"""

from .server import SocketRpcServer  # noqa: F401
from .shards import QueueFull, ShardPool  # noqa: F401
