"""Socket transport for the JSON-RPC frontend: concurrent serving.

``SocketRpcServer`` exposes the exact stdio protocol (line-delimited
JSON, same method surface, same ``max_request_bytes`` framing
discipline) over TCP or a unix-domain socket, with real concurrency:

* a listener thread accepts connections; each connection gets a reader
  thread that parses frames and routes them;
* requests with document affinity go to the per-document single-writer
  shard pool (serve/shards.py): same-document requests execute in
  arrival order on one worker, different documents run in parallel;
* requests without document affinity (``create``, ``load``,
  ``configure``, ``metrics``, ``syncState*``) execute inline on the
  connection thread — they only touch the handle tables, which the
  ``RpcServer`` guards with its registry lock;
* a full shard queue answers immediately with a ``Backpressure`` error
  (``rpc.errors{type=Backpressure}``) instead of buffering unboundedly —
  the client owns the retry.

Ordering contract: responses to the SAME document arrive in request
order; responses across documents (or for affinity-free methods) may
interleave. Clients match responses by ``id``, exactly as the JSON-RPC
shape always allowed.

Group commit: a worker drains up to ``max_batch`` queued requests for
one document in a single grab and executes them inside the durable
document's ``ack_scope`` — every journal append in the batch rides ONE
policy fsync, and no response is written until that fsync has returned
(the ack is durable, just amortized; ``group_commit.batch_size`` in the
journal records how many appends each physical fsync covered). Runs of
``receiveSyncMessage`` / ``syncSessionReceive`` frames for the same
document additionally coalesce their resident-device feed into a single
``DeviceDoc.apply_batches`` call.

Env knobs (all overridable by constructor arguments):

* ``AUTOMERGE_TPU_SERVE_WORKERS``      worker pool size (default 8)
* ``AUTOMERGE_TPU_SERVE_QUEUE_DEPTH``  per-document queue bound (128)
* ``AUTOMERGE_TPU_SERVE_BATCH``        max requests per drain (16)

Run: ``python -m automerge_tpu.rpc --socket HOST:PORT`` or
``--unix PATH`` (or ``python -m automerge_tpu serve ...``); a
``shutdown`` request from any connection stops the whole server after
flushing durable documents, exactly like EOF does in stdio mode.
"""

from __future__ import annotations

import contextlib
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..obs import prof
from ..ops.batched import CrossDocBatcher
from ..rpc import RpcServer, deadline_response, request_expired
from .admission import AdmissionController, Overloaded
from .shards import QueueFull, ShardPool

_OPEN_DURABLE_KEY = "__open_durable__"  # serializes name-cache races

# methods whose frames coalesce when adjacent in a drain (same doc, same
# sync/session handle): their device feed batches into one apply_batches
_COALESCE_METHODS = ("receiveSyncMessage", "syncSessionReceive")

# methods that must NOT hydrate a cold document before executing: they
# either retire it (free), or exist precisely because the document is
# cold (the migration source path ships a cold doc's on-disk bytes with
# no residency rebuild — hydrating it first would defeat that)
_NO_HYDRATE_METHODS = frozenset(
    {"free", "docFence", "migrateOut", "migrateTail", "migrateRelease"}
)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _run_trace_links(run) -> list:
    """Span links for a coalesced run: the wire trace context of every
    request the shared span covers (a coalesced receive executes many
    clients' frames under ONE span — links keep each attributable).
    Sanitization (and the 16-entry cap) is obs.decode_wire_traces' —
    the one place the wire trace-pair contract lives."""
    pairs = [
        [tr.get("t"), tr.get("s")]
        for _conn, req in run
        if isinstance(tr := req.get("trace"), dict)
    ]
    return obs.decode_wire_traces(pairs)


class _Conn:
    """One client connection: socket + serialized writes."""

    __slots__ = ("sock", "peer", "wlock", "alive")

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self.wlock = threading.Lock()
        self.alive = True

    def send(self, payload: str) -> None:
        """Write one response line; a dead peer is counted, never raised."""
        data = payload.encode("utf-8")
        try:
            with self.wlock:
                self.sock.sendall(data)
            obs.count("rpc.bytes_out", n=len(data))
        except Exception as e:
            if self.alive:
                self.alive = False
                obs.count("rpc.errors",
                          labels={"method": "transport", "type": "transport"})
                obs.event("rpc.transport_death", stage="write",
                          peer=self.peer, error=str(e))

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class SocketRpcServer:
    """The concurrent serving layer over one shared ``RpcServer`` state."""

    def __init__(
        self,
        rpc: Optional[RpcServer] = None,
        *,
        host: Optional[str] = None,
        port: int = 0,
        unix_path: Optional[str] = None,
        workers: Optional[int] = None,
        max_queue: Optional[int] = None,
        max_batch: Optional[int] = None,
        durable_dir: Optional[str] = None,
    ):
        if (host is None) == (unix_path is None):
            raise ValueError("exactly one of host or unix_path is required")
        self.rpc = rpc or RpcServer(durable_dir=durable_dir)
        # durable docs opened by a concurrent server compact off the ack
        # path (background thread + per-doc lock)
        self.rpc.serve_background_compact = True
        self._host = host
        self._port = port
        self._unix_path = unix_path
        self._listener: Optional[socket.socket] = None
        self._conns: Dict[int, _Conn] = {}
        self._conns_lock = threading.Lock()
        self._next_conn = 1
        self._shutdown = threading.Event()
        self._stopped = threading.Event()
        self._stop_lock = threading.Lock()
        self._ack_threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        # per-doc execution locks for plain (non-durable) documents; a
        # durable document supplies its own (shared with its background
        # compactor). Only ``merge`` ever takes two at once — always in
        # sorted handle order, so the acquisition order is global.
        self._plain_locks: Dict[int, threading.RLock] = {}
        self._plain_locks_guard = threading.Lock()
        self.pool = ShardPool(
            self._execute_batch,
            workers=workers or _env_int("AUTOMERGE_TPU_SERVE_WORKERS", 8),
            max_queue=max_queue
            or _env_int("AUTOMERGE_TPU_SERVE_QUEUE_DEPTH", 128),
            max_batch=max_batch or _env_int("AUTOMERGE_TPU_SERVE_BATCH", 16),
            name="rpc-worker",
        )
        # cross-document device-merge batcher: workers draining DIFFERENT
        # documents in the same drain cycle share ONE kernel launch for
        # their coalesced device feeds (AUTOMERGE_TPU_SERVE_BATCHED=
        # 1|0|auto; auto batches only on accelerator backends — on CPU the
        # per-doc host delta resolution is the fast path). The early-wake
        # threshold is capped at the POOL SIZE: at most `workers` docs can
        # ever be draining at once, so a full complement of submitters
        # wakes the flush leader immediately instead of every drain
        # sleeping out the whole batch window. Generations at least
        # AUTOMERGE_TPU_PIPELINE_MIN_DOCS wide flush as two overlapped
        # half-launches (the drain pipeline; see batched.CrossDocBatcher)
        # — submitters still block until their half is collected
        n_workers = len(self.pool.workers)
        self.batcher = CrossDocBatcher(
            max_docs=min(
                _env_int("AUTOMERGE_TPU_BATCH_DOCS", 32), n_workers
            )
        )
        # overload resilience: one per-node admission controller scores
        # load from the pool's dequeue waits + utilization, the store's
        # hydration/RSS pressure, sheds by priority class past the soft
        # limits, and runs the brownout state machine (which widens the
        # batcher window under sustained pressure). The rpc backref lets
        # clusterStatus advertise shed-mode on the heartbeat.
        self.admission = AdmissionController(
            pool=self.pool, store=self.rpc.store, batcher=self.batcher
        )
        self.pool.wait_observer = self.admission.note_wait
        self.rpc.admission = self.admission

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int] | str:
        """Bound address — (host, port) for TCP (resolves port 0), the
        path for unix sockets."""
        if self._unix_path is not None:
            return self._unix_path
        assert self._listener is not None, "server not started"
        return self._listener.getsockname()[:2]

    def start(self) -> None:
        if self._unix_path is not None:
            # a stale socket file from a dead server blocks bind; remove
            # only if nothing is listening on it
            if os.path.exists(self._unix_path):
                probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    probe.connect(self._unix_path)
                except OSError:
                    os.unlink(self._unix_path)
                else:
                    probe.close()
                    raise OSError(
                        f"socket {self._unix_path} already has a listener"
                    )
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(self._unix_path)
        else:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind((self._host, self._port))
        ls.listen(128)
        self._listener = ls
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True
        )
        self._accept_thread.start()
        # background integrity scrub (integrity.py): durable deployments
        # only — a handle-only server has no on-disk state to verify.
        # start() no-ops when AUTOMERGE_TPU_SCRUB=0 (the bench baseline)
        if self.rpc.durable_dir and self.rpc.scrubber is None:
            from ..integrity import Scrubber

            self.rpc.scrubber = Scrubber(self.rpc)
            self.rpc.scrubber.start()
        # history rings (obs/history.py): fixed-memory downsampled recent
        # past of the allowlisted gauges/counters, served by the
        # historyStatus RPC and dumped with flight recordings. start() is
        # idempotent and a no-op under AUTOMERGE_TPU_HISTORY=0
        from ..obs import history

        if history.enabled():
            history.start()

    def serve_forever(self) -> None:
        """start() + block until a ``shutdown`` request (or ``stop()``)."""
        if self._listener is None:
            self.start()
        try:
            self._shutdown.wait()
        finally:
            self.stop()
            # a shutdown REQUEST acks after the flush; the process must
            # not exit from under that in-flight response. A SECOND
            # concurrent shutdown's thread may still be registered but
            # unstarted at this instant — joining that raises, and its
            # conn dies with the process anyway
            for t in self._ack_threads:
                with contextlib.suppress(RuntimeError):
                    t.join(timeout=10)

    def stop(self) -> None:
        """Stop accepting, drain the pool, flush durable docs, close.
        Idempotent: the shutdown request, serve_forever's exit and an
        explicit call may all race here; one of them does the work and
        the rest wait for it."""
        self._shutdown.set()
        with self._stop_lock:
            if self._stopped.is_set():
                return
            self._stop_inner()
            self._stopped.set()

    def wait_stopped(self, timeout: Optional[float] = None) -> bool:
        """Block until a triggered shutdown has fully flushed and closed."""
        return self._stopped.wait(timeout)

    def _stop_inner(self) -> None:
        if self.rpc.scrubber is not None:
            self.rpc.scrubber.stop()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        self.pool.stop(drain=True)
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
        obs.gauge_set("serve.connections", 0)
        self.rpc.close_durables()
        if self._unix_path is not None and os.path.exists(self._unix_path):
            with contextlib.suppress(OSError):
                os.unlink(self._unix_path)

    # -- accept / read -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutdown
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass  # unix sockets have no Nagle to disable
            conn = _Conn(sock, str(addr))
            with self._conns_lock:
                cid = self._next_conn
                self._next_conn += 1
                self._conns[cid] = conn
                n = len(self._conns)
            obs.count("serve.accepted")
            obs.gauge_set("serve.connections", n)
            threading.Thread(
                target=self._conn_loop, args=(cid, conn),
                name=f"rpc-conn-{cid}", daemon=True,
            ).start()

    def _conn_loop(self, cid: int, conn: _Conn) -> None:
        rpc = self.rpc
        handoff = False  # True when the shutdown thread owns the socket
        f = conn.sock.makefile("rb")
        try:
            while not self._shutdown.is_set():
                # the stdio framing discipline, byte-exact: bounded
                # readline, then drain (and discard) an overlong line's
                # tail in limit-sized chunks up to its newline
                limit = rpc.max_request_bytes + 1
                try:
                    raw = f.readline(limit)
                    if len(raw) >= limit and not raw.endswith(b"\n"):
                        while True:
                            tail = f.readline(limit)
                            if not tail or tail.endswith(b"\n"):
                                break
                except Exception as e:
                    if conn.alive and not self._shutdown.is_set():
                        obs.count("rpc.errors", labels={
                            "method": "transport", "type": "transport"})
                        obs.event("rpc.transport_death", stage="read",
                                  peer=conn.peer, error=str(e))
                    return
                if not raw:
                    return  # EOF: client done
                line = raw.decode("utf-8", errors="replace")
                req, early = rpc._parse_line(line)
                if early is not None:
                    conn.send(rpc._encode_response(early) + "\n")
                    continue
                if req is None:
                    continue  # blank line
                if req.get("method") == "shutdown":
                    # drain in-flight work and flush durable docs BEFORE
                    # answering: when the response lands, the journals'
                    # flocks are released and the server is reusable.
                    # Claim the socket, register AND START the ack thread
                    # BEFORE raising the shutdown flag — the moment it is
                    # set, a racing stop() sweeps _conns closed and
                    # serve_forever starts joining _ack_threads (joining
                    # a registered-but-unstarted thread raises). The
                    # thread's own stop() call sets the flag anyway; the
                    # explicit set below just makes wake-up prompt.
                    with self._conns_lock:
                        self._conns.pop(cid, None)
                    handoff = True
                    t = threading.Thread(
                        target=self._stop_then_ack,
                        args=(conn, req.get("id")),
                        name="rpc-shutdown", daemon=True,
                    )
                    self._ack_threads.append(t)
                    t.start()
                    self._shutdown.set()
                    return
                self._route(conn, req)
        finally:
            if not handoff:
                with contextlib.suppress(Exception):
                    f.close()
                conn.close()
                with self._conns_lock:
                    self._conns.pop(cid, None)
                    n = len(self._conns)
                obs.gauge_set("serve.connections", n)

    def _stop_then_ack(self, conn: _Conn, rid) -> None:
        """Full stop (drain + durable flush + flock release), then answer
        the shutdown request — the ack means the server is truly down.
        The caller already removed ``conn`` from the sweep set."""
        self.stop()
        conn.send(self.rpc._encode_response(
            {"id": rid, "result": None}) + "\n")
        conn.close()

    # -- routing -------------------------------------------------------------

    def _affinity(self, req: dict):
        """The shard key for a request, or None to execute inline."""
        params = req.get("params") or {}
        method = req.get("method")
        if method in ("openDurable", "durableReopen"):
            # no handle yet (or the handle is being replaced); one queue
            # serializes the name-cache check against concurrent opens
            # and reopens of the same name
            return _OPEN_DURABLE_KEY
        d = params.get("doc")
        if isinstance(d, int):
            return d
        s = params.get("session")
        if s is not None:
            sd = self.rpc._session_docs.get(s)
            if sd is not None:
                return sd
        return None

    def _bounded_method(self, req: dict) -> str:
        """The request's method if it is in the allowlist, else
        "unknown" — keeps error-counter labels bounded."""
        m = req.get("method")
        return m if isinstance(m, str) and m in self.rpc.METHODS else "unknown"

    def _route(self, conn: _Conn, req: dict) -> None:
        # admission-stage deadline gate: a request that arrived already
        # expired (or aged out in the accept path) is refused before it
        # consumes a queue slot
        if self.rpc.deadlines_enabled and request_expired(req):
            conn.send(self.rpc._encode_response(deadline_response(
                req.get("id"), self._bounded_method(req), "admission")) + "\n")
            return
        # admission control: shed the lowest-priority classes first once
        # the load score crosses their thresholds
        try:
            self.admission.admit(req.get("method") or "")
        except Overloaded as e:
            err = {"type": "Overloaded", "message": str(e),
                   "retriable": True}
            if e.retry_after_ms is not None:
                err["retryAfterMs"] = int(e.retry_after_ms)
            conn.send(self.rpc._encode_response(
                {"id": req.get("id"), "error": err}) + "\n")
            return
        key = self._affinity(req)
        if key is None:
            # affinity-free: handle tables only, safe on this thread
            conn.send(self.rpc._encode_response(self.rpc.handle(req)) + "\n")
            return
        try:
            self.pool.submit(key, (conn, req))
        except QueueFull as e:
            # retriable by contract: backpressure is a transient level,
            # and the reference client retry loop (clients/python) backs
            # off on exactly this flag
            conn.send(self.rpc._encode_response({
                "id": req.get("id"),
                "error": {"type": "Backpressure", "message": str(e),
                          "retriable": True},
            }) + "\n")

    # -- execution (worker threads) ------------------------------------------

    def _doc_locks(self, req: dict) -> List[threading.RLock]:
        """Execution locks for every doc the request touches, in sorted
        handle order (the global acquisition order — no deadlocks)."""
        params = req.get("params") or {}
        handles = set()
        d = params.get("doc")
        if isinstance(d, int):
            handles.add(d)
        if req.get("method") == "merge" and isinstance(params.get("other"), int):
            handles.add(params["other"])
        # session-only requests (poll/receive/stats) mutate their doc's
        # core directly — they need the SAME doc lock or a background
        # compaction snapshot could race the sync apply
        s = params.get("session")
        if s is not None:
            sd = self.rpc._session_docs.get(s)
            if sd is not None:
                handles.add(sd)
        locks = []
        for h in sorted(handles):
            doc = self.rpc._docs.get(h)
            lock = getattr(doc, "lock", None)  # durable docs carry their own
            if lock is None:
                with self._plain_locks_guard:
                    lock = self._plain_locks.setdefault(h, threading.RLock())
            locks.append(lock)
        return locks

    def _execute_batch(self, key, items) -> None:
        """Drain one document's batch: every request under the doc's
        lock(s), the whole batch under ONE durable ack scope, responses
        written only after the covering fsync. The whole drain is one
        profiler cycle (``drain.cycle_seconds`` / ``drain.docs``), so
        cycle reports anchor to real serve drains, not just bench
        drains."""
        t_cycle = time.perf_counter()
        doc_name = (
            self.rpc._handle_names.get(key) or f"doc{key}"
            if isinstance(key, int)
            else str(key)
        )
        with prof.cycle(kind="serve", doc=doc_name):
            self._execute_batch_inner(key, items)
        obs.observe("drain.cycle_seconds", time.perf_counter() - t_cycle)
        docs = {key} if isinstance(key, int) else set()
        for _conn, req in items:
            d = (req.get("params") or {}).get("doc")
            if isinstance(d, int):
                docs.add(d)
        obs.observe("drain.docs", max(len(docs), 1))

    def _execute_batch_inner(self, key, items) -> None:
        rpc = self.rpc
        out: List[Tuple[_Conn, dict]] = []
        if rpc.deadlines_enabled:
            # dequeue-stage deadline gate: requests whose budget burned
            # away in the shard queue are answered without hydrating,
            # locking, or opening an ack scope for them
            live = []
            for conn, req in items:
                if request_expired(req):
                    out.append((conn, deadline_response(
                        req.get("id"), self._bounded_method(req), "dequeue")))
                else:
                    live.append((conn, req))
            items = live
        doc = (
            rpc._docs.get(key) if isinstance(key, int) and items else None
        )
        if doc is not None and getattr(doc, "_closed", False):
            # cold-demoted document: hydrate once, here, inside this
            # doc's ordered drain — the whole batch then runs against
            # the live instance under ONE ack scope. Failures (e.g. the
            # store's retriable hydration backpressure) fall through to
            # per-request handling, which answers each with the error.
            if all(
                req.get("method") in _NO_HYDRATE_METHODS
                for _c, req in items
            ):
                doc = None  # the cold doc stays cold; no ack scope needed
            else:
                try:
                    doc = rpc._ensure_resident(key)
                except Exception:
                    doc = None
        scope = getattr(doc, "ack_scope", None)
        try:
            with scope() if scope is not None else contextlib.nullcontext():
                i = 0
                while i < len(items):
                    conn, req = items[i]
                    j = self._coalesce_end(items, i)
                    # with the cross-doc batcher active, even a LENGTH-1
                    # receive run takes the coalesced path: its device
                    # feed then joins whatever other documents are
                    # draining right now in one shared kernel launch
                    # (a drain of 100 docs x 1 frame each is the case
                    # the batcher exists for)
                    if j > i or (
                        self._coalesce_key(req) is not None
                        and self._coalesce_single(req.get("method"))
                    ):
                        self._run_coalesced(items[i : j + 1], out)
                    else:
                        with contextlib.ExitStack() as st:
                            for lk in self._doc_locks(req):
                                st.enter_context(lk)
                            out.append((conn, rpc.handle(req)))
                        if req.get("method") == "free":
                            with self._plain_locks_guard:
                                self._plain_locks.pop(
                                    (req.get("params") or {}).get("doc"), None
                                )
                    i = j + 1
        except Exception as e:  # the deferred group fsync (scope exit) failed
            # an un-fsynced ack is no ack: every result in the batch is
            # converted to an error — the journal poisons itself until a
            # compaction repairs, so nothing later silently builds on this
            obs.count("rpc.errors", labels={"method": "group_commit",
                                            "type": type(e).__name__})
            err = {"type": type(e).__name__,
                   "message": f"group commit failed: {e}"}
            # a poisoned journal / replication-gate timeout is a transient
            # serving condition (failover, reopen, or heal restores it) —
            # tell the client retry loop so. A raw OSError here is the
            # injected-disk-fault first strike: the batch was NOT acked,
            # so a retry is the correct client move there too.
            retriable = getattr(e, "retriable", None)
            if retriable is None and isinstance(e, OSError):
                retriable = True
            err["retriable"] = bool(retriable) if retriable is not None else False
            out = [
                (c, r if "error" in r else {
                    "id": r.get("id"), "error": dict(err)})
                for c, r in out
            ]
        # one write per connection per batch: a drained flight's responses
        # coalesce into a single sendall (16 responses != 16 syscalls)
        with obs.span("serve.write", responses=len(out)):
            grouped: Dict[int, Tuple[_Conn, List[str]]] = {}
            for conn, resp in out:
                grouped.setdefault(id(conn), (conn, []))[1].append(
                    rpc._encode_response(resp)
                )
            for conn, payloads in grouped.values():
                conn.send("\n".join(payloads) + "\n")

    def _coalesce_key(self, req) -> Optional[tuple]:
        """Coalescing key for a request, or None when the method never
        coalesces. ``receiveSyncMessage`` runs on the document (frames
        from DIFFERENT peers still share one device feed);
        ``syncSessionReceive`` runs on the session (the run drains
        through that session's ``receive_many``). The cluster node
        extends this with the follower's ``replApply`` stream."""
        method = req.get("method")
        if method not in _COALESCE_METHODS:
            return None
        params = req.get("params") or {}
        return (
            method,
            params.get("session") if method == "syncSessionReceive"
            else params.get("doc"),
        )

    def _coalesce_single(self, method) -> bool:
        """Whether a LENGTH-1 run of ``method`` still routes through the
        coalesced path (so its device feed joins the cross-doc
        batcher)."""
        return self.batcher.active()

    def _coalesce_end(self, items, i) -> int:
        """Last index of the run starting at ``i`` of coalescable
        frames (length-1 runs return ``i``)."""
        key = self._coalesce_key(items[i][1])
        if key is None:
            return i
        j = i
        while j + 1 < len(items) and self._coalesce_key(items[j + 1][1]) == key:
            j += 1
        return j

    def _run_coalesced(self, run, out) -> None:
        """A run of receive frames for one doc/session: the host applies
        stay per-message (protocol state machines need each), but the
        resident-device feed drains into one ``apply_batches`` call."""
        method = run[0][1].get("method")
        if len(run) > 1:  # length-1 runs only ride the cross-doc batcher
            obs.count("rpc.coalesced", n=len(run), labels={"method": method})
        with contextlib.ExitStack() as st:
            for lk in self._doc_locks(run[0][1]):
                st.enter_context(lk)
            if method == "syncSessionReceive":
                self._run_session_receive(run, out)
            else:
                self._run_receive_sync(run, out)

    def _run_session_receive(self, run, out) -> None:
        rpc = self.rpc
        import base64

        frames, live = [], []
        for conn, req in run:
            p = req.get("params") or {}
            # the coalesced path bypasses rpc.handle: enforce the final
            # deadline stage per frame here
            if rpc.deadlines_enabled and request_expired(req):
                out.append((conn, deadline_response(
                    req.get("id"), "syncSessionReceive", "pre_fsync")))
                continue
            try:
                sess = rpc._session(p)
                frames.append(base64.b64decode(p["data"]))
                live.append((conn, req, sess))
            except Exception as e:
                obs.count("rpc.errors", labels={
                    "method": "syncSessionReceive", "type": type(e).__name__})
                out.append((conn, {"id": req.get("id"), "error": {
                    "type": type(e).__name__, "message": str(e),
                    "retriable": bool(getattr(e, "retriable", False))}}))
        if not live:
            return
        sess = live[0][2]
        dev = sess.device_doc
        feed = (
            (lambda batches: self._feed_device(dev, batches))
            if dev is not None
            else None
        )
        with obs.span("rpc.request", links=_run_trace_links(run),
                      labels={"method": "syncSessionReceive"}):
            accepted = sess.receive_many(
                frames, time.monotonic(), device_feed=feed
            )
        for (conn, req, _), ok in zip(live, accepted):
            out.append((conn, {"id": req.get("id"),
                               "result": {"accepted": ok}}))

    def _run_receive_sync(self, run, out) -> None:
        rpc = self.rpc
        import base64

        from ..sync.protocol import Message

        doc = None
        changes_batches = []
        with obs.span("rpc.request", links=_run_trace_links(run),
                      labels={"method": "receiveSyncMessage"}):
            for conn, req in run:
                p = req.get("params") or {}
                if rpc.deadlines_enabled and request_expired(req):
                    out.append((conn, deadline_response(
                        req.get("id"), "receiveSyncMessage", "pre_fsync")))
                    continue
                try:
                    doc = rpc._doc(p)
                    msg = Message.decode(base64.b64decode(p["data"]))
                    doc.receive_sync_message(rpc._syncs[p["sync"]], msg)
                    if msg.changes:
                        changes_batches.append(list(msg.changes))
                    out.append((conn, {"id": req.get("id"), "result": None}))
                except Exception as e:
                    obs.count("rpc.errors", labels={
                        "method": "receiveSyncMessage",
                        "type": type(e).__name__})
                    out.append((conn, {"id": req.get("id"), "error": {
                        "type": type(e).__name__, "message": str(e),
                        "retriable": bool(getattr(e, "retriable", False))}}))
        dev = getattr(doc, "device_doc", None)
        if dev is not None and changes_batches:
            try:
                self._feed_device(dev, changes_batches)
            except Exception as e:  # noqa: BLE001 — isolate the sidecar
                obs.count("sync.device_feed_error", error=str(e)[:200])

    def _feed_device(self, dev, batches) -> None:
        """Route a drained document's device feed through the cross-doc
        batcher (one shared kernel launch with whatever other documents
        are draining right now) or, when batching is off for this
        backend, through the per-doc pipelined path."""
        if self.batcher.active():
            self.batcher.apply(dev, batches)
        else:
            dev.apply_batches(batches)
