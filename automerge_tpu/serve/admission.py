"""Admission control and brownout for the serving tier.

The chaos fabric proves the cluster survives *faults*; this module is
what protects it from *load*. One per-node ``AdmissionController``
combines the serving layer's pressure signals into a scalar load score
and acts on it three ways:

* **Shedding.** Past a soft limit the node starts refusing the least
  important work first with ``Overloaded {retriable: true,
  retryAfterMs}`` — the HTAP insight (Real-Time LSM-Trees,
  arXiv:2101.06801) applied to the write path: protect the
  latency-critical class by explicitly degrading the rest. Priority
  classes, most- to least-protected::

      replication/ack > interactive mutation > sync generate > read
                      > background compact/rebalance

  Replication and control-plane traffic is NEVER shed (shedding acks
  under load turns an overload into an availability incident). Within a
  class, shedding is *proportional*: the refused fraction ramps 0 -> 1
  across one threshold width, so the admitted rate tracks capacity at a
  stable queue depth instead of bang-banging between flood and silence.

* **Advertisement.** ``advertisement()`` rides the ``clusterStatus``
  heartbeat so the router stops routing sheddable work at a node that
  would only refuse it.

* **Brownout.** Sustained pressure past an enter threshold (with
  enter/exit hysteresis so the state cannot flap) flips the process-wide
  ``degrade.BROWNOUT`` flag: reads and ``generateSyncMessage`` skip
  journal/recency touches, background compaction and cold-demotion churn
  defer, and the CrossDocBatcher window widens so drains amortize
  better. Entry dumps the flight recorder — the moment of degradation is
  exactly the moment to capture.

The load score is the MAX of normalized signals (each ~1.0 at its own
saturation point), sampled with a small cache interval so per-request
``admit()`` stays cheap:

* expected dequeue wait right now (deepest shard queue times the pool's
  recent per-item service time) and the recent observed dequeue wait
  (EWMA with time decay — the all-time ``serve.queue_wait`` histogram
  cannot decay after a burst), whichever is larger, over the target
  wait; an empty backlog overrides the EWMA entirely;
* shard-pool worker utilization (0..1);
* DocStore hydration-semaphore pressure (0..1);
* RSS over the configured store budget.

Utilization/hydration/RSS alone saturate at ~1.0, below the mutation
shed threshold: only sustained queue waits — the signal that latency
SLOs are actually burning — can escalate shedding to interactive
mutations.

Everything is wall-clock injectable (``now=``) so hysteresis is unit
testable without sleeps. ``AUTOMERGE_TPU_ADMISSION=0`` disables
shedding, deadline enforcement and brownout in one knob — the
uncontrolled baseline the overload bench compares against.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Any, Dict, Optional

from .. import obs
from ..degrade import BROWNOUT, brownout_active

__all__ = [
    "Overloaded",
    "AdmissionController",
    "priority_class",
    "admission_enabled",
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def admission_enabled() -> bool:
    """The one overload-resilience master switch (default on)."""
    return os.environ.get("AUTOMERGE_TPU_ADMISSION", "1") != "0"


class Overloaded(Exception):
    """The node refused this request to protect higher-priority work.

    Always retriable; carries the server's backoff hint so the client
    retry loop can pace itself instead of hammering a shedding node."""

    retriable = True

    def __init__(self, message: str, *, retry_after_ms: Optional[int] = None,
                 shed_class: Optional[str] = None):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms
        self.shed_class = shed_class


# -- priority classes ---------------------------------------------------------

# rank 0 is never shed; higher ranks shed earlier. Methods absent from
# every set below default to rank 1 (interactive mutation): a new method
# is protected until explicitly classified, never silently sheddable.
CLASS_NAMES = {0: "replication", 1: "mutation", 2: "sync", 3: "read",
               4: "background"}
NO_SHED_RANK = 5  # advertisement value for "nothing is being shed"

_REPLICATION = frozenset({
    # replication / ack path and the cluster control plane: shedding
    # these converts load into unavailability or split-brain
    "replApply", "replSnapshot", "replPing", "replHarvest",
    "clusterStatus", "clusterPromote", "clusterReplicateTo",
    "migrateOut", "migrateTail", "migrateIn", "migrateRelease",
    "metrics", "configure", "perfStatus", "profileStart", "profileStop",
    "chaosDisk", "shutdown",
})
_SYNC = frozenset({
    "generateSyncMessage", "syncSessionPoll", "syncSessionEncode",
    "syncSessionStats", "syncStateEncode",
})
_READ = frozenset({
    "get", "getAll", "keys", "length", "text", "marks",
    "getCursor", "getCursorPosition", "materialize", "heads",
    "save", "saveIncremental", "storeStatus", "durableInfo",
})
_BACKGROUND = frozenset({"durableCompact", "storeDemote", "docFence"})


def priority_class(method: str) -> tuple:
    """``(rank, class name)`` for one method (see module docstring)."""
    if method in _REPLICATION:
        return 0, CLASS_NAMES[0]
    if method in _SYNC:
        return 2, CLASS_NAMES[2]
    if method in _READ:
        return 3, CLASS_NAMES[3]
    if method in _BACKGROUND:
        return 4, CLASS_NAMES[4]
    return 1, CLASS_NAMES[1]


# -- the controller -----------------------------------------------------------


class AdmissionController:
    """Per-node load scoring, priority shedding and brownout hysteresis.

    ``pool`` / ``store`` / ``batcher`` are duck-typed and all optional
    (tests drive the controller with ``note_wait`` alone): the pool
    supplies ``utilization()``, the store its hydration semaphore and
    RSS budget, the batcher a mutable ``window`` the brownout widens.
    """

    def __init__(self, *, pool=None, store=None, batcher=None,
                 enabled: Optional[bool] = None):
        self.pool = pool
        self.store = store
        self.batcher = batcher
        self.enabled = admission_enabled() if enabled is None else bool(enabled)
        # score thresholds: background sheds at soft, interactive
        # mutations only at hard, read/sync on the line between
        self.soft = _env_float("AUTOMERGE_TPU_ADMISSION_SOFT", 0.75)
        self.hard = _env_float("AUTOMERGE_TPU_ADMISSION_HARD", 2.0)
        self.target_wait_s = _env_float(
            "AUTOMERGE_TPU_ADMISSION_TARGET_WAIT_S", 0.2)
        # brownout hysteresis: enter above, exit below, each sustained
        self.brownout_enter = _env_float(
            "AUTOMERGE_TPU_BROWNOUT_ENTER", 1.25)
        self.brownout_exit = _env_float("AUTOMERGE_TPU_BROWNOUT_EXIT", 0.6)
        self.enter_hold_s = _env_float(
            "AUTOMERGE_TPU_BROWNOUT_ENTER_HOLD_S", 1.0)
        self.exit_hold_s = _env_float(
            "AUTOMERGE_TPU_BROWNOUT_EXIT_HOLD_S", 2.0)
        self.window_widen = _env_float(
            "AUTOMERGE_TPU_BROWNOUT_BATCH_WIDEN", 4.0)
        # recent-wait estimate: EWMA over drain waits, halved every
        # decay_half_s of silence so the score can actually come down
        self.decay_half_s = _env_float(
            "AUTOMERGE_TPU_ADMISSION_DECAY_HALF_S", 2.0)
        self.sample_s = _env_float("AUTOMERGE_TPU_ADMISSION_SAMPLE_S", 0.05)
        self._lock = threading.Lock()
        self._wait_ewma = 0.0
        self._wait_ts = 0.0
        self._score = 0.0
        self._score_ts = -1.0
        self._enter_since: Optional[float] = None
        self._exit_since: Optional[float] = None
        self._batcher_base_window: Optional[float] = None
        # seeded: shed decisions inside the proportional band are
        # reproducible across runs like everything else in the harness
        self._rng = random.Random(0xAD417)
        self.transitions = {"on": 0, "off": 0}
        # export the resting state so the gauges exist before first load
        obs.gauge_set("cluster.brownout", 0.0, labels={"state": "on"})
        obs.gauge_set("cluster.brownout", 1.0, labels={"state": "off"})

    # -- signals -------------------------------------------------------------

    def note_wait(self, waited: float, now: Optional[float] = None) -> None:
        """Feed one drain's dequeue wait (installed as the ShardPool's
        ``wait_observer``)."""
        now = obs.now() if now is None else now
        with self._lock:
            self._wait_ewma = self._decayed_wait_locked(now)
            self._wait_ewma += 0.2 * (waited - self._wait_ewma)
            self._wait_ts = now

    def _decayed_wait_locked(self, now: float) -> float:
        if self._wait_ewma <= 0.0:
            return 0.0
        dt = max(now - self._wait_ts, 0.0)
        return self._wait_ewma * 0.5 ** (dt / max(self.decay_half_s, 1e-6))

    def _hydration_pressure(self) -> float:
        store = self.store
        sem = getattr(store, "_hydrations", None)
        budgets = getattr(store, "budgets", None)
        max_h = getattr(budgets, "max_hydrations", 0) or 0
        if sem is None or max_h <= 0:
            return 0.0
        free = getattr(sem, "_value", max_h)
        return max(0.0, min(1.0, (max_h - free) / max_h))

    def _rss_pressure(self) -> float:
        budgets = getattr(self.store, "budgets", None)
        max_rss = getattr(budgets, "max_rss_bytes", 0) or 0
        if max_rss <= 0:
            return 0.0
        try:
            from ..store.docstore import current_rss_bytes

            return current_rss_bytes() / max_rss
        except Exception:
            return 0.0

    def load_score(self, now: Optional[float] = None) -> float:
        """The scalar load score (cached for ``sample_s``); recomputing
        also steps the brownout state machine."""
        now = obs.now() if now is None else now
        with self._lock:
            if 0 <= now - self._score_ts < self.sample_s:
                return self._score
            ewma_wait = self._decayed_wait_locked(now)
        util_term = 0.0
        expected_wait = 0.0
        backlog = None
        if self.pool is not None:
            try:
                util_term = float(self.pool.utilization())
                backlog = int(self.pool.backlog())
                expected_wait = float(self.pool.expected_wait())
            except Exception:
                util_term = 0.0
        # present beats history, both ways: the pool's expected wait
        # (deepest queue x recent service time) sees a flood the moment
        # it lands, and an EMPTY backlog refutes the decayed EWMA — a
        # score pinned on history after the flood drained would idle
        # the node through the decay half-life
        if backlog == 0:
            ewma_wait = 0.0
        wait_term = (
            max(ewma_wait, expected_wait) / max(self.target_wait_s, 1e-6)
        )
        score = max(wait_term, util_term, self._hydration_pressure(),
                    self._rss_pressure())
        with self._lock:
            self._score = score
            self._score_ts = now
        obs.gauge_set("serve.load_score", score)
        if self.enabled:
            self._update_brownout(score, now)
        return score

    # -- shedding ------------------------------------------------------------

    def _shed_threshold(self, rank: int) -> float:
        if rank <= 0:
            return float("inf")
        if rank == 1:
            return self.hard
        step = (self.hard - self.soft) / 3.0
        # rank 4 (background) sheds first, at soft; rank 2 (sync) last
        return self.soft + (4 - rank) * step

    def retry_after_ms(self, score: Optional[float] = None,
                       now: Optional[float] = None) -> int:
        """The backoff hint: roughly two current queue-wait estimates,
        scaled up with the score, clamped to a sane band."""
        now = obs.now() if now is None else now
        if score is None:
            score = self.load_score(now)
        with self._lock:
            wait = self._decayed_wait_locked(now)
        hint = max(wait * 2.0, self.target_wait_s) * max(score, 1.0) * 1000.0
        return int(max(50, min(hint, 5000)))

    def shed_rank(self, score: Optional[float] = None,
                  now: Optional[float] = None) -> int:
        """Lowest rank currently being FULLY shed (score past the top of
        its proportional band — every request of rank >= this is being
        refused, so the router should stop shipping them here);
        ``NO_SHED_RANK`` (5) when no class is fully shed."""
        if score is None:
            score = self.load_score(now)
        for rank in (1, 2, 3, 4):
            if score >= 2.0 * self._shed_threshold(rank):
                return rank
        return NO_SHED_RANK

    def shed_fraction(self, rank: int, score: float) -> float:
        """Fraction of ``rank`` work being refused at ``score``: 0 below
        the class threshold, ramping linearly to 1 across one threshold
        width ([thresh, 2*thresh]). Proportional shedding gives the
        control loop a stable operating point — a hard cutoff bang-bangs
        between "admit everything" (waits spike) and "shed everything"
        (the queue drains, the wait signal goes silent, and the node
        idles until the EWMA decays), which burns most of the node's
        capacity on the idle half of the oscillation."""
        thresh = self._shed_threshold(rank)
        if thresh == float("inf") or score < thresh:
            return 0.0
        return min(1.0, (score - thresh) / max(thresh, 1e-9))

    def admit(self, method: str, now: Optional[float] = None) -> None:
        """Gate one request at admission: raises ``Overloaded`` when the
        method's priority class is being shed (probabilistically inside
        the proportional band). No-op when disabled."""
        if not self.enabled:
            return
        rank, cls = priority_class(method)
        if rank == 0:
            return
        score = self.load_score(now)
        frac = self.shed_fraction(rank, score)
        if frac <= 0.0:
            return
        if frac < 1.0 and self._rng.random() >= frac:
            return
        ra = self.retry_after_ms(score, now)
        obs.count("serve.shed", labels={"class": cls})
        raise Overloaded(
            f"shedding {cls} work at load {score:.2f} "
            f"(retry after {ra}ms)",
            retry_after_ms=ra, shed_class=cls,
        )

    # -- advertisement (rides clusterStatus) ---------------------------------

    def advertisement(self, now: Optional[float] = None) -> Dict[str, Any]:
        score = self.load_score(now)
        shed = self.shed_rank(score) if self.enabled else NO_SHED_RANK
        out: Dict[str, Any] = {
            "score": round(score, 3),
            "shedClass": shed,
            "brownout": brownout_active(),
        }
        if shed < NO_SHED_RANK:
            out["retryAfterMs"] = self.retry_after_ms(score, now)
        return out

    # -- brownout state machine ----------------------------------------------

    def _update_brownout(self, score: float, now: float) -> None:
        with self._lock:
            if not BROWNOUT.is_set():
                self._exit_since = None
                if score >= self.brownout_enter:
                    if self._enter_since is None:
                        self._enter_since = now
                    elif now - self._enter_since >= self.enter_hold_s:
                        self._enter_brownout_locked(score)
                else:
                    self._enter_since = None
            else:
                self._enter_since = None
                if score <= self.brownout_exit:
                    if self._exit_since is None:
                        self._exit_since = now
                    elif now - self._exit_since >= self.exit_hold_s:
                        self._exit_brownout_locked(score)
                else:
                    self._exit_since = None

    def _enter_brownout_locked(self, score: float) -> None:
        BROWNOUT.set()
        self._enter_since = None
        self.transitions["on"] += 1
        if self.batcher is not None and self._batcher_base_window is None:
            try:
                self._batcher_base_window = float(self.batcher.window)
                self.batcher.window = (
                    self._batcher_base_window * self.window_widen)
            except Exception:
                self._batcher_base_window = None
        obs.gauge_set("cluster.brownout", 1.0, labels={"state": "on"})
        obs.gauge_set("cluster.brownout", 0.0, labels={"state": "off"})
        obs.count("cluster.brownout_transitions", labels={"to": "on"})
        obs.event("brownout.enter", score=round(score, 3),
                  transitions=self.transitions["on"])
        # capture the moment of degradation while the evidence is hot
        try:
            obs.flight.dump(reason="brownout")
        except Exception:
            pass

    def _exit_brownout_locked(self, score: float) -> None:
        BROWNOUT.clear()
        self._exit_since = None
        self.transitions["off"] += 1
        if self.batcher is not None and self._batcher_base_window is not None:
            try:
                self.batcher.window = self._batcher_base_window
            except Exception:
                pass
            self._batcher_base_window = None
        obs.gauge_set("cluster.brownout", 0.0, labels={"state": "on"})
        obs.gauge_set("cluster.brownout", 1.0, labels={"state": "off"})
        obs.count("cluster.brownout_transitions", labels={"to": "off"})
        obs.event("brownout.exit", score=round(score, 3),
                  transitions=self.transitions["off"])

    def reset(self) -> None:
        """Test hook: clear brownout and every accumulated signal."""
        with self._lock:
            if BROWNOUT.is_set():
                self._exit_brownout_locked(0.0)
            self._wait_ewma = 0.0
            self._wait_ts = 0.0
            self._score = 0.0
            self._score_ts = -1.0
            self._enter_since = None
            self._exit_since = None
