"""The batched causal-resolution merge kernel (the north star).

Replaces the reference's sequential per-op seek/insert loop
(reference: rust/automerge/src/automerge.rs:1258-1280, op_tree.rs:212-239)
with one jit-compiled pass over the whole op log:

  1. succ resolution     — pred references (pre-resolved to row indices by
                           the host columnizer) scatter-added into per-op
                           succ / increment counters (batched ``add_succ``,
                           op_set.rs:194-203).
  2. visibility          — op visible iff it has no non-increment successor
                           (counters) / no successor at all (everything
                           else); deletes, increments and marks are never
                           visible (types.rs:712-744).
  3. per-key winners     — lexsort by (obj, key, row) + segmented reductions
                           give the winning op and conflict count for every
                           map prop and list-element run (vectorized
                           ``TopOps``, iter/top_ops.rs:44-103). Rows are in
                           Lamport order, so "max row" is "max Lamport".
  4. RGA linearization   — insert ops form a forest (parent = reference
                           element, siblings ordered by descending Lamport
                           id, query/insert.rs); document order is its
                           preorder traversal, computed with pointer-doubling
                           successor threading + Wyllie list ranking: two
                           O(log n)-step gather loops instead of a pointer
                           walk.

Everything is int32 with static power-of-two shapes: no 64-bit emulation on
TPU, one compiled kernel per capacity bucket, and the hot work is sorts,
gathers and segmented reductions — shapes XLA maps well onto the VPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from .oplog import ELEM_HEAD, PAD_ACTION, TAG_COUNTER, _capacity, _next_pow2

_DELETE = 3
_INCREMENT = 5
_MARK = 7
_PUT = 1

# plain int (weakly-typed in jax): a module-level jnp scalar would compile
# a kernel on the default backend at IMPORT time (~0.6s over the tunnel)
NONE32 = -1


def _ceil_log2(n: int) -> int:
    return max(1, int(n - 1).bit_length())


def succ_resolution(c):
    """Phase 1: pred scatter -> per-op succ/inc counters (batched add_succ).

    The bandwidth-heavy phase; parallel/sharding.py shards the pred stream
    across a device mesh and psums these partial counters. One fused
    scatter-add carries all three accumulators.

    ``covered`` gates each pred edge by its source op's clock coverage: a
    successor outside the read clock does not overwrite (the vectorized
    ``Clock::covers`` test on the succ side of ``visible_at``,
    reference: types.rs:712-744, clock.rs:71-77).
    """
    P = c["action"].shape[0]
    action = c["action"]
    tgt = c["pred_tgt"]
    src = c["pred_src"]
    hit = (tgt >= 0) & c["covered"][src]
    src_is_inc = action[src] == _INCREMENT
    tgt_c = jnp.where(hit, tgt, 0)
    one = jnp.ones_like(tgt_c)
    payload = jnp.stack(
        [
            jnp.where(hit & ~src_is_inc, one, 0),
            jnp.where(hit & src_is_inc, one, 0),
            jnp.where(hit & src_is_inc, c["value_i32"][src], 0),
        ],
        axis=1,
    )
    acc = jnp.zeros((P, 3), jnp.int32).at[tgt_c].add(payload)
    return acc[:, 0], acc[:, 1], acc[:, 2]


def visibility(c, succ_count, inc_count):
    """Phase 2: the visibility rule (types.rs:712-744), shared by the
    single-device kernel and the sharded path (parallel/sharding.py).

    ``covered`` masks ops outside the read clock (all-true for current
    state)."""
    action = c["action"]
    valid = action != PAD_ACTION
    never = (action == _DELETE) | (action == _INCREMENT) | (action == _MARK)
    is_counter = (action == _PUT) & (c["value_tag"] == TAG_COUNTER)
    # counter puts survive increment successors (types.rs:712-720)
    return (
        valid
        & c["covered"]
        & ~never
        & jnp.where(is_counter, succ_count == 0, (succ_count + inc_count) == 0)
    )


def resolve_state(c, succ_count, inc_count, counter_inc, obj_cap=None):
    """Phases 2-4: visibility, per-key winners, RGA linearization.

    Returns a dict of device arrays (all int32/bool, per-row unless noted):
      visible      — op currently visible
      counter_inc  — summed increment payloads landing on this op
      winner       — row of the winning visible op of this row's key group
                     (-1 if none visible)
      conflicts    — number of visible ops in this row's key group
      elem_index   — document-order position of this insert op among its
                     object's elements (-1 for non-inserts)
      obj_vis_len  — per dense-object visible element count   [indexed by
      obj_text_width — per dense-object visible text width     obj_dense]
      succ_count / inc_count — successor bookkeeping (patches/debug)
    """
    P = c["action"].shape[0]
    rows = jnp.arange(P, dtype=jnp.int32)
    action = c["action"]
    valid = action != PAD_ACTION
    insert = c["insert"]
    elem_ref = c["elem_ref"]
    obj_dense = c["obj_dense"]

    # --- 2. visibility -----------------------------------------------------
    # RGA linearization below deliberately ignores ``covered`` so element
    # order — which depends only on the insert forest — is identical across
    # historical views of one log.
    visible = visibility(c, succ_count, inc_count)

    # --- 3. per-key winners ------------------------------------------------
    is_map = c["prop"] >= 0
    # an insert op heads its own element run; updates/deletes name the run
    # they target via their (row-resolved) elem reference
    run_key = jnp.where(insert, rows, elem_ref)
    g_obj = jnp.where(valid, obj_dense, jnp.int32(P))
    g_kind = is_map.astype(jnp.int32)
    g_key = jnp.where(is_map, c["prop"], run_key)
    # the three group keys pack into ONE int32 when the object table is
    # small (obj_cap is static on the packed-transport path): a single-key
    # sort moves half the data of the 3-key + payload variant
    key_bits = _ceil_log2(P + 5)
    if obj_cap is not None and ((2 * (obj_cap + 2)) << key_bits) < (1 << 31):
        # invalid rows take the sentinel obj_cap+1 (> every valid obj_dense)
        g_obj_p = jnp.where(valid, obj_dense, jnp.int32(min(P, obj_cap + 1)))
        packed = (
            ((g_obj_p * 2 + g_kind) << key_bits)
            | (g_key + 4)  # run_key sentinels reach -3; offset keeps it positive
        )
        packed_s, sort_idx = jax.lax.sort((packed, rows), num_keys=1, is_stable=True)
        newseg = jnp.concatenate(
            [jnp.array([True]), packed_s[1:] != packed_s[:-1]]
        )
    else:
        # one multi-key sort pass (lexsort would run one full sort per key)
        g_obj_s, g_kind_s, g_key_s, sort_idx = jax.lax.sort(
            (g_obj, g_kind, g_key, rows), num_keys=3, is_stable=True
        )
        newseg = jnp.concatenate(
            [
                jnp.array([True]),
                (g_obj_s[1:] != g_obj_s[:-1])
                | (g_kind_s[1:] != g_kind_s[:-1])
                | (g_key_s[1:] != g_key_s[:-1]),
            ]
        )
    seg = (jnp.cumsum(newseg) - 1).astype(jnp.int32)
    vis_s = visible[sort_idx]
    cand = jnp.where(vis_s, jnp.arange(P, dtype=jnp.int32), NONE32)
    win_pos = jax.ops.segment_max(cand, seg, num_segments=P)
    seg_vis = jax.ops.segment_sum(vis_s.astype(jnp.int32), seg, num_segments=P)
    win_row = jnp.where(win_pos >= 0, sort_idx[jnp.clip(win_pos, 0, P - 1)], NONE32)
    seg_of_row = jnp.zeros(P, jnp.int32).at[sort_idx].set(seg)
    winner = win_row[seg_of_row]
    conflicts = seg_vis[seg_of_row]

    # --- 4. RGA linearization ---------------------------------------------
    # the shared sibling-forest builder (node space: [0,P) element nodes,
    # [P,2P+2) object roots, sentinel terminates every chain)
    is_elem, parent_row, first_child, next_sib = forest(c)

    core = {
        "visible": visible,
        "counter_inc": counter_inc,
        "winner": winner,
        "conflicts": conflicts,
        "succ_count": succ_count,
        "inc_count": inc_count,
        "first_child": first_child,
        "next_sib": next_sib,
        "parent_row": parent_row,
        "is_elem": is_elem,
    }

    # --- per-object stats (order-independent) ------------------------------
    elem_vis = is_elem & (winner >= 0)
    obj_idx = jnp.where(valid, obj_dense, jnp.int32(P + 1))
    core["obj_vis_len"] = jax.ops.segment_sum(
        elem_vis.astype(jnp.int32), obj_idx, num_segments=P + 2
    )
    w_width = jnp.where(elem_vis, c["width"][jnp.clip(winner, 0, P - 1)], 0)
    core["obj_text_width"] = jax.ops.segment_sum(
        w_width, obj_idx, num_segments=P + 2
    )
    return core


def device_linearize(c, core):
    """Document-order element indices computed fully on device.

    Pointer-doubling + Wyllie ranking: O(log n) passes of gathers. On TPU
    the ranking pass gathers along the (near-random) document-order chain,
    which the hardware handles far worse than the host's sequential walk —
    so the default pipeline uses the native preorder walk
    (native am_preorder_index) and this path serves the pure-device /
    multi-chip dry-run flow.
    """
    P = c["action"].shape[0]
    rows = jnp.arange(P, dtype=jnp.int32)
    # the doubling loops run in *element* space [0, P) + sentinel P: element
    # nodes are the only chain participants, so arrays (and the random
    # gathers, the expensive part on TPU) are half the full node space
    E = P + 1
    SE = jnp.int32(P)
    first_child = core["first_child"]  # node space (roots included)
    next_sib_e = jnp.concatenate([core["next_sib"][:P], jnp.array([-1], jnp.int32)])
    fc_e = jnp.concatenate([jnp.minimum(first_child[:P], SE + 1), jnp.array([-1], jnp.int32)])
    fc_e = jnp.where(fc_e > SE, NONE32, fc_e)  # child refs are always < P
    parent_row = core["parent_row"]
    is_elem = core["is_elem"]
    elem_ref = c["elem_ref"]

    # A(i): next sibling of i, else of nearest ancestor (threaded successor),
    # resolved by pointer doubling over the parent chain. Parents that are
    # object roots terminate the climb (ans = END).
    parent_e = jnp.concatenate(
        [
            jnp.where(is_elem & (elem_ref >= 0), elem_ref, SE),
            jnp.array([P], jnp.int32),
        ]
    ).astype(jnp.int32)
    is_elem_e = jnp.concatenate([is_elem, jnp.array([False])])
    has_sib = next_sib_e != NONE32
    done = has_sib | ~is_elem_e | (parent_e == SE)
    ans = jnp.where(has_sib & is_elem_e, next_sib_e, NONE32)
    jump = parent_e

    def _thread(_, st):
        ans, done, jump = st
        take = (~done) & done[jump]
        ans = jnp.where(take, ans[jump], ans)
        done = done | take
        jump = jump[jump]
        return ans, done, jump

    ans, done, jump = jax.lax.fori_loop(
        0, _ceil_log2(E) + 1, _thread, (ans, done, jump)
    )

    # preorder successor: first child, else A(i); Wyllie ranking gives the
    # distance to the chain end, hence the document-order index
    succ_e = jnp.where(fc_e != NONE32, fc_e, ans)
    nxt = jnp.where(succ_e < 0, SE, succ_e)
    nxt = nxt.at[SE].set(SE)
    dist = jnp.where(jnp.arange(E, dtype=jnp.int32) == SE, 0, 1).astype(jnp.int32)

    def _rank(_, st):
        dist, nxt = st
        return dist + dist[nxt], nxt[nxt]

    dist, nxt = jax.lax.fori_loop(0, _ceil_log2(E) + 1, _rank, (dist, nxt))
    # chain start per row: the root's first child (an element node)
    start = first_child[P + c["obj_dense"]]
    start_c = jnp.clip(start, 0, P - 1)
    return jnp.where(
        is_elem & (start >= 0), dist[start_c] - dist[rows], NONE32
    )


@jax.jit
def merge_kernel(c):
    """Single-device merge, everything on device (incl. linearization)."""
    core = resolve_state(c, *succ_resolution(c))
    core["elem_index"] = device_linearize(c, core)
    return core


@jax.jit
def merge_kernel_core(c):
    """Device merge without document-order ranking (the hybrid pipeline:
    the native preorder walk supplies elem_index on host)."""
    return resolve_state(c, *succ_resolution(c))


def device_linearize_condensed(c, core, rcap: int, obj_cap: int = None):
    """All-device document order via CHAIN CONDENSATION.

    The plain pointer-doubling ranking (device_linearize) pays two
    O(log N)-step loops of random gathers over the full row space — the
    known-weak all-device phase. This version collapses the preorder
    list into RUNS first: in actor-concatenated element order
    (``c["aorder"]``, host-supplied layout permutation), a typing chain
    is a CONTIGUOUS stretch of slots where each op is its predecessor's
    first child (the structure native/condense.cpp exploits host-side;
    reference locality: query/insert.rs:11-160). Runs are found with
    cumsum + segmented scans, and the two doubling loops (sibling-climb
    threading + Wyllie ranking) run over ``rcap``-sized run tables.

    Every full-width data movement is expressed as a SCATTER along the
    permutation (unique indices) rather than a gather — random gathers
    cost ~10x more than scatters on this hardware — leaving one small
    per-object gather in the whole pass. The caller guarantees the true
    run count fits ``rcap`` (OpLog counts runs host-side and picks the
    bucket).
    """
    P = c["action"].shape[0]
    i32 = jnp.int32
    ks = jnp.arange(P, dtype=i32)
    is_elem = core["is_elem"]
    er = c["elem_ref"]
    first_child = core["first_child"]
    next_sib = core["next_sib"][:P]
    seq = c["aorder"]  # compact slot k -> element row (pad sentinel = P)

    # first-child continuation, scatter-style: each element row p marks
    # ITS first child as a continuation (unique targets)
    fc_elem = first_child[:P]  # first child of element row p (node space<P)
    mark = is_elem & (fc_elem >= 0)
    is_cont = (
        jnp.zeros(P + 1, jnp.bool_)
        .at[jnp.where(mark, jnp.clip(fc_elem, 0, P - 1), P)]
        .set(True)[:P]
    )

    valid = seq < P
    seqc = jnp.clip(seq, 0, P - 1)
    # row -> compact slot (junk writes land in the spare slot)
    kpos = (
        jnp.full(P + 1, 0, i32)
        .at[jnp.where(valid, seqc, P)]
        .set(ks)[:P]
    )
    # per-slot facts: scatter each row's packed data to its slot
    row_pack = jnp.stack(
        [
            er,
            next_sib,
            is_cont.astype(i32) * 2 + (next_sib != NONE32).astype(i32),
        ],
        axis=1,
    )
    slot_tgt = jnp.where(is_elem, kpos, P)
    g = (
        jnp.zeros((P + 1, 3), i32)
        .at[slot_tgt]
        .set(row_pack)[:P]
    )
    er_k = g[:, 0]
    sib_k = g[:, 1]
    cont_bit = g[:, 2]

    # run segmentation: slot k continues its run iff it is a first-child
    # continuation AND its parent is the previous compact slot's row
    prev_row = jnp.concatenate([jnp.full(1, P, i32), seq[:-1]])
    cont_k = valid & (cont_bit >= 2) & (er_k == prev_row)
    brk = valid & ~cont_k
    run_of_k = jnp.cumsum(brk.astype(i32)) - 1

    # segmented scan carrying the run-start position and the "last
    # sibling-bearing member so far" answer (one scan, no gathers)
    flag_k = valid & ((cont_bit & 1) == 1)
    val_k = jnp.where(flag_k, sib_k, NONE32)

    def _seg_last(x, y):
        xv, xf, xs, xb = x
        yv, yf, ys, yb = y
        v = jnp.where(yb, yv, jnp.where(yf, yv, xv))
        f = jnp.where(yb, yf, xf | yf)
        s = jnp.where(yb, ys, xs)
        return (v, f, s, xb | yb)

    ans_k, ansf_k, start_k, _ = jax.lax.associative_scan(
        _seg_last, (val_k, flag_k, ks, brk)
    )
    off_k = ks - start_k

    # run tables (rcap capacity; host guarantees run count <= rcap).
    # Runs are CONTIGUOUS compact stretches: lengths from start diffs.
    rix = jnp.arange(rcap, dtype=i32)
    rsafe = jnp.clip(run_of_k, 0, rcap - 1)
    run_cnt = jnp.sum(brk.astype(i32))
    live_r = rix < run_cnt
    n_elems = jnp.sum(valid.astype(i32))
    run_start = (
        jnp.full(rcap + 1, 0, i32)
        .at[jnp.where(brk, rsafe, rcap)]
        .set(ks)[:rcap]
    )
    run_end = jnp.where(
        rix + 1 < run_cnt,
        jnp.concatenate([run_start[1:], jnp.zeros(1, i32)]),
        n_elems,
    )
    run_len = jnp.where(live_r, run_end - run_start, 0)

    # condensed sibling-climb: each run asks "A at my head's parent" —
    # answered within the parent's run prefix when a flagged member
    # exists, else inherited from THAT run's own climb (all rcap-sized)
    head_row = seq[jnp.clip(run_start, 0, P - 1)]
    par_head = jnp.where(live_r, er[jnp.clip(head_row, 0, P - 1)], NONE32)
    par_is_elem = par_head >= 0  # object-root parents (<0) end the climb
    pk = kpos[jnp.clip(par_head, 0, P - 1)]
    a_at_p = ans_k[pk]
    f_at_p = ansf_k[pk]
    prun = jnp.clip(run_of_k[pk], 0, rcap - 1)
    done_r = (~par_is_elem) | f_at_p
    ans_r = jnp.where(par_is_elem & f_at_p, a_at_p, NONE32)
    jump_r = jnp.where(par_is_elem, prun, rix)

    # static unroll: a flat HLO graph — fori_loop pays ~1ms/iteration of
    # launch overhead on this backend, dwarfing the tiny rcap-sized gathers
    for _ in range(_ceil_log2(rcap) + 1):
        take = (~done_r) & done_r[jump_r]
        ans_r = jnp.where(take, ans_r[jump_r], ans_r)
        done_r = done_r | take
        jump_r = jump_r[jump_r]

    # run successor: the tail's first child (a later run's head), else the
    # tail's climb answer (within-run prefix, else the run climb)
    tail_k = jnp.clip(run_start + run_len - 1, 0, P - 1)
    tail_row = seq[tail_k]
    fc_tail = first_child[jnp.clip(tail_row, 0, P - 1)]
    a_tail = jnp.where(ansf_k[tail_k], ans_k[tail_k], ans_r)
    nxt_row = jnp.where(live_r, jnp.where(fc_tail >= 0, fc_tail, a_tail), NONE32)
    succ_run = jnp.where(
        nxt_row >= 0,
        jnp.clip(run_of_k[kpos[jnp.clip(nxt_row, 0, P - 1)]], 0, rcap - 1),
        jnp.int32(rcap),
    )

    # Wyllie over runs, weights = run lengths; sentinel slot rcap = END
    dist_r = jnp.concatenate([jnp.where(live_r, run_len, 0), jnp.zeros(1, i32)])
    nxt_r = jnp.concatenate([succ_run, jnp.full(1, rcap, i32)])

    for _ in range(_ceil_log2(rcap) + 1):  # static unroll (see climb)
        dist_r = dist_r + dist_r[nxt_r]
        nxt_r = nxt_r[nxt_r]

    # broadcast each run's dist to its slots: scatter to head slots (rcap
    # writes), then carry-from-boundary with a segmented scan — no table
    # gather with full-width indices
    dist_at_head = (
        jnp.zeros(P + 1, i32)
        .at[jnp.where(live_r, jnp.clip(run_start, 0, P), P)]
        .set(dist_r[:rcap])[:P]
    )

    def _seg_carry(x, y):
        xv, xb = x
        yv, yb = y
        return (jnp.where(yb, yv, xv), xb | yb)

    dist_k, _ = jax.lax.associative_scan(_seg_carry, (dist_at_head, brk))

    # nodes from v (inclusive) to END: run dist minus offset; scatter the
    # per-slot value back to rows, then rank = T(object start) - T(v)
    t_slot = dist_k - off_k
    t_row = (
        jnp.zeros(P + 1, i32)
        .at[jnp.where(valid, seqc, P)]
        .set(t_slot)[:P]
    )
    if obj_cap is not None:
        # small static object table: T(start) per object via two tiny
        # gathers + ONE full-width table lookup
        roots = first_child[P : P + obj_cap + 2]
        t_start_obj = jnp.where(
            roots >= 0, t_row[jnp.clip(roots, 0, P - 1)], NONE32
        )
        t_start = t_start_obj[jnp.clip(c["obj_dense"], 0, obj_cap + 1)]
        return jnp.where(is_elem & (t_start >= 0), t_start - t_row, NONE32)
    start = first_child[P + c["obj_dense"]]
    startc = jnp.clip(start, 0, P - 1)
    return jnp.where(
        is_elem & (start >= 0), t_row[startc] - t_row, NONE32
    )


def condensed_caps(log) -> tuple:
    """(rcap, obj_cap) buckets for merge_kernel_condensed — routed through
    oplog._capacity, the ONE growth/bucket policy (shared with pad_columns
    and the packed transport) so a growing document reuses the compiled
    kernel for every size inside a bucket instead of retracing per row
    count."""
    rcap = _capacity(max(log.condensed_run_count(), 1), 32)
    obj_cap = _capacity(max(log.n_objs, 1), 16)
    return rcap, obj_cap


@functools.lru_cache(maxsize=None)
def merge_kernel_condensed(rcap: int, obj_cap: int = None):
    """jit'd all-device merge whose linearization condenses chains into at
    most ``rcap`` runs (one compiled kernel per (rcap, obj_cap) bucket).
    A static ``obj_cap`` also arms resolve_state's packed single-key
    winner sort."""

    @jax.jit
    def _kernel(c):
        core = resolve_state(c, *succ_resolution(c), obj_cap=obj_cap)
        core["elem_index"] = device_linearize_condensed(c, core, rcap, obj_cap)
        return core

    return _kernel


# -- scatter-based resolution -------------------------------------------------
#
# The sort-free winner formulation (a sequence run's group id is its
# run-head row; map groups index a dense obj x prop table) measured ~1.45x
# faster than the sort-based resolve_state on a v5e at the 1024-replica
# fan-in (32.5ms vs 47ms for 376k ops), with bit-identical outputs. It
# needs static group-table geometry (n_objs, n_props from the OpLog), so
# callers that have it get this kernel and the sort path remains both the
# fallback and the geometry-free default. Same gate as the native host
# engine and the sharded path: the dense table must stay O(P)-ish.


def scatter_geom_key(n_objs: int, n_props: int):
    """Pow2-bucketed (n_objs2, n_props) geometry: a growing document must
    reuse compiled kernels (one per capacity bucket, like obj_cap/P), and a
    larger group table changes nothing — the gid mapping stays injective
    and every output is per-row or fixed-size."""
    return (_next_pow2(max(n_objs + 2, 16)), _next_pow2(max(n_props, 1)))


def scatter_geometry_ok(P: int, n_objs: int, n_props: int) -> bool:
    # evaluated on the BUCKETED geometry (scatter_geom_key) so the gate
    # bounds the actual compiled table, not the pre-bucket request
    n_objs2, np_eff = scatter_geom_key(n_objs, n_props)
    return n_objs2 * np_eff <= 8 * P + 65536


def forest(c):
    """Sibling forest (parent / first_child / next_sib), shared by the
    scatter kernel and the sharded path (parallel/sharding.py).

    first_child is a scatter-max (children order is descending row =
    descending Lamport, query/insert.rs); next_sib adjacency keeps one
    sort — a few percent of the merge."""
    P = c["action"].shape[0]
    rows = jnp.arange(P, dtype=jnp.int32)
    valid = c["action"] != PAD_ACTION
    insert = c["insert"]
    elem_ref = c["elem_ref"]
    obj_dense = c["obj_dense"]
    N = 2 * P + 3
    S = jnp.int32(N - 1)
    is_elem = insert & valid
    parent_row = jnp.where(
        is_elem,
        jnp.where(
            elem_ref == ELEM_HEAD,
            P + obj_dense,
            jnp.where(elem_ref >= 0, elem_ref, S),
        ),
        S,
    ).astype(jnp.int32)
    first_child = (
        jnp.full(N, NONE32, jnp.int32)
        .at[jnp.where(is_elem, parent_row, N - 1)]
        .max(jnp.where(is_elem, rows, NONE32))
    )
    sib_parent = jnp.where(is_elem, parent_row, jnp.int32(N))
    sp_s, neg_rows = jax.lax.sort((sib_parent, -rows), num_keys=2, is_stable=True)
    sib_idx = -neg_rows
    nxt_same = jnp.concatenate([sp_s[1:] == sp_s[:-1], jnp.array([False])])
    nxt_row = jnp.concatenate([sib_idx[1:], jnp.array([-1], jnp.int32)])
    in_range = sp_s < N
    next_sib = (
        jnp.full(N, NONE32, jnp.int32)
        .at[jnp.where(in_range, sib_idx, N - 1)]
        .set(jnp.where(nxt_same & in_range, nxt_row, NONE32))
    )
    return is_elem, parent_row, first_child, next_sib


def resolve_state_scatter(c, succ_count, inc_count, counter_inc,
                          n_objs2: int, n_props: int):
    """Sort-free resolve_state: same output dict, winners via scatter-max/
    scatter-add over dense group ids."""
    P = c["action"].shape[0]
    G = P + 2 * n_objs2 + n_objs2 * n_props + 1
    rows = jnp.arange(P, dtype=jnp.int32)
    action = c["action"]
    valid = action != PAD_ACTION
    insert = c["insert"]
    elem_ref = c["elem_ref"]
    obj_dense = c["obj_dense"]
    prop = c["prop"]
    visible = visibility(c, succ_count, inc_count)

    run = jnp.where(insert, rows, elem_ref)
    seq_gid = jnp.where(
        run >= 0,
        run,
        P + obj_dense * 2 + jnp.where(elem_ref == ELEM_HEAD, 0, 1),
    )
    map_gid = P + 2 * n_objs2 + obj_dense * n_props + prop
    gid = jnp.where(prop >= 0, map_gid, seq_gid)
    gid = jnp.where(valid, gid, G - 1).astype(jnp.int32)
    win = (
        jnp.full(G, NONE32, jnp.int32)
        .at[gid]
        .max(jnp.where(visible, rows, NONE32))
    )
    cnt = jnp.zeros(G, jnp.int32).at[gid].add(visible.astype(jnp.int32))
    winner = jnp.where(valid, win[gid], NONE32)
    conflicts = jnp.where(valid, cnt[gid], 0)

    is_elem, parent_row, first_child, next_sib = forest(c)
    core = {
        "visible": visible,
        "counter_inc": counter_inc,
        "winner": winner,
        "conflicts": conflicts,
        "succ_count": succ_count,
        "inc_count": inc_count,
        "first_child": first_child,
        "next_sib": next_sib,
        "parent_row": parent_row,
        "is_elem": is_elem,
    }
    elem_vis = is_elem & (winner >= 0)
    obj_idx = jnp.where(valid, obj_dense, jnp.int32(P + 1))
    core["obj_vis_len"] = (
        jnp.zeros(P + 2, jnp.int32).at[obj_idx].add(elem_vis.astype(jnp.int32))
    )
    w_width = jnp.where(elem_vis, c["width"][jnp.clip(winner, 0, P - 1)], 0)
    core["obj_text_width"] = jnp.zeros(P + 2, jnp.int32).at[obj_idx].add(w_width)
    return core


_scatter_core_cache = {}


def scatter_kernel_core(n_objs: int, n_props: int):
    """Jitted geometry-specialized scatter-resolution kernel (no ranking)."""
    key = scatter_geom_key(n_objs, n_props)
    fn = _scatter_core_cache.get(key)
    if fn is None:
        n_objs2, np_eff = key

        @jax.jit
        def f(c):
            return resolve_state_scatter(
                c, *succ_resolution(c), n_objs2=n_objs2, n_props=np_eff
            )

        fn = _scatter_core_cache[key] = f
    return fn


# -- packed transport ---------------------------------------------------------
#
# Remote accelerators (this image reaches its TPU through a ~25 MB/s,
# ~90 ms-RTT tunnel) are round-trip- and byte-bound, not compute-bound.
# The packed path minimizes both:
#   in : per column, either slope-RLE runs (decoded on device, usually a
#        few KB total — encode_transport) or a plain int32 column when it
#        doesn't compress; action/insert/value_tag/covered travel bit-packed
#        in one flags word
#   out: one flat int32 vector, the requested per-row outputs concatenated;
#        boolean outputs bit-packed 32/word; per-object stats truncated to
#        a bucketed object capacity on device
# Linearization (elem_index) is computed HOST-side by host_linearize from
# the same numpy columns, overlapped with the device kernel — element
# order depends only on the insert forest, so it needs neither the merge
# results nor any extra transfer. device_linearize remains for the
# pure-device flow (multi-chip dry run, no native core).

_F_ACTION = 15
_F_INSERT = 1 << 4
_F_TAG_SHIFT = 5
_F_COVERED = 1 << 9


_OBJ_STATS = ("obj_vis_len", "obj_text_width")
# boolean / flag outputs travel as 32-bit bitmasks (1/32 the bytes)
_BIT_OUTPUTS = {"visible": None, "conflicts": 1}  # name -> "flag if > thresh"
# node-space outputs: [0,P) elements + [P,2P+2) object roots + sentinel
_NODE_OUTPUTS = ("first_child", "next_sib")

_P_ORDER = ("flags", "prop", "elem_ref", "obj_dense", "value_i32", "width")


_Q_ORDER = ("pred_src", "pred_tgt")


def _flags_column(cols) -> np.ndarray:
    return (
        cols["action"].astype(np.int32)
        | (cols["insert"].astype(np.int32) << 4)
        | (cols["value_tag"].astype(np.int32) << _F_TAG_SHIFT)
        | (cols["covered"].astype(np.int32) << 9)
    )


def _slope_rle(x: np.ndarray):
    """Slope-RLE one column: x[i] == w[run(i)] + slope*i, or None.

    Slope candidates: 0, 1 and the modal first-difference — the latter
    catches the stride-N patterns Lamport row order produces when N
    replicas' same-counter ops interleave (elem_ref then steps by N).
    Returns (w, cum, slope) int32 arrays, or None when the column doesn't
    compress below n/8 runs (caller ships it as a plain column).
    """
    n = len(x)
    if n == 0:
        return None
    x64 = x.astype(np.int64)
    cands = [0, 1]
    if n > 2:
        d = np.diff(x64[: min(n, 1 << 16)])
        vals, counts = np.unique(d, return_counts=True)
        mode = int(vals[np.argmax(counts)])
        if mode not in cands and abs(mode) < (1 << 20):
            cands.append(mode)
    best = None
    idx = np.arange(n, dtype=np.int64)
    for s in cands:
        y = x64 - s * idx
        b = np.flatnonzero(y[1:] != y[:-1]) + 1
        if best is None or len(b) < len(best[2]):
            best = (s, y, b)
    s, y, b = best
    if len(b) + 1 > max(n // 8, 15):
        return None
    starts = np.concatenate([[0], b])
    w = y[starts]
    if w.size and (w.min() < -(1 << 31) or w.max() >= (1 << 31)):
        return None
    cum = np.concatenate([b, [n]])
    return w.astype(np.int32), cum.astype(np.int32), s


def _note_h2d(actual: int, dense: int) -> None:
    """Byte accounting every H2D site shares: the counter pair the
    perf-report ratio line reads, plus the cycle profiler notes."""
    from ..obs import prof as _prof

    obs.count("device.h2d_bytes", n=actual)
    obs.count("device.h2d_dense_bytes", n=dense)
    _prof.note("h2d_bytes", actual)
    _prof.note("h2d_dense_bytes", dense)


def stage_cols_device(cols_np):
    """Compressed H2D staging for the dict-path launch sites.

    Per column: slope-RLE runs (the resident format's device image) are
    ``device_put`` as (w, cum) run tables padded to run-capacity buckets
    — so ``device_put`` moves compressed bytes, not dense int32 rows —
    and expanded ON device with one vectorized searchsorted gather per
    column (the ops/merge.py packed-transport ``_expand`` rule, run
    eagerly so the jit kernel caches never churn on data-dependent run
    shapes). A column whose run structure degenerates past the
    ``_slope_rle`` gate ships dense (counted via
    ``oplog.compress_fallback{column,reason=h2d}``).

    Records actual bytes moved as ``bytes=`` on the ``device.h2d`` span
    and on the ``device.h2d_bytes`` counter (dense-equivalent bytes ride
    on ``device.h2d_dense_bytes`` so compression wins are a ratio, not a
    guess). ``AUTOMERGE_TPU_COMPRESSED=0`` restores the plain dense
    upload everywhere.
    """
    from . import compressed as _C

    cols_np = {k: np.asarray(v) for k, v in cols_np.items()}
    P = len(cols_np["action"])
    dense_bytes = sum(v.nbytes for v in cols_np.values())
    if not _C.enabled():
        with obs.span("device.h2d", rows=P, bytes=dense_bytes):
            dev = {k: jnp.asarray(v) for k, v in cols_np.items()}
        _note_h2d(dense_bytes, dense_bytes)
        return dev
    dense = {}
    groups = {}  # column length -> [(name, (w, cum, slope), is_bool)]
    h2d_bytes = 0
    for k, v in cols_np.items():
        n = len(v)
        enc = None
        if n >= 32 and v.dtype in (np.int32, np.bool_):
            enc = _slope_rle(v if v.dtype == np.int32 else v.astype(np.int32))
            if enc is None:
                obs.count("oplog.compress_fallback",
                          labels={"column": k, "reason": "h2d"})
        if enc is None:
            dense[k] = v
            h2d_bytes += v.nbytes
        else:
            groups.setdefault(n, []).append((k, enc, v.dtype == np.bool_))
    # one stacked run table per column length (rows vs pred edges), so
    # the whole expansion is ONE fused jit dispatch per group — eager
    # per-column ops would pay ~50 dispatch overheads per launch
    stacks = []
    for n, cols in groups.items():
        rcap = _capacity(max(len(w) for _, (w, _, _), _ in cols), 16)
        K = len(cols)
        W = np.zeros((K, rcap), np.int32)
        C = np.full((K, rcap), np.int32(n), np.int32)
        S = np.empty(K, np.int32)
        for idx, (_, (w, cum, s), _) in enumerate(cols):
            W[idx, : len(w)] = w
            C[idx, : len(cum)] = cum
            S[idx] = s
        stacks.append((n, rcap, cols, W, C, S))
        h2d_bytes += W.nbytes + C.nbytes + S.nbytes
    with obs.span("device.h2d", rows=P, bytes=h2d_bytes):
        out = {k: jnp.asarray(v) for k, v in dense.items()}
        dev_stacks = [
            (n, rcap, cols, jnp.asarray(W), jnp.asarray(C), jnp.asarray(S))
            for n, rcap, cols, W, C, S in stacks
        ]
    # the eager run->dense expansion dispatch is its own profiler stage
    # (device.expand): it is the exact work the run-native kernels fuse
    # away, so the split must show it apart from the device_put h2d
    if dev_stacks:
        with obs.span("device.expand", rows=P, stacks=len(dev_stacks)):
            for n, rcap, cols, W, C, S in dev_stacks:
                bools = tuple(b for _, _, b in cols)
                expanded = _expander(n, rcap, bools)(W, C, S)
                for (k, _, _), col in zip(cols, expanded):
                    out[k] = col
    _note_h2d(h2d_bytes, dense_bytes)
    return out


_EXPAND_CACHE = {}


def _expander(n, rcap, bools):
    """Jit'd stacked run expansion: (K, rcap) run tables -> K dense
    (n,) columns in one dispatch. Cache key is (bucketed) shapes plus
    which outputs cast back to bool — slopes are dynamic inputs, so
    data-dependent slope choices never churn the jit cache."""
    key = (n, rcap, bools)
    fn = _EXPAND_CACHE.get(key)
    if fn is None:
        def f(W, C, S):
            i = jnp.arange(n, dtype=jnp.int32)

            def one(w, c, s):
                j = jnp.clip(
                    jnp.searchsorted(c, i, side="right"), 0, rcap - 1
                ).astype(jnp.int32)
                return w[j] + s * i

            cols = jax.vmap(one)(W, C, S)
            return tuple(
                cols[k].astype(jnp.bool_) if b else cols[k]
                for k, b in enumerate(bools)
            )

        fn = _EXPAND_CACHE[key] = jax.jit(f)
    return fn


# -- run-native resolution ----------------------------------------------------
#
# stage_cols_device ships run tables but expands them to dense columns
# EAGERLY (the device.expand dispatch) before the resolution kernel runs,
# so kernel input bandwidth is dense again the moment resolution starts.
# Run-native mode keeps the run tables as the KERNEL's input: the
# expansion gathers (searchsorted over R run heads + stride arithmetic —
# the StrideRuns.join trick, on device) move INSIDE the kernel jit, where
# XLA fuses them into their consumers, so device input traffic for
# run-eligible columns scales with run count, not history size (the
# LSM-OPD compute-on-compressed argument, arXiv:2508.11862). Kernels are
# specialized per column-encoding signature via control-flow duplication
# (arXiv:2302.10098): pure-RLE stacks (every stride 0) expand as a plain
# run gather w[j], delta+RLE stacks add the dynamic stride term
# w[j] + s*i, and a column whose run structure degenerates past the
# resident ratio gate (compressed.run_gate) ships dense, counted per
# column on device.run_native_fallback{column,reason}.


def run_native_enabled() -> bool:
    """Whether resolution kernels consume run tables directly (default
    on wherever compressed residency is). ``AUTOMERGE_TPU_RUN_NATIVE=0``
    restores the eager-expansion staging; ``AUTOMERGE_TPU_COMPRESSED=0``
    restores the fully dense differential oracle."""
    from . import compressed as _C

    return (
        _C.enabled()
        and os.environ.get("AUTOMERGE_TPU_RUN_NATIVE", "1") != "0"
    )


def stage_cols_run_native(cols_np):
    """Run-native H2D staging: per column, slope-RLE run tables are
    ``device_put`` padded to run-capacity buckets and STAY the kernel
    input (no eager expansion dispatch). Returns ``(dense, stacks,
    plan)``:

    * ``dense`` — {name: device array} for pass-through columns,
    * ``stacks`` — one tuple of device arrays per stack: ``(W, C)`` for
      a pure-RLE stack, ``(W, C, S)`` for a delta stack,
    * ``plan`` — static metadata, one ``(n, rcap, enc, names, bools)``
      entry per stack (``enc``: "rle" | "delta"), the specialization
      key ``run_native_kernel`` compiles against.

    Bytes staged here are exactly the resolution kernel's input; they
    ride the ``device.kernel_input_bytes`` counter next to their dense
    equivalent so the input-bandwidth win is a ratio, not a guess.
    """
    from . import compressed as _C

    cols_np = {k: np.asarray(v) for k, v in cols_np.items()}
    P = len(cols_np["action"])
    dense_bytes = sum(v.nbytes for v in cols_np.values())
    dense = {}
    groups = {}  # (length, enc class) -> [(name, (w, cum, slope), is_bool)]
    h2d_bytes = 0
    for k, v in cols_np.items():
        n = len(v)
        enc = None
        reason = None
        if n < 32:
            reason = "short"
        elif v.dtype not in (np.int32, np.bool_):
            reason = "dtype"
        else:
            enc = _slope_rle(v if v.dtype == np.int32 else v.astype(np.int32))
            if enc is not None and _C.run_gate(len(enc[0]), n):
                enc = None
            if enc is None:
                reason = "ratio"
                obs.count("oplog.compress_fallback",
                          labels={"column": k, "reason": "h2d"})
        if enc is None:
            obs.count("device.run_native_fallback",
                      labels={"column": k, "reason": reason})
            dense[k] = v
            h2d_bytes += v.nbytes
        else:
            cls = "rle" if enc[2] == 0 else "delta"
            groups.setdefault((n, cls), []).append(
                (k, enc, v.dtype == np.bool_)
            )
    plan = []
    host_stacks = []
    for (n, cls), cols in sorted(groups.items(), key=lambda kv: kv[0]):
        rcap = _capacity(max(len(w) for _, (w, _, _), _ in cols), 16)
        K = len(cols)
        W = np.zeros((K, rcap), np.int32)
        C = np.full((K, rcap), np.int32(n), np.int32)
        S = np.empty(K, np.int32)
        for idx, (_, (w, cum, s), _) in enumerate(cols):
            W[idx, : len(w)] = w
            C[idx, : len(cum)] = cum
            S[idx] = s
        plan.append((
            n, rcap, cls,
            tuple(k for k, _, _ in cols),
            tuple(b for _, _, b in cols),
        ))
        arrs = (W, C) if cls == "rle" else (W, C, S)
        host_stacks.append(arrs)
        h2d_bytes += sum(a.nbytes for a in arrs)
    with obs.span("device.h2d", rows=P, bytes=h2d_bytes):
        dense_dev = {k: jnp.asarray(v) for k, v in dense.items()}
        stacks = tuple(
            tuple(jnp.asarray(a) for a in arrs) for arrs in host_stacks
        )
    _note_h2d(h2d_bytes, dense_bytes)
    obs.count("device.kernel_input_bytes", n=h2d_bytes)
    obs.count("device.kernel_input_dense_bytes", n=dense_bytes)
    return dense_dev, stacks, tuple(plan)


_RUN_NATIVE_CACHE = {}


def run_native_kernel(plan, geom):
    """The jit'd run-native resolution kernel for one encoding plan.

    ``geom`` selects the resolution body: ``("core",)`` = the sort-based
    merge_kernel_core, ``("scatter", n_objs, n_props)`` = the
    geometry-specialized scatter-max winner kernel, ``("full",)`` =
    merge_kernel with on-device linearization. One compiled variant
    exists per (plan, geom) — the control-flow-duplication axis: every
    distinct per-column encoding signature compiles its own kernel whose
    in-jit expansion is specialized to the encoding class (pure-RLE:
    ``w[j]``; delta+RLE: ``w[j] + s*i`` with dynamic slopes), and XLA
    fuses those gathers into the resolution consumers."""
    key = (plan, geom)
    fn = _RUN_NATIVE_CACHE.get(key)
    if fn is None:
        if geom[0] == "scatter":
            core = scatter_kernel_core(geom[1], geom[2])
        elif geom[0] == "full":
            core = merge_kernel
        else:
            core = merge_kernel_core

        def f(dense, stacks):
            c = dict(dense)
            for (n, rcap, cls, names, bools), arrs in zip(plan, stacks):
                i = jnp.arange(n, dtype=jnp.int32)

                def gather(w, cum, _i=i, _rcap=rcap):
                    j = jnp.clip(
                        jnp.searchsorted(cum, _i, side="right"), 0, _rcap - 1
                    ).astype(jnp.int32)
                    return w[j]

                if cls == "rle":
                    colv = jax.vmap(gather)(arrs[0], arrs[1])
                else:
                    colv = jax.vmap(
                        lambda w, cum, s, _g=gather, _i=i: _g(w, cum) + s * _i
                    )(arrs[0], arrs[1], arrs[2])
                for idx, (name, b) in enumerate(zip(names, bools)):
                    c[name] = colv[idx].astype(jnp.bool_) if b else colv[idx]
            return core(c)

        fn = _RUN_NATIVE_CACHE[key] = jax.jit(f)
    return fn


def prepare_resolution(cols_np, n_objs=None, n_props=None, full=False):
    """Stage bucket-padded dict columns for one resolution launch and
    return a zero-arg dispatch closure (callers wrap the call in their
    own ``device.kernel`` span / trace annotation — staging spans
    ``device.h2d``/``device.expand`` land here, before it).

    Chooses the run-native staging (run tables stay the kernel input,
    counted as a ``path=run_native`` launch) when enabled and at least
    one column run-encodes, the eager-expansion staging otherwise. The
    kernel body is the scatter-max winner kernel when the geometry gate
    allows, the sort-based core otherwise; ``full=True`` pins the
    everything-on-device merge_kernel (on-chip linearization)."""
    P = len(cols_np["action"])
    if full:
        geom = ("full",)
    elif (
        n_objs is not None
        and n_props is not None
        and scatter_geometry_ok(P, n_objs, n_props)
    ):
        geom = ("scatter", n_objs, n_props)
    else:
        geom = ("core",)
    if run_native_enabled():
        dense, stacks, plan = stage_cols_run_native(cols_np)
        if plan:
            obs.count("device.kernel_launches",
                      labels={"path": "run_native"})
            fn = run_native_kernel(plan, geom)
            return lambda: fn(dense, stacks)
        cols_dev = dense  # nothing run-eligible: plain dense launch
    else:
        cols_dev = stage_cols_device(cols_np)
    if geom[0] == "scatter":
        core = scatter_kernel_core(geom[1], geom[2])
    elif geom[0] == "full":
        core = merge_kernel
    else:
        core = merge_kernel_core
    return lambda: core(cols_dev)


def encode_transport(cols) -> tuple:
    """Choose per column between slope-RLE runs and plain transfer.

    The op columns are extremely runny in real workloads (typing runs give
    ``elem_ref[i] = i-1`` or stride-N interleaves, long spans share one
    object/action/width), so most of the input compresses to a few KB —
    the difference between a ~25 MB/s tunnel being the bottleneck or not.
    Runs are decoded on device by one vectorized searchsorted per column
    (_expand).

    Returns (static_key, arrays) where ``static_key`` identifies the jit
    variant (which columns are plain) and ``arrays`` is the input pytree.
    """
    p_sources = dict(cols, flags=_flags_column(cols))
    groups = {
        "P": {k: p_sources[k].astype(np.int32) for k in _P_ORDER},
        "Q": {k: cols[k].astype(np.int32) for k in _Q_ORDER},
    }
    arrays = {}
    plain_names = []
    for gname, group in groups.items():
        length = len(next(iter(group.values())))
        encs = {}
        for k, x in group.items():
            e = _slope_rle(x)
            if e is None:
                plain_names.append(k)
            else:
                encs[k] = e
        if encs:
            r_cap = _next_pow2(max(max(len(w) for w, _, _ in encs.values()), 16))
            names = tuple(encs)
            W = np.zeros((len(encs), r_cap), np.int32)
            C = np.full((len(encs), r_cap), np.int32(length), np.int32)
            S = np.empty(len(encs), np.int32)
            for i, k in enumerate(names):
                w, cum, s = encs[k]
                W[i, : len(w)] = w
                C[i, : len(cum)] = cum
                S[i] = s
            arrays[f"w{gname}"] = W
            arrays[f"c{gname}"] = C
            arrays[f"s{gname}"] = S
        plain = [k for k in group if k not in encs]
        if plain:
            arrays[f"plain{gname}"] = np.stack([group[k] for k in plain])
    run_namesP = tuple(k for k in groups["P"] if k not in plain_names)
    run_namesQ = tuple(k for k in groups["Q"] if k not in plain_names)
    plainP = tuple(k for k in groups["P"] if k in plain_names)
    plainQ = tuple(k for k in groups["Q"] if k in plain_names)
    return (run_namesP, plainP, run_namesQ, plainQ), arrays


def _expand(w, cum, slope, n):
    """Decode one slope-RLE column on device: (R,) runs -> (n,) values."""
    i = jnp.arange(n, dtype=jnp.int32)
    j = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    j = jnp.clip(j, 0, w.shape[0] - 1)
    return w[j] + slope * i


def _unpack_transport(static_key, arrays, P, Q):
    run_namesP, plainP, run_namesQ, plainQ = static_key
    cols = {}
    for gname, run_names, plain_names, n in (
        ("P", run_namesP, plainP, P),
        ("Q", run_namesQ, plainQ, Q),
    ):
        for i, k in enumerate(run_names):
            cols[k] = _expand(
                arrays[f"w{gname}"][i], arrays[f"c{gname}"][i],
                arrays[f"s{gname}"][i], n,
            )
        for i, k in enumerate(plain_names):
            cols[k] = arrays[f"plain{gname}"][i]
    flags = cols.pop("flags")
    cols["action"] = flags & _F_ACTION
    cols["insert"] = (flags & _F_INSERT) != 0
    cols["value_tag"] = (flags >> _F_TAG_SHIFT) & 15
    cols["covered"] = (flags & _F_COVERED) != 0
    return cols


def _bitpack(v):
    """(P,) bool -> (P/32,) int32 bitmask (P is a multiple of 16)."""
    P = v.shape[0]
    pad = (-P) % 32
    b = jnp.pad(v.astype(jnp.uint32), (0, pad)).reshape(-1, 32)
    words = (b << jnp.arange(32, dtype=jnp.uint32)).sum(axis=1).astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(words, jnp.int32)


def _bitunpack(words, P):
    bits = np.unpackbits(
        np.asarray(words, np.int32).view(np.uint8), bitorder="little"
    )
    return bits[:P].astype(bool)


def _emit(core, fetch, obj_cap):
    """Concatenate the requested outputs into one int32 transfer vector."""
    outs = []
    for k in fetch:
        v = core[k]
        if k in _BIT_OUTPUTS:
            thresh = _BIT_OUTPUTS[k]
            flag = v if thresh is None else v > thresh
            outs.append(_bitpack(flag))
            continue
        v = v.astype(jnp.int32)
        if k in _OBJ_STATS:
            v = v[:obj_cap]
        outs.append(v.reshape(-1))
    return jnp.concatenate(outs)


def _runs_fn(fetch, obj_cap, static_key, P, Q, scatter_geom=None):
    @jax.jit
    def f(arrays):
        c = _unpack_transport(static_key, arrays, P, Q)
        if scatter_geom is not None:
            core = resolve_state_scatter(
                c, *succ_resolution(c),
                n_objs2=scatter_geom[0], n_props=scatter_geom[1],
            )
        else:
            core = resolve_state(c, *succ_resolution(c), obj_cap=obj_cap)
        if "elem_index" in fetch:
            core["elem_index"] = device_linearize(c, core)
        return _emit(core, fetch, obj_cap)

    return f


from .oplog import host_linearize  # noqa: F401  (moved: jax-free)


_packed_cache = {}


def _split_flat(flat, fetch, P, obj_cap):
    out = {}
    pos = 0
    words = (P + 31) // 32
    for k in fetch:
        if k in _BIT_OUTPUTS:
            v = _bitunpack(flat[pos : pos + words], P)
            pos += words
            if k == "conflicts":
                # travels as a "conflicted" flag; consumers compare > 1
                v = np.where(v, np.int32(2), np.int32(1))
        else:
            if k in _OBJ_STATS:
                size = obj_cap
            elif k in _NODE_OUTPUTS:
                size = 2 * P + 3
            else:
                size = P
            v = flat[pos : pos + size]
            pos += size
            if k == "is_elem":
                v = v.astype(bool)
        out[k] = v
    return out


def _packed_merge(cols_np, fetch, n_objs, n_props=None):
    from .. import native

    P = len(cols_np["action"])
    Q = len(cols_np["pred_src"])
    obj_cap = min(_capacity((n_objs or P) + 2, 16), P + 2)
    fetch = tuple(fetch)
    scatter_geom = (
        scatter_geom_key(n_objs, n_props)
        if n_objs is not None
        and n_props is not None
        and scatter_geometry_ok(P, n_objs, n_props)
        else None
    )

    # element order never needs the device (host_linearize): computing it
    # host-side while the kernel runs removes the two pointer-doubling
    # gather loops (the kernel's dominant cost) AND 4 B/op of readback
    # keep elem_index on device when it is the ONLY fetch (an explicitly
    # forced packed transport should exercise the device); otherwise rank
    # it host-side overlapped with the kernel
    host_elem = (
        "elem_index" in fetch and len(fetch) > 1 and native.preorder_available()
    )
    dev_fetch = (
        tuple(k for k in fetch if k != "elem_index") if host_elem else fetch
    )

    static_key, arrays = encode_transport(cols_np)
    key = (dev_fetch, obj_cap, static_key, P, Q, scatter_geom)
    fn = _packed_cache.get(key)
    if fn is None:
        fn = _packed_cache[key] = _runs_fn(
            dev_fetch, obj_cap, static_key, P, Q, scatter_geom
        )
    # the packed transport is already run-encoded (encode_transport);
    # record the bytes it actually moves so compression wins surface in
    # perf-report alongside the dict-path staging
    pk_bytes = sum(a.nbytes for a in arrays.values())
    with obs.span("device.h2d", rows=P, bytes=pk_bytes):
        arrays_dev = {k: jnp.asarray(v) for k, v in arrays.items()}
    _note_h2d(pk_bytes, sum(np.asarray(v).nbytes for v in cols_np.values()))
    with obs.span("device.kernel", rows=P):
        flat_dev = fn(arrays_dev)  # async dispatch
    elem_index = host_linearize(cols_np) if host_elem else None
    with obs.span("device.readback", rows=P):
        flat = np.asarray(flat_dev)
    with obs.span("device.materialize", rows=P):
        out = _split_flat(flat, dev_fetch, P, obj_cap)
    if host_elem:
        out["elem_index"] = elem_index
    return out


ALL_OUTPUTS = (
    "visible", "counter_inc", "winner", "conflicts", "succ_count",
    "inc_count", "first_child", "next_sib", "parent_row", "is_elem",
    "obj_vis_len", "obj_text_width", "elem_index",
)


def merge_columns(cols_np, linearize: str = "auto", fetch=None, n_objs=None,
                  n_props=None):
    """Host entry: numpy columns in, numpy resolution out.

    ``linearize``: "device" (all on chip), "native" (C++ preorder walk),
    or "auto" (native when available — the ranking pass's random gathers
    are a poor fit for TPU, see device_linearize).

    ``fetch`` selects which output arrays are brought back to the host
    (default: all). Device->host transfer is the dominant cost on remote
    accelerators, so read paths should request only what they consume.
    ``n_objs`` (when given) truncates the per-object stats to the live
    object count before transfer. ``n_props`` (with ``n_objs``) supplies
    the static group-table geometry that selects the faster sort-free
    scatter resolution (resolve_state_scatter) on the device paths;
    without it the sort-based kernel runs.

    Transport: against a non-CPU backend the packed path is used whenever
    ``fetch`` is restricted and ``linearize`` is left on "auto" (one array
    each way — see "packed transport" above); the dict path serves
    local/CPU runs where per-array transfer is free and the native
    preorder walk beats the on-device ranking, and any call that pins
    ``linearize`` explicitly. Override with AUTOMERGE_TPU_TRANSPORT=
    dict|packed. Packed caveat: ``conflicts`` comes back as a 1/2
    conflicted flag (consumers compare ``> 1``), not the exact
    visible-op count the dict path returns.
    """
    from .. import native

    # pure-linearization calls never need a device at all (element order is
    # a host computation); shortcut before anything touches the jax backend.
    # An explicit linearize="device" pin (the pure-device/dry-run flow)
    # still runs on chip.
    if (
        fetch is not None
        and set(fetch) == {"elem_index"}
        and linearize in ("auto", "native")
        and native.preorder_available()
    ):
        return {"elem_index": host_linearize(cols_np)}

    # Engine selection. The merge has two equivalent engines: the jit
    # kernel (device) and the O(n) native host merge (merge_cols.cpp).
    # A remote accelerator behind a thin link is round-trip-bound — ~0.3s
    # of transport minimum — while the host engine runs ~25ms/M ops, so
    # below AUTOMERGE_TPU_HOST_MERGE_MAX rows (default 16M; set 0 on
    # PCIe/DMA-attached hosts) the host engine wins end to end. On a
    # tunnel-attached device the threshold only bounds host memory:
    # transport cost per row exceeds the O(n) host merge cost per row at
    # EVERY size, so there is no crossover where the device path wins
    # e2e. AUTOMERGE_TPU_ENGINE=jax|native overrides.
    # The CPU backend keeps the jax path so tests exercise the kernel.
    engine = os.environ.get("AUTOMERGE_TPU_ENGINE", "auto")

    def _backend_is_accel() -> bool:
        # decide from the environment when possible: initializing the jax
        # backend (seconds over a tunnel) just to decide NOT to use it
        # would defeat the host engine's purpose
        plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
        if plat:
            return plat != "cpu"
        return jax.default_backend() != "cpu"

    if (
        engine != "jax"
        and linearize in ("auto", "native")
        and native.merge_available()
        and (
            engine == "native"
            or (
                len(cols_np["action"])
                <= int(os.environ.get("AUTOMERGE_TPU_HOST_MERGE_MAX", 1 << 24))
                and _backend_is_accel()
            )
        )
    ):
        need = fetch if fetch is not None else ALL_OUTPUTS
        with obs.span("merge.host", rows=len(cols_np["action"])):
            out = native.merge_cols(
                cols_np,
                n_objs if n_objs is not None else len(cols_np["action"]),
                want_elem_index="elem_index" in need,
            )
        return {k: out[k] for k in need}

    # the jit kernels need bucket-padded shapes; callers may hand over the
    # raw (unpadded) columns dict — the host engine above consumed it
    # as-is, the device path pads here (idempotent for padded input)
    from .oplog import pad_columns

    n_objs_eff = (
        n_objs
        if n_objs is not None
        else (
            int(np.asarray(cols_np["obj_dense"]).max()) + 1
            if len(cols_np["action"])
            else 1
        )
    )
    cols_np = pad_columns(cols_np, n_objs_eff)

    transport = os.environ.get("AUTOMERGE_TPU_TRANSPORT")
    if transport is None:
        transport = (
            "packed"
            if fetch is not None
            and linearize == "auto"
            and jax.default_backend() != "cpu"
            else "dict"
        )
    if transport == "packed":
        return _packed_merge(
            cols_np, fetch if fetch is not None else ALL_OUTPUTS, n_objs,
            n_props,
        )

    if linearize == "auto":
        linearize = "native" if native.preorder_available() else "device"
    need = set(fetch) if fetch is not None else set(ALL_OUTPUTS)

    def pull(out, keys):
        host = {}
        with obs.span("device.readback", rows=len(cols_np["action"])):
            for k in keys:
                v = out[k]
                if k in ("obj_vis_len", "obj_text_width") and n_objs is not None:
                    v = v[: n_objs + 2]
                host[k] = np.asarray(v)
        return host

    if linearize == "native":
        P = len(cols_np["action"])
        # staging (run-native or eager-expand) happens here, outside the
        # kernel span; the closure dispatches the specialized kernel
        dispatch = prepare_resolution(cols_np, n_objs, n_props)
        with obs.span("device.kernel", rows=P):
            out = dispatch()
        host = pull(out, need - {"elem_index"})
        if "elem_index" in need:
            # ranked from the host-resident columns — zero device traffic
            host["elem_index"] = host_linearize(cols_np)
        return host
    dispatch = prepare_resolution(cols_np, full=True)
    with obs.span("device.kernel", rows=len(cols_np["action"])):
        out = dispatch()
    return pull(out, need)
