"""Per-change decoded-column caches + single-pass native log assembly.

The reference's change chunk is already columnar (change_op_columns.rs);
decoding it on every merge is pure waste. Each StoredChange therefore
keeps its decoded, chunk-local column arrays (``cached_cols``), attached
on first decode — one batched native pass over all uncached changes —
and a merge assembles the final Lamport-ordered, reference-resolved
device columns with one native call (native/assemble.cpp):

  counting sort over consecutive-counter runs  ->  O(N) Lamport order
  column gathers through the emit permutation  ->  no concat middleman
  change-span reference resolution             ->  O(log C) per ref, not
                                                   a join against N rows

This is the "commit-time column cache" the fan-in merge rides: replicas
that built their changes locally (or decoded them once) ship ready
columns into every subsequent merge.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import native
from ..errors import AutomergeError
from ..types import ACTOR_BITS, get_text_encoding


class AssembleError(AutomergeError):
    pass


# The gather-heavy columns interleaved as one 24-byte record per op
# (AoS): the assembler's permuted reads touch ONE cache line per row
# (2.6 rows per line) instead of seven per-change column streams. The
# i64 field leads so it stays 8-aligned (24 % 8 == 0).
HOT_DTYPE = np.dtype(
    [
        ("elem_ctr", "<i8"),    # 0
        ("voff", "<u4"),        # 8  chunk-local value-heap offset
        ("vlen", "<u4"),        # 12 value payload length
        ("elem_actor", "<i4"),  # 16 chunk-local actor index (-1 = HEAD)
        ("action", "u1"),       # 20 storage action (0..15)
        ("vcode", "u1"),        # 21 value meta type code (meta & 0xF)
        ("insert", "u1"),       # 22
        ("_pad", "V1"),         # 23
    ]
)
assert HOT_DTYPE.itemsize == 24
# voff/vlen are u32: one change's value heap never approaches 4GB (a chunk
# that large fails elsewhere first); _split_batch guards anyway.

# shared all-minus-one buffer for changes without a key_str / mark_name
# column (grown on demand, never shrunk; cache rows only READ [0, n))
_NEG1_I32 = np.full(1024, -1, np.int32)


def _neg1(n: int) -> np.ndarray:
    global _NEG1_I32
    if len(_NEG1_I32) < n:
        _NEG1_I32 = np.full(max(n, 2 * len(_NEG1_I32)), -1, np.int32)
    return _NEG1_I32


class ChangeCols:
    """One change's decoded, chunk-local op columns (actor columns hold
    chunk-local indices; string columns hold ids into the attached
    tables). Arrays are C-contiguous with the exact dtypes the native
    assembler reads; ``ptr_row`` caches their addresses in the fixed
    18-slot layout of am_assemble_log."""

    __slots__ = (
        "n", "q", "obj_ctr", "obj_actor", "obj_has", "key_sid",
        "expand", "value_int", "width", "width_enc", "mark_sid",
        "pred_num", "pred_ctr", "pred_actor", "key_table", "mark_table",
        "vraw", "hot", "_ptrs", "_const", "rank_tab",
    )

    # the gather-heavy columns live ONLY in the hot record (strided views
    # for host-side consumers); the assembler reads them from the record
    @property
    def action(self) -> np.ndarray:
        return self.hot["action"]

    @property
    def elem_ctr(self) -> np.ndarray:
        return self.hot["elem_ctr"]

    @property
    def elem_actor(self) -> np.ndarray:
        return self.hot["elem_actor"]

    @property
    def insert(self) -> np.ndarray:
        return self.hot["insert"]

    @property
    def vcode(self) -> np.ndarray:
        return self.hot["vcode"]

    @property
    def vlen(self) -> np.ndarray:
        return self.hot["vlen"]

    @property
    def voff(self) -> np.ndarray:
        return self.hot["voff"]

    def const_scan(self) -> Tuple[np.ndarray, np.ndarray]:
        """(mask, value) per column slot: mask[k] when every row of
        column k carries the same value. Computed once per cache."""
        c = self._const
        if c is None:
            mask = np.zeros(18, bool)
            val = np.zeros(18, np.int64)
            n = self.n
            cols = {
                1: self.obj_ctr, 2: self.obj_actor, 3: self.obj_has,
                4: self.key_sid[:n], 7: self.insert, 8: self.expand,
                9: self.vcode, 10: self.vlen, 11: self.voff,
                12: self.value_int, 13: self.width,
                14: self.mark_sid[:n],
            }
            for k, a in cols.items():
                if n == 0:
                    continue  # empty changes don't constrain anything
                v = a[0]
                if n == 1 or (a == v).all():
                    mask[k] = True
                    val[k] = int(v)
            c = (mask, val)
            self._const = c
        return c

    def ptr_row(self) -> np.ndarray:
        p = self._ptrs
        if p is None:
            # slots 0/5/6/7/9/10/11 are served by the hot record; the
            # assembler never dereferences their cold pointers
            cols = (
                None, self.obj_ctr, self.obj_actor, self.obj_has,
                self.key_sid, None, None, None,
                self.expand, None, None, None,
                self.value_int, self.width, self.mark_sid, self.pred_num,
                self.pred_ctr, self.pred_actor, self.hot,
            )
            p = np.fromiter(
                (0 if a is None else a.ctypes.data for a in cols),
                dtype=np.int64,
                count=19,
            )
            self._ptrs = p
        return p

    def ensure_width_encoding(self) -> None:
        """Recompute text widths if the active encoding differs from the
        one the cache was built under (reference: text_value.rs — the
        index unit is a per-document property)."""
        enc = get_text_encoding()
        if enc == self.width_enc:
            return
        from .extract import _str_widths

        w = _str_widths(self.vraw, self.voff, self.vlen, self.vcode, self.n)
        self.width = np.ascontiguousarray(w, np.int32)
        self.width_enc = enc
        self._ptrs = None
        self._const = None


def _c32(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, np.int32)


def _c64(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, np.int64)


def _c8(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, np.uint8)


def ensure_change_cols(changes: Sequence) -> List[ChangeCols]:
    """Fetch-or-build every change's column cache.

    Uncached changes are decoded in ONE batched native pass
    (extract.batch_arrays) and the per-change views attached, so the
    decode cost is paid once per change object, not per merge."""
    caches: List[Optional[ChangeCols]] = [
        getattr(ch, "cached_cols", None) for ch in changes
    ]
    missing = [i for i, c in enumerate(caches) if c is None]
    if missing:
        from .extract import cached_cols_for_hash

        # hash-keyed cache first: a re-delivered change (fresh object off
        # the wire, same hash) costs one dict hit instead of a re-decode
        still = []
        for i in missing:
            cc = cached_cols_for_hash(getattr(changes[i], "hash", None))
            if cc is not None:
                changes[i].cached_cols = cc
                caches[i] = cc
            else:
                still.append(i)
        missing = still
    if missing:
        from .extract import batch_arrays, remember_cols_for_hash

        subset = [changes[i] for i in missing]
        for ch in subset:
            if ch.op_col_data is None:
                raise AssembleError("change has no retained column data")
        a = batch_arrays(subset)
        built = _split_batch(a, subset)
        for i, cc in zip(missing, built):
            changes[i].cached_cols = cc
            caches[i] = cc
            remember_cols_for_hash(getattr(changes[i], "hash", None), cc)
    enc = get_text_encoding()
    for cc in caches:
        if cc.width_enc != enc:
            cc.ensure_width_encoding()
    return caches  # type: ignore[return-value]


def _split_batch(a: Dict, changes: Sequence) -> List[ChangeCols]:
    """Slice one batch_arrays output into per-change ChangeCols views."""
    n_changes = len(changes)
    row_off = a["row_off"]
    pred_row_off = a["pred_row_off"]
    raw_off = a["raw_off"]
    raw_ln = a["raw_ln"]
    raw = a["vraw"]
    enc = get_text_encoding()

    # whole-batch conversions once; per-change slices are COPIED so a
    # retained change never pins the whole batch's arrays through views
    N = int(row_off[-1])
    hot_all = np.empty(N, HOT_DTYPE)
    # HEAD (no actor) is counter 0; a map op's slot is ignored by C
    hot_all["elem_ctr"] = np.where(a["key_has_actor"], a["key_ctr"], 0)
    voff_local = a["voff"] - raw_off[a["change_of_row"]]  # chunk-local
    if N and (
        int(a["vlen"].max(initial=0)) >= (1 << 32)
        or int(voff_local.max(initial=0)) >= (1 << 32)
        or int(voff_local.min(initial=0)) < 0
        or int(a["vlen"].min(initial=0)) < 0
    ):
        raise AssembleError("value heap exceeds the 24-byte record range")
    hot_all["vlen"] = a["vlen"]
    hot_all["voff"] = voff_local
    hot_all["action"] = a["action"]
    hot_all["elem_actor"] = a["key_actor"]
    hot_all["vcode"] = a["vcode"]
    hot_all["insert"] = a["insert"]
    obj_ctr = _c64(a["obj_ctr"])
    obj_actor = _c32(a["obj_actor"])
    obj_has = _c8(a["obj_has"])
    key_sid = (
        _c32(a["key_ids"]) if a["key_ids"] is not None else None
    )
    expand = _c8(a["expand"])
    value_int = _c64(a["value_int"])
    width = _c32(a["width"])
    mark_sid = (
        _c32(a["mark_ids"]) if a["mark_ids"] is not None else None
    )
    pred_num = _c32(a["pred_num"])
    pred_ctr = _c64(a["pred_ctr"])
    pred_actor = _c32(a["pred_actor"])
    key_table = a["key_table"]
    mark_table = a["mark_table"]

    # structured-dtype slice copies go through numpy's per-field slow path
    # (~17x a plain copy); copying through a flat byte view is a memcpy
    hot_bytes = hot_all.view(np.uint8).reshape(N, HOT_DTYPE.itemsize)

    out = []
    for c in range(n_changes):
        lo, hi = int(row_off[c]), int(row_off[c + 1])
        plo, phi = int(pred_row_off[c]), int(pred_row_off[c + 1])
        rlo = int(raw_off[c])
        cc = ChangeCols()
        cc.n = hi - lo
        cc.q = phi - plo
        cc.hot = hot_bytes[lo:hi].copy().view(HOT_DTYPE).reshape(cc.n)
        cc.obj_ctr = obj_ctr[lo:hi].copy()
        cc.obj_actor = obj_actor[lo:hi].copy()
        cc.obj_has = obj_has[lo:hi].copy()
        cc.key_sid = (
            key_sid[lo:hi].copy() if key_sid is not None else _neg1(cc.n)
        )
        cc.expand = expand[lo:hi].copy()
        cc.value_int = value_int[lo:hi].copy()
        cc.width = width[lo:hi].copy()
        cc.width_enc = enc
        cc.mark_sid = (
            mark_sid[lo:hi].copy() if mark_sid is not None else _neg1(cc.n)
        )
        cc.pred_num = pred_num[lo:hi].copy()
        cc.pred_ctr = pred_ctr[plo:phi].copy()
        cc.pred_actor = pred_actor[plo:phi].copy()
        cc.key_table = key_table if key_sid is not None else None
        cc.mark_table = mark_table if mark_sid is not None else None
        cc.vraw = raw[rlo : rlo + int(raw_ln[c])]
        cc._ptrs = None
        cc._const = None
        cc.rank_tab = None
        out.append(cc)
    return out


_UNIVERSE_IDS: Dict[bytes, int] = {}
_UNIVERSE_NEXT = [1]  # monotone: tokens never recycle, even across clears


def _universe_token(rank_of: Dict[bytes, int]) -> int:
    """Intern the actor universe (rank_of's keys are in rank order) to a
    small id; equal universes across merges share one token. The key is a
    LENGTH-PREFIXED join — actor ids are arbitrary bytes, so a separator
    join would be ambiguous — making token equality exact, with no
    hash/encoding collision corruption risk."""
    key = b"".join(
        len(a).to_bytes(4, "little") + a for a in rank_of
    )
    tok = _UNIVERSE_IDS.get(key)
    if tok is None:
        if len(_UNIVERSE_IDS) >= 4096:  # bound stale universes
            _UNIVERSE_IDS.clear()
        tok = _UNIVERSE_NEXT[0]
        _UNIVERSE_NEXT[0] += 1
        _UNIVERSE_IDS[key] = tok
    return tok


def _const_stacks(caches):
    """(li, mask_stack, value_stack) over non-empty changes — the shared
    input of _global_const and _per_change_const (assemble_log computes it
    once and threads it into both)."""
    li = np.asarray([i for i, cc in enumerate(caches) if cc.n > 0], np.int64)
    if not len(li):
        return li, np.zeros((0, 18), bool), np.zeros((0, 18), np.int64)
    scans = [caches[int(i)].const_scan() for i in li]
    ms = np.stack([m for m, _ in scans])
    vs = np.stack([v for _, v in scans])
    return li, ms, vs


def _global_const(
    caches, tab_all, tab_off, tab_size, prop_off, prop_size, prop_remap,
    mark_off, mark_size, mark_remap, total_raw, stacks=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Aggregate per-change constant columns into the assembler's global
    fill directives (see assemble.cpp g_flags docs): a column is fillable
    iff every non-empty change is constant AND agrees on the (translated)
    value."""
    g_flags = np.zeros(18, np.int64)
    g_vals = np.zeros(18, np.int64)
    li, ms, vs = stacks if stacks is not None else _const_stacks(caches)
    if not len(li):
        return g_flags, g_vals
    allc = ms.all(axis=0)
    same = (vs == vs[0]).all(axis=0)
    for k in (7, 8, 9, 10, 12, 13):
        if allc[k] and same[k]:
            g_flags[k] = 1
            g_vals[k] = vs[0, k]
    # voff is rebased by per-change raw offsets; fillable only when the
    # whole value heap is empty (then every local offset is 0)
    if allc[11] and same[11] and total_raw == 0:
        g_flags[11] = 1
        g_vals[11] = vs[0, 11]
    # object id: translate each change's constant (ctr, local actor, has)
    # through its actor table and require one global packed value
    if allc[1] and allc[2] and allc[3]:
        has = vs[:, 3] != 0
        oa = vs[:, 2]
        ts = tab_size[li]
        if ((~has) | ((oa >= 0) & (oa < ts))).all() and (
            (~has) | ((vs[:, 1] >= 0) & (vs[:, 1] < (1 << 43)))
        ).all():
            packed = np.where(
                has,
                (vs[:, 1] << ACTOR_BITS)
                | tab_all[(tab_off[li] + np.minimum(oa, ts - 1))],
                0,
            )
            if (packed == packed[0]).all():
                g_flags[1] = 1
                g_vals[1] = packed[0]
    # key_sid: all-seq (1) or one shared global map prop (2)
    if allc[4]:
        s = vs[:, 4]
        if (s == -1).all():
            g_flags[4] = 1
        elif (s >= 0).all():
            po = prop_off[li]
            if (po >= 0).all() and (s < prop_size[li]).all():
                gp = prop_remap[po + s]
                if (gp == gp[0]).all():
                    g_flags[4] = 2
                    g_vals[4] = gp[0]
    # mark name: none anywhere, or one shared global mark id
    if allc[14]:
        m = vs[:, 14]
        if (m == -1).all():
            g_flags[14] = 1
            g_vals[14] = -1
        elif (m >= 0).all():
            mo = mark_off[li]
            if (mo >= 0).all() and (m < mark_size[li]).all():
                gm = mark_remap[mo + m]
                if (gm == gm[0]).all():
                    g_flags[14] = 1
                    g_vals[14] = gm[0]
    return g_flags, g_vals


def _per_change_const(
    caches, tab_all, tab_off, tab_size, prop_off, prop_size, prop_remap,
    stacks=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-change constant shortcuts for the assembler's gather loop.

    Real changes overwhelmingly target ONE object and one key shape, so
    the per-row has/actor/ctr loads + actor-table translation collapse to
    a single C-array read even when the GLOBAL const scan fails (e.g. the
    make op itself rides in an early change). Returns:
      c_obj_key[c]: packed global object key every row of c targets
                    (0 = root), or -1 when the change's obj column varies;
      c_sid[c]:     -1 = every row seq-keyed, >= 0 = one global map prop,
                    -2 = varies.
    Vectorized over the (cached) per-change const scans.
    """
    C = len(caches)
    c_obj_key = np.full(C, -1, np.int64)
    c_sid = np.full(C, -2, np.int64)
    li, ms, vs = stacks if stacks is not None else _const_stacks(caches)
    empty = np.ones(C, bool)
    empty[li] = False
    c_obj_key[empty] = 0
    c_sid[empty] = -1
    if not len(li):
        return c_obj_key, c_sid

    # object: const has/actor/ctr columns -> one packed key per change
    oc = ms[:, 1] & ms[:, 2] & ms[:, 3]
    has = vs[:, 3] != 0
    oa = vs[:, 2]
    octr = vs[:, 1]
    ts = tab_size[li]
    valid = oc & ((~has) | ((oa >= 0) & (oa < ts) & (octr >= 0) & (octr < (1 << 43))))
    packed = np.where(
        has,
        (octr << ACTOR_BITS) | tab_all[tab_off[li] + np.clip(oa, 0, np.maximum(ts - 1, 0))],
        0,
    )
    c_obj_key[li[valid]] = packed[valid]

    # key sid: all-seq, or one prop remapped to its global id
    sc = ms[:, 4]
    s = vs[:, 4]
    seq = sc & (s == -1)
    c_sid[li[seq]] = -1
    po = prop_off[li]
    mp = sc & (s >= 0) & (po >= 0) & (s < prop_size[li])
    c_sid[li[mp]] = prop_remap[(po + np.clip(s, 0, None))[mp]]
    return c_obj_key, c_sid


def _remap_tables(
    caches: Sequence[ChangeCols], table_attr: str
) -> Tuple[List[str], np.ndarray, np.ndarray, np.ndarray]:
    """Union per-change string tables into one global table.

    Returns (global table, remap_all, off[c], size[c]) where
    remap_all[off[c] + local_id] = global id. Tables are memoized by
    object identity — synthesized/committed batches share one table
    object across thousands of changes, so the union is built once."""
    global_of: Dict[str, int] = {}
    remap_of_table: Dict[int, Tuple[int, int]] = {}  # id(table) -> (off, size)
    parts: List[np.ndarray] = []
    off = np.full(len(caches), -1, np.int64)
    size = np.zeros(len(caches), np.int64)
    pos = 0
    for c, cc in enumerate(caches):
        table = getattr(cc, table_attr)
        if table is None:
            continue
        key = id(table)
        hit = remap_of_table.get(key)
        if hit is None:
            remap = np.fromiter(
                (
                    global_of.setdefault(s, len(global_of))
                    for s in table
                ),
                dtype=np.int32,
                count=len(table),
            )
            parts.append(remap)
            hit = (pos, len(table))
            remap_of_table[key] = hit
            pos += len(table)
        off[c], size[c] = hit
    remap_all = (
        np.concatenate(parts) if parts else np.zeros(1, np.int32)
    )
    table_list = list(global_of)
    return table_list, _c32(remap_all), off, size


def ranked_from_caches(changes: Sequence, rank_of: Dict[bytes, int]):
    """extract.ranked_batch's output shape built from the commit-time
    ChangeCols caches — no chunk re-decode. Concat (change) order, packed
    ids rank-translated, string tables unioned globally. Serves the host
    flatten path (core/bulk_load.py) so a replica that just decoded its
    changes once never decodes them again for store rebuilds or stale
    reads.

    Semantic note vs ranked_batch: the cache schema encodes HEAD as
    elem_ctr == 0 (ChangeCols erases the has-actor flag at build — same
    convention the native assembler reads), while ranked_batch reads the
    has-actor flag from the raw chunk columns. The two agree on every
    well-formed chunk (op counters start at 1, so ctr 0 never names a
    real element); a malformed ctr-0-with-actor key decodes as HEAD here.
    The caller supplies rank_of (it also owns the actor-capacity check).
    """
    caches = ensure_change_cols(changes)
    C = len(caches)

    n_ops = np.fromiter((c.n for c in caches), np.int64, count=C)
    row_off = np.concatenate([[0], np.cumsum(n_ops)]).astype(np.int64)
    N = int(row_off[-1])
    cor = np.repeat(np.arange(C, dtype=np.int64), n_ops)
    start_op = np.fromiter((ch.start_op for ch in changes), np.int64, count=C)

    tab_parts = [[rank_of[bytes(x)] for x in ch.actors] for ch in changes]
    tab_size = np.fromiter((len(t) for t in tab_parts), np.int64, count=C)
    tab_off = np.concatenate([[0], np.cumsum(tab_size)])[:-1].astype(np.int64)
    tab_all = np.fromiter(
        (r for t in tab_parts for r in t), np.int64,
        count=int(tab_size.sum()),
    )
    author = tab_all[tab_off] if C else np.empty(0, np.int64)
    clip = max(len(tab_all) - 1, 0)

    def cat(field, dtype, sliced=False):
        """Concatenate one cached column across changes. ``sliced`` is for
        the sid columns whose backing buffer (the shared -1 filler) can
        exceed the change's row count."""
        if not C:
            return np.empty(0, dtype)
        if sliced:
            arrs = [getattr(c, field)[: c.n] for c in caches]
        else:
            arrs = [getattr(c, field) for c in caches]
        out = np.concatenate(arrs)
        return out if out.dtype == dtype else out.astype(dtype)

    within = np.arange(N, dtype=np.int64) - row_off[:-1][cor]
    id_key = ((start_op[cor] + within) << ACTOR_BITS) | author[cor]

    obj_has = cat("obj_has", np.bool_)
    obj_actor = cat("obj_actor", np.int64)
    obj_ctr = cat("obj_ctr", np.int64)
    if N and np.any(obj_actor[obj_has] >= tab_size[cor][obj_has]):
        raise AssembleError("actor index out of chunk-local table range")
    obj = np.where(
        obj_has,
        (obj_ctr << ACTOR_BITS)
        | tab_all[(tab_off[cor] + obj_actor).clip(max=clip)],
        np.int64(0),
    )

    key_tables, prop_remap, prop_off, _ = _remap_tables(caches, "key_table")
    sid = cat("key_sid", np.int64, sliced=True)
    any_keys = any(c.key_table is not None for c in caches)
    prop_ids = (
        np.where(
            sid >= 0,
            prop_remap[(prop_off[cor] + sid).clip(min=0, max=max(len(prop_remap) - 1, 0))],
            np.int32(-1),
        ).astype(np.int32)
        if any_keys
        else None
    )
    mark_tables, mark_remap, mark_off, _ = _remap_tables(caches, "mark_table")
    msid = cat("mark_sid", np.int64, sliced=True)
    any_marks = any(c.mark_table is not None for c in caches)
    mark_ids = (
        np.where(
            msid >= 0,
            mark_remap[(mark_off[cor] + msid).clip(min=0, max=max(len(mark_remap) - 1, 0))],
            np.int32(-1),
        ).astype(np.int32)
        if any_marks
        else None
    )

    elem_ctr = cat("elem_ctr", np.int64)
    elem_actor = cat("elem_actor", np.int64)
    if N:
        seq_rows = sid < 0
        if np.any(
            (elem_ctr[seq_rows] != 0)
            & (elem_actor[seq_rows] >= tab_size[cor][seq_rows])
        ):
            raise AssembleError("actor index out of chunk-local table range")
    elem = np.where(
        sid >= 0,
        np.int64(-1),
        np.where(
            elem_ctr == 0,
            np.int64(0),
            (elem_ctr << ACTOR_BITS)
            | tab_all[(tab_off[cor] + elem_actor).clip(max=clip)],
        ),
    )

    q_ops = np.fromiter((c.q for c in caches), np.int64, count=C)
    pred_row_off = np.concatenate([[0], np.cumsum(q_ops)]).astype(np.int64)
    Q = int(pred_row_off[-1])
    pred_num = cat("pred_num", np.int64)
    pred_src = np.repeat(np.arange(N, dtype=np.int64), pred_num)
    corq = np.repeat(np.arange(C, dtype=np.int64), q_ops)
    pred_ctr = (
        np.concatenate([np.asarray(c.pred_ctr, np.int64) for c in caches])
        if C
        else np.empty(0, np.int64)
    )
    pred_actor = (
        np.concatenate([np.asarray(c.pred_actor, np.int64) for c in caches])
        if C
        else np.empty(0, np.int64)
    )
    if Q and np.any(pred_actor >= tab_size[corq]):
        raise AssembleError("pred actor index out of chunk-local table range")
    pred_key = (pred_ctr << ACTOR_BITS) | tab_all[
        (tab_off[corq] + pred_actor).clip(max=clip)
    ]

    raw_ln = np.fromiter((len(c.vraw) for c in caches), np.int64, count=C)
    raw_off = np.concatenate([[0], np.cumsum(raw_ln)])[:-1].astype(np.int64)
    vraw = b"".join(c.vraw for c in caches)
    voff = cat("voff", np.int64) + raw_off[cor]

    a = {
        "n": N,
        "n_ops": n_ops,
        "row_off": row_off,
        "raw_off": raw_off,
        "raw_ln": raw_ln,
        "change_of_row": cor,
        "action": cat("action", np.int32),
        "insert": cat("insert", np.bool_),
        "expand": cat("expand", np.bool_),
        "vcode": cat("vcode", np.int32),
        "voff": voff,
        "vlen": cat("vlen", np.int64),
        "vraw": vraw,
        "value_int": cat("value_int", np.int64),
        "width": cat("width", np.int32),
        "key_ids": prop_ids,
        "key_table": key_tables,
        "mark_ids": mark_ids,
        "mark_table": mark_tables,
        "pred_num": pred_num,
        "pred_ctr": pred_ctr,
        "pred_actor": pred_actor,
        "pred_row_off": pred_row_off,
        "key_has_actor": None,  # consumed pre-translation only
        "key_ctr": None,
        "key_actor": None,
        "obj_ctr": obj_ctr,
        "obj_actor": obj_actor,
        "obj_has": obj_has,
    }
    return {
        "a": a,
        "id_key": id_key,
        "obj": obj,
        "prop_ids": prop_ids if prop_ids is not None else np.full(N, -1, np.int32),
        "elem": elem,
        "pred_src": pred_src,
        "pred_key": pred_key,
        "rank_of": rank_of,
    }


def assemble_log(log, changes: Sequence, rank_of: Dict[bytes, int]):
    """Fill ``log`` (an empty OpLog with actors/changes set) from cached
    per-change columns via the native assembler. Raises AssembleError on
    anything the C fast path rejects; callers fall back to the decode
    paths, which report canonical errors for malformed input."""
    lib = native.load()
    if lib is None or not hasattr(lib, "am_assemble_log"):
        raise native.NativeUnavailable("native assembler not available")
    caches = ensure_change_cols(changes)
    C = len(caches)
    n_ops = np.fromiter((c.n for c in caches), np.int64, count=C)
    q_ops = np.fromiter((c.q for c in caches), np.int64, count=C)
    N = int(n_ops.sum())
    Q = int(q_ops.sum())
    start_op = np.fromiter((ch.start_op for ch in changes), np.int64, count=C)
    if N and int((start_op + n_ops).max()) - 1 >= (1 << 43):
        raise AssembleError("counter outside packed range")

    # per-merge actor translation: chunk-local index -> global rank.
    # The translated table is memoized on the cache keyed by the actor
    # UNIVERSE (rank_of's sorted key join, interned to a token so the key
    # comparison is one int, not a byte-string compare per change) —
    # repeated merges over the same replica set skip the per-actor dict
    # lookups entirely.
    rank_token = _universe_token(rank_of)
    tab_parts = []
    for ch, cc in zip(changes, caches):
        rt = cc.rank_tab
        if rt is not None and rt[0] == rank_token:
            tab_parts.append(rt[1])
        else:
            t = [rank_of[bytes(a)] for a in ch.actors]
            cc.rank_tab = (rank_token, t)
            tab_parts.append(t)
    tab_size = np.fromiter((len(t) for t in tab_parts), np.int64, count=C)
    tab_off = np.concatenate([[0], np.cumsum(tab_size)])[:-1].astype(np.int64)
    tab_all = np.fromiter(
        (r for t in tab_parts for r in t), np.int64, count=int(tab_size.sum())
    )
    author = tab_all[tab_off] if C else np.empty(0, np.int64)

    props, prop_remap, prop_off, prop_size = _remap_tables(caches, "key_table")
    marks, mark_remap, mark_off, mark_size = _remap_tables(caches, "mark_table")

    # value raw heap: concatenate per-change buffers; C rebases offsets
    raw_base = np.zeros(C, np.int64)
    pos = 0
    for c, cc in enumerate(caches):
        raw_base[c] = pos
        pos += len(cc.vraw)
    raw_all = b"".join(cc.vraw for cc in caches)

    col_ptrs = np.empty((C, 19), np.int64)
    for c, cc in enumerate(caches):
        col_ptrs[c] = cc.ptr_row()

    stacks = _const_stacks(caches)
    g_flags, g_vals = _global_const(
        caches, tab_all, tab_off, tab_size, prop_off, prop_size, prop_remap,
        mark_off, mark_size, mark_remap, len(raw_all), stacks=stacks,
    )
    c_obj_key, c_sid = _per_change_const(
        caches, tab_all, tab_off, tab_size, prop_off, prop_size, prop_remap,
        stacks=stacks,
    )

    # outputs
    id_key = np.empty(N, np.int64)
    obj_key = np.empty(N, np.int64)
    prop = np.empty(N, np.int32)
    action = np.empty(N, np.int32)
    insert = np.empty(N, np.uint8)
    expand = np.empty(N, np.uint8)
    value_tag = np.empty(N, np.int32)
    value_int = np.empty(N, np.int64)
    width = np.empty(N, np.int32)
    mark_idx = np.empty(N, np.int32)
    vcode = np.empty(N, np.int32)
    voff = np.empty(N, np.int64)
    vlen = np.empty(N, np.int64)
    elem_ref = np.empty(N, np.int32)
    obj_dense = np.empty(N, np.int32)
    pred_src = np.empty(max(Q, 1), np.int32)
    pred_tgt = np.empty(max(Q, 1), np.int32)
    obj_table_buf = np.empty(N + 1, np.int64)
    out_meta = np.zeros(4, np.int64)

    if N:
        rc = lib.am_assemble_log(
            native._i64(n_ops), native._i64(q_ops), native._i64(start_op),
            native._i64(author), native._i64(tab_off), native._i64(tab_size),
            native._i64(prop_off), native._i64(prop_size),
            native._i64(mark_off), native._i64(mark_size),
            native._i64(raw_base), native._i64(col_ptrs.reshape(-1)), C,
            native._i64(tab_all), native._i32(prop_remap),
            native._i32(mark_remap), ACTOR_BITS,
            native._i64(g_flags), native._i64(g_vals),
            native._i64(c_obj_key), native._i64(c_sid),
            native._i64(id_key), native._i64(obj_key), native._i32(prop),
            native._i32(action), native._u8(insert), native._u8(expand),
            native._i32(value_tag), native._i64(value_int),
            native._i32(width), native._i32(mark_idx), native._i32(vcode),
            native._i64(voff), native._i64(vlen), native._i32(elem_ref),
            native._i32(obj_dense), N,
            native._i32(pred_src), native._i32(pred_tgt), Q,
            native._i64(obj_table_buf), native._i64(out_meta),
        )
        if rc < 0:
            raise AssembleError(f"native assembler rejected input ({rc})")
    else:
        rc = 0
        obj_table_buf[0] = 0
        out_meta[0] = 1

    from .extract import LazyValues

    log.n = N
    log.props = props
    log.mark_names = marks
    log.id_key = id_key
    log.obj_key = obj_key
    log.prop = prop
    log.action = action
    log.insert = insert.view(np.bool_)
    log.expand = expand.view(np.bool_)
    log.value_tag = value_tag
    log.value_int = value_int
    log.width = width
    log.mark_name_idx = mark_idx
    log.values = LazyValues(vcode, voff, vlen, raw_all)
    log.elem_ref = elem_ref
    log.pred_src = pred_src[:Q]
    log.pred_tgt = pred_tgt[:Q]
    if rc == 1:
        # partial history: some object id has no make op in this log —
        # fall back to the exact unique, still unioned with the make ids
        # so childless objects resolve identically on both paths
        # (mirrors oplog._finalize)
        from .oplog import MAKE_ACTIONS

        make_ids = id_key[np.isin(action, MAKE_ACTIONS)]
        obj_table = np.unique(np.concatenate([[0], make_ids, obj_key]))
        log.obj_table = obj_table
        log.obj_dense = np.searchsorted(obj_table, obj_key).astype(np.int32)
        log.n_objs = len(obj_table)
    else:
        log.n_objs = int(out_meta[0])
        log.obj_table = obj_table_buf[: log.n_objs].copy()
        log.obj_dense = obj_dense
    from .oplog import ELEM_MISSING

    log.n_miss_elem = int(np.count_nonzero(log.elem_ref == ELEM_MISSING))
    log.n_miss_pred = int(np.count_nonzero(log.pred_tgt < 0))
    return log
