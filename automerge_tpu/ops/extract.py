"""Vectorized change-column extraction: chunk bytes -> numpy op columns.

The north-star load path (BASELINE.json): instead of materializing one
Python ChangeOp per op and walking them into the op log, the change
chunk's own columnar encoding (reference: change/change_op_columns.rs) is
decoded straight into numpy arrays by the native codec core
(automerge_tpu/native/codecs.cpp) and assembled into the device column
layout. Strings (map keys, mark names) stay on the host path; scalar
payloads are kept as (type_code, offset, length) views into the raw value
buffer and materialized lazily on readback.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import native
from ..storage.change import (
    COL_ACTION,
    COL_EXPAND,
    COL_INSERT,
    COL_KEY_ACTOR,
    COL_KEY_CTR,
    COL_KEY_STR,
    COL_MARK_NAME,
    COL_OBJ_ACTOR,
    COL_OBJ_CTR,
    COL_PRED_ACTOR,
    COL_PRED_CTR,
    COL_PRED_GROUP,
    COL_VAL_META,
    COL_VAL_RAW,
    StoredChange,
)
from ..types import ScalarValue
from ..utils.codecs import rle_decode
from ..utils.leb128 import decode_sleb, decode_uleb

# value-metadata type codes (storage/values.py) — identical to the OpLog
# TAG_* codes for 0..9; anything else maps to TAG_UNKNOWN at readback
_CODE_ULEB = 3
_INT_CODES = (3, 4, 8, 9)  # uint, int, counter, timestamp


class ExtractError(ValueError):
    pass


def change_arrays(change: StoredChange) -> Dict[str, np.ndarray]:
    """Decode one change's op columns to arrays (chunk-local actor idxs)."""
    cols = change.op_col_data
    if cols is None:
        raise ExtractError("change has no retained column data")

    def col(spec) -> bytes:
        return cols.get(spec, b"")

    n = len(change.ops)
    cap = n + 1

    action, amask = native.rle_decode_array(col(COL_ACTION), False, cap)
    if len(action) != n or not amask.all():
        raise ExtractError("action column mismatch")
    obj_ctr, obj_mask = _padded(*native.rle_decode_array(col(COL_OBJ_CTR), False, cap), n)
    obj_actor, obj_amask = _padded(*native.rle_decode_array(col(COL_OBJ_ACTOR), False, cap), n)
    key_ctr, key_ctr_mask = _padded(*native.delta_decode_array(col(COL_KEY_CTR), cap), n)
    key_actor, key_actor_mask = _padded(
        *native.rle_decode_array(col(COL_KEY_ACTOR), False, cap), n
    )
    insert = _padded_bool(native.bool_decode_array(col(COL_INSERT), cap), n)
    expand = _padded_bool(native.bool_decode_array(col(COL_EXPAND), cap), n)
    meta, meta_mask = _padded(*native.rle_decode_array(col(COL_VAL_META), False, cap), n)
    meta = np.where(meta_mask, meta, 0)

    pred_num, pn_mask = _padded(*native.rle_decode_array(col(COL_PRED_GROUP), False, cap), n)
    pred_num = np.where(pn_mask, pred_num, 0)
    total_preds = int(pred_num.sum())
    pred_ctr, pc_mask = native.delta_decode_array(col(COL_PRED_CTR), total_preds + 1)
    pred_actor, pa_mask = native.rle_decode_array(col(COL_PRED_ACTOR), False, total_preds + 1)
    if len(pred_ctr) < total_preds or len(pred_actor) < total_preds:
        raise ExtractError("truncated pred columns")
    if total_preds and not (pc_mask[:total_preds].all() and pa_mask[:total_preds].all()):
        raise ExtractError("null pred entries")

    # value payloads: code + (offset, length) views into the raw buffer
    raw = cols.get(COL_VAL_RAW, b"")
    vcode = (meta & 0xF).astype(np.int32)
    vlen = (meta >> 4).astype(np.int64)
    voff = np.concatenate([[0], np.cumsum(vlen)])[:-1]
    if len(vlen) and int(voff[-1] + vlen[-1]) > len(raw):
        raise ExtractError("value raw column overrun")

    # integer payloads (uint/int/counter/timestamp + booleans) decoded now —
    # the kernel needs them; str/bytes/f64 stay lazy
    value_int = np.zeros(n, np.int64)
    int_rows = np.flatnonzero(np.isin(vcode, _INT_CODES) & (vlen > 0))
    for r in int_rows:
        o = int(voff[r])
        if vcode[r] == _CODE_ULEB:
            value_int[r], _ = decode_uleb(raw, o)
        else:
            value_int[r], _ = decode_sleb(raw, o)
    value_int[vcode == 2] = 1  # true

    # utf-8 char widths for string values, vectorized over the raw buffer
    width = np.ones(n, np.int32)
    if len(raw):
        rb = np.frombuffer(raw, np.uint8)
        cont = np.concatenate([[0], np.cumsum((rb & 0xC0) == 0x80)])
        srows = vcode == 6
        width[srows] = (
            vlen[srows] - (cont[(voff + vlen)[srows]] - cont[voff[srows]])
        ).astype(np.int32)

    # string-ish host columns (map keys, mark names): python decode, cheap
    # because RLE runs collapse repeats; None = entirely-null column (the
    # common case for text workloads), letting callers skip per-row work
    ks_bytes = col(COL_KEY_STR)
    if ks_bytes:
        key_str = rle_decode(ks_bytes, "str", n)
        key_str += [None] * (n - len(key_str))
    else:
        key_str = None
    mn_bytes = col(COL_MARK_NAME)
    if mn_bytes:
        mark_name = rle_decode(mn_bytes, "str", n)
        mark_name += [None] * (n - len(mark_name))
    else:
        mark_name = None

    return {
        "n": n,
        "action": action.astype(np.int32),
        "obj_ctr": np.where(obj_mask, obj_ctr, 0),
        "obj_has": obj_mask & obj_amask,
        "obj_actor": np.where(obj_amask, obj_actor, 0),
        "key_ctr": np.where(key_ctr_mask, key_ctr, -1),
        "key_has_ctr": key_ctr_mask,
        "key_actor": np.where(key_actor_mask, key_actor, 0),
        "key_has_actor": key_actor_mask,
        "key_str": key_str,
        "insert": insert,
        "expand": expand,
        "vcode": vcode,
        "voff": voff.astype(np.int64),
        "vlen": vlen,
        "vraw": raw,
        "value_int": value_int,
        "width": width,
        "pred_num": pred_num.astype(np.int64),
        "pred_ctr": pred_ctr[:total_preds],
        "pred_actor": pred_actor[:total_preds],
        "mark_name": mark_name,
    }


def _padded(vals: np.ndarray, mask: np.ndarray, n: int):
    if len(vals) > n:
        raise ExtractError("column longer than op count")
    if len(vals) < n:
        vals = np.concatenate([vals, np.zeros(n - len(vals), vals.dtype)])
        mask = np.concatenate([mask, np.zeros(n - len(mask), bool)])
    return vals, mask


def _padded_bool(vals: np.ndarray, n: int) -> np.ndarray:
    if len(vals) > n:
        raise ExtractError("boolean column longer than op count")
    if len(vals) < n:
        vals = np.concatenate([vals, np.zeros(n - len(vals), bool)])
    return vals.astype(bool)


_TAG_NAME = {
    0: "null",
    3: "uint",
    4: "int",
    5: "f64",
    6: "str",
    7: "bytes",
    8: "counter",
    9: "timestamp",
}


class LazyValues:
    """Row -> ScalarValue, materialized on demand from the raw value buffer.

    Drop-in for the eager python list the slow extraction path produces.
    """

    __slots__ = ("code", "off", "ln", "raw", "cache")

    def __init__(self, code: np.ndarray, off: np.ndarray, ln: np.ndarray, raw: bytes):
        self.code = code
        self.off = off
        self.ln = ln
        self.raw = raw
        self.cache: Dict[int, ScalarValue] = {}

    def __len__(self) -> int:
        return len(self.code)

    def __getitem__(self, row: int) -> ScalarValue:
        v = self.cache.get(row)
        if v is None:
            v = self._decode(row)
            self.cache[row] = v
        return v

    def _decode(self, row: int) -> ScalarValue:
        import struct

        code = int(self.code[row])
        o = int(self.off[row])
        ln = int(self.ln[row])
        chunk = self.raw[o : o + ln]
        if code == 0:
            return ScalarValue("null")
        if code == 1:
            return ScalarValue("bool", False)
        if code == 2:
            return ScalarValue("bool", True)
        if code == 3:
            return ScalarValue("uint", decode_uleb(chunk, 0)[0])
        if code == 4:
            return ScalarValue("int", decode_sleb(chunk, 0)[0])
        if code == 5:
            return ScalarValue("f64", struct.unpack("<d", chunk)[0])
        if code == 6:
            return ScalarValue("str", chunk.decode("utf-8"))
        if code == 7:
            return ScalarValue("bytes", chunk)
        if code == 8:
            return ScalarValue("counter", decode_sleb(chunk, 0)[0])
        if code == 9:
            return ScalarValue("timestamp", decode_sleb(chunk, 0)[0])
        return ScalarValue("unknown", (code, chunk))
