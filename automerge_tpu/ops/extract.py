"""Vectorized change-column extraction: chunk bytes -> numpy op columns.

The north-star load path (BASELINE.json): instead of materializing one
Python ChangeOp per op and walking them into the op log, the change
chunk's own columnar encoding (reference: change/change_op_columns.rs) is
decoded straight into numpy arrays by the native codec core
(automerge_tpu/native/codecs.cpp) and assembled into the device column
layout. Strings (map keys, mark names) stay on the host path; scalar
payloads are kept as (type_code, offset, length) views into the raw value
buffer and materialized lazily on readback.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from .. import native
from ..storage.change import (
    COL_ACTION,
    COL_EXPAND,
    COL_INSERT,
    COL_KEY_ACTOR,
    COL_KEY_CTR,
    COL_KEY_STR,
    COL_MARK_NAME,
    COL_OBJ_ACTOR,
    COL_OBJ_CTR,
    COL_PRED_ACTOR,
    COL_PRED_CTR,
    COL_PRED_GROUP,
    COL_VAL_META,
    COL_VAL_RAW,
    StoredChange,
)
from ..types import ScalarValue
from ..utils.codecs import rle_decode
from ..utils.leb128 import decode_sleb, decode_uleb

# value-metadata type codes (storage/values.py) — identical to the OpLog
# TAG_* codes for 0..9; anything else maps to TAG_UNKNOWN at readback
_CODE_ULEB = 3
_INT_CODES = (3, 4, 8, 9)  # uint, int, counter, timestamp


from ..errors import AutomergeError


def _str_widths(raw: bytes, voff, vlen, vcode, n) -> "np.ndarray":
    """Per-row text widths in the configured index unit, vectorized over
    the raw value buffer (reference: text_value.rs width-per-encoding)."""
    from ..types import get_text_encoding

    width = np.ones(n, np.int32)
    if not len(raw):
        return width
    srows = vcode == 6
    enc = get_text_encoding()
    if enc == "utf8":
        width[srows] = vlen[srows].astype(np.int32)
        return width
    rb = np.frombuffer(raw, np.uint8)
    cont = np.concatenate([[0], np.cumsum((rb & 0xC0) == 0x80)])
    cps = (vlen[srows] - (cont[(voff + vlen)[srows]] - cont[voff[srows]])).astype(
        np.int32
    )
    if enc == "utf16":
        # supplementary-plane code points (4-byte UTF-8) take two units
        supp = np.concatenate([[0], np.cumsum((rb & 0xF8) == 0xF0)])
        cps = cps + (supp[(voff + vlen)[srows]] - supp[voff[srows]]).astype(np.int32)
    width[srows] = cps
    return width


class ExtractError(AutomergeError):
    pass


def change_arrays(change: StoredChange) -> Dict[str, np.ndarray]:
    """Decode one change's op columns to arrays (chunk-local actor idxs)."""
    cols = change.op_col_data
    if cols is None:
        raise ExtractError("change has no retained column data")

    def col(spec) -> bytes:
        return cols.get(spec, b"")

    n = len(change.ops)
    cap = n + 1

    action, amask = native.rle_decode_array(col(COL_ACTION), False, cap)
    if len(action) != n or not amask.all():
        raise ExtractError("action column mismatch")
    obj_ctr, obj_mask = _padded(*native.rle_decode_array(col(COL_OBJ_CTR), False, cap), n)
    obj_actor, obj_amask = _padded(*native.rle_decode_array(col(COL_OBJ_ACTOR), False, cap), n)
    key_ctr, key_ctr_mask = _padded(*native.delta_decode_array(col(COL_KEY_CTR), cap), n)
    key_actor, key_actor_mask = _padded(
        *native.rle_decode_array(col(COL_KEY_ACTOR), False, cap), n
    )
    insert = _padded_bool(native.bool_decode_array(col(COL_INSERT), cap), n)
    expand = _padded_bool(native.bool_decode_array(col(COL_EXPAND), cap), n)
    meta, meta_mask = _padded(*native.rle_decode_array(col(COL_VAL_META), False, cap), n)
    meta = np.where(meta_mask, meta, 0)

    pred_num, pn_mask = _padded(*native.rle_decode_array(col(COL_PRED_GROUP), False, cap), n)
    pred_num = np.where(pn_mask, pred_num, 0)
    total_preds = int(pred_num.sum())
    pred_ctr, pc_mask = native.delta_decode_array(col(COL_PRED_CTR), total_preds + 1)
    pred_actor, pa_mask = native.rle_decode_array(col(COL_PRED_ACTOR), False, total_preds + 1)
    if len(pred_ctr) < total_preds or len(pred_actor) < total_preds:
        raise ExtractError("truncated pred columns")
    if total_preds and not (pc_mask[:total_preds].all() and pa_mask[:total_preds].all()):
        raise ExtractError("null pred entries")

    # value payloads: code + (offset, length) views into the raw buffer
    raw = cols.get(COL_VAL_RAW, b"")
    vcode = (meta & 0xF).astype(np.int32)
    vlen = (meta >> 4).astype(np.int64)
    voff = np.concatenate([[0], np.cumsum(vlen)])[:-1]
    if len(vlen) and int(voff[-1] + vlen[-1]) > len(raw):
        raise ExtractError("value raw column overrun")

    # integer payloads (uint/int/counter/timestamp + booleans) decoded now —
    # the kernel needs them; str/bytes/f64 stay lazy
    value_int = np.zeros(n, np.int64)
    int_rows = np.flatnonzero(np.isin(vcode, _INT_CODES) & (vlen > 0))
    for r in int_rows:
        o = int(voff[r])
        if vcode[r] == _CODE_ULEB:
            value_int[r], _ = decode_uleb(raw, o)
        else:
            value_int[r], _ = decode_sleb(raw, o)
    value_int[vcode == 2] = 1  # true

    width = _str_widths(raw, voff, vlen, vcode, n)

    # string-ish host columns (map keys, mark names): python decode, cheap
    # because RLE runs collapse repeats; None = entirely-null column (the
    # common case for text workloads), letting callers skip per-row work
    ks_bytes = col(COL_KEY_STR)
    if ks_bytes:
        key_str = rle_decode(ks_bytes, "str", n)
        key_str += [None] * (n - len(key_str))
    else:
        key_str = None
    mn_bytes = col(COL_MARK_NAME)
    if mn_bytes:
        mark_name = rle_decode(mn_bytes, "str", n)
        mark_name += [None] * (n - len(mark_name))
    else:
        mark_name = None

    return {
        "n": n,
        "action": action.astype(np.int32),
        "obj_ctr": np.where(obj_mask, obj_ctr, 0),
        "obj_has": obj_mask & obj_amask,
        "obj_actor": np.where(obj_amask, obj_actor, 0),
        "key_ctr": np.where(key_ctr_mask, key_ctr, -1),
        "key_has_ctr": key_ctr_mask,
        "key_actor": np.where(key_actor_mask, key_actor, 0),
        "key_has_actor": key_actor_mask,
        "key_str": key_str,
        "insert": insert,
        "expand": expand,
        "vcode": vcode,
        "voff": voff.astype(np.int64),
        "vlen": vlen,
        "vraw": raw,
        "value_int": value_int,
        "width": width,
        "pred_num": pred_num.astype(np.int64),
        "pred_ctr": pred_ctr[:total_preds],
        "pred_actor": pred_actor[:total_preds],
        "mark_name": mark_name,
    }


def _col_batch(changes, spec):
    """(concatenated bytes, per-change offsets, per-change lengths)."""
    parts = []
    off = np.empty(len(changes), np.int64)
    ln = np.empty(len(changes), np.int64)
    pos = 0
    for i, ch in enumerate(changes):
        b = ch.op_col_data.get(spec, b"")
        off[i] = pos
        ln[i] = len(b)
        pos += len(b)
        parts.append(b)
    return b"".join(parts), off, ln


def _np_u8(buf: bytes) -> np.ndarray:
    return np.frombuffer(buf, np.uint8) if len(buf) else np.zeros(1, np.uint8)


_POOL = None
_POOL_INIT = False
_POOL_LOCK = threading.Lock()


def _decode_pool():
    """Shared column-decode thread pool, or None on effectively-single-CPU
    hosts (scheduler affinity, not raw core count — cgroup-limited
    containers report many cpu_count cores they cannot use)."""
    global _POOL, _POOL_INIT
    if not _POOL_INIT:
        with _POOL_LOCK:
            if _POOL_INIT:  # lost the race; another thread built it
                return _POOL
            import os

            try:
                n = len(os.sched_getaffinity(0))
            except AttributeError:  # non-Linux
                n = os.cpu_count() or 1
            if n > 1:
                from concurrent.futures import ThreadPoolExecutor

                _POOL = ThreadPoolExecutor(
                    max_workers=min(8, n), thread_name_prefix="am-decode"
                )
            _POOL_INIT = True
    return _POOL



def _strtab_decode(buf: bytes, off, ln, row_off, nc: int, n_rows: int):
    """Drive am_rle_decode_batch_strtab: (ids per row, string table)."""
    lib = native.load()
    ids = np.empty(max(n_rows, 1), np.int32)
    max_tab = 1 << 20
    tab_off = np.empty(max_tab, np.int64)
    tab_len = np.empty(max_tab, np.int64)
    bufa = _np_u8(buf)
    tn = lib.am_rle_decode_batch_strtab(
        native._u8(bufa), native._i64(off), native._i64(ln),
        native._i64(row_off), nc, native._i32(ids), native._i64(tab_off),
        native._i64(tab_len), max_tab,
    )
    if tn < 0:
        raise ExtractError(f"malformed string column ({tn})")
    table = [
        buf[int(tab_off[i]) : int(tab_off[i]) + int(tab_len[i])].decode("utf-8")
        for i in range(tn)
    ]
    return ids[:n_rows], table


def batch_arrays(changes) -> Dict[str, object]:
    """Decode ALL changes' op columns in one native pass per column kind.

    Output rows are change-concatenated (same order the one-change-at-a-time
    path produced); actor columns still carry chunk-local indices — the
    caller translates them with one table gather (ops/oplog.py).
    """
    import ctypes

    lib = native.load()
    if lib is None:
        raise native.NativeUnavailable("native codecs not available")
    nc = len(changes)
    n_ops = np.asarray([len(ch.ops) for ch in changes], np.int64)
    for ch in changes:
        if ch.op_col_data is None:
            raise ExtractError("change has no retained column data")
    row_off = np.concatenate([[0], np.cumsum(n_ops)]).astype(np.int64)
    N = int(row_off[-1])

    def rle(spec, signed=False):
        buf, off, ln = _col_batch(changes, spec)
        out = np.empty(max(N, 1), np.int64)
        mask = np.empty(max(N, 1), np.uint8)
        rc = lib.am_rle_decode_batch(
            native._u8(_np_u8(buf)), native._i64(off), native._i64(ln),
            native._i64(row_off), nc, int(signed), native._i64(out),
            native._u8(mask),
        )
        if rc != 0:
            raise ExtractError(f"malformed column {spec} in change {-rc - 1}")
        return out[:N], mask[:N].astype(bool)

    def delta(spec):
        buf, off, ln = _col_batch(changes, spec)
        out = np.empty(max(N, 1), np.int64)
        mask = np.empty(max(N, 1), np.uint8)
        rc = lib.am_delta_decode_batch(
            native._u8(_np_u8(buf)), native._i64(off), native._i64(ln),
            native._i64(row_off), nc, native._i64(out), native._u8(mask),
        )
        if rc != 0:
            raise ExtractError(f"malformed column {spec} in change {-rc - 1}")
        return out[:N], mask[:N].astype(bool)

    def boolean(spec):
        buf, off, ln = _col_batch(changes, spec)
        out = np.empty(max(N, 1), np.uint8)
        rc = lib.am_bool_decode_batch(
            native._u8(_np_u8(buf)), native._i64(off), native._i64(ln),
            native._i64(row_off), nc, native._u8(out),
        )
        if rc != 0:
            raise ExtractError(f"malformed column {spec} in change {-rc - 1}")
        return out[:N].astype(bool)

    def strtab(spec):
        buf, off, ln = _col_batch(changes, spec)
        if not len(buf):
            return None, []
        return _strtab_decode(buf, off, ln, row_off, nc, N)

    # One task list, two execution strategies: on multi-core hosts the
    # independent column decodes overlap in the shared thread pool (the
    # Python byte assembly holds the GIL but every native decode releases
    # it via ctypes); effectively-single-core hosts (cgroup affinity, like
    # the bench box) run the same list serially — a pool there is pure
    # overhead.
    tasks = [
        (rle, COL_ACTION), (rle, COL_OBJ_CTR), (rle, COL_OBJ_ACTOR),
        (delta, COL_KEY_CTR), (rle, COL_KEY_ACTOR), (boolean, COL_INSERT),
        (boolean, COL_EXPAND), (rle, COL_VAL_META), (strtab, COL_KEY_STR),
        (strtab, COL_MARK_NAME), (rle, COL_PRED_GROUP),
    ]
    # small batches (incremental deltas) run serially: the pool's submit/
    # wait round-trip costs more than the decodes themselves below ~16k ops
    pool = _decode_pool() if N >= (1 << 14) else None
    if pool is not None:
        futs = [pool.submit(fn, spec) for fn, spec in tasks]
        results = [f.result() for f in futs]
    else:
        results = [fn(spec) for fn, spec in tasks]
    (
        (action, amask), (obj_ctr, obj_mask), (obj_actor, obj_amask),
        (key_ctr, key_ctr_mask), (key_actor, key_actor_mask), insert,
        expand, (meta, meta_mask), (key_ids, key_table),
        (mark_ids, mark_table), (pred_num, pn_mask),
    ) = results
    if not amask.all():
        raise ExtractError("action column mismatch")
    meta = np.where(meta_mask, meta, 0)
    pred_num = np.where(pn_mask, pred_num, 0)
    pn_cum = np.concatenate([[0], np.cumsum(pred_num)]).astype(np.int64)
    per_change_preds = pn_cum[row_off[1:]] - pn_cum[row_off[:-1]]
    pred_row_off = np.concatenate([[0], np.cumsum(per_change_preds)]).astype(np.int64)
    Q = int(pred_row_off[-1])

    def pred_col(spec, is_delta):
        buf, off, ln = _col_batch(changes, spec)
        out = np.empty(max(Q, 1), np.int64)
        mask = np.empty(max(Q, 1), np.uint8)
        fn = lib.am_delta_decode_batch if is_delta else None
        if is_delta:
            rc = lib.am_delta_decode_batch(
                native._u8(_np_u8(buf)), native._i64(off), native._i64(ln),
                native._i64(pred_row_off), nc, native._i64(out), native._u8(mask),
            )
        else:
            rc = lib.am_rle_decode_batch(
                native._u8(_np_u8(buf)), native._i64(off), native._i64(ln),
                native._i64(pred_row_off), nc, 0, native._i64(out),
                native._u8(mask),
            )
        if rc != 0:
            raise ExtractError(f"malformed pred column {spec} in change {-rc - 1}")
        if Q and not mask[:Q].all():
            raise ExtractError("null pred entries")
        return out[:Q]

    pred_ctr = pred_col(COL_PRED_CTR, True)
    pred_actor = pred_col(COL_PRED_ACTOR, False)

    # value payloads: per-change raw buffers concatenated; offsets rebased
    raw, raw_off, raw_ln = _col_batch(changes, COL_VAL_RAW)
    vcode = (meta & 0xF).astype(np.int32)
    vlen = (meta >> 4).astype(np.int64)
    change_of_row = np.repeat(np.arange(nc), n_ops)
    vend = np.cumsum(vlen)
    voff = vend - vlen
    # rebase per change: local offset + that change's slice start in `raw`
    base = np.zeros(nc, np.int64)
    if N:
        base_local = voff[row_off[:-1].clip(max=max(N - 1, 0))]
        base_local[n_ops == 0] = 0
        base = base_local
    voff = voff - base[change_of_row] + raw_off[change_of_row]
    limit = (raw_off + raw_ln)[change_of_row]
    if N and np.any(voff + vlen > limit):
        raise ExtractError("value raw column overrun")

    # integer payloads (the kernel needs them eagerly)
    value_int = np.empty(max(N, 1), np.int64)
    rawa = _np_u8(raw)
    rc = lib.am_leb_decode_rows(
        native._u8(rawa), len(raw), native._i64(voff), native._i64(vlen),
        native._i32(vcode), N, native._i64(value_int),
    )
    if rc != 0:
        raise ExtractError(f"bad integer value payload at row {-rc - 1}")
    value_int = value_int[:N]

    width = _str_widths(raw, voff, vlen, vcode, N)

    return {
        "n": N,
        "n_ops": n_ops,
        "row_off": row_off,
        "raw_off": raw_off,
        "raw_ln": raw_ln,
        "change_of_row": change_of_row,
        "action": action.astype(np.int32),
        "obj_ctr": np.where(obj_mask, obj_ctr, 0),
        "obj_has": obj_mask & obj_amask,
        "obj_actor": np.where(obj_amask, obj_actor, 0),
        "key_ctr": np.where(key_ctr_mask, key_ctr, -1),
        "key_actor": np.where(key_actor_mask, key_actor, 0),
        "key_has_actor": key_actor_mask,
        "key_ids": key_ids,
        "key_table": key_table,
        "mark_ids": mark_ids,
        "mark_table": mark_table,
        "insert": insert,
        "expand": expand,
        "vcode": vcode,
        "voff": voff,
        "vlen": vlen,
        "vraw": raw,
        "value_int": value_int,
        "width": width,
        "pred_num": pred_num.astype(np.int64),
        "pred_ctr": pred_ctr,
        "pred_actor": pred_actor,
        "pred_row_off": pred_row_off,
    }


from ..types import ACTOR_BITS  # packed id layout: ctr << bits | actor rank


def ranked_batch(changes, rank_of) -> Dict[str, object]:
    """batch_arrays + packed-id rank translation, shared by the device log
    (ops/oplog.py) and the host bulk rebuild (core/bulk_load.py).

    Returns the raw batch under ``"a"`` plus the translated columns:
    ``id_key`` (per-op packed id), ``obj`` (0 = root), ``prop_ids``
    (string-table id, -1 = seq key), ``elem`` (-1 = map op, 0 = HEAD,
    else packed id), ``pred_src`` (source row per pred edge) and
    ``pred_key`` (packed pred target). Raises ExtractError when a
    chunk-local actor index exceeds its change's actor table.
    """
    a = batch_arrays(changes)
    N = a["n"]
    nc = len(changes)
    cor = a["change_of_row"]
    tab = np.asarray(
        [rank_of[bytes(x)] for ch in changes for x in ch.actors], np.int64
    )
    tab_off = np.concatenate(
        [[0], np.cumsum([len(ch.actors) for ch in changes])]
    )[:-1].astype(np.int64)
    row_tab = tab_off[cor]
    author = tab[tab_off] if nc else np.empty(0, np.int64)
    start_op = np.asarray([ch.start_op for ch in changes], np.int64)
    tab_size = np.asarray([len(ch.actors) for ch in changes], np.int64)
    if N and (
        np.any(a["obj_actor"][a["obj_has"]] >= tab_size[cor][a["obj_has"]])
        or np.any(
            a["key_actor"][a["key_has_actor"]] >= tab_size[cor][a["key_has_actor"]]
        )
    ):
        raise ExtractError("actor index out of chunk-local table range")

    within = np.arange(N, dtype=np.int64) - a["row_off"][:-1][cor]
    id_key = ((start_op[cor] + within) << ACTOR_BITS) | author[cor]
    clip = max(len(tab) - 1, 0)
    obj = np.where(
        a["obj_has"],
        (a["obj_ctr"] << ACTOR_BITS) | tab[(row_tab + a["obj_actor"]).clip(max=clip)],
        np.int64(0),
    )
    prop_ids = a["key_ids"] if a["key_ids"] is not None else np.full(N, -1, np.int32)
    elem = np.where(
        prop_ids >= 0,
        np.int64(-1),
        np.where(
            a["key_has_actor"],
            (a["key_ctr"] << ACTOR_BITS) | tab[(row_tab + a["key_actor"]).clip(max=clip)],
            np.int64(0),  # HEAD (ctr 0, no actor)
        ),
    )
    pred_src = np.repeat(np.arange(N, dtype=np.int64), a["pred_num"])
    per_change_preds = np.diff(a["pred_row_off"])
    cop = np.repeat(np.arange(nc), per_change_preds)
    if len(cop) and np.any(a["pred_actor"] >= tab_size[cop]):
        raise ExtractError("pred actor index out of chunk-local table range")
    pred_key = (a["pred_ctr"] << ACTOR_BITS) | tab[
        (tab_off[cop] + a["pred_actor"]).clip(max=clip)
    ]
    return {
        "a": a,
        "id_key": id_key,
        "obj": obj,
        "prop_ids": prop_ids,
        "elem": elem,
        "pred_src": pred_src,
        "pred_key": pred_key,
    }


def _padded(vals: np.ndarray, mask: np.ndarray, n: int):
    if len(vals) > n:
        raise ExtractError("column longer than op count")
    if len(vals) < n:
        vals = np.concatenate([vals, np.zeros(n - len(vals), vals.dtype)])
        mask = np.concatenate([mask, np.zeros(n - len(mask), bool)])
    return vals, mask


def _padded_bool(vals: np.ndarray, n: int) -> np.ndarray:
    if len(vals) > n:
        raise ExtractError("boolean column longer than op count")
    if len(vals) < n:
        vals = np.concatenate([vals, np.zeros(n - len(vals), bool)])
    return vals.astype(bool)


_TAG_NAME = {
    0: "null",
    3: "uint",
    4: "int",
    5: "f64",
    6: "str",
    7: "bytes",
    8: "counter",
    9: "timestamp",
}


def _value_cache_cap() -> int:
    import os

    return int(os.environ.get("AUTOMERGE_TPU_VALUE_CACHE", 1 << 16))


class LazyValues:
    """Row -> ScalarValue, materialized on demand from the raw value buffer.

    Drop-in for the eager python list the slow extraction path produces.
    The per-row cache is BOUNDED (``cap``, default 65536 entries, env knob
    AUTOMERGE_TPU_VALUE_CACHE): a long-lived DeviceDoc over a multi-million
    row log would otherwise accrete one ScalarValue per row ever read.
    Eviction is insertion-order FIFO (one dict pop); ``hits``/``misses``
    count cache effectiveness for the bench / trace output.
    """

    __slots__ = ("code", "off", "ln", "raw", "cache", "cap", "hits", "misses")

    def __init__(self, code: np.ndarray, off: np.ndarray, ln: np.ndarray,
                 raw: bytes, cap: Optional[int] = None):
        self.code = code
        self.off = off
        self.ln = ln
        self.raw = raw
        self.cache: Dict[int, ScalarValue] = {}
        self.cap = _value_cache_cap() if cap is None else cap
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self.code)

    @property
    def nbytes(self) -> int:
        """Resident footprint of the heap (per-row index columns + the
        raw byte buffer) — the values side of the per-doc residency
        accounting (ops/compressed.py covers the op columns)."""
        return (
            self.code.nbytes + self.off.nbytes + self.ln.nbytes
            + len(self.raw)
        )

    def __getitem__(self, row: int) -> ScalarValue:
        v = self.cache.get(row)
        if v is None:
            self.misses += 1
            v = self._decode(row)
            if self.cap > 0:
                if len(self.cache) >= self.cap:
                    self.cache.pop(next(iter(self.cache)))
                self.cache[row] = v
        else:
            self.hits += 1
        return v

    def stats(self) -> Dict[str, int]:
        return {
            "hits": self.hits, "misses": self.misses,
            "size": len(self.cache), "cap": self.cap,
        }

    def _decode(self, row: int) -> ScalarValue:
        import struct

        code = int(self.code[row])
        o = int(self.off[row])
        ln = int(self.ln[row])
        # the raw heap may be a (shared, append-only) bytearray; values
        # must come out as immutable bytes
        chunk = bytes(self.raw[o : o + ln])
        if code == 0:
            return ScalarValue("null")
        if code == 1:
            return ScalarValue("bool", False)
        if code == 2:
            return ScalarValue("bool", True)
        if code == 3:
            return ScalarValue("uint", decode_uleb(chunk, 0)[0])
        if code == 4:
            return ScalarValue("int", decode_sleb(chunk, 0)[0])
        if code == 5:
            return ScalarValue("f64", struct.unpack("<d", chunk)[0])
        if code == 6:
            return ScalarValue("str", chunk.decode("utf-8"))
        if code == 7:
            return ScalarValue("bytes", chunk)
        if code == 8:
            return ScalarValue("counter", decode_sleb(chunk, 0)[0])
        if code == 9:
            return ScalarValue("timestamp", decode_sleb(chunk, 0)[0])
        return ScalarValue("unknown", (code, chunk))


# -- per-change-hash extraction cache ----------------------------------------
# Sync re-delivers changes as FRESH StoredChange objects (parsed off the
# wire), so the per-object ``cached_cols`` memo never hits for them. This
# bounded hash-keyed cache makes a re-delivered (or re-parsed) change's
# column decode one dict hit. LRU by re-insertion; the cap bounds worst-case
# host memory at a few thousand decoded changes.

_CHANGE_COLS_CACHE: "OrderedDict[bytes, object]" = None  # type: ignore[assignment]
_CHANGE_COLS_CAP = 4096


def _change_cache() -> "OrderedDict[bytes, object]":
    global _CHANGE_COLS_CACHE
    if _CHANGE_COLS_CACHE is None:
        from collections import OrderedDict

        _CHANGE_COLS_CACHE = OrderedDict()
    return _CHANGE_COLS_CACHE


def cached_cols_for_hash(h: Optional[bytes]):
    """Decoded ChangeCols for a change hash, or None (counts hit/miss)."""
    from .. import obs

    if h is None:
        return None
    cache = _change_cache()
    cc = cache.get(h)
    if cc is not None:
        cache.move_to_end(h)
        obs.count("extract.change_cache_hit")
    else:
        obs.count("extract.change_cache_miss")
    return cc


def remember_cols_for_hash(h: Optional[bytes], cc) -> None:
    if h is None or cc is None:
        return
    cache = _change_cache()
    cache[h] = cc
    cache.move_to_end(h)
    while len(cache) > _CHANGE_COLS_CAP:
        cache.popitem(last=False)


def doc_op_arrays(col_data) -> Dict[str, object]:
    """Decode document-chunk op columns (storage/document.py OP_*) into
    numpy arrays via the native codec core — the fast load path's input.

    Strict about shape regularities the encoder always produces (action
    column defines the row count and every other column covers or
    null-pads it); anything irregular raises ExtractError and the caller
    falls back to the per-op python decoder, which reports precise
    errors for genuinely malformed files.
    """
    from ..storage import document as D

    lib = native.load()
    if lib is None:
        raise native.NativeUnavailable("native codecs not available")

    def col(s) -> bytes:
        return col_data.get(s, b"")

    def rle_full(buf, signed=False):
        cap = max(1024, len(buf))
        while True:
            v, m = native.rle_decode_array(buf, signed, cap)
            if len(v) < cap:
                return v, m
            cap *= 4

    def delta_full(buf):
        cap = max(1024, len(buf))
        while True:
            v, m = native.delta_decode_array(buf, cap)
            if len(v) < cap:
                return v, m
            cap *= 4

    action, amask = rle_full(col(D.OP_ACTION))
    n = len(action)
    if n == 0 or not amask.all():
        raise ExtractError("doc ops: empty or null action column")

    def pad_to_n(v, m):
        if len(v) > n:
            raise ExtractError("doc ops: column longer than action column")
        if len(v) < n:
            v2 = np.zeros(n, v.dtype)
            v2[: len(v)] = v
            m2 = np.zeros(n, bool)
            m2[: len(m)] = m
            return v2, m2
        return v, m

    id_ctr, id_cm = pad_to_n(*delta_full(col(D.OP_ID_CTR)))
    id_actor, id_am = pad_to_n(*rle_full(col(D.OP_ID_ACTOR)))
    if not (id_cm.all() and id_am.all()):
        raise ExtractError("doc ops: missing id column values")
    obj_ctr, obj_cm = pad_to_n(*rle_full(col(D.OP_OBJ_CTR)))
    obj_actor, obj_am = pad_to_n(*rle_full(col(D.OP_OBJ_ACTOR)))
    if not np.array_equal(obj_cm, obj_am):
        raise ExtractError("doc ops: half-null object id")
    key_ctr, key_cm = pad_to_n(*delta_full(col(D.OP_KEY_CTR)))
    key_actor, key_am = pad_to_n(*rle_full(col(D.OP_KEY_ACTOR)))

    def bools(buf):
        out = native.bool_decode_array(buf, n)
        if len(out) < n:
            out = np.concatenate([out, np.zeros(n - len(out), bool)])
        return out.astype(np.uint8)

    insert = bools(col(D.OP_INSERT))
    expand = bools(col(D.OP_EXPAND))

    def strtab(buf):
        if not len(buf):
            return np.full(n, -1, np.int32), []
        return _strtab_decode(
            buf, np.zeros(1, np.int64), np.asarray([len(buf)], np.int64),
            np.asarray([0, n], np.int64), 1, n,
        )

    key_ids, key_table = strtab(col(D.OP_KEY_STR))
    mark_ids, mark_table = strtab(col(D.OP_MARK_NAME))

    vm, vmm = pad_to_n(*rle_full(col(D.OP_VAL_META)))
    if not vmm.all():
        raise ExtractError("doc ops: null value metadata")
    vcode = (vm & 15).astype(np.int32)
    vlen = (vm >> 4).astype(np.int64)
    voff = np.concatenate([[0], np.cumsum(vlen)[:-1]]).astype(np.int64)
    raw = col(D.OP_VAL_RAW)
    if n and int(voff[-1] + vlen[-1]) > len(raw):
        raise ExtractError("doc ops: value raw column overrun")

    succ_num, snm = pad_to_n(*rle_full(col(D.OP_SUCC_GROUP)))
    succ_num = np.where(snm, succ_num, 0).astype(np.int64)
    total = int(succ_num.sum())
    sa, sam = rle_full(col(D.OP_SUCC_ACTOR))
    sc, scm = delta_full(col(D.OP_SUCC_CTR))
    if len(sa) < total or len(sc) < total:
        raise ExtractError("doc ops: truncated succ columns")
    if not (sam[:total].all() and scm[:total].all()):
        raise ExtractError("doc ops: null succ id")

    return {
        "n": n,
        "action": action.astype(np.int64),
        "id_ctr": id_ctr.astype(np.int64),
        "id_actor": id_actor.astype(np.int64),
        "obj_ctr": np.where(obj_cm, obj_ctr, 0).astype(np.int64),
        "obj_actor": np.where(obj_am, obj_actor, 0).astype(np.int64),
        "obj_mask": obj_cm,
        "key_ctr": key_ctr.astype(np.int64),
        "key_ctr_mask": key_cm,
        "key_actor": np.where(key_am, key_actor, 0).astype(np.int64),
        "key_actor_mask": key_am,
        "key_ids": key_ids,
        "key_table": key_table,
        "mark_ids": mark_ids,
        "mark_table": mark_table,
        "insert": insert,
        "expand": expand,
        "vcode": vcode,
        "vlen": vlen,
        "voff": voff,
        "vraw": raw,
        "succ_num": succ_num,
        "succ_ctr": sc[:total].astype(np.int64),
        "succ_actor": sa[:total].astype(np.int64),
    }


def validate_doc_arrays(a, n_actors: int) -> None:
    """Bounds/magnitude guards over doc_op_arrays output: actor indices in
    [0, n_actors), counters within the 43-bit packed-id range. Raises
    ExtractError — callers fall back to the per-op python decoder, which
    reports the canonical error for genuinely malformed files."""
    lim = 1 << 43

    def ctr_ok(v, mask=None):
        if mask is not None:
            v = v[mask]
        if len(v) and (int(v.min()) < 0 or int(v.max()) >= lim):
            raise ExtractError("counter outside packed range")

    def actor_ok(v, mask=None):
        if mask is not None:
            v = v[mask]
        if len(v) and (int(v.min()) < 0 or int(v.max()) >= n_actors):
            raise ExtractError("actor index out of range")

    ctr_ok(a["id_ctr"])
    ctr_ok(a["succ_ctr"])
    ctr_ok(a["obj_ctr"], a["obj_mask"].astype(bool))
    ctr_ok(a["key_ctr"], a["key_ctr_mask"].astype(bool))
    actor_ok(a["id_actor"])
    actor_ok(a["succ_actor"])
    actor_ok(a["obj_actor"], a["obj_mask"].astype(bool))
    actor_ok(a["key_actor"], a["key_actor_mask"].astype(bool))
