"""Device op log + batched merge kernel.

Submodules import lazily (PEP 562): ``merge`` pulls in JAX (~1s cold), and
host-only paths (the bulk rebuild's use of ``ops.extract``) must not pay
for it.
"""

__all__ = [
    "CrossDocBatcher", "DeviceDoc", "OpLog", "apply_cross_doc",
    "merge_columns", "merge_kernel",
]


def __getattr__(name):
    if name == "DeviceDoc":
        from .device_doc import DeviceDoc

        return DeviceDoc
    if name in ("CrossDocBatcher", "apply_cross_doc"):
        from . import batched

        return getattr(batched, name)
    if name == "OpLog":
        from .oplog import OpLog

        return OpLog
    if name in ("merge_columns", "merge_kernel"):
        from . import merge

        return getattr(merge, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
