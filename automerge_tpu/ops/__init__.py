from .device_doc import DeviceDoc
from .merge import merge_columns, merge_kernel
from .oplog import OpLog

__all__ = ["DeviceDoc", "OpLog", "merge_columns", "merge_kernel"]
