"""Vectorized cross-document host staging: columnar passes over many
documents' pending changes, with per-doc offset ranges.

PR 7 collapsed N kernel launches per drain cycle into one, and the PR 11
observatory then measured the consequence: ~70% of batched-drain wall
clock was *host-side Python* — dominated by the per-document
``OpLog.append_changes`` splice and the per-document column extraction,
each a few dozen small-numpy calls whose dispatch overhead dwarfs the
actual work at serve-sized deltas. This module batches that host half
the same way ``ops/batched.py`` batched the device half: many documents'
pending changes are packed into ONE set of shared numpy column arrays
(disjoint per-doc row ranges) and the staging pipeline runs as a handful
of columnar passes instead of per-doc loops:

* **pack** — one shared column extraction over every document's ready
  changes (``ranked_from_caches`` with a union actor table), then packed
  (actor_rank, counter) keys are translated global->doc with one flat
  LUT gather per key column. The packed int64 key IS the offset-value
  coding of the (counter, actor) composite (arXiv:2209.08420): a
  Lamport-order comparison is a single int64 compare, never a Python
  tuple.
* **sort** — ONE ``lexsort`` over ``(doc, id_key)`` Lamport-orders every
  document's delta at once (contiguous doc ranges keep the result
  sliceable per doc), and duplicate-id / tail checks run as shared
  vector passes.
* **splice** — per document, a *specialized* tail-append splice: the
  passes are organized per column encoding (plain payload columns,
  packed-key columns, row-reference columns, string-table columns), the
  control-flow-duplication playbook of arXiv:2302.10098 — instead of the
  generic per-column splice machinery branching per call. Row references
  resolve through the shared ``join_rows`` id join; the resolution-array
  and successor-counter bookkeeping of ``DeviceDoc._apply_append`` runs
  in the same specialized form.

Soundness: the fast path is entered ONLY when its assumptions are
checked to hold — resident log non-empty with retained column bytes, no
unresolved (MISSING) references outstanding (``OpLog.n_miss_elem`` /
``n_miss_pred``, maintained incrementally), no new actors (a monotone
rank remap would touch every resident key), strictly-tail Lamport
position, and an object table that only extends at its end. Everything
else falls back per document to the scalar ``DeviceDoc.stage_ready``
path, which stays both the fallback and the differential oracle
(tests/test_host_batch.py asserts column-level OpLog equality and
identical materialized documents between the two).

Feed points: ``ops/batched.apply_cross_doc`` (the bench/CI driver),
``CrossDocBatcher`` (the serving drain — submitters hand raw batches to
the flush leader, which stages every co-arriving document in one
vectorized pass before the shared kernel launch), and the cluster
follower apply path (``cluster/node.py`` drains coalesced ``replApply``
runs through the same staging).

Env: ``AUTOMERGE_TPU_HOST_BATCH=0`` forces the per-doc scalar path
everywhere (the A/B and bisection knob).

Profiler stages: ``host_pack`` / ``host_sort`` / ``host_splice`` join
the PR 11 taxonomy, so ``drain.attributed_fraction`` stays >= 0.9 on
this path and ``perf-report`` shows where the staging wall went.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..obs import prof as _prof
from .device_doc import _INCREMENT, _MAKE_OBJ
from .extract import LazyValues
from .oplog import (
    ACTOR_BITS,
    ACTOR_MASK,
    ELEM_HEAD,
    ELEM_MAP,
    ELEM_MISSING,
    TAG_UNKNOWN,
    _capacity,
    _merge_table,
    join_rows,
)


def _tail_write(bufs: dict, name: str, old: np.ndarray, new, mm: int):
    """One buffered tail write — the ``_splice_col`` / ``_res_splice``
    fast path with the generic per-call machinery (asarray, dtype
    coercion, row-map branching) stripped: the hot staging loop pays a
    buffer check and a slice assignment per column, nothing else.
    ``new`` is the appended values (or a scalar fill). Capacity
    bucketing and buffer reuse match ``OpLog._splice_col`` exactly, so
    the scalar path can keep splicing the same buffers afterwards.

    Compressed-residency contract: a tail write never moves the
    resident prefix, so the log's compressed column image
    (ops/compressed.py) stays valid across it — each encoded column's
    covered-row cursor lags and the next sync extends the LAST RUN with
    the appended slice instead of re-encoding (StrideRuns.extend_tail);
    the id_key runs are even extended eagerly in ``_splice_doc`` so the
    reference joins run offset-value-coded."""
    n = len(old)
    buf = bufs.get(name)
    if buf is not None and old.base is buf and len(buf) >= mm:
        buf[n:mm] = new
        return buf[:mm]
    nbuf = np.empty(_capacity(mm), old.dtype)
    nbuf[:n] = old
    nbuf[n:mm] = new
    bufs[name] = nbuf
    return nbuf[:mm]


def enabled() -> bool:
    """Whether the vectorized cross-doc staging path is active
    (``AUTOMERGE_TPU_HOST_BATCH``, default on; ``0`` forces the per-doc
    scalar path for A/B comparison and bisection)."""
    return os.environ.get("AUTOMERGE_TPU_HOST_BATCH", "1") != "0"


class _DocPlan:
    """One document's slot in the shared staging pass."""

    __slots__ = (
        "dev", "ready", "label", "c0", "c1", "r0", "r1", "p0", "p1", "k",
        "rank_of", "all_bytes", "actors_changed",
    )

    def __init__(self, dev, ready, label):
        self.dev = dev
        self.ready = ready
        self.label = label
        self.c0 = self.c1 = self.r0 = self.r1 = self.p0 = self.p1 = 0
        self.k = 0
        # the document's (possibly extended) actor universe: delta
        # actors not yet resident insert by byte rank — a MONOTONE remap
        # of every resident packed key, handled in the splice pass
        # rather than falling back (every first contact with a new
        # editor would otherwise stage scalar)
        self.rank_of: Dict[bytes, int] = {}
        self.all_bytes: List[bytes] = []
        self.actors_changed = False


class DocResult:
    """Per-document outcome of ``stage_docs``."""

    __slots__ = ("applied", "error", "vectorized")

    def __init__(self):
        self.applied = 0
        self.error: Optional[BaseException] = None
        self.vectorized = False


def _admit(dev, ready, label) -> Optional[_DocPlan]:
    """Fast-path admission: every assumption the specialized tail splice
    relies on, checked up front so the splice itself never aborts
    mid-mutation. Returns the planned slot (with its actor-universe
    resolution) or None for the scalar path."""
    log = dev.log
    if log.n == 0 or log.n_miss_elem or log.n_miss_pred:
        return None
    if not isinstance(log.values, LazyValues):
        return None
    for ch in ready:
        if ch.op_col_data is None and ch.cached_cols is None:
            return None
    if not log._ensure_ref_keys():
        return None
    p = _DocPlan(dev, ready, label)
    old_rank = dev._rank_of
    delta_bytes = {bytes(a) for ch in ready for a in ch.actors}
    if delta_bytes <= old_rank.keys():
        p.rank_of = old_rank
        p.all_bytes = [a.bytes for a in log.actors]
    else:
        all_bytes = sorted(old_rank.keys() | delta_bytes)
        if len(all_bytes) >= (1 << ACTOR_BITS):
            return None
        p.all_bytes = all_bytes
        p.rank_of = {b: i for i, b in enumerate(all_bytes)}
        p.actors_changed = True
    return p


def _extract_all(plans: List[_DocPlan]):
    """One shared column extraction over every planned document's ready
    changes, under a union actor-rank table. Returns ``(r, g_bytes)`` or
    None (callers then fall back per doc — nothing was mutated)."""
    from .. import native
    from .assemble import AssembleError, ranked_from_caches

    changes = [ch for p in plans for ch in p.ready]
    g_bytes = sorted({bytes(a) for ch in changes for a in ch.actors})
    if len(g_bytes) >= (1 << ACTOR_BITS):
        return None
    g_rank = {b: i for i, b in enumerate(g_bytes)}
    try:
        r = ranked_from_caches(changes, g_rank)
    except (AssembleError, native.NativeUnavailable, ValueError):
        return None
    except Exception:
        if os.environ.get("AUTOMERGE_TPU_DEBUG"):
            raise
        return None
    return r, g_bytes


def _doc_string_table(ready, attr: str) -> List[str]:
    """First-occurrence union of one document's per-change string tables
    (the exact table ``ranked_from_caches`` would build for these
    changes alone). Identical table objects — synthesized batches share
    one — contribute once."""
    seen_tables = set()
    have = set()
    out: List[str] = []
    for ch in ready:
        t = getattr(ch.cached_cols, attr, None)
        if not t or id(t) in seen_tables:
            continue
        seen_tables.add(id(t))
        for s in t:
            if s not in have:
                have.add(s)
                out.append(s)
    return out


def _local_ids(g_ids, g_pos: Dict[str, int], doc_table: List[str],
               g_table_len: int) -> np.ndarray:
    """Translate global-table string ids to doc-table ids (-1 rides
    through) with one LUT gather."""
    g2d = np.full(max(g_table_len, 1), -1, np.int32)
    for i, s in enumerate(doc_table):
        g2d[g_pos[s]] = i
    ids = np.asarray(g_ids)
    return np.where(
        ids >= 0, g2d[np.clip(ids, 0, None)], np.int32(-1)
    ).astype(np.int32)


def stage_docs(work) -> Tuple[List, Dict[int, DocResult]]:
    """Stage many documents' drained device feeds through shared
    columnar passes.

    ``work``: iterable of ``(device_doc, batches)`` pairs (duplicate
    documents merge into one staging, like ``apply_cross_doc``).
    Returns ``(stages, results)``: the pack-eligible ``BatchStage`` list
    for ``resolve_stages``, and a per-document ``DocResult`` keyed by
    ``id(device_doc)`` (applied count, error, which path ran). Documents
    failing a fast-path assumption stage through the scalar
    ``DeviceDoc.stage_ready`` — bit-identical by construction.

    Each call is self-contained (dedup, the union actor table, and all
    offset ranges are per call), which is what lets the double-buffered
    drain (``apply_cross_doc`` with ``AUTOMERGE_TPU_DRAIN_PIPELINE``)
    run THIS staging for chunk N+1 while chunk N's packed kernel is
    still in flight — the host seconds spent here under a live launch
    are the drain's ``overlap_s``.
    """
    from .batched import BatchStage

    # -- dedup + causal order + admission: one span each for the whole
    # drain (the spans cover the surrounding glue too, so the cycle
    # profiler's attributed fraction holds even at tiny drain sizes)
    with obs.span("device.stage.dedup", docs=len(work)
                  if isinstance(work, list) else 0):
        merged: Dict[int, tuple] = {}
        order: List[int] = []
        for dev, batches in work:
            if dev._base is not dev:
                raise ValueError(
                    "stage_docs on a historical view; use the base doc"
                )
            key = id(dev)
            if key in merged:
                merged[key][1].extend(batches)
            else:
                merged[key] = (dev, list(batches))
                order.append(key)
        results: Dict[int, DocResult] = {k: DocResult() for k in order}
        flat: Dict[int, list] = {}
        for key in order:
            dev, batches = merged[key]
            flat[key] = [ch for b in batches for ch in b]
            dev._dedup_into_pending(flat[key])
    entries: List[tuple] = []  # (dev, ready, label)
    vec: List[_DocPlan] = []
    scalar: List[tuple] = []  # (key, dev, ready, label)
    with obs.span("device.stage.causal_order", docs=len(order)):
        for i, key in enumerate(order):
            dev = merged[key][0]
            ready = dev._drain_ready_pending()
            label = getattr(dev, "obs_name", None) or f"doc{i}"
            entries.append((key, dev, ready, label))
        for key, dev, ready, label in entries:
            if not ready:
                continue
            plan = _admit(dev, ready, label) if enabled() else None
            if plan is not None:
                vec.append(plan)
                results[key].vectorized = True
            else:
                scalar.append((key, dev, ready, label))

    stages: List = []
    pending_reresolve: List[tuple] = []  # (key, plan, dirty)

    g = None
    if vec:
        with obs.span("host.pack", docs=len(vec)):
            g = _pack_global(vec)
        if g is None:
            for p in vec:
                results[id(p.dev)].vectorized = False
                scalar.append((id(p.dev), p.dev, p.ready, p.label))
            obs.count("host_batch.fallback_docs",
                      n=len(vec), labels={"reason": "extract"})
            vec = []

    if vec:
        with obs.span("host.sort", rows=g["N"], docs=len(vec)):
            demoted = _sort_global(vec, g)
        dem_ids = {id(p) for p in demoted}
        for p in demoted:
            results[id(p.dev)].vectorized = False
            scalar.append((id(p.dev), p.dev, p.ready, p.label))
        if demoted:
            obs.count("host_batch.fallback_docs",
                      n=len(demoted), labels={"reason": "order"})
        vec = [p for p in vec if id(p) not in dem_ids]

    if vec:
        rows_total = spliced = 0
        with obs.span("host.splice", docs=len(vec)):
            for p in vec:
                res = results[id(p.dev)]
                t0 = time.perf_counter()
                try:
                    outcome = _splice_doc(p, g)
                except BaseException as e:  # noqa: BLE001 — isolate the doc
                    res.error = e
                    obs.count("host_batch.fallback_docs",
                              labels={"reason": "error"})
                    continue
                finally:
                    _prof.note_doc(p.label, time.perf_counter() - t0)
                kind = outcome[0]
                if kind == "scalar":
                    # a pre-mutation admission check failed late: the
                    # document is untouched, the scalar path takes it
                    res.vectorized = False
                    scalar.append((id(p.dev), p.dev, p.ready, p.label))
                    continue
                res.applied = len(p.ready)
                rows_total += p.k
                spliced += 1
                if kind == "stage":
                    stages.append(BatchStage(p.dev, outcome[1], outcome[2]))
                elif kind == "reresolve":
                    pending_reresolve.append((p, outcome[1]))
        obs.count("oplog.append_rows", n=rows_total)
        obs.count("host_batch.docs", n=spliced)
        obs.event("host_batch.splice", docs=spliced, rows=rows_total)

    # device-side per-doc fallbacks run OUTSIDE the host spans so their
    # kernel/h2d spans attribute to the device side of the cycle split
    for p, dirty in pending_reresolve:
        res = results[id(p.dev)]
        t0 = time.perf_counter()
        try:
            p.dev._reresolve(dirty)
            p.dev._export_doc_gauges()
        except BaseException as e:  # noqa: BLE001
            res.error = e
        _prof.note_doc(p.label, time.perf_counter() - t0)

    for key, dev, ready, label in scalar:
        res = results[key]
        t0 = time.perf_counter()
        try:
            applied, st = dev.stage_ready(ready)
            res.applied = applied
            if st is not None:
                stages.append(st)
        except BaseException as e:  # noqa: BLE001
            res.error = e
        _prof.note_doc(label, time.perf_counter() - t0)

    return stages, results


# -- the shared passes --------------------------------------------------------


def _pack_global(plans: List[_DocPlan]):
    """Extraction + packed-key translation for every planned document.
    Returns the shared-array context dict, or None when the one-shot
    extraction is unavailable (callers fall back per doc)."""
    ext = _extract_all(plans)
    if ext is None:
        return None
    r, g_bytes = ext
    a = r["a"]
    N = int(a["n"])
    row_off = np.asarray(a["row_off"], np.int64)
    pred_off = np.asarray(a["pred_row_off"], np.int64)
    raw_off = np.asarray(a["raw_off"], np.int64)
    raw_ln = np.asarray(a["raw_ln"], np.int64)

    c = 0
    k_of = np.empty(len(plans), np.int64)
    q_of = np.empty(len(plans), np.int64)
    for di, p in enumerate(plans):
        p.c0, p.c1 = c, c + len(p.ready)
        c = p.c1
        p.r0, p.r1 = int(row_off[p.c0]), int(row_off[p.c1])
        p.p0, p.p1 = int(pred_off[p.c0]), int(pred_off[p.c1])
        p.k = p.r1 - p.r0
        k_of[di] = p.k
        q_of[di] = p.p1 - p.p0

    # global->doc actor-rank translation: one flat LUT, one gather per
    # packed-key column. Rank order is byte order on both sides, so the
    # restriction of the global ranking to a document's universe is
    # exactly that document's ranking.
    G = max(len(g_bytes), 1)
    lut = np.zeros(len(plans) * G, np.int64)
    for di, p in enumerate(plans):
        base = di * G
        ro = p.rank_of
        for gi, b in enumerate(g_bytes):
            rk = ro.get(b)
            if rk is not None:
                lut[base + gi] = rk
    doc_of_row = np.repeat(np.arange(len(plans), dtype=np.int64), k_of)
    base_row = doc_of_row * G

    def translate(key):
        key = np.asarray(key, np.int64)
        idx = np.where(key > 0, key & ACTOR_MASK, 0)
        return np.where(
            key > 0,
            ((key >> ACTOR_BITS) << ACTOR_BITS) | lut[base_row + idx],
            key,
        )

    id_t = translate(r["id_key"])
    obj_t = translate(r["obj"])
    elem_t = translate(r["elem"])
    pk = np.asarray(r["pred_key"], np.int64)
    if len(pk):
        doc_of_pred = np.repeat(np.arange(len(plans), dtype=np.int64), q_of)
        pk_t = ((pk >> ACTOR_BITS) << ACTOR_BITS) | lut[
            doc_of_pred * G + (pk & ACTOR_MASK)
        ]
    else:
        pk_t = pk

    g_key_table = a["key_table"] or []
    g_mark_table = a["mark_table"] or []
    return {
        "N": N,
        "a": a,
        "r": r,
        "doc_of_row": doc_of_row,
        "id_t": id_t,
        "obj_t": obj_t,
        "elem_t": elem_t,
        "pk_t": pk_t,
        "raw_off": raw_off,
        "raw_ln": raw_ln,
        "n_changes": c,
        "key_pos": {s: i for i, s in enumerate(g_key_table)},
        "mark_pos": {s: i for i, s in enumerate(g_mark_table)},
    }


def _sort_global(plans: List[_DocPlan], g) -> List[_DocPlan]:
    """One Lamport sort for every document's delta, shared dup/tail
    checks, and the global->sorted gather of every row column. Returns
    the plans demoted to the scalar path."""
    a = g["a"]
    N = g["N"]
    doc_of_row = g["doc_of_row"]
    order_g = np.lexsort((g["id_t"], doc_of_row))
    inv_g = np.empty(N, np.int64)
    inv_g[order_g] = np.arange(N, dtype=np.int64)
    id_s = g["id_t"][order_g]

    # duplicate op ids within one document -> that doc goes scalar (the
    # scalar path then reports the canonical append_fallback/rebuild)
    bad = set()
    if N > 1:
        same = (doc_of_row[1:] == doc_of_row[:-1]) & (id_s[1:] == id_s[:-1])
        if np.any(same):
            bad.update(doc_of_row[1:][same].tolist())

    g["order_g"] = order_g
    g["inv_g"] = inv_g
    g["id_s"] = id_s
    g["obj_s"] = g["obj_t"][order_g]
    g["elem_s"] = g["elem_t"][order_g]
    g["action_s"] = np.asarray(a["action"], np.int32)[order_g]
    g["insert_s"] = np.asarray(a["insert"], np.bool_)[order_g]
    g["vtag_s"] = np.minimum(
        np.asarray(a["vcode"]), TAG_UNKNOWN
    ).astype(np.int32)[order_g]
    g["vint_s"] = np.asarray(a["value_int"], np.int64)[order_g]
    g["width_s"] = np.asarray(a["width"], np.int32)[order_g]
    g["expand_s"] = np.asarray(a["expand"], np.bool_)[order_g]
    g["vcode_s"] = np.asarray(a["vcode"], np.int32)[order_g]
    g["voff_s"] = np.asarray(a["voff"], np.int64)[order_g]
    g["vlen_s"] = np.asarray(a["vlen"], np.int64)[order_g]
    g["prop_s"] = np.asarray(g["r"]["prop_ids"], np.int32)[order_g]
    mark_ids = a["mark_ids"]
    g["mark_s"] = (
        np.asarray(mark_ids, np.int32)[order_g] if mark_ids is not None
        else None
    )

    demoted = []
    for di, p in enumerate(plans):
        if di in bad:
            demoted.append(p)
            continue
        if p.k:
            log = p.dev.log
            om = int(log.id_key[-1])
            if p.actors_changed:
                # compare against the POST-remap resident maximum (the
                # monotone remap preserves order, so the max row stays
                # the max)
                om = ((om >> ACTOR_BITS) << ACTOR_BITS) | p.rank_of[
                    log.actors[om & ACTOR_MASK].bytes
                ]
            if int(id_s[p.r0]) <= om:
                demoted.append(p)  # not a strict tail append -> scalar
    return demoted


def _splice_doc(p: _DocPlan, g):
    """The specialized tail splice for one document: replays exactly
    what ``OpLog.append_changes`` + ``DeviceDoc._apply_append`` +
    ``stage_batches`` would do for this (tail, same-actors, LazyValues)
    delta, organized as per-encoding column passes with the shared
    arrays pre-sorted. Returns ("stage", rows, dirty) |
    ("reresolve", dirty) | ("done",).

    No mutation happens until every admission check has passed: the
    only pre-commit writes go to scratch capacity buffers the resident
    arrays do not read past ``n``.
    """
    dev = p.dev
    log = dev.log
    ready = p.ready
    k = p.k
    n = log.n

    if k == 0:
        if p.actors_changed:
            # a zero-op change can still introduce its actor: the scalar
            # path owns the universe-only commit (_commit_actors)
            return ("scalar",)
        # dependency-only changes: bookkeeping, no rows (the scalar
        # path's n_new == 0 branch)
        log.changes.extend(ready)
        log.hashes().update(ch.hash for ch in ready)
        for ch in ready:
            dev._hash_index[ch.hash] = ch
        dev._views.clear()
        return ("done",)

    sl = slice(p.r0, p.r1)
    d_id = g["id_s"][sl]
    d_obj = g["obj_s"][sl]
    d_action = g["action_s"][sl]

    # -- actor-universe extension: monotone rank remap of the resident
    # packed keys (pure copies — nothing committed until the end; byte
    # order is rank order on both sides, so relative order of every
    # resident key is preserved and sortedness survives)
    if p.actors_changed:
        rank_map = np.fromiter(
            (p.rank_of[b] for b in (a.bytes for a in log.actors)),
            np.int64, count=len(log.actors),
        )

        def remap_packed(key):
            key = np.asarray(key, np.int64)
            idx = np.where(key > 0, key, 0) & ACTOR_MASK
            return np.where(
                key > 0,
                ((key >> ACTOR_BITS) << ACTOR_BITS) | rank_map[idx],
                key,
            )

        old_id = remap_packed(log.id_key)
        old_obj = remap_packed(log.obj_key)
        old_ek = remap_packed(log.elem_key)
        old_pk = remap_packed(log.pred_key)
        old_table = remap_packed(log.obj_table)
    else:
        old_id = log.id_key
        old_obj = log.obj_key
        old_ek = log.elem_key
        old_pk = log.pred_key
        old_table = log.obj_table

    # -- object table: must only extend at its end ------------------------
    # make actions are exactly the even codes below 8 (MAKE_ACTIONS =
    # 0/2/4/6): two compares beat np.isin's sort machinery per doc
    make_mask = (d_action < 8) & ((d_action & 1) == 0)
    make_new = d_id[make_mask]
    pos = np.searchsorted(old_table, d_obj)
    posc = np.clip(pos, 0, len(old_table) - 1)
    found = old_table[posc] == d_obj
    all_found = bool(np.all(found))
    if len(make_new) == 0 and all_found:
        add = make_new  # steady state: no new objects in this delta
    else:
        add_parts = [make_new]
        if not all_found:
            add_parts.append(d_obj[~found])
        add = np.unique(np.concatenate(add_parts))
    if len(add) and int(add[0]) <= int(old_table[-1]):
        # a new object id at or below the resident maximum would splice
        # INTO the table (dense-id remap of every resident row) — the
        # scalar path owns that case. Nothing has been mutated yet.
        obs.count("host_batch.fallback_docs", labels={"reason": "obj_order"})
        return ("scalar",)
    m = n + k

    # -- compressed residency: extend the id_key runs with the delta so
    # the reference joins below run offset-value-coded (searchsorted
    # over R run heads + stride arithmetic) instead of over all m rows;
    # the rest of the compressed image extends lazily on next sync — a
    # tail append never moves the resident prefix (ops/compressed.py)
    from . import compressed as _C

    idruns = None
    if _C.enabled() and not p.actors_changed:
        comp = log._comp
        if comp is None:
            comp = log._comp = _C.CompressedOpColumns()
        comp._sync_col("id_key", "delta", log.id_key, n)
        idruns = comp.extend_id(d_id)

    # -- packed-key and payload columns (tail writes only) ----------------
    if log._bufs is None:
        log._bufs = {}
    bufs = log._bufs
    tw = _tail_write
    id_new = tw(bufs, "id_key", old_id, d_id, m)
    obj_new = tw(bufs, "obj_key", old_obj, d_obj, m)
    ek_new = tw(bufs, "elem_key", old_ek, g["elem_s"][sl], m)
    action_new = tw(bufs, "action", log.action, d_action, m)
    insert_new = tw(bufs, "insert", log.insert, g["insert_s"][sl], m)
    vtag_new = tw(bufs, "value_tag", log.value_tag, g["vtag_s"][sl], m)
    vint_new = tw(bufs, "value_int", log.value_int, g["vint_s"][sl], m)
    width_new = tw(bufs, "width", log.width, g["width_s"][sl], m)
    expand_new = tw(bufs, "expand", log.expand, g["expand_s"][sl], m)

    # -- string-table columns ---------------------------------------------
    doc_keys = _doc_string_table(ready, "key_table")
    if doc_keys:
        props, d_prop = _merge_table(
            log.props, doc_keys,
            _local_ids(g["prop_s"][sl], g["key_pos"], doc_keys,
                       len(g["key_pos"])),
            np.arange(k),
        )
    else:
        # no change in this delta carries map keys: ids are all -1
        props = log.props
        d_prop = np.full(k, -1, np.int32)
    if g["mark_s"] is None:
        mark_names = log.mark_names
        d_mark = np.full(k, -1, np.int32)
    else:
        doc_marks = _doc_string_table(ready, "mark_table")
        if doc_marks:
            mark_names, d_mark = _merge_table(
                log.mark_names, doc_marks,
                _local_ids(g["mark_s"][sl], g["mark_pos"], doc_marks,
                           len(g["mark_pos"])),
                np.arange(k),
            )
        else:
            mark_names = log.mark_names
            d_mark = np.full(k, -1, np.int32)
    prop_new = tw(bufs, "prop", log.prop, d_prop, m)
    mark_new = tw(bufs, "mark_name_idx", log.mark_name_idx, d_mark, m)

    # -- row-reference columns (resolve through the shared id join) -------
    def _id_join(keys):
        if idruns is not None:
            obs.count("oplog.ovc_join", n=len(keys))
            return idruns.join(keys, ELEM_MISSING)
        return join_rows(id_new, keys, ELEM_MISSING)

    d_ek = g["elem_s"][sl]
    d_er = np.where(
        d_ek == -1,
        np.int32(ELEM_MAP),
        np.where(
            d_ek == 0, np.int32(ELEM_HEAD),
            _id_join(d_ek),
        ),
    ).astype(np.int32)
    er_new = tw(bufs, "elem_ref", log.elem_ref, d_er, m)
    n_miss_elem = int(np.count_nonzero(d_er == ELEM_MISSING))

    q = len(log.pred_src)
    p0, p1 = p.p0, p.p1
    src_g = g["r"]["pred_src"][p0:p1]
    if len(src_g):
        d_ps = (n + (g["inv_g"][src_g] - p.r0)).astype(np.int32)
        d_pk = g["pk_t"][p0:p1]
        d_pt = _id_join(d_pk)
        d_pt = np.where(
            d_pt == ELEM_MISSING, np.int32(-1), d_pt
        ).astype(np.int32)
    else:
        d_ps = np.empty(0, np.int32)
        d_pk = np.empty(0, np.int64)
        d_pt = np.empty(0, np.int32)
    qm = q + len(d_ps)
    ps_new = tw(bufs, "pred_src", log.pred_src, d_ps, qm)
    pt_new = tw(bufs, "pred_tgt", log.pred_tgt, d_pt, qm)
    pk_new = tw(bufs, "pred_key", old_pk, d_pk, qm)
    n_miss_pred = int(np.count_nonzero(d_pt == -1))

    # -- object table / dense ids -----------------------------------------
    if len(add):
        new_table = np.concatenate([old_table, add])
        od_new = np.searchsorted(new_table, d_obj).astype(np.int32)
    else:
        new_table = old_table
        od_new = posc.astype(np.int32)
    od_all = tw(bufs, "obj_dense", log.obj_dense, od_new, m)

    # -- values heap (LazyValues, append-only raw) ------------------------
    vals = log.values
    c1 = p.c1
    raw0 = int(g["raw_off"][p.c0])
    raw1 = (
        int(g["raw_off"][c1]) if c1 < g["n_changes"]
        else int(g["raw_off"][-1] + g["raw_ln"][-1])
    )
    base = len(vals.raw)
    code = tw(bufs, "vcode", vals.code, g["vcode_s"][sl], m)
    off = tw(bufs, "voff", vals.off, g["voff_s"][sl] - raw0 + base, m)
    ln = tw(bufs, "vlen", vals.ln, g["vlen_s"][sl], m)
    raw = vals.raw
    if not isinstance(raw, bytearray):
        raw = bytearray(raw)
    raw += g["a"]["vraw"][raw0:raw1]
    nv = LazyValues(code, off, ln, raw, cap=vals.cap)
    nv.hits, nv.misses = vals.hits, vals.misses

    # -- dirty objects (new dense numbering) ------------------------------
    one_obj = bool(od_new[0] == od_new[-1]) and bool(
        np.all(od_new == od_new[0])
    )
    if one_obj and len(make_new) == 0 and len(d_pt) == 0:
        # single-object insert-only delta (a typing burst): the dirty
        # set is that one object
        dirty = od_new[:1].astype(np.int64)
    else:
        parts = [od_new.astype(np.int64),
                 np.searchsorted(new_table, make_new)]
        if len(d_pt):
            hit = d_pt >= 0
            if np.any(hit):
                parts.append(od_all[d_pt[hit]].astype(np.int64))
        dirty = np.unique(np.concatenate(parts)).astype(np.int64)

    # -- commit the log ----------------------------------------------------
    log.id_key = id_new
    log.obj_key = obj_new
    log.elem_key = ek_new
    log.action = action_new
    log.prop = prop_new
    log.insert = insert_new
    log.value_tag = vtag_new
    log.value_int = vint_new
    log.width = width_new
    log.expand = expand_new
    log.mark_name_idx = mark_new
    log.elem_ref = er_new
    log.obj_dense = od_all
    log.pred_src = ps_new
    log.pred_tgt = pt_new
    log.pred_key = pk_new
    log.props = props
    log.mark_names = mark_names
    log.values = nv
    log.n = m
    log.n_objs = len(new_table)
    log.obj_table = new_table
    log.n_miss_elem = n_miss_elem
    log.n_miss_pred = n_miss_pred
    if p.actors_changed:
        from ..types import ActorId

        log.actors = [ActorId(b) for b in p.all_bytes]
        log._comp = None  # every resident packed key was rank-remapped
    log._actor_order = None
    log.changes.extend(ready)
    log.hashes().update(ch.hash for ch in ready)

    # -- DeviceDoc bookkeeping (the _apply_append tail specialization) ----
    for ch in ready:
        dev._hash_index[ch.hash] = ch
    if p.actors_changed:
        # host caches keyed by packed ids follow the same monotone map
        remap = {
            old: p.rank_of[b] for b, old in dev._rank_of.items()
        }
        dev._obj_type = {
            (
                key
                if key == 0
                else ((key >> ACTOR_BITS) << ACTOR_BITS)
                | remap[key & ACTOR_MASK]
            ): v
            for key, v in dev._obj_type.items()
        }
        dev._rank_of = dict(p.rank_of)
    dev._views.clear()
    nr = np.arange(n, m, dtype=np.int64)
    if len(make_new):
        for r_ in nr[make_mask]:
            dev._obj_type[int(log.id_key[r_])] = _MAKE_OBJ[int(log.action[r_])]

    rbufs = dev._res_bufs
    vis = tw(rbufs, "visible", dev.visible, False, m)
    win = tw(rbufs, "winner", dev.winner, -1, m)
    con = tw(rbufs, "conflicts", dev.conflicts, 0, m)
    ei = tw(rbufs, "elem_index", dev.elem_index, -1, m)
    old_ovl = dev.res["obj_vis_len"]
    old_otw = dev.res["obj_text_width"]
    if (
        len(add) == 0
        and len(old_ovl) == log.n_objs + 2
        and old_ovl.flags.writeable
        and old_otw.flags.writeable
    ):
        # table unchanged and the stat arrays are already exactly the
        # right (owned) shape: carry them forward in place, resetting
        # only the two pad slots — what the scalar path's fresh-zeros-
        # plus-copy produces. A doc fresh from resolve() holds padded
        # read-only device readbacks instead; those take the copy path.
        ovl = old_ovl
        otw = old_otw
        ovl[log.n_objs:] = 0
        otw[log.n_objs:] = 0
    else:
        ovl = np.zeros(log.n_objs + 2, np.int32)
        otw = np.zeros(log.n_objs + 2, np.int32)
        oo = np.asarray(old_ovl)
        ot = np.asarray(old_otw)
        take = min(len(old_table), len(oo))
        ovl[:take] = oo[:take]
        otw[:take] = ot[:take]
    dev.res = {
        "visible": vis, "winner": win, "conflicts": con,
        "elem_index": ei, "obj_vis_len": ovl, "obj_text_width": otw,
    }
    dev.visible = vis
    dev.winner = win
    dev.conflicts = con
    dev.elem_index = ei
    # the base view's covered mask is all-true by definition: extend it
    # through the same capacity buffer instead of a fresh O(rows) ones()
    dev.covered = tw(rbufs, "covered", dev.covered, True, m)

    dev.succ_count = tw(rbufs, "succ_count", dev.succ_count, 0, m)
    dev.inc_count = tw(rbufs, "inc_count", dev.inc_count, 0, m)
    value_int = np.asarray(log.value_int)
    cv = tw(rbufs, "counter_val", dev.counter_val, 0, m)
    cv[n:m] = value_int[n:m]
    dev.counter_val = cv
    if qm > q:
        src = ps_new[q:qm]
        tgt = pt_new[q:qm]
        ok = tgt >= 0
        src, tgt = src[ok], tgt[ok]
        is_inc = np.asarray(log.action)[src] == _INCREMENT
        np.add.at(dev.succ_count, tgt[~is_inc], 1)
        np.add.at(dev.inc_count, tgt[is_inc], 1)
        np.add.at(dev.counter_val, tgt[is_inc], value_int[src[is_inc]])

    # object-sorted row index: merge the delta into the resident order
    old_rbo = dev._rows_by_obj
    if p.actors_changed:
        # _obj_sorted holds packed VALUES: re-gather from the remapped
        # column (monotone remap preserved the sort)
        old_keys = np.asarray(log.obj_key)[:n][old_rbo]
    else:
        old_keys = dev._obj_sorted
    rbo = np.empty(m, np.int64)
    keys = np.empty(m, np.int64)
    if one_obj:
        # single-object delta: one contiguous insertion block — three
        # slice copies instead of the bincount/cumsum merge
        okey = int(d_obj[0])
        at = int(np.searchsorted(old_keys, okey, side="right"))
        rbo[:at] = old_rbo[:at]
        keys[:at] = old_keys[:at]
        rbo[at:at + k] = nr
        keys[at:at + k] = okey
        rbo[at + k:] = old_rbo[at:]
        keys[at + k:] = old_keys[at:]
    else:
        d_keys = np.asarray(log.obj_key)[nr]
        ordx = np.lexsort((nr, d_keys))
        d_rows = nr[ordx]
        d_keys = d_keys[ordx]
        pos2 = np.searchsorted(old_keys, d_keys, side="right")
        cnt = np.bincount(pos2, minlength=n + 1)
        old_pos = np.arange(n, dtype=np.int64) + np.cumsum(cnt[:n])
        rbo[old_pos] = old_rbo
        keys[old_pos] = old_keys
        new_pos = pos2 + np.arange(k, dtype=np.int64)
        rbo[new_pos] = d_rows
        keys[new_pos] = d_keys
    dev._rows_by_obj = rbo
    dev._obj_sorted = keys

    if p.actors_changed:
        dev._all_elems_cache.clear()
    else:
        for d in dirty:
            dev._all_elems_cache.pop(int(log.obj_table[d]), None)

    # -- stage or per-doc resolve (the stage_batches decision) ------------
    rows = dev._subset_rows(dirty)
    if (
        len(rows) / m > dev._dirty_fraction_limit()
        or len(dirty) >= log.n_objs
    ):
        return ("reresolve", dirty)
    dev._export_doc_gauges()
    return ("stage", rows, dirty)
